"""Benchmark: fleet DFM maximum-likelihood fits on device vs measured CPU.

Workload is the BASELINE.md headline config: 20-series dynamic factor
models (1 common factor, state dim 21), 5,000 timesteps, ~30% missing
observations.  The device side fits a batch of B independent models with
the fully on-device vmapped L-BFGS (``metran_tpu.parallel.fit_fleet``);
the baseline side runs a REAL reference-equivalent CPU fit (scipy
L-BFGS-B with finite differences over the native C++ sequential-
processing kernel — the stand-in for the reference's numba engine) and
times it end to end.

Staging (each phase emits a progress JSON line on stderr and persists
partial results, so a timeout localizes the failure instead of erasing
the run):

1. CPU baseline subprocess (runs in parallel with the device work).
2. Device init (timed; a wedged tunnel is detected by subprocess timeout).
3. Forward phase: one ``fleet_value_and_grad`` dispatch — small program,
   compile time reported separately from run time.
4. Fit phase: the chunked on-device L-BFGS (compile+first-run timed
   separately from the steady-state timed run).
5. Extra BASELINE configs (1k x 8-series forward fleet; 50-series
   smoother + decomposition) when budget remains.

If the device (tunneled TPU) cannot initialize or times out, the same
staged benchmark reruns on the CPU backend and the result is labeled
``"platform": "cpu"`` — a real measured number on the fallback platform
rather than a watchdog zero.

Prints ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "fits/s/chip", "vs_baseline": N,
     "detail": {...}}
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

N_SERIES = 20
N_FACTORS = 1
T_STEPS = 5_000
MISSING = 0.3
BATCH = 512  # lane-layout fleet: fleet axis rides the TPU lane dim
MAXITER = 60
CHUNK = 8  # L-BFGS iterations per dispatch (~15 s at B=512 — keeps every
#            device execution far below the tunnel's kill threshold)
MAX_LS = 4  # grid line-search trials (one stacked forward dispatch);
#             measured on-chip: 4 beats 6 (38.1 vs 26.0 fits/s — fewer
#             forward passes/iter) and 3 (37.2 — too many rejected steps)
REMAT_SEG = 100  # checkpointed filter segments: O(seg) autodiff memory
# f32 convergence thresholds: the gradient-noise floor of a float32
# deviance of magnitude ~1e5 sits far above scipy's f64 pgtol, so the
# fleet stops on gradient norm < TOL or per-chunk objective improvement
# < STALL_TOL (the f32 resolution floor), whichever first
TOL = 0.05
STALL_TOL = 1e-3
SEED = 0
METRIC = "DFM fits/sec/chip (20-series, 5k steps)"
# a 5,000-step sequential scan cannot execute in under ~1 us/step of
# device wall time; any timed dispatch faster than this is a broken
# measurement (VERDICT r2: a 15 ns/step "result" shipped unflagged)
MIN_PLAUSIBLE_DISPATCH_S = T_STEPS * 1e-6

# smoke mode for CI / local sanity runs: tiny shapes, same code paths
if os.environ.get("METRAN_TPU_BENCH_SMALL"):
    T_STEPS, BATCH, MAXITER, CHUNK = 200, 4, 8, 4
    MIN_PLAUSIBLE_DISPATCH_S = T_STEPS * 1e-6
    METRIC = "DFM fits/sec/chip (SMALL smoke config)"

T0 = time.monotonic()
REPO = os.path.dirname(os.path.abspath(__file__))
CACHE_DIR = os.path.join(REPO, ".cache")
JAX_CACHE = os.path.join(CACHE_DIR, "jax")


def elapsed() -> float:
    return time.monotonic() - T0


def progress(stage: str, **kw) -> None:
    """One progress line per phase on stderr (stdout stays for the final
    result line only)."""
    rec = {"t": round(elapsed(), 1), "stage": stage}
    rec.update(kw)
    print(json.dumps(rec), file=sys.stderr, flush=True)


def write_partial(path: str, payload: dict) -> None:
    """Persist phase results so a killed subprocess still reports them."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


# ----------------------------------------------------------------------
# workload
# ----------------------------------------------------------------------
def make_workload(rng, batch, n=N_SERIES, k=N_FACTORS, t=T_STEPS,
                  missing=MISSING):
    """Synthetic standardized DFM panels with a true common factor.

    Vectorized over the batch (one python loop over time only), so
    generating fleet-scale workloads (512+ models) stays cheap on host.
    """
    loadings = rng.uniform(0.4, 0.8, (batch, n, k)) / np.sqrt(k)
    phi_c = np.exp(-1.0 / rng.uniform(10.0, 60.0, (batch, k)))
    phi_s = np.exp(-1.0 / rng.uniform(5.0, 40.0, (batch, n)))
    e_c = rng.normal(size=(t, batch, k)) * np.sqrt(1 - phi_c**2)
    e_s = rng.normal(size=(t, batch, n)) * np.sqrt(1 - phi_s**2)
    common = np.zeros((t, batch, k))
    specific = np.zeros((t, batch, n))
    for i in range(1, t):
        common[i] = phi_c * common[i - 1] + e_c[i]
        specific[i] = phi_s * specific[i - 1] + e_s[i]
    comm = np.sum(loadings**2, axis=2)  # (batch, n)
    y = np.transpose(
        specific * np.sqrt(1 - comm)[None]
        + np.einsum("tbk,bnk->tbn", common, loadings),
        (1, 0, 2),
    )
    mask = rng.uniform(size=y.shape) > missing
    return np.where(mask, y, 0.0), mask, loadings


def _dfm_matrices(loadings, alpha):
    """Host-side (phi, q, z, r) for the CPU sequential kernel."""
    n, k = loadings.shape
    phi = np.exp(-1.0 / alpha)
    comm = np.sum(loadings**2, axis=1)
    q = np.diag(
        np.concatenate([(1 - phi[:n] ** 2) * (1 - comm), 1 - phi[n:] ** 2])
    )
    z = np.concatenate([np.eye(n), loadings], axis=1)
    return phi, q, z, np.zeros(n)


def _np_filter_deviance(phi, q, z, r, y, mask, warmup=1):
    """Pure-numpy sequential-processing deviance (fallback when the
    native kernel cannot build); same algorithm as the reference's
    numpy twin (metran/kalmanfilter.py:122-233)."""
    t_steps, m = y.shape
    n = phi.shape[0]
    mean = np.zeros(n)
    cov = np.eye(n)
    sigmas, detfs, counts = [], [], np.zeros(t_steps, int)
    for t in range(t_steps):
        mean = phi * mean
        cov = phi[:, None] * cov * phi[None, :] + q
        sigma = detf = 0.0
        for i in range(m):
            if not mask[t, i]:
                continue
            counts[t] += 1
            zi = z[i]
            v = y[t, i] - zi @ mean
            d = cov @ zi
            f = zi @ d + r[i]
            kgain = d / f
            cov = cov - np.outer(kgain, kgain) * f
            mean = mean + kgain * v
            sigma += v * v / f
            detf += np.log(f)
        sigmas.append(sigma)
        detfs.append(detf)
    observed = np.flatnonzero(counts > 0)
    keep = observed[warmup:]
    nobs = counts[warmup:].sum()
    sig = np.asarray(sigmas)
    det = np.asarray(detfs)
    return nobs * np.log(2 * np.pi) + det[keep].sum() + sig[keep].sum()


# ----------------------------------------------------------------------
# phase: CPU baseline (measured, not modeled)
# ----------------------------------------------------------------------
def run_cpu_baseline(out_path: str, budget_s: float) -> None:
    """Time a real reference-equivalent fit: scipy L-BFGS-B with
    finite-difference gradients over the native sequential kernel
    (reference: metran/solver.py:222-288 + kalmanfilter.py:236-400)."""
    from scipy.optimize import minimize

    # model 0 of the SAME batch workload the device fits, so the final
    # deviances are directly comparable (parity evidence, not just speed)
    rng = np.random.default_rng(SEED)
    y, mask, loadings = make_workload(rng, BATCH)
    y, mask, ld = y[0], mask[0], loadings[0]
    n_params = N_SERIES + N_FACTORS
    out = {"engine": None}

    try:
        from metran_tpu import native

        native.load()
        dev = lambda phi, q, z, r: native.deviance(  # noqa: E731
            phi, q, z, r, y, mask, warmup=1
        )
        out["engine"] = "native"
    except Exception as e:  # pragma: no cover - toolchain-less hosts
        progress("cpu_native_unavailable", error=str(e)[-200:])
        dev = lambda phi, q, z, r: _np_filter_deviance(  # noqa: E731
            phi, q, z, r, y, mask
        )
        out["engine"] = "numpy"

    def objective(alpha):
        return dev(*_dfm_matrices(ld, alpha))

    x0 = np.full(n_params, 10.0)
    objective(x0)  # warm (build/load)
    t0 = time.perf_counter()
    objective(x0)
    pass_s = time.perf_counter() - t0
    out["filter_pass_s"] = round(pass_s, 4)
    progress("cpu_pass_timed", pass_s=out["filter_pass_s"])
    write_partial(out_path, out)

    # cap the fit's function evaluations to the child's time budget; if
    # the cap binds, the timing still measures real optimizer progress
    # and `capped` records that convergence was cut short
    maxfun = int(max(100, min((budget_s - elapsed() - 10) / pass_s, 20000)))
    t0 = time.perf_counter()
    res = minimize(
        objective, x0=x0, method="l-bfgs-b",
        bounds=[(1e-5, None)] * n_params, options={"maxfun": maxfun},
    )
    fit_s = time.perf_counter() - t0
    out.update(
        fit_s=round(fit_s, 2),
        nfev=int(res.nfev),
        iterations=int(res.nit),
        converged=bool(res.success),
        capped=bool(res.nfev >= maxfun),
        deviance=float(res.fun),
        optimal_alpha_first=float(res.x[0]),
    )
    progress("cpu_fit_done", **{k: out[k] for k in
                                ("fit_s", "nfev", "iterations", "converged")})
    write_partial(out_path, out)


# ----------------------------------------------------------------------
# phase: device benchmark (runs in its own subprocess)
# ----------------------------------------------------------------------
def run_device_bench(out_path: str, budget_s: float,
                     force_cpu: bool = False) -> None:
    if force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE)

    def left() -> float:
        return budget_s - elapsed()

    progress("device_init_start",
             platform=os.environ.get("JAX_PLATFORMS", "default"))
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    t0 = time.perf_counter()
    devices = jax.devices()
    init_s = time.perf_counter() - t0
    platform = devices[0].platform
    out = {
        "platform": platform,
        "n_devices": len(devices),
        "device_init_s": round(init_s, 1),
    }
    progress("device_init_done", platform=platform, init_s=out["device_init_s"])
    write_partial(out_path, out)

    import jax.numpy as jnp

    # executed-matmul probe: the round-4 r4d wedge showed jax.devices()
    # returning instantly while the first real dispatch hung >900 s, so
    # init success alone proves nothing about tunnel health.  Running
    # (and materializing) one tiny matmul here gives the parent a
    # definite "this tunnel executes" marker; if it never appears the
    # parent bails after METRAN_TPU_BENCH_EXEC_TIMEOUT_S instead of
    # burning the whole device budget on a hung dispatch.
    t0 = time.perf_counter()
    probe = jnp.ones((128, 128), jnp.float32)
    float(jnp.sum(probe @ probe))
    out["device_exec_probe_s"] = round(time.perf_counter() - t0, 1)
    progress("device_exec_probe", s=out["device_exec_probe_s"])
    write_partial(out_path, out)

    from metran_tpu.parallel import fit_fleet, fleet_value_and_grad
    from metran_tpu.parallel.fleet import (
        Fleet, autocorr_init_params, default_init_params,
    )

    def make_fleet(y, mask, loadings):
        b = y.shape[0]
        return Fleet(
            y=jnp.asarray(y, jnp.float32),
            mask=jnp.asarray(mask),
            loadings=jnp.asarray(loadings, jnp.float32),
            dt=jnp.ones(b, jnp.float32),
            n_series=jnp.full(b, y.shape[2], np.int32),
        )

    from metran_tpu.utils.profiling import ThroughputCounter

    def timed_laps(fn, reps=3):
        """Time ``fn`` ``reps`` times, MATERIALIZING every output to host
        numpy inside the timed block (``np.asarray`` forces the full
        device->host sync; ``block_until_ready`` alone produced a
        physically impossible number on the experimental tunneled
        platform in round 2).  Returns (laps, plausible)."""
        cnt = ThroughputCounter(unit="dispatches")
        for _ in range(reps):
            with cnt.measure(n=1):
                jax.tree.map(np.asarray, fn())
        laps = [round(lap["seconds"], 4) for lap in cnt.laps]
        plausible = all(s >= MIN_PLAUSIBLE_DISPATCH_S for s in laps)
        if not plausible:
            progress("implausible_timing", laps_s=laps,
                     floor_s=MIN_PLAUSIBLE_DISPATCH_S)
        return laps, plausible

    # budget-driven batch choice (VERDICT r4 item 5): memory-bound on
    # directly-attached hardware, capped at the battle-tested 512 when
    # the device is reached through the axon tunnel (whose remote
    # compile service crashed on a batch-2048 compile in round 4).  The
    # full selection reasoning lands in the artifact.
    from metran_tpu.parallel.fleet import choose_fleet_batch

    hbm = None
    try:
        stats = devices[0].memory_stats()
        if stats:
            hbm = stats.get("bytes_limit")
    except Exception:
        pass
    # tunneled=None auto-detects via PALLAS_AXON_POOL_IPS, so the 512
    # cap applies on this rig's tunnel but lifts on directly-attached
    # TPU hardware
    sel = choose_fleet_batch(
        N_SERIES, N_FACTORS, T_STEPS, remat_seg=REMAT_SEG or 100,
        hbm_bytes=hbm, tunneled=None,
    )
    batch = min(4, BATCH) if force_cpu else sel["batch"]
    # applied_batch records what this run actually used (the CPU
    # fallback overrides the selection with a tiny batch)
    sel["applied_batch"] = batch
    out["batch_selection"] = sel
    progress("batch_selected", **sel)
    rng = np.random.default_rng(SEED)
    # always generate the canonical BATCH-model workload first, so
    # model 0 is identical across the device run, the CPU fallback and
    # the CPU baseline (deviances comparable) regardless of the chosen
    # batch; extra models (batch > BATCH) come from a second stream
    y, mask, loadings = make_workload(rng, BATCH)
    if batch > BATCH:
        rng2 = np.random.default_rng(SEED + 1)
        y2, mask2, loadings2 = make_workload(rng2, batch - BATCH)
        y = np.concatenate([y, y2])
        mask = np.concatenate([mask, mask2])
        loadings = np.concatenate([loadings, loadings2])
    fleet = make_fleet(y[:batch], mask[:batch], loadings[:batch])
    params0 = default_init_params(fleet)
    progress("workload_ready", batch=batch)

    # ---- forward: one lanes deviance+grad dispatch --------------------
    fwd_kwargs = dict(layout="lanes", remat_seg=REMAT_SEG)
    t0 = time.perf_counter()
    val, grad = fleet_value_and_grad(params0, fleet, **fwd_kwargs)
    np.asarray(val), np.asarray(grad)
    fwd_compile_s = time.perf_counter() - t0
    laps, plausible = timed_laps(
        lambda: fleet_value_and_grad(params0, fleet, **fwd_kwargs)
    )
    lap = float(np.median(laps))
    out["forward"] = {
        "compile_plus_first_run_s": round(fwd_compile_s, 2),
        "laps_s": laps,
        "plausible": plausible,
        "passes_per_s": round(batch / lap, 3) if plausible else 0.0,
        "deviance_model0_init": float(np.asarray(val)[0]),
    }
    progress("forward_done", **out["forward"])
    write_partial(out_path, out)

    # ---- tiny fit probe: minimal program, localizes a compile bomb ----
    fit_kwargs = dict(layout="lanes", remat_seg=REMAT_SEG, tol=TOL,
                      stall_tol=STALL_TOL, max_linesearch_steps=MAX_LS)
    tiny = make_fleet(y[:2], mask[:2], loadings[:2])
    t0 = time.perf_counter()
    tiny_fit = fit_fleet(tiny, maxiter=2, chunk=2, **fit_kwargs)
    np.asarray(tiny_fit.params)
    out["tiny_fit_probe_s"] = round(time.perf_counter() - t0, 1)
    progress("tiny_fit_done", s=out["tiny_fit_probe_s"])
    write_partial(out_path, out)

    # ---- fit: chunked lanes L-BFGS ------------------------------------
    # the fit starts from the data-driven lag-1-autocorrelation init
    # (a framework feature the reference lacks — measured on-chip it
    # cuts mean L-BFGS iterations ~25%, 11.5 -> 8.6); the jitted init
    # runs on device and is INSIDE the timed block, so the headline
    # measures the whole fit workflow
    def timed_fit(fl=None):
        fl = fleet if fl is None else fl
        p0 = autocorr_init_params(fl)
        fit = fit_fleet(
            fl, p0=p0, maxiter=MAXITER, chunk=CHUNK, **fit_kwargs
        )
        np.asarray(fit.params)
        return fit

    import resource as _resource

    def _rss_mb() -> float:
        return _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss / 1024.0

    def _resolve_grad(which: str) -> str:
        # "lanes" rides the sequential-engine resolution (fit_fleet's
        # _lanes_score rule)
        from metran_tpu.ops import resolve_grad_engine

        return resolve_grad_engine(
            None, "sequential" if which == "lanes" else which
        )

    rss_before_fit = _rss_mb()
    t0 = time.perf_counter()
    fit = timed_fit()
    fit_compile_s = time.perf_counter() - t0
    iters = float(np.mean(np.asarray(fit.iterations)))
    progress("fit_compiled", compile_plus_first_run_s=round(fit_compile_s, 1),
             iters_mean=round(iters, 1))
    t0 = time.perf_counter()
    fit = timed_fit()
    fit_run_s = time.perf_counter() - t0
    # stats below come from THIS run so mean/max are coherent
    iters_arr = np.asarray(fit.iterations)
    iters = float(np.mean(iters_arr))
    fit_plausible = fit_run_s >= MIN_PLAUSIBLE_DISPATCH_S
    if not fit_plausible:
        progress("implausible_timing", laps_s=[fit_run_s],
                 floor_s=MIN_PLAUSIBLE_DISPATCH_S)
    out["fit"] = {
        "compile_plus_first_run_s": round(fit_compile_s, 1),
        "run_s": round(fit_run_s, 2),
        "init": "autocorr (on-device, inside the timed block)",
        "plausible": fit_plausible,
        "fits_per_s": (
            round(batch / fit_run_s, 3) if fit_plausible else 0.0
        ),
        "lbfgs_iters_mean": round(iters, 1),
        "lbfgs_iters_max": int(iters_arr.max()),
        # converged includes lanes frozen at the f32 resolution floor
        # (FleetFit.stalled — the scipy-factr-style success contract);
        # stalled_frac reports that subset separately
        "converged_frac": round(float(np.mean(np.asarray(fit.converged))), 3),
        "stalled_frac": round(float(np.mean(np.asarray(fit.stalled))), 3),
        "deviance_model0": float(np.asarray(fit.deviance)[0]),
        "batch": batch,
        # the lanes fit differentiates through its analytical score by
        # default; recorded so rounds are comparable if the knob flips
        "grad_engine": _resolve_grad("lanes"),
        # host-process peak RSS across the fit phase (monotone counter:
        # the delta is the fit's incremental demand over the stages
        # before it — forward/backward buffers included on the CPU
        # backend, compile workspace included on first run)
        "peak_rss_mb": round(_rss_mb(), 1),
        "rss_delta_mb": round(_rss_mb() - rss_before_fit, 1),
    }
    progress("fit_done", **{k: out["fit"][k] for k in
                            ("run_s", "fits_per_s", "lbfgs_iters_mean",
                             "rss_delta_mb")})
    write_partial(out_path, out)

    # ---- single-model fit latency -------------------------------------
    # the per-user comparison against the CPU reference's one-model fit
    # (cpu_baseline.fit_s); rides the TPU lane-width pad (tiny fleets
    # replicated to 8 lanes — see fit_fleet lane_min_batch).  The pad
    # shape is a fresh compile, so the stage is budget-gated like the
    # other optional stages.
    if left() > 180:
        single = make_fleet(y[:1], mask[:1], loadings[:1])
        t0 = time.perf_counter()
        sfit = timed_fit(single)
        s_compile = time.perf_counter() - t0
        t0 = time.perf_counter()
        sfit = timed_fit(single)
        s_run = time.perf_counter() - t0
        s_plausible = s_run >= MIN_PLAUSIBLE_DISPATCH_S
        if not s_plausible:
            progress("implausible_timing", laps_s=[s_run],
                     floor_s=MIN_PLAUSIBLE_DISPATCH_S)
        out["single_fit"] = {
            "compile_plus_first_run_s": round(s_compile, 1),
            "fit_s": round(s_run, 4),
            "plausible": s_plausible,
            "iters": int(np.asarray(sfit.iterations)[0]),
            "converged": bool(np.asarray(sfit.converged)[0]),
        }
        progress("single_fit_done", **out["single_fit"])
        write_partial(out_path, out)

    # ---- post-fit products: stderr / simulate / decompose / etc -------
    # the batched inference products the reference computes per model
    # (metran/solver.py:258-266, kalmanfilter.py:569-644).  Round 5
    # ported them to lane layout (ops/lanes_products.py): those run as
    # whole-fleet single dispatches (chunking would waste the 128-wide
    # lane dim); the round-4 batch-layout configuration is kept as an
    # in-artifact control.  Lanes measurements materialize a device-side
    # SUM instead of the full (B, T, N) outputs: the result stays
    # device-resident as a real pipeline would consume it, and the
    # tunnel's ~15 s/256 MB D2H (a rig artifact, BASELINE.md) stays out
    # of the throughput number ("d2h_excluded": true marks these).
    if left() > 300:
        try:
            import jax.numpy as _jnp

            from metran_tpu.parallel import (
                fleet_decompose, fleet_innovations, fleet_sample,
                fleet_simulate, fleet_stderr,
            )

            nprod = min(32, batch)
            prod_chunk = 4 if not force_cpu else 2
            prods = {}

            def measure(name, fn, kw, n, reduce_out=False, layout=None):
                s = jax.tree.map(lambda a: a[:n], fleet)
                p = fit.params[:n]

                def run_once():
                    res = fn(p, s, **kw)
                    if reduce_out:
                        return [
                            np.asarray(_jnp.nansum(x)) for x in
                            (res if isinstance(res, tuple) else (res,))
                        ]
                    return jax.tree.map(np.asarray, res)

                t0 = time.perf_counter()
                run_once()
                c = time.perf_counter() - t0
                t0 = time.perf_counter()
                run_once()
                r = time.perf_counter() - t0
                prods[name] = {
                    "models": n, "batch_chunk": kw.get("batch_chunk"),
                    "layout": layout or kw.get("layout", "lanes"),
                    "d2h_excluded": bool(reduce_out),
                    "compile_plus_first_run_s": round(c, 1),
                    "run_s": round(r, 2),
                    "models_per_s": round(n / r, 2),
                }
                progress(f"postfit_{name}", **prods[name])
                return r

            # lanes products, whole fleet in one dispatch each
            if left() > 150:
                measure("simulate_lanes", fleet_simulate,
                        dict(smooth=True), batch, reduce_out=True)
            if left() > 150:
                measure("decompose_lanes", fleet_decompose,
                        dict(smooth=True), batch, reduce_out=True)
            if left() > 150:
                measure("innovations_lanes", fleet_innovations,
                        dict(warmup=50), batch, reduce_out=True)
            if left() > 180:
                nsamp = min(64, batch)
                measure("sample_lanes", fleet_sample,
                        dict(n_draws=4), nsamp, reduce_out=True)
            # the exact-AD Hessian runs batch-leading (the slow layout
            # on TPU): probe ONE 2-model dispatch first and only widen
            # when that dispatch stays far below the tunnel's ~60 s
            # execution kill threshold
            se_kw = dict(remat_seg=REMAT_SEG, batch_chunk=2)
            probe_r = measure("stderr", fleet_stderr, se_kw, 2,
                              layout="batch")
            if probe_r < 25.0 and left() > 180:
                se_kw["batch_chunk"] = prod_chunk
                measure("stderr", fleet_stderr, se_kw, nprod,
                        layout="batch")
            # the lane-layout FD Hessian (2P central-difference points
            # per model ride the lane axis)
            if left() > 150:
                measure(
                    "stderr_lanes_fd", fleet_stderr,
                    dict(remat_seg=REMAT_SEG, batch_chunk=prod_chunk,
                         method="lanes-fd"),
                    nprod, layout="lanes-fd",
                )
            # round-4 batch-layout control (same config as the r4
            # artifacts, full materialization): the lanes-vs-batch
            # speedup is readable from one artifact
            if left() > 120:
                measure("simulate", fleet_simulate,
                        dict(smooth=True, batch_chunk=prod_chunk,
                             layout="batch"), nprod)
            if left() > 120:
                measure("decompose", fleet_decompose,
                        dict(smooth=True, batch_chunk=prod_chunk,
                             layout="batch"), nprod)
            out["postfit_products"] = prods
            write_partial(out_path, out)
        except Exception as e:  # products must not sink the headline
            progress("postfit_failed", error=str(e)[-200:])

    # ---- multistart: rides the SAME compiled program as the fit -------
    # (VERDICT r4 item 7) n_starts=2 on the first batch/2 models makes
    # the replicated fleet exactly `batch` lanes with the fit stage's
    # static args -> compile-cache hit, so the stage costs one fit lap
    if left() > 180 and batch >= 4:
        try:
            from metran_tpu.parallel import multistart_fit_fleet

            half = jax.tree.map(lambda a: a[: batch // 2], fleet)
            t0 = time.perf_counter()
            ms_fit, ms_dev = multistart_fit_fleet(
                half, n_starts=2, maxiter=MAXITER, chunk=CHUNK,
                **fit_kwargs,
            )
            np.asarray(ms_fit.params)
            ms_s = time.perf_counter() - t0
            gain = np.asarray(ms_dev)[:, 0] - np.asarray(ms_fit.deviance)
            out["multistart"] = {
                "models": batch // 2, "n_starts": 2,
                "run_s": round(ms_s, 2),
                "effective_fits_per_s": round(batch / ms_s, 2),
                "deviance_gain_total": round(float(gain.sum()), 3),
                "deviance_gain_max": round(float(gain.max()), 4),
            }
            progress("multistart_done", **out["multistart"])
            write_partial(out_path, out)
        except Exception as e:
            progress("multistart_failed", error=str(e)[-200:])

    # ---- extra BASELINE configs, budget permitting --------------------
    if left() > 240:  # config 3: 1k x 8-series vmap fleet, forward+grad
        try:
            b3, n3, t3 = (1024, 8, 1000) if not force_cpu else (64, 8, 200)
            y3, m3, ld3 = make_workload(
                np.random.default_rng(1), b3, n=n3, k=1, t=t3
            )
            fleet3 = Fleet(
                y=jnp.asarray(y3, jnp.float32),
                mask=jnp.asarray(m3),
                loadings=jnp.asarray(ld3, jnp.float32),
                dt=jnp.ones(b3, jnp.float32),
                n_series=jnp.full(b3, n3, np.int32),
            )
            p3 = default_init_params(fleet3)
            t0 = time.perf_counter()
            v, g = fleet_value_and_grad(p3, fleet3, **fwd_kwargs)
            np.asarray(v), np.asarray(g)
            c3 = time.perf_counter() - t0
            laps3, ok3 = timed_laps(
                lambda: fleet_value_and_grad(p3, fleet3, **fwd_kwargs)
            )
            out["config3_vmap_fleet"] = {
                "batch": b3, "n_series": n3, "t_steps": t3,
                "compile_plus_first_run_s": round(c3, 1),
                "laps_s": laps3, "plausible": ok3,
                "grad_passes_per_s": (
                    round(b3 / float(np.median(laps3)), 1) if ok3 else 0.0
                ),
            }
            progress("config3_done", **out["config3_vmap_fleet"])
            write_partial(out_path, out)
        except Exception as e:  # extra configs must not sink the run
            progress("config3_failed", error=str(e)[-200:])

    if left() > 180:  # config 5: 50-series smoother + decomposition
        try:
            from metran_tpu.ops import (
                decompose_states, dfm_statespace, kalman_filter, project,
                rts_smoother,
            )

            n5, t5 = (50, 5000) if not force_cpu else (50, 500)
            y5, m5, ld5 = make_workload(
                np.random.default_rng(2), 1, n=n5, k=1, t=t5
            )
            dtype = jnp.float32
            ss5 = dfm_statespace(
                jnp.full(n5, 10.0, dtype), jnp.full(1, 10.0, dtype),
                jnp.asarray(ld5[0], dtype), 1.0,
            )
            y5j = jnp.asarray(y5[0], dtype)
            m5j = jnp.asarray(m5[0])

            def smooth_decompose():
                filt = kalman_filter(ss5, y5j, m5j, engine="joint")
                sm = rts_smoother(ss5, filt)
                sim = project(ss5.z, sm.mean_s, sm.cov_s)
                dec = decompose_states(ss5.z, sm.mean_s, n5)
                return sim, dec

            t0 = time.perf_counter()
            jax.tree.map(np.asarray, smooth_decompose())
            c5 = time.perf_counter() - t0
            laps5, ok5 = timed_laps(smooth_decompose)
            out["config5_smoother"] = {
                "n_series": n5, "t_steps": t5, "missing": MISSING,
                "compile_plus_first_run_s": round(c5, 1),
                "laps_s": laps5, "plausible": ok5,
                "smooth_decompose_per_s": (
                    round(1.0 / float(np.median(laps5)), 2) if ok5 else 0.0
                ),
            }
            progress("config5_done", **out["config5_smoother"])
            write_partial(out_path, out)
        except Exception as e:
            progress("config5_failed", error=str(e)[-200:])

# ----------------------------------------------------------------------
# phase: mesh scaling (virtual 8-device CPU mesh — BASELINE config 4)
# ----------------------------------------------------------------------
def run_mesh_bench(out_path: str, budget_s: float) -> None:
    """Measure fleet sharding overhead on a virtual 8-device CPU mesh.

    Virtual devices share one host's cores, so this measures the COST of
    sharding (GSPMD partitioning + collectives + per-shard dispatch),
    not a speedup; the v5e-8 extrapolation in BASELINE.md is
    single-chip-TPU-throughput x 8 minus the overhead bounded here.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from metran_tpu.parallel import (
        fit_fleet, fleet_value_and_grad, make_mesh,
    )
    from metran_tpu.parallel.fleet import Fleet, default_init_params

    out = {
        "n_virtual_devices": len(jax.devices()),
        # virtual devices SHARE one host's cores (and this phase overlaps
        # the TPU-bound device child): lap times bound the COST of
        # sharding under contention — they are not scaling numbers
        "contended": True,
        "note": "virtual 8-device CPU mesh on one host; measures "
                "sharding overhead bound, not device scaling",
    }
    b, t = 64, 1000
    y, mask, loadings = make_workload(np.random.default_rng(3), b, t=t)
    fleet = Fleet(
        y=jnp.asarray(y, jnp.float32),
        mask=jnp.asarray(mask),
        loadings=jnp.asarray(loadings, jnp.float32),
        dt=jnp.ones(b, jnp.float32),
        n_series=jnp.full(b, N_SERIES, np.int32),
    )
    p0 = default_init_params(fleet)
    from metran_tpu.parallel.mesh import batch_sharding

    kw = dict(layout="lanes", remat_seg=REMAT_SEG)
    scaling = {}
    for n_dev in (1, 2, 4, 8):
        mesh = make_mesh(n_dev)
        # inputs are batch-leading; GSPMD propagates the sharding through
        # the internal transpose to the lane-layout program
        bshard = lambda x: batch_sharding(mesh, np.ndim(x))  # noqa: E731
        fl = jax.tree.map(lambda a: jax.device_put(a, bshard(a)), fleet)
        p = jax.device_put(p0, bshard(p0))
        v, g = fleet_value_and_grad(p, fl, **kw)
        np.asarray(v)  # compile + first run
        laps = []
        for _ in range(3):
            t0 = time.perf_counter()
            v, g = fleet_value_and_grad(p, fl, **kw)
            np.asarray(v), np.asarray(g)
            laps.append(round(time.perf_counter() - t0, 4))
        scaling[str(n_dev)] = {
            "laps_s": laps, "lap_s": round(float(np.median(laps)), 4)
        }
        progress("mesh_vg", n_dev=n_dev, lap_s=scaling[str(n_dev)]["lap_s"])
        out["vg_strong_scaling"] = scaling
        write_partial(out_path, out)
    base = scaling["1"]["lap_s"]
    out["sharding_overhead_frac_8dev"] = round(
        scaling["8"]["lap_s"] / base - 1.0, 3
    )

    # one sharded fit vs unsharded fit (same small workload)
    if budget_s - elapsed() > 120:
        mesh = make_mesh(8)
        fit_kw = dict(maxiter=10, chunk=5, tol=TOL, stall_tol=STALL_TOL,
                      max_linesearch_steps=MAX_LS, **kw)
        for label, m in (("unsharded", None), ("mesh8", mesh)):
            t0 = time.perf_counter()
            fit = fit_fleet(fleet, mesh=m, **fit_kw)
            np.asarray(fit.params)
            t1 = time.perf_counter()
            fit = fit_fleet(fleet, mesh=m, **fit_kw)
            np.asarray(fit.params)
            out[f"fit_{label}"] = {
                "compile_plus_first_s": round(t1 - t0, 1),
                "run_s": round(time.perf_counter() - t1, 2),
                "deviance_model0": float(np.asarray(fit.deviance)[0]),
            }
            progress(f"mesh_fit_{label}", **out[f"fit_{label}"])
            write_partial(out_path, out)


def run_mesh_solo(out_path: str, budget_s: float) -> None:
    """Uncontended sharding-overhead measurement (VERDICT r3 item 8).

    Runs SOLO (the orchestrator schedules it after every other child has
    exited), so the 1-device vs 8-virtual-device value+grad lap ratio is
    a clean sharding-cost figure rather than a host-contention artifact
    (BASELINE.md's ~2.5% solo number, now driver-reproducible).
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from metran_tpu.parallel import fleet_value_and_grad, make_mesh
    from metran_tpu.parallel.fleet import Fleet, default_init_params
    from metran_tpu.parallel.mesh import batch_sharding

    out = {"contended": False, "solo": True}
    b, t = 64, 1000
    y, mask, loadings = make_workload(np.random.default_rng(3), b, t=t)
    fleet = Fleet(
        y=jnp.asarray(y, jnp.float32),
        mask=jnp.asarray(mask),
        loadings=jnp.asarray(loadings, jnp.float32),
        dt=jnp.ones(b, jnp.float32),
        n_series=jnp.full(b, N_SERIES, np.int32),
    )
    p0 = default_init_params(fleet)
    kw = dict(layout="lanes", remat_seg=REMAT_SEG)
    for n_dev in (1, 8):
        mesh = make_mesh(n_dev)
        bshard = lambda x: batch_sharding(mesh, np.ndim(x))  # noqa: E731
        fl = jax.tree.map(lambda a: jax.device_put(a, bshard(a)), fleet)
        p = jax.device_put(p0, bshard(p0))
        v, g = fleet_value_and_grad(p, fl, **kw)
        np.asarray(v)  # compile + first run (cache-warm from mesh phase)
        laps = []
        for _ in range(5):
            t0 = time.perf_counter()
            v, g = fleet_value_and_grad(p, fl, **kw)
            np.asarray(v), np.asarray(g)
            laps.append(round(time.perf_counter() - t0, 4))
        out[f"vg_lap_s_{n_dev}dev"] = round(float(np.median(laps)), 4)
        out[f"vg_laps_s_{n_dev}dev"] = laps
        progress("mesh_solo_vg", n_dev=n_dev,
                 lap_s=out[f"vg_lap_s_{n_dev}dev"])
        write_partial(out_path, out)
    out["sharding_overhead_frac_solo"] = round(
        out["vg_lap_s_8dev"] / out["vg_lap_s_1dev"] - 1.0, 4
    )
    progress("mesh_solo_done",
             overhead=out["sharding_overhead_frac_solo"])
    write_partial(out_path, out)


def run_serve_bench(out_path: str, budget_s: float) -> dict:
    """Serving-path scenario: batched forecast qps + update latency.

    Measures the `metran_tpu.serve` subsystem end to end on whatever
    backend the environment provides (the orchestrator runs it CPU-
    pinned alongside nothing): a registry of heterogeneous models in
    one shape bucket, batched forecast queries/sec through single
    compiled dispatches, and p50/p99 per-request latency of online
    assimilation updates through the micro-batching queue.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import jax

    import jax.numpy as jnp

    from metran_tpu.ops import dfm_statespace, kalman_filter
    from metran_tpu.serve import (
        MetranService, ModelRegistry, PosteriorState,
    )

    n_models, n, k_fct, t_hist = 128, 8, 1, 300
    steps, upd_k, upd_rounds = 14, 1, 40
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, t_hist, upd_rounds = 16, 60, 8
    deadline = time.monotonic() + budget_s
    out = {
        "platform": jax.default_backend(),
        "n_models": n_models,
        "n_series": n, "n_factors": k_fct, "t_hist": t_hist,
    }

    rng = np.random.default_rng(11)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = rng.uniform(size=y.shape) > MISSING
    y = np.where(mask, y, 0.0)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = kalman_filter(ss, yy, mm, engine="joint", store=False)
        return res.mean_f, res.cov_f

    t0 = time.perf_counter()
    means, covs = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, covs = np.asarray(means), np.asarray(covs)
    out["extract_states_s"] = round(time.perf_counter() - t0, 3)
    progress("serve_states_ready", s=out["extract_states_s"])

    reg = ModelRegistry(root=None)  # in-memory: measure compute, not disk
    for i in range(n_models):
        reg.put(PosteriorState(
            model_id=f"m{i}", version=0, t_seen=t_hist,
            mean=means[i], cov=covs[i],
            params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
            loadings=loadings[i], dt=1.0,
            scaler_mean=np.zeros(n), scaler_std=np.ones(n),
            names=tuple(f"s{j}" for j in range(n)),
        ), persist=False)

    # batched forecast qps: all models per flush, manual mode so every
    # lap is exactly one dispatch
    svc = MetranService(reg, flush_deadline=None, max_batch=4 * n_models)
    t0 = time.perf_counter()
    futs = [svc.forecast_async(f"m{i}", steps) for i in range(n_models)]
    svc.flush()
    [f.result() for f in futs]
    out["forecast_compile_plus_first_s"] = round(time.perf_counter() - t0, 3)
    laps = []
    while len(laps) < 5 and time.monotonic() < deadline - 30:
        t0 = time.perf_counter()
        futs = [svc.forecast_async(f"m{i}", steps) for i in range(n_models)]
        svc.flush()
        [f.result() for f in futs]
        laps.append(round(time.perf_counter() - t0, 4))
    if laps:
        out["forecast"] = {
            "steps": steps,
            "laps_s": laps,
            "batched_qps": round(n_models / float(np.median(laps)), 1),
        }
        progress("serve_forecast", qps=out["forecast"]["batched_qps"])
    out["compile_stats"] = dict(reg.compile_stats)
    write_partial(out_path, out)

    # update latency through the background micro-batcher (2 ms
    # deadline): per-request p50/p99 as a caller experiences it
    svc.close()
    new_obs = rng.normal(size=(upd_k, n))
    # warm the update kernel at EVERY batch width the flusher can
    # produce during measurement (jit keys on the batch dim; a round of
    # 8 submits can coalesce into any split like 5+3 under the 2 ms
    # deadline, and one cold compile would land straight in the p99).
    # Manual-flush mode pins each warmed width exactly; the compiled
    # kernels live in the shared registry, so they carry over.
    warm_svc = MetranService(reg, flush_deadline=None, persist_updates=False)
    for width in range(1, 9):
        warm = [
            warm_svc.update_async(f"m{i}", new_obs) for i in range(width)
        ]
        warm_svc.flush()
        [f.result() for f in warm]
    warm_svc.close()
    svc = MetranService(reg, flush_deadline=0.002, persist_updates=False)
    for _ in range(upd_rounds):
        if time.monotonic() > deadline - 10:
            break
        futs = [
            svc.update_async(f"m{i}", new_obs)
            for i in rng.choice(n_models, size=8, replace=False)
        ]
        [f.result() for f in futs]
    lat = svc.metrics.update_latency
    out["update"] = {
        "k": upd_k,
        "requests": lat.total,
        "p50_ms": round(lat.p50 * 1e3, 3),
        "p99_ms": round(lat.p99 * 1e3, 3),
        "mean_occupancy": round(svc.metrics.occupancy.mean_occupancy, 2),
    }
    # robustness counters ride along with the perf numbers: a clean run
    # reports zeros, and any nonzero here means the perf figures above
    # were measured on a degraded path
    out["errors"] = svc.metrics.errors.snapshot()
    out["health"] = svc.health()
    out["integrity"] = reg.integrity_stats
    svc.close()
    progress("serve_update", p50_ms=out["update"]["p50_ms"],
             p99_ms=out["update"]["p99_ms"])
    write_partial(out_path, out)

    # ------------------------------------------------------------------
    # arena vs dict registry at batch 512 (ROADMAP item 1's acceptance
    # measurement): the same update+forecast workload — one tick (k=1)
    # plus one forecast for every model — through (a) the device-
    # resident state arena's bulk fleet API and (b) the dict registry's
    # per-request path (the only path it has), paired interleaved laps
    # (AB/BA), ratio of medians.  Measured twice:
    #
    # - in-memory: both sides with persistence off — the pure
    #   host-work + transfer comparison (the device kernels are shared
    #   math, so they floor the ratio on a CPU host);
    # - durable: both sides at their PRODUCTION durability contract —
    #   the dict registry write-through-persists every update (its
    #   documented default), the arena dirties rows in place and
    #   checkpoints every `ckpt_every` ticks (spill time is charged to
    #   the arena's laps).
    #
    # Per-request HOST work on the arena path = bulk-lap host time /
    # batch, reported explicitly so the bound is a number.
    # ------------------------------------------------------------------
    b_arena = 32 if os.environ.get("METRAN_TPU_BENCH_SMALL") else 512
    if time.monotonic() < deadline - 60:
        import shutil
        import tempfile

        from metran_tpu.serve import ModelRegistry as _Reg

        tiles = -(-b_arena // n_models)  # posteriors tiled; ids unique
        arena_states = [
            PosteriorState(
                model_id=f"a{j}", version=0, t_seen=t_hist,
                mean=means[j % n_models], cov=covs[j % n_models],
                params=np.concatenate(
                    [alpha_sdf[j % n_models], alpha_cdf[j % n_models]]
                ),
                loadings=loadings[j % n_models], dt=1.0,
                scaler_mean=np.zeros(n), scaler_std=np.ones(n),
                names=tuple(f"s{i}" for i in range(n)),
            )
            for j in range(min(b_arena, tiles * n_models))
        ]
        ids = [st.model_id for st in arena_states]
        upd = rng.normal(size=(1, n))
        obs_batch = [upd] * len(ids)

        def _build(arena: bool, root=None, persist=False):
            reg2 = _Reg(
                root=root, arena=arena, arena_rows=b_arena,
                arena_mesh=0,
            )
            for st in arena_states:
                reg2.put(st, persist=persist)
            return MetranService(
                reg2, flush_deadline=None, max_batch=4 * b_arena,
                persist_updates=persist,
            )

        def _lap_arena(svc2):
            t0 = time.perf_counter()
            svc2.update_batch(ids, obs_batch)
            svc2.forecast_batch(ids, steps)
            return time.perf_counter() - t0

        def _lap_dict(svc2):
            t0 = time.perf_counter()
            futs2 = [svc2.update_async(m, upd) for m in ids]
            svc2.flush()
            [f.result() for f in futs2]
            futs2 = [svc2.forecast_async(m, steps) for m in ids]
            svc2.flush()
            [f.result() for f in futs2]
            return time.perf_counter() - t0

        svc_arena, svc_dict = _build(True), _build(False)
        _lap_arena(svc_arena)  # compile + warm (excluded)
        _lap_dict(svc_dict)
        pairs = []
        while len(pairs) < 4 and time.monotonic() < deadline - 40:
            if len(pairs) % 2 == 0:
                ta = _lap_arena(svc_arena)
                td = _lap_dict(svc_dict)
            else:
                td = _lap_dict(svc_dict)
                ta = _lap_arena(svc_arena)
            pairs.append((ta, td))
        svc_arena.close()
        svc_dict.close()
        if pairs:
            ta_s = [a for a, _ in pairs]
            td_s = [d for _, d in pairs]
            out["arena_vs_dict"] = {
                "batch": len(ids),
                "requests_per_lap": 2 * len(ids),
                "pairs": len(pairs),
                "arena_laps_s": [round(x, 4) for x in ta_s],
                "dict_laps_s": [round(x, 4) for x in td_s],
                "arena_qps": round(
                    2 * len(ids) / float(np.median(ta_s)), 1
                ),
                "dict_qps": round(
                    2 * len(ids) / float(np.median(td_s)), 1
                ),
                "arena_speedup": round(float(np.median(
                    [d / a for a, d in pairs]
                )), 2),
                # the whole arena lap is host work + shared device
                # kernels; per-request host budget = lap / requests
                "arena_us_per_request": round(
                    1e6 * float(np.median(ta_s)) / (2 * len(ids)), 1
                ),
                "dict_us_per_request": round(
                    1e6 * float(np.median(td_s)) / (2 * len(ids)), 1
                ),
            }
            progress(
                "serve_arena_vs_dict",
                batch=len(ids),
                arena_qps=out["arena_vs_dict"]["arena_qps"],
                dict_qps=out["arena_vs_dict"]["dict_qps"],
                speedup=out["arena_vs_dict"]["arena_speedup"],
            )
            write_partial(out_path, out)

        # durable variant: each path at its production durability
        ckpt_every = 16
        if time.monotonic() < deadline - 30:
            droot = tempfile.mkdtemp(prefix="bench_arena_")
            try:
                svc_arena = _build(
                    True, root=os.path.join(droot, "arena"),
                    persist=True,
                )
                svc_dict = _build(
                    False, root=os.path.join(droot, "dict"),
                    persist=True,
                )
                _lap_arena(svc_arena)
                svc_arena.registry.spill()
                _lap_dict(svc_dict)  # warm (excluded)
                t0 = time.perf_counter()
                laps_done = 0
                while (
                    laps_done < ckpt_every
                    and time.monotonic() < deadline - 15
                ):
                    _lap_arena(svc_arena)
                    laps_done += 1
                svc_arena.registry.spill()  # the checkpoint the laps
                #                             amortize (charged here)
                ta_dur = (time.perf_counter() - t0) / max(laps_done, 1)
                td_dur = _lap_dict(svc_dict)
                out["arena_vs_dict_durable"] = {
                    "batch": len(ids),
                    "dict_mode": "write-through npz per update "
                                 "(registry default)",
                    "arena_mode": (
                        f"in-place dirty rows, checkpoint spill every "
                        f"{ckpt_every} ticks (spill charged to laps)"
                    ),
                    "arena_laps": laps_done,
                    "arena_lap_s": round(ta_dur, 4),
                    "dict_lap_s": round(td_dur, 4),
                    "arena_qps": round(2 * len(ids) / ta_dur, 1),
                    "dict_qps": round(2 * len(ids) / td_dur, 1),
                    "arena_speedup": round(td_dur / ta_dur, 2),
                }
                progress(
                    "serve_arena_vs_dict_durable",
                    speedup=out["arena_vs_dict_durable"]["arena_speedup"],
                    arena_qps=out["arena_vs_dict_durable"]["arena_qps"],
                    dict_qps=out["arena_vs_dict_durable"]["dict_qps"],
                )
                svc_arena.close()
                svc_dict.close()
            finally:
                shutil.rmtree(droot, ignore_errors=True)
        write_partial(out_path, out)
    return out


def run_serve_load_bench(out_path: str, budget_s: float,
                         rps: "float | None" = None,
                         read_fraction: "float | None" = None,
                         cached_rps: "float | None" = None) -> dict:
    """Open-loop load generator against the arena serving path.

    Mixed read/write traffic at a FIXED arrival rate (open loop: the
    generator never slows down for the server, so falling behind shows
    up as queueing latency — the honest way to measure a latency SLO,
    unlike closed-loop benchmarks whose arrival rate collapses to the
    service rate).  Each request's latency is measured from its
    *scheduled* arrival instant to future resolution and reported as
    p50/p99/p999 plus the SLO-violation fraction against a stated SLO.

    Two sections share the discipline:

    - **dispatch** (the PR-6 path): every request rides the
      micro-batcher to a device dispatch, at ``--rps`` total arrivals;
    - **cached** (the materialized read path, ``serve.readpath``):
      reads are snapshot hits served from host memory at
      ``--cached-rps * read_fraction`` arrivals on the generator
      thread, while a writer thread sustains the remaining write
      fraction as arena fleet ticks whose commits republish the
      snapshots — the read-dominated regime the cache exists for,
      measured with hit-rate and fallback counts.

    ``--rps``/``--read-fraction``/``--cached-rps`` (CLI) or the
    ``METRAN_TPU_BENCH_LOAD_RPS``/``METRAN_TPU_BENCH_READ_FRACTION``/
    ``METRAN_TPU_BENCH_CACHED_RPS`` env knobs make the regime
    reproducible from the command line.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import threading

    import jax
    import jax.numpy as jnp

    from metran_tpu.ops import dfm_statespace, kalman_filter
    from metran_tpu.serve import (
        MetranService, ModelRegistry, PosteriorState,
    )

    n_models, n, k_fct, t_hist = 64, 8, 1, 200
    rate_rps = float(
        rps if rps is not None
        else os.environ.get("METRAN_TPU_BENCH_LOAD_RPS", "400")
    )
    read_frac = float(
        read_fraction if read_fraction is not None
        else os.environ.get("METRAN_TPU_BENCH_READ_FRACTION", "0.9")
    )
    cached_total_rps = float(
        cached_rps if cached_rps is not None
        else os.environ.get("METRAN_TPU_BENCH_CACHED_RPS", "120000")
    )
    duration_s = 15.0
    cached_duration_s = 6.0
    write_frac = 1.0 - read_frac
    slo_p99_ms = 50.0
    slo_cached_read_p99_ms = 1.0
    steps = 14
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, t_hist, duration_s = 16, 60, 4.0
        cached_duration_s = 2.0
        if rps is None:
            rate_rps = 100.0
        if cached_rps is None:
            cached_total_rps = 30000.0
    deadline = time.monotonic() + budget_s
    out = {
        "platform": jax.default_backend(),
        "mode": "arena",
        "n_models": n_models,
        "rate_rps": rate_rps,
        "duration_s": duration_s,
        "read_fraction": read_frac,
        "write_frac": write_frac,
        "slo_p99_ms": slo_p99_ms,
    }

    rng = np.random.default_rng(23)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = rng.uniform(size=y.shape) > MISSING
    y = np.where(mask, y, 0.0)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = kalman_filter(ss, yy, mm, engine="joint", store=False)
        return res.mean_f, res.cov_f

    means, covs = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, covs = np.asarray(means), np.asarray(covs)
    reg = ModelRegistry(root=None, arena=True, arena_rows=n_models)
    for i in range(n_models):
        reg.put(PosteriorState(
            model_id=f"m{i}", version=0, t_seen=t_hist,
            mean=means[i], cov=covs[i],
            params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
            loadings=loadings[i], dt=1.0,
            scaler_mean=np.zeros(n), scaler_std=np.ones(n),
            names=tuple(f"s{j}" for j in range(n)),
        ), persist=False)
    new_obs = rng.normal(size=(1, n))
    # warm every power-of-two dispatch width the generator can hit
    # (arena dispatches pad to powers of two, so these are ALL the
    # widths; a cold compile mid-run would stall the open loop and
    # snowball the backlog).  Manual-flush warm service pins each
    # width; compiled kernels live in the shared registry.
    warm_svc = MetranService(
        reg, flush_deadline=None, persist_updates=False
    )
    w = 1
    while w <= n_models:
        futs = [
            warm_svc.update_async(f"m{i}", new_obs) for i in range(w)
        ]
        warm_svc.flush()
        [f.result() for f in futs]
        futs = [
            warm_svc.forecast_async(f"m{i}", steps) for i in range(w)
        ]
        warm_svc.flush()
        [f.result() for f in futs]
        w *= 2
    warm_svc.close()
    svc = MetranService(reg, flush_deadline=0.002, persist_updates=False)
    progress("serve_load_warm")

    duration_s = min(duration_s, max(deadline - time.monotonic() - 20, 2))
    n_requests = int(rate_rps * duration_s)
    lat_lock = threading.Lock()
    read_lat: list = []
    write_lat: list = []
    failures = [0]

    def _record(scheduled, sink):
        def _done(f):
            now = time.monotonic()
            try:
                f.result()
            except Exception:
                failures[0] += 1
                return
            with lat_lock:
                sink.append(now - scheduled)

        return _done

    is_write = rng.uniform(size=n_requests) < write_frac
    targets = rng.integers(0, n_models, size=n_requests)
    t_start = time.monotonic() + 0.05
    behind_max = 0.0
    for i in range(n_requests):
        scheduled = t_start + i / rate_rps
        delay = scheduled - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        else:
            behind_max = max(behind_max, -delay)
        try:
            if is_write[i]:
                fut = svc.update_async(f"m{targets[i]}", new_obs)
                fut.add_done_callback(_record(scheduled, write_lat))
            else:
                fut = svc.forecast_async(f"m{targets[i]}", steps)
                fut.add_done_callback(_record(scheduled, read_lat))
        except Exception:
            failures[0] += 1
    # drain: everything submitted resolves through the background
    # flusher; bounded wait so a wedged worker cannot hang the bench
    t_end = time.monotonic() + 30.0
    while time.monotonic() < t_end:
        with lat_lock:
            done = len(read_lat) + len(write_lat)
        if done + failures[0] >= n_requests:
            break
        time.sleep(0.05)
    wall = time.monotonic() - t_start

    def _pcts(xs, slo_ms=None):
        if len(xs) == 0:
            return {}
        arr = np.sort(np.asarray(xs))

        def pct(q):
            return round(
                1e3 * float(arr[min(int(q * len(arr)), len(arr) - 1)]), 3
            )

        res = {
            "n": len(arr), "p50_ms": pct(0.50), "p99_ms": pct(0.99),
            "p999_ms": pct(0.999), "max_ms": round(1e3 * arr[-1], 3),
        }
        if slo_ms is not None:
            # the fraction of requests over the SLO — the quantity an
            # error budget is written against (a single p99 number
            # cannot say HOW MUCH of the traffic violated)
            res["slo_ms"] = slo_ms
            res["slo_violation_fraction"] = round(
                float(np.count_nonzero(arr > slo_ms / 1e3)) / len(arr), 6
            )
        return res

    out["requests"] = n_requests
    out["achieved_rps"] = round((n_requests - failures[0]) / wall, 1)
    out["failures"] = failures[0]
    out["generator_max_behind_s"] = round(behind_max, 4)
    out["read"] = _pcts(read_lat, slo_ms=slo_p99_ms)
    out["write"] = _pcts(write_lat, slo_ms=slo_p99_ms)
    p99_all = _pcts(read_lat + write_lat, slo_ms=slo_p99_ms)
    out["overall"] = p99_all
    out["slo_met"] = bool(
        p99_all and p99_all["p99_ms"] <= slo_p99_ms and not failures[0]
    )
    out["errors"] = svc.metrics.errors.snapshot()
    out["arena_stats"] = dict(reg.arena_stats)
    svc.close()
    progress(
        "serve_load", rps=out["achieved_rps"],
        p99_ms=p99_all.get("p99_ms"),
        p999_ms=p99_all.get("p999_ms"),
        slo_violation=p99_all.get("slo_violation_fraction"),
        slo_met=out["slo_met"],
    )
    write_partial(out_path, out)

    # ------------------------------------------------------------------
    # cached section: the materialized read path under the same
    # open-loop discipline.  Reads are snapshot hits (lock-free host
    # memory, no batcher/device) generated at a fixed rate on this
    # thread; a writer thread sustains the write fraction as arena
    # fleet ticks (`update_batch`) whose commits republish every
    # written model's snapshot — so reads keep hitting at full version
    # freshness while the posterior actually moves.
    # ------------------------------------------------------------------
    import gc
    import sys

    read_rps = cached_total_rps * read_frac
    write_rps = max(cached_total_rps - read_rps, 1.0)
    tick_w = min(n_models, 32)
    tick_interval = tick_w / write_rps
    cached_duration_s = min(
        cached_duration_s, max(deadline - time.monotonic() - 20, 1.0)
    )
    n_reads = int(read_rps * cached_duration_s)

    reg_c = ModelRegistry(root=None, arena=True, arena_rows=n_models)
    for i in range(n_models):
        reg_c.put(PosteriorState(
            model_id=f"m{i}", version=0, t_seen=t_hist,
            mean=means[i], cov=covs[i],
            params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
            loadings=loadings[i], dt=1.0,
            scaler_mean=np.zeros(n), scaler_std=np.ones(n),
            names=tuple(f"s{j}" for j in range(n)),
        ), persist=False)
    svc_c = MetranService(
        reg_c, flush_deadline=None, persist_updates=False,
        readpath=True, horizons=f"1-{steps}",
    )
    ids = [f"m{i}" for i in range(n_models)]
    # one warm tick compiles the fused kernel and publishes every
    # model's snapshot; a second with a different width warms the
    # writer's tick shape
    svc_c.update_batch(ids, rng.normal(size=(n_models, 1, n)))
    svc_c.update_batch(ids[:tick_w], rng.normal(size=(tick_w, 1, n)))
    progress("serve_load_cached_warm")

    stop = threading.Event()
    tick_lat: list = []
    writes_done = [0]

    def writer():
        wrng = np.random.default_rng(99)
        j = 0
        nxt = time.monotonic()
        while not stop.is_set():
            nxt += tick_interval
            d = nxt - time.monotonic()
            if d > 0:
                time.sleep(d)
            sel = [ids[(j + x) % n_models] for x in range(tick_w)]
            j = (j + tick_w) % n_models
            t0 = time.monotonic()
            svc_c.update_batch(sel, wrng.normal(size=(tick_w, 1, n)))
            tick_lat.append(time.monotonic() - t0)
            writes_done[0] += tick_w

    rng_t = np.random.default_rng(5)
    rid = [ids[t] for t in rng_t.integers(0, n_models, size=n_reads)]
    lat = np.empty(n_reads)
    fc = svc_c.forecast
    mono = time.monotonic
    inv = 1.0 / read_rps
    store = svc_c.readpath
    # microsecond-scale reads: shrink the GIL switch interval so the
    # writer thread's host phases cannot hold readers for the default
    # 5 ms, and keep the collector out of the measurement
    old_si = sys.getswitchinterval()
    sys.setswitchinterval(2e-4)
    gc_was = gc.isenabled()
    gc.disable()

    def read_loop(n: int, sink: np.ndarray) -> float:
        t_start = mono() + 0.02
        for i in range(n):
            scheduled = t_start + i * inv
            now = mono()
            if now < scheduled:
                d = scheduled - now
                if d > 1e-3:
                    time.sleep(d - 5e-4)
                while mono() < scheduled:
                    pass
            fc(rid[i], steps)
            sink[i] = mono() - scheduled
        return mono() - t_start

    wt = None
    try:
        # read-only leg first: the cached path at the TARGET read rate
        # with no concurrent writes — the cache's intrinsic capability,
        # separated from single-core read/write CPU contention (the
        # mixed leg below measures that contention honestly)
        n_ro = min(n_reads, int(read_rps * 1.5))
        lat_ro = np.empty(n_ro)
        h0, m0, s0 = store.hits, store.misses, store.stale
        wall_ro = read_loop(n_ro, lat_ro)
        ro_stats = _pcts(lat_ro, slo_ms=slo_cached_read_p99_ms)
        ro_cache = (store.hits - h0, store.misses - m0, store.stale - s0)
        # mixed leg: writer ticks running concurrently
        h0, m0, s0 = store.hits, store.misses, store.stale
        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        wall_c = read_loop(n_reads, lat)
    finally:
        stop.set()
        if wt is not None:
            wt.join(timeout=10.0)
        sys.setswitchinterval(old_si)
        if gc_was:
            gc.enable()
    dh = store.hits - h0
    dm = store.misses - m0
    ds = store.stale - s0
    read_stats = _pcts(lat, slo_ms=slo_cached_read_p99_ms)
    cached = {
        "mode": "arena + materialized readpath (snapshot hits)",
        "horizons": f"1-{steps}",
        "cpus": os.cpu_count(),
        "target_total_rps": cached_total_rps,
        "read_fraction": read_frac,
        "read_only": {
            # no concurrent writes: the read path's own capability at
            # the target arrival rate
            "reads": n_ro,
            "achieved_read_rps": round(n_ro / wall_ro, 1),
            "read": ro_stats,
            "cache": {
                "hits": ro_cache[0], "misses": ro_cache[1],
                "stale": ro_cache[2],
                "fallbacks": ro_cache[1] + ro_cache[2],
                "hit_rate": round(
                    ro_cache[0] / max(sum(ro_cache), 1), 6
                ),
            },
            "slo_met": bool(
                ro_stats
                and ro_stats["p99_ms"] <= slo_cached_read_p99_ms
            ),
        },
        "duration_s": round(wall_c, 3),
        "reads": n_reads,
        "achieved_read_rps": round(n_reads / wall_c, 1),
        "writes": writes_done[0],
        "achieved_write_rps": round(writes_done[0] / wall_c, 1),
        "write_tick": {
            "size": tick_w,
            **{k: v for k, v in _pcts(tick_lat).items()
               if k in ("n", "p50_ms", "p99_ms", "max_ms")},
        },
        "read": read_stats,
        "cache": {
            "hits": dh, "misses": dm, "stale": ds,
            "fallbacks": dm + ds,
            "hit_rate": round(dh / max(dh + dm + ds, 1), 6),
        },
        "slo_read_p99_ms": slo_cached_read_p99_ms,
        "slo_met": bool(
            read_stats
            and read_stats["p99_ms"] <= slo_cached_read_p99_ms
        ),
    }
    out["cached"] = cached
    out["cached_stats"] = store.stats()
    svc_c.close()
    progress(
        "serve_load_cached_readonly",
        read_rps=cached["read_only"]["achieved_read_rps"],
        p99_ms=ro_stats.get("p99_ms"),
        p999_ms=ro_stats.get("p999_ms"),
        slo_met=cached["read_only"]["slo_met"],
    )
    progress(
        "serve_load_cached",
        read_rps=cached["achieved_read_rps"],
        p99_ms=read_stats.get("p99_ms"),
        p999_ms=read_stats.get("p999_ms"),
        slo_violation=read_stats.get("slo_violation_fraction"),
        hit_rate=cached["cache"]["hit_rate"],
        slo_met=cached["slo_met"],
    )
    write_partial(out_path, out)
    return out


def run_serve_faults_bench(out_path: str, budget_s: float) -> dict:
    """Fault-injection scenario: throughput and recovery under faults.

    Exercises the `metran_tpu.reliability` layer end to end on the CPU
    backend and MEASURES the degradation story the robustness work
    promises, phase by phase:

    - clean batched update throughput as the baseline;
    - throughput with one poisoned model per batch (15/16 slots must
      keep committing — the isolation overhead is the delta vs clean);
    - circuit-breaker open -> half-open -> closed recovery latency
      after a burst of injected dispatch failures;
    - quarantine of a corrupted on-disk state (no crash, counted);
    - hard caller deadline under an injected slow dispatch (the
      observed block time must come in near the deadline, far under
      the injected wedge).
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import shutil
    import jax

    import jax.numpy as jnp

    from metran_tpu.ops import dfm_statespace, kalman_filter
    from metran_tpu.reliability import (
        CircuitOpenError, DeadlineExceededError, ReliabilityPolicy,
        RetryPolicy, StateIntegrityError, faultinject,
    )
    from metran_tpu.serve import (
        MetranService, ModelRegistry, PosteriorState,
    )

    n_models, n, k_fct, t_hist, rounds = 16, 8, 1, 100, 8
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, t_hist, rounds = 8, 40, 3
    deadline = time.monotonic() + budget_s
    out = {
        "platform": jax.default_backend(),
        "n_models": n_models, "n_series": n, "t_hist": t_hist,
    }

    rng = np.random.default_rng(17)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = rng.uniform(size=y.shape) > MISSING
    y = np.where(mask, y, 0.0)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = kalman_filter(ss, yy, mm, engine="joint", store=False)
        return res.mean_f, res.cov_f

    means, covs = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, covs = np.asarray(means), np.asarray(covs)

    store = os.path.join(CACHE_DIR, "serve_faults_store")
    shutil.rmtree(store, ignore_errors=True)
    reg = ModelRegistry(root=store)

    def make_state(i, poison=False):
        mean = np.full_like(means[i], np.nan) if poison else means[i]
        return PosteriorState(
            model_id=f"m{i}", version=0, t_seen=t_hist,
            mean=mean, cov=covs[i],
            params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
            loadings=loadings[i], dt=1.0,
            scaler_mean=np.zeros(n), scaler_std=np.ones(n),
            names=tuple(f"s{j}" for j in range(n)),
        )

    for i in range(n_models):
        reg.put(make_state(i))

    policy = ReliabilityPolicy(
        deadline_s=10.0,
        retry=RetryPolicy(max_attempts=2, backoff_s=0.005),
        breaker_failures=3, breaker_cooldown_s=0.25,
    )
    svc = MetranService(reg, flush_deadline=None, reliability=policy)
    new_obs = rng.normal(size=(1, n))

    def one_round():
        futs = []
        for i in range(n_models):
            try:  # a model whose breaker opened rejects AT submit
                futs.append(svc.update_async(f"m{i}", new_obs))
            except Exception:
                futs.append(None)
        svc.flush()
        done = fail = 0
        for f in futs:
            try:
                if f is None:
                    raise RuntimeError("rejected at submit")
                f.result(timeout=30)
                done += 1
            except Exception:
                fail += 1
        return done, fail

    one_round()  # compile warmup
    t0 = time.perf_counter()
    for _ in range(rounds):
        one_round()
    clean_s = time.perf_counter() - t0
    out["clean"] = {
        "rounds": rounds,
        "update_qps": round(n_models * rounds / clean_s, 1),
    }
    progress("faults_clean", qps=out["clean"]["update_qps"])

    # one poisoned model per batch: 15/16 slots must keep committing
    reg.put(make_state(3, poison=True))
    t0 = time.perf_counter()
    committed = failed = 0
    for _ in range(rounds):
        d, f = one_round()
        committed += d
        failed += f
    poisoned_s = time.perf_counter() - t0
    reg.put(make_state(3))  # heal
    out["poisoned_slot"] = {
        "committed": committed, "failed": failed,
        "expected_failed": rounds,  # exactly the poisoned slot per round
        "degraded_qps": round(committed / poisoned_s, 1),
        "isolation_ok": failed == rounds
        and committed == (n_models - 1) * rounds,
    }
    progress("faults_poisoned", **{
        k: v for k, v in out["poisoned_slot"].items() if k != "committed"
    })
    write_partial(out_path, out)

    # breaker recovery: a burst of dispatch failures opens m0's breaker;
    # measure fault-clear -> first committed update (cooldown + probe)
    with faultinject.active() as inj:
        inj.add("serve.dispatch", error=RuntimeError("injected outage"),
                match="update")
        breaker_failures = 0
        for _ in range(policy.breaker_failures * policy.retry.max_attempts):
            try:
                svc.update("m0", new_obs)
            except (RuntimeError, CircuitOpenError):
                breaker_failures += 1
            if svc.breakers.get("m0").state == "open":
                break
    opened = svc.breakers.get("m0").state == "open"
    t0 = time.perf_counter()
    recovered = False
    while time.perf_counter() - t0 < 10.0:
        try:
            svc.update("m0", new_obs)
            recovered = True
            break
        except CircuitOpenError:
            time.sleep(0.02)
    out["breaker"] = {
        "opened": opened,
        "recovered": recovered,
        "recovery_s": round(time.perf_counter() - t0, 3),
        "cooldown_s": policy.breaker_cooldown_s,
    }
    progress("faults_breaker", **out["breaker"])

    # quarantine: corrupt one on-disk state, drop the memory copy — the
    # service must degrade (request fails, file quarantined), not crash
    reg._states.pop("m5", None)
    with open(reg.path_for("m5"), "wb") as fh:
        fh.write(b"garbage " * 64)
    try:
        svc.forecast("m5", 4)
        quarantine_raised = False
    except StateIntegrityError:
        quarantine_raised = True
    out["quarantine"] = {
        "raised": quarantine_raised,
        "still_member": "m5" in reg,
        "events": reg.integrity_stats,
    }
    progress("faults_quarantine", **{
        "raised": quarantine_raised,
        "quarantined": reg.integrity_stats.get("quarantined", 0),
    })
    out["errors"] = svc.metrics.errors.snapshot()
    out["health"] = svc.health()
    svc.close()
    write_partial(out_path, out)

    # hard deadline under a wedged dispatch (background flusher mode)
    if time.monotonic() < deadline - 20:
        svc2 = MetranService(
            reg, flush_deadline=0.002,
            reliability=ReliabilityPolicy(
                deadline_s=0.15, retry=RetryPolicy(max_attempts=1),
            ),
        )
        with faultinject.active() as inj:
            inj.add("serve.dispatch", delay_s=1.0, times=1)
            t0 = time.perf_counter()
            try:
                svc2.forecast("m0", 4)
                blocked_s, fired = time.perf_counter() - t0, False
            except DeadlineExceededError:
                blocked_s, fired = time.perf_counter() - t0, True
        svc2.close()
        out["deadline"] = {
            "configured_s": 0.15,
            "injected_wedge_s": 1.0,
            "observed_block_s": round(blocked_s, 3),
            "fired": fired,
            "bounded": fired and blocked_s < 0.9,
        }
        progress("faults_deadline", **out["deadline"])
    shutil.rmtree(store, ignore_errors=True)
    write_partial(out_path, out)
    return out


# ----------------------------------------------------------------------
# phase: square-root engine (robustness cost + f32 drift per regime)
# ----------------------------------------------------------------------
def run_sqrt_bench(out_path: str, budget_s: float) -> dict:
    """Square-root vs covariance engine: runtime overhead and f32 drift.

    Two questions an operator picking ``engine="sqrt"`` asks:

    1. what does the QR-based robustness cost per deviance /
       value-and-grad evaluation versus the ``joint`` engine, and
    2. how much closer does f32 land to f64 per alpha regime — in
       particular the near-unit-root cap regime where the covariance
       engine's drift is 10x-bar material (tests/test_precision.py).

    Runs on whatever backend the environment provides; shapes follow
    the flagship benchmark config at a bounded T so the whole phase
    fits a small budget.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import jax

    # the drift half of this phase needs true float64 references; on an
    # accelerator the f64 evaluations run emulated (slow but correct —
    # the budget guard bounds them), the f32 timings are native either
    # way
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from metran_tpu.ops import deviance, dfm_statespace

    n, k_fct, t_steps, reps = N_SERIES, N_FACTORS, 2000, 3
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        t_steps, reps = 400, 2
    deadline = time.monotonic() + budget_s
    out = {
        "platform": jax.default_backend(),
        "n_series": n, "n_factors": k_fct, "t_steps": t_steps,
        "engines": ["joint", "sqrt"],
        "regimes": {}, "overhead": {},
    }

    rng = np.random.default_rng(0)
    loadings = rng.uniform(0.4, 0.8, (n, k_fct))
    mask = rng.uniform(size=(t_steps, n)) > MISSING
    mask[0] = False
    y = np.where(mask, rng.normal(size=(t_steps, n)), 0.0)
    regimes = {
        "init": np.full(n + k_fct, 10.0),
        "fast": np.full(n + k_fct, 0.1),
        "near_unit_root": np.full(n + k_fct, 3e4),
        "mixed": np.concatenate([np.linspace(0.1, 100.0, n), [1e4] * k_fct]),
    }

    def dev(alpha, dtype, engine):
        ss = dfm_statespace(
            jnp.asarray(alpha[:n], dtype), jnp.asarray(alpha[n:], dtype),
            jnp.asarray(loadings, dtype), 1.0,
        )
        return deviance(
            ss, jnp.asarray(y, dtype), jnp.asarray(mask), warmup=1,
            engine=engine,
        )

    # f32-vs-f64 deviance drift per regime, both engines
    for name, alpha in regimes.items():
        row = {}
        for engine in ("joint", "sqrt"):
            v64 = float(dev(alpha, jnp.float64, engine))
            v32 = float(dev(alpha, jnp.float32, engine))
            row[f"dev_rel_f32_{engine}"] = abs(v32 - v64) / abs(v64)
        row["abs_dev"] = abs(v64)
        out["regimes"][name] = row
        progress("sqrt_drift", regime=name, **{
            k: f"{v:.3e}" for k, v in row.items()
        })
        if time.monotonic() > deadline:
            out["truncated"] = "budget"
            write_partial(out_path, out)
            return out

    # runtime overhead: jitted deviance and value-and-grad, f32, the
    # interior init regime (representative optimizer workload)
    alpha32 = jnp.asarray(regimes["init"], jnp.float32)

    def timed(fn, *args):
        warm = fn(*args)  # warm (compile)
        (warm[0] if isinstance(warm, tuple) else warm).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(reps):
            r = fn(*args)
            (r[0] if isinstance(r, tuple) else r).block_until_ready()
        return (time.perf_counter() - t0) / reps

    for engine in ("joint", "sqrt"):
        f = jax.jit(lambda a, e=engine: dev(a, jnp.float32, e))
        vg = jax.jit(jax.value_and_grad(
            lambda a, e=engine: dev(a, jnp.float32, e)
        ))
        out["overhead"][f"deviance_s_{engine}"] = timed(f, alpha32)
        out["overhead"][f"value_and_grad_s_{engine}"] = timed(vg, alpha32)
    oh = out["overhead"]
    oh["sqrt_vs_joint_deviance"] = (
        oh["deviance_s_sqrt"] / max(oh["deviance_s_joint"], 1e-12)
    )
    oh["sqrt_vs_joint_value_and_grad"] = (
        oh["value_and_grad_s_sqrt"]
        / max(oh["value_and_grad_s_joint"], 1e-12)
    )
    progress("sqrt_overhead", **{
        k: round(v, 4) for k, v in oh.items()
    })
    write_partial(out_path, out)
    return out


# ----------------------------------------------------------------------
# phase: observability overhead (tracing + metrics on vs off)
# ----------------------------------------------------------------------
def run_obs_bench(out_path: str, budget_s: float) -> dict:
    """Instrumentation-overhead scenario: the serve path measured with
    the full observability stack (metrics registry + request tracing +
    event log, and — as shipped since ISSUE 13 — the capacity plane)
    against the same path with everything disabled.

    The acceptance bar is < 5% serve-throughput overhead for the PR 4
    stack (metrics + tracing + events, ``pr4_stack_pct`` — the series
    this phase has carried since r04): observability must be cheap
    enough to leave ON in production, or nobody has it when the
    incident happens.  The as-shipped total (``forecast_qps_pct``)
    and the capacity plane's own share (``capacity_share_pct``) are
    reported next to it; the capacity plane's OWN bars — <= 5% on the
    arena bulk update path, <= 1% on cached reads — are enforced by
    ``--phase capacity``, the same per-subsystem attribution
    discipline the detect phase uses.  Reported per mode: batched
    forecast qps (manual flush, one dispatch per lap) and update
    p50/p99 through the same path, plus the exposition size and span
    counts the instrumented run produced.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import jax
    import jax.numpy as jnp

    from metran_tpu.obs import (
        EventLog, MetricsRegistry, Observability, Tracer,
    )
    from metran_tpu.ops import dfm_statespace, kalman_filter
    from metran_tpu.serve import (
        MetranService, ModelRegistry, PosteriorState,
    )

    n_models, n, k_fct, t_hist = 64, 8, 1, 200
    steps, fc_rounds, upd_rounds = 14, 200, 40
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, t_hist, fc_rounds, upd_rounds = 16, 60, 10, 8
    deadline = time.monotonic() + budget_s
    out = {
        "platform": jax.default_backend(),
        "n_models": n_models, "n_series": n, "t_hist": t_hist,
        "modes": {},
    }

    rng = np.random.default_rng(7)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = rng.uniform(size=y.shape) > MISSING
    y = np.where(mask, y, 0.0)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = kalman_filter(ss, yy, mm, engine="joint", store=False)
        return res.mean_f, res.cov_f

    means, covs = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, covs = np.asarray(means), np.asarray(covs)

    def make_registry():
        reg = ModelRegistry(root=None)
        for i in range(n_models):
            reg.put(PosteriorState(
                model_id=f"m{i}", version=0, t_seen=t_hist,
                mean=means[i], cov=covs[i],
                params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
                loadings=loadings[i], dt=1.0,
                scaler_mean=np.zeros(n), scaler_std=np.ones(n),
                names=tuple(f"s{j}" for j in range(n)),
            ), persist=False)
        return reg

    new_obs = rng.normal(size=(1, n))
    # production-default ring sizes: the bar is the cost of leaving
    # instrumentation ON as shipped, not of an oversized capture
    # buffer.  Three services so the as-shipped number (which now
    # includes the PR 13 capacity plane) splits into the PR 4
    # metrics/tracing/events stack and the capacity plane's share —
    # the detect-phase attribution discipline.
    def full_obs():
        return Observability(
            metrics=MetricsRegistry(),
            tracer=Tracer(),
            events=EventLog(),
        )

    services = {
        "off": MetranService(
            make_registry(), flush_deadline=None, max_batch=4 * n_models,
            persist_updates=False, observability=Observability.disabled(),
        ),
        "nocap": MetranService(
            make_registry(), flush_deadline=None, max_batch=4 * n_models,
            persist_updates=False, observability=full_obs(),
            capacity=False,
        ),
        "on": MetranService(
            make_registry(), flush_deadline=None, max_batch=4 * n_models,
            persist_updates=False, observability=full_obs(),
        ),
    }

    def fc_lap(svc) -> float:
        t0 = time.perf_counter()
        futs = [svc.forecast_async(f"m{i}", steps)
                for i in range(n_models)]
        svc.flush()
        [f.result() for f in futs]
        return time.perf_counter() - t0

    def upd_round(svc, ids) -> None:
        futs = [svc.update_async(f"m{i}", new_obs) for i in ids]
        svc.flush()
        [f.result() for f in futs]

    # warm every kernel on both services (each owns its jit closures),
    # then drop the compile-dominated warm-up samples so the reported
    # percentiles describe steady-state traffic only
    for svc in services.values():
        fc_lap(svc)
        upd_round(svc, range(8))
        svc.metrics.update_latency.reset()
        svc.metrics.forecast_latency.reset()
    # interleave the two modes lap by lap: host drift (governor, cache,
    # neighbours) hits both alike, so the PAIRED per-lap ratio isolates
    # the instrumentation cost — a sequential A-then-B run was measured
    # drifting by more than the 5% bar itself.  The order inside each
    # pair alternates (AB, BA, AB, ...) so slow monotone drift cancels
    # out of the ratio instead of biasing one mode.
    names = list(services)
    fc_laps = {mode: [] for mode in names}
    fc_ratios = []
    fc_ratios_nocap = []
    fc_ratios_cap = []
    for r in range(fc_rounds):
        if time.monotonic() > deadline - 30:
            break
        order = names if r % 2 == 0 else names[::-1]
        pair = {mode: fc_lap(services[mode]) for mode in order}
        for mode, dt in pair.items():
            fc_laps[mode].append(dt)
        fc_ratios.append(pair["on"] / pair["off"])
        fc_ratios_nocap.append(pair["nocap"] / pair["off"])
        fc_ratios_cap.append(pair["on"] / pair["nocap"])
    for _ in range(upd_rounds):
        if time.monotonic() > deadline - 10:
            break
        ids = rng.choice(n_models, size=8, replace=False)
        for svc in services.values():
            upd_round(svc, ids)

    for mode, svc in services.items():
        lat = svc.metrics.update_latency
        laps = fc_laps[mode]
        res = {
            "forecast_qps": (
                round(n_models / float(np.median(laps)), 1)
                if laps else 0.0
            ),
            "forecast_laps": len(laps),
            "update_p50_ms": round(lat.p50 * 1e3, 3),
            "update_p99_ms": round(lat.p99 * 1e3, 3),
            "update_requests": lat.total,
        }
        obs = svc.obs
        if obs.metrics is not None:
            exposition = obs.metrics.render_prometheus()
            res["exposition_bytes"] = len(exposition)
            res["exposition_metrics"] = len(obs.metrics.names())
        if obs.tracer is not None:
            res["spans_recorded"] = len(obs.tracer.spans())
            res["spans_dropped"] = obs.tracer.dropped
        if obs.events is not None:
            res["events"] = obs.events.counts()
        out["modes"][mode] = res
        progress(f"obs_{mode}", qps=res["forecast_qps"],
                 p99_ms=res["update_p99_ms"])
        svc.close()
    off, on = out["modes"]["off"], out["modes"]["on"]
    p99_off = max(off["update_p99_ms"], 1e-9)
    # throughput overhead from the MEDIAN PAIRED ratio, not the ratio
    # of medians: each ratio compares two back-to-back laps, so host
    # drift between distant laps cannot masquerade as instrumentation
    # cost (qps overhead = 1 - 1/r for a lap-time ratio r)
    ratio = float(np.median(fc_ratios)) if fc_ratios else 1.0
    r_nocap = float(np.median(fc_ratios_nocap)) if fc_ratios_nocap else 1.0
    r_cap = float(np.median(fc_ratios_cap)) if fc_ratios_cap else 1.0
    out["overhead"] = {
        # positive = instrumentation costs throughput/latency; the
        # headline is the AS-SHIPPED stack (metrics + tracing +
        # events + the capacity plane), split into the PR 4 stack and
        # the capacity plane's own share
        "forecast_qps_pct": round(100.0 * (1.0 - 1.0 / ratio), 2),
        "pr4_stack_pct": round(100.0 * (1.0 - 1.0 / r_nocap), 2),
        "capacity_share_pct": round(100.0 * (1.0 - 1.0 / r_cap), 2),
        "update_p99_pct": round(
            100.0 * (on["update_p99_ms"] / p99_off - 1.0), 2
        ),
    }
    progress("obs_overhead", **out["overhead"])
    write_partial(out_path, out)

    # ------------------------------------------------------------------
    # cached-read path (serve.readpath): full instrumentation vs
    # disabled on SNAPSHOT HITS.  The cached read is a ~2µs host-memory
    # path with no span/breaker/batcher (the short-circuit in
    # forecast/forecast_async), and its cache counters are callback
    # gauges read at scrape time — so the 5% bar must hold with huge
    # margin here, and this measures that it does.
    # ------------------------------------------------------------------
    cr_reads = 2000 if os.environ.get("METRAN_TPU_BENCH_SMALL") else 20000
    cr_rounds = 5 if os.environ.get("METRAN_TPU_BENCH_SMALL") else 15

    def make_cached_service(bundle):
        reg = ModelRegistry(root=None, arena=True, arena_rows=n_models)
        for i in range(n_models):
            reg.put(PosteriorState(
                model_id=f"m{i}", version=0, t_seen=t_hist,
                mean=means[i], cov=covs[i],
                params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
                loadings=loadings[i], dt=1.0,
                scaler_mean=np.zeros(n), scaler_std=np.ones(n),
                names=tuple(f"s{j}" for j in range(n)),
            ), persist=False)
        svc = MetranService(
            reg, flush_deadline=None, persist_updates=False,
            observability=bundle, readpath=True, horizons=f"1-{steps}",
        )
        # one bulk tick publishes every model's snapshot
        svc.update_batch(
            [f"m{i}" for i in range(n_models)],
            np.broadcast_to(new_obs, (n_models, 1, n)),
        )
        return svc

    cached_svcs = {
        "off": make_cached_service(Observability.disabled()),
        "on": make_cached_service(Observability(
            metrics=MetricsRegistry(), tracer=Tracer(), events=EventLog(),
        )),
    }

    def cached_lap(svc) -> float:
        fcf = svc.forecast
        t0 = time.perf_counter()
        for i in range(cr_reads):
            fcf(f"m{i % n_models}", steps)
        return time.perf_counter() - t0

    for svc in cached_svcs.values():  # warm
        cached_lap(svc)
    cr_ratios, cr_laps = [], {"off": [], "on": []}
    for r in range(cr_rounds):
        if time.monotonic() > deadline - 5:
            break
        order = ("off", "on") if r % 2 == 0 else ("on", "off")
        pair = {mode: cached_lap(cached_svcs[mode]) for mode in order}
        for mode, dt in pair.items():
            cr_laps[mode].append(dt)
        cr_ratios.append(pair["on"] / pair["off"])
    cr_ratio = float(np.median(cr_ratios)) if cr_ratios else 1.0
    out["cached_read"] = {
        "reads_per_lap": cr_reads,
        "off_reads_per_s": (
            round(cr_reads / float(np.median(cr_laps["off"])), 1)
            if cr_laps["off"] else 0.0
        ),
        "on_reads_per_s": (
            round(cr_reads / float(np.median(cr_laps["on"])), 1)
            if cr_laps["on"] else 0.0
        ),
        "hits_on": cached_svcs["on"].readpath.hits,
        # positive = instrumentation costs cached-read throughput;
        # the bar is the same 5% the dispatch path carries
        "overhead_pct": round(100.0 * (1.0 - 1.0 / cr_ratio), 2),
    }
    for svc in cached_svcs.values():
        svc.close()
    progress(
        "obs_cached_read",
        on_reads_per_s=out["cached_read"]["on_reads_per_s"],
        overhead_pct=out["cached_read"]["overhead_pct"],
    )
    write_partial(out_path, out)
    return out


# ----------------------------------------------------------------------
# phase: input robustness (gated serving under sensor faults)
# ----------------------------------------------------------------------
def run_robust_obs_bench(out_path: str, budget_s: float) -> dict:
    """Statistical input-robustness scenario: accuracy under corrupted
    sensor feeds with the observation gate on vs off, plus the armed
    gate's cost on the serving hot path.

    Two acceptance claims (docs/concepts.md "Input robustness"):

    1. under spike / stuck / drift / unit-error sensor faults, GATED
       serving keeps posterior RMSE within 2x of a clean-data run
       while ungated serving measurably degrades (the
       ``reliability.scenarios`` harness — the same numbers the
       ``-m faults`` scenario tests assert);
    2. an ARMED gate costs < 3% forecast throughput versus the same
       service with the gate off (paired interleaved laps, the
       ``--phase obs`` methodology), and the per-update overhead is
       reported alongside.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import jax
    import jax.numpy as jnp

    from metran_tpu.obs import Observability
    from metran_tpu.ops import dfm_statespace, kalman_filter
    from metran_tpu.reliability.scenarios import run_sensor_fault_scenario
    from metran_tpu.serve import (
        GateSpec, MetranService, ModelRegistry, PosteriorState,
    )

    deadline = time.monotonic() + budget_s
    out = {
        "platform": jax.default_backend(),
        "scenarios": {},
        "overhead": {},
    }

    # -- accuracy under fault: gate on vs off per fault mode -----------
    n_steps = 60
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_steps = 30
    for mode in ("spike", "stuck", "drift", "unit"):
        res = run_sensor_fault_scenario(
            mode, policy="reject", nsigma=4.0, n_steps=n_steps,
        )
        res["within_2x_clean"] = bool(res["gated_vs_clean"] <= 2.0)
        res["ungated_degraded"] = bool(res["ungated_vs_gated"] >= 1.5)
        out["scenarios"][mode] = res
        progress(
            f"robust_{mode}",
            gated_vs_clean=round(res["gated_vs_clean"], 2),
            ungated_vs_gated=round(res["ungated_vs_gated"], 2),
            rejected=res["verdicts"].get("rejected", 0),
        )
        write_partial(out_path, out)
        if time.monotonic() > deadline - 90:
            out["truncated"] = "budget"
            return out

    # -- armed-gate overhead on the hot path ---------------------------
    n_models, n, k_fct, t_hist = 32, 8, 1, 120
    steps, rounds = 14, 120
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, rounds = 8, 12
    rng = np.random.default_rng(23)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = rng.uniform(size=y.shape) > MISSING
    y = np.where(mask, y, 0.0)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = kalman_filter(ss, yy, mm, engine="joint", store=False)
        return res.mean_f, res.cov_f

    means, covs = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, covs = np.asarray(means), np.asarray(covs)

    def make_registry():
        reg = ModelRegistry(root=None)
        for i in range(n_models):
            reg.put(PosteriorState(
                model_id=f"m{i}", version=0, t_seen=t_hist,
                mean=means[i], cov=covs[i],
                params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
                loadings=loadings[i], dt=1.0,
                scaler_mean=np.zeros(n), scaler_std=np.ones(n),
                names=tuple(f"s{j}" for j in range(n)),
            ), persist=False)
        return reg

    # a wide-open gate (nsigma=12): the overhead of RUNNING the gated
    # kernel + verdict booking, not of rejections changing the workload
    services = {
        "off": MetranService(
            make_registry(), flush_deadline=None,
            max_batch=4 * n_models, persist_updates=False,
            observability=Observability.disabled(),
            gate=GateSpec(policy="off"),
        ),
        "on": MetranService(
            make_registry(), flush_deadline=None,
            max_batch=4 * n_models, persist_updates=False,
            observability=Observability.disabled(),
            gate=GateSpec(policy="reject", nsigma=12.0, min_seen=1),
        ),
    }
    new_obs = rng.normal(size=(1, n)) * 0.1

    def fc_lap(svc) -> float:
        t0 = time.perf_counter()
        futs = [svc.forecast_async(f"m{i}", steps)
                for i in range(n_models)]
        svc.flush()
        [f.result() for f in futs]
        return time.perf_counter() - t0

    def upd_lap(svc) -> float:
        t0 = time.perf_counter()
        futs = [svc.update_async(f"m{i}", new_obs)
                for i in range(n_models)]
        svc.flush()
        [f.result() for f in futs]
        return time.perf_counter() - t0

    for svc in services.values():  # compile warm-up, both kernels
        fc_lap(svc)
        upd_lap(svc)
    fc_ratios, upd_ratios = [], []
    for r in range(rounds):
        if time.monotonic() > deadline - 20:
            break
        order = ("off", "on") if r % 2 == 0 else ("on", "off")
        fc_pair = {m: fc_lap(services[m]) for m in order}
        upd_pair = {m: upd_lap(services[m]) for m in order}
        fc_ratios.append(fc_pair["on"] / fc_pair["off"])
        upd_ratios.append(upd_pair["on"] / upd_pair["off"])
    fc_r = float(np.median(fc_ratios)) if fc_ratios else 1.0
    upd_r = float(np.median(upd_ratios)) if upd_ratios else 1.0
    out["overhead"] = {
        "laps": len(fc_ratios),
        # qps overhead = 1 - 1/r for a paired lap-time ratio r
        "forecast_qps_pct": round(100.0 * (1.0 - 1.0 / fc_r), 2),
        "update_qps_pct": round(100.0 * (1.0 - 1.0 / upd_r), 2),
        "bar_pct": 3.0,
    }
    for svc in services.values():
        svc.close()
    progress("robust_overhead", **{
        k: v for k, v in out["overhead"].items() if k != "laps"
    })
    write_partial(out_path, out)
    return out


def run_robust_bench(out_path: str, budget_s: float) -> dict:
    """Non-Gaussian observation robustness scenario: the implicit-MAP
    update engine measured against the reject gate (docs/concepts.md
    "Non-Gaussian observations", ISSUE 15).

    Three measurement stories:

    1. **accuracy under degraded sensors, per likelihood** — the
       ``run_robust_fault_scenario`` harness (clean / naive /
       reject-gated / robust on identical seeded corruption,
       observation-space RMSE pooled over stationary panels): the
       acceptance headline is censored serving beating the reject
       gate by >= 2x on railed streams, with quantized and
       heavy-tailed (Student-t vs spikes) modes reported alongside;
    2. **a censored seed sweep** — the 2x margin is realization
       physics (how deep the truth goes beyond the rail), so the
       sweep keeps milder regimes visible instead of cherry-picking
       one stream;
    3. **armed overhead** (paired interleaved laps, the ``--phase
       obs`` methodology): a censored spec whose stream never rails —
       the minority-armed regime, bar < 10% on a 90/10 read/write
       serving mix (the robust path touches only the update kernels;
       reads are untouched by construction) — with the update-only
       cost and the all-slots-armed ``huber_t`` cost reported
       honestly next to it.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import jax
    import jax.numpy as jnp

    from metran_tpu.obs import Observability
    from metran_tpu.ops import dfm_statespace, sqrt_kalman_filter
    from metran_tpu.reliability.scenarios import (
        run_robust_fault_scenario,
    )
    from metran_tpu.serve import (
        MetranService, ModelRegistry, PosteriorState, RobustSpec,
    )

    deadline = time.monotonic() + budget_s
    out = {
        "platform": jax.default_backend(),
        "scenarios": {},
        "censor_seed_sweep": [],
        "overhead": {},
    }
    small = bool(os.environ.get("METRAN_TPU_BENCH_SMALL"))

    # -- accuracy under fault: robust vs reject-gating per likelihood --
    steps = {"censor": 400, "quantize": 200, "spike": 200}
    if small:
        steps = {k: v // 4 for k, v in steps.items()}
    for mode in ("censor", "quantize", "spike"):
        res = run_robust_fault_scenario(mode, n_steps=steps[mode])
        res["meets_2x_bar"] = (
            bool(res["gated_vs_robust"] >= 2.0)
            if mode == "censor" else None
        )
        out["scenarios"][mode] = res
        progress(
            f"robust_{mode}",
            gated_vs_robust=round(res["gated_vs_robust"], 2),
            naive_vs_robust=round(res["naive_vs_robust"], 2),
            rmse_robust=round(res["rmse_robust"], 4),
        )
        write_partial(out_path, out)
        if time.monotonic() > deadline - 120:
            out["truncated"] = "budget"
            return out

    # -- censored seed sweep: the margin's realization spread ----------
    for seed in (0, 1, 3, 4):
        if time.monotonic() > deadline - 100:
            break
        res = run_robust_fault_scenario(
            mode="censor", seed=seed, n_steps=steps["censor"]
        )
        out["censor_seed_sweep"].append({
            "seed": seed,
            "railed_fraction": res["railed_fraction"],
            "gated_vs_robust": round(res["gated_vs_robust"], 3),
            "naive_vs_robust": round(res["naive_vs_robust"], 3),
        })
        write_partial(out_path, out)

    # -- armed overhead on the serving hot path ------------------------
    n_models, n, k_fct, t_hist = 32, 8, 1, 120
    steps_fc, rounds = 14, 80
    if small:
        n_models, rounds = 8, 10
    rng = np.random.default_rng(23)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = np.ones(y.shape, bool)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = sqrt_kalman_filter(ss, yy, mm, store=False)
        return res.mean_f, res.chol_f

    means, chols = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, chols = np.asarray(means), np.asarray(chols)

    def make_registry():
        reg = ModelRegistry(root=None, engine="sqrt")
        for i in range(n_models):
            reg.put(PosteriorState(
                model_id=f"m{i}", version=0, t_seen=t_hist,
                mean=means[i], cov=chols[i] @ chols[i].T,
                chol=chols[i],
                params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
                loadings=loadings[i], dt=1.0,
                scaler_mean=np.zeros(n), scaler_std=np.ones(n),
                names=tuple(f"s{j}" for j in range(n)),
            ), persist=False)
        return reg

    # a censored spec whose rails the stream never reaches: the armed
    # MINORITY-FLAGGED cost (the kernel runs, nothing flags); huber_t
    # is the honest all-slots-armed cost (every reading MAP-scored)
    services = {
        "off": MetranService(
            make_registry(), flush_deadline=None,
            max_batch=16 * n_models, persist_updates=False,
            observability=Observability.disabled(),
        ),
        "censored": MetranService(
            make_registry(), flush_deadline=None,
            max_batch=16 * n_models, persist_updates=False,
            observability=Observability.disabled(),
            robust=RobustSpec(likelihood="censored", rail_lo=-50.0,
                              rail_hi=50.0, min_seen=1),
        ),
        "huber": MetranService(
            make_registry(), flush_deadline=None,
            max_batch=16 * n_models, persist_updates=False,
            observability=Observability.disabled(),
            robust=RobustSpec(likelihood="huber_t", min_seen=1,
                              scale=0.1),
        ),
    }
    new_obs = rng.normal(size=(1, n)) * 0.1

    def upd_lap(svc) -> float:
        t0 = time.perf_counter()
        futs = [svc.update_async(f"m{i}", new_obs)
                for i in range(n_models)]
        svc.flush()
        [f.result() for f in futs]
        return time.perf_counter() - t0

    def mixed_lap(svc) -> float:
        # the 90/10 read/write serving mix the <10% bar is against
        # (the robust path touches only the update kernels)
        t0 = time.perf_counter()
        futs = [svc.forecast_async(f"m{i % n_models}", steps_fc)
                for i in range(9 * n_models)]
        ufuts = [svc.update_async(f"m{i}", new_obs)
                 for i in range(n_models)]
        svc.flush()
        [f.result() for f in futs]
        [f.result() for f in ufuts]
        return time.perf_counter() - t0

    for svc in services.values():  # compile warm-up
        upd_lap(svc)
        mixed_lap(svc)
    upd_ratios = {"censored": [], "huber": []}
    mix_ratios = {"censored": [], "huber": []}
    for r in range(rounds):
        if time.monotonic() > deadline - 20:
            break
        order = (
            ("off", "censored", "huber") if r % 2 == 0
            else ("huber", "censored", "off")
        )
        u = {m: upd_lap(services[m]) for m in order}
        x = {m: mixed_lap(services[m]) for m in order}
        for m in ("censored", "huber"):
            upd_ratios[m].append(u[m] / u["off"])
            mix_ratios[m].append(x[m] / x["off"])
    for svc in services.values():
        svc.close()

    def pct(ratios) -> float:
        r = float(np.median(ratios)) if ratios else 1.0
        return round(100.0 * (1.0 - 1.0 / r), 2)

    out["overhead"] = {
        "laps": len(upd_ratios["censored"]),
        # the acceptance number: minority-armed serving-mix overhead
        "serving_mix_pct": pct(mix_ratios["censored"]),
        "update_only_pct": pct(upd_ratios["censored"]),
        # honest all-slots-armed cost (every reading MAP-scored)
        "huber_all_slots_serving_mix_pct": pct(mix_ratios["huber"]),
        "huber_all_slots_update_only_pct": pct(upd_ratios["huber"]),
        "bar_pct": 10.0,
        "mix_read_fraction": 0.9,
    }
    progress("robust_overhead", **{
        k: v for k, v in out["overhead"].items() if k != "laps"
    })
    write_partial(out_path, out)
    return out


def run_steady_bench(out_path: str, budget_s: float) -> dict:
    """Bounded-cost serving scenario: steady-state gain freeze.

    Three acceptance claims (docs/concepts.md "Bounded-cost serving",
    ISSUE 8):

    1. the steady (frozen-gain, mean-only) update path sustains
       **>= 2x** the exact armed-gate update throughput at batch
       >= 256 (paired interleaved laps, the ``--phase obs``
       methodology — both services consume the identical stream);
    2. the realized max frozen-vs-exact posterior-mean deviation is
       measured and reported NEXT TO the configured freeze tolerance
       (the calibrated-approximation contract);
    3. update cost is **flat in t_seen** — the same tick costs the
       same whether the model has seen 1e2 or 1e6 grid steps (nothing
       on the serving path touches history).
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import jax
    import jax.numpy as jnp

    from metran_tpu.obs import Observability
    from metran_tpu.ops import dfm_statespace, kalman_filter
    from metran_tpu.serve import (
        ArenaUpdateAck, GateSpec, MetranService, ModelRegistry,
        PosteriorState, SteadySpec,
    )

    deadline = time.monotonic() + budget_s
    out = {"platform": jax.default_backend(), "steady": {},
           "flatness": {}}

    # n=16 series, 2 factors (state dim 18, padded (16, 24)): a mid-
    # size monitoring model, large enough that the covariance work the
    # steady path removes (the QR over stacked (N+S)-wide factor
    # blocks) dominates the tick over the shared host path — tiny
    # n=8 models on a 1-core host are host-bound on BOTH sides and
    # understate the kernel-level win
    n_models, n, k_fct, t_hist = 256, 16, 2, 400
    rounds = 60
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, rounds = 16, 8
    steady_tol = 1e-6
    rng = np.random.default_rng(31)
    alpha_sdf = rng.uniform(3.0, 15.0, (n_models, n))
    alpha_cdf = rng.uniform(5.0, 25.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = np.ones(y.shape, bool)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = kalman_filter(ss, yy, mm, engine="joint", store=False)
        return res.mean_f, res.cov_f

    means, covs = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, covs = np.asarray(means), np.asarray(covs)

    def make_service(steady: bool, t_seen: int = t_hist):
        reg = ModelRegistry(
            root=None, arena=True, arena_rows=n_models + 8
        )
        for i in range(n_models):
            reg.put(PosteriorState(
                model_id=f"m{i}", version=0, t_seen=t_seen,
                mean=means[i], cov=covs[i],
                params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
                loadings=loadings[i], dt=1.0,
                scaler_mean=np.zeros(n), scaler_std=np.ones(n),
                names=tuple(f"s{j}" for j in range(n)),
            ), persist=False)
        return MetranService(
            reg, flush_deadline=None, persist_updates=False,
            observability=Observability.disabled(),
            # armed gate (wide open): the EXACT armed-gate path is
            # the comparator the 2x bar is stated against
            gate=GateSpec(policy="reject", nsigma=12.0, min_seen=1),
            steady=SteadySpec(
                tol=steady_tol if steady else 0.0, min_seen=1
            ),
        )

    ids = [f"m{i}" for i in range(n_models)]
    services = {"steady": make_service(True), "exact": make_service(False)}

    def tick(svc, obs) -> float:
        t0 = time.perf_counter()
        res = svc.update_batch(ids, obs)
        dt = time.perf_counter() - t0
        bad = [r for r in res if not isinstance(r, ArenaUpdateAck)]
        if bad:
            raise RuntimeError(f"tick failed: {bad[0]!r}")
        return dt

    # warm-up: compiles both kernel variants AND lets the steady
    # service detect convergence and freeze (tick 1 detects, tick 2+
    # serve frozen)
    for _ in range(3):
        obs = rng.normal(size=(n_models, 1, n)) * 0.3
        for svc in services.values():
            tick(svc, obs)
    frozen = services["steady"]._steady_count()
    out["steady"]["n_models"] = n_models
    out["steady"]["frozen_after_warmup"] = frozen
    progress("steady_frozen", frozen=frozen, of=n_models)

    ratios, st_times, ex_times = [], [], []
    for r in range(rounds):
        if time.monotonic() > deadline - 60:
            break
        obs = rng.normal(size=(n_models, 1, n)) * 0.3
        order = (
            ("steady", "exact") if r % 2 == 0 else ("exact", "steady")
        )
        pair = {m: tick(services[m], obs) for m in order}
        st_times.append(pair["steady"])
        ex_times.append(pair["exact"])
        ratios.append(pair["exact"] / pair["steady"])
    ratio = float(np.median(ratios)) if ratios else 0.0
    st_med = float(np.median(st_times)) if st_times else 0.0
    ex_med = float(np.median(ex_times)) if ex_times else 0.0
    # both services consumed the identical stream: the end-state gap
    # IS the accumulated frozen-vs-exact deviation
    dev = max(
        float(np.max(np.abs(
            services["steady"].registry.get(m).mean
            - services["exact"].registry.get(m).mean
        )))
        for m in ids
    )
    out["steady"].update({
        "laps": len(ratios),
        "steady_updates_per_s": round(n_models / st_med) if st_med else 0,
        "exact_updates_per_s": round(n_models / ex_med) if ex_med else 0,
        "throughput_ratio": round(ratio, 2),
        "bar": 2.0,
        "meets_bar": bool(ratio >= 2.0),
        "max_mean_deviation": dev,
        "configured_tol": steady_tol,
    })
    progress(
        "steady_throughput", ratio=round(ratio, 2), bar=2.0,
        steady_qps=out["steady"]["steady_updates_per_s"],
        exact_qps=out["steady"]["exact_updates_per_s"],
        max_dev=f"{dev:.2e}", tol=steady_tol,
    )
    for svc in services.values():
        svc.close()
    write_partial(out_path, out)
    if time.monotonic() > deadline - 30:
        out["truncated"] = "budget"
        return out

    # -- update-cost-vs-t_seen flatness (exact path; nothing on the
    # serving path may touch history, whatever the counter says) ------
    flat_rounds = max(rounds // 3, 4)
    t_seen_grid = (100, 10_000, 1_000_000)
    flat_svcs = {t: make_service(False, t_seen=t) for t in t_seen_grid}
    for svc in flat_svcs.values():  # compile + warm
        for _ in range(2):
            tick(svc, rng.normal(size=(n_models, 1, n)) * 0.3)
    # interleaved round-robin (like the paired laps): transient host
    # noise lands on every t_seen equally instead of skewing one
    times = {t: [] for t in t_seen_grid}
    for r in range(flat_rounds):
        if time.monotonic() > deadline - 20:
            break
        obs = rng.normal(size=(n_models, 1, n)) * 0.3
        order = t_seen_grid if r % 2 == 0 else t_seen_grid[::-1]
        for t in order:
            times[t].append(tick(flat_svcs[t], obs))
    per_update_us = {
        str(t): round(1e6 * float(np.median(ts)) / n_models, 2)
        for t, ts in times.items() if ts
    }
    for svc in flat_svcs.values():
        svc.close()
    vals = list(per_update_us.values())
    out["flatness"] = {
        "per_update_us_by_t_seen": per_update_us,
        "max_over_min": round(max(vals) / min(vals), 3) if vals else 0.0,
        "flat": bool(vals and max(vals) / min(vals) < 1.25),
    }
    progress("steady_flatness", **per_update_us,
             max_over_min=out["flatness"]["max_over_min"])
    write_partial(out_path, out)
    return out


def run_refit_bench(out_path: str, budget_s: float) -> dict:
    """Continuous-adaptation cost story (`serve/refit.py`, ISSUE 9).

    Three measured claims:

    1. **refit throughput** — models/s through the grouped
       lanes-batch refit path (one `RefitWorker.run_once()` cycle over
       a fleet of stale candidates: anchored fit + shadow comparison +
       promotion), median over laps;
    2. **promotion swap latency** — p50/p95 of the worker's under-lock
       hot-swap timings (tail refilter + registry.put + cache
       restarts);
    3. **foreground serving impact** — two numbers.  `armed_overhead`:
       paired interleaved update+forecast laps with the worker
       attached (tail recording live) vs a twin service without one —
       the always-on cost of arming the feature, acceptance bar < 5%.
       `concurrent_degradation`: forecast qps while a refit cycle
       computes vs idle, reported raw next to `cpus` (on a 1-core host
       any background compute steals the core) and amortized by the
       duty cycle at the default 30 s scan interval —
       `amortized_degradation`, the production-relevant "while refits
       run" number, bar < 5%.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import jax
    import jax.numpy as jnp

    from metran_tpu.obs import Observability
    from metran_tpu.ops import dfm_statespace, sqrt_kalman_filter
    from metran_tpu.reliability.scenarios import simulate_dfm_panel
    from metran_tpu.serve import (
        MetranService, ModelRegistry, PosteriorState, RefitSpec,
        RefitWorker,
    )

    from metran_tpu.ops import resolve_grad_engine

    deadline = time.monotonic() + budget_s
    out = {
        "platform": jax.default_backend(),
        "cpus": os.cpu_count(),
        # which gradient engine the anchored batch fits differentiate
        # with this round (the adjoint by default since ISSUE 10 —
        # models/s here is comparable against the PR 9 autodiff
        # baseline in earlier round JSONs; the anchored objective has
        # no f32 carve-out, see parallel/fleet.py::refit_fleet)
        "grad_engine": resolve_grad_engine(None, "sqrt"),
        "refit": {}, "swap": {}, "foreground": {},
    }

    n_models, n, k_fct, t_hist = 16, 6, 1, 250
    tail_cap, holdout, min_tail, maxiter = 48, 12, 24, 10
    laps = 3
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, laps, t_hist = 4, 2, 120
    alpha_factor = 8.0
    rng = np.random.default_rng(47)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = (
        rng.uniform(0.4, 0.7, (n_models, n, k_fct)) / np.sqrt(k_fct)
    )
    # clean streams simulated from the TRUE dynamics; serving states
    # carry alphas inflated by `alpha_factor` — the stale-parameters
    # regime every candidate is re-fit out of
    n_ticks = 560
    ys = np.empty((n_models, t_hist + n_ticks, n))
    for i in range(n_models):
        ss_i = dfm_statespace(alpha_sdf[i], alpha_cdf[i], loadings[i], 1.0)
        _, ys[i], _ = simulate_dfm_panel(ss_i, t_hist + n_ticks, rng)

    mask_hist = np.ones((t_hist, n), bool)

    def one(a_s, a_c, ld, yy):
        ss = dfm_statespace(a_s * alpha_factor, a_c * alpha_factor,
                            ld, 1.0)
        res = sqrt_kalman_filter(ss, yy, jnp.asarray(mask_hist))
        return res.mean_f[-1], res.chol_f[-1]

    means, chols = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(ys[:, :t_hist]),
    )
    means, chols = np.asarray(means), np.asarray(chols)

    def make_service():
        reg = ModelRegistry(root=None, engine="sqrt")
        for i in range(n_models):
            chol = chols[i]
            reg.put(PosteriorState(
                model_id=f"m{i}", version=0, t_seen=t_hist,
                mean=means[i], cov=chol @ chol.T,
                params=np.concatenate(
                    [alpha_sdf[i], alpha_cdf[i]]
                ) * alpha_factor,
                loadings=loadings[i], dt=1.0,
                scaler_mean=np.zeros(n), scaler_std=np.ones(n),
                names=tuple(f"s{j}" for j in range(n)), chol=chol,
            ), persist=False)
        return MetranService(
            reg, flush_deadline=None, persist_updates=False,
            observability=Observability.disabled(),
        )

    ids = [f"m{i}" for i in range(n_models)]
    spec = RefitSpec(
        tail=tail_cap, holdout=holdout, min_tail=min_tail,
        maxiter=maxiter, margin=0.0, cooldown_s=0.0,
        deadline_s=600.0, staleness_obs=1, max_batch=n_models,
    )
    svc = make_service()
    worker = RefitWorker(svc, spec)
    cursor = [t_hist]

    def stream(svc_, k_ticks):
        c0 = cursor[0]
        for t in range(c0, c0 + k_ticks):
            svc_.update_batch(ids, [ys[i, t][None] for i in range(n_models)])
        cursor[0] = c0 + k_ticks

    def rearm():
        # promotions restart tails and reset fit marks; refill to FULL
        # capacity so every cycle's refit group shares one compiled
        # shape (a shorter tail is a different (T, ...) executable)
        for mid in ids:
            svc.monitor.note_fit(mid, svc.registry.get(mid).t_seen)
        stream(svc, tail_cap + 4)

    # -- 1. refit throughput ------------------------------------------
    rearm()
    t0 = time.perf_counter()
    worker.run_once()  # warm-up: compiles the refit runner
    warm_s = time.perf_counter() - t0
    progress("refit_warmup", seconds=round(warm_s, 2))
    cycle_times, scheduled, promoted = [], 0, 0
    for _ in range(laps):
        if time.monotonic() > deadline - 120:
            break
        rearm()
        t0 = time.perf_counter()
        rep = worker.run_once()
        cycle_times.append(time.perf_counter() - t0)
        scheduled += len(rep["scheduled"])
        promoted += len(rep["promoted"])
    cyc = float(np.median(cycle_times)) if cycle_times else 0.0
    out["refit"] = {
        "n_models": n_models,
        "tail_rows": tail_cap,
        "maxiter": maxiter,
        "laps": len(cycle_times),
        "cycle_s": round(cyc, 3),
        "compile_s": round(warm_s, 2),
        "models_per_s": round(n_models / cyc, 2) if cyc else 0.0,
        "scheduled": scheduled,
        "promoted": promoted,
    }
    progress("refit_throughput", **out["refit"])

    # -- 2. promotion swap latency ------------------------------------
    lat = np.asarray(worker.swap_latencies)
    out["swap"] = {
        "swaps": int(lat.size),
        "p50_ms": round(1e3 * float(np.percentile(lat, 50)), 3)
        if lat.size else 0.0,
        "p95_ms": round(1e3 * float(np.percentile(lat, 95)), 3)
        if lat.size else 0.0,
    }
    progress("refit_swap_latency", **out["swap"])
    write_partial(out_path, out)

    # -- 3a. armed overhead (paired interleaved laps) -----------------
    svc_plain = make_service()
    # twin consumes the identical stream so both sides stay warm
    for t in range(t_hist, cursor[0]):
        svc_plain.update_batch(
            ids, [ys[i, t][None] for i in range(n_models)]
        )

    def tick_lap(svc_, t):
        t0 = time.perf_counter()
        svc_.update_batch(ids, [ys[i, t][None] for i in range(n_models)])
        svc_.forecast_batch(ids, 14)
        return time.perf_counter() - t0

    pair_rounds = 24
    ratios = []
    for r in range(pair_rounds):
        if time.monotonic() > deadline - 60 or cursor[0] >= ys.shape[1]:
            break
        t = cursor[0]
        order = ("armed", "plain") if r % 2 == 0 else ("plain", "armed")
        pair = {}
        for side in order:
            pair[side] = tick_lap(svc if side == "armed" else svc_plain, t)
        cursor[0] = t + 1
        ratios.append(pair["armed"] / pair["plain"])
    armed_overhead = float(np.median(ratios)) - 1.0 if ratios else 0.0
    out["foreground"]["armed_overhead"] = round(armed_overhead, 4)
    out["foreground"]["armed_bar"] = 0.05
    out["foreground"]["pairs"] = len(ratios)
    progress("refit_armed_overhead", overhead=round(armed_overhead, 4))

    # -- 3b. concurrent-cycle degradation + duty-cycle amortization ---
    def forecast_lap(reps=8):
        t0 = time.perf_counter()
        for _ in range(reps):
            svc.forecast_batch(ids, 14)
        return (reps * n_models) / (time.perf_counter() - t0)

    forecast_lap(2)  # warm
    idle_qps = float(np.median([forecast_lap() for _ in range(3)]))
    # the background batch at the shipped cadence: ONE candidate per
    # scan (max_batch=1) — the full-batch cycle cost is section 1's
    # number; this section prices what production actually interleaves
    # with traffic every interval_s
    worker.spec = worker.spec._replace(max_batch=1)
    rearm()
    worker.run_once()  # warm the single-candidate shapes (compile)
    rearm()
    busy_qps, cycle_wall = idle_qps, 0.0
    done = threading.Event()

    def cycle_bg():
        t0 = time.perf_counter()
        try:
            worker.run_once()
        finally:
            done.set()
        return time.perf_counter() - t0

    bg = threading.Thread(target=cycle_bg)
    t_cycle0 = time.perf_counter()
    bg.start()
    busy = []
    while not done.is_set():
        busy.append(forecast_lap(4))
    bg.join()
    cycle_wall = time.perf_counter() - t_cycle0
    if busy:
        busy_qps = float(np.median(busy))
    concurrent_deg = max(0.0, 1.0 - busy_qps / idle_qps)
    # amortized at the shipped cadence: one cycle per interval_s
    interval = RefitSpec.from_defaults().interval_s or 30.0
    duty = min(1.0, cycle_wall / max(interval, cycle_wall))
    amortized = concurrent_deg * duty
    out["foreground"].update({
        "idle_forecast_qps": round(idle_qps),
        "busy_forecast_qps": round(busy_qps),
        "concurrent_degradation": round(concurrent_deg, 4),
        "cycle_wall_s": round(cycle_wall, 3),
        "default_interval_s": interval,
        "duty_cycle": round(duty, 4),
        "amortized_degradation": round(amortized, 4),
        "bar": 0.05,
        "meets_bar": bool(
            armed_overhead < 0.05 and amortized < 0.05
        ),
    })
    progress("refit_foreground", **out["foreground"])
    for s in (svc, svc_plain):
        s.close()
    worker.close()
    write_partial(out_path, out)
    return out


# ----------------------------------------------------------------------
# phase: gradient engines (closed-form adjoint vs autodiff)
# ----------------------------------------------------------------------
def run_detect_bench(out_path: str, budget_s: float) -> dict:
    """Online monitoring scenario: armed-detector overhead + quality.

    Two acceptance claims (docs/concepts.md "Online monitoring",
    ISSUE 11):

    1. the ARMED streaming detector (CUSUM + autocorrelation drift +
       anomaly flags fused into the update kernels) costs < 3% update
       throughput on the ARENA BULK path versus the same service with
       detection off — paired interleaved laps, ratio of medians (the
       PR 5 gate-overhead methodology).  Default thresholds on clean
       data, so the measured cost is RUNNING the detector, not alarms
       changing the workload;
    2. detection quality at those defaults: delay-vs-magnitude curves
       for drift and unit-error sensor faults plus the measured
       clean-stream false-alarm rate (the
       ``reliability.scenarios.run_detection_delay_scenario``
       harness — the same numbers the ``-m faults`` tests assert).
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import jax
    import jax.numpy as jnp

    from metran_tpu.obs import Observability
    from metran_tpu.ops import dfm_statespace, kalman_filter
    from metran_tpu.reliability.scenarios import (
        run_detection_delay_scenario,
    )
    from metran_tpu.serve import (
        DetectSpec, GateSpec, MetranService, ModelRegistry,
        PosteriorState,
    )

    deadline = time.monotonic() + budget_s
    n_models, n, k_fct, t_hist = 256, 8, 1, 200
    rounds = 24
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, t_hist, rounds = 32, 60, 6
    out = {
        "platform": jax.default_backend(),
        "n_models": n_models, "n_series": n, "n_factors": k_fct,
    }

    rng = np.random.default_rng(29)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = np.ones(y.shape, bool)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = kalman_filter(ss, yy, mm, engine="joint", store=False)
        return res.mean_f, res.cov_f

    means, covs = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, covs = np.asarray(means), np.asarray(covs)
    states = [
        PosteriorState(
            model_id=f"m{i}", version=0, t_seen=t_hist,
            mean=means[i], cov=covs[i],
            params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
            loadings=loadings[i], dt=1.0,
            scaler_mean=np.zeros(n), scaler_std=np.ones(n),
            names=tuple(f"s{j}" for j in range(n)),
        )
        for i in range(n_models)
    ]
    ids = [st.model_id for st in states]

    # three services isolate what the 3% bar is about.  Arming detect
    # on an ungated registry ALSO switches it to the z-score-emitting
    # gated kernel form (the documented shift, same as arming the
    # gate) — on this CPU host the sequential form is actually FASTER
    # than the joint kernel at these widths, so the raw on-vs-off
    # delta conflates the two effects.  The bar therefore applies to
    # detect+gate vs gate-only (identical gated core on both sides —
    # the measured cost IS the fused recursions + mirror refresh),
    # with the deployment-facing on-vs-off delta reported next to it.
    # Wide-open thresholds (the PR 5 gate methodology): the synthetic
    # ticks are not model-consistent, and alarms changing the host
    # workload is not what a clean-stream hot path pays.
    inert_gate = GateSpec(policy="reject", nsigma=1e3, min_seen=1)
    inert_detect = DetectSpec(
        enabled=True, min_seen=1, nsigma=1e3, cusum_h=1e9,
        lb_thresh=1e9,
    )

    def make_service(gate=None, detect=None):
        reg = ModelRegistry(
            root=None, arena=True, arena_rows=n_models, arena_mesh=0,
        )
        for st in states:
            reg.put(st, persist=False)
        return MetranService(
            reg, flush_deadline=None, max_batch=4 * n_models,
            persist_updates=False,
            observability=Observability.disabled(),
            gate=gate, detect=detect,
        )

    services = {
        "off": make_service(),
        "gate": make_service(gate=inert_gate),
        "both": make_service(gate=inert_gate, detect=inert_detect),
        "on": make_service(detect=inert_detect),
    }
    obs_rows = rng.normal(size=(rounds + 2, n_models, 1, n)) * 0.2

    def tick(svc, t) -> float:
        t0 = time.perf_counter()
        svc.update_batch(ids, obs_rows[t])
        return time.perf_counter() - t0

    for svc in services.values():  # compile + warm (excluded)
        tick(svc, 0)
        tick(svc, 1)
    names = list(services)
    ratios = {"detector": [], "vs_off": []}
    for r in range(rounds):
        if time.monotonic() > deadline - 45:
            break
        order = names if r % 2 == 0 else names[::-1]
        lap = {m: tick(services[m], r + 2) for m in order}
        ratios["detector"].append(lap["both"] / lap["gate"])
        ratios["vs_off"].append(lap["on"] / lap["off"])
    alarms = services["both"].health().get("detect", {})
    for svc in services.values():
        svc.close()

    def pct(rs):  # qps overhead = 1 - 1/r for a paired lap-time ratio
        r = float(np.median(rs)) if rs else 1.0
        return round(100.0 * (1.0 - 1.0 / r), 2)

    out["overhead"] = {
        "batch": n_models,
        "laps": len(ratios["detector"]),
        # the bar: fused recursions + mirror refresh, same kernel form
        "update_qps_pct": pct(ratios["detector"]),
        "bar_pct": 3.0,
        # the deployment delta (includes the joint->sequential kernel
        # shift an ungated registry pays when arming detection)
        "on_vs_off_qps_pct": pct(ratios["vs_off"]),
        # honesty check: nonzero alarm counts would mean the numbers
        # above include alarm booking, not just the recursions
        "alarms_during_laps": {
            k: alarms.get(k, 0)
            for k in ("anomaly", "changepoint_cusum", "changepoint_lb")
        },
    }
    progress(
        "detect_overhead", pct=out["overhead"]["update_qps_pct"],
        on_vs_off_pct=out["overhead"]["on_vs_off_qps_pct"],
        laps=len(ratios["detector"]),
    )
    write_partial(out_path, out)

    # -- detection quality at the same default thresholds --------------
    out["scenarios"] = {}
    n_steps, n_clean = 60, 1200
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_steps, n_clean = 30, 300
    for mode, mags in (("drift", (0.5, 1.0, 2.0)),
                       ("unit", (2.0, 5.0, 10.0))):
        if time.monotonic() > deadline - 30:
            out["truncated"] = "budget"
            write_partial(out_path, out)
            break
        res = run_detection_delay_scenario(
            mode, magnitudes=mags, n_steps=n_steps, n_clean=n_clean,
        )
        out["scenarios"][mode] = res
        progress(
            f"detect_{mode}",
            delays=[c["delay_steps"] for c in res["curve"]],
            fa_per_10k=round(res["false_alarms_per_10k"], 2),
        )
        write_partial(out_path, out)
    return out


def run_durability_bench(out_path: str, budget_s: float) -> dict:
    """Durability-plane scenario: WAL overhead + recovery replay rate.

    Two acceptance claims (docs/concepts.md "Durability & recovery",
    ISSUE 14):

    1. the ARMED write-ahead log (per-commit CRC-framed records,
       group-fdatasynced before every ack) costs <= 10% update
       throughput on the ARENA BULK path versus the same service with
       the WAL off, at matched observability — paired interleaved
       laps, ratio of medians (the PR 5/11 methodology).  Checkpoints
       are excluded from the laps (cadence 0) and measured separately:
       the bar is the PER-COMMIT price of durable acks;
    2. recovery replay throughput >= 10k commits/s: WAL tails of
       increasing length are replayed through
       ``MetranService.recover`` (bulk commit-group replay, same
       kernels as serving) and the wall clock is reported per tail —
       the RTO half of the durability contract, next to the
       ``recovery ms per 1k replayed commits`` headline
       ``tools/bench_trend.py`` trends.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from metran_tpu.obs import Observability
    from metran_tpu.ops import dfm_statespace, kalman_filter
    from metran_tpu.serve import (
        DurabilitySpec, MetranService, ModelRegistry, PosteriorState,
    )

    deadline = time.monotonic() + budget_s
    # batch 512 at flagship-like dimensions (n=16 series, 2 common
    # factors, k=2 rows per tick — the groundwater workload's shape,
    # not the n=8 toy): the WAL's group commit amortizes ONE
    # fdatasync (~0.6 ms median on this host's ext4 at live cadence)
    # over the whole tick, so the per-commit price is judged at the
    # batch size and kernel weight the bulk path actually runs
    n_models, n, k_fct, k_rows, t_hist = 512, 16, 2, 2, 200
    rounds = 24
    tails = (2048, 8192, 32768)
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, t_hist, rounds = 32, 60, 6
        tails = (64, 256)
    out = {
        "platform": jax.default_backend(),
        "n_models": n_models, "n_series": n, "n_factors": k_fct,
    }

    rng = np.random.default_rng(37)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = np.ones(y.shape, bool)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = kalman_filter(ss, yy, mm, engine="joint", store=False)
        return res.mean_f, res.cov_f

    means, covs = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, covs = np.asarray(means), np.asarray(covs)
    states = [
        PosteriorState(
            model_id=f"m{i}", version=0, t_seen=t_hist,
            mean=means[i], cov=covs[i],
            params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
            loadings=loadings[i], dt=1.0,
            scaler_mean=np.zeros(n), scaler_std=np.ones(n),
            names=tuple(f"s{j}" for j in range(n)),
        )
        for i in range(n_models)
    ]
    ids = [st.model_id for st in states]
    work = tempfile.mkdtemp(prefix="metran-durability-")

    def make_service(wal: bool, sub: str):
        root = os.path.join(work, sub)
        reg = ModelRegistry(
            root=root, arena=True, arena_rows=n_models, arena_mesh=0,
        )
        for st in states:
            reg.put(st, persist=False)
        return MetranService(
            reg, flush_deadline=None, max_batch=4 * n_models,
            persist_updates=False,
            durability=DurabilitySpec(
                enabled=wal, checkpoint_every=0
            ) if wal else None,
        )

    try:
        services = {
            "off": make_service(False, "off"),
            "wal": make_service(True, "wal"),
        }
        obs_rows = rng.normal(
            size=(rounds + 2, n_models, k_rows, n)
        ) * 0.2

        def tick(svc, t) -> float:
            t0 = time.perf_counter()
            svc.update_batch(ids, obs_rows[t])
            return time.perf_counter() - t0

        for svc in services.values():  # compile + warm (excluded)
            tick(svc, 0)
            tick(svc, 1)
        names = list(services)
        ratios = []
        for r in range(rounds):
            if time.monotonic() > deadline - 90:
                break
            order = names if r % 2 == 0 else names[::-1]
            lap = {m: tick(services[m], r + 2) for m in order}
            ratios.append(lap["wal"] / lap["off"])
        wal_status = services["wal"].health()["durability"]
        # one checkpoint at fleet size, timed separately (the cadence
        # cost the laps deliberately exclude)
        t_ck0 = time.perf_counter()
        ck = services["wal"].checkpoint()
        ck_wall = time.perf_counter() - t_ck0
        for svc in services.values():
            svc.close()

        r_med = float(np.median(ratios)) if ratios else 1.0
        out["overhead"] = {
            "batch": n_models,
            "laps": len(ratios),
            # qps overhead = 1 - 1/r for a paired lap-time ratio
            "update_qps_pct": round(100.0 * (1.0 - 1.0 / r_med), 2),
            "bar_pct": 10.0,
            "records_logged": wal_status["records_logged"],
            "bytes_logged": wal_status["bytes_logged"],
            "group_syncs": wal_status["group_syncs"],
            "checkpoint_wall_s": round(ck_wall, 4),
            "checkpoint_spilled": ck.get("spilled"),
        }
        progress(
            "durability_overhead",
            pct=out["overhead"]["update_qps_pct"],
            laps=len(ratios),
            syncs=wal_status["group_syncs"],
        )
        write_partial(out_path, out)

        # -- recovery replay rate vs tail length -----------------------
        out["recovery"] = {"tails": []}
        for tail in tails:
            if time.monotonic() > deadline - 30:
                out["truncated"] = "budget"
                break
            ticks = max(1, tail // n_models)
            root = os.path.join(work, f"rec-{tail}")
            reg = ModelRegistry(
                root=root, arena=True, arena_rows=n_models,
                arena_mesh=0,
            )
            for st in states:
                reg.put(st, persist=False)
            svc = MetranService(
                reg, flush_deadline=None, max_batch=4 * n_models,
                persist_updates=False,
                durability=DurabilitySpec(
                    enabled=True, checkpoint_every=0
                ),
            )
            stream = rng.normal(
                size=(ticks, n_models, k_rows, n)
            ) * 0.2
            for t in range(ticks):
                svc.update_batch(ids, stream[t])
            svc.batcher.close()  # crash: abandon, no close/spill
            del svc, reg
            t0 = time.perf_counter()
            rec = MetranService.recover(
                root,
                registry_kwargs={
                    "arena": True, "arena_rows": n_models,
                    "arena_mesh": 0,
                },
                flush_deadline=None, max_batch=4 * n_models,
                persist_updates=False,
                checkpoint_after=False,
            )
            wall = time.perf_counter() - t0
            rep = dict(rec.last_recovery or {})
            rec.close()
            n_replayed = int(rep.get("replayed", 0))
            out["recovery"]["tails"].append({
                "commits": ticks * n_models,
                "replayed": n_replayed,
                "recover_wall_s": round(wall, 4),
                "replay_wall_s": rep.get("replay_wall_s"),
                "commits_per_s": rep.get("commits_per_s"),
                "ms_per_1k_commits": round(
                    1e3 * wall / max(n_replayed / 1e3, 1e-9), 2
                ) if n_replayed else None,
            })
            progress(
                "durability_recovery", tail=ticks * n_models,
                replayed=n_replayed,
                commits_per_s=rep.get("commits_per_s"),
            )
            write_partial(out_path, out)
        longest = (
            out["recovery"]["tails"][-1]
            if out["recovery"]["tails"] else {}
        )
        out["recovery"]["replay_commits_per_s"] = longest.get(
            "commits_per_s"
        )
        out["recovery"]["ms_per_1k_commits"] = longest.get(
            "ms_per_1k_commits"
        )
        out["recovery"]["bar_commits_per_s"] = 10000.0
        write_partial(out_path, out)
        return out
    finally:
        shutil.rmtree(work, ignore_errors=True)


def run_serve_cluster_bench(out_path: str, budget_s: float) -> dict:
    """Multi-process serving plane scenario (`metran_tpu/cluster/`,
    ISSUE 16's measurement story).

    Two measured claims (docs/concepts.md "Multi-process serving"):

    1. **read scaling** — aggregate shared-memory plane reads/s across
       a 1-writer-N-reader cluster, paired against (a) ONE worker
       running the same tight loop alone (process-scaling ratio: the
       workers share no GIL, no locks, no device, so N workers should
       deliver ~N x one worker — capped by host cores, so the artifact
       also carries ``host_cores``, the core-capped
       ``scaling_ceiling_x`` and the per-core ``scaling_efficiency``,
       the honest number on a core-starved host) and (b) the
       single-process service's
       cached-read ceiling measured in THIS process right before the
       cluster spins up (the absolute claim: the split beats the one
       GIL it exists to escape).  Each worker's loop runs in-process
       against the mmap'd plane — one RPC triggers the whole loop, so
       socket cost amortizes out exactly like the single-process bench
       loops;
    2. **mixed 90/10 SLO** — client-observed p99 over a 90% forecast /
       10% update request mix routed through the frontend split
       (reads -> workers round-robin, updates -> the WAL-armed
       writer), against the 50 ms serving SLO; the writer's
       ``capacity_report`` cluster section rides along so the plane's
       own hit/fallback accounting is in the artifact next to the
       client-side percentiles.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import shutil
    import tempfile

    import jax

    from metran_tpu.cluster import ClusterFrontend, ClusterSpec
    from metran_tpu.cluster._testing import (
        make_states, seed_root, writer_service_factory,
    )
    from metran_tpu.serve import MetranService, ModelRegistry

    deadline = time.monotonic() + budget_s
    workers, n_models = 4, 32
    read_iters, mixed_requests = 30_000, 2_000
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        workers, n_models = 2, 8
        read_iters, mixed_requests = 2_000, 200
    horizons, steps = "1-5", 5
    out = {
        "platform": jax.default_backend(),
        "workers": workers, "n_models": n_models,
    }
    work = tempfile.mkdtemp(prefix="metran-cluster-")
    frontend = None
    try:
        # -- single-process cached-read ceiling (the paired baseline) --
        states = make_states(seed=7, n_models=n_models)
        ids = [st.model_id for st in states]
        reg = ModelRegistry(root=None)
        for st in states:
            reg.put(st, persist=False)
        svc = MetranService(
            reg, flush_deadline=None, persist_updates=False,
            readpath=True, horizons=horizons,
        )
        rng = np.random.default_rng(23)
        obs_warm = rng.normal(size=(n_models, 1, 5)) * 0.2
        for i, mid in enumerate(ids):
            svc.update(mid, obs_warm[i])  # publish snapshots
        for mid in ids[:8]:
            svc.forecast(mid, steps)  # warm the sync read path
        t0 = time.perf_counter()
        for i in range(read_iters):
            svc.forecast(ids[i % n_models], steps)
        single_rps = read_iters / (time.perf_counter() - t0)
        svc.close()
        progress("cluster_single_ceiling", reads_per_s=round(single_rps))
        out["read_scaling"] = {"reads_per_s_single": round(single_rps, 1)}
        write_partial(out_path, out)

        # -- the cluster: ONE writer + N read workers ------------------
        root = os.path.join(work, "fleet")
        seed_root(root, seed=7, n_models=n_models)
        spec = ClusterSpec(
            enabled=True, workers=workers, shm_mb=16.0,
            heartbeat_s=1.0, slots=4 * n_models, max_series=8,
        )
        frontend = ClusterFrontend(
            spec, writer_service_factory, (root, horizons, True),
        )
        for i, mid in enumerate(ids):
            frontend.update(mid, obs_warm[i])  # warm plane + kernels
        loop_payload = {"model_ids": ids, "steps": steps,
                        "iters": read_iters}
        # one worker alone (the per-process unit)
        solo = frontend._workers[0].client.call("read_loop", loop_payload)
        solo_rps = solo["iters"] / solo["elapsed_s"]
        # all N concurrently (the scaling claim)
        results = frontend.read_loop(ids, steps, read_iters)
        total_rps = sum(r["iters"] / r["elapsed_s"] for r in results)
        hits = sum(r["hits"] for r in results)
        # process scaling is physically capped by the host's cores: N
        # workers on a 1-core box time-slice one CPU, so the honest
        # claim is efficiency against min(workers, cores), reported
        # next to the raw ratio (the >= 4x bar presumes >= 4 cores)
        try:
            host_cores = len(os.sched_getaffinity(0))
        except AttributeError:  # pragma: no cover - non-Linux
            host_cores = os.cpu_count() or 1
        ceiling = float(min(workers, host_cores))
        rs = out["read_scaling"]
        rs.update({
            "reads_per_s_1worker": round(solo_rps, 1),
            "reads_per_s_total": round(total_rps, 1),
            "workers_reporting": len(results),
            "hit_fraction": round(
                hits / max(1, len(results) * read_iters), 4
            ),
            "scaling_x_vs_1worker": round(total_rps / solo_rps, 2),
            "scaling_x_vs_single": round(total_rps / single_rps, 2),
            "host_cores": host_cores,
            "scaling_ceiling_x": ceiling,
            "scaling_efficiency": round(
                (total_rps / solo_rps) / ceiling, 2
            ),
            "bar_scaling_x": 4.0,
        })
        progress(
            "cluster_read_scaling",
            total=round(total_rps), vs_single=rs["scaling_x_vs_single"],
            vs_1worker=rs["scaling_x_vs_1worker"],
            cores=host_cores, efficiency=rs["scaling_efficiency"],
        )
        write_partial(out_path, out)

        # -- mixed 90/10 through the frontend split --------------------
        if time.monotonic() < deadline - 60:
            obs_mix = rng.normal(size=(mixed_requests, 1, 5)) * 0.2
            upd_ms, read_ms = [], []
            for j in range(mixed_requests):
                mid = ids[j % n_models]
                t0 = time.perf_counter()
                if j % 10 == 0:
                    frontend.update(mid, obs_mix[j])
                    upd_ms.append(1e3 * (time.perf_counter() - t0))
                else:
                    frontend.forecast(mid, steps)
                    read_ms.append(1e3 * (time.perf_counter() - t0))
            all_ms = np.asarray(upd_ms + read_ms)
            report = frontend.capacity_report()
            out["mixed"] = {
                "requests": mixed_requests,
                "read_fraction": 0.9,
                "p50_ms": round(float(np.percentile(all_ms, 50)), 3),
                "p99_ms": round(float(np.percentile(all_ms, 99)), 3),
                "forecast_p99_ms": round(
                    float(np.percentile(read_ms, 99)), 3
                ),
                "update_p99_ms": round(
                    float(np.percentile(upd_ms, 99)), 3
                ),
                "slo_ms": 50.0,
                "cluster_stats": report.get("cluster"),
            }
            progress(
                "cluster_mixed", p99_ms=out["mixed"]["p99_ms"],
                update_p99_ms=out["mixed"]["update_p99_ms"],
            )
        else:
            out["truncated"] = "budget"
        write_partial(out_path, out)
        return out
    finally:
        if frontend is not None:
            frontend.close()
        shutil.rmtree(work, ignore_errors=True)


def run_obs_fleet_bench(out_path: str, budget_s: float) -> dict:
    """Fleet observability overhead scenario (`metran_tpu/obs/fleet.py`
    + the traced RPC envelope in `cluster/ipc.py`, ISSUE 19's
    measurement story; docs/concepts.md "Fleet observability").

    Two paired claims, each measured between TWO live clusters — one
    spawned with ``METRAN_TPU_OBS_TRACE=1`` in the environment (so the
    frontend, writer and every worker arm tracers and every frontend
    RPC carries the 3-tuple traced envelope) and one spawned with
    tracing off (the 2-tuple wire format, byte-identical to PR 16):

    1. **traced cluster RPC** (``rpc_overhead_pct``, bar <= 5%): the
       frontend update path — span begin/finish on both sides of the
       socket plus ~40 bytes of pickled context per request — against
       the identical untraced path, paired-interleaved lap ratios
       exactly like ``--phase obs`` (AB/BA order so host drift
       cancels);
    2. **shared-memory read path** (``read_overhead_pct``, bar ~0%):
       the workers' in-process ``read_loop`` plane reads.  Trace
       propagation rides the RPC *envelope* and the plane read path
       has no RPC per read by construction — this leg measures that
       the claim survives contact with a live fleet.

    A ``fleet_collect`` sample rides along (merge wall, process lane
    count, exposition size) so the artifact also records what the
    observability you are paying for actually buys.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import shutil
    import tempfile

    import jax

    from metran_tpu.cluster import ClusterFrontend, ClusterSpec
    from metran_tpu.cluster._testing import (
        make_states, seed_root, writer_service_factory,
    )

    deadline = time.monotonic() + budget_s
    workers, n_models = 2, 16
    upd_rounds, read_iters, read_rounds = 40, 20_000, 8
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, upd_rounds, read_iters, read_rounds = 8, 8, 2_000, 4
    horizons, steps = "1-5", 5
    out = {
        "platform": jax.default_backend(),
        "workers": workers, "n_models": n_models,
    }
    work = tempfile.mkdtemp(prefix="metran-obsfleet-")
    clusters = {}
    trace_env_before = os.environ.get("METRAN_TPU_OBS_TRACE")
    try:
        states = make_states(seed=7, n_models=n_models)
        ids = [st.model_id for st in states]
        rng = np.random.default_rng(23)
        obs_warm = rng.normal(size=(n_models, 1, 5)) * 0.2
        # spawn order matters: the env var crosses the spawn via
        # os.environ, arming (or not) every child's tracer at build
        for mode, armed in (("plain", "0"), ("traced", "1")):
            os.environ["METRAN_TPU_OBS_TRACE"] = armed
            root = os.path.join(work, mode)
            seed_root(root, seed=7, n_models=n_models)
            spec = ClusterSpec(
                enabled=True, workers=workers, shm_mb=16.0,
                heartbeat_s=1.0, slots=4 * n_models, max_series=8,
            )
            clusters[mode] = ClusterFrontend(
                spec, writer_service_factory, (root, horizons, True),
            )
            for i, mid in enumerate(ids):  # warm kernels + plane
                clusters[mode].update(mid, obs_warm[i])
        progress("obs_fleet_spawned", clusters=len(clusters))

        def upd_lap(frontend) -> float:
            t0 = time.perf_counter()
            for i, mid in enumerate(ids):
                frontend.update(mid, obs_warm[i])
            return time.perf_counter() - t0

        def read_lap(frontend) -> float:
            results = frontend.read_loop(ids, steps, read_iters)
            return max(r["elapsed_s"] for r in results)

        names = ("plain", "traced")
        upd_ratios, upd_laps = [], {m: [] for m in names}
        for r in range(upd_rounds):
            if time.monotonic() > deadline - 60:
                break
            order = names if r % 2 == 0 else names[::-1]
            pair = {m: upd_lap(clusters[m]) for m in order}
            for m, dt in pair.items():
                upd_laps[m].append(dt)
            upd_ratios.append(pair["traced"] / pair["plain"])
        read_ratios, read_laps = [], {m: [] for m in names}
        for r in range(read_rounds):
            if time.monotonic() > deadline - 30:
                break
            order = names if r % 2 == 0 else names[::-1]
            pair = {m: read_lap(clusters[m]) for m in order}
            for m, dt in pair.items():
                read_laps[m].append(dt)
            read_ratios.append(pair["traced"] / pair["plain"])
        # overhead from the MEDIAN PAIRED ratio (not ratio of
        # medians), the same drift-immune methodology as --phase obs
        u_ratio = float(np.median(upd_ratios)) if upd_ratios else 1.0
        r_ratio = float(np.median(read_ratios)) if read_ratios else 1.0
        out["overhead"] = {
            "rpc_overhead_pct": round(100.0 * (1.0 - 1.0 / u_ratio), 2),
            "read_overhead_pct": round(100.0 * (1.0 - 1.0 / r_ratio), 2),
            "update_laps": len(upd_ratios),
            "read_laps": len(read_ratios),
            "update_rps_plain": (
                round(n_models / float(np.median(upd_laps["plain"])), 1)
                if upd_laps["plain"] else 0.0
            ),
            "update_rps_traced": (
                round(n_models / float(np.median(upd_laps["traced"])), 1)
                if upd_laps["traced"] else 0.0
            ),
            "bar_rpc_pct": 5.0,
        }
        progress("obs_fleet_overhead", **{
            k: out["overhead"][k]
            for k in ("rpc_overhead_pct", "read_overhead_pct")
        })
        write_partial(out_path, out)

        # what the armed fleet actually buys: one merged collection
        fe = clusters["traced"]
        t0 = time.perf_counter()
        exposition = fe.fleet_report()
        report_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        trace = fe.fleet_trace_export()
        trace_s = time.perf_counter() - t0
        lanes = {
            ev.get("pid") for ev in trace.get("traceEvents", ())
            if ev.get("ph") == "X"
        }
        out["fleet_sample"] = {
            "report_wall_ms": round(1e3 * report_s, 2),
            "exposition_bytes": len(exposition),
            "exposition_processes": len({
                ln.split('process="')[1].split('"')[0]
                for ln in exposition.splitlines()
                if 'process="' in ln
            }),
            "trace_wall_ms": round(1e3 * trace_s, 2),
            "trace_span_lanes": len(lanes),
            "trace_events": len(trace.get("traceEvents", ())),
        }
        progress("obs_fleet_sample", **out["fleet_sample"])
        write_partial(out_path, out)
        return out
    finally:
        if trace_env_before is None:
            os.environ.pop("METRAN_TPU_OBS_TRACE", None)
        else:
            os.environ["METRAN_TPU_OBS_TRACE"] = trace_env_before
        for fe in clusters.values():
            fe.close()
        shutil.rmtree(work, ignore_errors=True)


def run_replication_bench(out_path: str, budget_s: float) -> dict:
    """WAL-shipped replication scenario (`cluster/replication.py`,
    ISSUE 17's measurement story).

    Three measured claims (docs/concepts.md "Replication & failover"):

    1. **steady-state ship lag** — the primary runs the flagship
       batch-512 arena bulk tick with one SPAWNED standby in live ship
       membership; every committed group is shipped synchronously
       before its acks, and the standby's ship replies feed the
       ack-to-applied lag samples.  Headline: ``repl_lag_p99_ms``
       (bar: < 250 ms — replica reads stay fresh at the bulk rate);
    2. **replica read fan-out** — the primary's in-process cached-read
       rate alone, then the same loop concurrently with TWO spawned
       standbys each running their own in-process ``read_loop`` off
       their own snapshot stores.  Like the cluster bench, scaling is
       reported against the core-capped ceiling (3 processes cannot
       beat min(3, cores) on a core-starved host) next to the raw
       ratio.  Headline: ``replica_read_scaling_x`` (bar: >= 2x total
       with 2 replicas, cores permitting);
    3. **failover RTO** — promote one standby (fence epoch bump +
       persisted fence, apply-queue drain, durability re-armed over
       its own log WITH the initial checkpoint) and serve a first
       read from it; the wall from promote-call to first-served-read
       is ``failover_rto_ms``.  The fenced ex-primary's next bulk
       tick must raise ``PrimaryFencedError`` before any ack — the
       zero-acked-loss half, asserted here and exhaustively by the
       failover chaos matrix in ``tests/test_replication.py``.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import multiprocessing
    import shutil
    import tempfile
    import threading

    import jax
    import jax.numpy as jnp

    from metran_tpu.cluster._testing import standby_service_factory
    from metran_tpu.cluster.ipc import rpc_call
    from metran_tpu.cluster.replication import (
        ReplicationSpec, standby_main,
    )
    from metran_tpu.ops import dfm_statespace, kalman_filter
    from metran_tpu.serve import (
        DurabilitySpec, MetranService, ModelRegistry, PosteriorState,
        PrimaryFencedError,
    )

    deadline = time.monotonic() + budget_s
    # the durability bench's flagship bulk shape: batch 512, n=16
    # series, 2 common factors, k=2 rows per tick — the lag bar is
    # judged at the batch size whose ONE group-fdatasync the ship
    # round-trip rides on
    n_models, n, k_fct, k_rows, t_hist = 512, 16, 2, 2, 100
    ticks, read_iters = 24, 6000
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, t_hist, ticks, read_iters = 16, 30, 6, 600
    horizons, steps = "1-5", 5
    out = {
        "platform": jax.default_backend(),
        "n_models": n_models, "n_series": n, "n_factors": k_fct,
    }

    rng = np.random.default_rng(41)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = np.ones(y.shape, bool)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = kalman_filter(ss, yy, mm, engine="joint", store=False)
        return res.mean_f, res.cov_f

    means, covs = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, covs = np.asarray(means), np.asarray(covs)
    states = [
        PosteriorState(
            model_id=f"m{i}", version=0, t_seen=t_hist,
            mean=means[i], cov=covs[i],
            params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
            loadings=loadings[i], dt=1.0,
            scaler_mean=np.zeros(n), scaler_std=np.ones(n),
            names=tuple(f"s{j}" for j in range(n)),
        )
        for i in range(n_models)
    ]
    ids = [st.model_id for st in states]
    work = tempfile.mkdtemp(prefix="metran-repl-")
    primary = None
    procs = []
    try:
        # persist the baseline once, then COPY it per standby — the
        # documented shared-baseline contract (a copied checkpoint)
        proot = os.path.join(work, "primary")
        reg = ModelRegistry(root=proot)
        for st in states:
            reg.put(st, persist=True)
        sroots = [os.path.join(work, f"standby{i}") for i in (1, 2)]
        for sroot in sroots:
            shutil.copytree(proot, sroot)

        repl_spec = ReplicationSpec(enabled=True, standbys=2).validate()
        primary = MetranService(
            ModelRegistry(
                root=proot, arena=True, arena_rows=n_models,
                arena_mesh=0,
            ),
            flush_deadline=None, max_batch=4 * n_models,
            persist_updates=False, readpath=True, horizons=horizons,
            durability=DurabilitySpec(enabled=True, checkpoint_every=0),
            replication=repl_spec,
        )

        ctx = multiprocessing.get_context("spawn")
        socks = []
        for i, sroot in enumerate(sroots, start=1):
            sock = os.path.join(work, f"standby{i}.sock")
            ready = os.path.join(work, f"standby{i}.ready")
            proc = ctx.Process(
                target=standby_main,
                args=(repl_spec, sock, standby_service_factory,
                      (sroot, horizons), ready),
                name=f"metran-bench-standby{i}", daemon=True,
            )
            proc.start()
            procs.append(proc)
            t0 = time.monotonic()
            while not os.path.exists(ready):
                if not proc.is_alive():
                    raise RuntimeError(f"standby{i} died during spawn")
                if time.monotonic() - t0 > 180.0:
                    raise RuntimeError(f"standby{i} never became ready")
                time.sleep(0.1)
            socks.append(sock)
        hub = primary.repl_hub

        # -- phase 1: steady-state ship lag at the bulk tick ----------
        obs_rows = rng.normal(
            size=(ticks + 2, n_models, k_rows, n)
        ) * 0.2
        attach1 = hub.add_standby(socks[0], name="standby1")
        primary.update_batch(ids, obs_rows[0])  # compile + warm
        primary.update_batch(ids, obs_rows[1])  # (standby compiles too)
        warm_t0 = time.monotonic()
        while hub.lag_seconds() > 0.0 \
                and time.monotonic() - warm_t0 < 120.0:
            hub.poll()  # wait out the standby's one-time XLA compile
            time.sleep(0.05)
        hub.lag_samples_s.clear()  # …and keep it out of the p99
        tick_s = []
        for t in range(ticks):
            if time.monotonic() > deadline - 120:
                out["truncated"] = "budget (lag laps)"
                break
            t0 = time.perf_counter()
            primary.update_batch(ids, obs_rows[t + 2])
            tick_s.append(time.perf_counter() - t0)
        drain_t0 = time.monotonic()
        while hub.lag_seconds() > 0.0 \
                and time.monotonic() - drain_t0 < 60.0:
            hub.poll()
            time.sleep(0.05)
        lag_ms = 1e3 * np.asarray(list(hub.lag_samples_s))
        out["lag"] = {
            "ticks": len(tick_s), "batch": n_models,
            "attach_catch_up_commits": attach1["catch_up_commits"],
            "shipped_commits": hub.shipped_commits,
            "tick_p50_ms": round(
                1e3 * float(np.median(tick_s)), 3
            ) if tick_s else None,
            "repl_lag_p50_ms": round(
                float(np.percentile(lag_ms, 50)), 3
            ) if lag_ms.size else None,
            "repl_lag_p99_ms": round(
                float(np.percentile(lag_ms, 99)), 3
            ) if lag_ms.size else None,
            "lag_samples": int(lag_ms.size),
            "bar_lag_p99_ms": 250.0,
        }
        progress(
            "repl_lag", p99_ms=out["lag"]["repl_lag_p99_ms"],
            ticks=len(tick_s),
        )
        write_partial(out_path, out)

        # -- phase 2: replica read fan-out (primary + 2 standbys) ------
        hub.add_standby(socks[1], name="standby2")
        for mid in ids[:8]:
            primary.forecast(mid, steps)  # warm the primary read path
        warm = {"model_ids": ids, "steps": steps, "iters": 64}
        for sock in socks:  # compile each standby's forecast kernel
            rpc_call(sock, "read_loop", warm, timeout_s=300.0)
        t0 = time.perf_counter()
        for i in range(read_iters):
            primary.forecast(ids[i % n_models], steps)
        primary_rps = read_iters / (time.perf_counter() - t0)
        loop = {"model_ids": ids, "steps": steps, "iters": read_iters}
        results = [None] * (1 + len(socks))

        def _standby_loop(j, sock):
            results[j] = rpc_call(sock, "read_loop", loop,
                                  timeout_s=600.0)

        threads = [
            threading.Thread(target=_standby_loop, args=(j + 1, sock))
            for j, sock in enumerate(socks)
        ]
        for th in threads:
            th.start()
        t0 = time.perf_counter()
        for i in range(read_iters):
            primary.forecast(ids[i % n_models], steps)
        results[0] = {"iters": read_iters,
                      "elapsed_s": time.perf_counter() - t0}
        for th in threads:
            th.join()
        total_rps = sum(
            r["iters"] / r["elapsed_s"] for r in results if r
        )
        try:
            host_cores = len(os.sched_getaffinity(0))
        except AttributeError:  # pragma: no cover - non-Linux
            host_cores = os.cpu_count() or 1
        ceiling = float(min(1 + len(socks), host_cores))
        out["read_scaling"] = {
            "reads_per_s_primary": round(primary_rps, 1),
            "reads_per_s_total": round(total_rps, 1),
            "replicas": len(socks),
            "scaling_x_vs_primary": round(total_rps / primary_rps, 2),
            "host_cores": host_cores,
            "scaling_ceiling_x": ceiling,
            "scaling_efficiency": round(
                (total_rps / primary_rps) / ceiling, 2
            ),
            "bar_scaling_x": 2.0,
        }
        progress(
            "repl_read_scaling", total=round(total_rps),
            vs_primary=out["read_scaling"]["scaling_x_vs_primary"],
            cores=host_cores,
        )
        write_partial(out_path, out)

        # -- phase 3: failover RTO + the fence ------------------------
        rpo_lag_s = hub.lag_seconds()
        t0 = time.perf_counter()
        report = rpc_call(
            socks[0], "repl_promote", {"checkpoint": True},
            timeout_s=600.0,
        )
        first = rpc_call(
            socks[0], "forecast",
            {"model_id": ids[0], "steps": steps}, timeout_s=300.0,
        )
        rto_ms = 1e3 * (time.perf_counter() - t0)
        fenced = False
        try:
            primary.update_batch(ids, obs_rows[-1])
        except PrimaryFencedError:
            fenced = True
        out["failover"] = {
            "rto_ms": round(rto_ms, 3),
            "promote_wall_ms": round(
                1e3 * report["promote_wall_s"], 3
            ),
            "rpo_lag_s_at_promote": round(rpo_lag_s, 6),
            "promoted_epoch": report["epoch"],
            "applied_commits": report["applied_commits"],
            "first_read_version": int(getattr(first, "version", 0)),
            "fenced_ack_rejected": fenced,
        }
        progress(
            "repl_failover", rto_ms=out["failover"]["rto_ms"],
            fenced=fenced,
        )
        write_partial(out_path, out)
        return out
    finally:
        if primary is not None:
            primary.close()
        for i, proc in enumerate(procs):
            try:
                rpc_call(os.path.join(work, f"standby{i + 1}.sock"),
                         "shutdown", timeout_s=10.0)
            except Exception:
                pass
            proc.join(timeout=15.0)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=5.0)
        shutil.rmtree(work, ignore_errors=True)


def run_capacity_bench(out_path: str, budget_s: float) -> dict:
    """Capacity & cost plane scenario (`obs/capacity.py`, ISSUE 13).

    Three measured claims:

    1. **Instrumentation overhead** — the capacity plane's own cost,
       isolated per the PR 11 detect methodology: full observability
       WITH capacity (stage decomposition + kernel ledger + SLO burn +
       cost ledger) vs full observability WITHOUT it, paired
       interleaved laps on the ARENA BULK update path at batch 256
       (bar <= 5%) and on CACHED snapshot reads (bar <= 1% — the
       cached path is deliberately untouched by the capacity plane,
       and this measures that it is).  The whole-stack-vs-disabled
       deployment delta is reported next to it, honestly.
    2. **Decomposition invariant** — on the open-loop serve-load
       generator (mixed 90/10 read/write through the micro-batcher),
       recorded stages must sum to >= 90% of end-to-end request wall
       (`CapacityTracker.coverage()`).
    3. **Saturation story** — the same run's `capacity_report()` must
       carry the ROADMAP item-1 evidence from live gauges alone:
       dispatch-thread utilization and the queue/lock stage shares.
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import jax
    import jax.numpy as jnp

    from metran_tpu.obs import (
        EventLog, MetricsRegistry, Observability, Tracer,
    )
    from metran_tpu.ops import dfm_statespace, kalman_filter
    from metran_tpu.serve import (
        MetranService, ModelRegistry, PosteriorState,
    )

    n_models, n, k_fct, t_hist = 256, 8, 1, 200
    n_load = 64  # open-loop decomposition leg fleet
    bulk_rounds, cr_reads, cr_rounds = 40, 20000, 15
    load_rps, load_s = 300.0, 6.0
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        n_models, n_load, t_hist = 16, 16, 60
        bulk_rounds, cr_reads, cr_rounds = 8, 2000, 5
        load_rps, load_s = 80.0, 2.0
    steps = 14
    deadline = time.monotonic() + budget_s
    out = {
        "platform": jax.default_backend(),
        "n_models": n_models, "n_series": n, "t_hist": t_hist,
    }

    rng = np.random.default_rng(31)
    alpha_sdf = rng.uniform(5.0, 40.0, (n_models, n))
    alpha_cdf = rng.uniform(10.0, 60.0, (n_models, k_fct))
    loadings = rng.uniform(0.3, 0.8, (n_models, n, k_fct)) / np.sqrt(k_fct)
    y = rng.normal(size=(n_models, t_hist, n))
    mask = rng.uniform(size=y.shape) > MISSING
    y = np.where(mask, y, 0.0)

    def one(a_s, a_c, ld, yy, mm):
        ss = dfm_statespace(a_s, a_c, ld, 1.0)
        res = kalman_filter(ss, yy, mm, engine="joint", store=False)
        return res.mean_f, res.cov_f

    means, covs = jax.jit(jax.vmap(one))(
        jnp.asarray(alpha_sdf), jnp.asarray(alpha_cdf),
        jnp.asarray(loadings), jnp.asarray(y), jnp.asarray(mask),
    )
    means, covs = np.asarray(means), np.asarray(covs)

    def make_service(bundle, readpath=False, flush_deadline=None,
                     capacity=None, fleet=None):
        fleet = n_models if fleet is None else fleet
        reg = ModelRegistry(
            root=None, arena=True, arena_rows=fleet,
        )
        for i in range(fleet):
            reg.put(PosteriorState(
                model_id=f"m{i}", version=0, t_seen=t_hist,
                mean=means[i], cov=covs[i],
                params=np.concatenate([alpha_sdf[i], alpha_cdf[i]]),
                loadings=loadings[i], dt=1.0,
                scaler_mean=np.zeros(n), scaler_std=np.ones(n),
                names=tuple(f"s{j}" for j in range(n)),
            ), persist=False)
        return MetranService(
            reg, flush_deadline=flush_deadline,
            persist_updates=False, observability=bundle,
            readpath=readpath,
            horizons=f"1-{steps}" if readpath else None,
            capacity=capacity,
        )

    def full_bundle():
        return Observability(
            metrics=MetricsRegistry(), tracer=Tracer(),
            events=EventLog(),
        )

    ids = [f"m{i}" for i in range(n_models)]

    # -- 1a. arena bulk update path: the capacity plane's own cost,
    # isolated (on vs off BOTH carry the full metrics/tracing/events
    # stack — the PR 11 detect methodology) next to the whole-stack
    # deployment delta vs everything disabled
    services = {
        "disabled": make_service(Observability.disabled()),
        "off": make_service(full_bundle(), capacity=False),
        "on": make_service(full_bundle()),
    }
    assert services["on"].capacity is not None
    assert services["off"].capacity is None
    assert services["disabled"].capacity is None
    bulk_obs = np.asarray(
        rng.normal(size=(n_models, 1, n)), dtype=float
    )

    def bulk_lap(svc) -> float:
        t0 = time.perf_counter()
        res = svc.update_batch(ids, bulk_obs)
        dt = time.perf_counter() - t0
        bad = [r for r in res if isinstance(r, BaseException)]
        if bad:
            raise bad[0]
        return dt

    for svc in services.values():  # warm: compiles + first snapshots
        bulk_lap(svc)
        bulk_lap(svc)
    names = list(services)
    ratios = {"capacity": [], "vs_disabled": []}
    for r in range(bulk_rounds):
        if time.monotonic() > deadline - 60:
            break
        order = names if r % 2 == 0 else names[::-1]
        lap = {m: bulk_lap(services[m]) for m in order}
        ratios["capacity"].append(lap["on"] / lap["off"])
        ratios["vs_disabled"].append(lap["on"] / lap["disabled"])
    bulk_coverage = services["on"].capacity.coverage()
    bulk_report = services["on"].capacity_report()
    for svc in services.values():
        svc.close()

    def pct(rs):  # qps overhead = 1 - 1/r for a paired lap-time ratio
        r = float(np.median(rs)) if rs else 1.0
        return round(100.0 * (1.0 - 1.0 / r), 2)

    out["overhead"] = {
        "batch": n_models,
        "laps": len(ratios["capacity"]),
        # the bar: stage stamps + kernel ledger + SLO burn + cost
        # ledger, same obs stack on both sides
        "update_qps_pct": pct(ratios["capacity"]),
        "bar_pct": 5.0,
        # the deployment delta (includes the pre-existing PR 4
        # metrics/tracing/events cost — reported honestly)
        "full_stack_vs_disabled_pct": pct(ratios["vs_disabled"]),
        "bulk_coverage": round(bulk_coverage, 4),
    }
    progress(
        "capacity_bulk_overhead", pct=out["overhead"]["update_qps_pct"],
        full_stack_pct=out["overhead"]["full_stack_vs_disabled_pct"],
        laps=out["overhead"]["laps"],
        coverage=out["overhead"]["bulk_coverage"],
    )
    write_partial(out_path, out)

    # -- 1b. cached snapshot reads: the path capacity must NOT touch
    # (same isolation: both sides carry the full obs stack)
    cached_svcs = {
        "off": make_service(
            full_bundle(), readpath=True, capacity=False
        ),
        "on": make_service(full_bundle(), readpath=True),
    }
    for svc in cached_svcs.values():
        svc.update_batch(ids, rng.normal(size=(n_models, 1, n)))

    def cached_lap(svc) -> float:
        fcf = svc.forecast
        t0 = time.perf_counter()
        for i in range(cr_reads):
            fcf(f"m{i % n_models}", steps)
        return time.perf_counter() - t0

    for svc in cached_svcs.values():
        cached_lap(svc)
    cr_ratios = []
    for r in range(cr_rounds):
        if time.monotonic() > deadline - 45:
            break
        order = ("off", "on") if r % 2 == 0 else ("on", "off")
        lap = {m: cached_lap(cached_svcs[m]) for m in order}
        cr_ratios.append(lap["on"] / lap["off"])
    cr_ratio = float(np.median(cr_ratios)) if cr_ratios else 1.0
    hits_on = cached_svcs["on"].readpath.hits
    for svc in cached_svcs.values():
        svc.close()
    out["cached_read"] = {
        "reads_per_lap": cr_reads,
        "laps": len(cr_ratios),
        "hits_on": hits_on,
        "overhead_pct": round(100.0 * (1.0 - 1.0 / cr_ratio), 2),
        "bar_pct": 1.0,
    }
    progress(
        "capacity_cached_overhead",
        pct=out["cached_read"]["overhead_pct"],
        laps=len(cr_ratios),
    )
    write_partial(out_path, out)

    # -- 2 + 3. open-loop mixed load: decomposition + saturation -------
    import threading

    svc = make_service(
        full_bundle(), flush_deadline=0.002, fleet=n_load
    )
    new_obs = rng.normal(size=(1, n))
    # warm every power-of-two dispatch width the generator can hit
    w = 1
    while w <= n_load:
        futs = [svc.update_async(f"m{i}", new_obs) for i in range(w)]
        [f.result(timeout=30) for f in futs]
        futs = [svc.forecast_async(f"m{i}", steps) for i in range(w)]
        [f.result(timeout=30) for f in futs]
        w *= 2
    load_s = min(load_s, max(deadline - time.monotonic() - 25, 2.0))
    n_req = int(load_rps * load_s)
    is_write = rng.uniform(size=n_req) < 0.1
    targets = rng.integers(0, n_load, size=n_req)
    failures = [0]
    lock = threading.Lock()
    resolved = [0]

    def _count(f):
        with lock:
            resolved[0] += 1

    t_start = time.monotonic() + 0.05
    for i in range(n_req):
        d = t_start + i / load_rps - time.monotonic()
        if d > 0:
            time.sleep(d)
        try:
            if is_write[i]:
                fut = svc.update_async(f"m{targets[i]}", new_obs)
            else:
                fut = svc.forecast_async(f"m{targets[i]}", steps)
            fut.add_done_callback(_count)
        except Exception:
            failures[0] += 1
    t_end = time.monotonic() + 20.0
    while time.monotonic() < t_end:
        with lock:
            if resolved[0] + failures[0] >= n_req:
                break
        time.sleep(0.05)
    report = svc.capacity_report()
    coverage = report["coverage"]
    stages = report["stages"]
    staged_total = sum(
        d["seconds_total"] for d in stages.values()
    ) or 1.0
    svc.close()
    out["decomposition"] = {
        "regime": f"open-loop {load_rps:.0f} rps, 0.9 read fraction",
        "requests": n_req,
        "failures": failures[0],
        "coverage": round(coverage, 4),
        "bar": 0.9,
        "pass": bool(coverage >= 0.9),
    }
    out["saturation"] = {
        # the ROADMAP item-1 story from live gauges alone
        "dispatch_utilization_60s": report["utilization_60s"],
        "queue_share": stages["queue"]["share"],
        "lock_share": stages["lock"]["share"],
        "device_share": stages["device"]["share"],
        "queue_wait_p99_ms": stages["queue"]["p99_ms"],
        "slo_burn": {
            k: round(w["burn_rate"], 3)
            for k, w in report["slo"]["windows"].items()
        },
    }
    # the full structured snapshot, renderable by
    # tools/capacity_report.py straight from this artifact
    report["kernels"] = report["kernels"][:12]
    out["report"] = report
    out["bulk_report_stages"] = {
        s: d["share"] for s, d in bulk_report["stages"].items()
    }
    progress(
        "capacity_decomposition", coverage=coverage,
        ok=out["decomposition"]["pass"],
        utilization=out["saturation"]["dispatch_utilization_60s"],
        queue_share=out["saturation"]["queue_share"],
    )
    write_partial(out_path, out)
    return out


def run_grad_bench(out_path: str, budget_s: float) -> dict:
    """Gradient-engine cost story (`ops/adjoint.py`, ISSUE 10).

    Three measured claims:

    1. **backward speed** — at the standard workload (T=5000 flagship
       shape, f64 — the CPU fit/refit regime where ``auto`` resolves
       to the adjoint), paired interleaved value-and-grad laps per
       engine: ``backward_s = value_and_grad_s - forward_s``, ratio =
       autodiff backward / adjoint backward, acceptance bar >= 2x;
    2. **backward memory flat in T** — subprocess peak-RSS deltas of
       one value-and-grad at T = 1e2/1e4/1e5 per gradient engine
       (``--phase grad-mem`` children; tracemalloc + jax device
       memory stats recorded when available — on the CPU backend the
       buffers are native, so peak RSS is the honest instrument);
    3. **anchored refit speed** — `refit_fleet` wall per batch under
       each engine (the background-refit path's models/s).
    """
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from metran_tpu.ops import deviance, dfm_statespace

    n, k_fct, t_steps = N_SERIES, N_FACTORS, T_STEPS
    pairs = 5
    mem_ts = (100, 10_000, 100_000)
    if os.environ.get("METRAN_TPU_BENCH_SMALL"):
        t_steps, pairs, mem_ts = 500, 2, (100, 2_000)
    deadline = time.monotonic() + budget_s
    out = {
        "platform": jax.default_backend(),
        "cpus": os.cpu_count(),
        "dtype": "float64",
        "n_series": n, "n_factors": k_fct, "t_steps": t_steps,
        "pairs": pairs,
        "engines": {}, "anchored": {}, "memory": {},
    }

    rng = np.random.default_rng(0)
    loadings = rng.uniform(0.4, 0.8, (n, k_fct))
    mask = rng.uniform(size=(t_steps, n)) > MISSING
    mask[0] = False
    y = np.where(mask, rng.normal(size=(t_steps, n)), 0.0)
    alpha = jnp.asarray(np.full(n + k_fct, 10.0))

    def dev(a, engine, grad):
        ss = dfm_statespace(a[:n], a[n:], jnp.asarray(loadings), 1.0)
        return deviance(
            ss, jnp.asarray(y), jnp.asarray(mask), warmup=1,
            engine=engine, grad=grad,
        )

    def lap(fn):
        t0 = time.perf_counter()
        r = fn(alpha)
        (r[0] if isinstance(r, tuple) else r).block_until_ready()
        return time.perf_counter() - t0

    for engine in ("sqrt", "joint"):
        fwd = jax.jit(lambda a, e=engine: dev(a, e, "autodiff"))
        vg = {
            mode: jax.jit(jax.value_and_grad(
                lambda a, e=engine, m=mode: dev(a, e, m)
            ))
            for mode in ("adjoint", "autodiff")
        }
        lap(fwd)  # warm (compile)
        for f in vg.values():
            lap(f)
        fwd_s = float(np.median([lap(fwd) for _ in range(3)]))
        # paired interleaved laps, alternating AB/BA order so drift
        # and contention hit both engines of each pair equally
        times = {"adjoint": [], "autodiff": []}
        for i in range(pairs):
            order = (
                ("adjoint", "autodiff") if i % 2 == 0
                else ("autodiff", "adjoint")
            )
            for mode in order:
                times[mode].append(lap(vg[mode]))
        vg_adj = float(np.median(times["adjoint"]))
        vg_auto = float(np.median(times["autodiff"]))
        bwd_adj = max(vg_adj - fwd_s, 1e-9)
        bwd_auto = max(vg_auto - fwd_s, 1e-9)
        out["engines"][engine] = {
            "forward_s": round(fwd_s, 5),
            "value_and_grad_s_adjoint": round(vg_adj, 5),
            "value_and_grad_s_autodiff": round(vg_auto, 5),
            "backward_s_adjoint": round(bwd_adj, 5),
            "backward_s_autodiff": round(bwd_auto, 5),
            "backward_speedup": round(bwd_auto / bwd_adj, 3),
            "value_and_grad_speedup": round(vg_auto / vg_adj, 3),
        }
        progress("grad_engine_timed", engine=engine,
                 **out["engines"][engine])
        write_partial(out_path, out)
        if time.monotonic() > deadline:
            out["truncated"] = "budget"
            write_partial(out_path, out)
            return out
    head = out["engines"].get("sqrt") or {}
    out["backward_speedup"] = head.get("backward_speedup", 0.0)
    out["bar"] = 2.0
    out["meets_bar"] = bool(out["backward_speedup"] >= 2.0)

    # flat-in-T backward memory: one subprocess per point so peak RSS
    # is a clean per-measurement instrument (RSS peaks are monotone
    # within a process).  Runs BEFORE the anchored section — memory is
    # the acceptance-critical claim, the refit A/B the bonus
    for t_mem in mem_ts:
        for grad in ("adjoint", "autodiff"):
            if time.monotonic() > deadline:
                out["memory"]["truncated"] = "budget"
                write_partial(out_path, out)
                return out
            mem_path = os.path.join(
                CACHE_DIR, f"bench_grad_mem_{t_mem}_{grad}.json"
            )
            if os.path.exists(mem_path):
                os.remove(mem_path)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS") or "cpu"
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--phase", "grad-mem", "--out", mem_path,
                 "--grad-t", str(t_mem), "--grad-mode", grad],
                stdout=subprocess.DEVNULL, env=env,
            )
            ok = _wait(
                proc, min(240.0, max(deadline - time.monotonic(), 30.0)),
                f"grad_mem_{t_mem}_{grad}",
            )
            rec = _read_json(mem_path) or {
                "error": "no output" if ok else "child failed/timeout"
            }
            out["memory"].setdefault(str(t_mem), {})[grad] = rec
            progress("grad_mem_point", t=t_mem, grad=grad, **{
                k: rec.get(k) for k in
                ("rss_delta_mb", "backward_s") if k in rec
            })
            write_partial(out_path, out)
    # headline comparison at the largest T (growth ratios degenerate
    # when the smaller points sit below RSS resolution — the adjoint's
    # deltas at T <= 1e4 measure 0 MB where the autodiff tape already
    # takes hundreds)
    try:
        t_hi = str(mem_ts[-1])
        peak_adj = out["memory"][t_hi]["adjoint"]["rss_delta_mb"]
        peak_auto = out["memory"][t_hi]["autodiff"]["rss_delta_mb"]
        out["memory"]["peak_mb_adjoint"] = peak_adj
        out["memory"]["peak_mb_autodiff"] = peak_auto
        out["memory"]["autodiff_vs_adjoint_peak"] = round(
            peak_auto / max(peak_adj, 1.0), 2
        )
        out["memory"]["max_t"] = int(mem_ts[-1])
        progress(
            "grad_mem_peak", t=int(mem_ts[-1]),
            adjoint_mb=peak_adj, autodiff_mb=peak_auto,
            ratio=out["memory"]["autodiff_vs_adjoint_peak"],
        )
    except (KeyError, TypeError):
        pass
    write_partial(out_path, out)

    # anchored refit objective: the background-refit fit path per
    # engine, at the refit bench's own scale (run_refit_bench: small
    # series counts and short tails — the full flagship shape costs
    # minutes per compile+run on a 1-core host and belongs to the
    # engines section above, which already measured it)
    if time.monotonic() > deadline:
        out["truncated"] = "budget"
        write_partial(out_path, out)
        return out
    try:
        from metran_tpu.parallel.fleet import refit_fleet

        b, tail, n_r = 8, 96, 6
        if os.environ.get("METRAN_TPU_BENCH_SMALL"):
            b, tail = 4, 48
        s_dim = n_r + k_fct
        lds = rng.uniform(0.4, 0.7, (b, n_r, k_fct))
        ym = rng.normal(size=(b, tail, n_r))
        mm = rng.uniform(size=(b, tail, n_r)) > MISSING
        m0 = np.zeros((b, s_dim))
        c0 = np.tile(np.eye(s_dim)[None], (b, 1, 1))
        p0 = np.full((b, n_r + k_fct), 10.0)

        def refit_wall(grad):
            t0 = time.perf_counter()
            refit_fleet(
                np.where(mm, ym, 0.0), mm, lds, np.ones(b), m0, c0,
                p0, maxiter=8, grad_engine=grad,
            )
            return time.perf_counter() - t0

        walls = {}
        for grad in ("adjoint", "autodiff"):
            refit_wall(grad)  # warm (compile)
            walls[grad] = refit_wall(grad)
            if time.monotonic() > deadline:
                break
        if len(walls) == 2:
            out["anchored"] = {
                "batch": b, "tail_rows": tail, "n_series": n_r,
                "maxiter": 8,
                "models_per_s_adjoint": round(b / walls["adjoint"], 2),
                "models_per_s_autodiff": round(
                    b / walls["autodiff"], 2
                ),
                "refit_speedup": round(
                    walls["autodiff"] / walls["adjoint"], 3
                ),
            }
            progress("grad_anchored", **out["anchored"])
        else:
            out["anchored"] = {"truncated": "budget"}
    except Exception as e:  # budget/oom must not sink the phase
        out["anchored"] = {"error": str(e)[-200:]}
    write_partial(out_path, out)
    return out


def run_grad_mem(out_path: str, t_steps: int, grad_mode: str) -> dict:
    """One backward-memory point (child of ``--phase grad``): peak RSS
    delta of one jitted value-and-grad at ``t_steps``, measured against
    a baseline taken after a tiny same-structure run has paid the
    import/compiler footprint.  tracemalloc only sees Python-side
    allocations (jax CPU buffers are native) and device memory stats
    are unavailable on CPU — both are still recorded, with RSS as the
    honest headline instrument."""
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", JAX_CACHE + "-cpu")
    import resource
    import tracemalloc

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from metran_tpu.ops import deviance, dfm_statespace

    n, k_fct = N_SERIES, N_FACTORS
    rng = np.random.default_rng(0)
    loadings = rng.uniform(0.4, 0.8, (n, k_fct))
    alpha = jnp.asarray(np.full(n + k_fct, 10.0))

    def make_vg(t):
        mask = rng.uniform(size=(t, n)) > MISSING
        y = jnp.asarray(np.where(mask, rng.normal(size=(t, n)), 0.0))
        mask = jnp.asarray(mask)

        def f(a):
            ss = dfm_statespace(
                a[:n], a[n:], jnp.asarray(loadings), 1.0
            )
            return deviance(
                ss, y, mask, warmup=1, engine="sqrt", grad=grad_mode
            )

        return jax.jit(jax.value_and_grad(f))

    def rss_kb() -> int:
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss

    # tiny twin first: imports, compiler machinery, executable caches
    tiny = make_vg(64)
    v, g = tiny(alpha)
    g.block_until_ready()
    base_kb = rss_kb()

    vg = make_vg(int(t_steps))
    tracemalloc.start()
    v, g = vg(alpha)  # compile + first run (allocates the real buffers)
    g.block_until_ready()
    t0 = time.perf_counter()
    v, g = vg(alpha)
    g.block_until_ready()
    bwd_plus_fwd_s = time.perf_counter() - t0
    _, py_peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    peak_kb = rss_kb()
    stats = jax.local_devices()[0].memory_stats()
    out = {
        "t_steps": int(t_steps),
        "grad": grad_mode,
        "engine": "sqrt",
        "rss_base_mb": round(base_kb / 1024.0, 1),
        "rss_peak_mb": round(peak_kb / 1024.0, 1),
        "rss_delta_mb": round((peak_kb - base_kb) / 1024.0, 1),
        "tracemalloc_peak_mb": round(py_peak / 1e6, 2),
        "device_memory_stats": (
            {k: int(v) for k, v in stats.items()
             if isinstance(v, (int, float))} if stats else None
        ),
        "value_and_grad_s": round(bwd_plus_fwd_s, 4),
    }
    write_partial(out_path, out)
    return out


# ----------------------------------------------------------------------
# orchestrator
# ----------------------------------------------------------------------
def _read_json(path: str):
    try:
        with open(path) as fh:
            return json.load(fh)
    except Exception:
        return None


def _dig(d, *keys):
    """Nested ``dict.get`` chain; None at the first miss."""
    for k in keys:
        if not isinstance(d, dict):
            return None
        d = d.get(k)
    return d


def _spawn(phase: str, out_path: str, budget: float, extra_env=None):
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--phase", phase,
         "--out", out_path, "--budget", str(budget)],
        stdout=subprocess.DEVNULL, env=env,
    )


def _last_known_good_tpu():
    """Summarize the newest committed on-chip bench record, if any.

    When the capture-time device is wedged and this run falls back to
    CPU, the artifact still carries a machine-readable pointer to the
    most recent REAL TPU record in ``bench_artifacts/`` — clearly
    labeled as builder-side provenance (captured by an earlier run of
    this same benchmark while the tunnel was alive), NOT a measurement
    of this run.  Readers wanting the raw evidence follow ``file``.
    """
    art_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_artifacts")
    try:
        names = [n for n in os.listdir(art_dir)
                 if n.startswith("BENCH_onchip") and n.endswith(".json")]
        # newest first by mtime (lexicographic order breaks across
        # rounds: "r10" sorts before "r4c"); stop at the first record
        # that is actually a TPU capture with a fit number
        names.sort(key=lambda n: os.path.getmtime(os.path.join(art_dir, n)),
                   reverse=True)
        for name in names:
            try:
                rec = _read_json(os.path.join(art_dir, name))
                if not rec or rec.get("platform") != "tpu":
                    continue
                fit = rec["detail"]["device"]["fit"]
                if not fit.get("fits_per_s"):
                    continue
            except Exception:  # malformed/shape-unexpected artifact:
                continue       # this path must never sink the fallback
            return {
                "file": f"bench_artifacts/{name}",
                "fits_per_s": fit["fits_per_s"],
                "converged_frac": fit.get("converged_frac"),
                "batch": fit.get("batch"),
                "provenance": (
                    "builder-side record from an earlier run of this "
                    "benchmark on the live tunnel; NOT captured by "
                    "this (fallback) run"
                ),
            }
    except Exception:
        pass
    return None


def _wait(proc, timeout: float, label: str) -> bool:
    try:
        proc.wait(timeout=max(timeout, 1.0))
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        progress(f"{label}_timeout", timeout_s=round(timeout, 0))
        proc.kill()
        proc.wait()
        return False


def _wait_device(proc, out_path: str, deadline: float,
                 init_timeout: float, poll_s: float = 5.0) -> str:
    """Wait for the device child, killing it EARLY if device init never
    completes — or if init succeeds but the executed-matmul probe never
    lands (the round-4 r4d wedge: instant jax.devices(), first dispatch
    hung >900 s) — so the retry/CPU fallback gets real budget.  The
    child being killed here is already hung mid-dispatch; the kill does
    not make the pool state worse (the dispatch is lost either way).

    Returns ``"ok"`` on a clean exit, else a human-readable failure
    reason — the round artifact records WHY a TPU attempt produced
    nothing instead of an information-free ``{"error": "no output"}``.
    """
    exec_timeout = float(
        os.environ.get("METRAN_TPU_BENCH_EXEC_TIMEOUT_S", "90")
    )
    init_deadline = time.monotonic() + init_timeout
    init_seen_at = None
    while True:
        try:
            proc.wait(timeout=poll_s)
            if proc.returncode == 0:
                return "ok"
            return (
                f"device child exited rc={proc.returncode} "
                "(crash/uncaught error before writing a fit result)"
            )
        except subprocess.TimeoutExpired:
            pass
        now = time.monotonic()
        part = _read_json(out_path)
        initialized = part is not None and "device_init_s" in part
        executed = part is not None and "device_exec_probe_s" in part
        if initialized and init_seen_at is None:
            init_seen_at = now
        if not initialized and now > init_deadline:
            progress("device_init_timeout", timeout_s=round(init_timeout, 0))
            proc.kill()
            proc.wait()
            return (
                f"device init did not complete within {init_timeout:.0f}s "
                "(wedged tunnel: jax backend never came up)"
            )
        if (initialized and not executed
                and now > init_seen_at + exec_timeout):
            progress("device_exec_timeout", timeout_s=round(exec_timeout, 0))
            proc.kill()
            proc.wait()
            return (
                "device initialized but the executed-matmul probe never "
                f"landed within {exec_timeout:.0f}s (wedged tunnel: "
                "first dispatch hung)"
            )
        if now > deadline:
            progress("device_timeout")
            proc.kill()
            proc.wait()
            return (
                "device-phase budget exhausted before a fit result "
                f"(killed at deadline; last stage: "
                f"{'executed probe' if executed else 'initialized' if initialized else 'pre-init'})"
            )


def main() -> None:
    budget = float(os.environ.get("METRAN_TPU_BENCH_BUDGET_S", "1100"))
    os.makedirs(JAX_CACHE, exist_ok=True)

    final = {"metric": METRIC, "value": 0.0, "unit": "fits/s/chip",
             "vs_baseline": 0.0}

    def _phase_summary(detail: dict) -> dict:
        """Small per-phase headline extract for the final stdout line
        (the full detail goes to the artifact file)."""
        g = lambda d, *ks: _dig(d, *ks)  # noqa: E731
        s = {
            "cpu_fit_s": g(detail, "cpu_baseline", "fit_s"),
            "serve_arena_speedup": g(
                detail, "serve", "arena_vs_dict", "arena_speedup"
            ),
            "serve_load_reads_per_s": g(
                detail, "serve_load", "cached", "achieved_read_rps"
            ),
            "serve_faults_degraded_qps": g(
                detail, "serve_faults", "poisoned_slot", "degraded_qps"
            ),
            "steady_speedup": g(
                detail, "steady", "steady", "throughput_ratio"
            ),
            "refit_models_per_s": g(
                detail, "refit", "refit", "models_per_s"
            ),
            "detect_overhead_pct": g(
                detail, "detect", "overhead", "update_qps_pct"
            ),
            "robust_gated_vs_robust": g(
                detail, "robust", "scenarios", "censor",
                "gated_vs_robust"
            ),
            "robust_overhead_pct": g(
                detail, "robust", "overhead", "serving_mix_pct"
            ),
            "capacity_overhead_pct": g(
                detail, "capacity", "overhead", "update_qps_pct"
            ),
            "capacity_cached_overhead_pct": g(
                detail, "capacity", "cached_read", "overhead_pct"
            ),
            "capacity_coverage": g(
                detail, "capacity", "decomposition", "coverage"
            ),
            "durability_overhead_pct": g(
                detail, "durability", "overhead", "update_qps_pct"
            ),
            "durability_recovery_ms_per_1k": g(
                detail, "durability", "recovery", "ms_per_1k_commits"
            ),
            "durability_replay_commits_per_s": g(
                detail, "durability", "recovery",
                "replay_commits_per_s"
            ),
            "cluster_reads_per_s": g(
                detail, "serve_cluster", "read_scaling",
                "reads_per_s_total"
            ),
            "cluster_read_scaling_x": g(
                detail, "serve_cluster", "read_scaling",
                "scaling_x_vs_single"
            ),
            "cluster_mixed_p99_ms": g(
                detail, "serve_cluster", "mixed", "p99_ms"
            ),
            "obs_fleet_rpc_overhead_pct": g(
                detail, "obs_fleet", "overhead", "rpc_overhead_pct"
            ),
            "obs_fleet_read_overhead_pct": g(
                detail, "obs_fleet", "overhead", "read_overhead_pct"
            ),
            "repl_lag_p99_ms": g(
                detail, "replication", "lag", "repl_lag_p99_ms"
            ),
            "failover_rto_ms": g(
                detail, "replication", "failover", "rto_ms"
            ),
            "replica_read_scaling_x": g(
                detail, "replication", "read_scaling",
                "scaling_x_vs_primary"
            ),
            "grad_backward_speedup": g(
                detail, "grad", "backward_speedup"
            ),
            "grad_mem_peak_mb_adjoint": g(
                detail, "grad", "memory", "peak_mb_adjoint"
            ),
            "grad_mem_peak_mb_autodiff": g(
                detail, "grad", "memory", "peak_mb_autodiff"
            ),
        }
        return {k: v for k, v in s.items() if v is not None}

    def emit_and_exit(code: int = 0):
        # the harness captures stdout and parses the final line as
        # JSON; rounds 4-5 embedded the ever-growing multi-phase
        # detail inline and the capture recorded "parsed": null.  Keep
        # the LAST stdout line small and self-contained (metric +
        # per-phase headline summary) and persist the full detail to a
        # committed artifact the line points at.
        detail = final.pop("detail", None)
        if detail is not None:
            rel = os.path.join("bench_artifacts",
                               "BENCH_detail_latest.json")
            path = os.path.join(REPO, rel)
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "w") as fh:
                    json.dump({**final, "detail": detail}, fh, indent=1)
                final["detail_file"] = rel
            except Exception as e:  # the summary line must still emit
                final["detail_file_error"] = str(e)[-120:]
            final["summary"] = _phase_summary(detail)
        print(json.dumps(final), flush=True)
        sys.exit(code)

    if os.environ.get("METRAN_TPU_BENCH_DRY_RUN"):
        # the bench-capture regression guard (tests/test_bench_capture
        # .py) drives the REAL final-line emitter — detail-file write,
        # per-phase summary extraction, the one compact stdout JSON —
        # without spawning any phase child.  PR 10 fixed the emitter
        # after rounds r01-r05 all recorded "parsed": null (the
        # ever-growing detail printed inline); this hook is what keeps
        # that contract pinned by a tier-1 test.  An optional
        # ..._DRY_RUN_DETAIL path injects a synthetic detail dict so
        # the test can assert the summary extraction itself.
        detail_src = os.environ.get("METRAN_TPU_BENCH_DRY_RUN_DETAIL")
        final["detail"] = (
            _read_json(detail_src) if detail_src else None
        ) or {"dry_run": True}
        emit_and_exit(0)

    def on_alarm(signum, frame):
        final.setdefault("detail", {})["error"] = (
            f"bench watchdog fired at {budget + 60:.0f}s"
        )
        emit_and_exit(1)

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(int(budget) + 60)

    cpu_path = os.path.join(CACHE_DIR, "bench_cpu.json")
    dev_path = os.path.join(CACHE_DIR, "bench_device.json")
    for p in (cpu_path, dev_path):
        if os.path.exists(p):
            os.remove(p)

    # CPU baseline and device bench run in parallel subprocesses; a
    # wedged TPU tunnel therefore cannot hang the whole benchmark
    # JAX_PLATFORMS=cpu + blanking the TPU-plugin autoregistration var
    # makes CPU children immune to a wedged device tunnel
    # CPU children get their own compilation cache: sharing the TPU
    # children's cache dir makes XLA load CPU AOT entries compiled under
    # a different host-feature set (SIGILL risk, noisy warnings)
    cpu_env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
               "JAX_COMPILATION_CACHE_DIR": JAX_CACHE + "-cpu"}
    # the CPU baseline runs SOLO first: it must own the host cores while
    # it times the reference-equivalent fit (running it alongside the
    # device child inflated it 22.7s -> 26s; alongside the mesh child,
    # 22.7s -> 61s — and vs_baseline with it)
    cpu_budget = min(400.0, budget * 0.4)
    cpu_proc = _spawn("cpu", cpu_path, cpu_budget, cpu_env)
    _wait(cpu_proc, cpu_budget + 30.0, "cpu_baseline")

    device_budget = budget - elapsed() - 120.0
    dev_proc = _spawn("device", dev_path, device_budget)
    # the (CPU-hungry) virtual-mesh phase overlaps only the TPU-bound
    # device child, never the CPU baseline
    mesh_path = os.path.join(CACHE_DIR, "bench_mesh.json")
    if os.path.exists(mesh_path):
        os.remove(mesh_path)
    mesh_budget = max(min(420.0, budget - elapsed() - 120.0), 60.0)
    mesh_proc = _spawn("mesh", mesh_path, mesh_budget, cpu_env)

    # the serving scenario (arena-vs-dict, qps, latency) spawns HERE,
    # alongside the device/mesh children rather than after them: a
    # device-stage budget blowout could previously starve it out of
    # the round JSON entirely (the serve numbers were asserted in-PR
    # but never captured).  CPU contention from the mesh child is
    # acceptable — the arena-vs-dict headline is a PAIRED interleaved
    # ratio, so contention hits both sides of each pair.
    serve_path = os.path.join(CACHE_DIR, "bench_serve.json")
    if os.path.exists(serve_path):
        os.remove(serve_path)
    serve_budget = max(min(300.0, budget * 0.35), 60.0)
    serve_proc = _spawn("serve", serve_path, serve_budget, cpu_env)

    init_timeout = float(
        os.environ.get("METRAN_TPU_BENCH_INIT_TIMEOUT_S", "300")
    )
    dev_reason = _wait_device(
        dev_proc, dev_path, time.monotonic() + device_budget, init_timeout
    )
    device = _read_json(dev_path) or {}
    if dev_reason != "ok" and "fit" not in device:
        device.setdefault("failure_reason", dev_reason)

    if "fit" not in device and budget - elapsed() > 420:
        # a wedged tunnel sometimes clears after the dead client is
        # reaped: one retry in a fresh subprocess before giving the
        # budget to the CPU fallback (round 2 lost its TPU headline to
        # a single unretried wedge)
        progress("device_retry", reason="no fit result from first attempt")
        first_attempt = device
        if os.path.exists(dev_path):
            os.remove(dev_path)
        retry_budget = budget - elapsed() - 150.0
        dev_proc = _spawn("device", dev_path, retry_budget)
        # when the FIRST attempt already failed at init (wedged tunnel),
        # a recovered tunnel initializes in seconds — give the retry a
        # short init window so a still-wedged device hands the remaining
        # budget to the CPU fallback instead of burning another full
        # init_timeout.  Only an attempt that also EXECUTED its probe
        # counts as healthy (init alone can succeed on a wedged tunnel);
        # an exec-hung first attempt gets the short window too.
        first_executed = "device_exec_probe_s" in first_attempt
        dev_reason = _wait_device(
            dev_proc, dev_path, time.monotonic() + retry_budget,
            init_timeout if first_executed else min(init_timeout, 120.0),
        )
        device = _read_json(dev_path) or {}
        if dev_reason != "ok" and "fit" not in device:
            device.setdefault("failure_reason", dev_reason)
        if first_attempt:
            device["first_attempt"] = first_attempt

    if "fit" not in device:
        # tunneled TPU failed or timed out: rerun the staged benchmark on
        # the CPU backend so the round still produces a measured number
        progress("device_fallback_cpu", reason="no fit result from device")
        fb_path = os.path.join(CACHE_DIR, "bench_device_cpu.json")
        if os.path.exists(fb_path):
            os.remove(fb_path)
        fb_budget = max(budget - elapsed() - 60.0, 120.0)
        fb_proc = _spawn("device-cpu", fb_path, fb_budget, cpu_env)
        _wait(fb_proc, fb_budget, "device_cpu")
        fallback = _read_json(fb_path) or {}
        if "fit" in fallback or "forward" in fallback:
            # record the ACTUAL failure reason, never a bare "no
            # output": the staged partials + _wait_device verdicts say
            # how far the attempt got and what killed it
            fallback["tpu_attempt"] = device or {
                "error": dev_reason if dev_reason != "ok" else
                "device child exited cleanly but wrote no result JSON",
            }
            fallback["last_known_good_tpu"] = _last_known_good_tpu()
            device = fallback

    cpu = _read_json(cpu_path) or {}
    _wait(mesh_proc, max(budget - elapsed() - 15.0, 5.0), "mesh")
    mesh = _read_json(mesh_path) or {}

    # serving-path scenario (spawned early, above): collect it now —
    # it normally finished while the device child ran
    _wait(serve_proc, max(serve_budget + 15.0 - elapsed(), 10.0), "serve")
    serve = _read_json(serve_path) or {}

    # open-loop load generator (ROADMAP item 2's measurement story):
    # p50/p99 of mixed read/write traffic at a fixed arrival rate
    # against a stated SLO, on the arena serving path
    serve_load = {}
    if budget - elapsed() > 90:
        sl_path = os.path.join(CACHE_DIR, "bench_serve_load.json")
        if os.path.exists(sl_path):
            os.remove(sl_path)
        sl_budget = max(min(120.0, budget - elapsed() - 60.0), 45.0)
        sl_proc = _spawn("serve-load", sl_path, sl_budget, cpu_env)
        _wait(sl_proc, sl_budget + 15.0, "serve_load")
        serve_load = _read_json(sl_path) or {}

    # fault-injection robustness scenario (CPU-pinned like serve):
    # error/degradation counters land in BENCH_*.json next to the perf
    # numbers, so robustness regressions show up in the same artifact
    serve_faults = {}
    if budget - elapsed() > 150:
        sf_path = os.path.join(CACHE_DIR, "bench_serve_faults.json")
        if os.path.exists(sf_path):
            os.remove(sf_path)
        sf_budget = max(min(180.0, budget - elapsed() - 60.0), 60.0)
        sf_proc = _spawn("serve-faults", sf_path, sf_budget, cpu_env)
        _wait(sf_proc, sf_budget + 15.0, "serve_faults")
        serve_faults = _read_json(sf_path) or {}

    # bounded-cost serving scenario (ROADMAP item 4's measurement
    # story): steady-path vs exact armed-gate update throughput
    # (paired interleaved), frozen-vs-exact deviation next to the
    # configured tolerance, and the update-cost-vs-t_seen flatness
    # curve — CPU-pinned like the other serve phases
    steady = {}
    if budget - elapsed() > 120:
        st_path = os.path.join(CACHE_DIR, "bench_steady.json")
        if os.path.exists(st_path):
            os.remove(st_path)
        st_budget = max(min(180.0, budget - elapsed() - 60.0), 60.0)
        st_proc = _spawn("steady", st_path, st_budget, cpu_env)
        _wait(st_proc, st_budget + 15.0, "steady")
        steady = _read_json(st_path) or {}

    # continuous-adaptation scenario (ISSUE 9's measurement story):
    # refit throughput through the lanes batch path, promotion swap
    # latency, and foreground serving impact while refits run —
    # CPU-pinned like the other serve phases
    refit = {}
    if budget - elapsed() > 120:
        rf_path = os.path.join(CACHE_DIR, "bench_refit.json")
        if os.path.exists(rf_path):
            os.remove(rf_path)
        rf_budget = max(min(180.0, budget - elapsed() - 60.0), 60.0)
        rf_proc = _spawn("refit", rf_path, rf_budget, cpu_env)
        _wait(rf_proc, rf_budget + 15.0, "refit")
        refit = _read_json(rf_path) or {}

    # online-monitoring scenario (ISSUE 11's measurement story):
    # armed-detector overhead on the arena bulk path (paired
    # interleaved, 3% bar) + detection-delay curves at a measured
    # clean-stream false-alarm rate — CPU-pinned like the others
    detect = {}
    if budget - elapsed() > 120:
        dt_path = os.path.join(CACHE_DIR, "bench_detect.json")
        if os.path.exists(dt_path):
            os.remove(dt_path)
        dt_budget = max(min(180.0, budget - elapsed() - 60.0), 60.0)
        dt_proc = _spawn("detect", dt_path, dt_budget, cpu_env)
        _wait(dt_proc, dt_budget + 15.0, "detect")
        detect = _read_json(dt_path) or {}

    # non-Gaussian observation robustness scenario (ISSUE 15's
    # measurement story): censored/quantized/heavy-tailed accuracy vs
    # the reject gate + the armed implicit-MAP overhead on the 90/10
    # serving mix — CPU-pinned like the other serve phases
    robust = {}
    if budget - elapsed() > 120:
        rb_path = os.path.join(CACHE_DIR, "bench_robust.json")
        if os.path.exists(rb_path):
            os.remove(rb_path)
        rb_budget = max(min(240.0, budget - elapsed() - 60.0), 60.0)
        rb_proc = _spawn("robust", rb_path, rb_budget, cpu_env)
        _wait(rb_proc, rb_budget + 15.0, "robust")
        robust = _read_json(rb_path) or {}

    # capacity & cost plane scenario (ISSUE 13's measurement story):
    # capacity-instrumentation overhead on the arena bulk path and on
    # cached reads (paired interleaved, 5%/1% bars) + the stage
    # decomposition's >= 90%-coverage invariant on the open-loop
    # generator — CPU-pinned like the other serve phases
    capacity = {}
    if budget - elapsed() > 120:
        cp_path = os.path.join(CACHE_DIR, "bench_capacity.json")
        if os.path.exists(cp_path):
            os.remove(cp_path)
        cp_budget = max(min(180.0, budget - elapsed() - 60.0), 60.0)
        cp_proc = _spawn("capacity", cp_path, cp_budget, cpu_env)
        _wait(cp_proc, cp_budget + 15.0, "capacity")
        capacity = _read_json(cp_path) or {}

    # durability-plane scenario (ISSUE 14's measurement story):
    # WAL-armed arena bulk overhead (paired interleaved, 10% bar) +
    # recovery replay throughput vs WAL tail length — CPU-pinned like
    # the other serve phases
    durability = {}
    if budget - elapsed() > 120:
        du_path = os.path.join(CACHE_DIR, "bench_durability.json")
        if os.path.exists(du_path):
            os.remove(du_path)
        du_budget = max(min(180.0, budget - elapsed() - 60.0), 60.0)
        du_proc = _spawn("durability", du_path, du_budget, cpu_env)
        _wait(du_proc, du_budget + 15.0, "durability")
        durability = _read_json(du_path) or {}

    # multi-process serving plane scenario (ISSUE 16's measurement
    # story): 1-writer-N-reader shared-memory read scaling vs the
    # single-process cached-read ceiling + the mixed 90/10 p99 through
    # the frontend split — CPU-pinned like the other serve phases
    serve_cluster = {}
    if budget - elapsed() > 150:
        sc_path = os.path.join(CACHE_DIR, "bench_serve_cluster.json")
        if os.path.exists(sc_path):
            os.remove(sc_path)
        sc_budget = max(min(240.0, budget - elapsed() - 60.0), 60.0)
        sc_proc = _spawn("serve-cluster", sc_path, sc_budget, cpu_env)
        _wait(sc_proc, sc_budget + 15.0, "serve_cluster")
        serve_cluster = _read_json(sc_path) or {}

    # WAL-shipped replication scenario (ISSUE 17's measurement story):
    # ship-lag p99 at the batch-512 bulk tick, 2-replica read fan-out,
    # failover RTO + the fenced ex-primary — CPU-pinned like the others
    replication = {}
    if budget - elapsed() > 150:
        rp_path = os.path.join(CACHE_DIR, "bench_replication.json")
        if os.path.exists(rp_path):
            os.remove(rp_path)
        rp_budget = max(min(240.0, budget - elapsed() - 60.0), 60.0)
        rp_proc = _spawn("replicate", rp_path, rp_budget, cpu_env)
        _wait(rp_proc, rp_budget + 15.0, "replication")
        replication = _read_json(rp_path) or {}

    # fleet observability overhead scenario (ISSUE 19's measurement
    # story): traced-vs-plain cluster RPC paired ratios + the
    # shared-memory read path's 0% claim — CPU-pinned like the others
    obs_fleet = {}
    if budget - elapsed() > 120:
        of_path = os.path.join(CACHE_DIR, "bench_obs_fleet.json")
        if os.path.exists(of_path):
            os.remove(of_path)
        of_budget = max(min(180.0, budget - elapsed() - 60.0), 60.0)
        of_proc = _spawn("obs-fleet", of_path, of_budget, cpu_env)
        _wait(of_proc, of_budget + 15.0, "obs_fleet")
        obs_fleet = _read_json(of_path) or {}

    # gradient-engine scenario (ISSUE 10's measurement story): adjoint
    # vs autodiff backward wall time at the standard workload, the
    # flat-in-T backward-memory curve, and the anchored refit
    # objective's fit speed per engine — CPU-pinned like the others
    grad = {}
    if budget - elapsed() > 120:
        gr_path = os.path.join(CACHE_DIR, "bench_grad.json")
        if os.path.exists(gr_path):
            os.remove(gr_path)
        gr_budget = max(min(240.0, budget - elapsed() - 60.0), 60.0)
        gr_proc = _spawn("grad", gr_path, gr_budget, cpu_env)
        _wait(gr_proc, gr_budget + 15.0, "grad")
        grad = _read_json(gr_path) or {}

    # solo (uncontended) sharding-overhead stage: runs after every other
    # child has exited so its ratio is clean (VERDICT r3 item 8)
    if budget - elapsed() > 90:
        solo_path = os.path.join(CACHE_DIR, "bench_mesh_solo.json")
        if os.path.exists(solo_path):
            os.remove(solo_path)
        solo_budget = max(budget - elapsed() - 30.0, 30.0)
        solo_proc = _spawn("mesh-solo", solo_path, solo_budget, cpu_env)
        _wait(solo_proc, solo_budget, "mesh_solo")
        solo = _read_json(solo_path)
        if solo:
            mesh["solo_overhead"] = solo

    detail = {"device": device, "cpu_baseline": cpu,
              "mesh_cpu_virtual": mesh, "serve": serve,
              "serve_load": serve_load,
              "serve_faults": serve_faults,
              "steady": steady,
              "refit": refit,
              "detect": detect,
              "robust": robust,
              "capacity": capacity,
              "durability": durability,
              "serve_cluster": serve_cluster,
              "replication": replication,
              "obs_fleet": obs_fleet,
              "grad": grad,
              "workload": {"n_series": N_SERIES, "n_factors": N_FACTORS,
                           "t_steps": T_STEPS, "missing": MISSING,
                           "maxiter": MAXITER, "tol": TOL}}
    final["detail"] = detail

    fit = device.get("fit")
    if fit:
        final["value"] = fit["fits_per_s"]
        final["platform"] = device.get("platform", "unknown")
    if fit and cpu.get("fit_s"):
        cpu_fits_per_s = 1.0 / cpu["fit_s"]
        final["vs_baseline"] = round(fit["fits_per_s"] / cpu_fits_per_s, 1)
        detail["cpu_fit_s_measured"] = cpu["fit_s"]
    single = device.get("single_fit")
    if (single and single.get("fit_s") and single.get("plausible")
            and cpu.get("fit_s")):
        # one-model latency vs the CPU reference's one-model fit
        single["vs_cpu_fit"] = round(cpu["fit_s"] / single["fit_s"], 1)
    progress("final", value=final["value"], vs_baseline=final["vs_baseline"])
    emit_and_exit(0 if final["value"] > 0 else 1)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--phase", default="main",
                        choices=["main", "cpu", "device", "device-cpu",
                                 "mesh", "mesh-solo", "serve",
                                 "serve-load", "serve-faults", "sqrt",
                                 "obs", "robust-obs", "robust",
                                 "steady", "refit", "detect",
                                 "capacity", "durability",
                                 "serve-cluster", "replicate",
                                 "obs-fleet", "grad", "grad-mem"])
    parser.add_argument("--out", default=None)
    parser.add_argument("--budget", type=float, default=900.0)
    parser.add_argument(
        "--grad-t", type=int, default=10_000,
        help="grad-mem: timestep count of the one measured "
             "value-and-grad",
    )
    parser.add_argument(
        "--grad-mode", default="adjoint",
        choices=["adjoint", "autodiff"],
        help="grad-mem: gradient engine of the measured backward pass",
    )
    parser.add_argument(
        "--rps", type=float, default=None,
        help="serve-load: total open-loop arrival rate of the "
             "dispatch section (default 400, env "
             "METRAN_TPU_BENCH_LOAD_RPS)",
    )
    parser.add_argument(
        "--read-fraction", type=float, default=None,
        help="serve-load: fraction of requests that are forecast "
             "reads in both sections (default 0.9, env "
             "METRAN_TPU_BENCH_READ_FRACTION)",
    )
    parser.add_argument(
        "--cached-rps", type=float, default=None,
        help="serve-load: total arrival rate of the cached "
             "(materialized read path) section (default 120000, env "
             "METRAN_TPU_BENCH_CACHED_RPS)",
    )
    args = parser.parse_args()
    if args.phase == "main":
        main()
    elif args.phase == "cpu":
        run_cpu_baseline(args.out, args.budget)
    elif args.phase == "mesh":
        run_mesh_bench(args.out, args.budget)
    elif args.phase == "mesh-solo":
        run_mesh_solo(args.out, args.budget)
    elif args.phase == "serve":
        out_path = args.out or os.path.join(CACHE_DIR, "bench_serve.json")
        os.makedirs(CACHE_DIR, exist_ok=True)
        serve_out = run_serve_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema
            qps = (serve_out.get("forecast") or {}).get("batched_qps", 0.0)
            print(json.dumps({
                "metric": "serve batched forecast queries/s",
                "value": qps, "unit": "queries/s", "vs_baseline": 0.0,
                "detail": serve_out,
            }), flush=True)
    elif args.phase == "serve-load":
        out_path = args.out or os.path.join(
            CACHE_DIR, "bench_serve_load.json"
        )
        os.makedirs(CACHE_DIR, exist_ok=True)
        sl_out = run_serve_load_bench(
            out_path, args.budget, rps=args.rps,
            read_fraction=args.read_fraction, cached_rps=args.cached_rps,
        )
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the cached-read headline (the scale number this phase
            # exists to measure); the dispatch-path SLO rides in detail
            cached = sl_out.get("cached") or {}
            print(json.dumps({
                "metric": (
                    "cached forecast reads/s (materialized read path, "
                    f"{sl_out.get('read_fraction')} read fraction, "
                    f"read p99 {(cached.get('read') or {}).get('p99_ms')}"
                    " ms)"
                ),
                "value": cached.get("achieved_read_rps", 0.0),
                "unit": "reads/s", "vs_baseline": 0.0,
                "detail": sl_out,
            }), flush=True)
    elif args.phase == "serve-faults":
        out_path = args.out or os.path.join(
            CACHE_DIR, "bench_serve_faults.json"
        )
        os.makedirs(CACHE_DIR, exist_ok=True)
        sf_out = run_serve_faults_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the degraded-throughput headline (how fast the service
            # still runs WITH a poisoned model in every batch)
            qps = (sf_out.get("poisoned_slot") or {}).get(
                "degraded_qps", 0.0
            )
            print(json.dumps({
                "metric": "serve update qps with 1/16 poisoned slots",
                "value": qps, "unit": "updates/s", "vs_baseline": 0.0,
                "detail": sf_out,
            }), flush=True)
    elif args.phase == "sqrt":
        out_path = args.out or os.path.join(CACHE_DIR, "bench_sqrt.json")
        os.makedirs(CACHE_DIR, exist_ok=True)
        sq_out = run_sqrt_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the robustness-cost headline (sqrt runtime per deviance
            # as a multiple of the joint engine's)
            ratio = (sq_out.get("overhead") or {}).get(
                "sqrt_vs_joint_deviance", 0.0
            )
            print(json.dumps({
                "metric": "sqrt engine deviance cost vs joint",
                "value": ratio, "unit": "x", "vs_baseline": 0.0,
                "detail": sq_out,
            }), flush=True)
    elif args.phase == "obs":
        out_path = args.out or os.path.join(CACHE_DIR, "bench_obs.json")
        os.makedirs(CACHE_DIR, exist_ok=True)
        obs_out = run_obs_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the instrumentation-cost headline (acceptance bar: < 5%)
            pct = (obs_out.get("overhead") or {}).get(
                "forecast_qps_pct", 0.0
            )
            print(json.dumps({
                "metric": "serve throughput overhead with full "
                          "observability",
                "value": pct, "unit": "%", "vs_baseline": 0.0,
                "detail": obs_out,
            }), flush=True)
    elif args.phase == "robust-obs":
        out_path = args.out or os.path.join(
            CACHE_DIR, "bench_robust_obs.json"
        )
        os.makedirs(CACHE_DIR, exist_ok=True)
        ro_out = run_robust_obs_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the accuracy headline (worst-case gated posterior RMSE as
            # a multiple of the clean run, across all 4 fault modes —
            # the acceptance bar is 2.0)
            ratios = [
                s.get("gated_vs_clean", 0.0)
                for s in (ro_out.get("scenarios") or {}).values()
            ]
            print(json.dumps({
                "metric": "worst gated-vs-clean posterior RMSE under "
                          "sensor faults",
                "value": round(max(ratios), 3) if ratios else 0.0,
                "unit": "x", "vs_baseline": 0.0,
                "detail": ro_out,
            }), flush=True)
    elif args.phase == "robust":
        out_path = args.out or os.path.join(
            CACHE_DIR, "bench_robust.json"
        )
        os.makedirs(CACHE_DIR, exist_ok=True)
        rb_out = run_robust_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema
            # with the accuracy headline (censored implicit-MAP RMSE
            # advantage over reject-gating on railed streams — the
            # acceptance bar is 2.0)
            cen = (rb_out.get("scenarios") or {}).get("censor") or {}
            print(json.dumps({
                "metric": "censored implicit-MAP RMSE advantage over "
                          "reject-gating on railed streams",
                "value": round(cen.get("gated_vs_robust", 0.0), 3),
                "unit": "x", "vs_baseline": 0.0,
                "detail": rb_out,
            }), flush=True)
    elif args.phase == "steady":
        out_path = args.out or os.path.join(CACHE_DIR, "bench_steady.json")
        os.makedirs(CACHE_DIR, exist_ok=True)
        st_out = run_steady_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the throughput-ratio headline (acceptance bar: >= 2x the
            # exact armed-gate update path at batch >= 256)
            st = st_out.get("steady") or {}
            print(json.dumps({
                "metric": (
                    "steady-path update throughput vs exact armed-gate "
                    f"(batch {st.get('n_models')}, max frozen-vs-exact "
                    f"mean dev {st.get('max_mean_deviation'):.2e} at "
                    f"tol {st.get('configured_tol')})"
                ),
                "value": st.get("throughput_ratio", 0.0),
                "unit": "x", "vs_baseline": 0.0,
                "detail": st_out,
            }), flush=True)
    elif args.phase == "refit":
        out_path = args.out or os.path.join(CACHE_DIR, "bench_refit.json")
        os.makedirs(CACHE_DIR, exist_ok=True)
        rf_out = run_refit_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the refit-throughput headline (cycle = anchored batch fit
            # + shadow comparison + promotion; acceptance bars: < 5%
            # armed foreground overhead and < 5% duty-cycle-amortized
            # degradation while a background refit batch runs)
            rf = rf_out.get("refit") or {}
            fg = rf_out.get("foreground") or {}
            print(json.dumps({
                "metric": (
                    "background refit throughput "
                    f"(batch {rf.get('n_models')}, "
                    f"{rf.get('tail_rows')}-row tails; swap p50 "
                    f"{(rf_out.get('swap') or {}).get('p50_ms')} ms; "
                    "foreground armed/amortized overhead "
                    f"{fg.get('armed_overhead')}/"
                    f"{fg.get('amortized_degradation')} vs 0.05 bar)"
                ),
                "value": rf.get("models_per_s", 0.0),
                "unit": "models/s", "vs_baseline": 0.0,
                "detail": rf_out,
            }), flush=True)
    elif args.phase == "detect":
        out_path = args.out or os.path.join(CACHE_DIR, "bench_detect.json")
        os.makedirs(CACHE_DIR, exist_ok=True)
        dt_out = run_detect_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the armed-detector overhead headline (acceptance bar:
            # < 3% on the arena bulk update path, paired interleaved)
            ov = dt_out.get("overhead") or {}
            print(json.dumps({
                "metric": (
                    "armed-detector update-throughput overhead on the "
                    f"arena bulk path (batch {ov.get('batch')}, "
                    f"{ov.get('laps')} paired laps; bar "
                    f"{ov.get('bar_pct')}%)"
                ),
                "value": ov.get("update_qps_pct", 0.0),
                "unit": "%", "vs_baseline": 0.0,
                "detail": dt_out,
            }), flush=True)
    elif args.phase == "capacity":
        out_path = args.out or os.path.join(
            CACHE_DIR, "bench_capacity.json"
        )
        os.makedirs(CACHE_DIR, exist_ok=True)
        cp_out = run_capacity_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema
            # with the instrumentation-cost headline (bars: <= 5%
            # arena bulk, <= 1% cached reads) next to the
            # decomposition-coverage invariant (>= 0.9)
            ov = cp_out.get("overhead") or {}
            dec = cp_out.get("decomposition") or {}
            print(json.dumps({
                "metric": (
                    "capacity-instrumentation overhead on the arena "
                    f"bulk update path (batch {ov.get('batch')}, "
                    f"{ov.get('laps')} paired laps; cached-read "
                    "overhead "
                    f"{(cp_out.get('cached_read') or {}).get('overhead_pct')}%"
                    f"; stage coverage {dec.get('coverage')} vs 0.9 "
                    "bar)"
                ),
                "value": ov.get("update_qps_pct", 0.0),
                "unit": "%", "vs_baseline": 0.0,
                "detail": cp_out,
            }), flush=True)
    elif args.phase == "durability":
        out_path = args.out or os.path.join(
            CACHE_DIR, "bench_durability.json"
        )
        os.makedirs(CACHE_DIR, exist_ok=True)
        du_out = run_durability_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema
            # with the WAL-overhead headline (bar: <= 10% on the
            # arena bulk path) next to the recovery replay rate
            # (bar: >= 10k commits/s)
            ov = du_out.get("overhead") or {}
            rc = du_out.get("recovery") or {}
            print(json.dumps({
                "metric": (
                    "WAL-armed arena bulk update overhead (batch "
                    f"{ov.get('batch')}, {ov.get('laps')} paired "
                    "laps; recovery replay "
                    f"{rc.get('replay_commits_per_s')} commits/s vs "
                    "10k bar)"
                ),
                "value": ov.get("update_qps_pct", 0.0),
                "unit": "%", "vs_baseline": 0.0,
                "detail": du_out,
            }), flush=True)
    elif args.phase == "serve-cluster":
        out_path = args.out or os.path.join(
            CACHE_DIR, "bench_serve_cluster.json"
        )
        os.makedirs(CACHE_DIR, exist_ok=True)
        sc_out = run_serve_cluster_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the aggregate shared-memory read rate next to both
            # scaling ratios (vs one worker alone, vs the
            # single-process cached-read ceiling) and the mixed-split
            # p99 against its 50 ms SLO
            rs = sc_out.get("read_scaling") or {}
            mx = sc_out.get("mixed") or {}
            print(json.dumps({
                "metric": (
                    f"cluster aggregate plane reads/s "
                    f"({rs.get('workers_reporting')} workers on "
                    f"{rs.get('host_cores')} core(s), "
                    f"{rs.get('scaling_efficiency')} of the "
                    f"core-capped ceiling; "
                    f"{rs.get('scaling_x_vs_single')}x single-process "
                    f"ceiling, {rs.get('scaling_x_vs_1worker')}x one "
                    f"worker; mixed 90/10 p99 {mx.get('p99_ms')} ms "
                    "vs 50 ms SLO)"
                ),
                "value": rs.get("reads_per_s_total", 0.0),
                "unit": "reads/s", "vs_baseline": 0.0,
                "detail": sc_out,
            }), flush=True)
    elif args.phase == "replicate":
        out_path = args.out or os.path.join(
            CACHE_DIR, "bench_replication.json"
        )
        os.makedirs(CACHE_DIR, exist_ok=True)
        rp_out = run_replication_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the ship-lag p99 headline (bar: < 250 ms at batch-512)
            # next to the failover RTO and the 2-replica read scaling
            lg = rp_out.get("lag") or {}
            rs = rp_out.get("read_scaling") or {}
            fo = rp_out.get("failover") or {}
            print(json.dumps({
                "metric": (
                    "replication ship-lag p99 (batch "
                    f"{lg.get('batch')} bulk ticks, "
                    f"{lg.get('lag_samples')} samples vs 250 ms bar; "
                    f"failover RTO {fo.get('rto_ms')} ms, "
                    f"{rs.get('scaling_x_vs_primary')}x reads with "
                    f"{rs.get('replicas')} replicas on "
                    f"{rs.get('host_cores')} core(s), fenced ack "
                    f"rejected={fo.get('fenced_ack_rejected')})"
                ),
                "value": lg.get("repl_lag_p99_ms", 0.0),
                "unit": "ms", "vs_baseline": 0.0,
                "detail": rp_out,
            }), flush=True)
    elif args.phase == "obs-fleet":
        out_path = args.out or os.path.join(
            CACHE_DIR, "bench_obs_fleet.json"
        )
        os.makedirs(CACHE_DIR, exist_ok=True)
        of_out = run_obs_fleet_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the traced-RPC overhead headline (bar: <= 5%) next to
            # the read path's 0%-by-construction claim
            ov = of_out.get("overhead") or {}
            fs = of_out.get("fleet_sample") or {}
            print(json.dumps({
                "metric": (
                    "traced cluster RPC overhead (paired, "
                    f"{ov.get('update_laps')} update laps vs 5% bar; "
                    f"plane read path {ov.get('read_overhead_pct')}%, "
                    f"{fs.get('trace_span_lanes')} merged process "
                    "lanes)"
                ),
                "value": ov.get("rpc_overhead_pct", 0.0),
                "unit": "%", "vs_baseline": 0.0,
                "detail": of_out,
            }), flush=True)
    elif args.phase == "grad":
        out_path = args.out or os.path.join(CACHE_DIR, "bench_grad.json")
        os.makedirs(CACHE_DIR, exist_ok=True)
        g_out = run_grad_bench(out_path, args.budget)
        if args.out is None:
            # standalone run: emit the BENCH_r* result-line schema with
            # the backward-speedup headline (acceptance bar: adjoint
            # backward >= 2x the autodiff-through-scan backward at the
            # standard T=5000 workload) next to the flat-in-T memory
            # growth factors
            mem = g_out.get("memory") or {}
            print(json.dumps({
                "metric": (
                    "adjoint-vs-autodiff backward speedup (sqrt "
                    f"engine, T={g_out.get('t_steps')}; peak backward "
                    f"memory at T={mem.get('max_t')}: adjoint "
                    f"{mem.get('peak_mb_adjoint')} MB vs autodiff "
                    f"{mem.get('peak_mb_autodiff')} MB)"
                ),
                "value": g_out.get("backward_speedup", 0.0),
                "unit": "x", "vs_baseline": 0.0,
                "detail": g_out,
            }), flush=True)
    elif args.phase == "grad-mem":
        out_path = args.out or os.path.join(
            CACHE_DIR, "bench_grad_mem.json"
        )
        os.makedirs(CACHE_DIR, exist_ok=True)
        run_grad_mem(out_path, args.grad_t, args.grad_mode)
    elif args.phase == "device":
        run_device_bench(args.out, args.budget)
    else:  # device-cpu fallback
        run_device_bench(args.out, args.budget, force_cpu=True)
