"""Benchmark: fleet DFM maximum-likelihood fits on device vs CPU reference.

Workload is the BASELINE.md headline config: 20-series dynamic factor
models (1 common factor, state dim 21), 5,000 timesteps, ~30% missing
observations.  The device side fits a batch of B independent models with
the fully on-device vmapped L-BFGS (`metran_tpu.parallel.fit_fleet`);
the baseline side times the reference algorithm's sequential-processing
filter pass on CPU (the native compiled kernel from `metran_tpu.native`
when available — the stand-in for the reference's numba engine — else the
plain numpy twin) and prices a CPU fit at
``iters * (n_params + 1)`` filter passes (finite-difference L-BFGS-B, one
pass per objective and ``n_params`` per gradient, using the same iteration
count the device optimizer needed — conservative for the baseline).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "fits/s/chip", "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

import numpy as np

N_SERIES = 20
N_FACTORS = 1
T_STEPS = 5_000
MISSING = 0.3
BATCH = 32
MAXITER = 40
SEED = 0


def make_workload(rng, batch):
    """Synthetic standardized DFM panels with a true common factor."""
    n, k, t = N_SERIES, N_FACTORS, T_STEPS
    loadings = rng.uniform(0.4, 0.8, (batch, n, k)) / np.sqrt(k)
    y = np.zeros((batch, t, n))
    for b in range(batch):
        phi_c = np.exp(-1.0 / rng.uniform(10.0, 60.0, k))
        phi_s = np.exp(-1.0 / rng.uniform(5.0, 40.0, n))
        common = np.zeros((t, k))
        specific = np.zeros((t, n))
        e_c = rng.normal(size=(t, k)) * np.sqrt(1 - phi_c**2)
        e_s = rng.normal(size=(t, n)) * np.sqrt(1 - phi_s**2)
        for i in range(1, t):
            common[i] = phi_c * common[i - 1] + e_c[i]
            specific[i] = phi_s * specific[i - 1] + e_s[i]
        comm = np.sum(loadings[b] ** 2, axis=1)
        y[b] = specific * np.sqrt(1 - comm) + common @ loadings[b].T
    mask = rng.uniform(size=y.shape) > MISSING
    return np.where(mask, y, 0.0), mask, loadings


def bench_device(y, mask, loadings):
    """Time the batched on-device MLE; returns (fits/sec/chip, iters)."""
    import jax
    import jax.numpy as jnp

    from metran_tpu.parallel import fit_fleet
    from metran_tpu.parallel.fleet import Fleet

    b = y.shape[0]
    fleet = Fleet(
        y=jnp.asarray(y, jnp.float32),
        mask=jnp.asarray(mask),
        loadings=jnp.asarray(loadings, jnp.float32),
        dt=jnp.ones(b, jnp.float32),
        n_series=jnp.full(b, N_SERIES, np.int32),
    )
    kwargs = dict(
        engine="joint", maxiter=MAXITER, chunk=8, tol=0.5, stall_tol=0.0
    )
    fit = fit_fleet(fleet, **kwargs)  # compile + run
    jax.block_until_ready(fit.params)
    start = time.perf_counter()
    fit = fit_fleet(fleet, **kwargs)
    jax.block_until_ready(fit.params)
    elapsed = time.perf_counter() - start
    iters = float(np.mean(np.asarray(fit.iterations)))
    return b / elapsed, iters


def cpu_filter_pass_seconds(y, mask, loadings):
    """Seconds for ONE sequential-processing filter pass on CPU.

    Uses the compiled native kernel (metran_tpu.native) when available —
    the honest stand-in for the reference's numba engine — else the plain
    numpy loop implementing the same algorithm
    (reference metran/kalmanfilter.py:122-233).
    """
    n, k = N_SERIES, N_FACTORS
    alpha = np.full(n + k, 10.0)
    phi = np.exp(-1.0 / alpha)
    comm = np.sum(loadings**2, axis=1)
    q = np.diag(
        np.concatenate([(1 - phi[:n] ** 2) * (1 - comm), 1 - phi[n:] ** 2])
    )
    z = np.concatenate([np.eye(n), loadings], axis=1)
    r = np.zeros(n)

    try:
        from metran_tpu.native import seq_filter_pass

        seq_filter_pass(phi, q, z, r, y[:8], mask[:8])  # probe: builds/loads
        runner = lambda: seq_filter_pass(phi, q, z, r, y, mask)  # noqa: E731
        engine = "native"
    except Exception:
        runner = lambda: _np_filter_pass(phi, q, z, r, y, mask)  # noqa: E731
        engine = "numpy"
    runner()  # warm (JIT/alloc)
    best = np.inf
    for _ in range(2):
        t0 = time.perf_counter()
        runner()
        best = min(best, time.perf_counter() - t0)
    return best, engine


def _np_filter_pass(phi, q, z, r, y, mask):
    t_steps, m = y.shape
    n = phi.shape[0]
    mean = np.zeros(n)
    cov = np.eye(n)
    sigma = 0.0
    detf = 0.0
    for t in range(t_steps):
        mean = phi * mean
        cov = phi[:, None] * cov * phi[None, :] + q
        for i in range(m):
            if not mask[t, i]:
                continue
            zi = z[i]
            v = y[t, i] - zi @ mean
            d = cov @ zi
            f = zi @ d + r[i]
            kgain = d / f
            cov = cov - np.outer(kgain, kgain) * f
            mean = mean + kgain * v
            sigma += v * v / f
            detf += np.log(f)
    return sigma, detf


def main():
    import signal
    import sys

    def _watchdog(signum, frame):
        # a wedged device tunnel must not hang the driver: report failure
        # as a JSON line and exit nonzero
        print(
            json.dumps(
                {
                    "metric": "DFM fits/sec/chip (20-series, 5k steps)",
                    "value": 0.0,
                    "unit": "fits/s/chip",
                    "vs_baseline": 0.0,
                    "error": "watchdog: device call exceeded 1200s",
                }
            )
        )
        sys.stdout.flush()
        sys.exit(1)

    signal.signal(signal.SIGALRM, _watchdog)
    signal.alarm(1200)

    rng = np.random.default_rng(SEED)
    y, mask, loadings = make_workload(rng, BATCH)

    fits_per_sec, iters = bench_device(y, mask, loadings)

    pass_s, engine = cpu_filter_pass_seconds(y[0], mask[0], loadings[0])
    n_params = N_SERIES + N_FACTORS
    cpu_fit_s = max(iters, 1.0) * (n_params + 1) * pass_s
    cpu_fits_per_sec = 1.0 / cpu_fit_s

    print(
        json.dumps(
            {
                "metric": "DFM fits/sec/chip (20-series, 5k steps)",
                "value": round(fits_per_sec, 3),
                "unit": "fits/s/chip",
                "vs_baseline": round(fits_per_sec / cpu_fits_per_sec, 1),
                "detail": {
                    "batch": BATCH,
                    "lbfgs_iters_mean": round(iters, 1),
                    "cpu_baseline_engine": engine,
                    "cpu_filter_pass_s": round(pass_s, 4),
                    "cpu_fit_s_est": round(cpu_fit_s, 2),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
