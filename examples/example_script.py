"""End-to-end Metran workflow on the five groundwater residual series.

The same user journey as the reference's example (ingest -> solve ->
inspect states/simulations -> mask outliers -> decompose -> plot),
running on the JAX engine with exact autodiff gradients.  Works on CPU
(float64, reference parity) and TPU alike.

Run:  python examples/example_script.py [data_dir]
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root

# Default to the CPU backend: an ambient tunneled-TPU platform makes
# ``jax.devices()`` hang indefinitely when the tunnel is wedged, and the
# JAX_PLATFORMS env var is ignored by that plugin (only the config call
# works).  Set METRAN_TPU_EXAMPLE_TPU=1 on a healthy accelerator host.
if not os.environ.get("METRAN_TPU_EXAMPLE_TPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import pandas as pd

import metran_tpu

DATA = Path(sys.argv[1]) if len(sys.argv) > 1 else (
    Path(__file__).resolve().parent / "data"
)


def load_series():
    series = []
    for fi in sorted(DATA.glob("*_res.csv")):
        s = pd.read_csv(
            fi, header=0, index_col=0, parse_dates=True,
            names=[fi.stem.split("_")[0]],
        ).squeeze()
        series.append(s)
    return series


def main():
    series = load_series()

    # construct + fit (factor analysis -> MLE via L-BFGS on the exact
    # autodiff gradient of the Kalman-filter likelihood)
    mt = metran_tpu.Metran(series, name="B21B0214")
    mt.solve()  # prints the fit + metran reports

    # smoothed states and per-series simulation with 95% CI
    states = mt.get_state_means()
    sim = mt.get_simulation("B21B0214005", alpha=0.05)
    print("\nsmoothed states:", states.shape, "simulation:", sim.shape)

    # counterfactual: hide one observation and compare projections
    mask = (0 * mt.get_observations()).astype(bool)
    mask.loc["1997-08-28", "B21B0214005"] = True
    mt.mask_observations(mask)
    sim_masked = mt.get_simulation("B21B0214005", alpha=None)
    mt.unmask_observations()
    delta = (sim["mean"] - sim_masked).abs().max()
    print(f"max simulation change from masking one observation: {delta:.4f}")

    # decomposition into specific + common contributions
    parts = mt.decompose_simulation("B21B0214001")
    print("decomposition columns:", list(parts.columns))

    # adequacy diagnostics: standardized one-step-ahead innovations and
    # the per-series Ljung-Box whiteness verdict (no reference
    # equivalent)
    innov = mt.get_innovations()
    print("innovation std (want ~1):", round(float(innov.stack().std()), 3))
    print(mt.test_whiteness(lags=15, warmup=50))

    # persistence: full model (data + fit) round-trips through one file
    path = Path("/tmp/metran_model.json")
    mt.to_file(path)
    mt2 = metran_tpu.Metran.from_file(path)
    print("reloaded objective:", round(mt2.fit.obj_func, 3))

    # plots
    mt.plots.scree_plot()
    plt.savefig("/tmp/scree.png")
    mt.plots.simulation("B21B0214003")
    plt.savefig("/tmp/simulation.png")
    print("plots written to /tmp/scree.png, /tmp/simulation.png")


if __name__ == "__main__":
    main()
