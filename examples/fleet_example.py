"""Fleet-scale fitting: many independent DFMs batched and mesh-sharded.

What the reference cannot do at all: fit hundreds/thousands of dynamic
factor models in one compiled program — vmapped over the fleet axis,
L-BFGS fully on device, optionally sharded over a ``jax.sharding.Mesh``
(data parallelism over models; on TPU pods the shards ride ICI).

Run on CPU with a virtual mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/fleet_example.py
"""

import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root

import numpy as np
import pandas as pd

import jax

# Default to the CPU backend: an ambient tunneled-TPU platform makes
# ``jax.devices()`` hang indefinitely when the tunnel is wedged, and the
# JAX_PLATFORMS env var is ignored by that plugin (only the config call
# works).  Set METRAN_TPU_EXAMPLE_TPU=1 on a healthy accelerator host.
if not os.environ.get("METRAN_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

from metran_tpu import data as mdata
from metran_tpu.diagnostics import fleet_whiteness
from metran_tpu.models.factoranalysis import FactorAnalysis
from metran_tpu.parallel import (
    autocorr_init_params,
    fit_fleet,
    fleet_forecast,
    fleet_innovations,
    fleet_sample,
    fleet_simulate,
    fleet_stderr,
    make_mesh,
    pack_fleet,
    pad_to_multiple,
    sweep_fit,
)
from metran_tpu.utils import ThroughputCounter


def synthetic_panel(rng, n_series=8, t=730):
    """One synthetic groundwater-like cluster (AR(1) + common factor)."""
    idx = pd.date_range("2010-01-01", periods=t, freq="D")
    phi_c = np.exp(-1.0 / rng.uniform(20, 60))
    common = np.zeros(t)
    for i in range(1, t):
        common[i] = phi_c * common[i - 1] + rng.normal() * np.sqrt(
            1 - phi_c**2
        )
    load = rng.uniform(0.5, 0.9, n_series)
    phi_s = np.exp(-1.0 / rng.uniform(5, 30, n_series))
    spec = np.zeros((t, n_series))
    for i in range(1, t):
        spec[i] = phi_s * spec[i - 1] + rng.normal(size=n_series) * np.sqrt(
            1 - phi_s**2
        )
    y = spec * np.sqrt(1 - load**2) + np.outer(common, load)
    y[rng.uniform(size=y.shape) < 0.2] = np.nan  # 20% missing
    return pd.DataFrame(y, index=idx, columns=[f"w{i}" for i in range(n_series)])


def main():
    rng = np.random.default_rng(0)
    n_models = 16

    # ingest + factor analysis per model (host side, cheap)
    panels, loadings = [], []
    for _ in range(n_models):
        frame = synthetic_panel(rng)
        standardized, std, mean = mdata.standardize(frame)
        panels.append(mdata.pack_panel(standardized, std=std, mean=mean))
        loadings.append(FactorAnalysis().solve(standardized))

    mesh = make_mesh()  # all available devices
    fleet = pack_fleet(
        panels, loadings,
        pad_batch_to=pad_to_multiple(n_models, mesh.size),
    )
    print(
        f"fleet: {fleet.batch} models x {fleet.y.shape[1]} steps x "
        f"{fleet.y.shape[2]} series on {mesh.size} devices"
    )

    counter = ThroughputCounter(unit="fits")
    with counter.measure(n=n_models):
        # practical fleet settings: the lane-layout kernel + grid
        # L-BFGS (the TPU hot path — see README), the data-driven
        # lag-1-autocorrelation init (~25% fewer iterations), a
        # deviance-scale tolerance, segmented gradient remat, and
        # per-iteration stall-freezing so each lane stops the moment it
        # hits the floating-point resolution floor near its optimum
        fit = fit_fleet(
            fleet, p0=autocorr_init_params(fleet),
            mesh=mesh, maxiter=40, chunk=10,
            tol=1e-2, stall_tol=1e-4,
            layout="lanes", remat_seg=128,
            checkpoint="/tmp/fleet_ckpt.npz",  # preemption-safe
        )
        jax.block_until_ready(fit.params)
    print(counter.summary())
    print(
        "deviance quantiles:",
        np.quantile(np.asarray(fit.deviance[:n_models]), [0.1, 0.5, 0.9]).round(1),
    )
    print(
        "converged:", int(np.asarray(fit.converged[:n_models]).sum()),
        "/", n_models,
        "(stalled at the resolution floor:",
        int(np.asarray(fit.stalled[:n_models]).sum()), ")",
    )

    # batched post-fit products: per-model stderr and smoothed
    # projections (method="lanes-fd" is the TPU-fast Hessian)
    stderr, _ = fleet_stderr(fit.params, fleet, method="lanes-fd",
                             batch_chunk=8)
    means, variances = fleet_simulate(fit.params, fleet, batch_chunk=8)
    # out-of-sample: 30-day forecasts for the whole fleet at once
    fmeans, fvars = fleet_forecast(fit.params, fleet, steps=30,
                                   batch_chunk=8)
    print("forecast grid (models, steps, series):", tuple(fmeans.shape))
    # adequacy + joint-path products for the whole fleet
    # warmup=50 drops the filter-init transient (the same default
    # Metran.test_whiteness uses) so the whiteness test is calibrated
    v, _ = fleet_innovations(fit.params, fleet, batch_chunk=8, warmup=50)
    wh = fleet_whiteness(np.asarray(v)[:n_models], lags=10)
    ok = np.isfinite(wh.pvalue)  # padded/untestable cells are NaN
    frac = float(np.mean(wh.pvalue[ok] >= 0.05))
    print("whiteness pass fraction (model, series cells):", round(frac, 2))
    draws = fleet_sample(fit.params, fleet, n_draws=4, batch_chunk=8)
    print("posterior path draws:", tuple(np.asarray(draws).shape))
    print(
        "median stderr(alpha):",
        float(np.nanmedian(np.asarray(stderr[:n_models]))).__round__(2),
        "| simulation grid:", tuple(means.shape),
    )

    # populations larger than one batch: sweep_fit chains bounded
    # fit_fleet calls (one compile), prefetches each next batch's host
    # work behind the current fit, and checkpoints per batch so a rerun
    # resumes at the first unfinished batch
    def batch_spec(seed, batch=4):
        def make():
            r = np.random.default_rng(seed)
            ps, lds = [], []
            for _ in range(batch):
                std, s_, m_ = mdata.standardize(synthetic_panel(r))
                ps.append(mdata.pack_panel(std, std=s_, mean=m_))
                lds.append(FactorAnalysis().solve(std))
            return pack_fleet(ps, lds)
        return make

    # fresh checkpoint dir per run: sweep checkpoints restore by
    # position with no fingerprint, so a stale dir would silently
    # serve the previous run's results (see sweep_fit docstring)
    res = sweep_fit(
        [batch_spec(s) for s in (1, 2, 3)],
        layout="lanes", maxiter=20, chunk=10, stall_tol=1e-4,
        checkpoint_dir=tempfile.mkdtemp(prefix="fleet_sweep_"),
    )
    print("sweep:", res.total, "models in", len(res.batch_sizes),
          "batches | converged:", int(res.converged.sum()))


if __name__ == "__main__":
    main()
