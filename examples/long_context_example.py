"""Sequence parallelism: filtering a series too long for one device.

The reference's Kalman loop is O(T) sequential (its numba recursion,
``metran/kalmanfilter.py:236-400``) and everything lives on one host.
``metran_tpu`` reformulates the filter/smoother as associative scans
(``ops/pkalman.py``), which makes the TIME axis shardable: each device
filters its own contiguous chunk of the series, and the devices
exchange ONE combine element each — the cross-device traffic is
O(n_devices), independent of T.

This example runs on the CPU backend with 8 virtual devices (the same
environment the test suite uses), so it works anywhere; on real
hardware the mesh axis maps onto TPU chips over ICI and the per-shard
arrays live in each chip's own HBM — sequences that overflow one
chip's memory simply shard further.

Run:  python examples/long_context_example.py
"""

import os
import sys

# runnable from a clean checkout without installing the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import time

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from metran_tpu.ops import (
    deviance_terms,
    dfm_statespace,
    sequence_sharded_filter,
)


def main():
    # respect a pre-existing device-count flag: T must divide the mesh
    n_devices = len(jax.devices())
    n, k, t = 8, 1, 32_768  # 32k steps: the regime blocking exists for
    t -= t % n_devices
    rng = np.random.default_rng(0)

    ss = dfm_statespace(
        rng.uniform(5.0, 40.0, n),
        rng.uniform(10.0, 60.0, k),
        rng.uniform(0.3, 0.8, (n, k)) / np.sqrt(k),
        1.0,
    )
    mask = rng.uniform(size=(t, n)) > 0.3
    y = np.where(mask, rng.normal(size=(t, n)), 0.0)

    mesh = Mesh(np.asarray(jax.devices()[:n_devices]), ("seq",))
    print(f"mesh: {n_devices} devices on axis 'seq'; T = {t:,} steps "
          f"({t // n_devices:,} per device)")

    t0 = time.monotonic()
    filt, smooth = sequence_sharded_filter(
        ss, y, mask, mesh, axis="seq", block=512
    )
    jax.block_until_ready((filt.mean_f, smooth.mean_s))
    print(f"compile + first run: {time.monotonic() - t0:.1f} s "
          "(the unsharded full-length combine tree took 188 s to "
          "compile on TPU and crashed XLA:CPU at this length)")

    t0 = time.monotonic()
    filt, smooth = sequence_sharded_filter(
        ss, y, mask, mesh, axis="seq", block=512
    )
    jax.block_until_ready((filt.mean_f, smooth.mean_s))
    print(f"steady run (filter + smoother): {time.monotonic() - t0:.2f} s")

    dev = float(deviance_terms(filt.sigma, filt.detf, jnp.asarray(mask)))
    print(f"deviance over the sharded axis: {dev:.3f}")

    # the smoothed states interpolate through the 30% gaps
    m = np.asarray(smooth.mean_s)
    print("smoothed state grid:", m.shape,
          f"finite: {np.isfinite(m).all()}")


if __name__ == "__main__":
    main()
