"""metran_tpu — TPU-native dynamic factor modeling of multivariate time
series (JAX/XLA).

A ground-up rebuild of the capabilities of ``pastas/metran`` designed for
TPU: the Kalman filter/smoother as ``lax.scan`` recursions compiled by XLA,
exact autodiff gradients of the marginal likelihood, ``vmap`` over fleets of
models, and device-mesh sharding for multi-chip scale.
"""

from . import config, data, io, ops, reliability, utils
from .io import load_model, save_model
from .utils import show_versions
from .version import __version__

__all__ = [
    "config",
    "data",
    "io",
    "load_model",
    "save_model",
    "ops",
    "reliability",
    "serve",
    "utils",
    "show_versions",
    "__version__",
]


def __getattr__(name):
    # Lazy imports keep `import metran_tpu` light and avoid import cycles.
    if name in ("Metran", "FactorAnalysis"):
        from . import models

        return getattr(models, name)
    if name in ("BaseSolver", "ScipySolve", "JaxSolve", "LanesSolve",
                "LmfitSolve", "SolverDivergenceError"):
        from .models import solver

        return getattr(solver, name)
    if name in ("serve", "MetranService", "ModelRegistry",
                "PosteriorState"):
        # importlib, not `from . import serve`: the latter re-enters
        # this __getattr__ for the not-yet-bound submodule attribute
        import importlib

        serve = importlib.import_module(".serve", __name__)
        return serve if name == "serve" else getattr(serve, name)
    raise AttributeError(f"module 'metran_tpu' has no attribute {name!r}")
