"""Multi-process serving plane (docs/concepts.md "Multi-process serving").

The single-process :class:`~metran_tpu.serve.MetranService` tops out
where one Python interpreter does: reads queue behind writes on one
GIL however many cores the host has.  This package is the split that
breaks it, with three pieces:

- :mod:`~metran_tpu.cluster.snapplane` — a seqlock-versioned
  ``multiprocessing.shared_memory`` slot table the writer publishes
  committed forecast snapshots into (the ``SnapshotStore``'s second
  sink); read workers probe it lock-free, with zero device traffic;
- :mod:`~metran_tpu.cluster.writer` / :mod:`~metran_tpu.cluster.
  worker` / :mod:`~metran_tpu.cluster.frontend` — the single-writer
  split: ONE process owns update dispatch, the ``StateArena`` and the
  WAL (the group-commit stream doubling as the cross-process commit
  notification), N processes serve reads, and a thin frontend routes
  while preserving the single-process API and semantics;
- :mod:`~metran_tpu.cluster.mesh` — ``jax.distributed`` batch-axis
  sharding that extends the arena's device mesh across processes,
  bit-identical to single-process at f64;
- :mod:`~metran_tpu.cluster.replication` — WAL frame shipping to
  continuously-replaying hot standbys (ack-synchronous, so failover
  loses zero acked commits), replica read fan-out, and epoch-fenced
  promotion (docs/concepts.md "Replication & failover").

Opt-in end to end: ``MetranService(cluster=ClusterSpec(...))`` arms
the writer-side plane, :class:`~metran_tpu.cluster.frontend.
ClusterFrontend` runs the topology; shipped off
(``METRAN_TPU_SERVE_CLUSTER``).
"""

from .frontend import ClusterFrontend
from .ipc import RpcClient, RpcServer
from .replication import (
    ReplicaStandby,
    ReplicationHub,
    ReplicationSpec,
    StaleEpochError,
    standby_main,
)
from .snapplane import SnapshotPlane, plane_bytes
from .spec import ClusterSpec
from .worker import ReadWorker, worker_main
from .writer import WriterHost, writer_main

__all__ = [
    "ClusterFrontend",
    "ClusterSpec",
    "ReadWorker",
    "ReplicaStandby",
    "ReplicationHub",
    "ReplicationSpec",
    "RpcClient",
    "RpcServer",
    "SnapshotPlane",
    "StaleEpochError",
    "WriterHost",
    "plane_bytes",
    "standby_main",
    "worker_main",
    "writer_main",
]
