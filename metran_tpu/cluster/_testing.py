"""Spawn-safe fixtures for cluster tests and the serve-cluster bench.

Writer and publisher processes are started with the **spawn** method,
so their entry callables must be picklable module-level functions the
child can re-import under the same dotted name.  Test modules are not
reliably importable inside a spawned child (pytest's rootdir-relative
imports do not exist there); this module is.  ``bench.py --phase
serve-cluster`` uses the same factory, so the measured topology is
exactly the tested topology.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "make_states",
    "seed_root",
    "standby_service_factory",
    "storm_publisher",
    "writer_service_factory",
]


def make_states(seed=7, n_models=4, n=5, kf=1, t=60,
                dtype=np.float64):
    """The readpath test fleet recipe: ``n_models`` small fitted DFMs
    with deterministic parameters (same seed -> bit-identical states,
    which the frontend parity test relies on)."""
    from ..ops import dfm_statespace, kalman_filter
    from ..serve import PosteriorState

    rng = np.random.default_rng(seed)
    states = []
    for i in range(n_models):
        loadings = (
            rng.uniform(0.3, 0.8, (n, kf)) / np.sqrt(kf)
        ).astype(dtype)
        a_s = rng.uniform(5.0, 40.0, n).astype(dtype)
        a_c = rng.uniform(10.0, 60.0, kf).astype(dtype)
        ss = dfm_statespace(a_s, a_c, loadings, 1.0)
        y = rng.normal(size=(t, n))
        mask = rng.uniform(size=(t, n)) > 0.3
        y = np.where(mask, y, 0.0)
        res = kalman_filter(ss, y.astype(dtype), mask, engine="joint")
        states.append(PosteriorState(
            model_id=f"m{i}", version=0, t_seen=t,
            mean=np.asarray(res.mean_f[-1], dtype),
            cov=np.asarray(res.cov_f[-1], dtype),
            params=np.concatenate([a_s, a_c]),
            loadings=loadings, dt=1.0,
            scaler_mean=rng.normal(size=n).astype(dtype),
            scaler_std=rng.uniform(0.5, 2.0, n).astype(dtype),
            names=tuple(f"s{j}" for j in range(n)),
        ))
    return states


def seed_root(root, seed=7, n_models=4, n=5, kf=1, t=60):
    """Persist the fixture fleet under ``root`` so a spawned writer
    (whose factory only receives the path) can load it from disk.
    Returns the model ids."""
    from ..serve import ModelRegistry

    reg = ModelRegistry(root=root)
    states = make_states(seed=seed, n_models=n_models, n=n, kf=kf, t=t)
    for st in states:
        reg.put(st, persist=True)
    return [st.model_id for st in states]


def writer_service_factory(spec, recovering, root, horizons="1-5",
                           durable=True, repl=False):
    """The ``ClusterFrontend`` service factory used by tests and bench.

    Builds the writer's ``MetranService`` over the fleet persisted by
    :func:`seed_root`; ``recovering=True`` (a frontend
    ``restart_writer`` after a writer crash) routes through
    ``MetranService.recover`` so the WAL tail replays before serving
    resumes.  ``repl=True`` arms the replication hub (requires
    ``durable``) so the frontend can ``attach_standby``.
    """
    import jax

    # the parity tests compare f64 bits against an in-process service
    # whose conftest enabled x64; this factory runs in a spawned child
    # where no conftest ever runs
    jax.config.update("jax_enable_x64", True)
    from ..cluster.replication import ReplicationSpec
    from ..serve import DurabilitySpec, MetranService, ModelRegistry

    replication = (
        ReplicationSpec(enabled=True) if repl
        else ReplicationSpec(enabled=False)
    )
    if recovering:
        return MetranService.recover(
            root, flush_deadline=None, persist_updates=False,
            readpath=True, horizons=horizons, cluster=spec,
            replication=replication,
        )
    durability = (
        DurabilitySpec(enabled=True, checkpoint_every=0)
        if durable else None
    )
    reg = ModelRegistry(root=root)
    return MetranService(
        reg, flush_deadline=None, persist_updates=False,
        readpath=True, horizons=horizons, durability=durability,
        cluster=spec, replication=replication,
    )


def standby_service_factory(root, horizons="1-5"):
    """A :func:`~metran_tpu.cluster.replication.standby_main` service
    factory: the fleet persisted under ``root`` (its OWN root — the
    same deterministic :func:`seed_root` seed as the primary's, or a
    copied checkpoint), read path armed, durability NOT armed
    (shipped frames land on the standby's log verbatim;
    ``promote()`` re-arms durability over it)."""
    import jax

    jax.config.update("jax_enable_x64", True)
    from ..serve import DurabilitySpec, MetranService, ModelRegistry

    reg = ModelRegistry(root=root)
    return MetranService(
        reg, flush_deadline=None, persist_updates=False,
        readpath=True, horizons=horizons,
        durability=DurabilitySpec(enabled=False),
    )


def storm_publisher(plane_name, model_id, n_series, n_horizons,
                    n_versions):
    """Torn-write storm process: publish versions ``1..n_versions`` of
    one model where every published buffer satisfies the invariant
    ``means == version`` and ``variances == 2 * version`` elementwise.
    A seqlock-violating reader would observe a mixed buffer; the storm
    test asserts no read ever does."""
    from ..serve.readpath import SnapshotEntry
    from .snapplane import SnapshotPlane

    plane = SnapshotPlane.attach(plane_name)
    try:
        names = tuple(f"s{j}" for j in range(n_series))
        for version in range(1, n_versions + 1):
            v = float(version)
            plane.publish_entries([SnapshotEntry(
                model_id=model_id, version=version, names=names,
                means=np.full((n_horizons, n_series), v),
                variances=np.full((n_horizons, n_series), 2.0 * v),
                published_at=v,
            )])
    finally:
        plane.close(unlink=False)
    return 0
