"""Cluster frontend: process supervision + request routing.

The thin half of the single-writer split.  A :class:`ClusterFrontend`
spawns ONE writer process (:mod:`metran_tpu.cluster.writer` — update
dispatch, ``StateArena``, WAL, snapshot-plane publication) and
``spec.workers`` read processes (:mod:`metran_tpu.cluster.worker`),
then routes: **updates to the writer** (whose in-process
``MetranService`` preserves the per-model ordering chain, breaker,
deadline and gate semantics — exceptions cross the socket as objects
and re-raise here, so callers cannot tell the split happened) and
**forecasts to the workers** round-robin (shared-memory plane hits;
worker-side fallthrough to the writer on miss/stale).

Failure policy (docs/concepts.md "Multi-process serving"):

- a worker transport failure (killed process, half-open socket) moves
  the read to the next worker and finally to the writer directly — a
  killed worker loses **zero acked reads**; the monitor thread then
  reaps and respawns it (``worker_exit`` → ``worker_restart`` events,
  ``worker_start`` on every spawn);
- application exceptions (breaker open, deadline, validation) are
  NEVER retried here — they re-raise exactly as the single-process
  service would, because retrying them would change semantics;
- a dead writer is surfaced (``writer_alive()``), and
  :meth:`restart_writer` respawns it with ``recovering=True`` so the
  factory routes through the service's existing WAL replay
  (:meth:`~metran_tpu.serve.MetranService.recover`) — no
  acked-commit loss.

Everything multiprocess uses the **spawn** start method: the children
build their own jax runtime; device buffers, WAL handles and socket
servers must never cross a fork.
"""

from __future__ import annotations

import multiprocessing
import os
import shutil
import threading
import time
from logging import getLogger
from typing import Callable, List, Optional, Tuple

from ..obs.fleet import (
    ChildTelemetry,
    ClockAlign,
    FleetScrapeServer,
    merge_chrome,
    merge_events,
    render_fleet_prometheus,
)
from .ipc import RpcClient, rpc_call
from .snapplane import SnapshotPlane
from .spec import ClusterSpec
from .worker import worker_main
from .writer import writer_main

logger = getLogger(__name__)

__all__ = ["ClusterFrontend"]

#: seconds a spawned process gets to signal readiness before the
#: frontend declares the spawn failed (first jax import + compile
#: cache warm can be slow on loaded CI hosts)
SPAWN_TIMEOUT_S = 180.0


def _wait_ready(path: str, proc, timeout_s: float = SPAWN_TIMEOUT_S):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return
        if not proc.is_alive():
            raise RuntimeError(
                f"cluster process {proc.name} died during startup "
                f"(exitcode {proc.exitcode})"
            )
        time.sleep(0.02)
    raise TimeoutError(
        f"cluster process {proc.name} not ready after {timeout_s}s"
    )


class _Worker:
    """One live read worker: process handle + RPC client + paths."""

    def __init__(self, index: int, proc, client: RpcClient,
                 socket_path: str, ready_path: str):
        self.index = index
        self.proc = proc
        self.client = client
        self.socket_path = socket_path
        self.ready_path = ready_path


class ClusterFrontend:
    """Spawn, supervise and route for one serving cluster.

    ``service_factory(spec, recovering, *factory_args)`` must be a
    picklable module-level callable returning the writer's
    ``MetranService`` (constructed with ``cluster=spec`` so the
    service creates and publishes into the snapshot plane); it runs
    INSIDE the writer process.
    """

    def __init__(
        self,
        spec: ClusterSpec,
        service_factory: Callable,
        factory_args: Tuple = (),
        observability=None,
    ):
        from ..obs import Observability

        self.spec = spec.validate()
        if not self.spec.enabled:
            raise ValueError(
                "ClusterFrontend needs an enabled ClusterSpec — a "
                "disabled spec means single-process serving, which "
                "needs no frontend"
            )
        self._factory = service_factory
        self._factory_args = tuple(factory_args)
        self._owns_socket_dir = not spec.socket_dir
        self.socket_dir = self.spec.resolve_socket_dir()
        self._owns_obs = observability is None
        self.obs = (
            observability if observability is not None
            else Observability.default()
        )
        self.events = self.obs.events
        self.tracer = self.obs.tracer
        # fleet observability (docs/concepts.md "Fleet observability"):
        # the frontend is both the collector and a telemetry part of
        # its own; offsets refine per collection (ClockAlign)
        self._telemetry = ChildTelemetry(self.obs, "frontend")
        self._fleet_clock = ClockAlign()
        self._fleet_gaps = None  # counter, set by _register_metrics
        self._scrape: Optional[FleetScrapeServer] = None
        self._ctx = multiprocessing.get_context("spawn")
        self._lock = threading.Lock()
        self._closed = False
        self._restarting = False  # pauses the monitor during bounces
        self._rr = 0  # round-robin cursor
        self.restarts = 0

        self.writer_socket = os.path.join(self.socket_dir, "writer.sock")
        self._writer_proc = None
        self.writer = None  # RpcClient
        self.plane: Optional[SnapshotPlane] = None
        self._workers: List[_Worker] = []
        #: replication standbys attached through this frontend — the
        #: promotion candidates (socket paths, attach order preserved)
        self.standby_sockets: List[str] = []
        self.promoted_socket: Optional[str] = None
        try:
            self._spawn_writer(recovering=False)
            for i in range(self.spec.workers):
                self._spawn_worker(i, restart=False)
        except BaseException:
            self.close()
            raise
        self._register_metrics()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="metran-cluster-monitor",
            daemon=True,
        )
        self._monitor.start()
        port = self.spec.resolve_fleet_port()
        if port:
            self._scrape = FleetScrapeServer(self.fleet_report, port)

    # -- spawning --------------------------------------------------------
    def _spawn_writer(self, recovering: bool) -> None:
        ready = os.path.join(
            self.socket_dir, f"writer.ready.{self.restarts}"
        )
        proc = self._ctx.Process(
            target=writer_main,
            args=(self.spec, self.writer_socket, self._factory,
                  self._factory_args, recovering, ready),
            name="metran-writer",
            daemon=True,
        )
        proc.start()
        _wait_ready(ready, proc)
        self._writer_proc = proc
        self.writer = RpcClient(self.writer_socket)
        hello = self.writer.call("hello")
        plane_name = hello["plane"]
        if self.plane is None or self.plane.name != plane_name:
            if self.plane is not None:
                self.plane.close(unlink=False)
            self.plane = SnapshotPlane.attach(plane_name)

    def _spawn_worker(self, index: int, restart: bool) -> None:
        tag = f"{index}.{self.restarts}"
        socket_path = os.path.join(self.socket_dir, f"worker{tag}.sock")
        ready = os.path.join(self.socket_dir, f"worker{tag}.ready")
        proc = self._ctx.Process(
            target=worker_main,
            args=(self.plane.name, socket_path, self.writer_socket,
                  self.spec.heartbeat_s, ready),
            name=f"metran-worker-{index}",
            daemon=True,
        )
        proc.start()
        _wait_ready(ready, proc)
        worker = _Worker(index, proc, RpcClient(socket_path),
                         socket_path, ready)
        with self._lock:
            if restart and index < len(self._workers):
                self._workers[index] = worker
            else:
                self._workers.append(worker)
        if self.events is not None:
            self.events.emit(
                "worker_start", fault_point="cluster.frontend",
                worker=index, pid=proc.pid, restart=restart,
            )

    # -- supervision -----------------------------------------------------
    def _monitor_loop(self) -> None:
        while not self._closed:
            time.sleep(self.spec.heartbeat_s)
            if self._closed:
                return
            if self._restarting:
                continue
            for worker in list(self._workers):
                if self._closed:
                    return
                if worker.proc.is_alive():
                    continue
                if self.events is not None:
                    self.events.emit(
                        "worker_exit", fault_point="cluster.frontend",
                        worker=worker.index, pid=worker.proc.pid,
                        exitcode=worker.proc.exitcode,
                    )
                try:
                    self._restart_worker(worker)
                except Exception:  # pragma: no cover - spawn failure
                    logger.exception(
                        "worker %d restart failed", worker.index
                    )

    def _restart_worker(self, worker: _Worker) -> None:
        worker.client.close()
        self.restarts += 1
        self._spawn_worker(worker.index, restart=True)
        if self.events is not None:
            self.events.emit(
                "worker_restart", fault_point="cluster.frontend",
                worker=worker.index,
            )

    def writer_alive(self) -> bool:
        proc = self._writer_proc
        return proc is not None and proc.is_alive()

    def restart_writer(self) -> None:
        """Respawn a dead writer with ``recovering=True`` — the factory
        routes through the service's WAL replay, so every acked commit
        survives (the existing durability contract, now cross-process).
        """
        if self.writer_alive():
            raise RuntimeError(
                "writer is alive; restart_writer is for crash recovery"
            )
        self._restarting = True
        try:
            if self.writer is not None:
                self.writer.close()
            old_plane = (
                self.plane.name if self.plane is not None else None
            )
            self.restarts += 1
            self._spawn_writer(recovering=True)
            if old_plane is not None and (
                self.plane is None or self.plane.name != old_plane
            ):
                # the crashed writer never unlinked its segment; reap
                # it, then bounce every worker onto the new plane —
                # they still hold read views of the dead one
                try:
                    leaked = SnapshotPlane.attach(old_plane)
                except (FileNotFoundError, ValueError):
                    pass
                else:
                    leaked.close(unlink=True)
                for worker in list(self._workers):
                    try:
                        worker.client.call("shutdown")
                    except Exception:
                        pass
                    worker.proc.join(timeout=10.0)
                    if worker.proc.is_alive():
                        worker.proc.terminate()
                        worker.proc.join(timeout=5.0)
                    self._restart_worker(worker)
        finally:
            self._restarting = False

    # -- replication (docs/concepts.md "Replication & failover") ---------
    def attach_standby(self, socket_path: str,
                       name: Optional[str] = None) -> dict:
        """Register a running standby (``cluster.replication.
        standby_main``) with the writer's replication hub.  The writer
        catches it up from its own WAL under the ship lock, then every
        subsequent commit is shipped before its ack — the standby
        becomes a promotion candidate and a read replica.  Returns the
        writer's attach summary."""
        out = self.writer.call(
            "repl_attach",
            {"socket_path": socket_path, "name": name},
        )
        if socket_path not in self.standby_sockets:
            self.standby_sockets.append(socket_path)
        return out

    def promote_standby(self, socket_path: Optional[str] = None,
                        checkpoint: bool = True) -> dict:
        """Fail over onto a standby after writer death: fence (epoch
        bump), drain its apply queue, re-arm durability over its log,
        and re-point this frontend's write path at it.  RTO is this
        call's wall-clock plus the first served read.

        If the standby's hello reports its own snapshot plane, the
        frontend swaps onto it and bounces the read workers (the
        ``restart_writer`` plane-swap path); a plane-less standby still
        serves — worker reads fall through to the promoted writer via
        the ordinary transport-failure routing."""
        if self.writer_alive():
            raise RuntimeError(
                "writer is alive; promote_standby is for failover — "
                "use restart_writer for same-host crash recovery"
            )
        socket_path = socket_path or (
            self.standby_sockets[0] if self.standby_sockets else None
        )
        if socket_path is None:
            raise RuntimeError(
                "no standby attached — nothing to promote"
            )
        t0 = time.monotonic()
        self._restarting = True
        try:
            if self.writer is not None:
                self.writer.close()
            report = rpc_call(
                socket_path, "repl_promote",
                {"checkpoint": checkpoint},
            )
            self.writer = RpcClient(socket_path)
            self.writer_socket = socket_path
            self.promoted_socket = socket_path
            if socket_path in self.standby_sockets:
                self.standby_sockets.remove(socket_path)
            hello = self.writer.call("hello")
            new_plane = hello.get("plane")
            old_plane = (
                self.plane.name if self.plane is not None else None
            )
            if new_plane is not None and new_plane != old_plane:
                if self.plane is not None:
                    self.plane.close(unlink=False)
                self.plane = SnapshotPlane.attach(new_plane)
                if old_plane is not None:
                    # the dead writer never unlinked its segment
                    try:
                        leaked = SnapshotPlane.attach(old_plane)
                    except (FileNotFoundError, ValueError):
                        pass
                    else:
                        leaked.close(unlink=True)
                self.restarts += 1
                for worker in list(self._workers):
                    try:
                        worker.client.call("shutdown")
                    except Exception:
                        pass
                    worker.proc.join(timeout=10.0)
                    if worker.proc.is_alive():
                        worker.proc.terminate()
                        worker.proc.join(timeout=5.0)
                    self._restart_worker(worker)
        finally:
            self._restarting = False
        report = dict(report)
        report["failover_wall_s"] = round(time.monotonic() - t0, 6)
        if self.events is not None:
            self.events.emit(
                "replica_promote", fault_point="cluster.frontend",
                socket=socket_path,
                epoch=report.get("epoch"),
                failover_wall_s=report["failover_wall_s"],
            )
        return report

    # -- routing (the preserved MetranService surface) -------------------
    def update(self, model_id: str, new_obs):
        """Route to the writer's serialized update dispatch; the
        returned posterior crossed the socket as host numpy.

        With a tracer armed the call runs inside a ``cluster.update``
        span, whose context rides the RPC envelope — the writer's
        ``rpc.update`` lane (and the dispatch stages, WAL commit,
        replication ship and standby apply under it) all join this
        span's correlation id."""
        payload = {"model_id": model_id, "new_obs": new_obs}
        if self.tracer is None:
            return self.writer.call("update", payload)
        with self.tracer.span("cluster.update", model_id=model_id):
            return self.writer.call("update", payload)

    def forecast(self, model_id: str, steps: int):
        """Route to a read worker (round-robin); a TRANSPORT failure
        moves to the next worker and finally the writer — zero failed
        reads under worker death.  Application exceptions re-raise
        unchanged (retrying a breaker/deadline would change
        semantics).  Traced like :meth:`update` (``cluster.forecast``
        → the serving worker's ``rpc.forecast`` lane)."""
        if self.tracer is None:
            return self._forecast(model_id, steps)
        with self.tracer.span("cluster.forecast", model_id=model_id):
            return self._forecast(model_id, steps)

    def _forecast(self, model_id: str, steps: int):
        payload = {"model_id": model_id, "steps": int(steps)}
        with self._lock:
            workers = list(self._workers)
            self._rr += 1
            start = self._rr
        for i in range(len(workers)):
            worker = workers[(start + i) % len(workers)]
            try:
                return worker.client.call("forecast", payload)
            except (ConnectionError, OSError, EOFError):
                continue
        return self.writer.call("forecast", payload)

    def put(self, state, persist: bool = False):
        return self.writer.call(
            "put", {"state": state, "persist": persist}
        )

    def meta(self, model_id: str):
        return self.writer.call("meta", {"model_id": model_id})

    def flush(self):
        return self.writer.call("flush")

    def capacity_report(self) -> dict:
        """The writer service's report, with a ``cluster`` section
        covering the WHOLE fleet this frontend supervises: the plane's
        frontend-side aggregate, every read worker's own reader ledger
        (its ``stats`` RPC — per-process hit/stale/fallback view of
        the shared plane), the writer's replication-hub status, and
        every attached standby's apply progress.  An unreachable child
        reports as such instead of silently vanishing from the fleet
        it is still part of."""
        report = self.writer.call("capacity_report")
        cluster = self.stats()
        workers = []
        for w in list(self._workers):
            try:
                workers.append(dict(w.client.call("stats"),
                                    worker=w.index))
            except Exception as exc:
                workers.append({"worker": w.index,
                                "error": repr(exc)})
        cluster["worker_reports"] = workers
        try:
            cluster["replication"] = self.writer.call("repl_status")
        except Exception as exc:
            cluster["replication"] = {"enabled": False,
                                      "error": repr(exc)}
        standbys = []
        for sock in list(self.standby_sockets):
            try:
                standbys.append(dict(rpc_call(sock, "repl_status"),
                                     socket=sock))
            except Exception as exc:
                standbys.append({"socket": sock, "error": repr(exc)})
        cluster["standbys"] = standbys
        report["cluster"] = cluster
        return report

    def stats(self) -> dict:
        stats = self.plane.stats(heartbeat_s=self.spec.heartbeat_s)
        stats["workers"] = len(self._workers)
        stats["restarts"] = self.restarts
        stats["writer_alive"] = self.writer_alive()
        return stats

    def read_loop(self, model_ids, steps: int, iters: int) -> List[dict]:
        """Fan the bench read loop over every worker concurrently; one
        result dict per worker (the paired-throughput measurement
        surface for ``bench.py --phase serve-cluster``)."""
        payload = {"model_ids": list(model_ids), "steps": int(steps),
                   "iters": int(iters)}
        results: List[Optional[dict]] = [None] * len(self._workers)

        def _one(i: int, worker: _Worker) -> None:
            results[i] = worker.client.call("read_loop", payload)

        threads = [
            threading.Thread(target=_one, args=(i, w), daemon=True)
            for i, w in enumerate(list(self._workers))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return [r for r in results if r is not None]

    # -- fleet observability (docs/concepts.md "Fleet observability") ----
    def fleet_collect(self, metrics: bool = True, events: bool = True,
                      spans: bool = True) -> List[dict]:
        """One telemetry part per live fleet process, frontend first.

        Fans the ``telemetry`` RPC over the writer, every read worker
        and every attached standby, labels each part (``frontend`` /
        ``writer`` / ``worker<i>`` / ``standby<i>``) and folds a
        fresh clock-offset estimate per child into the frontend's
        :class:`~metran_tpu.obs.fleet.ClockAlign` (the RPC round-trip
        brackets the child's anchor — Cristian's method, min-RTT
        retained).  A child that fails to answer is booked
        (``fleet_telemetry_gap`` event + gap counter) and skipped —
        one dead process must not blind the pane to the rest.
        """
        payload = {"metrics": bool(metrics), "events": bool(events),
                   "spans": bool(spans)}
        own = self._telemetry.collect(payload)
        own["process"] = "frontend"
        own["clock"] = {"offset": 0.0, "rtt_s": 0.0}
        parts: List[dict] = [own]
        targets: List[Tuple[str, Callable]] = [
            ("writer", lambda p: self.writer.call("telemetry", p)),
        ]
        with self._lock:
            workers = list(self._workers)
        for w in workers:
            targets.append((
                f"worker{w.index}",
                lambda p, c=w.client: c.call("telemetry", p),
            ))
        for i, sock in enumerate(list(self.standby_sockets)):
            targets.append((
                f"standby{i}",
                lambda p, s=sock: rpc_call(s, "telemetry", p),
            ))
        for label, caller in targets:
            t_send = time.monotonic()
            try:
                part = caller(payload)
            except Exception as exc:
                if self._fleet_gaps is not None:
                    self._fleet_gaps.inc()
                if self.events is not None:
                    self.events.emit(
                        "fleet_telemetry_gap",
                        fault_point="cluster.frontend",
                        process=label, error=repr(exc),
                    )
                continue
            t_recv = time.monotonic()
            part["process"] = label
            anchor = part.get("anchor") or {}
            off, rtt = self._fleet_clock.observe(
                label, anchor.get("mono", t_recv), t_send, t_recv
            )
            part["clock"] = {"offset": off, "rtt_s": rtt}
            parts.append(part)
        return parts

    def fleet_report(self) -> str:
        """The merged fleet Prometheus exposition: every process's
        registry under a ``process`` label (one scrape answers for the
        whole topology — the optional HTTP endpoint serves exactly
        this)."""
        return render_fleet_prometheus(
            self.fleet_collect(events=False, spans=False)
        )

    def fleet_events(self) -> List[dict]:
        """Every process's event records on one clock-aligned
        timeline, oldest first (``fleet_ts`` + ``process`` added; see
        :func:`~metran_tpu.obs.fleet.merge_events`) — the input
        ``tools/failover_timeline.py`` reconstructs a failover from."""
        return merge_events(self.fleet_collect(metrics=False,
                                               spans=False))

    def fleet_trace_export(self) -> dict:
        """One Chrome trace over the whole fleet, one process lane per
        pid, clock-aligned — a propagated correlation id renders as a
        frontend span containing the writer's and standby's lanes."""
        return merge_chrome(self.fleet_collect(metrics=False,
                                               events=False))

    # -- observability ---------------------------------------------------
    def _plane_stat(self, fn: Callable, default: float = 0.0) -> float:
        """Scrape-time plane accessor for gauge callbacks: resolve
        ``self.plane`` on every call — ``restart_writer`` swaps the
        plane when the recovered writer allocates a fresh segment, and
        a closure over the dead one would fail every scrape after the
        bounce (released memoryview)."""
        plane = self.plane
        if plane is None:
            return default
        try:
            return float(fn(plane))
        except (ValueError, OSError):  # mid-bounce: segment released
            return default

    def _register_metrics(self) -> None:
        if self.obs.metrics is None:
            return
        m = self.obs.metrics
        grace = 3.0 * self.spec.heartbeat_s
        m.gauge(
            "metran_serve_cluster_workers_live",
            "read workers with a fresh heartbeat in the shared plane's "
            "worker table (the fleet's live read capacity)",
            callback=lambda: self._plane_stat(
                lambda p: p.workers_live(grace)
            ),
        )
        m.gauge(
            "metran_serve_cluster_reader_hits_total",
            "forecast reads served straight from the shared-memory "
            "snapshot plane across all read workers (monotone; "
            "aggregated by one shared-memory scan at scrape time)",
            callback=lambda: self._plane_stat(
                lambda p: p.reader_counts()["hits"]
            ),
        )
        m.gauge(
            "metran_serve_cluster_reader_stale_total",
            "plane reads that exhausted their seqlock retries under "
            "write contention and degraded to fallthrough (monotone)",
            callback=lambda: self._plane_stat(
                lambda p: p.reader_counts()["stale"]
            ),
        )
        m.gauge(
            "metran_serve_cluster_fallbacks_total",
            "worker reads that fell through to the writer's compute "
            "path on miss/stale (monotone; the cluster's degraded-"
            "read counter)",
            callback=lambda: self._plane_stat(
                lambda p: p.reader_counts()["fallbacks"]
            ),
        )
        m.gauge(
            "metran_serve_fleet_processes",
            "fleet processes the frontend would fan telemetry over "
            "(itself + live writer + read workers + attached standbys)",
            callback=lambda: float(
                1
                + (1 if self.writer_alive() else 0)
                + len(self._workers)
                + len(self.standby_sockets)
            ),
        )
        self._fleet_gaps = m.counter(
            "metran_serve_fleet_telemetry_gaps_total",
            "fleet telemetry fan-outs where a child failed to answer "
            "its telemetry RPC and was skipped from the merged pane "
            "(each gap also books a fleet_telemetry_gap event)",
        )

    # -- lifecycle -------------------------------------------------------
    def close(self) -> None:
        """Shut down workers, then the writer (whose service close
        unlinks the plane), then local views and the rendezvous dir."""
        self._closed = True
        if self._scrape is not None:
            try:
                self._scrape.close()
            except Exception:
                pass
            self._scrape = None
        for worker in list(self._workers):
            try:
                worker.client.call("shutdown")
            except Exception:
                pass
            worker.client.close()
        for worker in list(self._workers):
            worker.proc.join(timeout=10.0)
            if worker.proc.is_alive():
                worker.proc.terminate()
                worker.proc.join(timeout=5.0)
        self._workers = []
        plane_name = self.plane.name if self.plane is not None else None
        if self.writer is not None:
            try:
                self.writer.call("shutdown")
            except Exception:
                pass
            self.writer.close()
            self.writer = None
        if self._writer_proc is not None:
            self._writer_proc.join(timeout=15.0)
            if self._writer_proc.is_alive():
                self._writer_proc.terminate()
                self._writer_proc.join(timeout=5.0)
            self._writer_proc = None
        if self.plane is not None:
            self.plane.close(unlink=False)
            self.plane = None
        if plane_name is not None:
            # a SIGKILLed writer never unlinked its segment; reap it
            # so a crashed cluster cannot leak /dev/shm across runs
            try:
                leaked = SnapshotPlane.attach(plane_name)
            except (FileNotFoundError, ValueError):
                pass
            else:
                leaked.close(unlink=True)
        if self._owns_obs and self.obs.events is not None:
            try:
                self.obs.events.close()
            except Exception:
                pass
        if self._owns_socket_dir:
            shutil.rmtree(self.socket_dir, ignore_errors=True)

    def __enter__(self) -> "ClusterFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
