"""Local-socket RPC for the cluster plane (unix domain sockets).

Deliberately minimal: the hot read path never touches a socket (it is
a shared-memory ``SnapshotPlane`` probe) — RPC carries only the cold
paths: update routing to the single writer, reader fallthrough on
miss/stale, admin (register/meta/stats), and the bench driver's
``read_loop``.  Framing is a 4-byte little-endian length prefix over a
pickled ``(op, payload)`` request — or ``(op, payload, ctx)`` when the
caller thread has an active trace, where ``ctx = (trace_id, span_id,
origin_pid)`` is the serialized :class:`~metran_tpu.obs.tracing.
SpanContext`; servers re-attach it so the handler's spans and events
join the originating correlation id (the fleet observability plane,
docs/concepts.md "Fleet observability").  Untraced calls still send
the 2-tuple, and servers accept both, so the envelope change costs
nothing when tracing is off and old/new processes interoperate during
a rolling restart.  The response stays a pickled ``(ok, value)``;
errors cross the boundary as the raised exception object, so a
frontend re-raises the writer's actual ``BreakerOpen`` /
``DeadlineExceeded`` / ``ValueError`` and the single-process semantics
survive the process split (tests/test_cluster.py parity suite).

Pickle is acceptable HERE and only here: both endpoints are processes
of the same trusted service on the same host, rendezvousing on a
0700-mode private socket directory — this is an IPC seam, not a
network protocol.
"""

from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading
from logging import getLogger
from typing import Any, Callable, Optional, Tuple

from ..obs.tracing import SpanContext, attach_context, current_context

logger = getLogger(__name__)

__all__ = ["RpcServer", "RpcClient", "rpc_call"]

_LEN = struct.Struct("<I")
#: sanity ceiling on one frame (a corrupt length prefix must not
#: trigger a multi-GB allocation)
MAX_FRAME = 256 * 1024 * 1024


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def _send_frame(sock: socket.socket, obj: Any) -> None:
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if len(blob) > MAX_FRAME:
        raise ValueError(f"frame of {len(blob)} bytes exceeds MAX_FRAME")
    sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds MAX_FRAME")
    return pickle.loads(_recv_exact(sock, n))


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        # one connection, many requests: clients hold the socket open
        while True:
            try:
                req = _recv_frame(self.request)
            except (ConnectionError, EOFError, OSError):
                return
            # 2-tuple (untraced / pre-PR-19 peer) or 3-tuple with ctx
            op, payload = req[0], req[1]
            ctx = req[2] if len(req) > 2 else None
            try:
                value = self.server.dispatch(op, payload, ctx)  # type: ignore
                reply = (True, value)
            except BaseException as exc:  # noqa: BLE001 - crossed to caller
                if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                    raise
                reply = (False, exc)
            try:
                _send_frame(self.request, reply)
            except (ConnectionError, OSError):
                return


class _ThreadedUnixServer(
    socketserver.ThreadingMixIn, socketserver.UnixStreamServer
):
    daemon_threads = True
    allow_reuse_address = True


class RpcServer:
    """Serve ``(op, payload)`` requests on a unix socket.

    ``dispatch(op, payload, ctx)`` routes into the handler table;
    unknown ops raise (and the error crosses back to the caller).
    When the request carried a trace ``ctx`` and the server was built
    with a ``tracer``, the handler runs inside an ``rpc.<op>`` span
    parented on the propagated context — the child-process lane of the
    fleet trace; with no tracer the context is still attached (events
    emitted by the handler join the correlation id).  Runs its accept
    loop on a daemon thread — ``close()`` shuts it down and unlinks
    the socket path.
    """

    def __init__(self, path: str,
                 handlers: dict[str, Callable[[Any], Any]],
                 tracer=None):
        self.path = path
        self.tracer = tracer
        self._handlers = dict(handlers)
        if os.path.exists(path):
            os.unlink(path)
        self._server = _ThreadedUnixServer(path, _Handler)
        self._server.dispatch = self.dispatch  # type: ignore[attr-defined]
        os.chmod(path, 0o600)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name=f"metran-rpc[{os.path.basename(path)}]",
            daemon=True,
        )
        self._thread.start()

    def dispatch(self, op: str, payload: Any,
                 ctx: Optional[Tuple[int, int, int]] = None) -> Any:
        handler = self._handlers.get(op)
        if handler is None:
            raise ValueError(f"unknown rpc op {op!r}")
        if ctx is None:
            return handler(payload)
        parent = SpanContext(int(ctx[0]), int(ctx[1]))
        tracer = self.tracer
        if tracer is not None:
            with tracer.span(f"rpc.{op}", parent=parent,
                             origin_pid=int(ctx[2])):
                return handler(payload)
        with attach_context(parent):
            return handler(payload)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


class RpcClient:
    """One persistent connection to an :class:`RpcServer`.

    Thread-safe (one in-flight request at a time under a lock — the
    cold paths this carries are not throughput-critical).  A broken
    connection reconnects once per call; a second failure raises to
    the caller, whose fallback policy (frontend: next worker, then the
    writer) decides what happens next.
    """

    def __init__(self, path: str, timeout_s: float = 30.0):
        self.path = path
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout_s)
        sock.connect(self.path)
        return sock

    def call(self, op: str, payload: Any = None,
             ctx: Any = "current") -> Any:
        """Round-trip one request.

        ``ctx`` is the trace context to propagate: the default
        ``"current"`` reads the caller thread's active
        :class:`SpanContext` (one contextvar read — nothing when
        tracing is off, which is why untraced RPCs still send the
        2-tuple envelope); an explicit ``(trace_id, span_id,
        origin_pid)`` tuple propagates a context the caller carried
        across a thread boundary itself (the replication hub's ship
        pool); ``None`` forces an untraced call.
        """
        if ctx == "current":
            sc = current_context()
            ctx = (
                None if sc is None
                else (sc.trace_id, sc.span_id, os.getpid())
            )
        req = (op, payload) if ctx is None else (op, payload, ctx)
        with self._lock:
            for attempt in (0, 1):
                if self._sock is None:
                    self._sock = self._connect()
                try:
                    _send_frame(self._sock, req)
                    ok, value = _recv_frame(self._sock)
                    break
                except (ConnectionError, OSError, EOFError):
                    self._close_locked()
                    if attempt:
                        raise
        if not ok:
            raise value
        return value

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()


def rpc_call(path: str, op: str, payload: Any = None,
             timeout_s: float = 30.0, ctx: Any = "current") -> Any:
    """One-shot convenience call (connect, request, close)."""
    client = RpcClient(path, timeout_s=timeout_s)
    try:
        return client.call(op, payload, ctx=ctx)
    finally:
        client.close()
