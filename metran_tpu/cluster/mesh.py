"""Multi-host arena mesh: ``jax.distributed`` batch sharding.

The single-process :class:`~metran_tpu.serve.state.StateArena` shards
its bucket leaves along the batch axis over a *local* device mesh
(``metran_tpu.parallel.mesh``, the virtual 8-CPU-device test topology
or one host's chips).  This module extends that same batch-axis
``NamedSharding`` across **processes**: a ``jax.distributed``-
initialized mesh spans every participating host's devices, each leaf
is assembled with ``jax.make_array_from_callback`` so every process
materializes only its addressable rows, and the batched serve kernels
(:func:`~metran_tpu.serve.engine.update_bucket` /
:func:`~metran_tpu.serve.engine.forecast_bucket`) run unchanged —
the fleet axis is embarrassingly parallel, so GSPMD inserts no
runtime collectives and per-row results are **bit-identical** to the
unsharded single-process kernels at f64 (tests/test_cluster.py,
the same contract the virtual-mesh arena pins in tests/test_arena.py).

The module doubles as its own subprocess entry point: the 2-process
bit-identity test launches ``python -m metran_tpu.cluster.mesh`` once
per process (gloo CPU collectives), each builds the SAME seeded
fixture, runs the sharded kernels over the distributed mesh, and
writes its local batch rows for the parent to reassemble and compare
against the unsharded reference.
"""

from __future__ import annotations

import argparse
import os
from logging import getLogger
from typing import Optional

import numpy as np

logger = getLogger(__name__)

__all__ = [
    "init_distributed",
    "global_batch_mesh",
    "shard_batch_tree",
    "local_batch_rows",
]


def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int,
                     initialization_timeout_s: float = 60.0) -> None:
    """Join the ``jax.distributed`` mesh (idempotent per process).

    On CPU backends the cross-process collective transport defaults
    unset; we pin ``gloo`` (the one the wheel ships) BEFORE backend
    init so a CPU pod behaves like the TPU pod the paper targets.
    Must run before any other jax API touches the backend.
    """
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - knob renamed/absent
        logger.debug("gloo collectives knob unavailable", exc_info=True)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        initialization_timeout=int(initialization_timeout_s),
    )


def global_batch_mesh():
    """A 1D batch-axis mesh over EVERY process's devices (global view;
    call after :func:`init_distributed`)."""
    import jax

    from ..parallel.mesh import make_mesh

    return make_mesh(devices=jax.devices())


def shard_batch_tree(mesh, tree, batch: Optional[int] = None):
    """Shard every leaf of a host pytree along axis 0 over ``mesh``.

    Uses ``jax.make_array_from_callback`` so each process materializes
    only the rows its devices own — the multi-process-safe assembly
    (a plain ``device_put`` of a global array assumes single
    controller).  Leaves whose leading dimension is not the batch size
    (``batch``, default the first leaf's) are replicated instead —
    the same rule the arena applies to its scalar sidecars.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.mesh import BATCH_AXIS, batch_sharding

    leaves = jax.tree_util.tree_leaves(tree)
    if batch is None:
        batch = int(np.shape(leaves[0])[0]) if leaves else 0

    def _put(leaf):
        arr = np.asarray(leaf)
        if arr.ndim and arr.shape[0] == batch:
            sharding = batch_sharding(mesh, arr.ndim)
        else:
            sharding = NamedSharding(mesh, PartitionSpec())
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    return jax.tree_util.tree_map(_put, tree)


def local_batch_rows(arr) -> tuple:
    """This process's addressable rows of a batch-sharded global array
    as ``(row_indices, values)`` — what a process contributes when the
    parent reassembles the global result."""
    rows = []
    vals = []
    for shard in arr.addressable_shards:
        idx = shard.index[0]
        start = idx.start or 0
        data = np.asarray(shard.data)
        rows.extend(range(start, start + data.shape[0]))
        vals.append(data)
    order = np.argsort(np.asarray(rows))
    stacked = np.concatenate(vals, axis=0)
    return np.asarray(rows)[order], stacked[order]


# ----------------------------------------------------------------------
# subprocess selftest entry (2-process bit-identity harness)
# ----------------------------------------------------------------------
def _selftest_fixture(seed: int, n_models: int, n: int, kf: int, t: int):
    """Deterministic same-in-every-process bucket fixture (the
    test_readpath _make_states recipe, seeded)."""
    from ..ops import dfm_statespace, kalman_filter
    from ..serve.state import PosteriorState

    rng = np.random.default_rng(seed)
    states = []
    for i in range(n_models):
        loadings = (
            rng.uniform(0.3, 0.8, (n, kf)) / np.sqrt(kf)
        ).astype(np.float64)
        a_s = rng.uniform(5.0, 40.0, n)
        a_c = rng.uniform(10.0, 60.0, kf)
        ss = dfm_statespace(a_s, a_c, loadings, 1.0)
        y = rng.normal(size=(t, n))
        mask = rng.uniform(size=(t, n)) > 0.3
        y = np.where(mask, y, 0.0)
        res = kalman_filter(ss, y, mask, engine="joint")
        states.append(PosteriorState(
            model_id=f"m{i}", version=0, t_seen=t,
            mean=np.asarray(res.mean_f[-1], np.float64),
            cov=np.asarray(res.cov_f[-1], np.float64),
            params=np.concatenate([a_s, a_c]),
            loadings=loadings, dt=1.0,
            scaler_mean=rng.normal(size=n),
            scaler_std=rng.uniform(0.5, 2.0, n),
            names=tuple(f"s{j}" for j in range(n)),
        ))
    y_new = rng.normal(size=(n_models, 1, n))
    mask_new = rng.uniform(size=(n_models, 1, n)) > 0.2
    return states, y_new, mask_new


def selftest_compute(states, y_new, mask_new, steps: int, mesh=None):
    """The serve kernels the arena dispatches — batched update then
    forecast — over ``mesh`` when given (leaves batch-sharded), else
    unsharded.  Returns host f64 ``(mean, cov, fmeans, fvars)``."""
    from ..serve.engine import forecast_bucket, stack_bucket, \
        update_bucket

    n = states[0].n_series
    s_dim = states[0].mean.shape[0]
    batch = stack_bucket(states, (n, s_dim), dtype=np.float64)
    y = np.asarray(y_new, np.float64)
    m = np.asarray(mask_new, bool)
    ss, mean, cov = batch.ss, batch.mean, batch.cov
    if mesh is not None:
        ss = shard_batch_tree(mesh, ss, batch=len(states))
        mean, cov, y, m = (
            shard_batch_tree(mesh, leaf, batch=len(states))
            for leaf in (mean, cov, y, m)
        )
    # (mean, cov, sigma, detf) — the sidecars are single-process
    # service concerns, not part of the sharding contract under test
    new_mean, new_cov = update_bucket(ss, mean, cov, y, m)[:2]
    fmeans, fvars = forecast_bucket(ss, new_mean, new_cov, steps)
    return new_mean, new_cov, fmeans, fvars


def _selftest_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="cluster mesh bit-identity selftest (one process "
        "of a jax.distributed pod)"
    )
    parser.add_argument("--coordinator", required=True)
    parser.add_argument("--num-processes", type=int, required=True)
    parser.add_argument("--process-id", type=int, required=True)
    parser.add_argument("--out", required=True)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--n-models", type=int, default=4)
    parser.add_argument("--n", type=int, default=5)
    parser.add_argument("--kf", type=int, default=1)
    parser.add_argument("--t", type=int, default=40)
    parser.add_argument("--steps", type=int, default=5)
    args = parser.parse_args(argv)

    init_distributed(
        args.coordinator, args.num_processes, args.process_id
    )
    import jax

    jax.config.update("jax_enable_x64", True)
    mesh = global_batch_mesh()
    states, y_new, mask_new = _selftest_fixture(
        args.seed, args.n_models, args.n, args.kf, args.t
    )
    out = selftest_compute(states, y_new, mask_new, args.steps, mesh=mesh)
    payload = {}
    for name, arr in zip(("mean", "cov", "fmeans", "fvars"), out):
        rows, vals = local_batch_rows(arr)
        payload[f"{name}_rows"] = rows
        payload[f"{name}"] = vals
    # the .npz suffix keeps np.savez from appending its own
    tmp = f"{args.out}.{os.getpid()}.tmp.npz"
    np.savez(tmp, **payload)
    os.replace(tmp, args.out)
    return 0


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    raise SystemExit(_selftest_main())
