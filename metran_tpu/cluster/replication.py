"""WAL-shipped replication: continuously-replaying hot standbys,
replica read fan-out, and zero-acked-loss failover.

The durability plane already turns every acked commit into a
CRC-framed WAL group (serve.durability) and reconstructs acked state
bit-identically by replaying those frames through the live update
kernels.  This module streams the **same frames** over the cluster
plane's unix-socket RPC instead of reading them post-crash, which
makes a second host's service a *continuously-replaying replica*:

- :class:`ReplicationHub` (primary side) — attached as the
  :class:`~metran_tpu.serve.durability.DurabilityManager`'s
  ``shipper``.  Every committed group is pushed to all live standbys
  **synchronously between the local fdatasync and the callers' acks**
  (every WAL crash point fires at or before the local append, so any
  commit a caller ever saw acked was already received — and locally
  persisted — by every connected standby: zero acked commits can be
  lost at failover).  A standby that cannot ack inside the ship
  timeout is dropped (it re-attaches and catches up through
  :func:`~metran_tpu.serve.durability.iter_frames` over the primary's
  own log), never allowed to block acks; per-standby ack-to-applied
  lag is tracked from the ship replies.
- :class:`ReplicaStandby` (standby side) — wraps a fully-constructed
  ``MetranService`` seeded from the same baseline as the primary.
  Shipped frames are CRC-verified at the receiving edge, appended
  **verbatim** to the standby's own WAL
  (:meth:`~metran_tpu.serve.durability.WriteAheadLog.append_encoded`)
  before the ship RPC is acked, then applied on a dedicated thread
  through :func:`~metran_tpu.serve.durability.replay_wal` — the SAME
  replay engine recovery uses, so the standby is **bit-identical at
  f64** to the primary at every replicated version, and a torn or
  short tail is never applied.  The standby publishes its own
  ``SnapshotStore`` (and shared-memory plane when armed), so replica
  read capacity scales with replicas.
- **Promotion with epoch fencing** — every ship carries the stream
  epoch in its header.  :meth:`ReplicaStandby.promote` bumps the
  epoch (persisted next to the standby's log), drains the apply
  queue, and re-arms a full ``DurabilityManager`` over the standby's
  log — the promoted service is immediately a durable primary.  The
  old primary's next ship is answered with :class:`StaleEpochError`,
  which fences its hub: the commit that discovered the fence and
  every commit after it fail **before any ack resolves**
  (:class:`~metran_tpu.serve.durability.PrimaryFencedError` re-raised
  by ``_wal_commit`` like a process death), so a fenced old primary
  can never ack a commit after promotion.

RPO/RTO contract (measured by ``bench.py --phase replicate`` and the
failover chaos matrix in ``reliability.scenarios``): RPO is the
replication lag at kill — **0 acked commits** by construction, since
shipping is ack-synchronous; RTO is the promotion wall-clock to the
first served read.  See docs/concepts.md "Replication & failover".
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from logging import getLogger
from pathlib import Path
from time import perf_counter
from typing import Dict, List, NamedTuple, Optional, Tuple

from ..serve.durability import (
    _FRAME_HEAD,
    REC_MAGIC,
    DurabilityManager,
    DurabilitySpec,
    PrimaryFencedError,
    RecoveryError,
    WalRecord,
    WriteAheadLog,
    decode_group,
    encode_group,
    iter_frames,
    list_segments,
    load_latest_manifest,
    replay_wal,
)
from ..obs.fleet import ChildTelemetry
from ..obs.tracing import current_context
from .ipc import RpcClient, RpcServer

logger = getLogger(__name__)

__all__ = [
    "PrimaryFencedError",
    "ReplicaBaselineError",
    "ReplicaStandby",
    "ReplicationHub",
    "ReplicationSpec",
    "StaleEpochError",
    "decode_frame",
    "load_epoch",
    "standby_main",
]

#: epoch fence file kept next to the standby's WAL segments — a
#: restarted standby must come back at (at least) its promoted epoch,
#: or a zombie primary could re-ship into it
EPOCH_FILE = "repl-epoch"


def load_epoch(wal_dir) -> int:
    """The persisted fence epoch next to a WAL directory (>= 1; 1 when
    no fence was ever written).  Written by
    :meth:`ReplicaStandby.promote` (and epoch adoptions in the ship
    handshake); read back by a restarted standby AND by
    :class:`ReplicationHub` at construction — a promoted standby that
    later arms replication as the new primary must announce its real
    epoch, not restart the stream at 1 (which a surviving standby at
    the promoted epoch would answer with :class:`StaleEpochError`,
    permanently fencing the legitimate primary on a mere attach)."""
    try:
        return max(1, int((Path(wal_dir) / EPOCH_FILE).read_text()))
    except (OSError, ValueError):
        return 1


class StaleEpochError(RuntimeError):
    """A ship/hello carried an epoch older than the standby's — the
    sender is a fenced ex-primary.  Crosses the RPC boundary pickled
    (``args`` holds only the epoch so unpickling reconstructs it);
    the hub converts it into a sticky
    :class:`~metran_tpu.serve.durability.PrimaryFencedError`."""

    def __init__(self, epoch: int):
        super().__init__(int(epoch))
        self.epoch = int(epoch)

    def __str__(self) -> str:
        return (
            "stale replication epoch: a standby was promoted to "
            f"epoch {self.epoch}"
        )


class ReplicaBaselineError(RuntimeError):
    """A standby's baseline cannot be caught up from the primary's
    WAL: checkpoints truncate the log, so the commits between the
    standby's versions and the oldest surviving frame are gone.
    Raised by ``add_standby`` at ATTACH time (the version vectors are
    exchanged in ``repl_hello``) instead of letting the standby's
    apply thread halt asynchronously after the attach already looked
    healthy — the remedy is always to reseed the standby from the
    primary's latest checkpoint."""


class ReplicationSpec(NamedTuple):
    """WAL-shipping replication policy (``MetranService(replication=
    ...)``; defaults from :func:`metran_tpu.config.serve_defaults` —
    ``METRAN_TPU_SERVE_REPL*``, shipped off).

    ``standbys`` is the expected standby count (capacity planning +
    the ``replicas_live`` gauge's denominator — attaching more is
    allowed); ``ack_timeout_s`` bounds each synchronous ship
    round-trip (a standby that cannot ack inside it is dropped and
    must re-attach, so a wedged replica degrades redundancy instead
    of stalling primary acks); ``lag_warn_records`` is the standby
    apply backlog that books a ``replica_lag`` event (with
    half-backlog hysteresis)."""

    enabled: bool = False
    standbys: int = 1
    ack_timeout_s: float = 30.0
    lag_warn_records: int = 1024
    socket_dir: str = ""  # "" = a per-run tempfile directory

    @classmethod
    def from_defaults(cls) -> "ReplicationSpec":
        from ..config import serve_defaults

        d = serve_defaults()
        return cls(
            enabled=bool(d["repl"]),
            standbys=int(d["repl_standbys"]),
            ack_timeout_s=float(d["repl_ack_timeout_s"]),
            lag_warn_records=int(d["repl_lag_warn"]),
            socket_dir=str(d["repl_socket_dir"]),
        ).validate()

    def validate(self) -> "ReplicationSpec":
        """Reject inert or broken combinations at construction."""
        if not self.enabled:
            return self
        if self.standbys < 1:
            raise ValueError(
                f"replication standbys must be >= 1, got "
                f"{self.standbys} — replication with no standby ships "
                "nowhere and protects nothing"
            )
        if not self.ack_timeout_s > 0.0:
            raise ValueError(
                f"replication ack_timeout_s must be > 0, got "
                f"{self.ack_timeout_s} — every commit waits on the "
                "ship ack for at most this long"
            )
        if self.lag_warn_records < 1:
            raise ValueError(
                f"replication lag_warn_records must be >= 1, got "
                f"{self.lag_warn_records}"
            )
        if self.socket_dir and not os.path.isdir(self.socket_dir):
            raise ValueError(
                f"replication socket_dir {self.socket_dir!r} does not "
                "exist — primaries and standbys rendezvous on unix "
                "sockets under it"
            )
        return self

    def resolve_socket_dir(self) -> str:
        """The rendezvous directory, creating a private one when the
        spec leaves it to us."""
        if self.socket_dir:
            return self.socket_dir
        import tempfile

        return tempfile.mkdtemp(prefix="metran_repl_")


def decode_frame(frame: bytes) -> List[WalRecord]:
    """Verify + decode one raw CRC-framed unit (``b"WR"`` + header +
    payload) — the receiving edge's defense: a shipped frame is
    re-verified against its own CRC before it is appended to the
    standby's log or queued for apply, so a corrupted transport can
    never plant a frame the recovery readers would later reject."""
    head_len = len(REC_MAGIC) + _FRAME_HEAD.size
    if len(frame) < head_len or frame[: len(REC_MAGIC)] != REC_MAGIC:
        raise ValueError("bad replication frame magic")
    length, crc = _FRAME_HEAD.unpack_from(frame, len(REC_MAGIC))
    payload = frame[head_len:]
    if len(payload) != length:
        raise ValueError("replication frame length mismatch")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise ValueError("replication frame CRC mismatch")
    return decode_group(payload)


class _Standby:
    """One live standby from the hub's point of view."""

    def __init__(self, name: str, socket_path: str, client: RpcClient):
        self.name = name
        self.socket_path = socket_path
        self.client = client
        self.shipped_group = 0
        self.received_group = 0
        self.applied_group = 0
        self.backlog = 0
        self.failures = 0
        self.lag_warned = False
        #: (group, ship-monotonic) of groups shipped but not yet seen
        #: applied — the ack-to-applied lag sample source
        self.pending: deque = deque()


class ReplicationHub:
    """Primary-side WAL frame shipper (the durability manager's
    ``shipper`` hook).

    ``ship(groups)`` runs on the dispatch thread between the local
    WAL fdatasync and the callers' acks — pushes to N >= 2 standbys
    fan out concurrently, so one commit's ship wall is bounded by ONE
    ``ack_timeout_s`` regardless of standby count.  ``add_standby``
    holds the hub lock through validation + catch-up; the live-stream
    handoff is still seamless because every shipped frame is on the
    primary's own WAL before ``ship`` is called (a catch-up that
    misses a frame's live ship window reads it from the log instead).
    Ordinary standby failures degrade (drop + re-attach); a
    :class:`StaleEpochError` reply fences the hub permanently."""

    def __init__(self, service, spec: ReplicationSpec):
        self.service = service
        self.spec = spec
        self._lock = threading.RLock()
        self._standbys: Dict[str, _Standby] = {}
        # the stream epoch resumes from the persisted fence next to
        # the service's own WAL: a promoted standby re-armed as the
        # new primary (promote() wrote the file before re-arming
        # durability over the same directory) must NOT restart at 1
        dur = getattr(service, "_durability", None)
        self.epoch = load_epoch(dur.dir) if dur is not None else 1
        self.fenced = False
        self.fenced_epoch: Optional[int] = None
        self.shipped_groups = 0
        self.shipped_commits = 0
        self.drops = 0
        #: recent ack-to-applied lag samples in seconds (the
        #: ``repl_lag_p99_ms`` bench headline's source)
        self.lag_samples_s: deque = deque(maxlen=8192)
        #: lazy fan-out pool (only with >= 2 standbys): pushes run
        #: concurrently so one commit's total ship wall is bounded by
        #: ONE ack timeout, not standby-count many
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_workers = 0

    # -- the ack-path hooks (called by DurabilityManager) ---------------
    def raise_if_fenced(self) -> None:
        if self.fenced:
            raise PrimaryFencedError(
                f"primary (epoch {self.epoch}) is fenced: a standby "
                f"was promoted to epoch {self.fenced_epoch}; this "
                "process can never ack a commit again"
            )

    def ship(self, groups) -> None:
        """Push one committed dispatch's group frames to every live
        standby, synchronously.  Called BEFORE any caller's ack
        resolves; raising here fails the round un-acked.

        With one standby the push runs inline; with N >= 2 the pushes
        fan out on the hub's pool so the total ship wall-clock per
        commit is bounded by ONE ``ack_timeout_s`` regardless of
        standby count (the RPC itself happens OUTSIDE the hub lock —
        only membership snapshot and bookkeeping hold it, so an
        attach's catch-up is the only thing a ship ever waits behind).
        The frames were appended to the primary's own WAL before this
        call, so a standby attaching concurrently can never miss them:
        either its catch-up read them from the log, or it joined
        membership before the snapshot below and gets them live."""
        groups = [g for g in groups if g.n_records]
        if not groups:
            return
        frames = [encode_group(g) for g in groups]
        # label the dispatch with its LAST (max) group id: the standby
        # acks applied-up-to this id only after every group in the
        # dispatch applied, so lag samples and backlog hysteresis stay
        # honest when one dispatch carries several commit groups
        group = int(groups[-1].group)
        n_records = sum(g.n_records for g in groups)
        # the commit's rider SpanContexts (set by the dispatch thread
        # under the update lock, serve.service) attribute the ship to
        # every request in the round AND ride the envelope to the
        # standby; pool threads have no contextvar, so the envelope
        # ctx is explicit — first rider carries the correlation id
        tracer = getattr(self.service, "tracer", None)
        traces = (
            getattr(self.service, "_commit_traces", None)
            if tracer is not None else None
        )
        ship_ctx = (
            (int(traces[0][0]), int(traces[0][1]), os.getpid())
            if traces else None
        )
        with self._lock:
            self.raise_if_fenced()
            targets = list(self._standbys.values())
        if not targets:
            return
        t0 = time.monotonic()
        if len(targets) == 1:
            self._push(targets[0], frames, group, n_records, t0,
                       ship_ctx)
        else:
            fence: Optional[PrimaryFencedError] = None
            pool = self._ship_pool(len(targets))
            futures = [
                pool.submit(
                    self._push, sb, frames, group, n_records, t0,
                    ship_ctx,
                )
                for sb in targets
            ]
            for fut in futures:
                try:
                    fut.result()
                except PrimaryFencedError as exc:
                    fence = exc
            if fence is not None:
                raise fence
        with self._lock:
            # a concurrent dispatch's push may have discovered the
            # fence while ours was in flight — never book (or ack) a
            # commit past that point
            self.raise_if_fenced()
            self.shipped_groups += 1
            self.shipped_commits += n_records
        if tracer is not None and traces:
            tracer.record_shared(
                "repl.ship", traces, t0, time.monotonic(),
                {"group": group, "commits": n_records,
                 "standbys": len(targets)},
            )

    def _ship_pool(self, n: int) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None or self._pool_workers < n:
                old = self._pool
                self._pool_workers = max(4, n)
                self._pool = ThreadPoolExecutor(
                    max_workers=self._pool_workers,
                    thread_name_prefix="metran-repl-ship",
                )
                if old is not None:
                    old.shutdown(wait=False)
            return self._pool

    def _push(self, sb: _Standby, frames, group: int, n_records: int,
              t0: float, ship_ctx=None) -> None:
        """One standby's ship RPC + bookkeeping.  The RPC runs outside
        the hub lock (pushes to different standbys are concurrent;
        ``RpcClient`` serializes per socket); only the books take it.
        ``ship_ctx`` is the explicit trace envelope (propagated
        correlation id) — ``None`` ships untraced."""
        try:
            reply = sb.client.call("repl_frames", {
                "epoch": self.epoch, "group": group,
                "n_records": n_records, "frames": frames,
            }, ctx=ship_ctx)
        except StaleEpochError as exc:
            with self._lock:
                self.fenced = True
                self.fenced_epoch = exc.epoch
            logger.error(
                "standby %s is at epoch %d > our %d: this primary is "
                "fenced and will never ack again", sb.name, exc.epoch,
                self.epoch,
            )
            raise PrimaryFencedError(
                f"standby {sb.name} was promoted to epoch "
                f"{exc.epoch}; this primary (epoch {self.epoch}) is "
                "fenced — the commit was NOT acked"
            ) from exc
        except Exception:
            # an unreachable/broken standby must degrade redundancy,
            # not block or fail primary acks: drop it (it re-attaches
            # and catches up from the primary's log)
            with self._lock:
                sb.failures += 1
                self.drops += 1
                self._standbys.pop(sb.name, None)
            logger.exception(
                "standby %s failed a ship and was dropped (it can "
                "re-attach and catch up)", sb.name,
            )
            try:
                sb.client.close()
            except Exception:  # pragma: no cover - teardown
                pass
            return
        with self._lock:
            sb.shipped_group = max(sb.shipped_group, group)
            sb.pending.append((group, t0))
            self._harvest(sb, reply, time.monotonic())

    def _harvest(self, sb: _Standby, reply: dict, now: float) -> None:
        """Fold one standby reply into the lag books (caller holds
        ``_lock``)."""
        applied = int(reply.get("applied", sb.applied_group))
        while sb.pending and sb.pending[0][0] <= applied:
            _g, t_ship = sb.pending.popleft()
            self.lag_samples_s.append(now - t_ship)
        sb.applied_group = applied
        sb.received_group = int(reply.get("received", sb.received_group))
        sb.backlog = int(reply.get("backlog", 0))
        events = self.service.events
        if sb.backlog >= self.spec.lag_warn_records:
            if not sb.lag_warned and events is not None:
                sb.lag_warned = True
                events.emit(
                    "replica_lag", fault_point="cluster.replication",
                    standby=sb.name, backlog=sb.backlog,
                    applied_group=sb.applied_group,
                    shipped_group=sb.shipped_group,
                )
        elif sb.backlog < max(1, self.spec.lag_warn_records // 2):
            sb.lag_warned = False

    # -- membership -----------------------------------------------------
    def add_standby(self, socket_path: str,
                    name: Optional[str] = None) -> dict:
        """Attach one standby: epoch handshake (the hello also drains
        the standby's apply queue and returns its version vector),
        baseline validation against the primary's checkpoint cut + WAL
        (a standby whose versions the surviving log cannot reach is
        refused HERE with :class:`ReplicaBaselineError` — reseed it
        from the latest checkpoint — instead of halting its apply
        thread asynchronously after the attach looked healthy), then
        catch-up from the primary's own WAL (under the ship lock, so
        no commit falls between catch-up and the live stream), then
        live membership.  Returns the handshake summary."""
        name = name or os.path.basename(socket_path)
        client = RpcClient(
            socket_path, timeout_s=self.spec.ack_timeout_s
        )
        with self._lock:
            self.raise_if_fenced()
            try:
                hello = client.call(
                    "repl_hello",
                    {"epoch": self.epoch, "pid": os.getpid()},
                )
            except StaleEpochError as exc:
                client.close()
                self.fenced = True
                self.fenced_epoch = exc.epoch
                raise PrimaryFencedError(
                    f"standby {name} is already at epoch {exc.epoch}; "
                    f"this primary (epoch {self.epoch}) is fenced"
                ) from exc
            except Exception:
                client.close()
                raise
            sb = _Standby(name, socket_path, client)
            try:
                self._validate_baseline(name, hello.get("versions"))
                caught_up = self._catch_up(sb)
            except BaseException:
                # an attach that cannot be validated or caught up must
                # not leak its connection (and never joined membership)
                try:
                    client.close()
                except Exception:  # pragma: no cover - teardown
                    pass
                raise
            self._standbys[name] = sb
            events = self.service.events
            if events is not None:
                events.emit(
                    "replica_connect",
                    fault_point="cluster.replication",
                    standby=name, catch_up_commits=caught_up,
                    epoch=self.epoch,
                )
            return {
                "standby": name, "epoch": self.epoch,
                "catch_up_commits": caught_up,
                "replicas": len(self._standbys),
            }

    def _validate_baseline(self, name: str, versions) -> None:
        """Attach-time reseed gate (caller holds ``_lock``).

        Checkpoints truncate the WAL, so catch-up can only bridge a
        standby whose versions reach the oldest surviving frame.  Two
        checks against the standby's post-drain version vector (from
        ``repl_hello``): every model in the latest checkpoint cut must
        be at least at its cut version (the frames below the cut are
        gone), and walking the surviving WAL from the vector must stay
        contiguous per model.  Either failing raises
        :class:`ReplicaBaselineError` — the replica needs a reseed
        from the primary's latest checkpoint, and saying so NOW beats
        an asynchronous apply halt after the attach returned success."""
        dur = self.service._durability
        if dur is None:  # pragma: no cover - hub always armed with WAL
            return
        if versions is None:
            # a pre-vector standby: the legacy behavior (gaps surface
            # as an apply halt on the first broken ship)
            logger.warning(
                "standby %s reported no version vector; baseline "
                "validation skipped", name,
            )
            return
        v = {str(m): int(ver) for m, ver in versions.items()}
        man = load_latest_manifest(dur.dir)
        cut = (man or {}).get("versions") or {}
        for mid, cut_v in cut.items():
            have = v.get(str(mid))
            if have is None or have < int(cut_v):
                raise ReplicaBaselineError(
                    f"standby {name} baseline predates the primary's "
                    f"checkpoint cut: model {mid!r} is at version "
                    f"{have if have is not None else 'ABSENT'} on the "
                    f"standby but the cut is at {int(cut_v)} and the "
                    "WAL below it was truncated — reseed the standby "
                    "from the primary's latest checkpoint"
                )
        # per-model contiguity over the surviving frames (frames a
        # concurrent dispatch appends mid-walk are a contiguous tail,
        # so a partial view can only pass conservatively)
        for frame in iter_frames(dur.dir, since_seq=1):
            for rec in frame.records:
                have = v.get(rec.model_id)
                if have is None:
                    raise ReplicaBaselineError(
                        f"standby {name} has no state for model "
                        f"{rec.model_id!r} but the primary's WAL "
                        "holds commits for it — reseed the standby "
                        "from the primary's latest checkpoint"
                    )
                if rec.version <= have:
                    continue
                if rec.version == have + 1:
                    v[rec.model_id] = rec.version
                else:
                    raise ReplicaBaselineError(
                        f"standby {name} baseline has a WAL gap for "
                        f"model {rec.model_id!r}: standby at version "
                        f"{have}, oldest unapplied surviving frame is "
                        f"{rec.version} — the commits between were "
                        "checkpoint-truncated; reseed the standby "
                        "from the primary's latest checkpoint"
                    )

    def _catch_up(self, sb: _Standby) -> int:
        """Re-ship every intact frame of the primary's own log (the
        follower API — commits since the last checkpoint; the standby
        skips anything its versions already cover).  Failures here
        raise: an attach that cannot catch up must not join live
        membership with a hole behind it."""
        dur = self.service._durability
        if dur is None:  # pragma: no cover - hub always armed with WAL
            return 0
        shipped = 0
        batch: List[bytes] = []
        batch_records: List[WalRecord] = []
        batch_group: Optional[int] = None

        def flush() -> None:
            nonlocal shipped
            if not batch:
                return
            reply = sb.client.call("repl_frames", {
                "epoch": self.epoch, "group": int(batch_group or 0),
                "n_records": len(batch_records),
                "frames": list(batch),
            })
            shipped += len(batch_records)
            sb.shipped_group = int(batch_group or 0)
            self._harvest(sb, reply, time.monotonic())

        for frame in iter_frames(dur.dir, since_seq=1):
            if not frame.records:
                continue
            g = int(frame.records[0].group)
            if batch and g != batch_group:
                flush()
                batch, batch_records = [], []
            batch_group = g
            batch.append(frame.data)
            batch_records.extend(frame.records)
        flush()
        return shipped

    # -- reporting ------------------------------------------------------
    def poll(self) -> None:
        """Refresh per-standby applied/backlog books off the ship path
        (the bench drain + gauge scrapes between quiet stretches).
        The status RPCs run outside the hub lock so a slow standby
        never stalls a concurrent ship's bookkeeping."""
        with self._lock:
            targets = list(self._standbys.values())
        for sb in targets:
            try:
                reply = sb.client.call("repl_status")
            except Exception:
                with self._lock:
                    sb.failures += 1
                continue
            with self._lock:
                self._harvest(sb, reply, time.monotonic())

    def replicas_live(self) -> int:
        return len(self._standbys)

    def lag_seconds(self) -> float:
        """Worst ack-to-applied lag across standbys right now (0 when
        every shipped group is applied everywhere)."""
        now = time.monotonic()
        worst = 0.0
        with self._lock:
            for sb in self._standbys.values():
                if sb.pending:
                    worst = max(worst, now - sb.pending[0][1])
        return worst

    def status(self) -> dict:
        with self._lock:
            return {
                "epoch": self.epoch,
                "fenced": self.fenced,
                "replicas": len(self._standbys),
                "shipped_groups": self.shipped_groups,
                "shipped_commits": self.shipped_commits,
                "drops": self.drops,
                "lag_s": round(self.lag_seconds(), 6),
                "standbys": {
                    sb.name: {
                        "shipped_group": sb.shipped_group,
                        "received_group": sb.received_group,
                        "applied_group": sb.applied_group,
                        "backlog": sb.backlog,
                        "failures": sb.failures,
                    }
                    for sb in self._standbys.values()
                },
            }

    def close(self) -> None:
        with self._lock:
            for sb in self._standbys.values():
                try:
                    sb.client.close()
                except Exception:  # pragma: no cover - teardown
                    pass
            self._standbys.clear()
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)


def _to_host(obj):
    from .writer import _to_host as impl

    return impl(obj)


class ReplicaStandby:
    """Continuously-replaying standby host around one seeded
    ``MetranService``.

    The wrapped service must share the primary's baseline (same
    states at the same versions — a copied checkpoint or the same
    deterministic seeding) and must NOT arm its own durability plane:
    shipped frames are appended to the standby's log **verbatim**, and
    :meth:`promote` re-arms a full durability manager over that log.
    Reads (``forecast``/``read_loop``) serve immediately from the
    standby's own ``SnapshotStore``/plane — the replica read fan-out;
    writes are refused until promotion."""

    def __init__(self, service, spec: ReplicationSpec,
                 socket_path: str, wal_dir=None):
        if service._durability is not None:
            raise ValueError(
                "standby service must not arm its own durability "
                "plane while replicating — shipped frames land on the "
                "standby's log verbatim, and promote() re-arms "
                "durability over it"
            )
        if service.registry.root is None:
            raise ValueError(
                "a standby needs a registry with a storage root (its "
                "local WAL and post-promotion checkpoints live there)"
            )
        self.service = service
        self.spec = spec
        self.socket_path = socket_path
        self.wal_dir = (
            Path(wal_dir) if wal_dir else service.registry.root / "wal"
        )
        self.wal_dir.mkdir(parents=True, exist_ok=True)
        self.epoch = self._load_epoch()
        existing = list_segments(self.wal_dir)
        next_seq = (existing[-1][0] + 1) if existing else 1
        self.log = WriteAheadLog(self.wal_dir, next_seq, fsync=True)
        self._cv = threading.Condition()
        #: (group, [WalRecord, ...], propagated SpanContext or None)
        self._queue: deque = deque()
        self._applying = False
        #: frame RPCs past the epoch check but not yet re-checked
        #: after their append — promote() fences the epoch first, then
        #: waits this out before draining and closing the log, so a
        #: racing ship can neither enqueue past the drain nor append
        #: to a closed log
        self._frames_inflight = 0
        self._apply_error: Optional[BaseException] = None
        self._stop = False
        self.promoted = False
        self.received_group = 0
        self.applied_group = 0
        self.received_commits = 0
        self.applied_commits = 0
        self.skipped_commits = 0
        self.last_promote: Optional[dict] = None
        self._shutdown = threading.Event()
        self._apply_thread = threading.Thread(
            target=self._apply_loop, name="metran-repl-apply",
            daemon=True,
        )
        self._apply_thread.start()
        self._telemetry = ChildTelemetry(
            getattr(service, "obs", None), "standby"
        )
        self.rpc = RpcServer(
            socket_path, self._handlers(),
            tracer=getattr(service, "tracer", None),
        )

    # -- epoch fence persistence ---------------------------------------
    def _load_epoch(self) -> int:
        return load_epoch(self.wal_dir)

    def _persist_epoch(self) -> None:
        tmp = self.wal_dir / f".{EPOCH_FILE}.{os.getpid()}.tmp"
        tmp.write_text(str(self.epoch))
        os.replace(tmp, self.wal_dir / EPOCH_FILE)

    # -- RPC surface ----------------------------------------------------
    def _handlers(self) -> dict:
        svc = self.service
        return {
            "hello": self._hello,
            "ping": lambda _p: "pong",
            "repl_hello": self._repl_hello,
            "repl_frames": self._repl_frames,
            "repl_status": lambda _p: self.status(),
            "repl_promote": lambda p: self.promote(
                epoch=(p or {}).get("epoch"),
                checkpoint=(p or {}).get("checkpoint", True),
            ),
            "forecast": lambda p: _to_host(
                svc.forecast(p["model_id"], p["steps"])
            ),
            "meta": lambda p: _to_host(svc.registry.meta(p["model_id"])),
            "read_loop": self._read_loop,
            "stats": lambda _p: self.status(),
            "update": self._update,
            "put": self._put,
            "flush": lambda _p: svc.flush(),
            "capacity_report": lambda _p: svc.capacity_report(),
            "telemetry": self._telemetry.collect,
            "shutdown": lambda _p: self._shutdown.set(),
        }

    def _hello(self, _payload) -> dict:
        plane = getattr(self.service, "cluster_plane", None)
        return {
            "pid": os.getpid(),
            "plane": plane.name if plane is not None else None,
            "promoted": self.promoted,
            "epoch": self.epoch,
        }

    def _repl_hello(self, payload) -> dict:
        epoch = int((payload or {}).get("epoch", 1))
        with self._cv:
            if self.promoted or epoch < self.epoch:
                raise StaleEpochError(self.epoch)
            if epoch > self.epoch:
                self.epoch = epoch
                self._persist_epoch()
            # drain before reporting: the version vector must reflect
            # every frame already on this standby's log (a re-attach
            # with a backlog would otherwise look staler than it is),
            # and a halted apply must refuse the attach HERE — a
            # silently-broken replica never rejoins live membership
            while ((self._queue or self._applying
                    or self._frames_inflight)
                    and self._apply_error is None):
                self._cv.wait(0.2)
            if self._apply_error is not None:
                raise RecoveryError(
                    "standby apply halted: "
                    f"{self._apply_error!r}"
                )
            # the version vector must cover the WHOLE baseline —
            # including states still on disk — so warm first (the
            # standby replays into them anyway; current_versions alone
            # only sees loaded/arena-resident states)
            reg = self.service.registry
            reg.warm()
            return {
                "epoch": self.epoch,
                "received": self.received_group,
                "applied": self.applied_group,
                "backlog": sum(len(q[1]) for q in self._queue),
                "versions": {
                    m: int(ver)
                    for m, ver in reg.current_versions().items()
                },
                "pid": os.getpid(),
            }

    def _repl_frames(self, payload) -> dict:
        epoch = int(payload["epoch"])
        with self._cv:
            if self.promoted or epoch < self.epoch:
                raise StaleEpochError(self.epoch)
            if self._apply_error is not None:
                raise RecoveryError(
                    "standby apply halted: "
                    f"{self._apply_error!r}"
                )
            if epoch > self.epoch:
                self.epoch = epoch
                self._persist_epoch()
            self._frames_inflight += 1
        group = int(payload["group"])
        records: List[WalRecord] = []
        try:
            for buf in payload["frames"]:
                # CRC re-verified at the receiving edge, then appended
                # VERBATIM — the standby's log is byte-identical to
                # the primary's stream, so the same readers replay it
                recs = decode_frame(buf)
                self.log.append_encoded(buf, len(recs))
                records.extend(recs)
        except BaseException:
            with self._cv:
                self._frames_inflight -= 1
                self._cv.notify_all()
            raise
        with self._cv:
            self._frames_inflight -= 1
            self._cv.notify_all()
            # re-check under the lock: promote() may have fenced the
            # epoch while it was released for the append above.
            # Refusing HERE — before the enqueue — keeps the
            # zero-acked-loss contract: the frames sit on our log but
            # the primary is answered StaleEpochError, so the commit
            # is never acked (and promotion's checkpoint cut is free
            # to truncate the never-applied tail).  Without this, a
            # ship racing promotion could enqueue after the drain with
            # the apply thread stopped and be ACKED without ever being
            # applied on the promoted timeline.
            if self.promoted or epoch < self.epoch:
                raise StaleEpochError(self.epoch)
            if records:
                # the ipc layer attached the ship's propagated trace
                # context to this handler thread; carry it with the
                # batch so the apply thread can attribute the replay
                self._queue.append((group, records, current_context()))
                self.received_group = max(self.received_group, group)
                self.received_commits += len(records)
                self._cv.notify_all()
            return {
                "received": self.received_group,
                "applied": self.applied_group,
                "backlog": sum(len(q[1]) for q in self._queue),
                "epoch": self.epoch,
            }

    def _update(self, payload):
        if not self.promoted:
            raise RuntimeError(
                "standby is read-only until promoted — updates go to "
                "the primary (promote() turns this replica into one)"
            )
        return _to_host(self.service.update(
            payload["model_id"], payload["new_obs"]
        ))

    def _put(self, payload):
        if not self.promoted:
            raise RuntimeError(
                "standby is read-only until promoted"
            )
        return self.service.registry.put(
            payload["state"], persist=payload.get("persist", False)
        )

    def _read_loop(self, payload) -> dict:
        """Bench surface: tight in-process forecast reads off the
        standby's own snapshot store — the quantity that scales with
        replicas (per the cluster worker's ``read_loop`` contract)."""
        model_ids = payload["model_ids"]
        steps = int(payload["steps"])
        iters = int(payload["iters"])
        svc = self.service
        n_models = len(model_ids)
        hits = 0
        t0 = perf_counter()
        for i in range(iters):
            svc.forecast(model_ids[i % n_models], steps)
            hits += 1
        elapsed = perf_counter() - t0
        return {"iters": iters, "hits": hits, "elapsed_s": elapsed,
                "pid": os.getpid()}

    # -- the apply engine ------------------------------------------------
    def _apply_loop(self) -> None:
        """Dedicated replay thread: drain the received-group queue
        through :func:`replay_wal` — the same kernels, the same group
        batching, the same version-landing checks as recovery, so the
        applied state is bit-identical at f64.  An apply failure
        (version gap, landing mismatch) halts replication on this
        standby — served reads stay available at the last applied
        version, promotion refuses."""
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait(0.2)
                if self._stop:
                    return
                batch = list(self._queue)
                self._queue.clear()
                self._applying = True
            records = [r for _, recs, _ctx in batch for r in recs]
            t_apply0 = time.monotonic()
            try:
                report = replay_wal(self.service, records)
            except BaseException as exc:  # noqa: BLE001 - halts apply
                logger.exception(
                    "standby apply failed — replication halted on "
                    "this standby (reads stay available at version "
                    "%d)", self.applied_group,
                )
                with self._cv:
                    self._apply_error = exc
                    self._applying = False
                    self._cv.notify_all()
                return
            with self._cv:
                self.applied_group = max(
                    self.applied_group, batch[-1][0]
                )
                self.applied_commits += int(report.get("replayed", 0))
                self.skipped_commits += int(report.get("skipped", 0))
                self._applying = False
                self._cv.notify_all()
            tracer = getattr(self.service, "tracer", None)
            if tracer is not None:
                ctxs = [c for _, _, c in batch if c is not None]
                if ctxs:
                    # one shared-interval span per propagated ship
                    # context: the standby lane's "repl.apply" closes
                    # the frontend → writer → standby chain
                    tracer.record_shared(
                        "repl.apply", ctxs, t_apply0, time.monotonic(),
                        {"group": int(batch[-1][0]),
                         "commits": len(records)},
                    )

    # -- promotion -------------------------------------------------------
    def promote(self, epoch: Optional[int] = None,
                checkpoint: bool = True) -> dict:
        """Promote this standby to primary: bump + persist the fence
        epoch FIRST (an in-flight ship from the old primary now
        answers :class:`StaleEpochError`), drain the apply queue
        through the replay engine, then re-arm a full
        :class:`~metran_tpu.serve.durability.DurabilityManager` over
        the standby's own log (``checkpoint=True`` cuts the baseline
        immediately, so the promoted primary is durable on its own).
        Returns the promotion report; RTO is this wall plus the first
        served read (measured by the caller)."""
        t0 = time.monotonic()
        with self._cv:
            if self.promoted:
                raise RuntimeError("standby is already promoted")
            # the fence must be strictly monotonic: an explicit epoch
            # below (or at) the current one would let a zombie at the
            # same epoch keep shipping
            self.epoch = (
                max(self.epoch + 1, int(epoch))
                if epoch is not None else self.epoch + 1
            )
            self._persist_epoch()
            # the fence is up: new frame RPCs refuse at entry.  Wait
            # out any ship already past the entry check (mid-append —
            # it will refuse at its post-append re-check instead of
            # enqueueing, so the old primary is never acked), then
            # drain: everything received must be applied before this
            # replica serves as primary
            while ((self._frames_inflight or self._queue
                    or self._applying)
                    and self._apply_error is None):
                self._cv.wait(0.2)
            if self._apply_error is not None:
                raise RecoveryError(
                    "standby apply halted before promotion: "
                    f"{self._apply_error!r}"
                )
            self.promoted = True
            self._stop = True
            self._cv.notify_all()
        self._apply_thread.join(timeout=10.0)
        self.log.close()
        svc = self.service
        svc._durability = DurabilityManager(
            svc,
            DurabilitySpec(enabled=True, dir=str(self.wal_dir)),
            recovered=True,
            initial_checkpoint=checkpoint,
        )
        svc._register_durability_gauges()
        report = {
            "epoch": self.epoch,
            "applied_group": self.applied_group,
            "applied_commits": self.applied_commits,
            "skipped_commits": self.skipped_commits,
            "received_commits": self.received_commits,
            "checkpointed": bool(checkpoint),
            "promote_wall_s": round(time.monotonic() - t0, 6),
        }
        self.last_promote = report
        if svc.events is not None:
            svc.events.emit(
                "replica_promote", fault_point="cluster.replication",
                **report,
            )
        return report

    # -- reporting / lifecycle -------------------------------------------
    def status(self) -> dict:
        with self._cv:
            return {
                "epoch": self.epoch,
                "promoted": self.promoted,
                "received": self.received_group,
                "applied": self.applied_group,
                "backlog": sum(len(q[1]) for q in self._queue),
                "received_commits": self.received_commits,
                "applied_commits": self.applied_commits,
                "skipped_commits": self.skipped_commits,
                "apply_error": (
                    repr(self._apply_error)
                    if self._apply_error is not None else None
                ),
                "pid": os.getpid(),
            }

    def serve(self) -> None:
        """Block until a ``shutdown`` RPC arrives (the process-entry
        idle loop; RPC and apply run on their own threads)."""
        while not self._shutdown.wait(0.5):
            pass

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            # let a mid-append frame RPC finish before the log closes
            # under it (bounded: appends are short)
            deadline = time.monotonic() + 5.0
            while self._frames_inflight and time.monotonic() < deadline:
                self._cv.wait(0.2)
        self._apply_thread.join(timeout=5.0)
        self.rpc.close()
        if not self.promoted:
            try:
                self.log.close()
            except Exception:  # pragma: no cover - teardown
                pass


def standby_main(
    spec: ReplicationSpec,
    socket_path: str,
    service_factory,
    factory_args: Tuple = (),
    ready_path: Optional[str] = None,
) -> int:
    """Process entry for a spawned standby (the writer_main twin).

    ``service_factory(*factory_args)`` must be a picklable
    module-level callable returning the standby's seeded
    ``MetranService`` (durability NOT armed) — it runs inside this
    process; jax state never crosses a fork.  Writes ``ready_path``
    once RPC is up, then serves until a ``shutdown`` RPC."""
    import traceback

    service = None
    standby = None
    try:
        service = service_factory(*factory_args)
        standby = ReplicaStandby(service, spec, socket_path)
        if ready_path:
            tmp = f"{ready_path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(str(os.getpid()))
            os.replace(tmp, ready_path)
        standby.serve()
        return 0
    except Exception:
        logger.error(
            "standby process failed:\n%s", traceback.format_exc()
        )
        return 1
    finally:
        if standby is not None:
            try:
                standby.close()
            except Exception:  # pragma: no cover - teardown
                pass
        if service is not None:
            try:
                service.close()
            except Exception:  # pragma: no cover - teardown
                logger.exception("standby service close failed")
