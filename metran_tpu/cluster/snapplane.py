"""Shared-memory snapshot plane: seqlock-versioned cross-process reads.

The single-process read path (:mod:`metran_tpu.serve.readpath`) serves
forecast hits from immutable host-memory snapshots — but those
snapshots live in ONE interpreter, so read capacity is capped by one
GIL however many cores the host has.  This module is the cross-process
half: the writer process publishes every committed
:class:`~metran_tpu.serve.readpath.SnapshotEntry` into a
``multiprocessing.shared_memory`` segment laid out as an open-addressed
slot table, and N read-worker processes (:mod:`metran_tpu.cluster.
worker`) map the same segment and serve hits with **zero writer locks,
zero sockets and zero device traffic** — read capacity now scales with
processes, not threads.

Consistency is a classic **seqlock** per slot, not a lock:

- the (single) writer bumps the slot's sequence word to an odd value,
  writes the record (header, key, names, moment payload), then bumps
  it even again;
- a reader snapshots the sequence word, copies the record, and
  re-reads the word: equal-and-even proves the copy is torn-free, odd
  or changed means a concurrent write — retry, and after a bounded
  number of attempts report a miss (the caller falls through to the
  compute path, exactly like a cache miss — contention degrades to a
  fallthrough, never a wrong answer).

The protocol is safe on the strong-store-order hosts this plane
targets (x86-64's TSO; the sequence word is an aligned 8-byte store,
atomic on every platform CPython runs on).  Nothing here depends on
the GIL — the two sequence reads bracket a byte-copy of the record, so
a torn write is always detected by the second read.

**WAL-anchored publication.**  The plane's header carries a monotone
``commit_seq`` the writer bumps once per publish batch — the same
group-commit boundary the durability plane's WAL frames are cut at —
plus the writer's pid and a heartbeat stamp.  Readers learn writer
liveness and publication progress from this one header; there is no
second notification protocol (docs/concepts.md "Multi-process
serving").  A worker table in the same segment gives every reader
process a claimed row for its own heartbeat and hit/stale/miss/
fallback counters, so the frontend aggregates fleet read telemetry
with one shared-memory scan and no RPC.

Capacity is fixed at creation (``ClusterSpec.shm_mb``): slots are
sized for the configured horizon set and the widest padded series
count, and :func:`plane_bytes` is the sizing contract
``ClusterSpec.validate_layout`` enforces before a writer ever maps the
segment.
"""

from __future__ import annotations

import hashlib
import os
import struct
import time
from logging import getLogger
from multiprocessing import resource_tracker, shared_memory
from typing import Dict, Iterable, Optional

import numpy as np

from ..serve.readpath import SnapshotEntry, contiguous_prefix, \
    parse_horizons

logger = getLogger(__name__)

__all__ = [
    "SnapshotPlane",
    "plane_bytes",
]

#: layout magic + version: an attach to a segment some OTHER program
#: created (or an older layout) must fail loudly, not serve garbage
MAGIC = 0x4D54524E53504C31  # "MTRNSPL1"
LAYOUT_VERSION = 1

HEADER_BYTES = 256
#: fixed worker-table capacity: readers claim rows, the frontend scans
#: them.  64 rows is far past any same-host worker count (the point of
#: workers is one per core).
MAX_WORKERS = 64
WORKER_ROW_BYTES = 128
WORKERS_OFF = HEADER_BYTES
SLOTS_OFF = WORKERS_OFF + MAX_WORKERS * WORKER_ROW_BYTES

# header field offsets (all naturally aligned)
_OFF_MAGIC = 0  # u64
_OFF_LAYOUT = 8  # u32
_OFF_NSLOTS = 12  # u32
_OFF_SLOT_BYTES = 16  # u64
_OFF_H = 24  # u32
_OFF_NPAD = 28  # u32
_OFF_PREFIX = 32  # u32
_OFF_WAL = 36  # u32 (1 = writer has an armed WAL: commit_seq is
#                     stamped at group-commit boundaries)
_OFF_COMMIT_SEQ = 40  # u64, monotone publish-batch counter
_OFF_WRITER_PID = 48  # u64
_OFF_WRITER_STAMP = 56  # f64, epoch-seconds heartbeat

_HEADER_STRUCT = struct.Struct("<QIIQIIIIQQd")

# worker-row field offsets (relative to the row)
_W_STATE = 0  # u32: 0 = free, 1 = claimed
_W_PID = 8  # u64
_W_BEAT = 16  # f64 epoch heartbeat
_W_HITS = 24  # u64
_W_STALE = 32  # u64
_W_MISSES = 40  # u64
_W_FALLBACKS = 48  # u64

# slot record: fixed header, then key/names/payload regions
_S_SEQ = 0  # u64 seqlock word
_S_HASH = 8  # u64 stable key hash
_S_KEYLEN = 16  # u32 (0 + hash==0: never used; 0 + hash!=0: tombstone)
_S_NSERIES = 20  # u32
_S_NAMESLEN = 24  # u32
_S_VERSION = 32  # i64
_S_PUBLISHED = 40  # f64
SLOT_FIXED = 48
KEY_BYTES = 64
#: per-series budget for the '\0'-joined names blob; entries whose
#: joined names exceed it publish without names (readers fall back to
#: the compute path for those models) — counted, never silent
NAME_BYTES_PER_SERIES = 32

#: probe ceiling for open addressing: past this the table is treated
#: as full for that key (publish drops, read misses)
PROBE_LIMIT = 64
#: seqlock read retries before a contended slot degrades to a miss
READ_RETRIES = 16


def _key_hash(model_id: str) -> int:
    """Stable (cross-process) 63-bit key hash — ``hash()`` is salted
    per interpreter and useless as a shared-memory rendezvous."""
    digest = hashlib.blake2b(
        model_id.encode("utf-8"), digest_size=8
    ).digest()
    h = int.from_bytes(digest, "little") & 0x7FFFFFFFFFFFFFFF
    return h or 1  # 0 means "never used" in the slot table


def _slot_bytes(h: int, n_pad_max: int) -> int:
    names = NAME_BYTES_PER_SERIES * n_pad_max
    payload = 2 * h * n_pad_max * 8
    raw = SLOT_FIXED + KEY_BYTES + names + payload
    return (raw + 63) & ~63  # 64-byte aligned slots


def plane_bytes(horizons, n_pad_max: int, n_slots: int) -> int:
    """Total segment size for a plane with this geometry — the sizing
    contract :meth:`ClusterSpec.validate_layout` checks against
    ``shm_mb`` before any segment is created."""
    horizons = parse_horizons(horizons)
    return SLOTS_OFF + int(n_slots) * _slot_bytes(
        len(horizons), int(n_pad_max)
    )


def _attach_segment(name: str) -> shared_memory.SharedMemory:
    """Attach without adopting ownership: Python 3.10's
    ``resource_tracker`` registers every attach and unlinks the
    segment when THAT process exits — which would tear the plane down
    under every other process the moment one worker dies.  3.13 grew
    ``track=False`` for exactly this; on older interpreters the
    documented workaround is unregistering the attach-side handle."""
    try:
        return shared_memory.SharedMemory(name=name, track=False)
    except TypeError:
        pass
    shm = shared_memory.SharedMemory(name=name)
    try:
        resource_tracker.unregister("/" + shm.name, "shared_memory")
    except Exception:  # pragma: no cover - tracker internals drifted
        logger.debug("resource_tracker unregister failed", exc_info=True)
    return shm


class SnapshotPlane:
    """One mapped view of the shared snapshot segment.

    Exactly one process constructs with :meth:`create` (the writer —
    it owns the segment and the slot directory); every other process
    :meth:`attach`\\ es read-only semantics (readers never write slots;
    they may claim a worker row for heartbeat/counters).  ``read`` is
    the whole reader hot path: a probe over the open-addressed table
    with a seqlock-consistent copy per candidate slot.
    """

    def __init__(self, shm: shared_memory.SharedMemory, *,
                 owner: bool, events=None):
        self.shm = shm
        self.owner = owner
        self.events = events
        buf = shm.buf
        (magic, layout, n_slots, slot_bytes, h, n_pad, prefix, wal,
         _seq, _pid, _stamp) = _HEADER_STRUCT.unpack_from(buf, 0)
        if magic != MAGIC or layout != LAYOUT_VERSION:
            raise ValueError(
                f"shared segment {shm.name!r} is not a snapshot plane "
                f"(magic {magic:#x}, layout {layout}); refusing to "
                "serve from it"
            )
        self.n_slots = int(n_slots)
        self.slot_bytes = int(slot_bytes)
        self.h = int(h)
        self.n_pad_max = int(n_pad)
        self.prefix = int(prefix)
        # whole-segment u64/f64 views; every aligned field is read and
        # written through these (single 8-byte stores — atomic)
        self._u64 = np.frombuffer(buf, dtype=np.uint64)
        self._f64 = np.frombuffer(buf, dtype=np.float64)
        self._i64 = np.frombuffer(buf, dtype=np.int64)
        self._mv = memoryview(buf)
        #: writer-side slot directory (model_id -> slot index); readers
        #: probe instead
        self._dir: Dict[str, int] = {}
        #: reader-side hot caches.  ``_rcache`` remembers where a model
        #: last resolved (offset, encoded key, hash) so steady-state
        #: reads skip hashing and probing; the in-slot hash + key check
        #: still runs on every read, so a reclaimed or tombstoned slot
        #: self-invalidates back to a full probe.  ``_names_cache``
        #: memoizes decoded names blobs (they change only when a model's
        #: series set does).
        self._rcache: Dict[str, tuple] = {}
        self._names_cache: Dict[bytes, tuple] = {}
        #: per-slot payload views (offset -> (means, variances)); the
        #: mapping is fixed for the segment's lifetime, so the
        #: frombuffer construction cost is paid once per slot.  Cleared
        #: in :meth:`close` — cached views pin the exported buffer.
        self._views: Dict[int, tuple] = {}
        self._worker_row: Optional[int] = None
        # unlocked telemetry, same contract as SnapshotStore's
        self.publishes = 0
        self.dropped = 0  # entries that could not land (table/names)

    # -- construction ----------------------------------------------------
    @classmethod
    def create(cls, horizons, n_pad_max: int, n_slots: int,
               shm_mb: float, name: Optional[str] = None,
               events=None, wal_anchored: bool = False
               ) -> "SnapshotPlane":
        """Create and initialize the segment (writer side).

        Raises ``ValueError`` when the requested geometry does not fit
        ``shm_mb`` — the same check :meth:`ClusterSpec.validate_layout`
        runs, enforced again here so a mis-wired caller cannot map a
        plane its readers would overrun."""
        horizons = parse_horizons(horizons)
        if not horizons:
            raise ValueError(
                "a snapshot plane needs a non-empty horizon set "
                "(METRAN_TPU_SERVE_HORIZONS)"
            )
        n_slots = int(n_slots)
        n_pad_max = int(n_pad_max)
        if n_slots < 1 or n_pad_max < 1:
            raise ValueError(
                f"plane geometry must be positive, got n_slots="
                f"{n_slots}, n_pad_max={n_pad_max}"
            )
        total = plane_bytes(horizons, n_pad_max, n_slots)
        budget = int(float(shm_mb) * 1024 * 1024)
        if total > budget:
            raise ValueError(
                f"snapshot plane needs {total} bytes for {n_slots} "
                f"slots x {len(horizons)} horizons x {n_pad_max} "
                f"padded series, but shm_mb={shm_mb} allows only "
                f"{budget}; raise METRAN_TPU_SERVE_CLUSTER_SHM_MB or "
                "shrink the horizon set"
            )
        if name is None:
            name = f"metran_snap_{os.getpid()}_{os.urandom(4).hex()}"
        shm = shared_memory.SharedMemory(
            name=name, create=True, size=total
        )
        shm.buf[:total] = b"\x00" * total
        _HEADER_STRUCT.pack_into(
            shm.buf, 0, MAGIC, LAYOUT_VERSION, n_slots,
            _slot_bytes(len(horizons), n_pad_max), len(horizons),
            n_pad_max, contiguous_prefix(horizons), int(wal_anchored),
            0, os.getpid(), time.time(),
        )
        return cls(shm, owner=True, events=events)

    @classmethod
    def attach(cls, name: str, events=None) -> "SnapshotPlane":
        """Map an existing plane (reader side)."""
        return cls(_attach_segment(name), owner=False, events=events)

    @property
    def name(self) -> str:
        return self.shm.name

    # -- header fields ---------------------------------------------------
    def _u(self, off: int) -> int:
        return int(self._u64[off // 8])

    def _set_u(self, off: int, value: int) -> None:
        self._u64[off // 8] = np.uint64(value)

    @property
    def commit_seq(self) -> int:
        return self._u(_OFF_COMMIT_SEQ)

    @property
    def writer_pid(self) -> int:
        return self._u(_OFF_WRITER_PID)

    @property
    def wal_anchored(self) -> bool:
        return bool(struct.unpack_from("<I", self._mv, _OFF_WAL)[0])

    def writer_beat(self) -> None:
        """Stamp writer liveness (called per publish batch AND from the
        writer's idle heartbeat thread)."""
        self._f64[_OFF_WRITER_STAMP // 8] = time.time()
        self._set_u(_OFF_WRITER_PID, os.getpid())

    def writer_age_s(self) -> float:
        """Seconds since the writer last stamped the header."""
        return max(
            time.time() - float(self._f64[_OFF_WRITER_STAMP // 8]), 0.0
        )

    def writer_alive(self, max_age_s: float) -> bool:
        """Liveness as readers judge it: a recent heartbeat, or a
        writer pid that still exists (a busy writer mid-dispatch may
        miss a beat; a dead one cannot answer ``kill -0``)."""
        if self.writer_age_s() <= max_age_s:
            return True
        pid = self.writer_pid
        if pid <= 0:
            return False
        try:
            os.kill(pid, 0)
        except OSError:
            return False
        return True

    # -- worker table ----------------------------------------------------
    def _wrow(self, idx: int) -> int:
        return WORKERS_OFF + int(idx) * WORKER_ROW_BYTES

    def claim_worker(self) -> int:
        """Claim a worker-table row for this process (heartbeat +
        counters); returns the row index.  Rows whose pid is gone are
        reclaimed, so restarts do not leak the table."""
        for idx in range(MAX_WORKERS):
            row = self._wrow(idx)
            state = struct.unpack_from("<I", self._mv, row + _W_STATE)[0]
            if state:
                pid = self._u(row + _W_PID)
                alive = False
                if pid > 0:
                    try:
                        os.kill(pid, 0)
                        alive = True
                    except OSError:
                        alive = False
                if alive and pid != os.getpid():
                    continue
            # (re)claim: zero the counters, stamp pid + beat, mark live
            self._mv[row:row + WORKER_ROW_BYTES] = (
                b"\x00" * WORKER_ROW_BYTES
            )
            self._set_u(row + _W_PID, os.getpid())
            self._f64[(row + _W_BEAT) // 8] = time.time()
            struct.pack_into("<I", self._mv, row + _W_STATE, 1)
            self._worker_row = idx
            return idx
        raise RuntimeError(
            f"worker table full ({MAX_WORKERS} rows) — more reader "
            "processes than the plane supports"
        )

    def release_worker(self) -> None:
        """Mark this process's row free (clean worker shutdown)."""
        if self._worker_row is None:
            return
        row = self._wrow(self._worker_row)
        struct.pack_into("<I", self._mv, row + _W_STATE, 0)
        self._worker_row = None

    def worker_beat(self) -> None:
        if self._worker_row is not None:
            row = self._wrow(self._worker_row)
            self._f64[(row + _W_BEAT) // 8] = time.time()

    def _count(self, field_off: int, n: int = 1) -> None:
        if self._worker_row is not None:
            row = self._wrow(self._worker_row)
            self._u64[(row + field_off) // 8] += np.uint64(n)

    def count_fallback(self, n: int = 1) -> None:
        """Book a read that fell through to the writer's compute path
        (miss/stale/contended) — the cluster's degraded-read counter."""
        self._count(_W_FALLBACKS, n)

    def workers_live(self, max_age_s: float) -> int:
        """Claimed rows with a fresh heartbeat or a live pid."""
        now = time.time()
        live = 0
        for idx in range(MAX_WORKERS):
            row = self._wrow(idx)
            if not struct.unpack_from("<I", self._mv, row + _W_STATE)[0]:
                continue
            beat = float(self._f64[(row + _W_BEAT) // 8])
            if now - beat <= max_age_s:
                live += 1
                continue
            pid = self._u(row + _W_PID)
            try:
                os.kill(pid, 0)
                live += 1
            except OSError:
                pass
        return live

    def reader_counts(self) -> Dict[str, int]:
        """Aggregate hit/stale/miss/fallback counters across every
        claimed worker row (one shared-memory scan, no RPC)."""
        out = {"hits": 0, "stale": 0, "misses": 0, "fallbacks": 0}
        for idx in range(MAX_WORKERS):
            row = self._wrow(idx)
            if not struct.unpack_from("<I", self._mv, row + _W_STATE)[0]:
                continue
            out["hits"] += self._u(row + _W_HITS)
            out["stale"] += self._u(row + _W_STALE)
            out["misses"] += self._u(row + _W_MISSES)
            out["fallbacks"] += self._u(row + _W_FALLBACKS)
        return out

    # -- slot geometry ---------------------------------------------------
    def _slot_off(self, idx: int) -> int:
        return SLOTS_OFF + (idx % self.n_slots) * self.slot_bytes

    def _payload_views(self, off: int):
        views = self._views.get(off)
        if views is not None:
            return views
        names_bytes = NAME_BYTES_PER_SERIES * self.n_pad_max
        base = off + SLOT_FIXED + KEY_BYTES + names_bytes
        n = self.h * self.n_pad_max
        means = np.frombuffer(
            self.shm.buf, dtype=np.float64, count=n, offset=base
        ).reshape(self.h, self.n_pad_max)
        variances = np.frombuffer(
            self.shm.buf, dtype=np.float64, count=n, offset=base + 8 * n
        ).reshape(self.h, self.n_pad_max)
        self._views[off] = (means, variances)
        return means, variances

    # -- write (single writer process) -----------------------------------
    def publish_entries(self, entries: Iterable[SnapshotEntry],
                        commit_seq: Optional[int] = None) -> int:
        """Publish one batch of committed entries into the slot table
        (the :class:`~metran_tpu.serve.readpath.SnapshotStore` mirror
        sink).  Bumps ``commit_seq`` once per non-empty batch — the
        cross-process commit notification — and stamps the writer
        heartbeat.  Returns entries landed; entries that cannot land
        (table full past the probe limit, names blob over budget,
        series count over the plane's pad width) are dropped and
        counted, a capacity degradation that reads fall through on —
        never a torn or wrong answer."""
        landed = 0
        for entry in entries:
            if self._publish_one(entry):
                landed += 1
            else:
                self.dropped += 1
        if landed:
            self.publishes += 1
            if commit_seq is None:
                commit_seq = self.commit_seq + 1
            self._set_u(_OFF_COMMIT_SEQ, commit_seq)
            self.writer_beat()
            if self.events is not None:
                self.events.emit(
                    "snapshot_plane_publish",
                    fault_point="cluster.snapplane",
                    models=landed, commit_seq=int(commit_seq),
                )
        return landed

    def _claim_slot(self, model_id: str, key_hash: int) -> Optional[int]:
        idx = self._dir.get(model_id)
        if idx is not None:
            return idx
        tomb = None
        for i in range(PROBE_LIMIT):
            idx = (key_hash + i) % self.n_slots
            off = self._slot_off(idx)
            slot_hash = self._u(off + _S_HASH)
            key_len = struct.unpack_from(
                "<I", self._mv, off + _S_KEYLEN
            )[0]
            if slot_hash == 0:  # never used: claimable, probe ends
                self._dir[model_id] = idx if tomb is None else tomb
                return self._dir[model_id]
            if key_len == 0:  # tombstone: remember, keep probing
                if tomb is None:
                    tomb = idx
                continue
            if slot_hash == key_hash:
                key = bytes(
                    self._mv[off + SLOT_FIXED:off + SLOT_FIXED + key_len]
                )
                if key.decode("utf-8", "replace") == model_id:
                    self._dir[model_id] = idx
                    return idx
        if tomb is not None:
            self._dir[model_id] = tomb
            return tomb
        return None

    def _publish_one(self, entry: SnapshotEntry) -> bool:
        model_id = entry.model_id
        key = model_id.encode("utf-8")
        n = int(entry.means.shape[-1])
        h = int(entry.means.shape[0])
        names_blob = "\x00".join(entry.names).encode("utf-8")
        if (
            len(key) > KEY_BYTES
            or n > self.n_pad_max
            or h > self.h
            or len(names_blob) > NAME_BYTES_PER_SERIES * self.n_pad_max
        ):
            return False
        key_hash = _key_hash(model_id)
        idx = self._claim_slot(model_id, key_hash)
        if idx is None:
            return False
        off = self._slot_off(idx)
        seq_i = (off + _S_SEQ) // 8
        seq0 = int(self._u64[seq_i])
        # seqlock write: odd while the record is inconsistent
        self._u64[seq_i] = np.uint64(seq0 + 1)
        struct.pack_into(
            "<QIII", self._mv, off + _S_HASH,
            key_hash, len(key), n, len(names_blob),
        )
        self._i64[(off + _S_VERSION) // 8] = np.int64(entry.version)
        self._f64[(off + _S_PUBLISHED) // 8] = float(entry.published_at)
        self._mv[off + SLOT_FIXED:off + SLOT_FIXED + len(key)] = key
        names_off = off + SLOT_FIXED + KEY_BYTES
        self._mv[names_off:names_off + len(names_blob)] = names_blob
        means, variances = self._payload_views(off)
        means[:h, :n] = np.asarray(entry.means, np.float64)
        variances[:h, :n] = np.asarray(entry.variances, np.float64)
        self._u64[seq_i] = np.uint64(seq0 + 2)
        return True

    def forget(self, model_id: str) -> None:
        """Tombstone a model's slot (removed from service); later
        probes skip it, later claims reuse it."""
        key_hash = _key_hash(model_id)
        idx = self._claim_slot(model_id, key_hash)
        if idx is None:
            return
        off = self._slot_off(idx)
        seq_i = (off + _S_SEQ) // 8
        seq0 = int(self._u64[seq_i])
        self._u64[seq_i] = np.uint64(seq0 + 1)
        struct.pack_into("<I", self._mv, off + _S_KEYLEN, 0)
        self._u64[seq_i] = np.uint64(seq0 + 2)
        self._dir.pop(model_id, None)
        self._rcache.pop(model_id, None)

    # -- read (the cross-process hot path) -------------------------------
    def read(self, model_id: str,
             steps: int) -> Optional[SnapshotEntry]:
        """Seqlock-consistent read of the model's published entry,
        ``None`` on miss/contention/uncovered-steps (the caller falls
        through to the compute path).  The returned entry's arrays are
        COPIES — a reader must never hold views into slots the writer
        re-publishes into."""
        if steps < 1 or steps > self.prefix:
            self._count(_W_MISSES)
            return None
        cached = self._rcache.get(model_id)
        if cached is not None:
            off, key, key_hash = cached
            got = self._read_slot(off, key, key_hash, steps, model_id)
            if isinstance(got, SnapshotEntry):
                self._count(_W_HITS)
                return got
            if got == "contended":
                self._count(_W_MISSES)
                return None
            del self._rcache[model_id]  # slot moved/reclaimed: reprobe
        key = model_id.encode("utf-8")
        key_hash = _key_hash(model_id)
        for i in range(PROBE_LIMIT):
            off = self._slot_off(key_hash + i)
            got = self._read_slot(off, key, key_hash, steps, model_id)
            if got == "empty":
                break
            if got is None or got == "tombstone":
                continue
            if got == "contended":
                # bounded retries exhausted inside _read_slot: degrade
                # to a miss rather than spin under a write storm
                break
            self._rcache[model_id] = (off, key, key_hash)
            self._count(_W_HITS)
            return got
        self._count(_W_MISSES)
        return None

    def _read_slot(self, off: int, key: bytes, key_hash: int,
                   steps: int, model_id: Optional[str] = None):
        seq_i = (off + _S_SEQ) // 8
        for _ in range(READ_RETRIES):
            s1 = int(self._u64[seq_i])
            if s1 & 1:
                continue
            slot_hash, key_len, n, names_len = struct.unpack_from(
                "<QIII", self._mv, off + _S_HASH
            )
            if slot_hash == 0:
                return "empty" if int(self._u64[seq_i]) == s1 else None
            if slot_hash != key_hash:
                return None  # other key: probe on (hash is stable)
            if key_len == 0:
                return (
                    "tombstone" if int(self._u64[seq_i]) == s1 else None
                )
            stored = bytes(
                self._mv[off + SLOT_FIXED:off + SLOT_FIXED + key_len]
            )
            version = int(self._i64[(off + _S_VERSION) // 8])
            published = float(self._f64[(off + _S_PUBLISHED) // 8])
            names_off = off + SLOT_FIXED + KEY_BYTES
            names_blob = bytes(
                self._mv[names_off:names_off + names_len]
            )
            means_v, vars_v = self._payload_views(off)
            means = np.array(means_v[:steps, :n])
            variances = np.array(vars_v[:steps, :n])
            if int(self._u64[seq_i]) != s1:
                continue  # torn copy detected: retry
            if stored != key:
                return None
            if names_len:
                names = self._names_cache.get(names_blob)
                if names is None:
                    names = tuple(
                        names_blob.decode("utf-8", "replace")
                        .split("\x00")
                    )
                    if len(self._names_cache) < 4096:  # bounded memo
                        self._names_cache[names_blob] = names
            else:
                names = tuple(f"s{j}" for j in range(n))
            return SnapshotEntry(
                model_id=(
                    key.decode("utf-8") if model_id is None
                    else model_id
                ),
                version=version,
                means=means, variances=variances, names=names,
                published_at=published,
            )
        self._count(_W_STALE)
        return "contended"

    # -- introspection ---------------------------------------------------
    def entries(self) -> int:
        """Live (non-tombstoned) slots — an O(n_slots) scan, for
        telemetry only."""
        count = 0
        for idx in range(self.n_slots):
            off = self._slot_off(idx)
            slot_hash, key_len = struct.unpack_from(
                "<QI", self._mv, off + _S_HASH
            )
            if slot_hash and key_len:
                count += 1
        return count

    def stats(self, heartbeat_s: float = 2.0) -> Dict[str, object]:
        counts = self.reader_counts()
        return {
            "commit_seq": self.commit_seq,
            "writer_pid": self.writer_pid,
            "writer_age_s": round(self.writer_age_s(), 3),
            "workers_live": self.workers_live(3.0 * heartbeat_s),
            "entries": self.entries(),
            "publishes": self.publishes,
            "dropped": self.dropped,
            **{f"reader_{k}": v for k, v in counts.items()},
        }

    # -- lifecycle -------------------------------------------------------
    def close(self, unlink: Optional[bool] = None) -> None:
        """Drop this mapping; the owner also unlinks the segment (pass
        ``unlink=False`` to keep it — e.g. a writer handing off to a
        recovery successor)."""
        self.release_worker()
        # numpy views pin the exported buffer; drop them before close
        self._u64 = self._f64 = self._i64 = None
        self._views.clear()
        self._mv.release()
        try:
            self.shm.close()
        except OSError:  # pragma: no cover - already closed
            pass
        if unlink is None:
            unlink = self.owner
        if unlink:
            # re-register first (idempotent set add): when a test
            # creates AND attaches in one process, the attach-side
            # unregister in _attach_segment stripped the registration
            # unlink() is about to remove, and the tracker logs a
            # KeyError for the unmatched unregister otherwise
            try:
                resource_tracker.register(
                    "/" + self.shm.name, "shared_memory"
                )
            except Exception:  # pragma: no cover - tracker internals
                pass
            try:
                self.shm.unlink()
            except FileNotFoundError:  # pragma: no cover - double close
                pass
