"""ClusterSpec: the multi-process serving plane's policy surface.

One NamedTuple spec in the DetectSpec/RobustSpec mold: defaults from
:func:`metran_tpu.config.serve_defaults`
(``METRAN_TPU_SERVE_CLUSTER{,_WORKERS,_SHM_MB,_SOCKET_DIR,
_HEARTBEAT_S}``), shipped **off**, with a :meth:`validate` that
rejects inert or broken combinations at construction instead of
letting a mis-sized plane degrade silently at 3am.  Passed as
``MetranService(cluster=ClusterSpec(...))`` on the writer side and to
:class:`~metran_tpu.cluster.frontend.ClusterFrontend` on the routing
side (docs/concepts.md "Multi-process serving").
"""

from __future__ import annotations

import os
import tempfile
from typing import NamedTuple, Optional

__all__ = ["ClusterSpec"]

#: floor on the shared segment so a misconfigured plane cannot be
#: created too small to hold even its own header + worker table
_MIN_SHM_MB = 1.0


class ClusterSpec(NamedTuple):
    """Multi-process serving topology and sizing.

    Armed (``enabled=True``) on a :class:`~metran_tpu.serve.
    MetranService`, the service creates the shared-memory snapshot
    plane and mirrors every :class:`~metran_tpu.serve.readpath.
    SnapshotStore` publication into it (the "second sink"); the same
    spec drives :class:`~metran_tpu.cluster.frontend.ClusterFrontend`,
    which spawns ONE writer process owning update dispatch, the
    ``StateArena`` and the WAL, plus ``workers`` read processes
    serving forecast hits straight from the plane.

    ``shm_mb`` is a hard budget: :meth:`validate_layout` (called with
    the actual horizon set and pad width before the segment is
    created) rejects geometries that cannot fit, because a plane too
    small for the bucket set silently drops every publish and serves
    nothing — the definition of an inert combo.

    ``heartbeat_s`` is both cadences: workers stamp their claimed
    worker-table row and the writer stamps the plane header every
    ``heartbeat_s``; liveness judgments (frontend restart of dead
    workers, reader writer-alive checks) use a 3x grace multiple.
    """

    enabled: bool = False
    workers: int = 2
    shm_mb: float = 64.0
    socket_dir: str = ""  # "" = a per-frontend tempfile directory
    heartbeat_s: float = 2.0
    #: slots in the plane's open-addressed table (models it can hold;
    #: sized ~2x the expected fleet for probe headroom).  The default
    #: geometry (1024 slots x 64 padded series over the default
    #: ``1-30`` horizon set, ~34 MB) fits the default ``shm_mb`` so
    #: that ``METRAN_TPU_SERVE_CLUSTER=1`` alone is never inert.
    slots: int = 1024
    #: widest (padded) per-model series count a slot can hold; models
    #: wider than this publish nowhere and their reads fall through —
    #: counted (``dropped``), never silent
    max_series: int = 64
    #: fleet-metrics scrape port: ``None`` defers to
    #: ``METRAN_TPU_OBS_FLEET_PORT`` (via ``obs_defaults``), ``0``
    #: ships the endpoint off, ``>0`` binds a loopback HTTP server
    #: serving ``ClusterFrontend.fleet_report()`` on that port
    fleet_port: Optional[int] = None

    @classmethod
    def from_defaults(cls) -> "ClusterSpec":
        from ..config import serve_defaults

        d = serve_defaults()
        return cls(
            enabled=bool(d["cluster"]),
            workers=int(d["cluster_workers"]),
            shm_mb=float(d["cluster_shm_mb"]),
            socket_dir=str(d["cluster_socket_dir"]),
            heartbeat_s=float(d["cluster_heartbeat_s"]),
        ).validate()

    def validate(self) -> "ClusterSpec":
        """Reject inert or broken combinations — a cluster with no
        readers, a heartbeat that never fires, or a segment too small
        to exist is paid for and silently useless."""
        if not self.enabled:
            return self
        if self.workers < 1:
            raise ValueError(
                f"cluster workers must be >= 1, got {self.workers} — "
                "a cluster with no read workers serves nothing the "
                "single-process path would not"
            )
        if not self.heartbeat_s > 0.0:
            raise ValueError(
                f"cluster heartbeat_s must be > 0, got "
                f"{self.heartbeat_s} — liveness detection (worker "
                "restart, writer-alive checks) keys off this cadence"
            )
        if self.shm_mb < _MIN_SHM_MB:
            raise ValueError(
                f"cluster shm_mb must be >= {_MIN_SHM_MB}, got "
                f"{self.shm_mb} — the plane header and worker table "
                "alone need real space, and a plane that cannot hold "
                "the bucket set drops every publish"
            )
        if self.slots < 1:
            raise ValueError(
                f"cluster slots must be >= 1, got {self.slots}"
            )
        if self.max_series < 1:
            raise ValueError(
                f"cluster max_series must be >= 1, got "
                f"{self.max_series}"
            )
        if self.fleet_port is not None and not (
            0 <= int(self.fleet_port) <= 65535
        ):
            raise ValueError(
                f"cluster fleet_port must be 0 (off) or a valid TCP "
                f"port, got {self.fleet_port}"
            )
        if self.socket_dir and not os.path.isdir(self.socket_dir):
            raise ValueError(
                f"cluster socket_dir {self.socket_dir!r} does not "
                "exist — frontends and workers rendezvous on unix "
                "sockets under it"
            )
        return self

    def validate_layout(self, horizons,
                        n_pad_max: Optional[int] = None) -> "ClusterSpec":
        """Check the plane geometry the service will actually create
        fits ``shm_mb`` (the shm-too-small-for-the-bucket-set reject).
        Called with the resolved horizon set — and the widest padded
        series count when it differs from ``max_series`` — before any
        segment exists."""
        self.validate()
        if not self.enabled:
            return self
        if n_pad_max is None:
            n_pad_max = self.max_series
        from .snapplane import plane_bytes

        need = plane_bytes(horizons, n_pad_max, self.slots)
        budget = int(self.shm_mb * 1024 * 1024)
        if need > budget:
            raise ValueError(
                f"cluster shm_mb={self.shm_mb} cannot hold the "
                f"configured bucket set: {self.slots} slots x "
                f"{n_pad_max} padded series over the horizon set "
                f"need {need / 1e6:.1f} MB; raise "
                "METRAN_TPU_SERVE_CLUSTER_SHM_MB or shrink "
                "METRAN_TPU_SERVE_HORIZONS"
            )
        return self

    def resolve_fleet_port(self) -> int:
        """The fleet-scrape port to bind, ``0`` meaning off: the
        spec's explicit ``fleet_port`` when set, else the
        ``METRAN_TPU_OBS_FLEET_PORT`` env default."""
        if self.fleet_port is not None:
            return int(self.fleet_port)
        from ..config import obs_defaults

        return int(obs_defaults()["fleet_port"])

    def resolve_socket_dir(self) -> str:
        """The rendezvous directory, creating a private one when the
        spec leaves it to us."""
        if self.socket_dir:
            return self.socket_dir
        return tempfile.mkdtemp(prefix="metran_cluster_")
