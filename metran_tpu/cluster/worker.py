"""Cluster read workers: shared-memory forecast serving processes.

Each worker attaches the writer's :class:`~metran_tpu.cluster.
snapplane.SnapshotPlane`, claims a worker-table row (heartbeat +
hit/stale/miss/fallback counters the frontend aggregates with one
shared-memory scan), and answers ``forecast`` RPCs from the frontend
with a plane probe — **zero device traffic, zero writer locks, zero
GIL shared with the writer**.  A miss, stale (seqlock-contended) or
uncovered-horizon read falls through to the writer over its unix
socket exactly like today's single-process compute fallback, counted
(``reader_fallback`` event, plane fallback counter) but never failed:
contention and capacity degrade to fallthrough, never to a wrong or
refused answer.

The ``read_loop`` op is the bench harness's measurement surface: the
paired ``--phase serve-cluster`` methodology needs each worker's
tight in-process reads/s (the quantity that scales with processes),
not socket round-trips — one RPC triggers N plane reads and returns
the count and elapsed wall, so the per-call IPC cost amortizes out of
the measurement exactly like the single-process bench loops.
"""

from __future__ import annotations

import os
import threading
import traceback
from logging import getLogger
from time import perf_counter
from typing import Optional

from ..obs.fleet import ChildTelemetry
from .ipc import RpcClient, RpcServer
from .snapplane import SnapshotPlane

logger = getLogger(__name__)

__all__ = ["ReadWorker", "worker_main"]


class ReadWorker:
    """One read process's serving state (plane view + writer client).

    ``observability`` is the worker's own bundle (each read process is
    a fleet lane of its own — metrics registry, event ring, optional
    tracer); when given, it supplies ``events`` unless one was passed
    explicitly, and arms the ``telemetry`` RPC op plus traced-RPC
    re-attachment on the server.
    """

    def __init__(self, plane_name: str, socket_path: str,
                 writer_socket: str, heartbeat_s: float = 2.0,
                 events=None, observability=None):
        self.obs = observability
        if events is None and observability is not None:
            events = observability.events
        self.plane = SnapshotPlane.attach(plane_name, events=events)
        self.plane.claim_worker()
        self.heartbeat_s = heartbeat_s
        self.events = events
        self._writer = RpcClient(writer_socket)
        self._shutdown = threading.Event()
        self._telemetry = ChildTelemetry(observability, "worker")
        self.rpc = RpcServer(socket_path, {
            "ping": lambda _p: "pong",
            "forecast": self._forecast,
            "read_loop": self._read_loop,
            "stats": lambda _p: self.plane.stats(
                heartbeat_s=self.heartbeat_s
            ),
            "telemetry": self._telemetry.collect,
            "shutdown": lambda _p: self._shutdown.set(),
        }, tracer=getattr(observability, "tracer", None))

    def _forecast(self, payload):
        """One forecast read: plane hit, else writer fallthrough."""
        model_id = payload["model_id"]
        steps = int(payload["steps"])
        entry = self.plane.read(model_id, steps)
        if entry is not None:
            # late import: Forecast lives in serve.service, and a read
            # worker should not pay the full service import just to
            # name the result type at module load
            from ..serve.service import Forecast

            return Forecast(
                means=entry.means[:steps],
                variances=entry.variances[:steps],
                names=entry.names,
                version=entry.version,
            )
        self.plane.count_fallback()
        if self.events is not None:
            self.events.emit(
                "reader_fallback", model_id=model_id,
                fault_point="cluster.worker", steps=steps,
            )
        return self._writer.call(
            "forecast", {"model_id": model_id, "steps": steps}
        )

    def _read_loop(self, payload):
        """Bench surface: ``iters`` tight plane reads over a model
        cycle, in-process.  Returns hit/fallback counts + elapsed."""
        model_ids = payload["model_ids"]
        steps = int(payload["steps"])
        iters = int(payload["iters"])
        plane = self.plane
        n_models = len(model_ids)
        hits = 0
        t0 = perf_counter()
        for i in range(iters):
            if plane.read(model_ids[i % n_models], steps) is not None:
                hits += 1
        elapsed = perf_counter() - t0
        return {"iters": iters, "hits": hits, "elapsed_s": elapsed,
                "pid": os.getpid()}

    def serve(self) -> None:
        """Heartbeat loop until shutdown (RPC runs on daemon threads)."""
        while not self._shutdown.wait(self.heartbeat_s):
            self.plane.worker_beat()

    def close(self) -> None:
        self.rpc.close()
        self._writer.close()
        self.plane.close(unlink=False)


def worker_main(plane_name: str, socket_path: str, writer_socket: str,
                heartbeat_s: float = 2.0,
                ready_path: Optional[str] = None) -> int:
    """Process entry (spawn-friendly module-level function).

    Builds the worker's own ``Observability.default()`` bundle (env
    knobs crossed the spawn via ``os.environ``), so every read process
    is a first-class fleet-telemetry lane.
    """
    from ..obs import Observability

    worker = None
    obs = Observability.default()
    try:
        worker = ReadWorker(
            plane_name, socket_path, writer_socket,
            heartbeat_s=heartbeat_s, observability=obs,
        )
        if ready_path:
            tmp = f"{ready_path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(str(os.getpid()))
            os.replace(tmp, ready_path)
        worker.serve()
        return 0
    except Exception:
        logger.error("read worker failed:\n%s", traceback.format_exc())
        return 1
    finally:
        if worker is not None:
            try:
                worker.close()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
        if obs.events is not None:
            obs.events.close()
