"""The cluster's single writer process.

The multi-process split keeps ONE owner for everything that mutates:
update dispatch, the device ``StateArena``, the WAL.  This module is
that process.  It hosts a full :class:`~metran_tpu.serve.
MetranService` constructed with ``cluster=ClusterSpec(...)`` — which
makes the service create the shared-memory :class:`~metran_tpu.
cluster.snapplane.SnapshotPlane` and mirror every committed
publication into it — and exposes the cold paths over a unix-socket
:class:`~metran_tpu.cluster.ipc.RpcServer`: update routing from the
frontend, reader fallthrough on miss/stale, registration and admin.
The hot read path never arrives here; that is the point.

Because the writer's group-commit stream is already serialized, the
WAL frame IS the cross-process commit notification: the plane header's
``commit_seq`` advances with each publish batch at the same boundary
the WAL frames are cut, so readers learn liveness and publication
progress from shared memory without a second protocol — and a killed
writer restarts through the service's existing
:meth:`~metran_tpu.serve.MetranService.recover` replay with no
acked-commit loss (the frontend's ``restart_writer``).

The process entry (:func:`writer_main`) is spawn-friendly: the
frontend passes a picklable module-level ``service_factory(spec,
recovering, *args)`` that builds the service inside THIS process (jax
state, device buffers and WAL handles must never cross a fork).
"""

from __future__ import annotations

import os
import threading
import traceback
from logging import getLogger
from typing import Callable, Optional, Tuple

import numpy as np

from ..obs.fleet import ChildTelemetry
from .ipc import RpcServer
from .spec import ClusterSpec

logger = getLogger(__name__)

__all__ = ["WriterHost", "writer_main"]


def _to_host(obj):
    """Device arrays -> host numpy across an arbitrary result pytree,
    so RPC replies never try to pickle live device buffers."""
    import jax

    def leaf(x):
        if hasattr(x, "device_buffer") or type(x).__module__.startswith(
            ("jaxlib", "jax")
        ):
            return np.asarray(x)
        return x

    return jax.tree_util.tree_map(leaf, obj)


class WriterHost:
    """The RPC surface wrapped around the writer's ``MetranService``.

    Ops mirror the service API the frontend preserves: ``update`` /
    ``forecast`` / ``flush`` / ``put`` / ``meta`` / ``capacity_report``
    / ``stats``; ``hello`` hands readers the plane's segment name;
    ``telemetry`` serves this process's fleet-observability part
    (metrics/events/spans + clock anchor, obs/fleet.py).
    Exceptions cross the socket as objects and re-raise frontend-side,
    so breaker/deadline/gate semantics survive the split.
    """

    def __init__(self, service, spec: ClusterSpec, socket_path: str):
        self.service = service
        self.spec = spec
        self.plane = getattr(service, "cluster_plane", None)
        if self.plane is None:
            raise ValueError(
                "writer service has no snapshot plane — construct it "
                "with cluster=ClusterSpec(enabled=True)"
            )
        self._shutdown = threading.Event()
        self._telemetry = ChildTelemetry(
            getattr(service, "obs", None), "writer"
        )
        self.rpc = RpcServer(
            socket_path, self._handlers(),
            tracer=getattr(service, "tracer", None),
        )

    def _handlers(self) -> dict:
        svc = self.service
        return {
            "hello": self._hello,
            "ping": lambda _p: "pong",
            "update": lambda p: _to_host(
                svc.update(p["model_id"], p["new_obs"])
            ),
            "forecast": lambda p: _to_host(
                svc.forecast(p["model_id"], p["steps"])
            ),
            "put": lambda p: svc.registry.put(
                p["state"], persist=p.get("persist", False)
            ),
            "meta": lambda p: _to_host(svc.registry.meta(p["model_id"])),
            "flush": lambda _p: svc.flush(),
            "capacity_report": lambda _p: svc.capacity_report(),
            "stats": lambda _p: self.plane.stats(
                heartbeat_s=self.spec.heartbeat_s
            ),
            "repl_attach": self._repl_attach,
            "repl_status": self._repl_status,
            "telemetry": self._telemetry.collect,
            "shutdown": lambda _p: self._shutdown.set(),
        }

    def _repl_attach(self, payload) -> dict:
        """Attach a standby to this writer's replication hub (the
        frontend's ``attach_standby`` lands here — membership belongs
        to the process that owns the ship path)."""
        hub = getattr(self.service, "repl_hub", None)
        if hub is None:
            raise RuntimeError(
                "writer service has no replication hub armed — "
                "construct it with replication=ReplicationSpec("
                "enabled=True, ...) and a WAL"
            )
        return hub.add_standby(
            payload["socket_path"], name=payload.get("name")
        )

    def _repl_status(self, _payload) -> dict:
        hub = getattr(self.service, "repl_hub", None)
        if hub is None:
            return {"enabled": False, "replicas": 0}
        out = hub.status()
        out["enabled"] = True
        return out

    def _hello(self, _payload) -> dict:
        return {
            "plane": self.plane.name,
            "pid": os.getpid(),
            "heartbeat_s": self.spec.heartbeat_s,
        }

    def serve(self) -> None:
        """Block in the idle-heartbeat loop until ``shutdown`` arrives.
        Publishes already stamp the plane header; this keeps
        ``writer_age_s`` fresh through quiet stretches so reader
        liveness checks do not need publish traffic."""
        while not self._shutdown.wait(self.spec.heartbeat_s):
            self.plane.writer_beat()

    def close(self) -> None:
        self.rpc.close()


def writer_main(
    spec: ClusterSpec,
    socket_path: str,
    service_factory: Callable,
    factory_args: Tuple = (),
    recovering: bool = False,
    ready_path: Optional[str] = None,
) -> int:
    """Process entry: build the service, serve RPC until shutdown.

    ``service_factory(spec, recovering, *factory_args)`` returns the
    :class:`~metran_tpu.serve.MetranService`; ``recovering=True`` is
    set when the frontend respawns a writer after a crash, so the
    factory routes through ``MetranService.recover`` (WAL replay) —
    construction vs recovery is the factory's policy, not ours.

    Writes ``ready_path`` (when given) once RPC is up — the spawn
    barrier the frontend waits on instead of polling the socket.
    """
    service = None
    host = None
    try:
        service = service_factory(spec, recovering, *factory_args)
        host = WriterHost(service, spec, socket_path)
        if ready_path:
            tmp = f"{ready_path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(str(os.getpid()))
            os.replace(tmp, ready_path)
        host.serve()
        return 0
    except Exception:
        logger.error("writer process failed:\n%s", traceback.format_exc())
        return 1
    finally:
        if host is not None:
            try:
                host.close()
            except Exception:  # pragma: no cover - teardown best-effort
                pass
        if service is not None:
            try:
                service.close()
            except Exception:  # pragma: no cover - teardown best-effort
                logger.exception("writer service close failed")
