"""Precision / platform policy.

The reference computes everything in float64 (``metran/kalmanfilter.py:
307-312``) and the parity bar is 1e-6 on the log-likelihood (BASELINE.md).
On CPU we therefore enable JAX x64 and run the filter in float64.  On TPU,
float64 is emulated and slow; the fleet/bench paths use float32 state with
the same algorithms (validated against the f64 CPU path), so precision is a
per-call dtype choice, not a global flag.
"""

from __future__ import annotations

import os

import jax


def enable_x64(enable: bool = True) -> None:
    """Toggle float64 support process-wide (safe to call at any time)."""
    jax.config.update("jax_enable_x64", bool(enable))


def default_dtype():
    """float64 when x64 is enabled (CPU/parity), else float32 (TPU)."""
    import jax.numpy as jnp

    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


if os.environ.get("METRAN_TPU_X64", "").lower() in ("1", "true", "yes"):
    enable_x64(True)
