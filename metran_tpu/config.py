"""Precision / platform policy.

The reference computes everything in float64 (``metran/kalmanfilter.py:
307-312``) and the parity bar is 1e-6 on the log-likelihood (BASELINE.md).
Policy:

- **CPU backend**: float64, enabled automatically the first time a model
  is constructed (``ensure_precision``), matching the reference bit-for-bit
  semantics.
- **TPU backend**: float64 is emulated and slow, so the default stays
  float32; the fleet/bench paths use float32 state with the same
  algorithms, validated against the f64 CPU path.

Set ``METRAN_TPU_X64=1`` to force x64 regardless of backend, or call
``enable_x64(False)`` after import to opt out.
"""

from __future__ import annotations

import os
from logging import getLogger

import jax

logger = getLogger(__name__)

_precision_checked = False


def enable_x64(enable: bool = True) -> None:
    """Toggle float64 support process-wide (safe to call at any time)."""
    jax.config.update("jax_enable_x64", bool(enable))


def ensure_precision() -> None:
    """Enable x64 on CPU backends (once); leave accelerators at f32.

    Called by model construction so that plain `Metran(series).solve()`
    on CPU reproduces the float64 reference to the documented parity bar
    without any configuration.
    """
    global _precision_checked
    if _precision_checked or jax.config.jax_enable_x64:
        _precision_checked = True
        return
    _precision_checked = True
    if jax.default_backend() == "cpu":
        logger.info("CPU backend detected: enabling float64 (reference parity).")
        enable_x64(True)


def default_dtype():
    """float64 when x64 is enabled (CPU/parity), else float32 (TPU)."""
    import jax.numpy as jnp

    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def is_accelerator() -> bool:
    """True when the default JAX backend is an accelerator (TPU/GPU).

    Drives backend-aware defaults: on an accelerator ``Metran`` picks the
    batched-update filter engine and the on-device ``JaxSolve`` solver so
    a naive ``Metran(series).solve()`` stays on device; on CPU the
    reference-parity defaults (sequential engine, ``ScipySolve``) apply.
    """
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # backend init failure: fall back to CPU behavior
        return False


if os.environ.get("METRAN_TPU_X64", "").lower() in ("1", "true", "yes"):
    enable_x64(True)
