"""Precision / platform policy.

The reference computes everything in float64 (``metran/kalmanfilter.py:
307-312``) and the parity bar is 1e-6 on the log-likelihood (BASELINE.md).
Policy:

- **CPU backend**: float64, enabled automatically the first time a model
  is constructed (``ensure_precision``), matching the reference bit-for-bit
  semantics.
- **TPU backend**: float64 is emulated and slow, so the default stays
  float32; the fleet/bench paths use float32 state with the same
  algorithms, validated against the f64 CPU path.

**Cap-regime exemption (measured, tests/test_precision.py) — and its
square-root repeal.**  f32 meets the 1e-6 deviance parity bar in every
*interior* alpha regime (worst measured rel. error 1.7e-7, i.e. >=5.8x
margin).  The one exemption is the degenerate near-unit-root boundary
``alpha ~ 3e4`` (``phi = 0.99997``): there the covariance-form engines
carry a measured 1.4e-6 residual, and the cap regime gets its own
10x-headroom bar.  The earlier reading of that residual as a
representation floor (``|dev| * eps_f32 * O(sqrt(T))``) turned out
pessimistic: the QR square-root engine (``engine="sqrt"``,
ops/kalman.py) measures 4.7e-8 in the SAME regime at the same dtype —
30x better, meeting the uncapped interior bars everywhere — so the
error was algorithmic (covariance differencing + Cholesky of a
near-singular innovation), not representational.  The covariance
engines keep their capped bar; the sqrt engine carries uncapped bars
(tests/test_precision.py::check_f32_sqrt) and is the accelerator
default for ``Metran``.  The fleet solver's soft alpha cap
(``parallel/fleet.py::_soft_cap``) remains: the regime is still
flat/degenerate for *optimization* whatever the engine.

Set ``METRAN_TPU_X64=1`` to force x64 regardless of backend, or call
``enable_x64(False)`` after import to opt out.
"""

from __future__ import annotations

import os
from logging import getLogger

import jax

logger = getLogger(__name__)

_precision_checked = False


def enable_x64(enable: bool = True) -> None:
    """Toggle float64 support process-wide (safe to call at any time)."""
    jax.config.update("jax_enable_x64", bool(enable))


def ensure_precision() -> None:
    """Enable x64 on CPU backends (once); leave accelerators at f32.

    Called by model construction so that plain `Metran(series).solve()`
    on CPU reproduces the float64 reference to the documented parity bar
    without any configuration.
    """
    global _precision_checked
    if _precision_checked or jax.config.jax_enable_x64:
        _precision_checked = True
        return
    _precision_checked = True
    if jax.default_backend() == "cpu":
        logger.info("CPU backend detected: enabling float64 (reference parity).")
        enable_x64(True)


def default_dtype():
    """float64 when x64 is enabled (CPU/parity), else float32 (TPU)."""
    import jax.numpy as jnp

    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def is_accelerator() -> bool:
    """True when the default JAX backend is an accelerator (TPU/GPU).

    Drives backend-aware defaults: on an accelerator ``Metran`` picks the
    batched-update filter engine and the on-device ``JaxSolve`` solver so
    a naive ``Metran(series).solve()`` stays on device; on CPU the
    reference-parity defaults (sequential engine, ``ScipySolve``) apply.
    """
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # backend init failure: fall back to CPU behavior
        return False


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions.

    The top-level ``jax.shard_map`` (with ``check_vma``) only exists
    from jax 0.6; earlier versions ship it as
    ``jax.experimental.shard_map.shard_map`` with the equivalent switch
    named ``check_rep``.  One wrapper so the sharded filter and the
    explicit-SPMD fleet path run on both.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


# ----------------------------------------------------------------------
# gradient engine (fit paths)
# ----------------------------------------------------------------------
# How fits differentiate the filter deviance (docs/concepts.md
# "Gradient engine"):
#
# - "adjoint": the closed-form Kalman-score VJP (ops/adjoint.py for the
#   batch-leading sequential/joint/sqrt engines, the lanes kernel's
#   analytical score for layout="lanes") — one cheap covariance-form
#   reverse sweep, no autodiff through QR/Cholesky, near-flat backward
#   memory in T;
# - "autodiff": reverse-mode autodiff through the filter scan (the only
#   mode that produces gradients w.r.t. loadings/observations);
# - "auto" (default): adjoint wherever it is defined, autodiff for the
#   associative-scan engines.
GRAD_ENGINE = "auto"
GRAD_ENGINES = ("auto", "adjoint", "autodiff")


def grad_engine(value=None) -> str:
    """Validated gradient-engine mode (``METRAN_TPU_GRAD_ENGINE``).

    ``value`` overrides the environment when given.  Unknown values
    RAISE — a typo'd engine name must not silently fall back to a
    different gradient path (the two differ in cost, memory and
    differentiable inputs).
    """
    if value is None:
        value = os.environ.get("METRAN_TPU_GRAD_ENGINE") or GRAD_ENGINE
    v = str(value).strip().lower()
    if v not in GRAD_ENGINES:
        raise ValueError(
            f"unknown gradient engine {value!r} (from "
            "METRAN_TPU_GRAD_ENGINE or an explicit grad_engine "
            f"argument); expected one of {GRAD_ENGINES}"
        )
    return v


# ----------------------------------------------------------------------
# serving defaults (metran_tpu.serve)
# ----------------------------------------------------------------------
SERVE_FLUSH_DEADLINE_S = 0.005  # micro-batch coalescing window
SERVE_MAX_BATCH = 256  # a batch this full dispatches immediately
SERVE_BUCKET_MULTIPLE = 8  # shape-bucket rounding for (n_series, n_state)
SERVE_MAX_COMPILED = 32  # LRU capacity for compiled serve kernels
# reliability defaults (metran_tpu.reliability wired into MetranService)
SERVE_REQUEST_DEADLINE_S = 30.0  # hard cap on any sync service call
SERVE_RETRY_ATTEMPTS = 2  # total attempts for transient failures
SERVE_RETRY_BACKOFF_S = 0.02  # first-retry backoff (doubles per retry)
SERVE_BREAKER_FAILURES = 5  # consecutive failures that open a breaker
SERVE_BREAKER_COOLDOWN_S = 30.0  # open -> half-open probe window
SERVE_VALIDATE_UPDATES = 1  # per-slot posterior finiteness/PSD checks
SERVE_ENGINE = "joint"  # assimilation kernel; "sqrt" = square-root
#                         serving (factored posteriors, PSD by
#                         construction — the robust f32 choice)
# device-resident state arena (docs/concepts.md "Scale & sharding").
# OFF by default: the arena changes the durability contract (updates
# persist on spill/checkpoint, not per request) and the update() return
# type (a lightweight ack instead of a materialized PosteriorState), so
# arming it is a deployment decision.
SERVE_ARENA = 0  # 1 = serve from device-resident sharded state arenas
SERVE_ARENA_ROWS = 1024  # per-bucket arena capacity (rows preallocated)
SERVE_ARENA_MESH = 0  # devices to shard each arena across (0 = single
#                       device / no mesh; -1 = every visible device)
# materialized forecast read path (docs/concepts.md "Read path &
# caching").  OFF by default like the arena: the cache trades update
# cost (one fused horizon pass per commit) for lock-free µs-scale
# reads, and arming it is a deployment decision.  Results are
# bit-identical to the compute path at matching version (f64), so the
# switch changes economics, not answers.
SERVE_READPATH = 0  # 1 = serve forecasts from commit-time snapshots
SERVE_HORIZONS = "1-30"  # horizon set precomputed at commit time
#                          ("1-30", "1,7,30", "1-14,30" all parse)
# observation-gate defaults (statistical input robustness; see
# docs/concepts.md "Input robustness").  The gate ships OFF: arming it
# is a per-deployment calibration decision (nsigma trades false
# rejections of real level shifts against spike protection).
SERVE_GATE_POLICY = "off"  # "reject" | "huber" | "inflate" | "off"
SERVE_GATE_NSIGMA = 4.0  # gate at z^2 > nsigma^2 (chi-square(1) null)
SERVE_GATE_MIN_SEEN = 32  # disarm models with t_seen below this (cold
#                           filters' innovations are over-dispersed)
# non-Gaussian observation robustness: implicit-MAP update engine for
# censored / quantized / heavy-tailed sensors (docs/concepts.md
# "Non-Gaussian observations").  Ships OFF: arming it is a
# per-deployment sensor-model decision (rails and quanta describe the
# physical logger), and the robust spec is mutually exclusive with an
# enabled observation gate (the likelihood IS the outlier treatment).
SERVE_ROBUST = 0  # 1 = arm the implicit-MAP robust update path
SERVE_ROBUST_LIKELIHOOD = "censored"  # "censored" | "quantized" |
#                                       "huber_t" (| "gaussian": the
#                                       exact kernel, for pinning)
SERVE_ROBUST_RAIL_LO = float("-inf")  # low saturation rail, data units
SERVE_ROBUST_RAIL_HI = float("inf")  # high saturation rail, data units
SERVE_ROBUST_QUANTUM = 0.0  # quantization cell width, data units
SERVE_ROBUST_NU = 4.0  # Student-t degrees of freedom (huber_t; > 2)
SERVE_ROBUST_SCALE = 0.05  # sensor-noise scale in STANDARDIZED units
#                            (smooths the censored/quantized
#                            likelihoods; the DFM's r = 0 channel is a
#                            hard indicator without it)
SERVE_ROBUST_MIN_SEEN = 32  # disarm models below this t_seen (cold
#                             filters' innovations are over-dispersed)
# steady-state (frozen-gain) serving defaults (docs/concepts.md
# "Bounded-cost serving").  Ships OFF (tol = 0.0): freezing trades a
# bounded, measured posterior deviation (within the freeze tolerance)
# for ≥2x update throughput, and that trade is a deployment decision.
SERVE_STEADY_TOL = 0.0  # freeze when the posterior factor moves <= tol
#                         across a fully-observed append (0 disables)
SERVE_STEADY_MIN_SEEN = 256  # assimilated-steps floor before freezing
# fixed-lag smoothed products (MetranService.smoothed): window length
# in grid steps; 0 disables tracking (the rolling anchor costs one
# O(k) replay kernel per commit once armed).
SERVE_FIXED_LAG = 0
# online monitoring: streaming anomaly / changepoint / autocorrelation
# -drift detection fused into the update kernels, with alerting and
# changepoint-triggered refits (docs/concepts.md "Online monitoring").
# Ships OFF: arming it selects the gated (z-score-emitting) kernel
# variants and adds per-slot detector state, and the thresholds are a
# per-deployment calibration (false-alarm rate vs detection delay).
SERVE_DETECT = 0  # 1 = arm streaming detection + alerting
SERVE_DETECT_CUSUM_K = 0.5  # CUSUM reference value (innovation sigmas;
#                             tuned for shifts of ~2k sigmas)
SERVE_DETECT_CUSUM_H = 12.0  # CUSUM alarm threshold (delay ~ h/(d-k)
#                              steps for a d-sigma shift; false-alarm
#                              ARL grows exponentially in h)
SERVE_DETECT_LB_WINDOW = 64  # effective window of the autocorrelation
#                              -drift recursion (must exceed the lag, 1)
SERVE_DETECT_LB_THRESH = 25.0  # autocorrelation-drift alarm bar on the
#                                chi-square(1) statistic (25 = 5 sigma)
SERVE_DETECT_NSIGMA = 5.0  # per-observation anomaly bar (z^2 > nsigma^2)
SERVE_DETECT_MIN_SEEN = 64  # disarm models below this t_seen (cold
#                             filters' innovations are over-dispersed)
SERVE_DETECT_ALERT_COOLDOWN_S = 60.0  # alert raise/clear hysteresis
#                                       window (seconds)
# continuous adaptation: background refit + champion/challenger
# promotion (docs/concepts.md "Continuous adaptation").  Ships OFF:
# arming it spends fit compute on serving hosts and lets the service
# replace its own parameters, both deployment decisions.
SERVE_REFIT = 0  # 1 = run the background RefitWorker inside the service
SERVE_REFIT_INTERVAL_S = 30.0  # scan cadence of the background thread
SERVE_REFIT_TAIL = 256  # observation rows retained per model
SERVE_REFIT_HOLDOUT = 32  # held-out rows for the shadow comparison
SERVE_REFIT_MIN_TAIL = 64  # candidates need at least this many rows
SERVE_REFIT_MAX_BATCH = 32  # candidates refit per cycle
SERVE_REFIT_MAXITER = 40  # L-BFGS iterations per refit
SERVE_REFIT_MARGIN = 0.0  # challenger must beat champion held-out
#                           deviance by this much to promote
SERVE_REFIT_STALENESS_OBS = 0  # refit after this many obs since last
#                                fit (0 = degradation-triggered only)
SERVE_REFIT_STALENESS_AGE_S = 0.0  # ... or this many seconds (0 = off)
SERVE_REFIT_COOLDOWN_S = 60.0  # hysteresis after any refit outcome
SERVE_REFIT_DEADLINE_S = 120.0  # fit wall-clock budget per cycle;
#                                 an overrun rejects (champion keeps
#                                 serving) instead of promoting late
# crash-safe durability plane (serve.durability; docs/concepts.md
# "Durability & recovery").  Ships OFF: the WAL adds one group-synced
# append per update dispatch (measured <= 10% on the arena bulk path,
# bench.py --phase durability) and checkpoints spend disk, both
# deployment decisions.  Armed, every acked update is durable before
# its ack and MetranService.recover() reconstructs acked state
# bit-identically at f64.
SERVE_WAL = 0  # 1 = per-commit write-ahead log + checkpoints
SERVE_WAL_DIR = ""  # WAL directory ("" = <registry root>/wal)
SERVE_WAL_FSYNC = 1  # group fdatasync before each dispatch's acks
#                      (0 = OS page cache only: survives process
#                      death, not power loss)
SERVE_WAL_CHECKPOINT_EVERY = 1024  # auto-checkpoint cadence in logged
#                                    commits (0 = manual only)
# multi-process serving plane (metran_tpu.cluster; docs/concepts.md
# "Multi-process serving").  Ships OFF: the split spawns a writer
# process plus read workers and maps a shared-memory snapshot plane —
# a process-topology decision, not a library default.  Armed, ONE
# writer owns update dispatch / StateArena / WAL while N workers
# serve forecast hits from the seqlock plane with zero writer locks.
SERVE_CLUSTER = 0  # 1 = multi-process serving (ClusterFrontend)
SERVE_CLUSTER_WORKERS = 2  # read-worker processes (>= 1)
SERVE_CLUSTER_SHM_MB = 64.0  # shared snapshot-plane budget; validated
#                              against the horizon set x slot count
#                              at construction (too small = rejected)
SERVE_CLUSTER_SOCKET_DIR = ""  # unix-socket rendezvous dir ("" = a
#                                private per-frontend temp dir)
SERVE_CLUSTER_HEARTBEAT_S = 2.0  # worker/writer liveness cadence
#                                  (restart + writer-alive checks use
#                                  a 3x grace multiple)
# WAL-shipped replication (metran_tpu.cluster.replication;
# docs/concepts.md "Replication & failover").  Ships OFF: every
# committed group adds one synchronous ship round-trip per standby
# before its callers ack — a topology decision (and the primary needs
# standby endpoints to ship to).  Armed, each standby holds every
# acked commit in its own log before the ack resolves, replays it
# through the recovery kernels (bit-identical at f64), and can be
# promoted with epoch fencing — the old primary can never ack again.
SERVE_REPL = 0  # 1 = ship committed WAL frames to standbys
SERVE_REPL_STANDBYS = 1  # standby endpoints the hub expects (>= 1)
SERVE_REPL_ACK_TIMEOUT_S = 30.0  # per-ship RPC round-trip budget; a
#                                  standby that cannot ack inside it
#                                  is dropped (it re-attaches and
#                                  catches up), never blocks acks
SERVE_REPL_LAG_WARN = 1024  # standby apply backlog (records) that
#                             books a replica_lag event (hysteresis:
#                             one event per excursion)
SERVE_REPL_SOCKET_DIR = ""  # standby rendezvous dir ("" = a private
#                             per-run temp dir)
# observability defaults (metran_tpu.obs wired into MetranService)
OBS_TRACE = 0  # request-scoped span tracing (metrics/events stay on)
OBS_TRACE_BUFFER = 4096  # finished spans kept in the tracer ring
OBS_EVENT_BUFFER = 2048  # reliability events kept in the log ring
OBS_EVENT_SINK = ""  # JSON-lines file sink path ("" = ring only)
OBS_EVENT_SINK_MAX_MB = 0.0  # rotate the sink to a .1 suffix past this
#                              size (0 = unbounded); only path-
#                              constructed (owned) sinks rotate
# capacity & cost plane (metran_tpu.obs.capacity; docs/concepts.md
# "Capacity & cost").  ON by default whenever metrics are on — the
# stage stamps are per-dispatch, measured <= 5% on the arena bulk path
# and 0% on cached reads (bench.py --phase capacity).
OBS_CAPACITY = 1  # 0 = no stage/SLO/cost instrumentation
OBS_CAPACITY_SAMPLE = 1  # record every Nth dispatch (sampled-subset
#                          mode for deployments where even the
#                          per-dispatch stamps matter)
OBS_SLO_MS = 50.0  # the serve-latency SLO the burn rate measures
#                    against (p99 < OBS_SLO_MS, 1% violation budget)
OBS_FLEET_PORT = 0  # fleet-metrics scrape endpoint: ClusterFrontend
#                     binds a loopback HTTP server on this port
#                     serving fleet_report() (merged multi-process
#                     Prometheus exposition); 0 = off (the default —
#                     an open port is an operator opt-in)


def _env(name, cast, default):
    """One env-var override: ``cast(value)`` when set and parsable,
    ``default`` otherwise (unparsable values warn and fall back)."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return cast(raw)
    except ValueError:
        logger.warning("ignoring unparsable %s=%r", name, raw)
        return default


def serve_defaults() -> dict:
    """Serving-layer knobs, each overridable via ``METRAN_TPU_SERVE_*``.

    ``flush_deadline_s`` trades tail latency for batch occupancy (the
    classic micro-batching dial); ``bucket_multiple`` trades padding
    FLOPs for executable reuse across heterogeneous models.  Read at
    :class:`~metran_tpu.serve.ModelRegistry` /
    :class:`~metran_tpu.serve.MetranService` construction.
    """

    return {
        "flush_deadline_s": _env(
            "METRAN_TPU_SERVE_FLUSH_DEADLINE_S", float,
            SERVE_FLUSH_DEADLINE_S,
        ),
        "max_batch": _env(
            "METRAN_TPU_SERVE_MAX_BATCH", int, SERVE_MAX_BATCH
        ),
        "bucket_multiple": _env(
            "METRAN_TPU_SERVE_BUCKET_MULTIPLE", int, SERVE_BUCKET_MULTIPLE
        ),
        "max_compiled": _env(
            "METRAN_TPU_SERVE_MAX_COMPILED", int, SERVE_MAX_COMPILED
        ),
        "request_deadline_s": _env(
            "METRAN_TPU_SERVE_DEADLINE_S", float, SERVE_REQUEST_DEADLINE_S
        ),
        "retry_attempts": _env(
            "METRAN_TPU_SERVE_RETRY_ATTEMPTS", int, SERVE_RETRY_ATTEMPTS
        ),
        "retry_backoff_s": _env(
            "METRAN_TPU_SERVE_RETRY_BACKOFF_S", float, SERVE_RETRY_BACKOFF_S
        ),
        "breaker_failures": _env(
            "METRAN_TPU_SERVE_BREAKER_FAILURES", int, SERVE_BREAKER_FAILURES
        ),
        "breaker_cooldown_s": _env(
            "METRAN_TPU_SERVE_BREAKER_COOLDOWN_S", float,
            SERVE_BREAKER_COOLDOWN_S,
        ),
        "validate_updates": _env(
            "METRAN_TPU_SERVE_VALIDATE_UPDATES", int, SERVE_VALIDATE_UPDATES
        ),
        "engine": _env(
            "METRAN_TPU_SERVE_ENGINE", str, SERVE_ENGINE
        ),
        "arena": _env(
            "METRAN_TPU_SERVE_ARENA", int, SERVE_ARENA
        ),
        "arena_rows": _env(
            "METRAN_TPU_SERVE_ARENA_ROWS", int, SERVE_ARENA_ROWS
        ),
        "arena_mesh": _env(
            "METRAN_TPU_SERVE_ARENA_MESH", int, SERVE_ARENA_MESH
        ),
        "readpath": _env(
            "METRAN_TPU_SERVE_READPATH", int, SERVE_READPATH
        ),
        "horizons": _env(
            "METRAN_TPU_SERVE_HORIZONS", str, SERVE_HORIZONS
        ),
        "gate_policy": _env(
            "METRAN_TPU_SERVE_GATE_POLICY", str, SERVE_GATE_POLICY
        ),
        "gate_nsigma": _env(
            "METRAN_TPU_SERVE_GATE_NSIGMA", float, SERVE_GATE_NSIGMA
        ),
        "gate_min_seen": _env(
            "METRAN_TPU_SERVE_GATE_MIN_SEEN", int, SERVE_GATE_MIN_SEEN
        ),
        "robust": _env(
            "METRAN_TPU_SERVE_ROBUST", int, SERVE_ROBUST
        ),
        "robust_likelihood": _env(
            "METRAN_TPU_SERVE_ROBUST_LIKELIHOOD", str,
            SERVE_ROBUST_LIKELIHOOD,
        ),
        "robust_rail_lo": _env(
            "METRAN_TPU_SERVE_ROBUST_RAIL_LO", float,
            SERVE_ROBUST_RAIL_LO,
        ),
        "robust_rail_hi": _env(
            "METRAN_TPU_SERVE_ROBUST_RAIL_HI", float,
            SERVE_ROBUST_RAIL_HI,
        ),
        "robust_quantum": _env(
            "METRAN_TPU_SERVE_ROBUST_QUANTUM", float,
            SERVE_ROBUST_QUANTUM,
        ),
        "robust_nu": _env(
            "METRAN_TPU_SERVE_ROBUST_NU", float, SERVE_ROBUST_NU
        ),
        "robust_scale": _env(
            "METRAN_TPU_SERVE_ROBUST_SCALE", float, SERVE_ROBUST_SCALE
        ),
        "robust_min_seen": _env(
            "METRAN_TPU_SERVE_ROBUST_MIN_SEEN", int,
            SERVE_ROBUST_MIN_SEEN,
        ),
        "steady_tol": _env(
            "METRAN_TPU_SERVE_STEADY_TOL", float, SERVE_STEADY_TOL
        ),
        "steady_min_seen": _env(
            "METRAN_TPU_SERVE_STEADY_MIN_SEEN", int,
            SERVE_STEADY_MIN_SEEN,
        ),
        "fixed_lag": _env(
            "METRAN_TPU_SERVE_FIXED_LAG", int, SERVE_FIXED_LAG
        ),
        "detect": _env(
            "METRAN_TPU_SERVE_DETECT", int, SERVE_DETECT
        ),
        "detect_cusum_k": _env(
            "METRAN_TPU_SERVE_DETECT_CUSUM_K", float,
            SERVE_DETECT_CUSUM_K,
        ),
        "detect_cusum_h": _env(
            "METRAN_TPU_SERVE_DETECT_CUSUM_H", float,
            SERVE_DETECT_CUSUM_H,
        ),
        "detect_lb_window": _env(
            "METRAN_TPU_SERVE_DETECT_LB_WINDOW", int,
            SERVE_DETECT_LB_WINDOW,
        ),
        "detect_lb_thresh": _env(
            "METRAN_TPU_SERVE_DETECT_LB_THRESH", float,
            SERVE_DETECT_LB_THRESH,
        ),
        "detect_nsigma": _env(
            "METRAN_TPU_SERVE_DETECT_NSIGMA", float,
            SERVE_DETECT_NSIGMA,
        ),
        "detect_min_seen": _env(
            "METRAN_TPU_SERVE_DETECT_MIN_SEEN", int,
            SERVE_DETECT_MIN_SEEN,
        ),
        "detect_alert_cooldown_s": _env(
            "METRAN_TPU_SERVE_DETECT_ALERT_COOLDOWN_S", float,
            SERVE_DETECT_ALERT_COOLDOWN_S,
        ),
        "refit": _env(
            "METRAN_TPU_SERVE_REFIT", int, SERVE_REFIT
        ),
        "refit_interval_s": _env(
            "METRAN_TPU_SERVE_REFIT_INTERVAL_S", float,
            SERVE_REFIT_INTERVAL_S,
        ),
        "refit_tail": _env(
            "METRAN_TPU_SERVE_REFIT_TAIL", int, SERVE_REFIT_TAIL
        ),
        "refit_holdout": _env(
            "METRAN_TPU_SERVE_REFIT_HOLDOUT", int, SERVE_REFIT_HOLDOUT
        ),
        "refit_min_tail": _env(
            "METRAN_TPU_SERVE_REFIT_MIN_TAIL", int, SERVE_REFIT_MIN_TAIL
        ),
        "refit_max_batch": _env(
            "METRAN_TPU_SERVE_REFIT_MAX_BATCH", int,
            SERVE_REFIT_MAX_BATCH,
        ),
        "refit_maxiter": _env(
            "METRAN_TPU_SERVE_REFIT_MAXITER", int, SERVE_REFIT_MAXITER
        ),
        "refit_margin": _env(
            "METRAN_TPU_SERVE_REFIT_MARGIN", float, SERVE_REFIT_MARGIN
        ),
        "refit_staleness_obs": _env(
            "METRAN_TPU_SERVE_REFIT_STALENESS_OBS", int,
            SERVE_REFIT_STALENESS_OBS,
        ),
        "refit_staleness_age_s": _env(
            "METRAN_TPU_SERVE_REFIT_STALENESS_AGE_S", float,
            SERVE_REFIT_STALENESS_AGE_S,
        ),
        "refit_cooldown_s": _env(
            "METRAN_TPU_SERVE_REFIT_COOLDOWN_S", float,
            SERVE_REFIT_COOLDOWN_S,
        ),
        "refit_deadline_s": _env(
            "METRAN_TPU_SERVE_REFIT_DEADLINE_S", float,
            SERVE_REFIT_DEADLINE_S,
        ),
        "cluster": _env(
            "METRAN_TPU_SERVE_CLUSTER", int, SERVE_CLUSTER
        ),
        "cluster_workers": _env(
            "METRAN_TPU_SERVE_CLUSTER_WORKERS", int,
            SERVE_CLUSTER_WORKERS,
        ),
        "cluster_shm_mb": _env(
            "METRAN_TPU_SERVE_CLUSTER_SHM_MB", float,
            SERVE_CLUSTER_SHM_MB,
        ),
        "cluster_socket_dir": os.environ.get(
            "METRAN_TPU_SERVE_CLUSTER_SOCKET_DIR",
            SERVE_CLUSTER_SOCKET_DIR,
        ),
        "cluster_heartbeat_s": _env(
            "METRAN_TPU_SERVE_CLUSTER_HEARTBEAT_S", float,
            SERVE_CLUSTER_HEARTBEAT_S,
        ),
        "repl": _env("METRAN_TPU_SERVE_REPL", int, SERVE_REPL),
        "repl_standbys": _env(
            "METRAN_TPU_SERVE_REPL_STANDBYS", int, SERVE_REPL_STANDBYS
        ),
        "repl_ack_timeout_s": _env(
            "METRAN_TPU_SERVE_REPL_ACK_TIMEOUT_S", float,
            SERVE_REPL_ACK_TIMEOUT_S,
        ),
        "repl_lag_warn": _env(
            "METRAN_TPU_SERVE_REPL_LAG_WARN", int, SERVE_REPL_LAG_WARN
        ),
        "repl_socket_dir": os.environ.get(
            "METRAN_TPU_SERVE_REPL_SOCKET_DIR", SERVE_REPL_SOCKET_DIR
        ),
        "wal": _env("METRAN_TPU_SERVE_WAL", int, SERVE_WAL),
        "wal_dir": os.environ.get(
            "METRAN_TPU_SERVE_WAL_DIR", SERVE_WAL_DIR
        ),
        "wal_fsync": _env(
            "METRAN_TPU_SERVE_WAL_FSYNC", int, SERVE_WAL_FSYNC
        ),
        "wal_checkpoint_every": _env(
            "METRAN_TPU_SERVE_WAL_CHECKPOINT_EVERY", int,
            SERVE_WAL_CHECKPOINT_EVERY,
        ),
    }


def obs_defaults() -> dict:
    """Observability knobs, each overridable via ``METRAN_TPU_OBS_*``.

    ``trace`` arms request-scoped span tracing (metrics and the event
    ring are always on — they are allocation-light; tracing adds a
    handful of timestamped records per request, so it is the one knob
    that defaults OFF).  Read at
    :meth:`metran_tpu.obs.Observability.default`.
    """

    return {
        "trace": _env("METRAN_TPU_OBS_TRACE", int, OBS_TRACE),
        "trace_buffer": _env(
            "METRAN_TPU_OBS_TRACE_BUFFER", int, OBS_TRACE_BUFFER
        ),
        "event_buffer": _env(
            "METRAN_TPU_OBS_EVENT_BUFFER", int, OBS_EVENT_BUFFER
        ),
        "event_sink": os.environ.get(
            "METRAN_TPU_OBS_EVENT_SINK", OBS_EVENT_SINK
        ),
        "event_sink_max_mb": _env(
            "METRAN_TPU_OBS_EVENT_SINK_MAX_MB", float,
            OBS_EVENT_SINK_MAX_MB,
        ),
        "capacity": _env(
            "METRAN_TPU_OBS_CAPACITY", int, OBS_CAPACITY
        ),
        "capacity_sample": _env(
            "METRAN_TPU_OBS_CAPACITY_SAMPLE", int, OBS_CAPACITY_SAMPLE
        ),
        "slo_ms": _env(
            "METRAN_TPU_OBS_SLO_MS", float, OBS_SLO_MS
        ),
        "fleet_port": _env(
            "METRAN_TPU_OBS_FLEET_PORT", int, OBS_FLEET_PORT
        ),
    }


if os.environ.get("METRAN_TPU_X64", "").lower() in ("1", "true", "yes"):
    enable_x64(True)
