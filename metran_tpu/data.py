"""Host-side data layer: ingestion, standardization, dense packing.

Parity with the reference's data handling (``metran/metran.py:102-197,
509-603``): accepts a DataFrame or list/tuple of Series/single-column
DataFrames, requires >= 2 series and a DatetimeIndex, truncates to
tmin/tmax dropping all-NaN rows, resamples to a regular grid
(``asfreq``, gaps become NaN rows), z-scores each series, and enforces a
minimum cross-sectional overlap per series.

Instead of the reference's ragged missing-data index compression
(``metran/kalmanfilter.py:646-674``), observations are packed to a dense
``(T, n_series)`` float array plus a boolean mask — the static-shape
encoding the TPU filter consumes (SURVEY.md section 7 step 1).
"""

from __future__ import annotations

from dataclasses import dataclass
from logging import getLogger
from typing import List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from .utils import freq_to_days, frequency_is_supported

logger = getLogger(__name__)


@dataclass
class Panel:
    """A standardized, regular-grid multivariate series panel.

    Attributes
    ----------
    values : (T, n_series) float array of standardized observations with
        NaNs replaced by 0 (ignored under ``mask``).
    mask : (T, n_series) bool array, True where an observation is present.
    index : the regular DatetimeIndex of the grid.
    names : series names, in column order.
    std, mean : per-series standardization constants (original units).
    dt : grid step in days.
    """

    values: np.ndarray
    mask: np.ndarray
    index: pd.DatetimeIndex
    names: List[str]
    std: np.ndarray
    mean: np.ndarray
    dt: float

    @property
    def n_series(self) -> int:
        return self.values.shape[1]

    @property
    def n_timesteps(self) -> int:
        return self.values.shape[0]


def combine_series(
    oseries: Union[pd.DataFrame, Sequence[Union[pd.Series, pd.DataFrame]]],
) -> pd.DataFrame:
    """Combine accepted input types into a single DataFrame.

    Mirrors the reference's input handling (``metran/metran.py:509-567``):
    lists/tuples of Series or single-column DataFrames are concatenated;
    unnamed series get ``Series{i+1}`` names; fewer than 2 series raises.
    Objects exposing a pandas ``.series`` attribute (duck-typed
    ``pastas.TimeSeries``, accepted by the reference at
    ``metran/metran.py:536-538``) are unwrapped, preserving drop-in
    compatibility without a pastas dependency.
    """
    if isinstance(oseries, (list, tuple)):
        collected = []
        for i, os in enumerate(oseries):
            if not isinstance(os, (pd.Series, pd.DataFrame)) and isinstance(
                getattr(os, "series", None), (pd.Series, pd.DataFrame)
            ):
                os = os.series  # pastas.TimeSeries-like wrapper
            if isinstance(os, pd.DataFrame):
                if os.shape[1] > 1:
                    msg = "One or more series have DataFrame with multiple columns"
                    logger.error(msg)
                    raise Exception(msg)
                os = os.squeeze()
            elif not isinstance(os, pd.Series):
                msg = "List elements must be pandas Series or DataFrame"
                logger.error(msg)
                raise TypeError(msg)
            if os.name is None:
                os = os.rename(f"Series{i + 1}")
            collected.append(os)
        frame = pd.concat(collected, axis=1) if len(collected) > 1 else pd.DataFrame()
    elif isinstance(oseries, pd.DataFrame):
        frame = oseries
    else:
        msg = "Input type should be either a list, tuple, or pandas.DataFrame"
        logger.error(msg)
        raise TypeError(msg)

    if frame.shape[1] < 2:
        msg = f"Metran requires at least 2 series, found {frame.shape[1]}"
        logger.error(msg)
        raise Exception(msg)
    return frame


def truncate(
    frame: pd.DataFrame, tmin=None, tmax=None
) -> pd.DataFrame:
    """Clip to [tmin, tmax] and drop rows where every series is NaN."""
    tmin = frame.index.min() if tmin is None else tmin
    tmax = frame.index.max() if tmax is None else tmax
    return frame.loc[tmin:tmax].dropna(how="all")


def test_cross_section(frame: pd.DataFrame, min_pairs: int = 20) -> None:
    """Require each series to overlap others on >= min_pairs dates.

    For every series, counts dates where that series is observed together
    with at least one other series; raises when any count is below
    ``max(min_pairs, 1)`` (reference: ``metran/metran.py:150-197``).
    """
    if min_pairs == 0:
        logger.warning("min_pairs must be greater than 0.")
    present = frame.notna()
    row_count = present.sum(axis=1)
    # reference counts rows where the series is present (row_count >= 1 by
    # construction after dropna(how="all")), i.e. dates usable for the filter
    pairs = {name: int(row_count[present[name]].count()) for name in frame.columns}
    bad = [name for name, n in pairs.items() if n < max(min_pairs, 1)]
    if bad:
        msg = (
            "Number of cross-sectional data is less than "
            + str(min_pairs)
            + " for series "
            + ", ".join(str(b) for b in bad)
        )
        logger.error(msg)
        raise Exception(msg)


def standardize(frame: pd.DataFrame):
    """Z-score each column; returns (standardized, std, mean)."""
    std = frame.std()
    mean = frame.mean()
    return (frame - mean) / std, np.asarray(std.values, float), np.asarray(
        mean.values, float
    )


def build_panel(
    oseries,
    freq: str = "D",
    tmin=None,
    tmax=None,
    min_pairs: int = 20,
    dtype=np.float64,
) -> Panel:
    """Full ingestion pipeline: combine, truncate, grid, standardize, pack."""
    frequency_is_supported(freq)
    frame = combine_series(oseries)
    frame = truncate(frame, tmin, tmax)
    if not isinstance(frame.index, pd.DatetimeIndex):
        msg = "Index of series must be DatetimeIndex"
        logger.error(msg)
        raise TypeError(msg)
    frame = frame.asfreq(freq)
    standardized, std, mean = standardize(frame)
    test_cross_section(standardized, min_pairs=min_pairs)
    return pack_panel(standardized, std=std, mean=mean, freq=freq, dtype=dtype)


def pack_panel(
    standardized: pd.DataFrame,
    std: Optional[np.ndarray] = None,
    mean: Optional[np.ndarray] = None,
    freq: str = "D",
    dtype=np.float64,
) -> Panel:
    """Pack a standardized regular-grid DataFrame into dense arrays."""
    raw = np.asarray(standardized.values, dtype)
    mask = np.isfinite(raw)
    values = np.where(mask, raw, 0.0)
    n = raw.shape[1]
    if std is None:
        std = np.ones(n)
    if mean is None:
        mean = np.zeros(n)
    return Panel(
        values=values,
        mask=mask,
        index=standardized.index,
        names=[str(c) for c in standardized.columns],
        std=np.asarray(std, float),
        mean=np.asarray(mean, float),
        dt=freq_to_days(freq),
    )


def panel_to_frame(panel: Panel, values: np.ndarray, columns=None) -> pd.DataFrame:
    """Wrap a (T, k) array back into a DataFrame on the panel's grid."""
    if columns is None:
        columns = panel.names
    return pd.DataFrame(np.asarray(values), index=panel.index, columns=columns)
