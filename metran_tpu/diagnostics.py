"""Quantitative residual diagnostics for fitted models.

The reference ships no residual diagnostics at all (its products end at
simulation/decomposition, ``metran/kalmanfilter.py:569-644``); this
module turns the innovation accessor (:func:`metran_tpu.ops.innovations`)
into test statistics, so "is this fit adequate" is a number rather than
a visual judgement.

Host-side numpy by design: the statistics are O(T * lags) on data that
already lives on host as DataFrames, far below any dispatch-worthy
size.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import numpy as np
from scipy.stats import chi2


class LjungBoxResult(NamedTuple):
    """Per-series Ljung-Box portmanteau test results (arrays of shape
    (n_series,))."""

    q: np.ndarray  # Q statistic
    pvalue: np.ndarray  # chi-squared survival value at dof
    dof: np.ndarray  # degrees of freedom used
    nobs: np.ndarray  # finite observations entering the statistic


def ljung_box(
    v: np.ndarray, lags: int = 20, n_params: int = 0
) -> LjungBoxResult:
    """Ljung-Box whiteness test per residual series.

    Portmanteau statistic over lags ``1..lags`` on the standardized
    one-step-ahead innovations; under the null of a well-specified
    model Q is approximately chi-squared with ``lags - n_params``
    degrees of freedom, so a small p-value means serial structure the
    model missed.

    Missing values (NaN) are handled pairwise: lag ``k``'s
    autocorrelation ``rho_k`` uses the ``n_k`` pairs where both
    endpoints are observed, normalized by the series' overall second
    moment (innovations have mean 0 and unit variance under the null),
    and each lag contributes ``n_k * rho_k^2`` to Q — the
    exact-variance weighting (``var(rho_k) ~ 1/n_k``), which for
    complete data reduces to the textbook ``(n-k) rho_k^2`` per-lag
    term.  The classic ``n(n+2)/(n-k)`` factor would over-reject under
    missingness, where ``n_k`` is systematically smaller than ``n``.

    Parameters
    ----------
    v : (T,) or (T, n_series) standardized innovations, NaN where
        missing (the output of ``Metran.get_innovations``; pass a
        ``warmup`` there so the filter's initialization transient does
        not register as model failure).
    lags : highest lag in the statistic; series shorter than
        ``lags + 1`` finite points get NaN results.
    n_params : degrees-of-freedom correction for fitted parameters
        (the classic ARMA correction).  For a DFM there is no single
        right value (each series carries one specific ``alpha`` and a
        share of the common ones); the default 0 is conservative
        toward flagging.
    """
    v = np.asarray(v, float)
    if v.ndim == 1:
        v = v[:, None]
    if v.ndim != 2:
        raise ValueError(f"expected (T,) or (T, n) residuals, got {v.shape}")
    if not 0 < lags < v.shape[0]:
        raise ValueError(f"lags must be in [1, T); got {lags}, T={v.shape[0]}")
    n_series = v.shape[1]
    q = np.full(n_series, np.nan)
    pv = np.full(n_series, np.nan)
    dof = np.full(n_series, max(int(lags) - int(n_params), 1))
    nobs = np.zeros(n_series, dtype=int)
    for i in range(n_series):
        x = v[:, i]
        finite = np.isfinite(x)
        n = int(finite.sum())
        nobs[i] = n
        if n < lags + 1:
            continue
        m2 = float(np.mean(x[finite] ** 2))
        if m2 <= 0.0:
            continue
        acc = 0.0
        for k in range(1, int(lags) + 1):
            a, b = x[:-k], x[k:]
            ok = finite[:-k] & finite[k:]
            n_k = int(ok.sum())
            if n_k == 0:
                continue
            rho = float(np.mean(a[ok] * b[ok])) / m2
            acc += n_k * rho * rho
        q[i] = acc
        pv[i] = float(chi2.sf(q[i], dof[i]))
    return LjungBoxResult(q, pv, dof, nobs)


def fleet_whiteness(
    v, lags: int = 20, n_params: int = 0
) -> LjungBoxResult:
    """Ljung-Box over a fleet of innovation panels.

    ``v`` is the (B, T, N) residual array — the FIRST element of the
    ``(v, f)`` pair :func:`metran_tpu.parallel.fleet_innovations`
    returns (standardized, NaN at missing/padded positions).  Returns
    a :class:`LjungBoxResult` whose arrays have shape (B, N) — one
    verdict per model and series.
    Padded series slots are all-NaN and come back NaN (untestable),
    matching the fleet padding convention.
    """
    v = np.asarray(v, float)
    if v.ndim != 3:
        raise ValueError(f"expected (B, T, N) innovations, got {v.shape}")
    b, t, n = v.shape
    flat = np.moveaxis(v, 1, 0).reshape(t, b * n)
    res = ljung_box(flat, lags=lags, n_params=n_params)
    return LjungBoxResult(*(a.reshape(b, n) for a in res))


def whiteness_table(
    innovations_frame, lags: int = 20, n_params: int = 0,
    alpha: float = 0.05,
):
    """Ljung-Box results as a DataFrame indexed like the input columns.

    Columns: ``nobs``, ``Q``, ``dof``, ``pvalue`` and the nullable
    boolean ``white`` (``pvalue >= alpha`` — True means no evidence
    against whiteness at that level; ``<NA>`` means the test could not
    run, e.g. too few finite points for ``lags``).
    """
    from pandas import DataFrame, Series, isna

    res = ljung_box(innovations_frame.to_numpy(), lags=lags,
                    n_params=n_params)
    white = Series(
        res.pvalue >= alpha, dtype="boolean",
        index=list(innovations_frame.columns),
    ).mask(isna(res.pvalue))
    return DataFrame(
        {
            "nobs": res.nobs,
            "Q": res.q,
            "dof": res.dof,
            "pvalue": res.pvalue,
            "white": white,
        },
        index=list(innovations_frame.columns),
    )
