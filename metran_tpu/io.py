"""Model serialization and fleet checkpoint/resume.

The reference has **no** persistence at all (SURVEY.md section 5: no
to_file/from_file anywhere; fitted state lives only in memory).  This
module adds both layers the TPU-scale story needs:

- :func:`save_model` / :func:`load_model` — a fitted :class:`Metran`
  round-trips through a single self-contained JSON file (data, settings,
  factor loadings, parameter table with optima/stderr, fit statistics),
  so inference products (states, simulations, decompositions, reports)
  are available without re-solving.
- :func:`save_fleet_state` / :func:`load_fleet_state` — dense pytree
  checkpoints (npz) of the chunked fleet L-BFGS used by
  ``fit_fleet(checkpoint=...)`` for preemption-safe long runs.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional

import numpy as np
import pandas as pd

FORMAT_VERSION = 1


class LoadedFit:
    """Fit statistics restored from disk (stands in for a solver object)."""

    _name = "LoadedFit"

    def __init__(self, obj_func, nfev, aic, pcov=None, pcor=None):
        self.obj_func = obj_func
        self.nfev = nfev
        self.aic = aic
        self.pcov = pcov
        self.pcor = pcor


def fsync_dir(directory) -> None:
    """fsync a directory so a just-renamed entry survives power loss.

    ``rename()`` alone updates the directory in the page cache; until
    the directory inode itself is flushed, a power cut can roll the
    rename back (the classic crash-consistency gap — the file's DATA
    was fsynced, but the NAME pointing at it was not).  Called by
    :func:`atomic_savez` and the WAL manifest writer after every
    rename-into-place.  The descriptor is closed on every path,
    including an fsync failure.  Platforms whose directories refuse
    ``fsync`` (some network filesystems raise ``EINVAL``/
    ``ENOTSUP``) degrade to a no-op — the rename is still atomic
    against process death, just not against power loss.
    """
    import errno
    import os

    fd = os.open(str(directory), os.O_RDONLY)
    try:
        os.fsync(fd)
    except OSError as exc:  # pragma: no cover - odd filesystems
        if exc.errno not in (errno.EINVAL, errno.ENOTSUP, errno.EBADF):
            raise
    finally:
        os.close(fd)


def atomic_savez(path, **arrays) -> Path:
    """Write ``arrays`` to ``path`` as an ``.npz``, atomically.

    Writes to a uniquely-named temp sibling (pid + random suffix; a
    FIXED tmp name let two concurrent writers — e.g. a fleet checkpoint
    and a serve posterior-state flush in the same directory — clobber
    each other's half-written file), fsyncs so the rename can never
    publish an empty/partial file after a crash, then renames into
    place, so readers never observe a half-written checkpoint.  The
    ``.npz`` suffix on the temp name keeps ``np.savez`` from appending
    its own.  Shared by the fleet-state checkpoints below, the sweep
    runner's per-batch results (``parallel/sweep.py``) and the serving
    layer's posterior states (``serve/state.py``).

    A writer killed between open() and rename leaves its temp file
    behind (so does an injected :class:`~metran_tpu.reliability.
    faultinject.SimulatedCrash`, which this function deliberately does
    NOT clean up after — it models the process dying); dot-prefixed
    temp names keep such leftovers invisible to readers, and
    :func:`sweep_stale_tmps` reclaims them at the next startup.

    Fault points: ``io.atomic_savez`` (entry — injectable IO errors) and
    ``io.atomic_savez.rename`` (between fsync and rename — crash
    window).
    """
    import os
    import uuid

    from .reliability.faultinject import SimulatedCrash, fire

    path = Path(path)
    fire("io.atomic_savez", str(path))
    # pid + process START TIME + random suffix: the pid alone is not an
    # owner identity once several serving processes share a registry
    # dir — the kernel recycles pids, and a sweep that trusts a live
    # recycled pid would pin a dead writer's temp forever (see
    # sweep_stale_tmps)
    tmp = path.with_name(
        f".{path.name}.{os.getpid()}-{_proc_start_ticks(os.getpid())}"
        f"-{uuid.uuid4().hex[:8]}.tmp.npz"
    )
    try:
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrays)
            fh.flush()
            os.fsync(fh.fileno())
        fire("io.atomic_savez.rename", str(path))
        tmp.replace(path)
        # rename alone is not durable across power loss: the directory
        # entry lives in the page cache until the directory inode is
        # flushed — fsync it so a power cut cannot resurrect the old
        # file under a name whose new bytes were already acked durable
        fsync_dir(path.parent)
    except SimulatedCrash:
        raise  # a killed writer leaves its temp behind; the sweep reclaims it
    except BaseException:
        if tmp.exists():  # failed write/rename: don't litter
            tmp.unlink()
        raise
    return path


_TMP_NAME_RE = None  # compiled lazily; module import stays regex-free


def _proc_start_ticks(pid: int) -> int:
    """The process's kernel start time in clock ticks since boot
    (``/proc/<pid>/stat`` field 22), 0 when unreadable (non-/proc
    platforms, or the process is already gone).

    ``(pid, start_ticks)`` is the real owner identity for on-disk
    artifacts: pids recycle, but a recycled pid gets a NEW start time,
    so a temp tagged with both can never be pinned by an unrelated
    process that happened to inherit its writer's pid.
    """
    try:
        with open(f"/proc/{pid}/stat", "rb") as fh:
            stat = fh.read()
        # the comm field (2) is a parenthesized, possibly-space-filled
        # process name: split AFTER the last ')', then field 22 is at
        # index 19 of the remainder (fields 3..)
        rest = stat[stat.rindex(b")") + 2:].split()
        return int(rest[19])
    except (OSError, ValueError, IndexError):
        return 0


def sweep_stale_tmps(directory) -> list:
    """Delete ``atomic_savez`` temp files left by writers killed mid-write.

    Matches the temp-name shape ``.{name}.{pid}-{starttime}-{hex8}
    .tmp.npz`` (and the pre-start-time shape ``.{name}.{pid}-{hex8}
    .tmp.npz`` older writers left behind) and only removes a temp whose
    writer is provably gone.  A LIVE writer — same pid AND same process
    start time, including this process: another thread may be mid-write
    right now — is skipped, so the sweep can run concurrently with
    writers.  The start-time check is what makes this safe with
    multiple serving processes sharing a registry dir: a pid the kernel
    recycled to an unrelated process no longer counts as the temp's
    owner (it has a different start time), so a dead writer's temp can
    never be pinned forever by pid reuse.  Old-shape temps carry no
    start time and keep the conservative pid-only liveness check.
    Returns the paths removed.  Called by ``ModelRegistry`` at startup
    so a crash-looping service cannot accumulate unbounded garbage, and
    safe to call from any process that owns a checkpoint directory.
    """
    import os
    import re

    global _TMP_NAME_RE
    if _TMP_NAME_RE is None:
        _TMP_NAME_RE = re.compile(
            r"^\.(?P<name>.+)\.(?P<pid>\d+)"
            r"(?:-(?P<start>\d+))?-[0-9a-f]{8}\.tmp\.npz$"
        )

    def pid_alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:  # exists, owned by someone else
            return True
        return True

    removed = []
    directory = Path(directory)
    if not directory.is_dir():
        return removed
    for p in directory.glob(".*.tmp.npz"):
        m = _TMP_NAME_RE.match(p.name)
        if m is None:
            continue
        pid = int(m.group("pid"))
        if m.group("start") is not None:
            # owner identity is (pid, start_ticks): a live pid with a
            # DIFFERENT start time is a recycled pid, not the writer
            if pid_alive(pid) and (
                _proc_start_ticks(pid) == int(m.group("start"))
            ):
                continue
        elif pid_alive(pid):
            continue  # old-shape temp: pid-only check (conservative)
        try:
            p.unlink()
        except FileNotFoundError:  # pragma: no cover - concurrent sweep
            continue
        removed.append(p)
    return removed


def _frame_to_dict(frame: pd.DataFrame) -> dict:
    return {
        "index": [str(i) for i in frame.index],
        "columns": [str(c) for c in frame.columns],
        "values": np.where(
            np.isfinite(frame.values.astype(float)), frame.values, None
        ).tolist(),
    }


def _frame_from_dict(d: dict, datetime_index: bool = True) -> pd.DataFrame:
    idx = pd.DatetimeIndex(d["index"]) if datetime_index else d["index"]
    values = np.array(
        [[np.nan if v is None else v for v in row] for row in d["values"]],
        dtype=float,
    )
    return pd.DataFrame(values, index=idx, columns=d["columns"])


def save_model(mt, path) -> Path:
    """Serialize a (fitted or unfitted) Metran model to one JSON file."""
    path = Path(path)
    payload = {
        "format_version": FORMAT_VERSION,
        "name": mt.name,
        "engine": mt._engine,
        "settings": {
            k: (str(v) if isinstance(v, pd.Timestamp) else v)
            for k, v in mt.settings.items()
        },
        "file_info": {k: str(v) for k, v in mt.file_info.items()},
        "oseries_unstd": _frame_to_dict(mt.oseries_unstd),
        "parameters": {
            "index": list(mt.parameters.index),
            "columns": list(mt.parameters.columns),
            "values": [
                [None if (isinstance(v, float) and np.isnan(v)) else v for v in row]
                for row in mt.parameters.where(pd.notna(mt.parameters), None)
                .values.tolist()
            ],
        },
        "factors": None if mt.factors is None else np.asarray(mt.factors).tolist(),
        "eigval": None
        if getattr(mt, "eigval", None) is None
        else np.asarray(mt.eigval).tolist(),
        "fep": getattr(mt, "fep", None),
        "fit": None,
    }
    if mt.fit is not None and getattr(mt.fit, "obj_func", None) is not None:
        payload["fit"] = {
            "obj_func": float(mt.fit.obj_func),
            "nfev": int(mt.fit.nfev) if mt.fit.nfev is not None else None,
            "aic": float(mt.fit.aic) if mt.fit.aic is not None else None,
            "pcor": None
            if mt.fit.pcor is None
            else {
                "index": list(mt.fit.pcor.index),
                "values": mt.fit.pcor.values.tolist(),
            },
            "pcov": None
            if mt.fit.pcov is None
            else {
                "index": list(mt.fit.pcov.index),
                "values": mt.fit.pcov.values.tolist(),
            },
        }
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(json.dumps(payload))
    tmp.replace(path)
    return path


def load_model(path, cls=None):
    """Rebuild a Metran model (with fitted state) from :func:`save_model`.

    ``cls`` lets subclasses reconstruct as themselves (defaults to
    :class:`Metran`).
    """
    from .models.metran import Metran

    if cls is None:
        cls = Metran
    payload = json.loads(Path(path).read_text())
    if payload.get("format_version") != FORMAT_VERSION:
        raise ValueError(
            f"unsupported model file format: {payload.get('format_version')}"
        )
    frame = _frame_from_dict(payload["oseries_unstd"])
    settings = payload["settings"]
    mt = cls(
        frame,
        name=payload["name"],
        freq=settings.get("freq"),
        tmin=settings.get("tmin"),
        tmax=settings.get("tmax"),
        engine=payload["engine"],
    )
    mt.settings.update(
        {k: v for k, v in settings.items() if k not in ("freq", "tmin", "tmax")}
    )

    if payload["factors"] is not None:
        mt.factors = np.asarray(payload["factors"], float)
        mt.nfactors = mt.factors.shape[1]
    if payload["eigval"] is not None:
        mt.eigval = np.asarray(payload["eigval"], float)
    if payload["fep"] is not None:
        mt.fep = payload["fep"]

    par = payload["parameters"]
    values = [
        [np.nan if v is None else v for v in row] for row in par["values"]
    ]
    mt.parameters = pd.DataFrame(values, index=par["index"], columns=par["columns"])

    fit = payload["fit"]
    if fit is not None:
        pcor = pcov = None
        if fit["pcor"] is not None:
            pcor = pd.DataFrame(
                fit["pcor"]["values"],
                index=fit["pcor"]["index"],
                columns=fit["pcor"]["index"],
            )
        if fit["pcov"] is not None:
            pcov = pd.DataFrame(
                fit["pcov"]["values"],
                index=fit["pcov"]["index"],
                columns=fit["pcov"]["index"],
            )
        mt.fit = LoadedFit(fit["obj_func"], fit["nfev"], fit["aic"], pcov, pcor)
    return mt


# ----------------------------------------------------------------------
# fleet checkpoints (dense pytrees -> npz)
# ----------------------------------------------------------------------
def save_fleet_state(path, theta, state, frozen, prev_value, meta: dict) -> Path:
    """Checkpoint the chunked fleet L-BFGS carry to ``path`` (npz
    format, written atomically via a temp file)."""
    import jax

    path = Path(path)
    leaves, _ = jax.tree_util.tree_flatten((theta, state, frozen))
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays["prev_value"] = (
        np.asarray(prev_value) if prev_value is not None else np.zeros(0)
    )
    arrays["meta_json"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    return atomic_savez(path, **arrays)


def load_fleet_state(path, like_theta, like_state, like_frozen):
    """Restore a fleet checkpoint into the given pytree structure.

    Returns ``(theta, state, frozen, prev_value, meta)`` or ``None`` when
    no (or an incompatible) checkpoint exists.
    """
    import jax

    path = Path(path)
    if not path.exists():
        return None
    with np.load(path, allow_pickle=False) as data:
        if "meta_json" not in data:
            return None
        meta = json.loads(bytes(data["meta_json"]).decode())
        template = (like_theta, like_state, like_frozen)
        leaves, treedef = jax.tree_util.tree_flatten(template)
        keys = [f"leaf_{i}" for i in range(len(leaves))]
        # leaf count must match exactly (an extra or missing leaf means a
        # different optimizer-state structure, e.g. another optax version)
        n_stored = sum(1 for k in data.files if k.startswith("leaf_"))
        if n_stored != len(leaves) or any(k not in data for k in keys):
            return None
        stored = [data[k] for k in keys]

        # shape AND dtype must match the live template: a checkpoint
        # written under a different precision mode (e.g. jax_enable_x64
        # flipped) would otherwise silently promote the resumed fit.
        # Integer width is the one tolerated drift: optax narrows some
        # counter leaves (e.g. ``info.num_linesearch_steps``) to int32
        # inside an update step while a fresh x64 ``opt.init`` template
        # carries a WEAK-typed int64, so a checkpoint written mid-run
        # never dtype-matches the restore template exactly.  The stored
        # dtype is kept (it is exactly what an uninterrupted run's carry
        # holds); only the int-vs-int compatibility is checked.
        def compatible(s, l):
            if s.shape != np.shape(l):
                return False
            want = np.result_type(l)
            return s.dtype == want or (
                np.issubdtype(s.dtype, np.integer)
                and np.issubdtype(want, np.integer)
            )

        if any(not compatible(s, l) for s, l in zip(stored, leaves)):
            return None
        theta, state, frozen = jax.tree_util.tree_unflatten(treedef, stored)
        prev_value = data["prev_value"]
        prev_value = None if prev_value.size == 0 else prev_value
    return theta, state, frozen, prev_value, meta


__all__ = [
    "atomic_savez",
    "fsync_dir",
    "FORMAT_VERSION",
    "LoadedFit",
    "load_fleet_state",
    "load_model",
    "save_fleet_state",
    "save_model",
    "sweep_stale_tmps",
]
