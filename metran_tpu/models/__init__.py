"""Model-level API: the Metran orchestrator, factor analysis, solvers."""

from .factoranalysis import FactorAnalysis
from .metran import Metran
from .solver import BaseSolver, JaxSolve, LmfitSolve, ScipySolve

__all__ = [
    "BaseSolver",
    "FactorAnalysis",
    "JaxSolve",
    "LmfitSolve",
    "Metran",
    "ScipySolve",
]
