"""Model-level API: the Metran orchestrator, factor analysis, solvers."""

from .factoranalysis import FactorAnalysis
from .metran import Metran
from .solver import (
    BaseSolver, JaxSolve, LanesSolve, LmfitSolve, ScipySolve,
)

__all__ = [
    "BaseSolver",
    "FactorAnalysis",
    "JaxSolve",
    "LanesSolve",
    "LmfitSolve",
    "Metran",
    "ScipySolve",
]
