"""FactorAnalysis model class (API-compatible with the reference).

Thin stateful wrapper around :mod:`metran_tpu.ops.fa` exposing the same
surface as the reference class (``metran/factoranalysis.py:13-118``):
``solve(oseries) -> loadings`` plus ``eigval``/``fep``/``factors``
attributes and ``get_eigval_weight``.  Underscored helpers are provided as
aliases so code written against the reference keeps working.
"""

from __future__ import annotations

from logging import getLogger
from typing import Optional

import numpy as np

from ..ops import fa as _fa

logger = getLogger(__name__)


class FactorAnalysis:
    """Estimate factor loadings of multivariate series by minres.

    Parameters
    ----------
    maxfactors : int, optional
        Maximum number of factors to keep.
    mode : str, optional
        "reference" (default) reproduces the reference implementation's
        numerical behavior exactly; "textbook" uses the corrected MAP test
        and descending eigen-ordering (see ops/fa.py docstring).

    Examples
    --------
    >>> fa = FactorAnalysis()
    >>> factors = fa.solve(oseries)
    """

    def __init__(self, maxfactors: Optional[int] = None, mode: str = "reference"):
        self.maxfactors = maxfactors
        self.mode = mode
        self.eigval: Optional[np.ndarray] = None
        self.factors: Optional[np.ndarray] = None
        self.fep: Optional[float] = None

    def get_eigval_weight(self) -> np.ndarray:
        """Each eigenvalue as a fraction of the eigenvalue sum."""
        return self.eigval / np.sum(self.eigval)

    def solve(self, oseries) -> Optional[np.ndarray]:
        """Run the full factor-analysis pipeline on a series DataFrame.

        Returns the (n_series, n_factors) loading matrix, or None when no
        proper common factors can be derived (callers treat that as a
        failed model, matching the reference).
        """
        corr = _fa.correlation_matrix(oseries)
        result = _fa.factor_analysis(corr, maxfactors=self.maxfactors, mode=self.mode)
        self.eigval = result.eigval
        self.factors = result.factors
        self.fep = result.fep
        return self.factors

    # ------------------------------------------------------------------
    # drop-in aliases for the reference's underscored API
    # ------------------------------------------------------------------
    @staticmethod
    def _get_correlations(oseries):
        return _fa.correlation_matrix(oseries)

    @staticmethod
    def _get_eigval(correlation):
        return _fa.sorted_scaled_eig(correlation)

    def _maptest(self, cov, eigvec, eigval=None):
        return _fa.map_test(cov, eigvec, mode=self.mode)

    def _minres(self, s, nf, covar=False):
        return _fa.minres(s, nf, mode=self.mode)

    @staticmethod
    def _rotate(phi, gamma=1, maxiter=20, tol=1e-6):
        return _fa.varimax(phi, gamma=gamma, maxiter=maxiter, tol=tol)
