"""Stateful shell around the pure JAX Kalman kernels.

Plays the role of the reference's ``SPKalmanFilter`` object
(``metran/kalmanfilter.py:479-778``): holds the packed observations, the
currently-set state-space matrices and lazily-cached filter/smoother
results, so model accessors can re-use a single filter pass.  All numerics
happen in :mod:`metran_tpu.ops`.
"""

from __future__ import annotations

from logging import getLogger
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..data import Panel
from ..ops import (
    FilterResult,
    SmootherResult,
    StateSpace,
    decompose_states,
    deviance_terms,
    kalman_filter,
    project,
    rts_smoother,
)

logger = getLogger(__name__)


class KalmanRunner:
    """Caches filter/smoother products for the currently-set matrices."""

    def __init__(self, panel: Panel, engine: str = "sequential"):
        self.engine = engine
        self.mask_active = False  # True while masked observations are set
        self.set_observations(panel)
        self.ss: Optional[StateSpace] = None
        self.init_states()

    # mirror of the reference's cache-invalidation entry point
    def init_states(self) -> None:
        self.filtered: Optional[FilterResult] = None
        self.smoothed: Optional[SmootherResult] = None
        # square-root engines: the factored filter pass is cached so
        # the smoother consumes factors, not reconstituted covariances
        self._sqrt_filtered = None

    def set_observations(self, panel: Panel) -> None:
        self.panel = panel
        self.y = jnp.asarray(panel.values)
        self.mask = jnp.asarray(panel.mask)
        self.init_states()

    def set_matrices(self, ss: StateSpace) -> None:
        self.ss = ss
        self.init_states()

    def run_filter(self) -> FilterResult:
        if self.filtered is None:
            if self.mask_active:
                logger.info("Running Kalman filter with masked observations.")
            if self.engine in ("sqrt", "sqrt_parallel"):
                # run ONE factored pass, cache the factors for the
                # smoother (PSD by construction end to end) and expose
                # the reconstituted moments through the usual accessors
                from ..ops import (
                    chol_outer,
                    sqrt_kalman_filter,
                    sqrt_parallel_filter,
                )

                sq = (
                    sqrt_parallel_filter(self.ss, self.y, self.mask)
                    if self.engine == "sqrt_parallel"
                    else sqrt_kalman_filter(self.ss, self.y, self.mask)
                )
                self._sqrt_filtered = sq
                self.filtered = FilterResult(
                    sq.mean_p, chol_outer(sq.chol_p), sq.mean_f,
                    chol_outer(sq.chol_f), sq.sigma, sq.detf,
                )
            else:
                self.filtered = kalman_filter(
                    self.ss, self.y, self.mask, engine=self.engine
                )
        return self.filtered

    def run_smoother(self) -> SmootherResult:
        if self.smoothed is None:
            filtered = self.run_filter()
            if self._sqrt_filtered is not None:
                # smooth the factored pass: rts_smoother dispatches on
                # the SqrtFilterResult type and stays in factors
                filtered = self._sqrt_filtered
            self.smoothed = rts_smoother(
                self.ss, filtered, engine=self.engine
            )
        return self.smoothed

    def get_mle(self, warmup: int = 1) -> float:
        res = self.run_filter()
        return float(deviance_terms(res.sigma, res.detf, self.mask, warmup=warmup))

    def _states(self, method: str):
        if method == "filter":
            res = self.run_filter()
            return res.mean_f, res.cov_f
        res = self.run_smoother()
        return res.mean_s, res.cov_s

    def state_means(self, method: str = "smoother") -> np.ndarray:
        return np.asarray(self._states(method)[0])

    def state_variances(self, method: str = "smoother") -> np.ndarray:
        covs = self._states(method)[1]
        return np.asarray(jnp.diagonal(covs, axis1=-2, axis2=-1))

    def simulate(self, observation_matrix, method: str = "smoother"):
        means, covs = self._states(method)
        sim_means, sim_vars = project(jnp.asarray(observation_matrix), means, covs)
        return np.asarray(sim_means), np.asarray(sim_vars)

    def forecast(self, observation_matrix, steps: int):
        """h-step-ahead observation means/variances beyond the data end.

        Uses the filtered state at the last timestep (the smoothed and
        filtered moments coincide at ``T``) and the closed-form
        diagonal-transition predictive recursion
        (:mod:`metran_tpu.ops.forecast` — no scan, the reference has no
        forecasting at all).  ``observation_matrix`` chooses the units
        (standardized Z or std-scaled Z, as in :meth:`simulate`).
        """
        from ..ops.forecast import _forecast_from_filtered

        filt = self.run_filter()
        ss = self.ss._replace(z=jnp.asarray(observation_matrix))
        means, variances = _forecast_from_filtered(
            ss, filt.mean_f[-1], filt.cov_f[-1], int(steps)
        )
        return np.asarray(means), np.asarray(variances)

    def innovations(self, standardized: bool = True, warmup: int = 0):
        """One-step-ahead prediction residuals
        (:func:`metran_tpu.ops.innovations`), reusing the cached filter
        pass; NaN where no observation is present or within the first
        ``warmup`` steps."""
        from ..ops import innovations as _innovations

        v, f = _innovations(
            self.ss, self.y, self.mask, filt=self.run_filter(),
            standardized=standardized, warmup=int(warmup),
        )
        return np.asarray(v), np.asarray(f)

    def sample_states(self, key, n_draws: int, draw_chunk: int = 8):
        """Joint posterior state-path draws
        (:func:`metran_tpu.ops.sample_states`), reusing the cached
        smoother pass for the data side; the parallel engines fall back
        to their sequential counterparts for the per-draw passes
        (identical results, without the associative scan's compile cost
        per draw)."""
        from ..ops import sample_states as _sample_states

        engine = {"parallel": "joint", "sqrt_parallel": "sqrt"}.get(
            self.engine, self.engine
        )
        return np.asarray(_sample_states(
            self.ss, self.y, self.mask, key, n_draws=n_draws,
            engine=engine, sm_data=self.run_smoother().mean_s,
            draw_chunk=draw_chunk,
        ))

    def decompose(self, observation_matrix, method: str = "smoother"):
        means, _ = self._states(method)
        sdf, cdf = decompose_states(
            jnp.asarray(observation_matrix), means, self.panel.n_series
        )
        return np.asarray(sdf), np.asarray(cdf)
