"""The Metran model class: user-facing shell over the TPU engine.

API-compatible with the reference ``Metran`` (``metran/metran.py:31-1314``):
same constructor, parameter table, accessors, masking workflow and reports.
Internally the likelihood/filter/smoother run as jitted JAX computations on
dense masked arrays; gradients of the likelihood are exact (autodiff).
"""

from __future__ import annotations

import functools
from logging import getLogger
from os import getlogin
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from pandas import DataFrame, Series, Timestamp, concat, date_range
from scipy.stats import norm

from .. import data as _data
from ..ops import dfm_statespace, deviance
from ..utils import freq_to_days, frequency_is_supported, validate_name
from .factoranalysis import FactorAnalysis
from .kalman_runner import KalmanRunner
from .solver import ScipySolve

logger = getLogger(__name__)

_ENGINE_ALIASES = {
    "numba": "sequential",  # reference names accepted for drop-in use
    "numpy": "sequential",
    "sequential": "sequential",
    "joint": "joint",
    "parallel": "parallel",  # associative-scan parallel-in-time engine
    "sqrt": "sqrt",  # QR square-root engine (robust f32 default)
    "sqrt_parallel": "sqrt_parallel",  # square-root associative scan
}


@functools.partial(jax.jit, static_argnames=("warmup", "engine", "grad"))
def _dfm_deviance(p, y, mask, loadings, dt, warmup, engine,
                  grad="autodiff"):
    n_series = loadings.shape[0]
    ss = dfm_statespace(p[:n_series], p[n_series:], loadings, dt)
    return deviance(ss, y, mask, warmup=warmup, engine=engine, grad=grad)


_dfm_deviance_vg = jax.jit(
    jax.value_and_grad(_dfm_deviance),
    static_argnames=("warmup", "engine", "grad"),
)


class Metran:
    """Multivariate time-series analysis using a dynamic factor model.

    Parameters
    ----------
    oseries : pandas.DataFrame or list/tuple of pandas.Series/DataFrame
        Series to be analyzed; index must be a DatetimeIndex.
    name : str, optional
        Model name (default "Cluster").
    freq : str, optional
        Simulation frequency (fixed-length pandas offsets like "D", "7D").
    tmin, tmax : str, optional
        Start/end of the analysis period.
    engine : str, optional
        Kalman engine: "sequential" (parity with the reference's
        sequential processing), "joint" (batched Cholesky update),
        "sqrt" (QR square-root filtering/smoothing — covariances PSD
        by construction, the numerically robust float32 engine),
        "parallel" (associative-scan parallel-in-time filter/smoother,
        O(log T) depth) or "sqrt_parallel" (associative scan over
        triangular factors).  The reference's "numba"/"numpy" names are
        accepted aliases of "sequential".  Default: backend-aware —
        "sequential" on CPU (float64 reference parity), "sqrt" on
        accelerators (float32, where the covariance-form engines can
        lose PSD near ``phi -> 1``; see docs/concepts.md "Numerical
        robustness").
    """

    def __init__(
        self,
        oseries,
        name: str = "Cluster",
        freq: Optional[str] = None,
        tmin=None,
        tmax=None,
        engine: Optional[str] = None,
    ):
        from ..config import ensure_precision, is_accelerator

        ensure_precision()
        if engine is None:
            # float32 accelerators default to the square-root engine:
            # same likelihood, PSD-by-construction covariances (the
            # covariance-form "joint" engine can NaN-poison a filter
            # pass when f32 roundoff makes an innovation covariance
            # indefinite near phi -> 1)
            engine = "sqrt" if is_accelerator() else "sequential"
        self.settings = {
            "tmin": None,
            "tmax": None,
            "freq": "D",
            "min_pairs": 20,
            "solver": None,
            "warmup": 1,
        }
        if tmin is not None:
            self.settings["tmin"] = tmin
        if tmax is not None:
            self.settings["tmax"] = tmax
        if freq is not None:
            self.settings["freq"] = frequency_is_supported(freq)
        self._engine = _ENGINE_ALIASES[engine]

        self.nfactors = 0
        self.factors: Optional[np.ndarray] = None
        self.set_observations(oseries)
        self.parameters = DataFrame(
            columns=["initial", "pmin", "pmax", "vary", "name"]
        )
        self.set_init_parameters()

        self.masked_observations = None
        self.fit = None
        self.kf: Optional[KalmanRunner] = None

        self.name = validate_name(name)
        self.file_info = self._get_file_info()

        from .plots import MetranPlot

        self.plots = MetranPlot(self)

    # ------------------------------------------------------------------
    # dimensions
    # ------------------------------------------------------------------
    @property
    def nparam(self) -> int:
        return self.parameters.index.size

    @property
    def nstate(self) -> int:
        return self.nseries + self.nfactors

    @property
    def _dt(self) -> float:
        return freq_to_days(self.settings["freq"])

    # ------------------------------------------------------------------
    # data handling
    # ------------------------------------------------------------------
    def set_observations(self, oseries) -> None:
        """Ingest observations (reference: ``metran/metran.py:509-579``)."""
        frame = _data.combine_series(oseries)
        self.snames = [str(c) for c in frame.columns]
        frame = _data.truncate(
            frame, self.settings["tmin"], self.settings["tmax"]
        )
        import pandas as pd

        if not isinstance(frame.index, pd.DatetimeIndex):
            msg = "Index of series must be DatetimeIndex"
            logger.error(msg)
            raise TypeError(msg)
        frame = frame.asfreq(self.settings["freq"])
        self.nseries = frame.shape[1]
        self.oseries_unstd = frame
        self.oseries, self.oseries_std, self.oseries_mean = _data.standardize(frame)
        self.test_cross_section()

    def standardize(self, oseries):
        standardized, self.oseries_std, self.oseries_mean = _data.standardize(oseries)
        return standardized

    def truncate(self, oseries):
        return _data.truncate(oseries, self.settings["tmin"], self.settings["tmax"])

    def test_cross_section(self, oseries=None, min_pairs: Optional[int] = None):
        if oseries is None:
            oseries = self.oseries
        if min_pairs is None:
            min_pairs = self.settings["min_pairs"]
        _data.test_cross_section(oseries, min_pairs=min_pairs)

    def get_observations(self, standardized: bool = False, masked: bool = False):
        oseries = self.masked_observations if masked else self.oseries
        if not standardized:
            oseries = oseries * self.oseries_std + self.oseries_mean
        return oseries

    def _active_panel(self) -> _data.Panel:
        frame = (
            self.masked_observations
            if self.masked_observations is not None
            else self.oseries
        )
        return _data.pack_panel(
            frame,
            std=self.oseries_std,
            mean=self.oseries_mean,
            freq=self.settings["freq"],
        )

    # ------------------------------------------------------------------
    # masking (counterfactual / outlier analysis)
    # ------------------------------------------------------------------
    def mask_observations(self, mask) -> None:
        """Hide selected observations from the filter/smoother without
        altering the stored data (reference: ``metran/metran.py:464-495``)."""
        if mask.shape != self.oseries.shape:
            logger.error(
                "Dimensions of mask %s do not equal dimensions of series %s. "
                "Mask cannot be applied.",
                mask.shape,
                self.oseries.shape,
            )
            return
        self.masked_observations = self.oseries.mask(mask.astype(bool))
        if self.kf is not None:
            self.kf.set_observations(self._active_panel())
            self.kf.mask_active = True

    def unmask_observations(self) -> None:
        self.masked_observations = None
        if self.kf is not None:
            self.kf.set_observations(self._active_panel())
            self.kf.mask_active = False

    # ------------------------------------------------------------------
    # factor analysis
    # ------------------------------------------------------------------
    def get_factors(self, oseries=None) -> Optional[np.ndarray]:
        if oseries is None:
            oseries = self.oseries
        fa = FactorAnalysis()
        self.factors = fa.solve(oseries)
        self.eigval = fa.eigval
        if self.factors is not None:
            self.nfactors = self.factors.shape[1]
            self.fep = fa.fep
        else:
            self.nfactors = 0
        return self.factors

    def get_communality(self) -> np.ndarray:
        return np.sum(np.square(self.factors), axis=1)

    def get_specificity(self) -> np.ndarray:
        return 1 - self.get_communality()

    # ------------------------------------------------------------------
    # parameters
    # ------------------------------------------------------------------
    def set_init_parameters(self, method: str = "reference") -> None:
        """Populate the initial-parameter table.

        ``method="reference"`` (default) uses the reference's constant
        ``alpha = 10`` for every state (metran/metran.py:439-462).
        ``method="autocorr"`` seeds each alpha from the data's lag-1
        autocorrelations instead (see
        :func:`metran_tpu.parallel.autocorr_init_params`) — measured to
        cut L-BFGS iterations ~25 percent with identical optima; it
        needs factor loadings, so call it after ``get_factors`` (done
        automatically by ``solve(init="autocorr")``).
        """
        if method == "autocorr":
            if self.factors is None:
                raise ValueError(
                    "init method 'autocorr' needs factor loadings; call "
                    "get_factors first or use solve(init='autocorr')"
                )
            import jax.numpy as jnp

            from ..parallel.fleet import Fleet, autocorr_init_params

            panel = self._active_panel()
            fleet = Fleet(
                y=jnp.asarray(panel.values[None]),
                mask=jnp.asarray(panel.mask[None]),
                loadings=jnp.asarray(np.asarray(self.factors)[None]),
                dt=jnp.full(1, panel.dt),
                n_series=np.full(1, self.nseries, np.int32),
            )
            alpha = np.asarray(autocorr_init_params(fleet))[0]
            init_sdf = alpha[: self.nseries]
            init_cdf = alpha[self.nseries :]
        elif method == "reference":
            init_sdf = np.full(self.nseries, 10.0)
            init_cdf = np.full(self.nfactors, 10.0)
        else:
            raise ValueError(
                f"unknown init method {method!r}; expected 'reference' "
                "or 'autocorr'"
            )
        cols = ["initial", "pmin", "pmax", "vary", "name"]
        for n in range(self.nfactors):
            self.parameters.loc[f"cdf{n + 1}_alpha", cols] = (
                init_cdf[n], 1e-5, None, True, "cdf",
            )
        for n in range(self.nseries):
            self.parameters.loc[f"{self.snames[n]}_sdf_alpha", cols] = (
                init_sdf[n], 1e-5, None, True, "sdf",
            )

    def get_parameters(self, initial: bool = False) -> Series:
        if not initial and "optimal" in self.parameters:
            return self.parameters["optimal"]
        return self.parameters["initial"]

    @property
    def _canonical_idx(self) -> np.ndarray:
        """Gather indices mapping the parameter-table row order
        ([cdf..., sdf...]) to the canonical state ordering
        [sdf alphas..., cdf alphas...] used by the state-space builder."""
        kinds = self.parameters["name"].values
        return np.concatenate(
            [np.flatnonzero(kinds == "sdf"), np.flatnonzero(kinds == "cdf")]
        )

    def _table_array(self, p) -> np.ndarray:
        """Coerce parameters (array/Series/dict) to a float array in the
        parameter-table row order — the order solvers optimize in."""
        if isinstance(p, dict):
            p = Series(p)
        if isinstance(p, Series):
            p = p.reindex(self.parameters.index).values
        return np.asarray(p, float)

    def _param_array(self, p) -> np.ndarray:
        """Coerce parameters to the canonical order
        [sdf alphas..., cdf alphas...] used by the state-space builder."""
        return self._table_array(p)[self._canonical_idx]

    # ------------------------------------------------------------------
    # state-space matrices (host-side views for reports/parity)
    # ------------------------------------------------------------------
    def _phi(self, alpha):
        return np.exp(-self._dt / alpha)

    def get_transition_matrix(self, p=None, initial=False) -> np.ndarray:
        if p is None:
            p = self.get_parameters(initial)
        a = self._param_array(p)
        return np.diag(self._phi(a))

    def get_transition_covariance(self, p=None, initial=False) -> np.ndarray:
        if p is None:
            p = self.get_parameters(initial)
        a = self._param_array(p)
        phi = self._phi(a)
        communality = np.sum(np.square(self.factors), axis=1)
        q = 1 - phi**2
        q[: self.nseries] *= 1 - communality
        return np.diag(q)

    def get_transition_variance(self, p=None, initial=False) -> np.ndarray:
        return np.diag(self.get_transition_covariance(p, initial))

    def get_observation_matrix(self, p=None, initial=False) -> np.ndarray:
        return np.concatenate(
            [np.eye(self.nseries), np.atleast_2d(self.factors)], axis=1
        )

    def get_observation_variance(self) -> np.ndarray:
        return np.zeros(self.nseries)

    def get_scaled_observation_matrix(self, p=None) -> np.ndarray:
        from ..ops import scale_observation_matrix

        return np.asarray(
            scale_observation_matrix(self.get_observation_matrix(p), self.oseries_std)
        )

    def _get_matrices(self, p, initial=False):
        return (
            self.get_transition_matrix(p, initial),
            self.get_transition_covariance(p, initial),
            self.get_observation_matrix(p, initial),
            self.get_observation_variance(),
        )

    def _statespace(self, p):
        a = self._param_array(p)
        return dfm_statespace(
            a[: self.nseries], a[self.nseries:], jnp.asarray(self.factors), self._dt
        )

    # ------------------------------------------------------------------
    # likelihood
    # ------------------------------------------------------------------
    def _init_kalmanfilter(self, oseries=None, engine: Optional[str] = None) -> None:
        if engine is not None:
            self._engine = _ENGINE_ALIASES[engine]
        self.kf = KalmanRunner(self._active_panel(), engine=self._engine)

    def _resolved_grad(self, grad=None) -> str:
        """The gradient engine this model's fits differentiate with
        (``METRAN_TPU_GRAD_ENGINE`` unless overridden; see
        :func:`metran_tpu.ops.resolve_grad_engine`)."""
        from ..config import default_dtype
        from ..ops import resolve_grad_engine

        return resolve_grad_engine(grad, self._engine, default_dtype())

    def _deviance_jax(self, p_table, grad=None):
        """Deviance of the *table-order* parameter vector (the order the
        solvers optimize in) as a traced JAX value.  The reorder to the
        canonical [sdf..., cdf...] layout happens inside the trace, so
        gradients/Hessians come back in table order.  ``grad`` selects
        the gradient engine (``None`` = configured default); Hessian
        consumers pass ``"autodiff"`` — the closed-form adjoint is
        reverse-mode-only."""
        idx = jnp.asarray(self._canonical_idx)
        return _dfm_deviance(
            jnp.take(jnp.asarray(p_table), idx),
            self.kf.y,
            self.kf.mask,
            jnp.asarray(self.factors),
            self._dt,
            self.settings["warmup"],
            self._engine,
            self._resolved_grad(grad),
        )

    def _deviance_value_and_grad(self, p_table):
        """(deviance, gradient) at the table-order parameter vector; the
        gradient is returned in table order as well."""
        idx = jnp.asarray(self._canonical_idx)
        value, grad = _dfm_deviance_vg(
            jnp.take(jnp.asarray(p_table), idx),
            self.kf.y,
            self.kf.mask,
            jnp.asarray(self.factors),
            self._dt,
            self.settings["warmup"],
            self._engine,
            self._resolved_grad(),
        )
        return value, jnp.zeros_like(grad).at[idx].set(grad)

    def get_mle(self, p) -> float:
        """Deviance (-2 log L) at parameters ``p`` — the solver objective.

        Note: like the reference (``metran/metran.py:605-622``), this leaves
        the filter set to ``p``, and is the per-iteration hot path.
        """
        p_tab = self._table_array(p)
        if self.kf is None:
            self._init_kalmanfilter()
        self.kf.set_matrices(self._statespace(p_tab))
        return float(self._deviance_jax(p_tab))

    # ------------------------------------------------------------------
    # inference products
    # ------------------------------------------------------------------
    def _run_kalman(self, method: str = "smoother", p=None) -> None:
        if self.kf is None:
            self._init_kalmanfilter()
        if p is not None:
            self.kf.set_matrices(self._statespace(p))
        elif self.kf.ss is None:
            self.kf.set_matrices(self._statespace(self.get_parameters()))
        if method == "filter":
            self.kf.run_filter()
        else:
            self.kf.run_smoother()

    def _state_columns(self):
        return [f"{name}_sdf" for name in self.snames] + [
            f"cdf{i + 1}" for i in range(self.nfactors)
        ]

    def get_state_means(self, p=None, method: str = "smoother") -> DataFrame:
        self._run_kalman(method, p=p)
        means = self.kf.state_means(method)
        return DataFrame(means, index=self.oseries.index, columns=self._state_columns())

    def get_state_variances(self, p=None, method: str = "smoother") -> DataFrame:
        self._run_kalman(method, p=p)
        variances = self.kf.state_variances(method)
        return DataFrame(
            variances, index=self.oseries.index, columns=self._state_columns()
        )

    def get_state(self, i: int, p=None, alpha: float = 0.05, method="smoother"):
        if i < 0 or i >= self.nstate:
            logger.error("Value of i must be >=0 and <%s", self.nstate)
            return None
        state = self.get_state_means(p=p, method=method).iloc[:, i]
        if alpha is None:
            return state
        if not 0 < alpha < 1:
            msg = "The value of alpha must be between 0 and 1."
            logger.error(msg)
            raise Exception(msg)
        z = norm.ppf(1 - alpha / 2.0)
        variances = self.get_state_variances(p=p, method=method).iloc[:, i]
        iv = z * np.sqrt(variances)
        state = concat([state, state - iv, state + iv], axis=1)
        state.columns = ["mean", "lower", "upper"]
        return state

    def get_simulated_means(
        self, p=None, standardized: bool = False, method: str = "smoother"
    ) -> DataFrame:
        self._run_kalman(method, p=p)
        if standardized:
            observation_matrix = self.get_observation_matrix(p=p)
            observation_means = np.zeros(self.nseries)
        else:
            observation_matrix = self.get_scaled_observation_matrix(p=p)
            observation_means = self.oseries_mean
        means, _ = self.kf.simulate(observation_matrix, method=method)
        return (
            DataFrame(means, index=self.oseries.index, columns=self.oseries.columns)
            + observation_means
        )

    def get_simulated_variances(
        self, p=None, standardized: bool = False, method: str = "smoother"
    ) -> DataFrame:
        self._run_kalman(method, p=p)
        if standardized:
            observation_matrix = self.get_observation_matrix(p=p)
        else:
            observation_matrix = self.get_scaled_observation_matrix(p=p)
        _, variances = self.kf.simulate(observation_matrix, method=method)
        return DataFrame(
            variances, index=self.oseries.index, columns=self.oseries.columns
        )

    def get_simulation(
        self, name, p=None, alpha=0.05, standardized=False, method="smoother"
    ):
        means = self.get_simulated_means(p=p, standardized=standardized, method=method)
        if name not in means.columns:
            logger.error("Unknown name: %s", name)
            return None
        sim = means.loc[:, name]
        if alpha is None:
            return sim
        if not 0 < alpha < 1:
            msg = "The value of alpha must be between 0 and 1."
            logger.error(msg)
            raise Exception(msg)
        z = norm.ppf(1 - alpha / 2.0)
        variances = self.get_simulated_variances(
            p=p, standardized=standardized, method=method
        ).loc[:, name]
        iv = z * np.sqrt(variances)
        sim = concat([sim, sim - iv, sim + iv], axis=1)
        sim.columns = ["mean", "lower", "upper"]
        return sim

    def get_innovations(
        self, p=None, standardized: bool = True, warmup: int = 0
    ) -> DataFrame:
        """One-step-ahead prediction residuals per series.

        The whiteness diagnostic for the fitted model (no reference
        equivalent): standardized innovations of a well-specified model
        are ~N(0, 1) and serially uncorrelated, so structure left in
        them (drift, autocorrelation, fat tails, a single outlying
        date) localizes what the model misses.  Masked/missing dates
        are NaN.

        Parameters
        ----------
        p : optional parameter array; defaults to the fitted (or
            initial) parameters, like the other accessors.
        standardized : divide each residual by its predicted standard
            deviation (scale-free, the diagnostic default).  With
            ``False``, residuals are in standardized-observation units
            (the units the filter runs in; multiply by
            ``oseries_std`` for the original units).
        warmup : NaN out the first ``warmup`` timesteps.  The filter
            starts from mean 0 / covariance I rather than the
            stationary prior, so the earliest dates can sit outside
            the N(0, 1) band purely from the initialization transient
            (a stretch of the order of the longest ``alpha`` time
            scale); pass e.g. ``warmup=50`` when that matters.
        """
        self._run_kalman("filter", p=p)
        v, _ = self.kf.innovations(standardized=standardized, warmup=warmup)
        return DataFrame(v, index=self.oseries.index, columns=self.oseries.columns)

    def sample_simulation(
        self, name, n_draws: int = 100, seed: int = 0, p=None,
        standardized: bool = False,
    ) -> DataFrame:
        """Joint posterior sample paths of one series' latent signal.

        Durbin-Koopman simulation smoother draws
        (:func:`metran_tpu.ops.sample_states`, projected through the
        observation matrix): each column is one complete path from the
        joint posterior, honoring the current masking.  Unlike
        :meth:`get_simulation`'s marginal confidence band, paths carry
        the cross-time dependence, so a functional of a whole path
        (an annual minimum over a gap, a crossing time) can be
        evaluated per draw and summarized — the stochastic gap-filling
        workflow.  With the DFM's zero observation noise, every path
        passes exactly through the observed values and spreads only
        where data is missing.

        Returns a (T, n_draws) DataFrame on the observation grid, in
        data units unless ``standardized``.
        """
        if name not in self.oseries.columns:
            logger.error("Unknown name: %s", name)
            return None
        self._run_kalman("smoother", p=p)
        idx = int(list(self.oseries.columns).index(name))
        draws = self.kf.sample_states(
            jax.random.PRNGKey(int(seed)), n_draws=int(n_draws)
        )
        z = np.asarray(
            self.get_observation_matrix(p=p)
            if standardized else self.get_scaled_observation_matrix(p=p)
        )
        paths = np.asarray(draws) @ z[idx]
        if not standardized:
            paths = paths + float(np.asarray(self.oseries_mean)[idx])
        return DataFrame(
            paths.T, index=self.oseries.index,
            columns=[f"draw{j}" for j in range(int(n_draws))],
        )

    def test_whiteness(
        self, p=None, lags: int = 20, warmup: int = 50,
        alpha: float = 0.05, n_params: int = 0,
    ) -> DataFrame:
        """Ljung-Box whiteness test on the standardized innovations.

        The quantitative companion of :meth:`get_innovations` /
        ``plots.innovations`` (no reference equivalent): one row per
        series with the portmanteau Q statistic over ``lags`` lags, its
        p-value, and the boolean verdict at ``alpha``.  A False
        ``white`` flags serial structure the fitted model does not
        capture in that series.  ``warmup`` (default 50) excludes the
        filter's initialization transient; ``n_params`` optionally
        corrects the degrees of freedom for fitted parameters (see
        :func:`metran_tpu.diagnostics.ljung_box`).
        """
        from ..diagnostics import whiteness_table

        innov = self.get_innovations(p=p, warmup=warmup)
        table = whiteness_table(
            innov, lags=lags, n_params=n_params, alpha=alpha
        )
        # nullable boolean: <NA> means "not testable", which is
        # neither passing nor failing
        failing = [str(s) for s in table.index[table["white"].eq(False).fillna(False)]]
        if failing:
            logger.info(
                "Ljung-Box rejects whiteness at alpha=%g for: %s",
                alpha, ", ".join(failing),
            )
        return table

    def _forecast_moments(self, steps, p=None, standardized=False):
        self._run_kalman("filter", p=p)
        if standardized:
            observation_matrix = self.get_observation_matrix(p=p)
            observation_means = np.zeros(self.nseries)
        else:
            observation_matrix = self.get_scaled_observation_matrix(p=p)
            observation_means = self.oseries_mean
        means, variances = self.kf.forecast(observation_matrix, steps)
        index = date_range(
            self.oseries.index[-1], periods=steps + 1,
            freq=self.settings["freq"],
        )[1:]
        return means, variances, observation_means, index

    def get_forecast_means(
        self, steps: int, p=None, standardized: bool = False
    ) -> DataFrame:
        """Out-of-sample forecast means for every series, ``steps``
        grid periods beyond the last observation.

        A capability the reference does not have (its products end at
        the data, `metran/kalmanfilter.py:569-644`):
        closed-form h-step-ahead predictive moments from the filtered
        state at ``T`` (:mod:`metran_tpu.ops.forecast`).  Forecasts
        decay toward each series' unconditional mean with variances
        growing to the stationary variance.
        """
        means, _, observation_means, index = self._forecast_moments(
            steps, p=p, standardized=standardized
        )
        return (
            DataFrame(means, index=index, columns=self.oseries.columns)
            + observation_means
        )

    def get_forecast_variances(
        self, steps: int, p=None, standardized: bool = False
    ) -> DataFrame:
        """Out-of-sample forecast variances (see :meth:`get_forecast_means`)."""
        _, variances, _, index = self._forecast_moments(
            steps, p=p, standardized=standardized
        )
        return DataFrame(variances, index=index, columns=self.oseries.columns)

    def forecast(
        self, name, steps: int = 30, p=None, alpha=0.05,
        standardized: bool = False,
    ):
        """Forecast one series ``steps`` periods ahead, with a
        ``(1 - alpha)`` prediction interval (same contract as
        :meth:`get_simulation`; ``alpha=None`` returns the mean only).
        """
        if name not in self.oseries.columns:
            logger.error("Unknown name: %s", name)
            return None
        if alpha is not None and not 0 < alpha < 1:
            msg = "The value of alpha must be between 0 and 1."
            logger.error(msg)
            raise Exception(msg)
        # one moments pass covers both the mean and the interval
        means, variances, observation_means, index = self._forecast_moments(
            steps, p=p, standardized=standardized
        )
        col = list(self.oseries.columns).index(name)
        fc = Series(
            means[:, col] + observation_means[col], index=index, name=name
        )
        if alpha is None:
            return fc
        z = norm.ppf(1 - alpha / 2.0)
        iv = z * np.sqrt(variances[:, col])
        fc = concat([fc, fc - iv, fc + iv], axis=1)
        fc.columns = ["mean", "lower", "upper"]
        return fc

    def decompose_simulation(
        self, name, p=None, standardized: bool = False, method: str = "smoother"
    ):
        if name not in self.oseries.columns:
            logger.error("Unknown name: %s", name)
            return None
        self._run_kalman(method, p=p)
        if standardized:
            observation_matrix = self.get_observation_matrix(p=p)
            observation_means = np.zeros(self.nseries)
        else:
            observation_matrix = self.get_scaled_observation_matrix(p=p)
            observation_means = self.oseries_mean
        sdf, cdf = self.kf.decompose(observation_matrix, method=method)
        col = list(self.oseries.columns).index(name)
        parts = [
            Series(sdf[:, col] + observation_means[col], index=self.oseries.index)
        ]
        cols = ["sdf"]
        for k in range(self.nfactors):
            parts.append(Series(cdf[k][:, col], index=self.oseries.index))
            cols.append(f"cdf{k + 1}")
        df = concat(parts, axis=1)
        df.columns = cols
        return df

    # ------------------------------------------------------------------
    # solve
    # ------------------------------------------------------------------
    def solve(
        self,
        solver=None,
        report: bool = True,
        engine: Optional[str] = None,
        init: str = "reference",
        **kwargs,
    ) -> None:
        """Estimate parameters by maximum likelihood.

        Parameters
        ----------
        solver : solver class (not instance), optional
            e.g. ``ScipySolve``, ``JaxSolve`` or ``LanesSolve``.
            Default: backend-aware — ``ScipySolve`` on CPU (reference
            parity); on accelerators ``LanesSolve`` (the fleet lanes
            engine at batch 1: fixed-structure compiled programs,
            bounded dispatches, lanes-fd standard errors), falling back
            to ``JaxSolve`` when some parameters are fixed.
        report : bool, optional
            Print fit and metran reports when done.
        engine : str, optional
            Kalman engine override ("sequential"/"joint"/"sqrt"/
            "parallel"/"sqrt_parallel"; the reference's "numba"/"numpy"
            map to "sequential").
        init : str or None, optional
            Initial-parameter strategy: "reference" (constant alpha=10,
            reference parity), "autocorr" (data-driven lag-1
            autocorrelation seed — same optimum, fewer iterations; see
            :meth:`set_init_parameters`), or ``None`` to keep a
            hand-edited ``parameters["initial"]`` table (warm starts;
            built with the default method first if the table is empty).
        **kwargs
            Passed through to the solver's minimize call.
        """
        factors = self.get_factors(self.oseries)
        if factors is None:
            return
        self._init_kalmanfilter(engine=engine)
        if init is not None:
            self.set_init_parameters(method=init)
        elif self.parameters is None or len(self.parameters) != (
            self.nseries + self.nfactors
        ):
            # init=None promises "keep my hand-edited table", but the
            # table is absent or inconsistent with the factor structure
            # (__init__ seeds sdf rows before factors exist, so "non-
            # empty" alone is not "usable") — build the default one
            self.set_init_parameters()

        if solver is None:
            from ..config import is_accelerator

            if is_accelerator():
                from .solver import JaxSolve, LanesSolve

                # lanes engine: fixed-structure programs, bounded
                # dispatches — the TPU-proven path.  It optimizes every
                # parameter over the standard box; other fits take the
                # general JaxSolve instead.
                desired = (
                    LanesSolve if LanesSolve.supports(self) else JaxSolve
                )
            else:
                desired = ScipySolve
            # the auto-choice is parameter-table-dependent, so a cached
            # AUTO-selected solver is re-validated each solve (in both
            # directions); an explicitly requested solver stays sticky
            if self.fit is None or (
                getattr(self, "_fit_auto", False)
                and not isinstance(self.fit, desired)
            ):
                self.fit = desired(mt=self)
                self._fit_auto = True
        else:
            if self.fit is None or not isinstance(self.fit, solver):
                self.fit = solver(mt=self)
            # an explicit request always pins the choice, even when the
            # cached instance already matches (it may have been cached
            # by auto-selection)
            self._fit_auto = False
        self.settings["solver"] = self.fit._name

        success, optimal, stderr = self.fit.solve(**kwargs)

        # solver works in the parameter-table row order
        self.parameters["optimal"] = optimal
        self.parameters["stderr"] = stderr

        if not success:
            logger.warning("Model parameters could not be estimated well.")

        # basin-failure guard: from some starting points (notably the
        # constant init on panels whose specific parts are near-white)
        # L-BFGS slides EVERY alpha to the lower bound, a local optimum
        # where the model explains nothing — innovations then inherit
        # the data's full autocorrelation (tests/test_diagnostics.py
        # reproduces this).  Detectable, so say it.
        # "collapsed" = the AR decay is effectively white at this grid:
        # phi = exp(-dt/alpha) < e^-10 ~ 5e-5, i.e. alpha < dt/10 — tied
        # to the actual grid step rather than a fixed constant so the
        # guard tracks pmin/dt if either changes
        opt = np.asarray(optimal, float)
        collapse_thresh = float(self._dt) / 10.0
        if np.isfinite(opt).all() and (opt < collapse_thresh).all():
            remedy = (
                "Retry with solve(init='autocorr') (data-driven "
                "starting point)"
                if init != "autocorr" else
                "The data-driven init also landed here — try explicit "
                "initial values (parameters['initial']) or a different "
                "solver"
            )
            logger.warning(
                "All AR time scales collapsed to the lower bound — this "
                "is typically a local optimum where the model explains "
                "nothing.  %s, and check test_whiteness().", remedy,
            )

        if report:
            output = report if isinstance(report, str) else "full"
            print("\n" + self.fit_report(output=output))
            print("\n" + self.metran_report())

    # ------------------------------------------------------------------
    # persistence (new capability; the reference has none, SURVEY.md §5)
    # ------------------------------------------------------------------
    def to_file(self, path):
        """Serialize the model (data, factors, fitted parameters, fit
        statistics) to a single JSON file; see metran_tpu.io."""
        from .. import io as _io

        return _io.save_model(self, path)

    @classmethod
    def from_file(cls, path) -> "Metran":
        """Load a model saved with :meth:`to_file` (as ``cls``)."""
        from .. import io as _io

        return _io.load_model(path, cls=cls)

    def to_posterior_state(self, model_id=None, p=None):
        """Freeze this model into a serving :class:`~metran_tpu.serve.
        PosteriorState` (filtered posterior at the last timestep plus
        matrices and scaler stats) for the online-assimilation service;
        see :mod:`metran_tpu.serve`."""
        from ..serve.state import posterior_state_from_metran

        return posterior_state_from_metran(self, model_id=model_id, p=p)

    # ------------------------------------------------------------------
    # reports
    # ------------------------------------------------------------------
    def _get_file_info(self) -> dict:
        file_info = getattr(self, "file_info", None) or {
            "date_created": Timestamp.now()
        }
        file_info["date_modified"] = Timestamp.now()
        from ..version import __version__

        file_info["metran_tpu_version"] = __version__
        try:
            file_info["owner"] = getlogin()
        except Exception:
            file_info["owner"] = "Unknown"
        return file_info

    def fit_report(self, output: str = "full") -> str:
        """Fit statistics + parameter table (+|rho|>0.5 correlations).

        Same sections and layout as the reference (``metran/metran.py:
        1079-1183``).
        """
        model = {
            "tmin": str(self.settings["tmin"]),
            "tmax": str(self.settings["tmax"]),
            "freq": self.settings["freq"],
            "solver": self.settings["solver"],
        }
        fit = {
            "obj": f"{self.fit.obj_func:.2f}",
            "nfev": self.fit.nfev,
            "AIC": f"{self.fit.aic:.2f}",
            "": "",
        }
        parameters = self.parameters.loc[
            :, ["optimal", "stderr", "initial", "vary"]
        ].copy()
        stderr_pct = parameters["stderr"] / parameters["optimal"]
        parameters["stderr"] = "-"
        parameters.loc[parameters["vary"].astype(bool), "stderr"] = (
            stderr_pct.abs().apply("±{:.2%}".format)
        )
        parameters["initial"] = parameters["initial"].astype(str)
        parameters.loc[~parameters["vary"].astype(bool), "initial"] = "-"

        width = len(str(parameters).split("\n")[1])
        w = max(width - 45, 0)
        header = (
            f"Fit report {self.name[:14]:<16}{'':>{w}}Fit Statistics\n"
            + "=" * width
            + "\n"
        )
        basic = ""
        for (k1, v1), (k2, v2) in zip(model.items(), fit.items()):
            basic += f"{k1:<8} {str(v1):<16} {'':>{w}} {k2:<7} {v2:>{max(w, 1)}}\n"

        block = (
            f"\nParameters ({int(parameters.vary.sum())} were optimized)\n"
            + "=" * width
            + f"\n{parameters}"
        )

        correlations = ""
        if output == "full" and self.fit.pcor is not None:
            cor = {}
            pcor = self.fit.pcor
            for idx in pcor.index:
                for col in pcor.columns:
                    if (
                        abs(pcor.loc[idx, col]) > 0.5
                        and idx != col
                        and (col, idx) not in cor
                    ):
                        cor[(idx, col)] = round(pcor.loc[idx, col], 2)
            body = (
                DataFrame(cor.values(), index=cor.keys(), columns=["rho"]).to_string(
                    header=False
                )
                if cor
                else "None"
            )
            correlations = (
                "\n\nParameter correlations |rho| > 0.5\n" + "=" * width + "\n" + body
            )
        note = ""
        if getattr(self.fit, "nonpsd_pcov", False):
            note = (
                "\n\nWarning: parameter covariance was not positive "
                "semi-definite;\nnegative variances were clipped to "
                "zero — treat the affected\nstderr values as "
                "unreliable (flat or degenerate optimum)."
            )
        tele = ""
        telemetry = getattr(self.fit, "telemetry", None)
        if telemetry is not None and telemetry.stop_reason is not None:
            # why the optimizer stopped (metran_tpu.obs.FitTelemetry):
            # stop reason, checkpointed deviance drop, gradient norm,
            # line-search stalls, divergence diagnosis when any
            tele = (
                "\n\nFit telemetry\n" + "=" * width + "\n"
                + telemetry.summary()
            )
        return header + basic + block + correlations + note + tele

    def metran_report(self, output: str = "full") -> str:
        """Factor analysis, communality, state/observation parameters
        (+|rho|>0.5 state correlations); reference ``metran/metran.py:
        1185-1314``."""
        model = {
            "tmin": str(self.settings["tmin"]),
            "tmax": str(self.settings["tmax"]),
            "freq": self.settings["freq"],
        }
        fit = {"nfct": str(self.nfactors), "fep": f"{self.fep:.2f}%", "": ""}

        phi = np.diag(self.get_transition_matrix())
        q = self.get_transition_variance()
        names = self._state_columns()
        transition = DataFrame(np.array([phi, q]).T, index=names, columns=["phi", "q"])
        idx_width = max(len(n) for n in transition.index)

        communality = Series(
            self.get_communality(), index=self.oseries.columns, name=""
        )
        communality.index = [str(i).ljust(idx_width) for i in communality.index]
        communality = communality.apply("{:.2%}".format).to_frame()

        observation = DataFrame(
            self.factors,
            index=self.oseries.columns,
            columns=[f"gamma{i + 1}" for i in range(self.nfactors)],
        )
        observation.index = [str(i).ljust(idx_width) for i in observation.index]
        observation["scale"] = self.oseries_std
        observation["mean"] = self.oseries_mean

        width = max(
            len(str(transition).split("\n")[1]),
            len(str(observation).split("\n")[1]),
            44,
        )
        w = max(width - 43, 0)
        header = (
            f"Metran report {self.name[:14]:<14}{'':>{w}}Factor Analysis\n"
            + "=" * width
            + "\n"
        )
        factors = ""
        for (k1, v1), (k2, v2) in zip(model.items(), fit.items()):
            factors += f"{k1:<8} {str(v1):<19} {k2:<7} {str(v2):>{max(w, 1)}}\n"

        blocks = (
            "\nCommunality\n" + "=" * width + f"\n{communality}\n"
            "\nState parameters\n" + "=" * width + f"\n{transition}\n"
            "\nObservation parameters\n" + "=" * width + f"\n{observation}\n"
        )

        correlations = ""
        if output == "full":
            cor = {}
            pcor = self.get_state_means().corr()
            for idx in pcor.index:
                for col in pcor.columns:
                    if (
                        abs(pcor.loc[idx, col]) > 0.5
                        and idx != col
                        and (col, idx) not in cor
                    ):
                        cor[(idx, col)] = round(pcor.loc[idx, col], 2)
            body = (
                DataFrame(cor.values(), index=cor.keys(), columns=["rho"]).to_string(
                    header=False
                )
                if cor
                else "None"
            )
            correlations = (
                "\nState correlations |rho| > 0.5\n" + "=" * width + "\n" + body + "\n"
            )
        return header + factors + blocks + correlations
