"""Visualization for Metran models, exposed as ``mt.plots``.

Covers the reference's plot surface (``metran/plots.py``: scree plot,
stacked state means, simulations with confidence bands, sdf/cdf
decompositions) with an independent implementation.  The visual
conventions — specific factors in blue (C0), common factors cycling from
red (C3), legends above the axes, gridded panels with data-driven height
ratios — match the reference so figures stay familiar to its users, but
the code is organized around small layout/style helpers (`_stack`,
`_component_style`, `_window`) instead of per-method gridspec wrangling.
"""

from __future__ import annotations

from logging import getLogger

from typing import List, NamedTuple, Optional, Sequence, Tuple

import matplotlib.pyplot as plt
import numpy as np
from pandas import DataFrame, Timestamp
from scipy.stats import norm

from ..utils import get_height_ratios

logger = getLogger(__name__)

_PANEL_W = 10.0  # house figure width
_PANEL_H = 2.0  # per-panel height in stacked figures


class _Style(NamedTuple):
    color: str
    label: str
    zorder: int


def _component_style(column: str, cdf_rank: int = 0) -> _Style:
    """House style for a state/decomposition column.

    Specific factors ("<series>_sdf") draw in blue on top; the k-th common
    factor ("cdf<k>") draws behind in the red-onward color cycle.
    """
    if column.startswith("cdf"):
        color = f"C{3 + cdf_rank % 10}"
        label = f"common dynamic factor {column[len('cdf'):]}"
        return _Style(color, label, 2)
    series = column[: -len("_sdf")] if column.endswith("_sdf") else column
    return _Style("C0", f"specific dynamic factor {series}", 3)


def _decorate(ax) -> None:
    """Grid plus the house legend: above the axis, unframed, 3 columns."""
    ax.grid(visible=True)
    ax.legend(loc=(0, 1), ncol=3, frameon=False, numpoints=3)


def _window(index, tmin, tmax) -> Tuple:
    """Resolve a (tmin, tmax) request against a DatetimeIndex."""
    lo = index[0] if tmin is None else Timestamp(tmin)
    hi = index[-1] if tmax is None else Timestamp(tmax)
    return lo, hi


def _panel_limits(frame: DataFrame, lo, hi) -> List[Tuple[float, float]]:
    """Per-column (min, max) over the plot window, for height ratios."""
    visible = frame.loc[lo:hi]
    return [(float(visible[c].min()), float(visible[c].max())) for c in frame]


def _stack(n_panels: int, ratios: Sequence[float], height: Optional[float] = None):
    """A shared-x column of axes whose heights follow ``ratios``."""
    fig = plt.figure(figsize=(_PANEL_W, height or n_panels * _PANEL_H))
    grid = fig.add_gridspec(nrows=n_panels, ncols=1, height_ratios=list(ratios))
    axes: List = []
    for row in range(n_panels):
        axes.append(fig.add_subplot(grid[row], sharex=axes[0] if axes else None))
    return fig, axes


class MetranPlot:
    """Plotting namespace bound to a solved :class:`Metran` model."""

    def __init__(self, mt):
        self.mt = mt

    # -- factor analysis ------------------------------------------------
    def scree_plot(self):
        """Eigenvalue scree plot of the factor analysis."""
        eigval = np.asarray(self.mt.eigval)
        rank = 1 + np.arange(eigval.shape[0])
        fig, ax = plt.subplots(figsize=(_PANEL_W, 4))
        ax.bar(rank, eigval, facecolor="none", edgecolor="C0", linewidth=2)
        ax.plot(rank, eigval, marker="o", ms=7, mfc="none", color="C3")
        ax.set_xticks(rank)
        ax.set_xlabel("eigenvalue number")
        ax.set_ylabel("eigenvalue")
        ax.grid(visible=True)
        fig.tight_layout()
        return ax

    # -- states ---------------------------------------------------------
    def state_means(self, tmin=None, tmax=None, adjust_height=True):
        """Stacked panels of every smoothed state mean (sdf + cdf)."""
        states = self.mt.get_state_means()
        lo, hi = _window(states.index, tmin, tmax)
        limits = _panel_limits(states, lo, hi) if adjust_height else None
        ratios = (
            get_height_ratios(limits)
            if adjust_height
            else np.ones(states.columns.size)
        )
        fig, axes = _stack(states.columns.size, ratios)
        cdf_rank = 0
        for ax, column in zip(axes, states.columns):
            style = _component_style(column, cdf_rank=cdf_rank)
            cdf_rank += column.startswith("cdf")
            ax.plot(states.index, states[column], color=style.color,
                    label=style.label)
            _decorate(ax)
        if limits is not None:
            for ax, lim in zip(axes, limits):
                ax.set_ylim(lim)
        axes[-1].set_xlabel("")
        fig.tight_layout()
        return fig.axes

    # -- simulations ----------------------------------------------------
    def simulation(self, name, alpha=0.05, tmin=None, tmax=None, ax=None):
        """Simulated mean for one series, with observations and CI band."""
        sim = self.mt.get_simulation(name, alpha=alpha)
        obs = self.mt.get_observations(
            standardized=False,
            masked=self.mt.masked_observations is not None,
        )[name]

        fig = None
        if ax is None:
            fig, ax = plt.subplots(figsize=(_PANEL_W, 4))
        if alpha is None:  # point simulation only — sim is a Series
            ax.plot(sim.index, np.asarray(sim), label=f"simulation {name}")
        else:
            ax.plot(sim.index, sim["mean"], label=f"simulation {name}")
            ax.fill_between(
                sim.index, sim["lower"], sim["upper"], color="gray",
                alpha=0.5, label=f"{1 - alpha:.0%}-confidence interval",
            )
        ax.plot(obs.index, obs, ls="none", marker=".", ms=3, color="k",
                label="observations")
        _decorate(ax)
        ax.set_xlim(_window(sim.index, tmin, tmax))
        if fig is not None:
            fig.tight_layout()
        return ax

    def forecast(self, name, steps=90, alpha=0.05, context=365, ax=None):
        """In-sample simulation continued by the out-of-sample forecast.

        No reference counterpart (the reference has no forecasting):
        the last ``context`` grid periods of the simulation, the
        observation dots, and the ``steps``-period forecast mean with
        its widening prediction interval beyond the data's end (marked
        by a vertical line).
        """
        sim = self.mt.get_simulation(name, alpha=alpha)
        fc = self.mt.forecast(name, steps=steps, alpha=alpha)
        obs = self.mt.get_observations(
            standardized=False,
            masked=self.mt.masked_observations is not None,
        )[name]

        fig = None
        if ax is None:
            fig, ax = plt.subplots(figsize=(_PANEL_W, 4))
        sim = sim.iloc[-int(context):]
        if alpha is None:  # point values only — sim/fc are Series
            ax.plot(sim.index, np.asarray(sim), label=f"simulation {name}")
            ax.plot(fc.index, np.asarray(fc), ls="--",
                    label=f"forecast {name}")
        else:
            ax.plot(sim.index, sim["mean"], label=f"simulation {name}")
            ax.plot(fc.index, fc["mean"], ls="--", label=f"forecast {name}")
            ax.fill_between(
                fc.index, fc["lower"], fc["upper"], color="gray",
                alpha=0.5, label=f"{1 - alpha:.0%}-prediction interval",
            )
        obs = obs.loc[sim.index[0]:]
        ax.plot(obs.index, obs, ls="none", marker=".", ms=3, color="k",
                label="observations")
        ax.axvline(obs.index[-1], color="k", lw=0.8, ls=":")
        _decorate(ax)
        if fig is not None:
            fig.tight_layout()
        return ax

    def innovations(self, name=None, alpha=0.05, tmin=None, tmax=None,
                    warmup=0, ax=None):
        """Standardized one-step-ahead innovations with N(0,1) bands.

        No reference counterpart (the reference exposes no residuals):
        the whiteness diagnostic view of :meth:`Metran.get_innovations`
        — residual dots for ``name`` (or every series when ``name`` is
        None) against the two-sided ``alpha`` normal band; points
        outside the band flag dates the fitted model does not explain
        at that confidence.  The earliest dates can exceed the band
        from the filter's initialization transient alone; ``warmup``
        hides the first that-many steps (see
        :meth:`Metran.get_innovations`).
        """
        innov = self.mt.get_innovations(warmup=warmup)
        cols = list(innov.columns) if name is None else [name]
        if any(c not in innov.columns for c in cols):
            logger.error("Unknown name: %s", name)
            return None
        fig = None
        if ax is None:
            fig, ax = plt.subplots(figsize=(_PANEL_W, 4))
        lo, hi = _window(innov.index, tmin, tmax)
        window = innov.loc[lo:hi]
        for col in cols:
            s = window[col].dropna()
            ax.plot(s.index, s, ls="none", marker=".", ms=3, label=col)
        if alpha is not None:
            z = norm.ppf(1 - alpha / 2.0)
            for b in (-z, z):
                ax.axhline(b, color="k", lw=0.8, ls=":")
            if len(window.index):  # empty window: bands only, no label
                ax.text(
                    window.index[0], z,
                    f" ±{z:.2f} ({1 - alpha:.0%} band)",
                    va="bottom", fontsize=8,
                )
        ax.axhline(0.0, color="k", lw=0.8)
        ax.set_ylabel("standardized innovation")
        _decorate(ax)
        if fig is not None:
            fig.tight_layout()
        return ax

    def sample_paths(self, name, n_draws=32, seed=0, tmin=None, tmax=None,
                     ax=None):
        """Joint posterior path fan for one series with observations.

        No reference counterpart (the reference has no sampling): thin
        overlaid draws from :meth:`Metran.sample_simulation` — each
        passes exactly through the observed dots and spreads only in
        the gaps, so a masked stretch shows the genuine joint
        uncertainty of the reconstruction (unlike the marginal CI
        band, neighboring dates within one path move together).
        """
        paths = self.mt.sample_simulation(name, n_draws=n_draws, seed=seed)
        if paths is None:
            return None
        obs = self.mt.get_observations(
            masked=self.mt.masked_observations is not None,
        )[name]
        fig = None
        if ax is None:
            fig, ax = plt.subplots(figsize=(_PANEL_W, 4))
        lo, hi = _window(paths.index, tmin, tmax)
        window = paths.loc[lo:hi]
        ax.plot(window.index, window.to_numpy(), color="C0", lw=0.6,
                alpha=0.25)
        ax.plot([], [], color="C0", lw=1.2,
                label=f"{n_draws} posterior paths {name}")
        obs = obs.loc[lo:hi]
        ax.plot(obs.index, obs, ls="none", marker=".", ms=3, color="k",
                label="observations")
        _decorate(ax)
        if fig is not None:
            fig.tight_layout()
        return ax

    def simulations(self, alpha=0.05, tmin=None, tmax=None):
        """One simulation panel per observed series, shared axes."""
        def draw(name, ax):
            self.simulation(name, alpha=alpha, tmin=tmin, tmax=tmax, ax=ax)

        return self._series_grid(draw)

    # -- decompositions -------------------------------------------------
    def decomposition(self, name, tmin=None, tmax=None, ax=None, split=False,
                      adjust_height=True, **kwargs):
        """sdf + per-factor cdf contributions to one simulated series.

        ``split=True`` gives each contribution its own panel (heights
        scaled to the data range unless ``adjust_height=False``); the
        default overlays them on a single axis.
        """
        parts = self.mt.decompose_simulation(name, **kwargs)
        lo, hi = _window(parts.index, tmin, tmax)
        styles = []
        cdf_rank = 0
        for column in parts.columns:
            styles.append(_component_style(column, cdf_rank=cdf_rank))
            cdf_rank += column.startswith("cdf")

        def draw(target, column, style):
            target.plot(parts.index, parts[column], color=style.color,
                        zorder=style.zorder, label=f"{column} {name}")
            _decorate(target)

        if ax is not None:  # caller-managed axis: always overlay
            for column, style in zip(parts.columns, styles):
                draw(ax, column, style)
            return ax.figure.axes

        if split:
            limits = _panel_limits(parts, lo, hi) if adjust_height else None
            ratios = (
                get_height_ratios(limits)
                if adjust_height
                else np.ones(parts.columns.size)
            )
            fig, axes = _stack(parts.columns.size, ratios, height=6)
            for target, column, style in zip(axes, parts.columns, styles):
                draw(target, column, style)
            if limits is not None:
                for target, lim in zip(axes, limits):
                    target.set_ylim(lim)
        else:
            fig, one = plt.subplots(figsize=(_PANEL_W, 4))
            for column, style in zip(parts.columns, styles):
                draw(one, column, style)
        fig.tight_layout()
        return fig.axes

    def decompositions(self, tmin=None, tmax=None, **kwargs):
        """One overlay decomposition panel per observed series."""
        def draw(name, ax):
            self.decomposition(name, tmin=tmin, tmax=tmax, ax=ax, **kwargs)

        return self._series_grid(draw)

    # -- shared layout --------------------------------------------------
    def _series_grid(self, draw):
        """A shared-x/y panel per observed series; ``draw(name, ax)``."""
        names = list(self.mt.snames)
        fig, axes = plt.subplots(
            len(names), 1, sharex=True, sharey=True,
            figsize=(_PANEL_W, len(names) * _PANEL_H), squeeze=False,
        )
        axes = axes.ravel()
        for name, ax in zip(names, axes):
            draw(name, ax)
        fig.tight_layout()
        return axes
