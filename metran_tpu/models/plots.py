"""Plotting helpers exposed as ``mt.plots`` (reference: ``metran/plots.py``).

Same plot surface: scree plot, stacked state means, per-series simulation
with observations and confidence band, and sdf/cdf decomposition (optionally
split over axes with height ratios).
"""

from __future__ import annotations

import matplotlib.pyplot as plt
import numpy as np
from pandas import Timestamp

from ..utils import get_height_ratios


class MetranPlot:
    """Plots available directly from the Metran class."""

    def __init__(self, mt):
        self.mt = mt

    def scree_plot(self):
        """Eigenvalue scree plot of the factor analysis."""
        n_ev = np.arange(self.mt.eigval.shape[0]) + 1
        fig, ax = plt.subplots(1, 1, figsize=(10, 4))
        ax.plot(n_ev, self.mt.eigval, marker="o", ms=7, mfc="none", c="C3")
        ax.bar(n_ev, self.mt.eigval, facecolor="none", edgecolor="C0", linewidth=2)
        ax.grid(visible=True)
        ax.set_xticks(n_ev)
        ax.set_ylabel("eigenvalue")
        ax.set_xlabel("eigenvalue number")
        fig.tight_layout()
        return ax

    def state_means(self, tmin=None, tmax=None, adjust_height=True):
        """Stacked plots of all smoothed specific/common state means."""
        states = self.mt.get_state_means()
        tmin = states.index[0] if tmin is None else tmin
        tmax = states.index[-1] if tmax is None else tmax

        ylims = []
        if adjust_height:
            for s in states:
                hs = states.loc[tmin:tmax, s]
                ylims.append((float(hs.min()), float(hs.max())))
            hrs = get_height_ratios(ylims)
        else:
            hrs = [1] * states.columns.size

        fig = plt.figure(figsize=(10, states.columns.size * 2))
        gs = fig.add_gridspec(ncols=1, nrows=states.columns.size, height_ratios=hrs)

        ax0 = None
        for i, col in enumerate(states.columns):
            iax = fig.add_subplot(gs[i], sharex=ax0)
            if ax0 is None:
                ax0 = iax
            if col.startswith("cdf"):
                c, lbl = "C3", f"common dynamic factor {col[3:]}"
            else:
                c, lbl = "C0", f"specific dynamic factor {col.replace('_sdf', '')}"
            states.loc[:, col].plot(ax=iax, label=lbl, color=c)
            iax.legend(loc=(0, 1), ncol=3, frameon=False, numpoints=3)
            iax.grid(visible=True)
            if adjust_height:
                iax.set_ylim(ylims[i])
        iax.set_xlabel("")
        fig.tight_layout()
        return fig.axes

    def simulation(self, name, alpha=0.05, tmin=None, tmax=None, ax=None):
        """Simulated mean + observations (+ confidence band) for a series."""
        sim = self.mt.get_simulation(name, alpha=alpha)
        obs = self.mt.get_observations(
            standardized=False, masked=self.mt.masked_observations is not None
        ).loc[:, name]

        tmin = sim.index[0] if tmin is None else Timestamp(tmin)
        tmax = sim.index[-1] if tmax is None else Timestamp(tmax)

        created_fig = None
        if ax is None:
            created_fig, ax = plt.subplots(1, 1, figsize=(10, 4))

        if alpha is None:
            ax.plot(sim.index, sim, label=f"simulation {name}")
        else:
            ax.plot(sim.index, sim["mean"], label=f"simulation {name}")
            ax.fill_between(
                sim.index,
                sim["lower"],
                sim["upper"],
                color="gray",
                alpha=0.5,
                label=f"{1 - alpha:.0%}-confidence interval",
            )
        ax.plot(
            obs.index, obs, marker=".", ms=3, color="k", ls="none", label="observations"
        )
        ax.legend(loc=(0, 1), ncol=3, frameon=False, numpoints=3)
        ax.grid(visible=True)
        ax.set_xlim(tmin, tmax)
        if created_fig is not None:
            created_fig.tight_layout()
        return ax

    def simulations(self, alpha=0.05, tmin=None, tmax=None):
        """Simulation plot per observed series, shared axes."""
        nrows = len(self.mt.snames)
        fig, axes = plt.subplots(
            nrows, 1, sharex=True, sharey=True, figsize=(10, nrows * 2)
        )
        for i, name in enumerate(self.mt.snames):
            self.simulation(name, alpha=alpha, tmin=tmin, tmax=tmax, ax=axes.flat[i])
        fig.tight_layout()
        return axes

    def decomposition(
        self,
        name,
        tmin=None,
        tmax=None,
        ax=None,
        split=False,
        adjust_height=True,
        **kwargs,
    ):
        """Plot the sdf + cdf decomposition of a simulated series."""
        decomposition = self.mt.decompose_simulation(name, **kwargs)
        tmin = decomposition.index[0] if tmin is None else tmin
        tmax = decomposition.index[-1] if tmax is None else tmax

        fig = None
        if ax is None:
            if adjust_height and split:
                ylims = [
                    (
                        float(decomposition.loc[tmin:tmax, s].min()),
                        float(decomposition.loc[tmin:tmax, s].max()),
                    )
                    for s in decomposition
                ]
                hrs = get_height_ratios(ylims)
            elif split:
                ylims, hrs = None, [1] * decomposition.columns.size
            else:
                ylims, hrs = None, [1]
            nrows = decomposition.columns.size if split else 1
            fig = plt.figure(figsize=(10, 6 if split else 4))
            gs = fig.add_gridspec(ncols=1, nrows=nrows, height_ratios=hrs)

        cdfcount = 0
        iax = ax
        ax0 = None
        for i, col in enumerate(decomposition.columns):
            if fig is not None and (i == 0 or split):
                iax = fig.add_subplot(gs[i], sharex=ax0)
                if ax0 is None:
                    ax0 = iax
            if col.startswith("cdf"):
                c = f"C{3 + cdfcount % 10}"
                cdfcount += 1
                zorder = 2
            else:
                c, zorder = "C0", 3
            s = decomposition[col]
            iax.plot(s.index, s, label=f"{col} {name}", color=c, zorder=zorder)
            iax.grid(visible=True)
            iax.legend(loc=(0, 1), ncol=3, frameon=False, numpoints=3)
            if fig is not None and split and adjust_height and ylims is not None:
                iax.set_ylim(ylims[i])
        if fig is not None:
            fig.tight_layout()
        return iax.figure.axes

    def decompositions(self, tmin=None, tmax=None, **kwargs):
        """Decomposition plot per observed series, shared axes."""
        nrows = len(self.mt.snames)
        fig, axes = plt.subplots(
            nrows, 1, sharex=True, sharey=True, figsize=(10, nrows * 2)
        )
        for i, name in enumerate(self.mt.snames):
            self.decomposition(
                name,
                tmin=tmin,
                tmax=tmax,
                ax=axes.flat[i],
                split=False,
                adjust_height=False,
                **kwargs,
            )
        fig.tight_layout()
        return axes
