"""Solvers for maximum-likelihood estimation of Metran models.

Same plugin boundary as the reference (``metran/solver.py``): a solver class
is handed the model, reads its parameter table, minimizes
``mt.get_mle(p)`` (the deviance, -2 log L) and returns
``(success, optimal, stderr)``.  Differences, by design:

- the objective and its **exact gradient** are computed on-device by JAX
  autodiff (the reference uses finite differences through scipy);
- the parameter covariance for standard errors comes from the **exact
  autodiff Hessian** at the optimum (reference: numerical Hessian with an
  epsilon-escalation repair loop, ``solver.py:65-140``), with the same
  nearest-PSD repair as a fallback;
- ``JaxSolve`` runs L-BFGS fully on-device (optax) under ``jit`` with a
  bound-preserving reparameterization, so fleets of models can be solved
  with ``vmap``/``pjit`` without host round-trips.
"""

from __future__ import annotations

from logging import getLogger
from typing import Callable, NamedTuple, Optional, Tuple

import numpy as np
from pandas import DataFrame

logger = getLogger(__name__)


class SolverDivergenceError(RuntimeError):
    """The fit objective became non-finite during optimization.

    The actionable replacement for an opaque optimizer failure: carries
    the offending parameter point (``params``; unconstrained ``theta``
    when raised from :func:`run_lbfgs` before the solver maps it back),
    the non-finite ``value``, and the iteration count.  Typical causes:
    an ``alpha`` driven into a degenerate region where the innovation
    covariance is ill-conditioned, or a float32 run whose deviance
    overflowed — tighten the parameter bounds (``pmin``/``pmax``), cap
    ``alpha`` (the fleet solver's soft cap), or run under
    ``METRAN_TPU_X64=1``.
    """

    def __init__(self, message: str, params=None, value=None, n_iters=None):
        super().__init__(message)
        self.params = params
        self.value = value
        self.n_iters = n_iters


def near_psd(a: np.ndarray, epsilon: float = 0.0) -> np.ndarray:
    """Nearest positive semi-definite matrix by eigenvalue clipping.

    Same scaling construction as the reference's ``_nearPSD``
    (``metran/solver.py:167-192``).
    """
    n = a.shape[0]
    eigval, eigvec = np.linalg.eig(a)
    val = np.maximum(eigval, epsilon)
    vec = np.asarray(eigvec)
    with np.errstate(divide="ignore", invalid="ignore"):
        t = 1.0 / (vec**2 @ val.T)
        t = np.sqrt(np.diag(np.asarray(t).reshape(n)))
        b = t @ vec * np.diag(np.sqrt(np.asarray(val).reshape(n)))
    return b @ b.T


class BaseSolver:
    """Shared machinery: objective plumbing, covariance, correlations."""

    _name = "BaseSolver"

    def __init__(self, mt, **kwargs):
        self.mt = mt
        self.pcov: Optional[DataFrame] = None
        self.pcor: Optional[DataFrame] = None
        self.nfev: Optional[int] = None
        self.result = None
        self.obj_func: Optional[float] = None
        self.aic: Optional[float] = None
        # True when the parameter covariance had negative variances
        # (clipped to zero in _finalize; surfaced in the fit report)
        self.nonpsd_pcov: bool = False
        # per-fit optimizer trajectory (metran_tpu.obs.FitTelemetry):
        # filled by solvers that run through run_lbfgs (JaxSolve);
        # surfaced by Metran.fit_report()
        self.telemetry = None

    # -- objective ------------------------------------------------------
    def objfunction(self, p, callback: Optional[Callable] = None) -> float:
        if callback is not None:
            p = callback(p)
        return float(self.mt.get_mle(p))

    def _full_params(self, x: np.ndarray) -> np.ndarray:
        """Embed varying parameters into the full parameter vector."""
        par = self.initial.copy()
        par[self.vary] = x
        return par

    def _setup(self):
        self.vary = self.mt.parameters.vary.values.astype(bool)
        self.initial = self.mt.parameters.initial.values.astype(float).copy()
        self.names = self.mt.parameters.index[self.vary]
        pmin = self.mt.parameters.pmin.values[self.vary]
        pmax = self.mt.parameters.pmax.values[self.vary]
        self.bounds = [
            (
                None if b is None or (isinstance(b, float) and np.isnan(b)) else b,
                None if u is None or (isinstance(u, float) and np.isnan(u)) else u,
            )
            for b, u in zip(pmin, pmax)
        ]

    # -- covariance / stderr -------------------------------------------
    def _get_covariance(self, x: np.ndarray) -> np.ndarray:
        """Parameter covariance from the exact autodiff Hessian of the
        deviance over the varying parameters, with nearest-PSD repair."""
        import jax

        def dev_vary(xv):
            import jax.numpy as jnp

            full = jnp.asarray(self.initial).at[np.flatnonzero(self.vary)].set(xv)
            # grad="autodiff" pinned: jax.hessian forward-differentiates
            # the gradient, which a custom_vjp (the closed-form adjoint
            # gradient engine) does not admit
            return self.mt._deviance_jax(full, grad="autodiff")

        hessian = np.asarray(jax.hessian(dev_vary)(np.asarray(x, float)))
        cov = np.linalg.pinv(hessian)
        if np.amin(np.diag(cov)) <= 0:
            try:
                cov = np.linalg.pinv(near_psd(hessian))
            except Exception as e:
                logger.debug("Could not repair covariance: %s", e)
        return cov

    @staticmethod
    def _get_correlations(pcov: DataFrame) -> DataFrame:
        # clip: a non-PSD pcov's negative variances would otherwise
        # emit sqrt RuntimeWarnings.  The clipped (zero) rows divide to
        # +/-inf, not NaN — map every non-finite entry to NaN so a
        # clipped parameter's undefined correlations stay excluded from
        # fit_report's |rho| > 0.5 listing exactly as the pre-clip NaN
        # rows were
        d = np.sqrt(np.clip(np.diag(pcov.values), 0.0, None))
        with np.errstate(divide="ignore", invalid="ignore"):
            corr = pcov.values / np.outer(d, d)
        corr[~np.isfinite(corr)] = np.nan
        return DataFrame(corr, index=pcov.index, columns=pcov.columns)

    def _finalize(self, x, fun, nfev, success, pcov=None):
        """Common post-optimization bookkeeping shared by solvers."""
        if pcov is None:
            pcov = self._get_covariance(x)
        diag = np.diag(pcov)
        neg = diag < 0
        self.nonpsd_pcov = bool(np.any(neg))
        if self.nonpsd_pcov:
            # a numerical Hessian at a flat/degenerate optimum can come
            # out indefinite: clip the negative variances to zero
            # (stderr 0) instead of spraying RuntimeWarnings and NaN
            # stderrs; Metran.fit_report carries an explicit note
            logger.warning(
                "parameter covariance is not PSD (%d negative "
                "variance(s) clipped to zero); treat the affected "
                "standard errors as unreliable", int(neg.sum()),
            )
        _stderr = np.sqrt(np.clip(diag, 0.0, None))
        optimal = self._full_params(np.asarray(x, float))
        stderr = np.full(len(optimal), np.nan)
        stderr[self.vary] = _stderr
        self.pcov = DataFrame(pcov, index=self.names, columns=self.names)
        self.pcor = self._get_correlations(self.pcov)
        self.nfev = int(nfev)
        self.obj_func = float(fun)
        self.aic = 2 * int(self.vary.sum()) + self.obj_func
        return bool(success), optimal, stderr


class ScipySolve(BaseSolver):
    """scipy.optimize.minimize driving the on-device objective.

    Drop-in equivalent of the reference's default solver
    (``metran/solver.py:195-305``), with the gradient supplied by JAX
    autodiff (``use_grad=False`` recovers the reference's gradient-free
    finite-difference behavior).
    """

    _name = "ScipySolve"

    def solve(self, method: str = "l-bfgs-b", use_grad: bool = True, **kwargs):
        from scipy.optimize import minimize

        self._setup()
        x0 = self.initial[self.vary]

        if use_grad:
            value_and_grad = self.mt._deviance_value_and_grad
            idx = np.flatnonzero(self.vary)

            def fun(x):
                v, g = value_and_grad(self._full_params(x))
                return float(v), np.asarray(g, float)[idx]

            self.result = minimize(
                fun=fun, x0=x0, method=method, jac=True, bounds=self.bounds, **kwargs
            )
        else:
            self.result = minimize(
                fun=self.objfunction,
                x0=x0,
                method=method,
                bounds=self.bounds,
                args=(self._full_params,),
                **kwargs,
            )

        # stderr: L-BFGS-B inverse-Hessian approximation when available,
        # exact autodiff Hessian otherwise (reference: solver.py:257-266)
        pcov = None
        if hasattr(self.result, "hess_inv"):
            try:
                pcov = np.asarray(self.result.hess_inv.todense())
            except AttributeError:
                pcov = np.asarray(self.result.hess_inv)
            # sign test instead of isnan(sqrt(...)): same verdict, no
            # RuntimeWarning noise from sqrt of a negative variance
            d = np.diag(pcov)
            if np.isnan(d).any() or (d < 0).any():
                pcov = None
        if pcov is None:
            pcov = self._get_covariance(self.result.x)

        success = getattr(self.result, "success", True)
        return self._finalize(
            self.result.x, self.result.fun, self.result.nfev, success, pcov
        )


class JaxSolve(BaseSolver):
    """Fully on-device L-BFGS (optax) with bound-preserving reparam.

    The whole optimization loop — objective, gradient, line search, updates
    — runs inside one ``jit``, so it can be ``vmap``-ed over fleets of
    models (see ``metran_tpu.parallel``).  Bounds are enforced through
    ``alpha = pmin + exp(theta)`` (upper bounds, when finite, via a scaled
    sigmoid), matching the reference's L-BFGS-B box constraints.
    """

    _name = "JaxSolve"

    def solve(self, maxiter: int = 200, tol: Optional[float] = None,
              **kwargs):
        import jax
        import jax.numpy as jnp

        if kwargs.pop("n_starts", 1) > 1:
            logger.warning(
                "n_starts is a LanesSolve feature; JaxSolve runs a "
                "single-start fit (this model fell back because some "
                "parameters are fixed or carry custom bounds)"
            )

        self._setup()
        idx = np.flatnonzero(self.vary)
        lower = np.array(
            [b[0] if b[0] is not None else -np.inf for b in self.bounds]
        )
        upper = np.array(
            [b[1] if b[1] is not None else np.inf for b in self.bounds]
        )

        transform = BoxTransform(lower, upper)
        dev_full = self.mt._deviance_jax

        def objective(theta):
            x = transform.forward(theta)
            full = jnp.asarray(self.initial).at[idx].set(x)
            return dev_full(full)

        theta0 = transform.inverse(jnp.asarray(self.initial[self.vary]))
        from ..obs.telemetry import FitTelemetry

        self.telemetry = FitTelemetry()
        try:
            theta, value, _iters, nfev, converged = run_lbfgs(
                objective, theta0, maxiter=maxiter, tol=tol,
                raise_on_divergence=True, telemetry=self.telemetry,
                grad_engine=self.mt._resolved_grad(),
            )
        except SolverDivergenceError as exc:
            # name the offending parameters (data units, table order)
            # instead of surfacing an opaque optimizer failure
            x_bad = np.asarray(transform.forward(jnp.asarray(exc.params)),
                               float)
            at = ", ".join(
                f"{name}={val:.6g}" for name, val in zip(self.names, x_bad)
            )
            raise SolverDivergenceError(
                f"fit objective for model {self.mt.name!r} became "
                f"non-finite (value={exc.value!r}) after {exc.n_iters} "
                f"iterations at parameters [{at}] — likely an "
                "ill-conditioned innovation covariance in a degenerate "
                "alpha region; tighten pmin/pmax for those parameters, "
                "cap alpha, or rerun with METRAN_TPU_X64=1",
                params=x_bad, value=exc.value, n_iters=exc.n_iters,
            ) from exc
        x = np.asarray(transform.forward(theta), float)

        return self._finalize(x, float(value), int(nfev), bool(converged))


class BoxTransform:
    """Smooth bijection from unconstrained theta to box [lower, upper]."""

    def __init__(self, lower: np.ndarray, upper: np.ndarray):
        self.lower = np.asarray(lower, float)
        self.upper = np.asarray(upper, float)

    def forward(self, theta):
        import jax.numpy as jnp

        lo, up = self.lower, self.upper
        both = np.isfinite(lo) & np.isfinite(up)
        only_lo = np.isfinite(lo) & ~np.isfinite(up)
        only_up = ~np.isfinite(lo) & np.isfinite(up)
        # NaN-safe branch arithmetic: every branch is computed under AD even
        # when unselected, so infinities must never enter any branch
        lo_s = np.where(np.isfinite(lo), lo, 0.0)
        up_s = np.where(np.isfinite(up), up, 1.0)
        x = theta
        x = jnp.where(only_lo, lo_s + jnp.exp(theta), x)
        x = jnp.where(only_up, up_s - jnp.exp(-theta), x)
        x = jnp.where(both, lo_s + (up_s - lo_s) * jax_sigmoid(theta), x)
        return x

    def inverse(self, x):
        import jax.numpy as jnp

        lo, up = self.lower, self.upper
        both = np.isfinite(lo) & np.isfinite(up)
        only_lo = np.isfinite(lo) & ~np.isfinite(up)
        only_up = ~np.isfinite(lo) & np.isfinite(up)
        theta = x
        theta = jnp.where(only_lo, jnp.log(jnp.maximum(x - lo, 1e-12)), theta)
        theta = jnp.where(only_up, -jnp.log(jnp.maximum(up - x, 1e-12)), theta)
        frac = jnp.clip((x - lo) / jnp.where(both, up - lo, 1.0), 1e-9, 1 - 1e-9)
        theta = jnp.where(both, jnp.log(frac) - jnp.log1p(-frac), theta)
        return theta


def jax_sigmoid(x):
    import jax

    return jax.nn.sigmoid(x)


def tree_norm(tree):
    """Global l2 norm of a pytree.

    ``optax.tree_utils.tree_norm`` where available (>= 0.2.5), falling
    back to the older ``tree_l2_norm`` name so a pinned-down environment
    still solves.
    """
    import optax.tree_utils as otu

    fn = getattr(otu, "tree_norm", None) or otu.tree_l2_norm
    return fn(tree)


def zoom_linesearch(max_linesearch_steps: int):
    """Zoom line search restarting each search at step length 1.

    ``initial_guess_strategy="one"`` is optax's own default for
    ``optax.lbfgs()`` but only exists as a kwarg from 0.2.4; older
    versions hardcode the equivalent behavior, so just drop it there.
    """
    import optax

    try:
        return optax.scale_by_zoom_linesearch(
            max_linesearch_steps=max_linesearch_steps,
            initial_guess_strategy="one",
        )
    except TypeError:
        return optax.scale_by_zoom_linesearch(
            max_linesearch_steps=max_linesearch_steps
        )


def lbfgs_trace_ctx(dtype):
    """Trace context for optax L-BFGS runs of the given parameter dtype.

    optax 0.2.x seeds its zoom-line-search state with *default-dtype*
    scalars (``jnp.asarray(0.0)``, ``jnp.asarray(jnp.inf)``), so a
    float32 objective under an x64-enabled backend mixes f64 state
    leaves into f32 iterates and hits ``lax.cond`` branch-type
    mismatches (``TypeError: true_fun and false_fun output must have
    identical types``) on the very first iteration — the root cause of
    the former tier-1 "f32/optax" failures.  Tracing the whole
    optimizer (state init included) under ``jax.experimental.
    disable_x64`` makes every default 32-bit, which is also exactly the
    regime the f32 path models: a real f32 accelerator has x64 off.
    float64 runs trace under the ambient config unchanged.
    """
    import jax
    import jax.numpy as jnp

    if jnp.dtype(dtype).itemsize < 8 and jax.config.jax_enable_x64:
        from jax.experimental import disable_x64

        return disable_x64()
    from contextlib import nullcontext

    return nullcontext()


def lbfgs_advance(objective, opt, theta, state, tol, maxiter, max_new_iters,
                  nfev=0):
    """Advance an optax L-BFGS run by up to ``max_new_iters`` iterations.

    The shared device-side core of :func:`run_lbfgs` and the fleet solver
    (``metran_tpu.parallel.fleet``): a ``while_loop`` using optax's zoom
    line search via ``value_and_grad_from_state`` so each iteration reuses
    the line-search evaluations.  Stops at convergence (gradient norm
    below ``tol``), at ``maxiter`` total iterations, or after
    ``max_new_iters`` iterations of this call (chunking), whichever comes
    first.  Returns ``(theta, state, nfev)`` to carry across chunked
    calls; ``nfev`` counts true objective evaluations (one per line-search
    step, plus the initial evaluation), comparable to scipy's ``nfev``.
    """
    import jax
    import jax.numpy as jnp
    import optax
    import optax.tree_utils as otu

    value_and_grad = optax.value_and_grad_from_state(objective)
    count0 = otu.tree_get(state, "count")

    def step(carry):
        theta, state, nfev = carry
        count = otu.tree_get(state, "count")
        # value_and_grad_from_state reuses the stored value/grad except on
        # the very first iteration, where it evaluates the objective once
        nfev = nfev + jnp.where(count == 0, 1, 0).astype(jnp.int32)
        value, grad = value_and_grad(theta, state=state)
        updates, state = opt.update(
            grad, state, theta, value=value, grad=grad, value_fn=objective
        )
        theta = optax.apply_updates(theta, updates)
        steps = otu.tree_get(state, "info").num_linesearch_steps
        return theta, state, nfev + jnp.asarray(steps, jnp.int32)

    def cond(carry):
        _, state, _ = carry
        count = otu.tree_get(state, "count")
        err = tree_norm(otu.tree_get(state, "grad"))
        return (
            ((count == 0) | (err >= tol))
            & (count < maxiter)
            & (count - count0 < max_new_iters)
        )

    return jax.lax.while_loop(
        cond, step, (theta, state, jnp.asarray(nfev, jnp.int32))
    )


def default_gtol(dtype) -> float:
    """Default gradient-norm tolerance resolvable in ``dtype``.

    ``sqrt(machine eps)``: 1.5e-8 in float64 (the reference regime —
    scipy's L-BFGS-B ``pgtol`` ballpark), 3.5e-4 in float32, where
    gradients computed from an objective with ~1e-7 relative noise
    cannot meaningfully shrink below this.
    """
    import numpy as _np

    return float(_np.sqrt(_np.finfo(_np.dtype(dtype)).eps))


def default_ftol(dtype) -> float:
    """Default relative-improvement stopping tolerance for ``dtype``.

    The scipy L-BFGS-B ``factr`` criterion — stop (and report success)
    when ``f_prev - f <= ftol * max(|f_prev|, |f|, 1)`` — with
    ``factr * eps`` scaled per dtype: ``1e7 * eps`` in float64 (scipy's
    default ``factr``, the stop the reference inherits,
    ``metran/solver.py:252-256``) and ``1e2 * eps`` in
    float32 (~1e-5 relative: just above the float32 objective
    resolution floor, where the gradient-norm test is unreachable and
    iterations stop producing any decrease).
    """
    import numpy as _np

    dt = _np.dtype(dtype)
    factr = 1e7 if dt.itemsize >= 8 else 1e2
    return float(factr * _np.finfo(dt).eps)


def run_lbfgs(objective, theta0, maxiter: int = 200,
              tol: Optional[float] = None, ftol: Optional[float] = None,
              raise_on_divergence: bool = False, telemetry=None,
              grad_engine: Optional[str] = None):
    """Chunked optax L-BFGS loop with dtype-aware stopping.

    ``telemetry`` (a :class:`metran_tpu.obs.FitTelemetry`) records the
    run's trajectory at zero device cost — one checkpoint per host-side
    convergence check (deviance, gradient norm, nfev, the chunk's wall
    time — backward passes included, so per-iteration cost is
    diagnosable per engine), the precise stop reason, line-search stall
    counts and any divergence diagnosis — surfaced by
    ``Metran.fit_report()``.

    ``grad_engine`` is the resolved gradient engine the objective
    differentiates with (``"adjoint"``/``"autodiff"``) — recorded into
    the telemetry so a fit report states WHICH backward pass its
    timings describe; it does not alter the objective (deviance-based
    objectives resolve the engine themselves, see
    :func:`metran_tpu.ops.deviance`).  Validated eagerly: unknown
    values raise.

    Returns ``(theta, value, n_iters, nfev, converged)`` where ``nfev``
    counts true objective evaluations (scipy-comparable).  ``converged``
    is True when either the gradient-norm test (``tol``, default
    :func:`default_gtol`) or the scipy-style relative-improvement test
    (``ftol``, default :func:`default_ftol`) fired — the latter is what
    actually terminates float32 runs, where gradient norms plateau well
    above any f64-style ``tol`` while the optimum is already resolved to
    the objective's resolution floor (scipy reports success for its
    ``factr`` stop the same way).  The loop runs on device in chunks of
    up to 20 iterations; the host checks the stopping tests between
    chunks, so the improvement test compares values a whole chunk apart
    (strictly more conservative than scipy's per-iteration check).

    A non-finite objective value never reports success; with
    ``raise_on_divergence=True`` it raises
    :class:`SolverDivergenceError` carrying the offending ``theta`` (the
    solver layer maps it back to named parameters) instead of returning
    ``converged=False`` — callers that cannot act on a NaN optimum get a
    diagnosis instead of a downstream mystery.  A run that stops at a
    point *worse than its starting value* (a line-search failure
    creeping to a stationary point — e.g. a saddle of a divergent
    objective) is likewise never reported converged, whatever the
    gradient norm says.
    """
    import jax
    import jax.numpy as jnp
    import numpy as _np
    import optax
    import optax.tree_utils as otu

    if grad_engine is not None:
        from ..config import grad_engine as _validate_grad

        grad_engine = _validate_grad(grad_engine)
    theta0 = jnp.asarray(theta0)
    if tol is None:
        tol = default_gtol(theta0.dtype)
    if ftol is None:
        ftol = default_ftol(theta0.dtype)
    opt = optax.lbfgs()
    chunk = min(20, maxiter)

    @jax.jit
    def advance(theta, state, nfev):
        return lbfgs_advance(
            objective, opt, theta, state, tol, maxiter, chunk, nfev
        )

    with lbfgs_trace_ctx(theta0.dtype):
        # one extra objective evaluation, for two guards: a start that
        # is already non-finite diagnoses immediately, and no stopping
        # test may report success at a value worse than this
        value0 = float(objective(theta0))
        if telemetry is not None:
            telemetry.record_start(value0)
            telemetry.record_grad_engine(grad_engine)
        if not _np.isfinite(value0):
            if telemetry is not None:
                telemetry.record_stop(
                    "init_nonfinite", False,
                    divergence=(
                        "non-finite at the initial parameters "
                        f"(value={value0!r})"
                    ),
                )
            if raise_on_divergence:
                raise SolverDivergenceError(
                    "fit objective is non-finite at the initial "
                    f"parameters (value={value0!r})",
                    params=_np.asarray(theta0, float),
                    value=value0, n_iters=0,
                )
            return theta0, jnp.asarray(value0), 0, 1, False
        # nfev starts at 1: the value0 guard above is a true objective
        # evaluation (matching the early-divergence return's count)
        theta, state, nfev = theta0, opt.init(theta0), 1
        prev_value = None
        converged = False
        reason = "maxiter"
        import time as _time

        while True:
            _t0 = _time.perf_counter()
            theta, state, nfev = advance(theta, state, nfev)
            value = float(otu.tree_get(state, "value"))
            count = int(otu.tree_get(state, "count"))
            gnorm = float(tree_norm(otu.tree_get(state, "grad")))
            # value/count/gnorm are host reads of the finished dispatch,
            # so the elapsed time covers the chunk's real device work
            # (forward + backward passes), not just its submission
            _wall = _time.perf_counter() - _t0
            if telemetry is not None:
                # one record per device chunk — the deviance curve,
                # gradient-norm trail and chunk wall time, at
                # host-checkpoint granularity
                telemetry.record_checkpoint(count, value, gnorm,
                                            int(nfev), wall_s=_wall)
            if not _np.isfinite(value):
                reason = "diverged"
                if telemetry is not None:
                    telemetry.record_stop(
                        "diverged", False,
                        divergence=(
                            f"value={value!r} after {count} L-BFGS "
                            "iterations"
                        ),
                    )
                if raise_on_divergence:
                    raise SolverDivergenceError(
                        f"fit objective became non-finite "
                        f"(value={value!r}) after {count} L-BFGS "
                        "iterations",
                        params=_np.asarray(theta, float),
                        value=value, n_iters=count,
                    )
                break  # diverged — never report success
            if gnorm < tol:
                converged = True
                reason = "gradient"
                break
            # floor stop: the value CHANGED by less than the resolution
            # tolerance across a whole chunk.  Two-sided on purpose — a
            # chunk that made the value meaningfully worse (line-search
            # failure excursion) must keep running or exhaust maxiter
            # unconverged, not masquerade as a factr-style success.
            if prev_value is not None and (
                abs(prev_value - value)
                <= ftol * max(abs(prev_value), abs(value), 1.0)
            ):
                converged = True  # resolution-floor stop, factr-style
                reason = "floor"
                break
            if count >= maxiter:
                break
            prev_value = value
        if converged and not (
            value <= value0 + ftol * max(abs(value0), abs(value), 1.0)
        ):
            # stationary (or stalled) at a point worse than the start:
            # the iterates went uphill through line-search failure
            # fallbacks — that is a failed run, not an optimum
            converged = False
            reason = "worse_than_start"
        if telemetry is not None and reason != "diverged":
            telemetry.record_stop(reason, converged)
    return (
        theta,
        otu.tree_get(state, "value"),
        otu.tree_get(state, "count"),
        nfev,
        converged,
    )


class BatchedLbfgsFit(NamedTuple):
    """Result of :func:`batched_lbfgs` (host arrays, leading B).

    ``converged`` is the gradient-norm verdict only (finite value AND
    ``gnorm < tol``); callers with an external acceptance test — the
    refit worker's held-out champion/challenger comparison — treat it
    as telemetry, not a gate.  ``value0`` is the objective at the
    start point, so a run that *worsened* (line-search failure creep)
    is diagnosable without re-evaluating.
    """

    theta: np.ndarray
    value: np.ndarray
    value0: np.ndarray
    iterations: np.ndarray
    gnorm: np.ndarray
    converged: np.ndarray


def batched_lbfgs(objective, theta0, data=(), maxiter: int = 60,
                  tol: Optional[float] = None,
                  max_linesearch_steps: int = 16,
                  grad_engine: Optional[str] = None) -> BatchedLbfgsFit:
    """Solve B independent problems with one vmapped L-BFGS dispatch.

    The generic single-round batch driver over the shared
    :func:`lbfgs_advance` core: ``objective(theta_i, *data_i) ->
    scalar`` is vmapped over the leading axis of ``theta0`` and every
    leaf of ``data``, each lane running the same optax zoom-linesearch
    L-BFGS as :func:`run_lbfgs` (:func:`lbfgs_trace_ctx` dtype
    discipline) to convergence or ``maxiter`` in ONE jitted device
    execution — batches are expected small, so chunking/host
    checkpointing would cost more than it saves.  The serving stack's
    background refit builds its own runner on the same core because it
    adds a trust-region/restart schedule around each lane
    (:func:`metran_tpu.parallel.fleet.refit_fleet`); use this driver
    when a plain warm-started descent is enough.  A lane whose
    objective diverges simply reports a non-finite ``value`` (and
    ``converged=False``); it cannot poison its batch mates.

    ``grad_engine`` is validated eagerly (unknown values raise) but
    does not rewrite a generic ``objective`` — deviance-based
    objectives resolve the configured gradient engine themselves
    (:func:`metran_tpu.ops.deviance`); pass it to make a driver
    call's intent explicit and typo-proof.
    """
    import jax
    import jax.numpy as jnp
    import optax
    import optax.tree_utils as otu

    if grad_engine is not None:
        from ..config import grad_engine as _validate_grad

        _validate_grad(grad_engine)
    theta0 = jnp.asarray(theta0)
    if tol is None:
        tol = default_gtol(theta0.dtype)
    opt = optax.lbfgs(linesearch=zoom_linesearch(max_linesearch_steps))

    def lane(theta, *di):
        def obj(th):
            return objective(th, *di)

        value0 = obj(theta)
        state = opt.init(theta)
        theta, state, _nfev = lbfgs_advance(
            obj, opt, theta, state, tol, maxiter, maxiter
        )
        value = otu.tree_get(state, "value")
        count = otu.tree_get(state, "count")
        gnorm = tree_norm(otu.tree_get(state, "grad"))
        return theta, value, value0, count, gnorm

    with lbfgs_trace_ctx(theta0.dtype):
        theta, value, value0, count, gnorm = jax.jit(jax.vmap(lane))(
            theta0, *data
        )
    theta = np.asarray(theta)
    value = np.asarray(value, float)
    gnorm = np.asarray(gnorm, float)
    return BatchedLbfgsFit(
        theta=theta,
        value=value,
        value0=np.asarray(value0, float),
        iterations=np.asarray(count, np.int64),
        gnorm=gnorm,
        converged=np.isfinite(value) & (gnorm < float(tol)),
    )


class LanesSolve(BaseSolver):
    """Single-model solve on the fleet lanes engine — the accelerator
    default.

    Routes ``Metran.solve()`` through the same machinery as
    ``fit_fleet(layout="lanes")``: the lane-layout Kalman kernel with
    its analytical adjoint and the fixed-structure grid-line-search
    L-BFGS (:mod:`metran_tpu.parallel.lanes_lbfgs`).  Versus ``JaxSolve``
    (optax zoom line search under one big ``jit``) this compiles much
    smaller programs and keeps every device dispatch short and bounded —
    the properties that make the fleet path robust on real TPU runtimes
    — while converging to the same optima (``tests/test_parallel.py::
    test_fit_fleet_matches_jaxsolve_single``).  Standard errors come
    from the lane-layout FD Hessian (``fleet_stderr(method="lanes-fd")``).

    Scope: optimizes every parameter with the fleet box (``alpha`` in
    ``[ALPHA_PMIN, alpha_max soft cap]`` — the reference's lower bound,
    ``metran/metran.py:446-462``, plus the float32 safety cap).  Fixed
    parameters (``vary=False``) or custom ``pmin/pmax`` are not
    supported; ``Metran.solve`` falls back to :class:`JaxSolve` then.
    """

    _name = "LanesSolve"

    @classmethod
    def supports(cls, mt) -> bool:
        """True when the fit is expressible on the lanes engine: every
        parameter varying, with the fleet's standard box (the
        reference-default ``pmin`` and no upper bound)."""
        from ..parallel.fleet import ALPHA_PMIN

        pt = mt.parameters
        if not pt.vary.values.astype(bool).all():
            return False
        pmin = pt.pmin.values.astype(float)
        pmax = pt.pmax.values.astype(float)
        return bool(
            np.allclose(pmin, ALPHA_PMIN) and np.isnan(pmax).all()
        )

    def solve(self, maxiter: int = 100, tol: Optional[float] = None,
              stall_tol: Optional[float] = None,
              stall_rtol: Optional[float] = None, chunk: int = 8,
              remat_seg: Optional[int] = 100, n_starts: int = 1,
              **kwargs):
        """Minimize the deviance on the lanes engine.

        ``n_starts > 1`` adds a multi-start basin search
        (:func:`metran_tpu.parallel.multistart_fit_fleet`): the extra
        initial points ride the lane axis, so the whole search is still
        one compiled program per dispatch; the best basin's optimum is
        returned (``nfev`` is the winning start's evaluation count).
        """
        import jax.numpy as jnp

        from ..parallel import fleet as _fleet

        self._setup()
        if not self.supports(self.mt):
            raise ValueError(
                "LanesSolve optimizes all parameters over the fleet's "
                "standard box (pmin=1e-5, no pmax); use JaxSolve/"
                "ScipySolve for fits with fixed (vary=False) "
                "parameters or custom bounds"
            )
        mt = self.mt
        panel = mt._active_panel()
        flt = _fleet.pack_fleet([panel], [mt.factors])
        idx = mt._canonical_idx  # canonical[i] = table[idx[i]]
        p0 = jnp.asarray(mt._param_array(self.initial))[None]
        if stall_rtol is None and stall_tol is None:
            # scipy-factr default: stop once per-iteration improvement
            # falls below ftol * |current f| (the grid-line-search
            # L-BFGS converges to the optimum long before its gradient
            # norm can pass an absolute f64 test; the reference's scipy
            # stop is exactly this relative criterion and reports
            # success).  Evaluated per-iteration on device.
            stall_rtol = default_ftol(p0.dtype)
        # multistart-only knobs: fit_fleet has a fixed signature, so
        # they must never reach the single-start path
        ms_kwargs = {
            k: kwargs.pop(k) for k in ("seed", "spread") if k in kwargs
        }
        fit_kwargs = dict(
            maxiter=maxiter, tol=tol, stall_tol=stall_tol,
            stall_rtol=stall_rtol or 0.0, chunk=chunk, layout="lanes",
            remat_seg=remat_seg, **kwargs
        )
        if n_starts > 1:
            # winner per basin; nfev reported is the winning start's
            # count (per-start counts live in the discarded lanes)
            fit, _ = _fleet.multistart_fit_fleet(
                flt, n_starts=n_starts, p0=p0, **ms_kwargs, **fit_kwargs
            )
        else:
            fit = _fleet.fit_fleet(flt, p0=p0, **fit_kwargs)
        params = np.asarray(fit.params[0], float)  # canonical order
        # stderr re-derives from the covariance diagonal in _finalize
        _, pcov_c = _fleet.fleet_stderr(
            fit.params, flt, remat_seg=remat_seg, method="lanes-fd"
        )
        pcov_c = np.asarray(pcov_c[0], float)

        n = len(params)
        x = np.empty(n)
        x[idx] = params  # back to table row order
        pcov = np.empty((n, n))
        pcov[np.ix_(idx, idx)] = pcov_c
        return self._finalize(
            x, float(fit.deviance[0]), int(fit.nfev[0]),
            bool(fit.converged[0]), pcov,
        )


class LmfitSolve(BaseSolver):
    """lmfit-backed solver for API parity with the reference.

    lmfit is optional; constructing this class without it installed raises
    ImportError, exactly like the reference (``metran/solver.py:333-341``).
    """

    _name = "LmfitSolve"

    def __init__(self, mt, **kwargs):
        try:
            import lmfit  # noqa: F401
        except ImportError as e:
            msg = "lmfit not installed. Please install lmfit first."
            logger.error(msg)
            raise ImportError(msg) from e
        super().__init__(mt, **kwargs)

    def solve(self, method: str = "lbfgsb", **kwargs):
        import lmfit

        self._setup()
        parameters = lmfit.Parameters()
        table = self.mt.parameters
        for name in table.index:
            row = table.loc[name]
            pmin = None if row.pmin is None or np.isnan(row.pmin) else row.pmin
            pmax = None if row.pmax is None or (
                isinstance(row.pmax, float) and np.isnan(row.pmax)
            ) else row.pmax
            if method == "lbfgsb":
                parameters.add(name, value=row.initial, vary=bool(row.vary))
            else:
                parameters.add(
                    name, value=row.initial, min=pmin, max=pmax, vary=bool(row.vary)
                )
        if method == "lbfgsb":
            kwargs["bounds"] = [
                (b if b is not None else -np.inf, u if u is not None else np.inf)
                for (b, u) in self.bounds
            ]

        mini = lmfit.Minimizer(
            userfcn=self.objfunction,
            params=parameters,
            scale_covar=False,
            fcn_args=(lambda p: np.array([v.value for v in p.values()]),),
            **kwargs,
        )
        self.result = mini.minimize(method=method)
        optimal = np.array([p.value for p in self.result.params.values()])
        x = optimal[self.vary]

        pcov = getattr(self.result, "covar", None)
        success = getattr(self.result, "success", True)
        fun = self.objfunction(optimal)
        return self._finalize(x, fun, self.result.nfev, success, pcov)
