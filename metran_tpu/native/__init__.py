"""Native (C++) CPU runtime for metran_tpu.

The compiled host-side twin of the XLA engines: a sequential-processing
Kalman filter/smoother/deviance in C++ (``kalman.cpp``), loaded through
``ctypes``.  This plays the role the numba-jitted kernel plays in the
reference (``metran/kalmanfilter.py:236-400``): a fast CPU path for
host-only deployments, the honest CPU baseline for ``bench.py``, and an
independent implementation for parity testing against the ``lax.scan``
engines.

The shared library is always built locally on demand (``g++ -O3``) into
``metran_tpu/native/build/`` — build artifacts are never shipped in the
repo, so the binary always matches the host ISA.  Rebuilds key on a
content hash of the C++ source, not mtimes (checkout-time mtimes are
meaningless).  Set ``METRAN_TPU_NO_NATIVE=1`` to disable
(pure-Python/JAX operation is always available), or
``METRAN_TPU_NATIVE_MARCH=native`` to opt into host-specific codegen.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
from logging import getLogger
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

logger = getLogger(__name__)

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "kalman.cpp"
_BUILD_DIR = _HERE / "build"
_LIB_PATH = _BUILD_DIR / "libmetran_native.so"
_STAMP_PATH = _BUILD_DIR / "libmetran_native.stamp"

_lib: Optional[ctypes.CDLL] = None


class NativeUnavailable(RuntimeError):
    """Raised when the native library cannot be built or loaded."""


def _build_flags() -> list:
    flags = ["-O3", "-shared", "-fPIC"]
    march = os.environ.get("METRAN_TPU_NATIVE_MARCH")
    if march:  # opt-in only: host-specific ISA breaks on other machines
        flags.append(f"-march={march}")
    return flags


def _build_stamp() -> str:
    """Content hash keying the build: source bytes + compile flags."""
    h = hashlib.sha256(_SRC.read_bytes())
    h.update(" ".join(_build_flags()).encode())
    return h.hexdigest()


def _build(stamp: str) -> Path:
    _BUILD_DIR.mkdir(exist_ok=True)
    cmd = ["g++", *_build_flags(), "-o", str(_LIB_PATH), str(_SRC)]
    logger.info("building native kernel: %s", " ".join(cmd))
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
    except OSError as e:  # g++ missing entirely
        raise NativeUnavailable(f"no C++ toolchain: {e}") from e
    if proc.returncode != 0:
        raise NativeUnavailable(
            f"native build failed (exit {proc.returncode}): {proc.stderr[-500:]}"
        )
    _STAMP_PATH.write_text(stamp)
    return _LIB_PATH


def _probe() -> None:
    """Run one tiny filter call in a subprocess before trusting the library.

    A stale or foreign binary (wrong ISA, truncated file) dies with
    SIGILL/SIGSEGV — in a subprocess that is a catchable nonzero exit,
    not a crash of the caller's process.
    """
    code = (
        "import numpy as np; from metran_tpu.native import seq_filter_pass; "
        "seq_filter_pass(np.full(2,.5), np.eye(2)*.1, np.eye(2), "
        "np.zeros(2), np.zeros((3,2)), np.ones((3,2),bool))"
    )
    env = dict(os.environ, METRAN_TPU_NATIVE_PROBED="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env,
        cwd=str(_HERE.parent.parent), timeout=120,
    )
    if proc.returncode != 0:
        raise NativeUnavailable(
            f"native library failed sanity probe (exit {proc.returncode}): "
            f"{proc.stderr[-300:]}"
        )


def load() -> ctypes.CDLL:
    """Load (building if needed) the native library; raises if impossible."""
    global _lib
    if _lib is not None:
        return _lib
    if os.environ.get("METRAN_TPU_NO_NATIVE"):
        raise NativeUnavailable("disabled by METRAN_TPU_NO_NATIVE")
    stamp = _build_stamp()
    have = _STAMP_PATH.read_text() if _STAMP_PATH.exists() else None
    if not _LIB_PATH.exists() or have != stamp:
        _build(stamp)
        if not os.environ.get("METRAN_TPU_NATIVE_PROBED"):
            _probe()
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError as e:
        raise NativeUnavailable(f"cannot load native library: {e}") from e

    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64 = ctypes.c_int64

    lib.seq_kalman_filter.restype = ctypes.c_int
    lib.seq_kalman_filter.argtypes = [
        f64p, f64p, f64p, f64p, f64p, u8p, i64, i64, i64,
        f64p, f64p, f64p, f64p, f64p, f64p,
    ]
    lib.seq_kalman_deviance.restype = ctypes.c_double
    lib.seq_kalman_deviance.argtypes = [f64p, f64p, u8p, i64, i64, i64]
    lib.seq_kalman_smoother.restype = ctypes.c_int
    lib.seq_kalman_smoother.argtypes = [
        f64p, f64p, f64p, f64p, f64p, i64, i64, f64p, f64p,
    ]
    _lib = lib
    return lib


def available() -> bool:
    try:
        load()
        return True
    except NativeUnavailable:
        return False


def _f64(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.float64))


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def seq_filter_pass(phi, q, z, r, y, mask) -> Tuple[float, float]:
    """One filter pass; returns (sum sigma, sum detf).  Moment storage is
    skipped — this is the likelihood-evaluation hot path."""
    lib = load()
    phi, q, z, r, y = map(_f64, (phi, q, z, r, y))
    mask8 = np.ascontiguousarray(np.asarray(mask, dtype=np.uint8))
    t, m = y.shape
    n = phi.shape[0]
    sigma = np.empty(t)
    detf = np.empty(t)
    rc = lib.seq_kalman_filter(
        _ptr(phi), _ptr(q), _ptr(z), _ptr(r), _ptr(y),
        mask8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        t, m, n, _ptr(sigma), _ptr(detf), None, None, None, None,
    )
    if rc != 0:
        raise RuntimeError(f"native filter failed (rc={rc})")
    return float(sigma.sum()), float(detf.sum())


def filter(phi, q, z, r, y, mask):
    """Full filter pass storing moments.

    Returns dict with mean_p/cov_p/mean_f/cov_f/sigma/detf (same layout
    as the JAX ``kalman_filter`` FilterResult).
    """
    lib = load()
    phi, q, z, r, y = map(_f64, (phi, q, z, r, y))
    mask8 = np.ascontiguousarray(np.asarray(mask, dtype=np.uint8))
    t, m = y.shape
    n = phi.shape[0]
    out = {
        "sigma": np.empty(t),
        "detf": np.empty(t),
        "mean_f": np.empty((t, n)),
        "cov_f": np.empty((t, n, n)),
        "mean_p": np.empty((t, n)),
        "cov_p": np.empty((t, n, n)),
    }
    rc = lib.seq_kalman_filter(
        _ptr(phi), _ptr(q), _ptr(z), _ptr(r), _ptr(y),
        mask8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        t, m, n, _ptr(out["sigma"]), _ptr(out["detf"]),
        _ptr(out["mean_f"]), _ptr(out["cov_f"]),
        _ptr(out["mean_p"]), _ptr(out["cov_p"]),
    )
    if rc != 0:
        raise RuntimeError(f"native filter failed (rc={rc})")
    return out


def deviance(phi, q, z, r, y, mask, warmup: int = 1) -> float:
    """-2 log L with reference warmup semantics, entirely in native code."""
    lib = load()
    phi, q, z, r, y = map(_f64, (phi, q, z, r, y))
    mask8 = np.ascontiguousarray(np.asarray(mask, dtype=np.uint8))
    t, m = y.shape
    n = phi.shape[0]
    sigma = np.empty(t)
    detf = np.empty(t)
    rc = lib.seq_kalman_filter(
        _ptr(phi), _ptr(q), _ptr(z), _ptr(r), _ptr(y),
        mask8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        t, m, n, _ptr(sigma), _ptr(detf), None, None, None, None,
    )
    if rc != 0:
        raise RuntimeError(f"native filter failed (rc={rc})")
    return float(
        lib.seq_kalman_deviance(
            _ptr(sigma), _ptr(detf),
            mask8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            t, m, warmup,
        )
    )


def smoother(phi, filt):
    """RTS smoother over stored filter moments; returns (mean_s, cov_s)."""
    lib = load()
    phi = _f64(phi)
    mean_f = _f64(filt["mean_f"])
    cov_f = _f64(filt["cov_f"])
    mean_p = _f64(filt["mean_p"])
    cov_p = _f64(filt["cov_p"])
    t, n = mean_f.shape
    mean_s = np.empty((t, n))
    cov_s = np.empty((t, n, n))
    rc = lib.seq_kalman_smoother(
        _ptr(phi), _ptr(mean_f), _ptr(cov_f), _ptr(mean_p), _ptr(cov_p),
        t, n, _ptr(mean_s), _ptr(cov_s),
    )
    if rc != 0:
        raise RuntimeError(f"native smoother failed (rc={rc}): cov not PD")
    return mean_s, cov_s


__all__ = [
    "NativeUnavailable",
    "available",
    "deviance",
    "filter",
    "load",
    "seq_filter_pass",
    "smoother",
]
