// Native CPU sequential-processing Kalman filter for the Metran DFM.
//
// The compiled-CPU twin of the JAX engines in metran_tpu/ops/kalman.py and
// the framework's stand-in for the reference's numba-jitted kernel
// (reference: metran/kalmanfilter.py:236-400 — algorithm reimplemented
// fresh, not translated): per-timestep diagonal-Phi predict followed by
// Koopman-style sequential scalar updates with rank-1 covariance
// downdates, accumulating sigma = sum v^2/f and detf = sum log f.
//
// Exposed as a plain C ABI consumed through ctypes (metran_tpu/native/
// __init__.py).  Used for fast host-side reference evaluation, parity
// testing against the XLA path, and as the honest CPU baseline in
// bench.py.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libmetran_native.so kalman.cpp

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Run the sequential-processing filter over a regular grid.
//
//   phi  : (n)      diagonal transition
//   q    : (n, n)   transition covariance (row-major)
//   z    : (m, n)   observation matrix
//   r    : (m)      observation noise variance
//   y    : (t, m)   observations (masked entries ignored)
//   mask : (t, m)   uint8, 1 where observed
//
// Outputs (pre-allocated by the caller):
//   sigma_out, detf_out : (t)   per-step sums of v^2/f and log f
//   mean_f, cov_f       : (t, n) / (t, n, n)  filtered moments, or nullptr
//   mean_p, cov_p       : (t, n) / (t, n, n)  predicted moments, or nullptr
//
// Returns 0 on success.
int seq_kalman_filter(const double* phi, const double* q, const double* z,
                      const double* r, const double* y, const uint8_t* mask,
                      int64_t t_steps, int64_t m, int64_t n,
                      double* sigma_out, double* detf_out, double* mean_f,
                      double* cov_f, double* mean_p, double* cov_p) {
  std::vector<double> mean(n, 0.0);
  std::vector<double> cov(n * n, 0.0);
  std::vector<double> d(n);
  for (int64_t i = 0; i < n; ++i) cov[i * n + i] = 1.0;  // P0 = I

  for (int64_t t = 0; t < t_steps; ++t) {
    // predict: mean = phi*mean; cov = phi_r * cov * phi_c + q
    for (int64_t i = 0; i < n; ++i) mean[i] *= phi[i];
    for (int64_t rr = 0; rr < n; ++rr) {
      const double pr = phi[rr];
      double* crow = cov.data() + rr * n;
      const double* qrow = q + rr * n;
      for (int64_t cc = 0; cc < n; ++cc)
        crow[cc] = pr * crow[cc] * phi[cc] + qrow[cc];
    }
    if (mean_p) std::memcpy(mean_p + t * n, mean.data(), n * sizeof(double));
    if (cov_p)
      std::memcpy(cov_p + t * n * n, cov.data(), n * n * sizeof(double));

    double sigma = 0.0, detf = 0.0;
    for (int64_t i = 0; i < m; ++i) {
      if (!mask[t * m + i]) continue;
      const double* zi = z + i * n;
      // v = y - z.mean ; d = P z ; f = z.d + r
      double v = y[t * m + i];
      for (int64_t j = 0; j < n; ++j) v -= zi[j] * mean[j];
      double f = r[i];
      for (int64_t rr = 0; rr < n; ++rr) {
        double acc = 0.0;
        const double* crow = cov.data() + rr * n;
        for (int64_t cc = 0; cc < n; ++cc) acc += crow[cc] * zi[cc];
        d[rr] = acc;
      }
      for (int64_t j = 0; j < n; ++j) f += zi[j] * d[j];
      // k = d / f ; P -= k k^T f ; mean += k v
      const double finv = 1.0 / f;
      for (int64_t rr = 0; rr < n; ++rr) {
        const double krr = d[rr] * finv;
        double* crow = cov.data() + rr * n;
        for (int64_t cc = 0; cc < n; ++cc) crow[cc] -= krr * d[cc];
        mean[rr] += krr * v;
      }
      sigma += v * v * finv;
      detf += std::log(f);
    }
    sigma_out[t] = sigma;
    detf_out[t] = detf;
    if (mean_f) std::memcpy(mean_f + t * n, mean.data(), n * sizeof(double));
    if (cov_f)
      std::memcpy(cov_f + t * n * n, cov.data(), n * n * sizeof(double));
  }
  return 0;
}

// Deviance (-2 log L) with the reference's warmup semantics
// (metran/kalmanfilter.py:550-567): sigma/detf sums skip the first
// `warmup` *observed* timesteps; nobs skips the first `warmup` grid steps.
double seq_kalman_deviance(const double* sigma, const double* detf,
                           const uint8_t* mask, int64_t t_steps, int64_t m,
                           int64_t warmup) {
  constexpr double kLog2Pi = 1.8378770664093453;
  int64_t nobs = 0, obs_rank = 0;
  double acc = 0.0;
  for (int64_t t = 0; t < t_steps; ++t) {
    int64_t count = 0;
    for (int64_t i = 0; i < m; ++i) count += mask[t * m + i] ? 1 : 0;
    if (t >= warmup) nobs += count;
    if (count > 0) {
      if (obs_rank >= warmup) acc += sigma[t] + detf[t];
      ++obs_rank;
    }
  }
  return static_cast<double>(nobs) * kLog2Pi + acc;
}

// RTS smoother (backward recursion) over stored filter moments.
// G_t = P^f_t Phi^T (P^p_{t+1})^{-1}, solved via Cholesky of P^p_{t+1}.
// In-place outputs mean_s (t, n), cov_s (t, n, n).
int seq_kalman_smoother(const double* phi, const double* mean_f,
                        const double* cov_f, const double* mean_p,
                        const double* cov_p, int64_t t_steps, int64_t n,
                        double* mean_s, double* cov_s) {
  std::memcpy(mean_s + (t_steps - 1) * n, mean_f + (t_steps - 1) * n,
              n * sizeof(double));
  std::memcpy(cov_s + (t_steps - 1) * n * n, cov_f + (t_steps - 1) * n * n,
              n * n * sizeof(double));
  std::vector<double> chol(n * n), a(n * n), g(n * n), tmp(n * n), dv(n), dm(n);

  for (int64_t t = t_steps - 2; t >= 0; --t) {
    const double* ppn = cov_p + (t + 1) * n * n;  // P^p_{t+1}
    // Cholesky ppn = L L^T (lower)
    std::memcpy(chol.data(), ppn, n * n * sizeof(double));
    for (int64_t j = 0; j < n; ++j) {
      double diag = chol[j * n + j];
      for (int64_t kk = 0; kk < j; ++kk)
        diag -= chol[j * n + kk] * chol[j * n + kk];
      if (diag <= 0.0) return 1;  // not PD
      diag = std::sqrt(diag);
      chol[j * n + j] = diag;
      for (int64_t i2 = j + 1; i2 < n; ++i2) {
        double acc = chol[i2 * n + j];
        for (int64_t kk = 0; kk < j; ++kk)
          acc -= chol[i2 * n + kk] * chol[j * n + kk];
        chol[i2 * n + j] = acc / diag;
      }
      for (int64_t kk = j + 1; kk < n; ++kk) chol[j * n + kk] = 0.0;
    }
    // A = P^f_t * diag(phi)   (Phi diagonal => P^f Phi^T = P^f * phi cols)
    const double* pf = cov_f + t * n * n;
    for (int64_t rr = 0; rr < n; ++rr)
      for (int64_t cc = 0; cc < n; ++cc)
        a[rr * n + cc] = pf[rr * n + cc] * phi[cc];
    // Solve G ppn = A  =>  G = A ppn^{-1}; with ppn = L L^T:
    // solve (L L^T) X^T = A^T column-by-column, G = X
    for (int64_t rr = 0; rr < n; ++rr) {
      // forward solve L w = A[rr, :]^T
      for (int64_t i2 = 0; i2 < n; ++i2) {
        double acc = a[rr * n + i2];
        for (int64_t kk = 0; kk < i2; ++kk)
          acc -= chol[i2 * n + kk] * dv[kk];
        dv[i2] = acc / chol[i2 * n + i2];
      }
      // backward solve L^T x = w
      for (int64_t i2 = n - 1; i2 >= 0; --i2) {
        double acc = dv[i2];
        for (int64_t kk = i2 + 1; kk < n; ++kk)
          acc -= chol[kk * n + i2] * g[rr * n + kk];
        g[rr * n + i2] = acc / chol[i2 * n + i2];
      }
    }
    // mean_s[t] = mean_f[t] + G (mean_s[t+1] - mean_p[t+1])
    const double* msn = mean_s + (t + 1) * n;
    const double* mpn = mean_p + (t + 1) * n;
    for (int64_t i2 = 0; i2 < n; ++i2) dm[i2] = msn[i2] - mpn[i2];
    for (int64_t rr = 0; rr < n; ++rr) {
      double acc = mean_f[t * n + rr];
      for (int64_t cc = 0; cc < n; ++cc) acc += g[rr * n + cc] * dm[cc];
      mean_s[t * n + rr] = acc;
    }
    // cov_s[t] = P^f_t + G (cov_s[t+1] - P^p_{t+1}) G^T
    const double* csn = cov_s + (t + 1) * n * n;
    for (int64_t rr = 0; rr < n; ++rr)
      for (int64_t cc = 0; cc < n; ++cc)
        tmp[rr * n + cc] = csn[rr * n + cc] - ppn[rr * n + cc];
    // tmp2 = G * tmp  (reuse a)
    for (int64_t rr = 0; rr < n; ++rr)
      for (int64_t cc = 0; cc < n; ++cc) {
        double acc = 0.0;
        for (int64_t kk = 0; kk < n; ++kk)
          acc += g[rr * n + kk] * tmp[kk * n + cc];
        a[rr * n + cc] = acc;
      }
    for (int64_t rr = 0; rr < n; ++rr)
      for (int64_t cc = 0; cc < n; ++cc) {
        double acc = pf[rr * n + cc];
        for (int64_t kk = 0; kk < n; ++kk)
          acc += a[rr * n + kk] * g[cc * n + kk];
        cov_s[t * n * n + rr * n + cc] = acc;
      }
  }
  return 0;
}

}  // extern "C"
