"""End-to-end observability for the serving and fitting stacks.

After PRs 1-3 the repo runs a multi-threaded serving pipeline
(micro-batching, deferred ordering chains, circuit breakers,
quarantine) whose behavior was invisible beyond ad-hoc counters.  This
package is the unified layer that makes it observable — and the
numbers it produces are what lets an operator trade accuracy, batching
and engine choice against latency (the computation-aware filtering
argument, arXiv:2405.08971):

- :mod:`~metran_tpu.obs.metrics` — :class:`MetricsRegistry`: one
  thread-safe home for counters/gauges/histograms with ``snapshot()``
  and Prometheus text exposition; the serving instruments
  (:class:`LatencyRecorder`, :class:`EventCounters`,
  :class:`OccupancyCounter`) are registry-backed.
- :mod:`~metran_tpu.obs.tracing` — :class:`Tracer`: request-scoped
  spans under one correlation ID from submit through batcher wait,
  dispatch, engine, integrity gate and commit — across the batcher
  thread boundary and the deferred-chain/retry paths — exported as
  Chrome trace-event JSON (Perfetto-compatible).
- :mod:`~metran_tpu.obs.events` — :class:`EventLog`: a bounded
  structured JSON-lines log of attributed reliability events (breaker
  transitions, quarantines, retries, chain breaks, poisoned updates),
  post-mortem-reconstructable per model.
- :mod:`~metran_tpu.obs.telemetry` — :class:`FitTelemetry`: per-fit
  optimizer trajectory (deviance curve, gradient norms, stop reason)
  surfaced in ``fit_report()``.
- :mod:`~metran_tpu.obs.fleet` — the multi-process merge layer:
  :class:`ChildTelemetry` parts served over the cluster RPC plane,
  clock alignment (:class:`ClockAlign`), and the merged fleet
  exposition / event timeline / Chrome trace renderers behind
  ``ClusterFrontend.fleet_report()`` and friends.

:class:`Observability` bundles the three serving-side pieces for
injection into :class:`~metran_tpu.serve.MetranService`; defaults come
from :func:`metran_tpu.config.obs_defaults` (``METRAN_TPU_OBS_*``
environment knobs).  See docs/concepts.md "Observability" for the
metric-name catalogue, the span map and the event schema.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .capacity import (
    STAGES,
    BurnRateMonitor,
    CapacityTracker,
    ModelCostLedger,
)
from .events import EVENT_KINDS, EventLog, read_sink
from .fleet import (
    ChildTelemetry,
    ClockAlign,
    FleetScrapeServer,
    clock_anchor,
    merge_chrome,
    merge_events,
    render_fleet_prometheus,
)
from .metrics import (
    DEFAULT_LATENCY_BUCKETS,
    DEFAULT_SIZE_BUCKETS,
    Counter,
    EventCounters,
    Gauge,
    Histogram,
    LatencyRecorder,
    MetricsRegistry,
    OccupancyCounter,
)
from .telemetry import FitTelemetry
from .tracing import (
    Span,
    SpanContext,
    Tracer,
    attach_context,
    current_context,
    current_trace_id,
)


@dataclass
class Observability:
    """The serving stack's observability bundle (inject into
    :class:`~metran_tpu.serve.MetranService`).

    Any component may be ``None`` — the corresponding instrumentation
    then compiles down to an ``is None`` check on the hot path.
    :meth:`default` builds the configured default (metrics + event
    ring always on — they are cheap; tracing opt-in via
    ``METRAN_TPU_OBS_TRACE=1`` or an explicit :class:`Tracer`);
    :meth:`disabled` turns everything off (the bench baseline).
    """

    metrics: Optional[MetricsRegistry] = None
    tracer: Optional[Tracer] = None
    events: Optional[EventLog] = None

    @classmethod
    def default(cls) -> "Observability":
        """Config-driven default (see :func:`metran_tpu.config.
        obs_defaults`)."""
        from ..config import obs_defaults

        d = obs_defaults()
        return cls(
            metrics=MetricsRegistry(),
            tracer=(
                Tracer(maxlen=d["trace_buffer"]) if d["trace"] else None
            ),
            events=EventLog(
                maxlen=d["event_buffer"],
                sink=d["event_sink"] or None,
                max_sink_mb=d["event_sink_max_mb"] or None,
            ),
        )

    @classmethod
    def disabled(cls) -> "Observability":
        """No instrumentation at all (overhead-measurement baseline)."""
        return cls(metrics=None, tracer=None, events=None)

    def render_prometheus(self) -> str:
        """Exposition text of the bundled registry ("" when none)."""
        return (
            self.metrics.render_prometheus()
            if self.metrics is not None else ""
        )


__all__ = [
    "BurnRateMonitor",
    "CapacityTracker",
    "ChildTelemetry",
    "ClockAlign",
    "Counter",
    "DEFAULT_LATENCY_BUCKETS",
    "DEFAULT_SIZE_BUCKETS",
    "EVENT_KINDS",
    "EventCounters",
    "EventLog",
    "FitTelemetry",
    "FleetScrapeServer",
    "Gauge",
    "Histogram",
    "LatencyRecorder",
    "MetricsRegistry",
    "ModelCostLedger",
    "Observability",
    "OccupancyCounter",
    "STAGES",
    "Span",
    "SpanContext",
    "Tracer",
    "attach_context",
    "clock_anchor",
    "current_context",
    "current_trace_id",
    "merge_chrome",
    "merge_events",
    "read_sink",
    "render_fleet_prometheus",
]
