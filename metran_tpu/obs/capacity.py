"""Capacity & cost observability: where every millisecond — and every
device-second — goes.

PR 7's load bench showed the single-process ceiling ("one Python core
does 75% of the work and reads queue behind writes"), but that number
came from a one-off probe.  This module is the instrumentation the
service *carries*, so the saturation story reads off live gauges —
before and after the multi-process split is judged against it:

- **Stage-latency decomposition** (:class:`CapacityTracker`): every
  dispatched update/forecast/bulk-tick request decomposes into the
  canonical :data:`STAGES` — queue wait, lock wait, host prep, device
  time, publish — as a per-stage :class:`~metran_tpu.obs.metrics.
  LatencyRecorder` family (``metran_serve_stage_<stage>_seconds``
  histograms) with an invariant check that recorded stages sum to
  >= 90% of end-to-end request wall (``coverage()``; the
  ``metran_serve_stage_coverage_ratio`` gauge, validated by
  ``bench.py --phase capacity``).
- **Dispatch-thread utilization** (``utilization()``): the fraction of
  recent wall time the dispatch thread spent inside dispatches — the
  GIL-ceiling gauge.  Near 1.0 with queue/lock stages dominating IS
  the ROADMAP item-1 saturation story, read from a scrape.
- **SLO burn rate** (:class:`BurnRateMonitor`): rolling multi-window
  (5m/1h by default, injectable clock) error-budget burn for the
  p99 < 50 ms serve SLO, fed per request from the dispatch paths.
- **Per-model cost accounting** (:class:`ModelCostLedger`):
  update/read/gate/detect/refit counts and amortized device-seconds
  per model, with ``top_models(by="device_s")`` for fleet triage.

The per-(bucket, kernel-kind) **compile & device-time ledger** lives
with the compiled-kernel cache it instruments
(:class:`~metran_tpu.serve.registry.CompiledFnCache`); everything is
assembled into one structured snapshot by
:meth:`~metran_tpu.serve.MetranService.capacity_report` and rendered
by ``tools/capacity_report.py``.

Cost discipline (the bars ``bench.py --phase capacity`` enforces:
<= 5% on the arena bulk update path, <= 1% on cached reads):

- stage timing is a handful of ``time.monotonic()`` stamps per
  *dispatch* (never per request) flushed in one bulk recorder call;
- ``sample_every=N`` records only every Nth dispatch — the
  sampled-subset mode for deployments where even the stamps matter
  (the reported distributions and coverage then describe the sampled
  subset; fractions stay unbiased);
- the **cached read path is deliberately untouched**: a snapshot hit
  is ~2 µs of host memory and the 1% bar leaves no room for even one
  per-read dict operation, so cached reads appear only in the
  store-level aggregate cache counters (``serve.readpath``), never in
  the per-model ledger.  Documented in docs/concepts.md
  ("Capacity & cost").

The device stage is bracketed on the dispatch thread: the kernel-cache
ledger calls ``jax.block_until_ready`` on the dispatch outputs (the
serving paths materialize them immediately afterward anyway, so the
block moves a wait it does not add), and the outer stamps therefore
measure true kernel wall, not async-dispatch submission time.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from logging import getLogger
from typing import Dict, List, Optional, Tuple

from .metrics import LatencyRecorder, MetricsRegistry

logger = getLogger(__name__)

#: The canonical stage catalogue.  Every stage label the serving layer
#: records (``CapacityTracker.observe_stage``) must be listed here AND
#: documented in the stage table of docs/concepts.md ("Capacity &
#: cost") — ``tools/check_metrics.py`` AST-scans both, the same drift
#: gate the event-kind catalogue carries.  Order is the pipeline
#: order; see the concepts table for exact boundaries.
STAGES = (
    "queue",      # submit/enqueue -> dispatch claim (incl. defer wait)
    "lock",       # _update_lock + arena-lock acquisition waits
    "host_prep",  # lookup/stacking/validation/standardization
    "device",     # kernel dispatch -> outputs materialized on host
    "publish",    # per-slot finalize: commit, snapshot, telemetry
    "wal",        # write-ahead-log group append + fdatasync (pre-ack)
)

#: default burn-rate windows (seconds) and their gauge labels
DEFAULT_BURN_WINDOWS: Tuple[float, ...] = (300.0, 3600.0)

#: default serve SLO (seconds) — the p99 < 50 ms bar the load bench
#: measures against — and the violation budget the burn rate divides
#: by (p99 < SLO == at most 1% of requests over it)
DEFAULT_SLO_S = 0.050
DEFAULT_SLO_BUDGET = 0.01


def window_label(seconds: float) -> str:
    """A compact metric-name-safe label for a burn window (300 ->
    ``5m``, 3600 -> ``1h``, 90000 -> ``25h``)."""
    s = int(seconds)
    if s % 3600 == 0:
        return f"{s // 3600}h"
    if s % 60 == 0:
        return f"{s // 60}m"
    return f"{s}s"


class BurnRateMonitor:
    """Rolling multi-window SLO error-budget burn (thread-safe).

    The SLO is stated as a latency bound plus a violation budget: with
    ``slo_s=0.05`` and ``budget=0.01``, "p99 < 50 ms" — at most 1% of
    requests may exceed 50 ms.  ``burn_rate(window)`` is the windowed
    violation fraction divided by the budget: 1.0 means the budget is
    being consumed exactly at its sustainable rate, >1 means it burns
    faster (the standard multi-window burn-rate alerting quantity —
    page on the short window, ticket on the long one).

    Implementation: time-bucketed (``bucket_s``-wide) counters in a
    bounded deque sized to the longest window — O(1) memory however
    long the service lives, O(windows) per read.  ``clock`` is
    injectable (monotonic seconds) so the burn-rate math is unit
    -testable deterministically.
    """

    def __init__(self, slo_s: float = DEFAULT_SLO_S,
                 budget: float = DEFAULT_SLO_BUDGET,
                 windows: Tuple[float, ...] = DEFAULT_BURN_WINDOWS,
                 bucket_s: float = 10.0, clock=time.monotonic):
        if slo_s <= 0:
            raise ValueError(f"slo_s must be > 0, got {slo_s}")
        if not 0 < budget <= 1:
            raise ValueError(f"budget must be in (0, 1], got {budget}")
        if not windows:
            raise ValueError("at least one burn window is required")
        self.slo_s = float(slo_s)
        self.budget = float(budget)
        self.windows = tuple(sorted(float(w) for w in windows))
        if self.windows[0] <= 0:
            raise ValueError(f"windows must be > 0, got {windows}")
        self.bucket_s = float(bucket_s)
        self._clock = clock
        # (bucket_index, total, violations); bounded to the longest
        # window plus one partial bucket
        n = int(self.windows[-1] / self.bucket_s) + 2
        self._buckets: "deque[list]" = deque(maxlen=n)
        self._lock = threading.Lock()
        self.total = 0
        self.violations = 0

    def observe(self, latency_s: float, n: int = 1) -> None:
        """Book ``n`` requests at ``latency_s`` seconds each."""
        viol = n if latency_s > self.slo_s else 0
        self._book(n, viol)

    def observe_many(self, latencies) -> None:
        """Book a batch of per-request latencies in one lock trip."""
        total = 0
        viol = 0
        slo = self.slo_s
        for v in latencies:
            total += 1
            if v > slo:
                viol += 1
        if total:
            self._book(total, viol)

    def _book(self, n: int, violations: int) -> None:
        idx = int(self._clock() / self.bucket_s)
        with self._lock:
            if self._buckets and self._buckets[-1][0] == idx:
                b = self._buckets[-1]
                b[1] += n
                b[2] += violations
            else:
                self._buckets.append([idx, n, violations])
            self.total += n
            self.violations += violations

    def window_stats(self, window_s: float) -> Dict[str, float]:
        """Requests/violations/fraction/burn over the trailing window."""
        now_idx = self._clock() / self.bucket_s
        min_idx = now_idx - float(window_s) / self.bucket_s
        total = viol = 0
        with self._lock:
            for idx, n, v in self._buckets:
                if idx >= min_idx:
                    total += n
                    viol += v
        frac = viol / total if total else 0.0
        return {
            "window_s": float(window_s),
            "requests": total,
            "violations": viol,
            "violation_fraction": frac,
            "burn_rate": frac / self.budget,
        }

    def burn_rate(self, window_s: float) -> float:
        """Windowed violation fraction over the budget (see class doc)."""
        return self.window_stats(window_s)["burn_rate"]

    def snapshot(self) -> dict:
        """Every configured window's stats plus the SLO statement."""
        return {
            "slo_ms": self.slo_s * 1e3,
            "budget": self.budget,
            "requests_total": self.total,
            "violations_total": self.violations,
            "windows": {
                window_label(w): self.window_stats(w)
                for w in self.windows
            },
        }


class ModelCostLedger:
    """Per-model cost accounting: who is spending the fleet's capacity.

    Tracks, per model id: ``updates`` / ``reads`` committed through
    the dispatch paths, ``gate_flags`` (observations the gate acted
    on), ``detect_alarms``, ``refits``, and amortized ``device_s`` —
    each batched dispatch's measured device wall split evenly over its
    riders (the honest per-model share of a shared execution).
    Cached snapshot reads are deliberately NOT counted here (see the
    module docstring's 1%-bar note); they appear in the aggregate
    cache counters only.

    Bounded: past ``max_models`` tracked ids the cheapest half (by
    ``device_s``) is pruned and counted in ``pruned`` — fleet-scale
    services keep their hottest models' accounting, which is what
    ``top_models`` triage needs.
    """

    FIELDS = ("updates", "reads", "gate_flags", "detect_alarms",
              "refits", "device_s")
    _IDX = {f: i for i, f in enumerate(FIELDS)}
    _DEV = FIELDS.index("device_s")

    def __init__(self, max_models: int = 100_000):
        self.max_models = int(max_models)
        # entries are flat lists indexed by _IDX — the charge paths
        # run per rider per dispatch, and list indexing beats a
        # six-key dict measurably at fleet batch sizes
        self._models: Dict[str, list] = {}
        self._lock = threading.Lock()
        self.pruned = 0

    def _prune(self) -> None:
        dev = self._DEV
        keep = sorted(
            self._models.items(), key=lambda kv: kv[1][dev],
            reverse=True,
        )[: self.max_models // 2]
        self.pruned += len(self._models) - len(keep)
        self._models = dict(keep)

    def charge(self, model_id: str, field: str, n: int = 1,
               device_s: float = 0.0) -> None:
        idx = self._IDX[field]
        with self._lock:
            e = self._models.get(model_id)
            if e is None:
                if len(self._models) >= self.max_models:
                    self._prune()  # before inserting: the new entry
                    # (zero device_s) must survive its own charge
                e = self._models[model_id] = [0, 0, 0, 0, 0, 0.0]
            e[idx] += n
            if device_s:
                e[self._DEV] += device_s

    def charge_many(self, model_ids, field: str,
                    device_s_total: float = 0.0) -> None:
        """One dispatch's outcome for all its riders: ``field`` += 1
        each, the shared device wall split evenly."""
        n = len(model_ids)
        if not n:
            return
        idx = self._IDX[field]
        dev = self._DEV
        share = device_s_total / n
        cap = self.max_models
        with self._lock:
            models = self._models
            for mid in model_ids:
                e = models.get(mid)
                if e is None:
                    if len(models) >= cap:
                        self._prune()
                        models = self._models
                    e = models[mid] = [0, 0, 0, 0, 0, 0.0]
                e[idx] += 1
                e[dev] += share

    def count_refit(self, model_id: str) -> None:
        self.charge(model_id, "refits")

    def __len__(self) -> int:
        with self._lock:
            return len(self._models)

    def top_models(self, by: str = "device_s",
                   limit: int = 10) -> List[dict]:
        """The ``limit`` most expensive models by ``by`` (any of
        :data:`FIELDS`), each as ``{"model_id": ..., **costs}``."""
        if by not in self._IDX:
            raise ValueError(
                f"unknown cost field {by!r}; expected one of "
                f"{self.FIELDS}"
            )
        idx = self._IDX[by]
        with self._lock:
            items = sorted(
                self._models.items(), key=lambda kv: kv[1][idx],
                reverse=True,
            )[: int(limit)]
        return [
            {"model_id": mid,
             **{f: (round(e[i], 6) if f == "device_s" else e[i])
                for f, i in self._IDX.items()}}
            for mid, e in items
        ]

    def snapshot(self, limit: Optional[int] = None) -> dict:
        return {
            "tracked_models": len(self),
            "pruned": self.pruned,
            "top_by_device_s": self.top_models(
                "device_s", limit if limit is not None else 10
            ),
        }


class _DispatchAcc:
    """One sampled dispatch's stage accumulator (single-threaded —
    dispatches run on one thread; no lock)."""

    __slots__ = ("stages", "counts")

    def __init__(self):
        self.stages = dict.fromkeys(STAGES, 0.0)
        self.counts = dict.fromkeys(STAGES, 0)


class CapacityTracker:
    """The service-side stage/utilization/SLO aggregator (module doc).

    Usage, on a dispatch thread::

        acc = tracker.begin_dispatch()        # None when sampled out
        ...
        tracker.observe_stage("lock", dt)     # no-op when not sampled
        ...
        tracker.end_dispatch(acc, waits, t_claim, t_end)

    ``begin_dispatch`` parks the accumulator in a thread-local so the
    helpers the dispatch body calls (`_run_update_dict`,
    `_arena_dispatch_rows`, ...) record stages without signature
    changes; dispatches run one-per-thread-at-a-time, so a begin that
    finds a parked accumulator treats it as leaked by an exception
    path and discards it (see :meth:`begin_dispatch`).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 sample_every: int = 1,
                 slo_s: float = DEFAULT_SLO_S,
                 slo_budget: float = DEFAULT_SLO_BUDGET,
                 burn_windows: Tuple[float, ...] = DEFAULT_BURN_WINDOWS,
                 max_models: int = 100_000,
                 clock=time.monotonic):
        self.clock = clock
        self.sample_every = max(1, int(sample_every))
        self.slo = BurnRateMonitor(
            slo_s=slo_s, budget=slo_budget, windows=burn_windows,
            clock=clock,
        )
        self.costs = ModelCostLedger(max_models=max_models)
        self.recorders: Dict[str, LatencyRecorder] = {
            s: LatencyRecorder(
                registry=registry,
                name=f"metran_serve_stage_{s}_seconds",
                help=f"per-dispatch {s} stage wall (seconds); see the "
                     "stage table in docs/concepts.md (Capacity & cost)",
            )
            for s in STAGES
        }
        self._lock = threading.Lock()
        self._totals = dict.fromkeys(STAGES, 0.0)
        self._counts = dict.fromkeys(STAGES, 0)
        self._wall_s = 0.0     # sum over requests of end-to-end wall
        self._staged_s = 0.0   # sum over requests of attributed stages
        self._requests = 0
        self._dispatches = 0
        self._sampled = 0
        self._busy_s = 0.0     # dispatch-thread seconds inside dispatches
        self._t0 = float(clock())
        # (instant, cumulative busy) marks for windowed utilization
        self._busy_marks: "deque[tuple]" = deque(maxlen=1024)
        self._tls = threading.local()
        if registry is not None:
            registry.gauge(
                "metran_serve_stage_coverage_ratio",
                "recorded stages over end-to-end request wall (the "
                "decomposition invariant; bar >= 0.9)",
                callback=self.coverage,
            )
            registry.gauge(
                "metran_serve_dispatch_utilization",
                "fraction of recent wall time the dispatch thread "
                "spent inside dispatches (the GIL-ceiling gauge)",
                callback=self.utilization,
            )
            for w in self.slo.windows:
                registry.gauge(
                    f"metran_serve_slo_burn_rate_{window_label(w)}",
                    f"error-budget burn rate over the trailing "
                    f"{window_label(w)} (1.0 = budget consumed at "
                    "exactly its sustainable rate)",
                    callback=(lambda w=w: self.slo.burn_rate(w)),
                )

    # -- dispatch lifecycle ---------------------------------------------
    def begin_dispatch(self) -> Optional[_DispatchAcc]:
        """Start one dispatch's stage accounting, or ``None`` when this
        dispatch is sampled out.

        One dispatch runs per thread at a time, so an accumulator
        still parked in the thread-local here was LEAKED by an
        exception path (an injected whole-batch dispatch fault, a
        crashed finalize) — it is discarded (its partial stats never
        flush) rather than left to blind capacity accounting on this
        thread forever."""
        with self._lock:
            self._dispatches += 1
            sampled = (self._dispatches - 1) % self.sample_every == 0
            if sampled:
                self._sampled += 1
        if not sampled:
            self._tls.acc = None  # clear any leaked accumulator too
            return None
        acc = _DispatchAcc()
        self._tls.acc = acc
        return acc

    def active(self) -> Optional[_DispatchAcc]:
        """The dispatch accumulator parked on this thread, if any."""
        return getattr(self._tls, "acc", None)

    def device_charge(self, measured_s: float) -> float:
        """Scale one SAMPLED dispatch's measured device wall to its
        cost-ledger charge: under ``sample_every=N`` each sampled
        dispatch stands for N dispatches, so the per-model amortized
        device-seconds stay an unbiased estimate instead of an N-fold
        undercount (the same convention the kernel ledger uses)."""
        return measured_s * self.sample_every

    def observe_stage(self, stage: str, seconds: float,
                      n: int = 1) -> None:
        """Accumulate ``seconds`` of ``stage`` into the active
        dispatch (no-op off a sampled dispatch).  ``stage`` must be a
        :data:`STAGES` member — call sites pass literals, which is
        what the ``tools/check_metrics.py`` stage drift gate scans."""
        acc = getattr(self._tls, "acc", None)
        if acc is None:
            return
        acc.stages[stage] += seconds
        acc.counts[stage] += n

    def end_dispatch(self, acc: _DispatchAcc, waits, t_claim: float,
                     t_end: float, latencies=None) -> None:
        """Flush one sampled dispatch: per-stage histograms (one
        sample per stage per dispatch; per-request samples for the
        queue stage), the coverage sums, the busy-time marks, and the
        SLO burn monitor.

        ``waits`` are the riders' queue waits (enqueue -> claim,
        seconds; an empty list books the dispatch as one queue-less
        request — the bulk-tick form).  Per-request end-to-end wall is
        ``wait_i + (t_end - t_claim)``: every rider experiences the
        full shared dispatch, which is exactly what its future's
        resolution latency shows."""
        if self._tls.acc is acc:
            self._tls.acc = None
        q_list = list(waits) if waits else None
        n_req = len(q_list) if q_list is not None else 1
        q_sum = sum(q_list) if q_list is not None else 0.0
        span = max(t_end - t_claim, 0.0)
        staged_shared = sum(
            acc.stages[s] for s in STAGES if s != "queue"
        )
        if q_list is not None:
            self.recorders["queue"].record_many(q_list)
        for s in STAGES:
            if s != "queue" and acc.counts[s]:
                self.recorders[s].record(acc.stages[s])
        with self._lock:
            self._totals["queue"] += q_sum
            self._counts["queue"] += n_req
            for s in STAGES:
                if s != "queue" and acc.counts[s]:
                    self._totals[s] += acc.stages[s]
                    self._counts[s] += 1
            self._wall_s += q_sum + n_req * span
            self._staged_s += q_sum + n_req * min(staged_shared, span)
            self._requests += n_req
            self._busy_s += span
            self._busy_marks.append((t_end, self._busy_s))
        if latencies is not None:
            # the caller already holds the riders' end-to-end
            # latencies (the same values wait_i + span would rebuild)
            self.slo.observe_many(latencies)
        elif q_list is not None:
            self.slo.observe_many([w + span for w in q_list])
        else:
            self.slo.observe(span)

    # -- read -----------------------------------------------------------
    def coverage(self) -> float:
        """Attributed stage seconds over end-to-end request wall,
        cumulative over the sampled dispatches (the >= 0.9 invariant
        ``bench.py --phase capacity`` validates).  1.0 until the first
        dispatch (nothing to decompose is vacuously covered)."""
        with self._lock:
            if self._wall_s <= 0.0:
                return 1.0
            return self._staged_s / self._wall_s

    def utilization(self, window_s: float = 60.0) -> float:
        """Fraction of the trailing ``window_s`` the dispatch thread
        spent inside dispatches (sampled dispatches only — scale by
        ``sample_every`` mentally when sampling; default 1 records
        all).  Falls back to the lifetime average while the mark
        window is still filling."""
        now = float(self.clock())
        with self._lock:
            busy_now = self._busy_s
            marks = self._busy_marks
            anchor_t, anchor_busy = self._t0, 0.0
            if (
                marks
                and len(marks) == marks.maxlen
                and marks[0][0] >= now - window_s
            ):
                # the deque is full and even its OLDEST retained mark
                # is inside the window (sustained high dispatch rate):
                # anchor there — falling back to (_t0, 0) would read a
                # long-lived service as idle at exactly the moment it
                # saturates
                anchor_t, anchor_busy = marks[0]
            else:
                for t, b in marks:
                    if t >= now - window_s:
                        break
                    anchor_t, anchor_busy = t, b
        elapsed = max(now - anchor_t, 1e-9)
        return min(max((busy_now - anchor_busy) / elapsed, 0.0), 1.0)

    def stage_report(self) -> dict:
        """Per-stage totals/percentiles/shares (the report body)."""
        with self._lock:
            totals = dict(self._totals)
            counts = dict(self._counts)
        staged = sum(totals.values())
        out = {}
        for s in STAGES:
            rec = self.recorders[s]
            out[s] = {
                "seconds_total": round(totals[s], 6),
                "count": counts[s],
                "p50_ms": round(rec.p50 * 1e3, 4),
                "p99_ms": round(rec.p99 * 1e3, 4),
                "share": round(totals[s] / staged, 4) if staged else 0.0,
            }
        return out

    def report(self) -> dict:
        """The tracker's half of ``service.capacity_report()``."""
        with self._lock:
            dispatches = self._dispatches
            sampled = self._sampled
            requests = self._requests
            busy = self._busy_s
            wall = self._wall_s
        return {
            "stages": self.stage_report(),
            "coverage": round(self.coverage(), 4),
            "dispatches": dispatches,
            "sampled_dispatches": sampled,
            "sample_every": self.sample_every,
            "requests": requests,
            "busy_s": round(busy, 4),
            "request_wall_s": round(wall, 4),
            "utilization_60s": round(self.utilization(60.0), 4),
            "slo": self.slo.snapshot(),
            "models": self.costs.snapshot(),
        }


__all__ = [
    "BurnRateMonitor",
    "CapacityTracker",
    "DEFAULT_BURN_WINDOWS",
    "DEFAULT_SLO_BUDGET",
    "DEFAULT_SLO_S",
    "ModelCostLedger",
    "STAGES",
    "window_label",
]
