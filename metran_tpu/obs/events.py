"""Structured, attributed reliability-event log (JSON lines).

Counters say *how many* breaker trips or quarantines happened; a
post-mortem needs *which model, which request, in what order, why*.
The :class:`EventLog` is the serving stack's flight recorder: every
reliability event — breaker ``open``/``half_open``/``closed``
transitions, quarantines, retries, chain breaks, poisoned-update
rejections, deadline hits — is emitted as one structured record::

    {"ts": 1722772800.123, "kind": "breaker_open", "model_id": "m7",
     "request_id": "3f2a-00000004", "fault_point": "serve.dispatch",
     "detail": {"previous": "closed", "failures": 5}}

``model_id`` + ``request_id`` (the tracing correlation ID when tracing
is on) + ``fault_point`` (the named code location, matching
``reliability.faultinject`` point names where one exists) make the log
joinable against traces and metrics: a model's outage reconstructs
from ``log.for_model("m7")`` alone — breaker opened after N rejected
updates at the integrity gate, cooled down, probe succeeded, closed.

Every record also carries the emitting ``pid`` and a ``mono``
(monotonic-clock) stamp alongside the wall ``ts``: the fleet merge in
:mod:`metran_tpu.obs.fleet` orders events from many processes by
aligning each process's monotonic timeline against a (wall, mono)
anchor, which wall clocks alone (settable, skewable) cannot provide.

Storage is a bounded ring buffer (memory-safe for long-lived services)
plus an optional append-only JSON-lines **file sink** flushed per
event, so a crash loses nothing that was emitted.  A sink write
failure disables the sink (and logs once) rather than ever failing the
serving path — telemetry must not take down what it observes.

Stdlib-only, thread-safe; one ``emit()`` is a dict build, a deque
append and (with a sink) one buffered write.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from collections import deque
from logging import getLogger
from typing import Dict, List, Optional

logger = getLogger(__name__)

#: JSON-lines sink schema version.  v1 (PR 4..18) had no ``v`` key and
#: no ``pid``/``mono`` fields; v2 lines carry ``"v": 2`` plus both.
#: :func:`read_sink` reads either — old sinks stay post-mortem-able.
SINK_SCHEMA_VERSION = 2

#: The canonical event-kind catalogue.  Every ``kind`` the package
#: emits must be listed here AND documented in the event-schema table
#: of docs/concepts.md ("Structured event log") — ``tools/
#: check_metrics.py`` AST-scans both and the ``obs``-marked tier-1
#: drift gate fails on any mismatch, so an undeclared or undocumented
#: kind cannot ship.  Kinds are not enforced at ``emit()`` time (the
#: log accepts ad-hoc kinds from embedding applications); the gate
#: governs what THIS package emits.
EVENT_KINDS = (
    "breaker_open",
    "breaker_half_open",
    "breaker_closed",
    "quarantine",
    "served_last_good",
    "retry",
    "deadline_exceeded",
    "chain_break",
    "poisoned_update",
    "poisoned_forecast",
    "persist_failure",
    "observation_rejected",
    "observation_downweighted",
    "robust_update",
    "robust_fallback",
    "robust_solver_nonconverged",
    "empty_update",
    "arena_load",
    "arena_spill",
    "snapshot_publish",
    "steady_freeze",
    "steady_thaw",
    "anomaly",
    "changepoint",
    "alert_raised",
    "alert_cleared",
    "degraded",
    "refit_scheduled",
    "refit_promoted",
    "refit_rejected",
    "refit_failed",
    "wal_sync_failure",
    "wal_torn_record",
    "checkpoint",
    "checkpoint_failure",
    "spill_failure",
    "recovery_start",
    "recovery_complete",
    "worker_start",
    "worker_exit",
    "worker_restart",
    "snapshot_plane_publish",
    "reader_fallback",
    "replica_connect",
    "replica_lag",
    "replica_promote",
    "primary_fenced",
    "fleet_telemetry_gap",
)


class EventLog:
    """Bounded structured event ring with optional JSON-lines sink.

    Parameters
    ----------
    maxlen : events kept in memory (oldest dropped).
    sink : a path (opened append-mode) or an open text file-like; each
        event is written as one JSON line and flushed.  ``None``
        disables the sink (ring buffer only).
    clock : epoch-seconds time source (injectable for tests).
    mono_clock : monotonic time source stamped as ``mono`` on every
        record (injectable for tests); the fleet merge orders on this.
    max_sink_mb : bound the on-disk sink by size (``METRAN_TPU_OBS_
        EVENT_SINK_MAX_MB``; ``None``/0 = unbounded, the historical
        behavior).  A **path-constructed** sink reaching the bound is
        rotated: the current file moves to ``<path>.1`` (replacing any
        earlier rotation — at most two files ever exist, so a
        long-lived service cannot fill the disk) and a fresh file is
        opened at the path; the fd the log owned is closed, the new
        one is owned — the close-semantics contract is unchanged.
        Caller-provided file objects are never rotated (the log does
        not know their path and does not own their lifecycle).
    """

    def __init__(self, maxlen: int = 2048, sink=None,
                 clock=time.time, max_sink_mb: Optional[float] = None,
                 mono_clock=time.monotonic):
        self._events: "deque[dict]" = deque(maxlen=int(maxlen))
        self._lock = threading.Lock()
        self._clock = clock
        self._mono = mono_clock
        self._pid = os.getpid()
        self._counts: Dict[str, int] = {}
        self.dropped = 0  # events pushed out of the ring (lifetime)
        self.rotations = 0  # sink files rotated to the .1 suffix
        self._sink = None
        self._owns_sink = False
        self._sink_path: Optional[str] = None
        self._max_sink_bytes = (
            int(float(max_sink_mb) * 1024 * 1024) if max_sink_mb else 0
        )
        if sink is not None:
            if isinstance(sink, (str, bytes)) or hasattr(sink, "__fspath__"):
                try:
                    self._sink = open(sink, "a", encoding="utf-8")
                    self._owns_sink = True
                    self._sink_path = os.fspath(sink)
                except OSError:
                    # degrade-don't-fail, same contract as a write
                    # failure: an unwritable sink path must not stop
                    # the service this log observes from constructing
                    logger.exception(
                        "event-log sink %r could not be opened; "
                        "continuing with the in-memory ring only", sink,
                    )
            else:
                self._sink = sink

    def emit(self, kind: str, model_id: Optional[str] = None,
             request_id: Optional[str] = None,
             fault_point: Optional[str] = None, **detail) -> dict:
        """Record one event; returns the record (a plain dict).

        ``request_id`` defaults to the caller thread's active tracing
        correlation ID, so events emitted on the request path join the
        trace without explicit plumbing; cross-thread emitters (the
        dispatch path) pass it explicitly.
        """
        if request_id is None:
            from .tracing import current_trace_id

            request_id = current_trace_id()
        event = {
            "ts": float(self._clock()),
            "mono": float(self._mono()),
            "pid": self._pid,
            "kind": str(kind),
            "model_id": model_id,
            "request_id": request_id,
            "fault_point": fault_point,
            "detail": detail,
        }
        line = None
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(event)
            self._counts[event["kind"]] = (
                self._counts.get(event["kind"], 0) + 1
            )
            sink = self._sink
            if sink is not None:
                versioned = dict(event, v=SINK_SCHEMA_VERSION)
                try:
                    line = json.dumps(versioned, default=repr)
                except (TypeError, ValueError):  # exotic detail payload
                    safe = dict(versioned, detail=repr(detail))
                    line = json.dumps(safe)
        if sink is not None and line is not None:
            try:
                sink.write(line + "\n")
                sink.flush()
            except (OSError, ValueError, io.UnsupportedOperation):
                # a full disk / closed file must degrade the sink, not
                # the serving path that emitted the event.  A write
                # that raced a size rotation (its fd closed under it)
                # only loses its own line — the fresh sink stays up.
                with self._lock:
                    disabled = self._sink is sink
                    if disabled:
                        self._sink = None
                        owns, self._owns_sink = self._owns_sink, False
                    else:
                        owns = False  # rotated away mid-write
                if owns:
                    try:
                        sink.close()  # release the fd we opened
                    except (OSError, ValueError):
                        pass
                if disabled:
                    logger.exception(
                        "event-log sink failed; disabling the file "
                        "sink (in-memory ring continues)"
                    )
            else:
                self._maybe_rotate(sink)
        return event

    def _maybe_rotate(self, sink) -> None:
        """Rotate an owned, path-constructed sink past the size bound
        (see the constructor doc); no-op otherwise.  A rotation
        failure degrades to ring-only like a write failure."""
        if not self._max_sink_bytes:
            return
        try:
            size = sink.tell()
        except (OSError, ValueError):
            return
        if size < self._max_sink_bytes:
            return
        with self._lock:
            if (
                self._sink is not sink
                or not self._owns_sink
                or self._sink_path is None
            ):
                return  # caller-provided, already swapped, or closed
            try:
                sink.close()
                os.replace(self._sink_path, self._sink_path + ".1")
                self._sink = open(
                    self._sink_path, "a", encoding="utf-8"
                )
                self.rotations += 1
            except OSError:
                self._sink = None
                self._owns_sink = False
                logger.exception(
                    "event-log sink rotation failed; disabling the "
                    "file sink (in-memory ring continues)"
                )

    # -- read -----------------------------------------------------------
    def tail(self, n: int = 50) -> List[dict]:
        """The most recent ``n`` events, oldest first."""
        with self._lock:
            events = list(self._events)
        return events[-int(n):]

    def snapshot(self) -> List[dict]:
        """Every buffered event, oldest first."""
        with self._lock:
            return list(self._events)

    def for_model(self, model_id: str) -> List[dict]:
        """One model's buffered events, oldest first — the post-mortem
        view (see module docstring)."""
        with self._lock:
            return [
                e for e in self._events if e["model_id"] == model_id
            ]

    def for_request(self, request_id: str) -> List[dict]:
        """Events attributed to one correlation ID, oldest first."""
        with self._lock:
            return [
                e for e in self._events if e["request_id"] == request_id
            ]

    def counts(self) -> Dict[str, int]:
        """Lifetime event totals by kind (survives ring eviction)."""
        with self._lock:
            return dict(self._counts)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def close(self) -> None:
        """Close a sink this log opened itself (path-constructed)."""
        with self._lock:
            sink, self._sink = self._sink, None
            owns, self._owns_sink = self._owns_sink, False
        if sink is not None and owns:
            try:
                sink.close()
            except OSError:  # pragma: no cover - close-on-full-disk
                pass

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_sink(path) -> List[dict]:
    """Parse a JSON-lines sink back into event records, any schema
    version.

    v1 lines (no ``v`` key — sinks written before PR 19) are upgraded
    in place with ``pid=None, mono=None`` so consumers see one shape;
    the ``v`` marker itself is stripped (it describes the line, not
    the event).  Malformed lines are skipped, not fatal: a sink that
    caught a crash mid-write must still be readable past the tear —
    the whole point of flushing per event.
    """
    records: List[dict] = []
    try:
        fh = open(path, "r", encoding="utf-8")
    except OSError:
        return records
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line
            if not isinstance(rec, dict) or "kind" not in rec:
                continue
            rec.pop("v", None)
            rec.setdefault("pid", None)
            rec.setdefault("mono", None)
            records.append(rec)
    return records


__all__ = ["EVENT_KINDS", "EventLog", "SINK_SCHEMA_VERSION", "read_sink"]
