"""Fleet observability: merge per-process telemetry into one pane.

PRs 16/17 split the service into a frontend + writer + N read workers
+ hot standbys, but the PR 4/12 observability stack stayed
process-local: each process has its own ``MetricsRegistry``,
``EventLog`` ring and tracer, unreachable from the routing side.  This
module is the merge layer: every cluster child serves a ``telemetry``
RPC op (a :class:`ChildTelemetry` bound to its ``Observability``
bundle) returning one **telemetry part** — pid, role, a (wall,
monotonic) clock anchor, structured metric samples
(:meth:`~metran_tpu.obs.metrics.MetricsRegistry.export_samples`),
event records and finished spans — and the frontend merges parts into:

- one Prometheus exposition with a ``process`` label distinguishing
  the emitting process (:func:`render_fleet_prometheus`),
- one clock-aligned event timeline (:func:`merge_events`),
- one Chrome trace with a process lane per pid
  (:func:`merge_chrome`), where a propagated correlation id
  (``cluster/ipc.py`` envelope) stitches frontend → writer → standby
  spans into a single tree.

**Clock alignment.**  Wall clocks across processes are settable and
skewable; monotonic clocks are well-ordered but have per-process
arbitrary epochs (on Linux the raw readings are system-wide, but the
merge must not depend on that).  Each part therefore carries an
anchor pairing the two clocks read back-to-back
(:func:`clock_anchor`), and :class:`ClockAlign` refines it with a
Cristian-style estimate per telemetry round-trip: the child's anchor
monotonic reading is assumed to coincide with the midpoint of the
collector's request/response monotonic stamps, and the estimate with
the smallest round-trip time wins.  Merged timestamps (``fleet_ts``)
live on the collector's monotonic timeline; :func:`fleet_wall` maps
them back to wall time for human rendering.

The ``process`` label is **reserved**: package code must not register
metrics carrying it (``tools/check_metrics.py`` gates this), because
the fleet merge stamps it on every sample and a pre-existing value
would be silently overwritten.

Stdlib-only, like the rest of ``obs``; no cluster imports (the
cluster frontend imports *this*, never the reverse).
"""

from __future__ import annotations

import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from logging import getLogger
from typing import Any, Callable, Dict, List, Optional, Tuple

from .metrics import _escape_help, _escape_label, _format_value

logger = getLogger(__name__)

__all__ = [
    "ChildTelemetry",
    "ClockAlign",
    "FleetScrapeServer",
    "clock_anchor",
    "fleet_wall",
    "merge_chrome",
    "merge_events",
    "render_fleet_prometheus",
]

#: telemetry-part schema version (forward-compat marker on the wire)
PART_VERSION = 1


def clock_anchor() -> Dict[str, float]:
    """A (wall, monotonic) clock pairing read back-to-back.

    The wall stamp is the midpoint of two reads bracketing the
    monotonic read, so the pairing error is bounded by half the
    three-call window (sub-microsecond in practice) rather than one
    full scheduler preemption.
    """
    w0 = time.time()
    mono = time.monotonic()
    w1 = time.time()
    return {"wall": (w0 + w1) / 2.0, "mono": mono}


def fleet_wall(ref_anchor: Dict[str, float], fleet_ts: float) -> float:
    """Map a merged (collector-monotonic) timestamp to wall seconds
    using the collector's own anchor."""
    return float(ref_anchor["wall"]) + (
        float(fleet_ts) - float(ref_anchor["mono"])
    )


class ClockAlign:
    """Per-process clock-offset estimates, best round-trip wins.

    ``observe()`` is called once per telemetry collection with the
    child's anchor monotonic reading and the collector's monotonic
    stamps bracketing the RPC; the offset maps child-monotonic values
    onto the collector's monotonic timeline
    (``ref_mono = child_mono + offset``).  Estimates accumulate across
    periodic collections — the minimum-RTT one is kept, so alignment
    *improves* over a fleet's lifetime instead of jittering with load.
    """

    def __init__(self):
        self._lock = threading.Lock()
        #: key -> (offset_s, rtt_s)
        self._est: Dict[str, Tuple[float, float]] = {}

    def observe(self, key: str, child_mono: float,
                ref_mono_send: float,
                ref_mono_recv: float) -> Tuple[float, float]:
        """Fold one round-trip into the estimate for ``key``; returns
        the retained ``(offset_s, rtt_s)``."""
        rtt = max(0.0, float(ref_mono_recv) - float(ref_mono_send))
        off = (
            (float(ref_mono_send) + float(ref_mono_recv)) / 2.0
            - float(child_mono)
        )
        with self._lock:
            cur = self._est.get(key)
            if cur is None or rtt <= cur[1]:
                self._est[key] = (off, rtt)
            return self._est[key]

    def offset(self, key: str) -> Optional[float]:
        with self._lock:
            est = self._est.get(key)
        return est[0] if est is not None else None

    def snapshot(self) -> Dict[str, Tuple[float, float]]:
        with self._lock:
            return dict(self._est)


class ChildTelemetry:
    """One process's ``telemetry`` RPC handler: its whole
    ``Observability`` bundle as a single mergeable part.

    Every cluster process (frontend included — the collector is a
    process too) holds one, bound to its bundle and role.  Registers
    the child-side fleet metrics on the bundle's registry when there
    is one: a process-uptime callback gauge and a serves counter that
    doubles as evidence the telemetry plane is actually being scraped.
    """

    def __init__(self, obs, role: str):
        self.obs = obs
        self.role = str(role)
        self._t0 = time.monotonic()
        self._serves = None
        m = getattr(obs, "metrics", None) if obs is not None else None
        if m is not None:
            m.gauge(
                "metran_cluster_process_uptime_seconds",
                "seconds since this process's telemetry handler was "
                "armed (one per fleet process, merged under the "
                "process label)",
                callback=lambda: time.monotonic() - self._t0,
            )
            self._serves = m.counter(
                "metran_cluster_telemetry_serves_total",
                "telemetry collections served by this process — zero "
                "on a live fleet means nobody is scraping the pane",
            )

    def collect(self, payload: Optional[dict] = None) -> dict:
        """Build the telemetry part.  ``payload`` (the RPC payload)
        may disable sections: ``{"metrics": False, "events": False,
        "spans": False}`` — a metrics-only scrape should not drag a
        2048-event ring over the socket every 15 seconds."""
        payload = payload or {}
        if self._serves is not None:
            self._serves.inc()
        obs = self.obs
        part: Dict[str, Any] = {
            "v": PART_VERSION,
            "pid": os.getpid(),
            "role": self.role,
            "anchor": clock_anchor(),
            "uptime_s": time.monotonic() - self._t0,
        }
        m = getattr(obs, "metrics", None) if obs is not None else None
        part["metrics"] = (
            m.export_samples()
            if (m is not None and payload.get("metrics", True))
            else None
        )
        ev = getattr(obs, "events", None) if obs is not None else None
        part["events"] = (
            ev.snapshot()
            if (ev is not None and payload.get("events", True))
            else []
        )
        tr = getattr(obs, "tracer", None) if obs is not None else None
        part["spans"] = (
            tr.spans()
            if (tr is not None and payload.get("spans", True))
            else []
        )
        return part


# ----------------------------------------------------------------------
# merge layer


def _ref_anchor(parts: List[dict]) -> Dict[str, float]:
    for part in parts:
        anchor = part.get("anchor")
        if isinstance(anchor, dict) and "wall" in anchor:
            return anchor
    return clock_anchor()


def _part_offset(part: dict, ref_anchor: Dict[str, float]) -> float:
    """child-monotonic -> collector-monotonic offset for one part:
    the collector's min-RTT estimate when it attached one
    (``part["clock"]["offset"]``), else the anchor-wall fallback
    (exact when wall clocks agree — always, same-host)."""
    clock = part.get("clock") or {}
    off = clock.get("offset")
    if isinstance(off, (int, float)):
        return float(off)
    anchor = part.get("anchor") or {}
    try:
        return (
            float(anchor["wall"]) - float(anchor["mono"])
        ) - (
            float(ref_anchor["wall"]) - float(ref_anchor["mono"])
        )
    except (KeyError, TypeError, ValueError):
        return 0.0


def _part_label(part: dict, index: int) -> str:
    label = part.get("process") or part.get("role")
    if not label:
        pid = part.get("pid")
        label = f"pid{pid}" if pid is not None else f"part{index}"
    return str(label)


def render_fleet_prometheus(parts: List[dict]) -> str:
    """One Prometheus exposition over many parts, every sample gaining
    a ``process`` label.

    One ``# HELP``/``# TYPE`` pair per family (first part to carry the
    family wins the metadata); families sorted by name, samples in
    part order then each part's own sample order — which keeps every
    process's histogram buckets in cumulative ``le`` order, as the
    grammar requires per label subgroup.  A family re-registered with
    a *different type* by another process is a telemetry bug; its
    conflicting samples are dropped and logged rather than emitting an
    exposition Prometheus would reject wholesale.
    """
    families: Dict[str, dict] = {}
    order: List[str] = []
    for index, part in enumerate(parts):
        label = _part_label(part, index)
        for fam in part.get("metrics") or []:
            name = str(fam.get("name", ""))
            if not name:
                continue
            entry = families.get(name)
            if entry is None:
                entry = {
                    "type": fam.get("type", "untyped"),
                    "help": fam.get("help", ""),
                    "rows": [],
                }
                families[name] = entry
                order.append(name)
            elif entry["type"] != fam.get("type", "untyped"):
                logger.warning(
                    "fleet metric %r: process %r reports type %r but "
                    "family is %r; dropping its samples", name, label,
                    fam.get("type"), entry["type"],
                )
                continue
            for sample in fam.get("samples") or []:
                sname = str(sample[0])
                labels = dict(sample[1])
                labels.pop("process", None)  # reserved (module doc)
                labels["process"] = label
                entry["rows"].append((sname, labels, float(sample[2])))
    lines: List[str] = []
    for name in sorted(order):
        entry = families[name]
        lines.append(f"# HELP {name} {_escape_help(entry['help'])}")
        lines.append(f"# TYPE {name} {entry['type']}")
        for sname, labels, value in entry["rows"]:
            inner = ",".join(
                f'{k}="{_escape_label(str(v))}"'
                for k, v in sorted(labels.items())
            )
            lines.append(f"{sname}{{{inner}}} {_format_value(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def merge_events(parts: List[dict]) -> List[dict]:
    """All parts' event records on one timeline, oldest first.

    Each record gains ``process`` (the part label) and ``fleet_ts``
    (collector-monotonic seconds, see module doc); v1 records without
    a ``mono`` stamp fall back to mapping their wall ``ts`` through
    the collector's anchor — coarser, but still ordered.  Input
    records are not mutated.
    """
    ref = _ref_anchor(parts)
    wall_to_ref = float(ref["mono"]) - float(ref["wall"])
    out: List[dict] = []
    for index, part in enumerate(parts):
        offset = _part_offset(part, ref)
        label = _part_label(part, index)
        for event in part.get("events") or []:
            rec = dict(event)
            mono = rec.get("mono")
            if isinstance(mono, (int, float)):
                fleet_ts = float(mono) + offset
            else:
                fleet_ts = float(rec.get("ts", 0.0)) + wall_to_ref
            rec["fleet_ts"] = fleet_ts
            rec["process"] = label
            out.append(rec)
    out.sort(key=lambda r: r["fleet_ts"])
    return out


def merge_chrome(parts: List[dict]) -> dict:
    """All parts' finished spans as one Chrome trace (``chrome://
    tracing``, Perfetto), one process lane per pid.

    Span timestamps are clock-aligned onto the collector's monotonic
    timeline then re-based to the earliest span, so lanes overlay
    truthfully: a writer span propagated from a frontend RPC renders
    *inside* the frontend's span.  ``args`` keeps the correlation
    ``trace_id``/``span_id``/``parent_id`` (plus the part label as
    ``process``), so one update's tree reassembles across lanes by
    querying the propagated trace id.  Metadata events name each lane
    ``<label> (pid N)`` and sort lanes in part order.
    """
    ref = _ref_anchor(parts)
    rows: List[Tuple[float, float, int, dict, str]] = []
    lanes: List[Tuple[int, str]] = []
    seen_pids = set()
    for index, part in enumerate(parts):
        offset = _part_offset(part, ref)
        label = _part_label(part, index)
        pid = int(part.get("pid") or 0)
        if pid not in seen_pids and part.get("spans"):
            seen_pids.add(pid)
            lanes.append((pid, label))
        for span in part.get("spans") or []:
            rows.append((
                float(span["ts"]) + offset,
                float(span["dur"]),
                pid,
                span,
                label,
            ))
    t0 = min((ts for ts, *_ in rows), default=0.0)
    events: List[dict] = []
    for sort_index, (pid, label) in enumerate(lanes):
        events.append({
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": f"{label} (pid {pid})"},
        })
        events.append({
            "name": "process_sort_index", "ph": "M", "pid": pid,
            "args": {"sort_index": sort_index},
        })
    for ts, dur, pid, span, label in rows:
        args = dict(span.get("args") or {})
        args["trace_id"] = span["trace_id"]
        args["span_id"] = span["span_id"]
        if span.get("parent_id") is not None:
            args["parent_id"] = span["parent_id"]
        args["process"] = label
        events.append({
            "name": span["name"],
            "cat": span["name"].split(".", 1)[0],
            "ph": "X",
            "ts": (ts - t0) * 1e6,
            "dur": dur * 1e6,
            "pid": pid,
            "tid": span.get("tid", 0),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ----------------------------------------------------------------------
# optional scrape endpoint


class FleetScrapeServer:
    """Minimal stdlib HTTP endpoint serving the merged exposition.

    Shipped **off** (``METRAN_TPU_OBS_FLEET_PORT=0``); when armed the
    frontend binds it on localhost and ``GET /metrics`` runs the
    supplied zero-argument ``collect`` callable (which performs the
    fleet telemetry fan-out — a scrape is a collection, there is no
    cache to go stale).  A collection failure answers 500 with the
    error text instead of killing the listener: the pane must not be
    torn down by one dead child.
    """

    def __init__(self, collect: Callable[[], str], port: int,
                 host: str = "127.0.0.1"):
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API
                if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                try:
                    body = outer._collect().encode("utf-8")
                except Exception as exc:  # degrade, never die
                    body = f"# fleet collection failed: {exc!r}\n".encode()
                    self.send_response(500)
                else:
                    self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet; we have our own log
                pass

        self._collect = collect
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            name=f"metran-fleet-scrape[{self.port}]",
            daemon=True,
        )
        self._thread.start()
        logger.info("fleet scrape endpoint on %s:%d", host, self.port)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
