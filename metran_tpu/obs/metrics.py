"""Unified metrics: one thread-safe registry, Prometheus exposition.

Before this module every subsystem kept its own ad-hoc counters
(``ServeMetrics`` latency recorders, the registry's integrity
``EventCounters``, ``CompiledFnCache.hits/misses``) with no single
place an operator — or a scrape endpoint — could read them from.  The
:class:`MetricsRegistry` is that place: **counters** (monotone totals),
**gauges** (instantaneous values, optionally computed by a callback at
collection time) and **fixed-bucket histograms** (latency/size
distributions), each registered once by name, collected together via
:meth:`MetricsRegistry.snapshot` (nested dict, JSON-ready) or
:meth:`MetricsRegistry.render_prometheus` (the Prometheus text
exposition format, ready to serve from any HTTP handler).

The serving instruments — :class:`LatencyRecorder`,
:class:`EventCounters`, :class:`OccupancyCounter`, historically in
``metran_tpu.utils.profiling`` (aliases remain there) — live here and
are *registry-backed*: constructed with ``registry=``/``name=`` they
mirror every observation into the shared registry (a histogram for
latencies and batch sizes, a ``kind``-labelled counter family for
events) while keeping their original standalone behavior — exact
percentiles from bounded sample windows, lifetime totals — so existing
callers see no change.

Metric names follow the Prometheus conventions this package enforces
(``tools/check_metrics.py``): snake_case, ``_total`` suffix on counter
families, ``_seconds`` on time histograms.  The full name catalogue is
in docs/concepts.md ("Observability").

Everything here is stdlib-only and allocation-light: instruments sit on
the serving hot path.
"""

from __future__ import annotations

import bisect
import contextlib
import math
import re
import threading
import time
from dataclasses import dataclass, field
from logging import getLogger
from typing import Callable, Dict, Iterator, List, Optional, Tuple

logger = getLogger(__name__)

# Prometheus allows [a-zA-Z_:][a-zA-Z0-9_:]*; this package additionally
# requires plain snake_case (no colons — those are reserved for
# recording rules — and no capitals), which check_metrics.py enforces
# statically over the whole package.
_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")
_LABEL_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

#: default buckets for request-latency histograms (seconds): sub-ms
#: through 10 s, roughly log-spaced — micro-batched serve latencies sit
#: in the 0.5-50 ms range on CPU, lower on a real accelerator.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: default buckets for batch-size histograms (powers of two up to the
#: default ``max_batch``).
DEFAULT_SIZE_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256,
)


def _format_value(v: float) -> str:
    """Prometheus sample-value formatting: integral floats render as
    integers, everything else as repr (NaN/Inf as ``NaN``/``+Inf``)."""
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return (
        str(v).replace("\\", r"\\").replace("\n", r"\n").replace('"', r"\"")
    )


def _escape_help(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


class _Metric:
    """Shared shape of every instrument: name, help, label names, lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 label_names: Tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        for ln in self.label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(
                    f"label name {ln!r} of metric {name!r} is not "
                    "snake_case"
                )
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.label_names}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[ln]) for ln in self.label_names)


class Counter(_Metric):
    """Monotone total, optionally split by a fixed label set.

    >>> c = registry.counter("metran_serve_errors_total",
    ...                      "errors by kind", label_names=("kind",))
    >>> c.inc(kind="retries")
    >>> c.value(kind="retries")
    1.0
    """

    kind = "counter"

    def __init__(self, name, help="", label_names=()):
        super().__init__(name, help, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(
                f"counter {self.name!r} cannot decrease (n={n}); use a "
                "gauge for values that go down"
            )
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def collect(self) -> dict:
        with self._lock:
            values = dict(self._values)
        if not self.label_names:
            return {"value": values.get((), 0.0)}
        return {
            "values": {
                ",".join(f"{ln}={lv}" for ln, lv in zip(self.label_names, k)):
                v for k, v in sorted(values.items())
            }
        }

    def _samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        with self._lock:
            values = dict(self._values)
        if not self.label_names:
            return [(self.name, {}, values.get((), 0.0))]
        return [
            (self.name, dict(zip(self.label_names, k)), v)
            for k, v in sorted(values.items())
        ]


class Gauge(_Metric):
    """Instantaneous value; set directly or computed by a callback.

    A ``callback`` (zero-argument callable returning a number) is
    evaluated at collection time — the natural fit for values another
    object already tracks (queue depth, cache residency, a sliding
    window's error rate) so no code has to remember to push updates.  A
    callback that raises yields ``NaN`` for that scrape rather than
    killing the exposition.
    """

    kind = "gauge"

    def __init__(self, name, help="", label_names=(),
                 callback: Optional[Callable[[], float]] = None):
        super().__init__(name, help, label_names)
        if callback is not None and label_names:
            raise ValueError(
                f"gauge {name!r}: callbacks are only supported on "
                "unlabelled gauges"
            )
        self._values: Dict[Tuple[str, ...], float] = {}
        self._callback = callback

    def set(self, v: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(v)

    def inc(self, n: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def value(self, **labels) -> float:
        if self._callback is not None:
            try:
                return float(self._callback())
            except Exception:
                logger.exception("gauge callback %r failed", self.name)
                return float("nan")
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def collect(self) -> dict:
        if self._callback is not None or not self.label_names:
            return {"value": self.value()}
        with self._lock:
            values = dict(self._values)
        return {
            "values": {
                ",".join(f"{ln}={lv}" for ln, lv in zip(self.label_names, k)):
                v for k, v in sorted(values.items())
            }
        }

    def _samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        if self._callback is not None or not self.label_names:
            return [(self.name, {}, self.value())]
        with self._lock:
            values = dict(self._values)
        return [
            (self.name, dict(zip(self.label_names, k)), v)
            for k, v in sorted(values.items())
        ]


class Histogram(_Metric):
    """Fixed-bucket distribution (unlabelled; buckets chosen at
    registration).

    Exposes the Prometheus histogram triplet: cumulative
    ``{name}_bucket{le="..."}`` counts (closing with ``le="+Inf"``),
    ``{name}_sum`` and ``{name}_count``.  Quantile *estimates* come
    from the buckets at scrape time; exact recent percentiles remain
    the job of :class:`LatencyRecorder`'s sample window.
    """

    kind = "histogram"

    def __init__(self, name, help="",
                 buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, ())
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError(f"histogram {name!r} needs at least 1 bucket")
        if any(b != b or math.isinf(b) for b in bounds):
            raise ValueError(
                f"histogram {name!r}: finite bucket bounds only "
                "(+Inf is implicit)"
            )
        self.buckets = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot: > max bound
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        # first bound with v <= bound (== the `le` bucket); C bisect —
        # this runs once per served request via LatencyRecorder
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def observe_many(self, values) -> None:
        """Bulk observe under ONE lock acquisition — the serving
        dispatch path books a whole batch's gate scores at once
        instead of paying per-value lock traffic.  Large batches
        bucket vectorized (searchsorted + bincount): the capacity
        plane books a dispatch's whole rider set per call, and B
        python bisects were a measurable slice of its overhead bar."""
        vals = [float(v) for v in values]
        n = len(vals)
        if not n:
            return
        if n >= 16:
            import numpy as _np

            arr = _np.asarray(vals)
            where = _np.searchsorted(self.buckets, arr, side="left")
            # match bisect_left's NaN placement (every comparison
            # false -> bucket 0) so bucket counts cannot depend on
            # which path a batch size selects
            nan = _np.isnan(arr)
            if nan.any():
                where[nan] = 0
            idxs = _np.bincount(
                where, minlength=len(self._counts),
            )
            total = float(arr.sum())
            with self._lock:
                counts = self._counts
                for i, c in enumerate(idxs):
                    if c:
                        counts[i] += int(c)
                self._sum += total
                self._count += n
            return
        idxs = [bisect.bisect_left(self.buckets, v) for v in vals]
        with self._lock:
            for i in idxs:
                self._counts[i] += 1
            self._sum += sum(vals)
            self._count += n

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def collect(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum = 0
        out = []
        for bound, c in zip(self.buckets, counts):
            cum += c
            out.append({"le": bound, "count": cum})
        out.append({"le": float("inf"), "count": total})
        return {"buckets": out, "sum": s, "count": total}


class MetricsRegistry:
    """Thread-safe, get-or-create registry of named instruments.

    Registration is idempotent: asking for an existing name returns the
    existing instrument when the type (and label set) matches, so every
    subsystem can declare the metrics it publishes without coordination
    — and raises when it does not, so two subsystems can never silently
    share one name for different things.  Re-registering a callback
    gauge rebinds the callback (a fresh service attached to a long-lived
    registry must read the *new* object's state).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, _Metric]" = {}

    # -- registration ---------------------------------------------------
    def _register(self, factory: Callable[[], _Metric], name: str,
                  kind: str, label_names: Tuple[str, ...]) -> _Metric:
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} is not snake_case "
                "([a-z_][a-z0-9_]*)"
            )
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.kind != kind or (
                    tuple(existing.label_names) != tuple(label_names)
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.label_names} "
                        f"(requested {kind}{tuple(label_names)})"
                    )
                return existing
            metric = factory()
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                label_names: Tuple[str, ...] = ()) -> Counter:
        return self._register(
            lambda: Counter(name, help, label_names), name, "counter",
            tuple(label_names),
        )

    def gauge(self, name: str, help: str = "",
              label_names: Tuple[str, ...] = (),
              callback: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._register(
            lambda: Gauge(name, help, label_names, callback=callback),
            name, "gauge", tuple(label_names),
        )
        if callback is not None and g._callback is not callback:
            g._callback = callback  # rebind (see class docstring)
        return g

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS,
                  ) -> Histogram:
        h = self._register(
            lambda: Histogram(name, help, buckets), name, "histogram", ()
        )
        if tuple(h.buckets) != tuple(sorted(float(b) for b in buckets)):
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}"
            )
        return h

    # -- read -----------------------------------------------------------
    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._metrics

    def __len__(self) -> int:
        with self._lock:
            return len(self._metrics)

    def snapshot(self) -> Dict[str, dict]:
        """Every metric's current value(s) as one nested, JSON-ready
        dict — the programmatic twin of :meth:`render_prometheus`."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: Dict[str, dict] = {}
        for name, m in metrics:
            entry = {"type": m.kind, "help": m.help}
            entry.update(m.collect())
            out[name] = entry
        return out

    def export_samples(self) -> List[dict]:
        """Every metric family as structured, losslessly mergeable
        samples: ``[{"name", "type", "help", "samples": [(sample_name,
        {label: value}, value), ...]}, ...]``, families sorted by name.

        This is the fleet-telemetry wire format
        (:mod:`metran_tpu.obs.fleet`): unlike :meth:`snapshot`, whose
        labelled values are keyed by a rendered ``"k=v,k2=v2"`` string
        (ambiguous to parse back when a label VALUE contains ``=`` or
        ``,``), each sample here keeps its label dict intact, so a
        frontend can re-render a merged exposition with a ``process``
        label added without ever parsing anything.  Histograms expand
        to their exposition triplet (cumulative ``_bucket`` rows with
        a string ``le`` label, then ``_sum``/``_count``).
        """
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: List[dict] = []
        for name, m in metrics:
            if isinstance(m, Histogram):
                data = m.collect()
                samples = [
                    (
                        f"{name}_bucket",
                        {"le": ("+Inf" if math.isinf(b["le"])
                                else _format_value(b["le"]))},
                        float(b["count"]),
                    )
                    for b in data["buckets"]
                ]
                samples.append((f"{name}_sum", {}, float(data["sum"])))
                samples.append(
                    (f"{name}_count", {}, float(data["count"]))
                )
            else:
                samples = [
                    (sname, dict(labels), float(v))
                    for sname, labels, v in m._samples()
                ]
            out.append({"name": name, "type": m.kind, "help": m.help,
                        "samples": samples})
        return out

    def render_prometheus(self) -> str:
        """The Prometheus text exposition format (version 0.0.4).

        Deterministic: metrics sorted by name, label sets sorted, one
        ``# HELP``/``# TYPE`` pair per metric family preceding its
        samples.  Serve it from any HTTP handler with content type
        ``text/plain; version=0.0.4``.
        """
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in metrics:
            lines.append(f"# HELP {name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, Histogram):
                data = m.collect()
                for b in data["buckets"]:
                    le = (
                        "+Inf" if math.isinf(b["le"])
                        else _format_value(b["le"])
                    )
                    lines.append(
                        f'{name}_bucket{{le="{le}"}} {b["count"]}'
                    )
                lines.append(f"{name}_sum {_format_value(data['sum'])}")
                lines.append(f"{name}_count {data['count']}")
                continue
            for sname, labels, value in m._samples():
                if labels:
                    inner = ",".join(
                        f'{k}="{_escape_label(v)}"'
                        for k, v in sorted(labels.items())
                    )
                    lines.append(f"{sname}{{{inner}}} {_format_value(value)}")
                else:
                    lines.append(f"{sname} {_format_value(value)}")
        return "\n".join(lines) + ("\n" if lines else "")


# ----------------------------------------------------------------------
# registry-backed serving instruments (back-compat aliases live in
# metran_tpu.utils.profiling, their historical home)
# ----------------------------------------------------------------------
@dataclass
class LatencyRecorder:
    """Per-request latency samples with percentile summaries.

    The serving layer's request-path instrument (``metran_tpu.serve``):
    record wall seconds per request, read p50/p99 — the numbers a
    latency SLO is written against.  Bounded memory: beyond ``maxlen``
    samples the oldest half is dropped (quantiles then describe recent
    traffic, which is what an operator wants from a live service).
    Thread-safe: the serving layer records from several dispatch
    threads at once (background flusher + size-triggered submitters),
    and an unlocked truncation racing an append would drop samples.

    Registry-backed when constructed with ``registry=``/``name=``:
    every sample is additionally observed into a fixed-bucket
    :class:`Histogram` of that name (``DEFAULT_LATENCY_BUCKETS``), so
    the exposition endpoint carries the full distribution while the
    exact recent percentiles stay here.
    """

    unit: str = "s"
    maxlen: int = 100_000
    samples: List[float] = field(default_factory=list)
    total: int = 0
    registry: Optional[MetricsRegistry] = field(
        default=None, repr=False, compare=False
    )
    name: Optional[str] = None
    help: str = ""
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def __post_init__(self):
        self._hist = (
            self.registry.histogram(
                self.name, self.help or "request latency (seconds)"
            )
            if self.registry is not None and self.name else None
        )

    def record(self, seconds: float) -> None:
        with self._lock:
            self.samples.append(float(seconds))
            self.total += 1
            if len(self.samples) > self.maxlen:
                del self.samples[: len(self.samples) // 2]
        if self._hist is not None:
            self._hist.observe(seconds)

    def record_many(self, values) -> None:
        """Record a batch of samples under ONE lock acquisition (and
        one bulk histogram observe) — the dispatch paths book a whole
        batch's latencies at once instead of paying per-request lock
        traffic."""
        vals = [float(v) for v in values]
        if not vals:
            return
        with self._lock:
            self.samples.extend(vals)
            self.total += len(vals)
            if len(self.samples) > self.maxlen:
                del self.samples[: len(self.samples) // 2]
        if self._hist is not None:
            self._hist.observe_many(vals)

    def reset(self) -> None:
        """Forget the recorded samples (``total`` and the backing
        registry histogram keep their lifetime counts) — percentiles
        then describe traffic recorded after the reset.  Used to drop
        warm-up/compile laps from a measurement window."""
        with self._lock:
            self.samples.clear()

    @contextlib.contextmanager
    def measure(self) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self.record(time.perf_counter() - start)

    def percentile(self, q: float) -> float:
        """q in [0, 100]; 0.0 when nothing has been recorded."""
        with self._lock:  # snapshot only — sort outside, off the
            samples = list(self.samples)  # dispatch threads' lock
        if not samples:
            return 0.0
        ordered = sorted(samples)
        idx = min(
            len(ordered) - 1, max(0, round(q / 100.0 * (len(ordered) - 1)))
        )
        return ordered[idx]

    @property
    def p50(self) -> float:
        return self.percentile(50.0)

    @property
    def p99(self) -> float:
        return self.percentile(99.0)

    @property
    def p999(self) -> float:
        return self.percentile(99.9)

    def slo_violation_fraction(self, slo_s: float) -> float:
        """Fraction of the recent sample window over ``slo_s`` seconds
        — the quantity an error budget is written against (a single
        p99 cannot say HOW MUCH of the traffic violated)."""
        with self._lock:
            samples = list(self.samples)
        if not samples:
            return 0.0
        return sum(1 for v in samples if v > slo_s) / len(samples)

    def stats(self, slo_s: Optional[float] = None) -> dict:
        """Percentile snapshot (ms) for health/capacity endpoints:
        window size, p50/p99/p999/mean, and — with an SLO — the
        windowed violation fraction next to the stated bound.  ONE
        locked snapshot and ONE sort serve every quantile, so the
        numbers are mutually consistent and a health scrape pays a
        single pass over the sample window."""
        with self._lock:
            samples = list(self.samples)
            total = self.total
        n = len(samples)
        if not n:
            ordered = []

            def pct(q):
                return 0.0
        else:
            ordered = sorted(samples)

            def pct(q):
                idx = min(
                    n - 1, max(0, round(q / 100.0 * (n - 1)))
                )
                return ordered[idx]

        out = {
            "n": n,
            "total": total,
            "p50_ms": round(pct(50.0) * 1e3, 4),
            "p99_ms": round(pct(99.0) * 1e3, 4),
            "p999_ms": round(pct(99.9) * 1e3, 4),
            "mean_ms": round(
                (sum(ordered) / n if n else 0.0) * 1e3, 4
            ),
        }
        if slo_s is not None:
            out["slo_ms"] = slo_s * 1e3
            out["slo_violation_fraction"] = round(
                sum(1 for v in ordered if v > slo_s) / n if n
                else 0.0, 6,
            )
        return out

    @property
    def mean(self) -> float:
        with self._lock:
            samples = list(self.samples)
        return sum(samples) / len(samples) if samples else 0.0

    def summary(self) -> str:
        return (
            f"{self.total} samples: p50={self.p50 * 1e3:.2f}ms "
            f"p99={self.p99 * 1e3:.2f}ms mean={self.mean * 1e3:.2f}ms"
        )


@dataclass
class EventCounters:
    """Named lifetime event counters (thread-safe).

    The error/degradation half of the serving telemetry: every
    reliability event (a poisoned update rejected, a file quarantined, a
    deadline missed, a breaker rejection, a retry) increments a named
    counter here, so operators and ``bench.py`` track robustness next to
    latency and occupancy.  Counters are exact lifetime totals — rates
    over recent traffic live in
    :class:`metran_tpu.reliability.health.HealthMonitor`.

    Registry-backed when constructed with ``registry=``/``name=`` (or
    bound later via :meth:`bind`): increments mirror into a
    ``kind``-labelled :class:`Counter` family of that name, so the
    exposition endpoint sees ``{name}{kind="retries"} 3``.
    """

    counts: Dict[str, int] = field(default_factory=dict)
    registry: Optional[MetricsRegistry] = field(
        default=None, repr=False, compare=False
    )
    name: Optional[str] = None
    help: str = ""
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def __post_init__(self):
        self._counter = None
        if self.registry is not None and self.name:
            self.bind(self.registry, self.name, self.help)

    def bind(self, registry: MetricsRegistry, name: str,
             help: str = "") -> None:
        """Mirror this instrument into ``registry`` as a
        ``kind``-labelled counter family named ``name``; counts
        accumulated before binding are carried over."""
        counter = registry.counter(
            name, help or "events by kind", label_names=("kind",)
        )
        with self._lock:
            if self._counter is counter:
                return
            self._counter = counter
            backlog = dict(self.counts)
        for k, v in backlog.items():
            counter.inc(v, kind=k)

    def increment(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counts[name] = self.counts.get(name, 0) + int(n)
            counter = self._counter
        if counter is not None:
            counter.inc(int(n), kind=name)

    def get(self, name: str) -> int:
        with self._lock:
            return self.counts.get(name, 0)

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self.counts.values())

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counts)

    def summary(self) -> str:
        snap = self.snapshot()
        if not snap:
            return "no error events"
        inner = ", ".join(f"{k}={v}" for k, v in sorted(snap.items()))
        return f"events: {inner}"


@dataclass
class OccupancyCounter:
    """Batch-occupancy accounting for the micro-batching queue.

    How full device dispatches actually run — the efficiency half of
    the serving telemetry (latency being the other): ``mean_occupancy``
    near 1 means the batcher coalesces nothing and each request pays a
    full dispatch.  Totals are running counters (exact over the whole
    lifetime); ``batches`` keeps only the most recent ``maxlen`` sizes,
    bounded like :class:`LatencyRecorder` for long-lived services, and
    thread-safe for the same reason (concurrent dispatch threads).

    Registry-backed when constructed with ``registry=``/``name=``:
    batch sizes feed a power-of-two :class:`Histogram`
    (``DEFAULT_SIZE_BUCKETS``) whose ``_count``/``_sum`` are the
    dispatch and request totals.
    """

    maxlen: int = 100_000
    batches: List[int] = field(default_factory=list)
    dispatches: int = 0
    requests: int = 0
    registry: Optional[MetricsRegistry] = field(
        default=None, repr=False, compare=False
    )
    name: Optional[str] = None
    help: str = ""
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def __post_init__(self):
        self._hist = (
            self.registry.histogram(
                self.name, self.help or "requests per device dispatch",
                buckets=DEFAULT_SIZE_BUCKETS,
            )
            if self.registry is not None and self.name else None
        )

    def record(self, size: int) -> None:
        with self._lock:
            self.batches.append(int(size))
            self.dispatches += 1
            self.requests += int(size)
            if len(self.batches) > self.maxlen:
                del self.batches[: len(self.batches) // 2]
        if self._hist is not None:
            self._hist.observe(size)

    @property
    def mean_occupancy(self) -> float:
        return self.requests / self.dispatches if self.dispatches else 0.0

    def summary(self) -> str:
        return (
            f"{self.requests} requests over {self.dispatches} dispatches "
            f"(mean occupancy {self.mean_occupancy:.1f})"
        )


__all__ = [
    "Counter",
    "DEFAULT_LATENCY_BUCKETS",
    "DEFAULT_SIZE_BUCKETS",
    "EventCounters",
    "Gauge",
    "Histogram",
    "LatencyRecorder",
    "MetricsRegistry",
    "OccupancyCounter",
]
