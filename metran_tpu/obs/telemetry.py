"""Per-fit optimizer telemetry (deviance curve, gradients, stop reason).

A fit that "did not converge" — or converged suspiciously fast — is
undiagnosable from ``(success, optimal, stderr)`` alone.
:class:`FitTelemetry` is the flight recorder ``run_lbfgs`` fills as it
drives the chunked on-device L-BFGS loop (``models/solver.py``): the
deviance curve and gradient norms at every host-side checkpoint (one
per device chunk, up to 20 iterations each), true objective-evaluation
counts, line-search stall detection, the precise stop reason, and —
when the objective went non-finite — the divergence diagnosis.
``JaxSolve`` attaches it as ``solver.telemetry`` and
``Metran.fit_report()`` surfaces the one-line summary, so "why did
this fit stop" is answered by the report instead of a re-run under a
debugger.

Stop reasons (:attr:`FitTelemetry.stop_reason`):

- ``"gradient"`` — gradient-norm test fired (``tol``);
- ``"floor"`` — scipy-factr-style relative-improvement test fired
  (``ftol``; the normal float32 stop);
- ``"maxiter"`` — iteration budget exhausted, not converged;
- ``"diverged"`` — objective became non-finite (see ``divergence``);
- ``"worse_than_start"`` — a stopping test fired at a value worse than
  the starting point (line-search failure creep; never reported as
  success);
- ``"init_nonfinite"`` — the objective was already non-finite at the
  initial parameters.

Host-side and dependency-free: recording happens between device
chunks, off the jitted path, so telemetry costs nothing inside the
compiled optimizer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class FitTelemetry:
    """One optimization run's recorded trajectory (see module docstring).

    ``checkpoints`` holds one record per host-side convergence check —
    ``{"iters", "value", "grad_norm", "nfev"[, "wall_s"]}`` —
    chunk-granular, so a 200-iteration fit carries ~10 records, not
    200.  ``grad_engine`` names the gradient engine the run
    differentiated with (``"adjoint"`` = the closed-form Kalman-score
    VJP, ``"autodiff"`` = reverse-mode through the filter scan), so the
    per-iteration wall times — forward + backward fused inside each
    device chunk — are attributable to the backward pass that actually
    ran.
    """

    checkpoints: List[Dict] = field(default_factory=list)
    n_iters: int = 0
    nfev: int = 0
    converged: Optional[bool] = None
    stop_reason: Optional[str] = None
    divergence: Optional[str] = None
    linesearch_stalls: int = 0
    value0: Optional[float] = None
    value: Optional[float] = None
    grad_engine: Optional[str] = None

    def record_start(self, value0: float) -> None:
        self.value0 = float(value0)

    def record_grad_engine(self, engine: Optional[str]) -> None:
        """Name the gradient engine this run differentiates with."""
        self.grad_engine = None if engine is None else str(engine)

    def record_checkpoint(self, iters: int, value: float,
                          grad_norm: float, nfev: int,
                          wall_s: Optional[float] = None) -> None:
        """One host-side convergence check (between device chunks).

        A checkpoint whose value failed to improve on its predecessor
        counts as a **line-search stall** — the signature of zoom
        line-search failure fallbacks creeping along a flat or
        degenerate objective.  ``wall_s`` is the chunk's host-measured
        wall time (device forward + backward work included).
        """
        if self.checkpoints and not (
            float(value) < self.checkpoints[-1]["value"]
        ):
            self.linesearch_stalls += 1
        rec = {
            "iters": int(iters),
            "value": float(value),
            "grad_norm": float(grad_norm),
            "nfev": int(nfev),
        }
        if wall_s is not None:
            rec["wall_s"] = round(float(wall_s), 6)
        self.checkpoints.append(rec)
        self.n_iters = int(iters)
        self.nfev = int(nfev)
        self.value = float(value)

    def record_stop(self, reason: str, converged: bool,
                    divergence: Optional[str] = None) -> None:
        self.stop_reason = str(reason)
        self.converged = bool(converged)
        if divergence is not None:
            self.divergence = str(divergence)

    # -- read -----------------------------------------------------------
    def deviance_curve(self) -> List[float]:
        """Objective value at each checkpoint (chunk-granular)."""
        return [c["value"] for c in self.checkpoints]

    def grad_norms(self) -> List[float]:
        """Gradient l2 norm at each checkpoint."""
        return [c["grad_norm"] for c in self.checkpoints]

    def improvement(self) -> Optional[float]:
        """Total deviance decrease start-to-stop (None before a run)."""
        if self.value0 is None or self.value is None:
            return None
        return self.value0 - self.value

    def iteration_wall_s(self) -> Optional[float]:
        """Mean wall seconds per L-BFGS iteration over the timed
        chunks (None when no chunk carried a wall time).

        The FIRST timed chunk is excluded whenever a later one exists:
        it carries the jit trace+compile of the optimizer program
        (typically dwarfing steady-state chunk time), which would
        systematically inflate a per-engine backward-cost comparison.
        Single-chunk fits have nothing else to report, so their
        (compile-inclusive) number is returned as-is — callers reading
        it for engine attribution should prefer multi-chunk runs.
        """
        timed = [c for c in self.checkpoints if "wall_s" in c]
        if not timed or self.n_iters <= 0:
            return None
        if len(timed) >= 2:
            iters = timed[-1]["iters"] - timed[0]["iters"]
            if iters > 0:
                return sum(c["wall_s"] for c in timed[1:]) / iters
        return timed[0]["wall_s"] / max(timed[0]["iters"], 1)

    def snapshot(self) -> Dict:
        """JSON-ready dict (bench/report consumption)."""
        return {
            "n_iters": self.n_iters,
            "nfev": self.nfev,
            "converged": self.converged,
            "stop_reason": self.stop_reason,
            "divergence": self.divergence,
            "linesearch_stalls": self.linesearch_stalls,
            "value0": self.value0,
            "value": self.value,
            "grad_engine": self.grad_engine,
            "iteration_wall_s": self.iteration_wall_s(),
            "checkpoints": [dict(c) for c in self.checkpoints],
        }

    def summary(self) -> str:
        """One line for ``fit_report()``."""
        if self.stop_reason is None:
            return "no run recorded"
        grad = (
            f"{self.checkpoints[-1]['grad_norm']:.3g}"
            if self.checkpoints else "n/a"
        )
        imp = self.improvement()
        parts = [
            f"stop={self.stop_reason}",
            f"iters={self.n_iters}",
            f"nfev={self.nfev}",
            f"|grad|={grad}",
        ]
        if self.grad_engine:
            parts.insert(0, f"grad_engine={self.grad_engine}")
        it_wall = self.iteration_wall_s()
        if it_wall is not None:
            parts.append(f"s/iter={it_wall:.3g}")
        if imp is not None:
            parts.append(f"ddev={imp:.6g}")
        if self.linesearch_stalls:
            parts.append(f"linesearch_stalls={self.linesearch_stalls}")
        if self.divergence:
            parts.append(f"divergence={self.divergence}")
        return " ".join(parts)


__all__ = ["FitTelemetry"]
