"""Request-scoped tracing with correlation IDs and Chrome trace export.

Latency percentiles say a request was slow; they cannot say *where* —
batcher coalescing wait?  cold compile?  engine kernel?  integrity
gate?  The :class:`Tracer` answers that with **spans**: named, timed
intervals that share one **correlation (trace) ID** per request, so a
single ``MetranService.update`` call yields a connected tree::

    serve.update                      (sync call: deadline + retries)
      serve.update.request            (one attempt: submit -> resolve)
        serve.batcher_wait            (enqueue -> dispatch claim)
        serve.dispatch                (whole batched device dispatch)
        serve.engine                  (the jitted kernel execution)
        serve.integrity_gate          (per-slot posterior validation)
        serve.commit                  (registry write-through)

Propagation is hybrid, matching the serving stack's threading model:
on the *caller* thread spans nest via a ``contextvars`` context (so a
retry attempt automatically joins its sync call's trace), while across
the *batcher thread boundary* — where a request is dispatched on a
different thread, possibly much later (deferred same-model chains) —
the :class:`SpanContext` rides the request object explicitly and
stages re-attach to it with :meth:`Tracer.record`.

Finished spans land in a bounded ring buffer; :meth:`Tracer.
export_chrome` renders them as Chrome trace-event JSON (the
``chrome://tracing`` / Perfetto format), which composes with the XLA
device traces from :func:`metran_tpu.utils.profiling.trace`: span
names match the ``jax.profiler.TraceAnnotation`` names the serve
kernels emit (``serve.engine``), so host spans and device timelines
line up by name in one Perfetto view.

Stdlib-only; when no tracer is installed the serving layer's guard is
a single ``is None`` check per call site.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from logging import getLogger
from typing import Any, Dict, Iterator, List, NamedTuple, Optional

logger = getLogger(__name__)

#: the caller-thread trace context (see module docstring); one var for
#: the whole process — contexts are per-thread/per-task by construction.
_current: "contextvars.ContextVar[Optional[SpanContext]]" = (
    contextvars.ContextVar("metran_tpu_trace", default=None)
)


class SpanContext(NamedTuple):
    """The portable identity of a span: pass it across threads to
    parent further spans onto the same trace.

    ``trace_id`` is an opaque correlation token, unique within one
    :class:`Tracer` — a plain int, because the hot path mints one per
    request and string formatting there is measurable overhead (the
    Chrome export carries the process id separately).

    The two optional fields serve the *request-span* hot path
    (:meth:`Tracer.begin`): a submission allocates exactly ONE object
    carrying identity + its own parent and start time, rides the
    request across the batcher thread boundary (stages parent on
    ``trace_id``/``span_id``), and is closed later with
    :meth:`Tracer.finish`.  Code that only re-parents (``record*``)
    never reads them.
    """

    trace_id: int
    span_id: int
    parent_id: Optional[int] = None
    t0: float = 0.0


class Span:
    """One named, timed interval; ``end()`` is idempotent and
    thread-safe (futures' done-callbacks race cancellation paths)."""

    __slots__ = (
        "name", "context", "parent_id", "t0", "t1", "tid", "attrs",
        "_tracer",
    )

    def __init__(self, tracer: "Tracer", name: str, context: SpanContext,
                 parent_id: Optional[int], t0: float,
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.t0 = t0
        self.t1: Optional[float] = None
        self.tid = threading.get_ident()
        self.attrs = attrs

    @property
    def trace_id(self) -> str:
        return self.context.trace_id

    def end(self, **attrs) -> None:
        """Close the span (first call wins; later calls are no-ops)."""
        self._tracer._finish(self, attrs)

    def __repr__(self) -> str:  # debugging aid, not part of the export
        state = "open" if self.t1 is None else f"{self.t1 - self.t0:.6f}s"
        return f"<Span {self.name} {self.context.trace_id} {state}>"


class Tracer:
    """Bounded ring buffer of finished spans + context propagation.

    Parameters
    ----------
    maxlen : finished spans kept (oldest dropped) — bounded memory for
        long-lived services; export what you need, when you need it.
    clock : monotonic-seconds time source.  The default matches the
        serving layer's ``time.monotonic`` request timestamps, so
        pre-timed spans (:meth:`record`, e.g. batcher wait measured
        from ``Request.enqueued_at``) share the tracer's timeline.
    annotate_device : also enter a ``jax.profiler.TraceAnnotation`` of
        the span's name inside :meth:`span` blocks, so host spans show
        up on XLA device traces captured around the same workload.
        Off by default (requires jax; adds a TraceMe per span).
    """

    def __init__(self, maxlen: int = 4096,
                 clock=time.monotonic, annotate_device: bool = False):
        self.clock = clock
        self.annotate_device = bool(annotate_device)
        # The ring is COLUMNAR: eight preallocated lists, one per span
        # field, written by slot assignment.  A record therefore
        # allocates NO GC-tracked container — the naive
        # tuple-in-a-deque ring was measured costing more in garbage
        # collection than in its own bytecode (every appended tuple
        # survives into the older generations and is re-scanned on
        # every collection; the ring alone doubled the process's
        # gen0 rate and put 8% of serve wall time into the collector).
        # Rows are written under a short lock (8 slot stores); reads
        # snapshot under the same lock on the cold path.
        m = max(1, int(maxlen))
        self._maxlen = m
        self._head = 0  # rows ever written; row i lives at i % maxlen
        self._c_name: List[Any] = [None] * m
        self._c_trace: List[Any] = [0] * m
        self._c_span: List[Any] = [0] * m
        self._c_parent: List[Any] = [None] * m
        self._c_ts: List[Any] = [0.0] * m
        self._c_dur: List[Any] = [0.0] * m
        self._c_tid: List[Any] = [0] * m
        self._c_args: List[Any] = [None] * m
        self._lock = threading.Lock()
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._pid = os.getpid()
        self._epoch = float(clock())

    @property
    def dropped(self) -> int:
        """Spans pushed out of the ring since creation/:meth:`clear`."""
        return max(0, self._head - self._maxlen)

    def _append(self, name, trace_id, span_id, parent_id, ts, dur,
                tid, args) -> None:
        m = self._maxlen
        with self._lock:
            i = self._head
            self._head = i + 1
            j = i % m
            self._c_name[j] = name
            self._c_trace[j] = trace_id
            self._c_span[j] = span_id
            self._c_parent[j] = parent_id
            self._c_ts[j] = ts
            self._c_dur[j] = dur
            self._c_tid[j] = tid
            self._c_args[j] = args

    # -- context --------------------------------------------------------
    def current(self) -> Optional[SpanContext]:
        """The caller thread's active span context, if any."""
        return _current.get()

    def new_trace_id(self) -> int:
        return next(self._trace_ids)

    def make_context(self,
                     parent: Optional[SpanContext] = None) -> SpanContext:
        """A fresh span identity WITHOUT an open-span object — for
        spans whose interval is recorded later via :meth:`record_span`
        (children recorded meanwhile already parent on it)."""
        return SpanContext(
            parent.trace_id if parent is not None else self.new_trace_id(),
            next(self._span_ids),
        )

    def begin(self) -> SpanContext:
        """Open a request span as ONE allocation: a :class:`SpanContext`
        carrying its own parent (the caller thread's current context,
        a fresh trace when none) and start time.  The submission
        hot-path primitive: the context rides the request object across
        the batcher thread boundary — stages parent on it immediately —
        and the outcome callback the serving layer registers anyway
        closes it with :meth:`finish` (an open ``Span`` object + its
        own done-callback would be pure overhead)."""
        parent = _current.get()
        if parent is not None:
            return SpanContext(
                parent.trace_id, next(self._span_ids), parent.span_id,
                self.clock(),
            )
        return SpanContext(
            next(self._trace_ids), next(self._span_ids), None,
            self.clock(),
        )

    def finish(self, name: str, ctx: SpanContext, attrs=None) -> None:
        """Close a span opened with :meth:`begin` (interval =
        ``ctx.t0`` .. now).

        ``attrs`` is a dict, or — the zero-allocation hot-path form —
        a bare string, exposed on the read path as ``{"label": <str>}``
        (the serving layer labels successful request spans with their
        model id this way; a dict per success was measurable as pure
        allocator/GC load)."""
        t1 = self.clock()
        self._append(
            name, ctx.trace_id, ctx.span_id, ctx.parent_id, ctx.t0,
            t1 - ctx.t0 if t1 > ctx.t0 else 0.0,
            threading.get_ident(), attrs,
        )

    def finish_many(self, name: str, entries, end: float) -> None:
        """Close many :meth:`begin` contexts at one shared end time;
        ``entries`` are ``(ctx, attrs)`` pairs (attrs as in
        :meth:`finish`).

        The batched-resolution primitive: a dispatch that resolves B
        requests can close all their request spans in one lock-held
        sweep instead of B :meth:`finish` calls from B done-callbacks
        — measured worth several percent of serve throughput on the
        forecast hot path.
        """
        tid = threading.get_ident()
        m = self._maxlen
        with self._lock:
            i = self._head
            for ctx, attrs in entries:
                j = i % m
                i += 1
                self._c_name[j] = name
                self._c_trace[j] = ctx[0]
                self._c_span[j] = ctx[1]  # the span's OWN id (begin)
                self._c_parent[j] = ctx[2]
                self._c_ts[j] = ctx[3]
                self._c_dur[j] = end - ctx[3] if end > ctx[3] else 0.0
                self._c_tid[j] = tid
                self._c_args[j] = attrs
            self._head = i

    def record_span(self, name: str, ctx: SpanContext,
                    parent: Optional[SpanContext], start: float,
                    end: float, attrs: Optional[dict] = None) -> None:
        """Append a pre-timed span under an identity allocated earlier
        with :meth:`make_context` (children recorded meanwhile already
        point at ``ctx.span_id``)."""
        self._append(
            name, ctx.trace_id, ctx.span_id,
            parent.span_id if parent is not None else None,
            start, end - start if end > start else 0.0,
            threading.get_ident(), attrs,
        )

    def record_many(self, name: str, entries, end: float,
                    attrs: Optional[dict] = None) -> None:
        """Append one pre-timed span per ``(parent_ctx, start)`` entry,
        all sharing ``name``/``end``/``attrs`` (the attrs DICT is
        shared by reference — treat it as frozen).

        The batched-dispatch primitive: one device execution serves B
        requests, and attributing its stage to every rider must not
        cost B full :meth:`record` calls on the dispatch thread.
        """
        self._record_batch(name, entries, end, attrs, shared_start=None)

    def record_shared(self, name: str, ctxs, start: float, end: float,
                      attrs: Optional[dict] = None) -> None:
        """Like :meth:`record_many` but for one shared interval
        attributed to every context in ``ctxs`` — the common batched
        case (one engine execution, B riders), where the caller can
        pass a plain list of contexts and skip building per-entry
        pairs."""
        self._record_batch(name, ctxs, end, attrs, shared_start=start)

    def _record_batch(self, name, entries, end, attrs,
                      shared_start) -> None:
        """One lock-held columnar write loop for both batched forms:
        ``shared_start=None`` means ``entries`` are ``(ctx, start)``
        pairs; otherwise they are bare contexts sharing the interval
        ``shared_start``..``end``."""
        tid = threading.get_ident()
        ids = self._span_ids
        m = self._maxlen
        shared_dur = (
            None if shared_start is None
            else (end - shared_start if end > shared_start else 0.0)
        )
        with self._lock:
            i = self._head
            for entry in entries:
                if shared_dur is None:
                    ctx, start = entry
                    dur = end - start if end > start else 0.0
                else:
                    ctx, start, dur = entry, shared_start, shared_dur
                j = i % m
                i += 1
                self._c_name[j] = name
                self._c_trace[j] = ctx[0]
                self._c_span[j] = next(ids)
                self._c_parent[j] = ctx[1]
                self._c_ts[j] = start
                self._c_dur[j] = dur
                self._c_tid[j] = tid
                self._c_args[j] = attrs
            self._head = i

    # -- span lifecycle -------------------------------------------------
    def start(self, name: str, parent: Any = "current",
              **attrs) -> Span:
        """Open a span.

        ``parent`` is a :class:`SpanContext` (explicit cross-thread
        attach), ``"current"`` (default: the caller thread's active
        context, a fresh root when none), or ``None`` (force a new
        root/trace).  The returned span must be closed with
        :meth:`Span.end` — from any thread.
        """
        if parent == "current":
            parent = _current.get()
        if parent is not None and not isinstance(parent, SpanContext):
            parent = getattr(parent, "context", None)
        trace_id = (
            parent.trace_id if parent is not None else self.new_trace_id()
        )
        ctx = SpanContext(trace_id, next(self._span_ids))
        return Span(
            self, name, ctx,
            parent.span_id if parent is not None else None,
            float(self.clock()), attrs,
        )

    def _finish(self, span: Span, attrs: Dict[str, Any]) -> None:
        # the t1 guard tolerates the benign double-end race — a
        # duplicate row in the ring at worst, never a crash
        if span.t1 is not None:
            return  # idempotent: first end() wins
        span.t1 = t1 = self.clock()
        if attrs:
            span.attrs.update(attrs)
        ctx = span.context
        self._append(
            span.name, ctx.trace_id, ctx.span_id, span.parent_id,
            span.t0, t1 - span.t0, span.tid, span.attrs,
        )

    def record(self, name: str, parent: Any, start: float, end: float,
               **attrs) -> None:
        """Append an already-timed span (clock-of-this-tracer seconds).

        The cross-thread primitive: the dispatch path measures a stage
        once and attributes it to each affected request's trace without
        holding per-request open spans — e.g. ``serve.batcher_wait``
        from ``Request.enqueued_at`` to the dispatch claim.
        """
        if parent is not None and not isinstance(parent, SpanContext):
            parent = getattr(parent, "context", None)
        if parent is not None:
            trace_id, parent_id = parent[0], parent[1]
        else:
            trace_id, parent_id = self.new_trace_id(), None
        self._append(
            name, trace_id, next(self._span_ids), parent_id,
            start, end - start if end > start else 0.0,
            threading.get_ident(), attrs,
        )

    @contextlib.contextmanager
    def span(self, name: str, parent: Any = "current",
             **attrs) -> Iterator[Span]:
        """Context-managed span that installs itself as the caller
        thread's current context (children opened inside nest under
        it, including across ``yield``-free helper calls)."""
        sp = self.start(name, parent=parent, **attrs)
        token = _current.set(sp.context)
        device_ctx = contextlib.nullcontext()
        if self.annotate_device:
            try:
                import jax

                device_ctx = jax.profiler.TraceAnnotation(name)
            except Exception:  # jax unavailable: host spans still work
                pass
        try:
            with device_ctx:
                yield sp
        except BaseException as exc:
            sp.attrs.setdefault("error", repr(exc))
            raise
        finally:
            _current.reset(token)
            sp.end()

    # -- read / export --------------------------------------------------
    def spans(self, trace_id: Optional[int] = None,
              name: Optional[str] = None) -> List[dict]:
        """Finished spans as dicts (oldest first), optionally filtered
        — the cold read path; the ring itself stores columns."""
        with self._lock:
            h = self._head
            m = self._maxlen
            n = min(h, m)
            raw = []
            for k in range(h - n, h):
                j = k % m
                raw.append((
                    self._c_name[j], self._c_trace[j], self._c_span[j],
                    self._c_parent[j], self._c_ts[j], self._c_dur[j],
                    self._c_tid[j], self._c_args[j],
                ))
        out = []
        for (nm, tr, sid, pid, ts, dur, tid, args) in raw:
            if trace_id is not None and tr != trace_id:
                continue
            if name is not None and nm != name:
                continue
            if args is None:
                args = {}
            elif isinstance(args, str):
                args = {"label": args}  # finish()'s bare-string form
            else:
                args = dict(args)
            out.append({
                "name": nm, "trace_id": tr, "span_id": sid,
                "parent_id": pid, "ts": ts, "dur": dur, "tid": tid,
                "args": args,
            })
        return out

    def trace_ids(self) -> List[int]:
        with self._lock:
            h, m = self._head, self._maxlen
            seen = {
                self._c_trace[k % m] for k in range(max(0, h - m), h)
            }
        return sorted(seen)

    def clear(self) -> None:
        with self._lock:
            self._head = 0
            m = self._maxlen
            # fresh columns, so cleared rows' strings/dicts are freed
            self._c_name = [None] * m
            self._c_trace = [0] * m
            self._c_span = [0] * m
            self._c_parent = [None] * m
            self._c_ts = [0.0] * m
            self._c_dur = [0.0] * m
            self._c_tid = [0] * m
            self._c_args = [None] * m

    def export_chrome(self) -> dict:
        """Chrome trace-event JSON (``chrome://tracing``, Perfetto).

        Complete events (``"ph": "X"``) with microsecond ``ts`` relative
        to the tracer's epoch; ``args`` carries the correlation
        ``trace_id``/``span_id``/``parent_id`` so a Perfetto query can
        reassemble one request's tree across thread tracks.
        """
        events = []
        for s in self.spans():
            args = dict(s["args"])
            args["trace_id"] = s["trace_id"]
            args["span_id"] = s["span_id"]
            if s["parent_id"] is not None:
                args["parent_id"] = s["parent_id"]
            events.append({
                "name": s["name"],
                "cat": s["name"].split(".", 1)[0],
                "ph": "X",
                "ts": (s["ts"] - self._epoch) * 1e6,
                "dur": s["dur"] * 1e6,
                "pid": self._pid,
                "tid": s["tid"],
                "args": args,
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save_chrome(self, path) -> str:
        """Write :meth:`export_chrome` to ``path``; returns the path."""
        payload = self.export_chrome()
        with open(path, "w") as fh:
            json.dump(payload, fh)
        logger.info(
            "wrote %d trace events to %s", len(payload["traceEvents"]),
            path,
        )
        return str(path)


def current_trace_id() -> Optional[int]:
    """The caller thread's active correlation ID, if any (module-level
    so event emitters need no tracer handle)."""
    ctx = _current.get()
    return ctx.trace_id if ctx is not None else None


def current_context() -> Optional[SpanContext]:
    """The caller thread's active :class:`SpanContext`, if any — what
    the cluster RPC client serializes into the envelope so a child
    process can parent its spans onto the caller's trace."""
    return _current.get()


@contextlib.contextmanager
def attach_context(ctx: Optional[SpanContext]) -> Iterator[None]:
    """Install a foreign :class:`SpanContext` (e.g. deserialized from
    an RPC envelope) as the caller thread's current context for the
    duration of the block.

    Unlike :meth:`Tracer.span` this opens NO span and touches no ring:
    it only re-parents — spans and events emitted inside join the
    originating trace (``current_trace_id()`` returns the propagated
    correlation id).  ``ctx=None`` is a no-op, so call sites need no
    branch on whether a context actually arrived.
    """
    if ctx is None:
        yield
        return
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)


__all__ = [
    "Span",
    "SpanContext",
    "Tracer",
    "attach_context",
    "current_context",
    "current_trace_id",
]
