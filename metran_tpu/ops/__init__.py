"""Pure-JAX numerical kernels: state-space builders, Kalman filtering and
smoothing, factor analysis."""

from .kalman import (
    FilterResult,
    SmootherResult,
    decompose_states,
    deviance,
    deviance_terms,
    filter_append,
    filter_update,
    innovations,
    kalman_filter,
    log_likelihood,
    project,
    rts_smoother,
    sample_states,
)
from .forecast import (
    forecast_observation_moments,
    forecast_state_moments,
)
from .lanes import (
    lanes_deviance_terms,
    lanes_dfm_deviance,
    lanes_statespace,
)
from .lanes_products import (
    lanes_filter_project,
    lanes_forecast,
    lanes_innovations,
    lanes_sample,
    lanes_smooth,
)
from .pkalman import (
    parallel_deviance,
    parallel_filter,
    parallel_smoother,
    sequence_sharded_filter,
)
from .statespace import StateSpace, ar1_decay, dfm_statespace, scale_observation_matrix

__all__ = [
    "FilterResult",
    "innovations",
    "forecast_observation_moments",
    "forecast_state_moments",
    "SmootherResult",
    "StateSpace",
    "ar1_decay",
    "decompose_states",
    "deviance",
    "deviance_terms",
    "dfm_statespace",
    "filter_append",
    "filter_update",
    "kalman_filter",
    "lanes_deviance_terms",
    "lanes_dfm_deviance",
    "lanes_filter_project",
    "lanes_forecast",
    "lanes_innovations",
    "lanes_sample",
    "lanes_smooth",
    "lanes_statespace",
    "log_likelihood",
    "parallel_deviance",
    "parallel_filter",
    "parallel_smoother",
    "project",
    "sample_states",
    "sequence_sharded_filter",
    "rts_smoother",
    "scale_observation_matrix",
]
