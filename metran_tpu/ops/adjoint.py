"""Closed-form adjoint gradients for the sequential-scan filter deviance.

Every fit in the repo gets its gradient from reverse-mode autodiff
through the filter's ``lax.scan``: JAX tapes O(T) per-step residuals on
the forward pass (for the ``sqrt`` engine that tape includes the QR
internals) and transposes every primitive on the way back — the
backward pass costs a multiple of the forward and its memory grows
linearly in T.  But the score of a linear-Gaussian state-space model
has a compact closed form (arXiv:2303.16846 — backpropagation through
the Kalman filter via closed-form expressions; the orthogonal-
transformation structure of arXiv:2502.11686 is what lets the square-
root engine's gradient reuse covariance-form factors): per rank-1
sequential update

    v = y_i - z_i.m ; d = P z_i ; f = z_i.d + r_i ; k = d/f
    m' = m + k v ;  P' = P - d d'/f
    sigma_t += v^2/f ; detf_t += log f

the incoming adjoints ``u = mbar'``, ``S = Pbar'``, ``sb = sigmabar``,
``db = detfbar`` propagate as

    vbar = 2 sb v/f + (u.d)/f
    fbar = -sb v^2/f^2 + db/f + (d'Sd)/f^2 - (u.d) v/f^2
    dbar = -(S + S')d/f + u v/f + fbar z_i
    Pbar = S + outer(dbar, z_i) ;  mbar = u - vbar z_i

and through the diagonal-transition predict ``m_p = phi m``,
``P_p = (phi phi') P + diag(q)``:

    phibar += u m + ((S.P) phi) + ((S.P)' phi)   [elementwise products]
    qbar   += diag(S)
    mbar = u phi ;  Pbar = S (phi phi')

— cotangents only for ``(phi, q)``, the quantities the MLE parameters
(the AR decay alphas, plus ``dt``) actually reach.
``z``/``r``/``y``/``mask`` and the anchor posterior of the anchored
variant are treated as fixed data: their cotangents are **exactly
zero** (never silently partial); use ``grad="autodiff"`` when
gradients w.r.t. loadings or observations are needed.  The rank-1 form
above is the derivation the lane-layout kernel has carried since the
TPU fit hot path landed (``ops/lanes.py``); here the same derivative
is *evaluated* in the equivalent JOINT (vector) form (see
:func:`_terms_bwd`) so every backward step is a handful of small
matmuls plus one Cholesky of the masked innovation covariance —
matrix-shaped work instead of a per-slot scan — and the
``jax.custom_vjp`` covers the ``sequential``/``joint``/``sqrt`` scan
engines everywhere a fit differentiates them: the single-model
solvers, the batch-layout fleet fit, and the refit worker's anchored
tail objective.  No primitive is ever autodiff-transposed — in
particular not the QR whose VJP dominates the sqrt engine's autodiff
backward.

Structure (``_terms_core``, a ``jax.custom_vjp``):

- **primal/forward**: the chosen engine's own scan, bit-identical to
  the un-differentiated deviance — values never change with the
  gradient engine — additionally stacking only the per-segment boundary
  carries (O(T/seg) means + factors, ~30 bytes/step at the flagship
  shape vs the multi-KB/step autodiff tape);
- **backward**: one reverse sweep over segments.  Each segment is
  replayed forward from its boundary carry through the covariance-form
  joint recursion (the cheapest exact evaluation of the shared
  posterior — no QR), storing that segment's per-step innovation and
  gain blocks (``K``/``e``/``L^-1 Z`` — O(S.N) per step, bounded by
  the segment length), then the closed-form expressions run backward
  over it.  Peak backward memory is O(T/seg + seg), near-flat in T
  (``bench.py --phase grad`` measures it at T = 1e2/1e4/1e5), where
  the autodiff tape is O(T); measured on the standard T=5000 CPU
  workload the backward pass runs >=2x faster than the
  autodiff-through-scan backward for both the sqrt and joint engines.

The covariance-form replay is shared by all three engines: the
sequential, joint and square-root updates compute the same posterior in
exact arithmetic, so their derivatives coincide; at float64 the
closed-form gradient matches autodiff through each engine to ~1e-13
relative (tests/test_adjoint.py pins 1e-10 across all four alpha
regimes).  At float32 the replay carries covariance-form roundoff, so
the ``sqrt`` engine's *gradient* loses its extra near-unit-root
robustness under the adjoint (its primal value keeps it) — which is
why :func:`resolve_grad_engine`'s ``auto`` mode keeps autodiff for the
f32 sqrt deviance; the f32 gradient bars of tests/test_precision.py
hold either way.

A replay step whose masked innovation covariance is indefinite in the
working precision (the degenerate case the joint engine's ``ok`` guard
maps to a ``+inf`` deviance) passes its adjoint through unchanged — it
contributes nothing instead of poisoning the sweep with a garbage
factor; the corresponding primal is ``+inf``, a rejected step whose
gradient the optimizer never uses.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .statespace import StateSpace

#: engines the closed-form adjoint covers (the sequential-scan
#: engines; the associative-scan ``parallel`` engines materialize
#: O(T n^2) moments and keep autodiff).
ADJOINT_ENGINES = ("sequential", "joint", "sqrt")

#: default backward segment length: boundary-carry memory is
#: O(T/seg . S^2) and replay residuals O(seg . S.N), balanced around
#: the flagship shapes; any value gives identical gradients.
DEFAULT_SEG = 128


def _q_diag(q: jnp.ndarray) -> jnp.ndarray:
    """(n,) diagonal of the (diagonal) process covariance.

    Same contract as ``kalman._q_sqrt_diag``: a non-diagonal ``Q``
    reaching a traced path must never be silently truncated — the
    returned diagonal is NaN-poisoned so the deviance books a loud
    ``+inf`` instead of a plausible-but-wrong value.  The DFM builder
    only emits diagonal ``Q``, for which XLA folds the check away.
    """
    diag = jnp.diagonal(q)
    is_diag = jnp.all(q == jnp.diag(diag))
    return jnp.where(is_diag, diag, jnp.asarray(jnp.nan, q.dtype))


def _segment(y, maskf, seg):
    """Zero-pad ``(y, mask-as-float)`` to a multiple of ``seg`` steps and
    reshape to (n_seg, seg, ...); padded steps are all-masked no-ops
    (the masked filter's semantics for missing rows)."""
    t_steps = y.shape[0]
    pad = (-t_steps) % seg
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad,) + y.shape[1:], y.dtype)])
        maskf = jnp.concatenate(
            [maskf, jnp.zeros((pad,) + maskf.shape[1:], maskf.dtype)]
        )
    return (
        y.reshape(-1, seg, *y.shape[1:]),
        maskf.reshape(-1, seg, *maskf.shape[1:]),
    )


def _engine_step(engine, phi, qdiag, z, r):
    """One filter timestep of ``engine`` as a ``(carry, (y, maskf)) ->
    (carry, (sigma, detf))`` scan body — the engine's OWN forward
    (``kalman._make_core_step`` / ``_make_sqrt_core_step``), so primal
    values are bit-identical to the un-differentiated deviance."""
    from .kalman import _make_core_step, _make_sqrt_core_step

    dtype = phi.dtype
    ss = StateSpace(phi=phi, q=jnp.diag(qdiag), z=z, r=r)
    core = (
        _make_sqrt_core_step(ss, dtype)
        if engine == "sqrt"
        else _make_core_step(ss, engine, dtype)
    )

    def step(carry, xs):
        y_t, mf_t = xs
        _, _, mean_f, fac_f, sigma, detf = core(
            carry[0], carry[1], y_t, mf_t > 0
        )
        return (mean_f, fac_f), (sigma, detf)

    return step


def _run_segments(engine, phi, qdiag, z, r, mean0, fac0, y_seg, m_seg,
                  keep_bounds):
    """Forward filter over pre-segmented inputs; one definition for the
    custom-vjp primal and fwd rules.  Returns flattened (sigma, detf)
    plus the stacked segment-boundary carries when ``keep_bounds``."""
    step = _engine_step(engine, phi, qdiag, z, r)

    def body(c, xs):
        c2, out = lax.scan(step, c, xs)
        return (c2, out + (c,)) if keep_bounds else (c2, out)

    _, outs = lax.scan(body, (mean0, fac0), (y_seg, m_seg))
    sig, det = outs[0], outs[1]
    flat = (sig.reshape(-1), det.reshape(-1))
    return flat + ((outs[2],) if keep_bounds else (None,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _terms_core(engine, seg, phi, qdiag, z, r, mean0, fac0, y_seg,
                m_seg):
    """Per-step (sigma, detf) with a closed-form (phi, q) adjoint.

    ``fac0`` is the initial carry factor in the ENGINE's own form (a
    Cholesky factor for ``sqrt``, a covariance otherwise); ``m_seg`` is
    the mask as float (bool inputs have no cotangent type).  Cotangents
    are produced for ``(phi, qdiag)`` only — every other input comes
    back exactly zero (see the module docstring).
    """
    sig, det, _ = _run_segments(
        engine, phi, qdiag, z, r, mean0, fac0, y_seg, m_seg, False
    )
    return sig, det


def _terms_fwd(engine, seg, phi, qdiag, z, r, mean0, fac0, y_seg, m_seg):
    sig, det, bounds = _run_segments(
        engine, phi, qdiag, z, r, mean0, fac0, y_seg, m_seg, True
    )
    return (sig, det), (phi, qdiag, z, r, mean0, fac0, y_seg, m_seg,
                        bounds)


def _terms_bwd(engine, seg, residuals, cotangents):
    """The closed-form reverse pass, evaluated in JOINT (vector) form.

    The sequential, joint and sqrt updates compute the same posterior,
    so their derivative is one function; evaluating it in the joint
    form keeps every per-step operation matrix-shaped (one small
    Cholesky + matmuls — MXU/BLAS-friendly) instead of a per-slot
    rank-1 scan whose loop overhead dominates the backward at
    reference widths.  With incoming adjoints ``(u, S)`` of the
    filtered ``(m_f, P_f)`` and ``A = I - K Z``, ``e = F^{-1} v``,
    ``w = Z' e``:

        m_p-bar = A'u - 2 sb w
        P_p-bar = A'S A + db Z'F^{-1}Z - sb w w' + (A'u) w'

    then the diagonal-transition predict adjoint of the module
    docstring.  Validated bitwise-level against autodiff through each
    engine in tests/test_adjoint.py (f64 rel ~1e-13).
    """
    phi, qdiag, z, r, mean0, fac0, y_seg, m_seg, bounds = residuals
    n = phi.shape[0]
    m_obs = z.shape[0]
    dtype = phi.dtype
    eye_m = jnp.eye(m_obs, dtype=dtype)
    n_seg = y_seg.shape[0]
    sb_all, db_all = cotangents
    sb_seg = sb_all.reshape(n_seg, seg)
    db_seg = db_all.reshape(n_seg, seg)

    def replay_step(c, xs):
        """Covariance-form joint predict+update (one Cholesky of the
        masked innovation covariance — same structure as the joint
        engine's forward, no QR), storing the per-step carry plus the
        gain/innovation blocks the closed form needs."""
        m, p = c
        y_t, mf_t = xs
        mask_t = mf_t > 0
        m_p = phi * m
        p_p = phi[:, None] * p * phi[None, :] + jnp.diag(qdiag)
        z_m = z * mf_t[:, None]
        v = jnp.where(mask_t, y_t - z @ m_p, 0.0)
        pz = p_p @ z_m.T  # (S, N)
        f = z_m @ pz + jnp.diag(
            jnp.where(mask_t, r, 0.0) + (1.0 - mf_t)
        )
        chol = jnp.linalg.cholesky(f)
        # a degraded step (indefinite-in-precision F) is the one the
        # primal maps to +inf: its filtered moments pass through, so
        # its adjoint passes through too (zero contribution)
        ok = jnp.all(jnp.isfinite(chol))
        chol_safe = jnp.where(ok, chol, eye_m)
        kt = jax.scipy.linalg.cho_solve((chol_safe, True), pz.T)
        e = jax.scipy.linalg.cho_solve((chol_safe, True), v)
        # Z'F^-1 Z = (L^-1 Z)'(L^-1 Z): one triangular solve now, one
        # rank-N product in the sweep — never a full F^-1
        li_z = jax.scipy.linalg.solve_triangular(
            chol_safe, z_m, lower=True
        )
        m_f = jnp.where(ok, m_p + kt.T @ v, m_p)
        p_f = jnp.where(ok, p_p - kt.T @ pz.T, p_p)
        return (m_f, p_f), (m, p, kt, e, li_z, ok)

    def step_bwd(c, xs):
        """One reverse timestep: joint update adjoint, then the
        diagonal-transition predict adjoint."""
        u, s, phib, qb = c
        (m0, p0, kt, e, li_z, ok), mf_t, sb_t, db_t = xs
        z_m = z * mf_t[:, None]
        w = z_m.T @ e  # (S,)
        au = u - z_m.T @ (kt @ u)  # A'u
        sa = s - (s @ kt.T) @ z_m  # S A
        asa = sa - z_m.T @ (kt @ sa)  # A'S A
        u_p = jnp.where(ok, au - 2.0 * sb_t * w, u)
        s_p = jnp.where(
            ok,
            asa
            + db_t * (li_z.T @ li_z)
            - sb_t * jnp.outer(w, w)
            + jnp.outer(au, w),
            s,
        )
        # predict backward: (u_p, s_p) is the adjoint of (m_p, P_p);
        # m0/p0 are the pre-predict carry
        sc = s_p * p0
        phib = phib + u_p * m0 + sc @ phi + sc.T @ phi
        qb = qb + jnp.diagonal(s_p)
        return (
            u_p * phi, s_p * phi[:, None] * phi[None, :], phib, qb
        ), None

    def seg_bwd(carry, xs):
        (bm, bf), y_s, mf_s, sb_s, db_s = xs
        # replay this segment forward from its boundary carry (sqrt
        # boundaries reconstitute S S' once per segment, never per step)
        p_b = bf @ bf.T if engine == "sqrt" else bf
        _, stored = lax.scan(replay_step, (bm, p_b), (y_s, mf_s))
        carry, _ = lax.scan(
            step_bwd, carry, (stored, mf_s, sb_s, db_s), reverse=True
        )
        return carry, None

    c0 = (jnp.zeros(n, dtype), jnp.zeros((n, n), dtype),
          jnp.zeros_like(phi), jnp.zeros_like(qdiag))
    (_, _, phibar, qbar), _ = lax.scan(
        seg_bwd, c0, (bounds, y_seg, m_seg, sb_seg, db_seg),
        reverse=True,
    )
    return (phibar, qbar, jnp.zeros_like(z), jnp.zeros_like(r),
            jnp.zeros_like(mean0), jnp.zeros_like(fac0),
            jnp.zeros_like(y_seg), jnp.zeros_like(m_seg))


_terms_core.defvjp(_terms_fwd, _terms_bwd)


def adjoint_deviance_terms(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    engine: str = "sequential",
    seg: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-timestep (sigma, detf) with the closed-form (phi, q) VJP.

    Values are bit-identical to the ``engine``'s own likelihood scan
    (``kalman.deviance``'s non-remat path); only differentiation
    changes.  ``seg`` is the backward segment length (default
    :data:`DEFAULT_SEG`; a fit path's ``remat_seg`` maps onto it).
    Requires the DFM's diagonal ``Q`` — a traced non-diagonal ``Q`` is
    NaN-poisoned into a loud ``+inf`` deviance, like the square-root
    engine (:func:`_q_diag`).

    Gradient contract: exact w.r.t. ``phi``/``q`` — and hence the AR
    decay parameters and ``dt`` through the state-space builder — while
    ``z``/``r``/``y``/``mask`` get exactly-zero cotangents (fixed data
    in the MLE).  Use autodiff for loading/observation gradients.
    """
    if engine not in ADJOINT_ENGINES:
        raise ValueError(
            f"the closed-form adjoint covers engines {ADJOINT_ENGINES}; "
            f"got {engine!r} (the associative-scan engines keep "
            "autodiff)"
        )
    from .kalman import _check_diagonal_q, _init_state

    _check_diagonal_q(ss.q)
    dtype = ss.q.dtype
    t_steps = y.shape[0]
    seg = int(seg) if seg else DEFAULT_SEG
    seg = max(1, min(seg, t_steps))
    y = jnp.asarray(y, dtype)
    maskf = jnp.asarray(mask, bool).astype(dtype)
    y_seg, m_seg = _segment(y, maskf, seg)
    mean0, fac0 = _init_state(ss, dtype)  # identity: factor == cov
    sig, det = _terms_core(
        engine, seg, ss.phi, _q_diag(ss.q), ss.z, ss.r, mean0, fac0,
        y_seg, m_seg,
    )
    return sig[:t_steps], det[:t_steps]


def anchored_adjoint_deviance(
    ss: StateSpace,
    mean0: jnp.ndarray,
    chol0: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
) -> jnp.ndarray:
    """Anchored tail deviance with the closed-form (phi, q) VJP.

    The adjoint twin of the refit objective
    (:func:`metran_tpu.parallel.fleet.anchored_fleet_deviance`'s lane):
    the square-root filter seeded from the anchor posterior
    ``N(mean0, chol0 chol0')``, summed ``sigma + detf`` over the tail —
    bit-identical values to ``sqrt_filter_append``'s scan, so the
    champion/challenger contract (objective ≡ scorer) is preserved
    (tests/test_adjoint.py pins it).  The backward pass replays the
    tail from the anchor in covariance form (one segment — tails are
    short) and runs the closed-form sweep; the anchor itself is fixed
    data (exactly-zero cotangents), matching the refit semantics where
    only the AR decay parameters are optimized.
    """
    from .kalman import _check_diagonal_q

    _check_diagonal_q(ss.q)
    dtype = ss.q.dtype
    y = jnp.atleast_2d(jnp.asarray(y, dtype))
    maskf = jnp.atleast_2d(jnp.asarray(mask, bool)).astype(dtype)
    seg = y.shape[0]
    y_seg, m_seg = _segment(y, maskf, seg)
    sig, det = _terms_core(
        "sqrt", seg, ss.phi, _q_diag(ss.q), ss.z, ss.r,
        jnp.asarray(mean0, dtype), jnp.asarray(chol0, dtype),
        y_seg, m_seg,
    )
    return jnp.sum(sig) + jnp.sum(det)


def resolve_grad_engine(grad: Optional[str], engine: str,
                        dtype=None) -> str:
    """Resolve a gradient-engine request to ``"adjoint"``/``"autodiff"``.

    ``grad`` is an explicit mode or ``None`` for the configured default
    (:func:`metran_tpu.config.grad_engine`, env
    ``METRAN_TPU_GRAD_ENGINE``; unknown values raise instead of
    silently falling back).  ``"auto"`` picks the closed-form adjoint
    for the sequential-scan engines and autodiff for everything else,
    with ONE dtype carve-out when ``dtype`` is provided: a **float32
    square-root** deviance keeps autodiff.  The sqrt engine's uncapped
    f32 gradient bars exist precisely because its QR backward avoids
    covariance-form roundoff near ``phi -> 1`` (tests/test_precision);
    the adjoint's covariance-form sweep would reintroduce that noise
    (measured ~1e-4 rel in the near-unit-root regime vs the sqrt
    autodiff's ~4e-7), so ``auto`` preserves the engine's robustness
    contract and leaves the trade to an explicit ``grad="adjoint"``.
    An explicit ``"adjoint"`` with an uncovered engine raises.
    """
    from ..config import grad_engine as _grad_engine

    mode = _grad_engine(grad)
    if mode == "auto":
        if engine not in ADJOINT_ENGINES:
            return "autodiff"
        if (engine == "sqrt" and dtype is not None
                and jnp.dtype(dtype).itemsize < 8):
            return "autodiff"
        return "adjoint"
    if mode == "adjoint" and engine not in ADJOINT_ENGINES:
        raise ValueError(
            f"grad='adjoint' requires an engine in {ADJOINT_ENGINES}; "
            f"got {engine!r} — use grad='auto' (falls back to autodiff "
            "for the associative-scan engines) or grad='autodiff'"
        )
    return mode


__all__ = [
    "ADJOINT_ENGINES",
    "DEFAULT_SEG",
    "adjoint_deviance_terms",
    "anchored_adjoint_deviance",
    "resolve_grad_engine",
]
