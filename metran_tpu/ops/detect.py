"""Streaming anomaly & changepoint detection over normalized innovations.

The gated serving kernels (:func:`metran_tpu.ops.gated_filter_append`
and friends) already emit each observed slot's signed normalized
innovation ``z = v / sqrt(f)`` — standard normal, serially independent
under a well-specified model.  This module turns that stream into the
three online detection statistics the monitoring product serves
(docs/concepts.md "Online monitoring"), as O(1)-state recursions cheap
enough to fuse into the update dispatch itself:

- **anomaly**: a single observation with ``z^2 > nsigma^2`` — the
  chi-square(1) outlier test, same null as the observation gate but
  bookable independently of any gate policy (including gate off);
- **changepoint (two-sided CUSUM)**: per-slot Page recursions
  ``C+ <- max(0, C+ + z - k)`` and ``C- <- max(0, C- - z - k)``
  alarming at ``C > h`` — the classical sequential test for a
  sustained mean shift of the innovations, which is exactly what a
  level/datum shift, a persistent drift, or stale dynamics leave
  behind after the filter stops tracking.  The tripped accumulator
  resets on alarm (one alarm per detected episode, re-armed);
- **autocorrelation drift (windowed Ljung-Box-style)**: an
  exponentially-windowed lag-1 portmanteau statistic
  ``Q = n_eff * rho_1^2`` with ``rho_1 = S_zz / S_z2`` maintained by
  forgetting-factor recursions (``lambda = 1 - 1/window``).  Under
  whiteness ``Q ~ chi-square(1)``; serial structure — the signature of
  *misspecified dynamics* rather than bad readings, the thing the
  offline Ljung-Box diagnostic (:mod:`metran_tpu.diagnostics`) tests
  after the fact — pushes it up.  Alarms need the window at least
  half full (``n_eff >= window/2``), so a cold recursion cannot alarm
  on two lucky draws.

Everything here is pure JAX, jit/vmap-friendly, and branch-free per
slot: the serving engine (:mod:`metran_tpu.serve.engine`) appends one
:func:`detect_append` pass to its fused update kernels so an arena
bulk tick pays **zero extra kernel launches** for detection, and the
(``DETECT_STATE_ROWS``, N) carried state becomes one more
:class:`~metran_tpu.serve.state.StateArena` leaf.

State layout (:data:`DETECT_STATE_ROWS` = 6 rows, one column per
observation slot): ``[C+, C-, z_prev, S_zz, S_z2, n_eff]``.  A fresh
model starts at :func:`detect_init` (all zeros); unobserved slots and
disarmed models carry every row through unchanged, so missing data
never decays or corrupts the statistics.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "DETECT_STATE_ROWS",
    "detect_append",
    "detect_init",
    "detect_stats",
]

#: rows of the carried per-slot detector state:
#: ``[cusum_pos, cusum_neg, z_prev, s_zz, s_z2, n_eff]``.
DETECT_STATE_ROWS = 6


def detect_init(n_obs: int, dtype=None) -> jnp.ndarray:
    """A fresh (:data:`DETECT_STATE_ROWS`, ``n_obs``) detector state
    (all zeros — no evidence, no window).  ``dtype`` defaults to the
    active precision policy (:func:`metran_tpu.config.default_dtype`)."""
    if dtype is None:
        from ..config import default_dtype

        dtype = default_dtype()
    return jnp.zeros((DETECT_STATE_ROWS, int(n_obs)), dtype)


def detect_stats(state: jnp.ndarray) -> jnp.ndarray:
    """The display/alarm statistics of a detector state.

    Returns a (3, N) array ``[cusum_pos, cusum_neg, lb_q]`` (batched
    over any leading axes): the two CUSUM accumulators verbatim plus
    the current Ljung-Box-style drift statistic
    ``Q = n_eff * (S_zz / S_z2)^2`` (0 while the window is empty) —
    what the serving layer's host mirrors and ``service.anomalies()``
    report per slot.
    """
    state = jnp.asarray(state)
    szz = state[..., 3, :]
    sz2 = state[..., 4, :]
    nef = state[..., 5, :]
    tiny = jnp.asarray(jnp.finfo(state.dtype).tiny, state.dtype)
    rho = szz / jnp.maximum(sz2, tiny)
    return jnp.stack(
        [state[..., 0, :], state[..., 1, :], nef * rho * rho], axis=-2
    )


def _lb_q(szz, sz2, nef, tiny):
    rho = szz / jnp.maximum(sz2, tiny)
    return nef * rho * rho


def _detect_scan(state, zs, mask, armed, *, cusum_k, cusum_h,
                 lb_window, lb_thresh, nsigma):
    """The raw recursion (traceable; see :func:`detect_append`)."""
    dtype = state.dtype
    zs = jnp.atleast_2d(jnp.asarray(zs, dtype))
    mask = jnp.atleast_2d(jnp.asarray(mask, bool))
    k = jnp.asarray(cusum_k, dtype)
    h = jnp.asarray(cusum_h, dtype)
    lam = jnp.asarray(1.0 - 1.0 / float(lb_window), dtype)
    warm = jnp.asarray(0.5 * float(lb_window), dtype)
    q_bar = jnp.asarray(lb_thresh, dtype)
    a_bar = jnp.asarray(float(nsigma) ** 2, dtype)
    tiny = jnp.asarray(jnp.finfo(dtype).tiny, dtype)
    zero = jnp.zeros((), dtype)

    def step(carry, xs):
        cpos, cneg, prev, szz, sz2, nef = carry
        z_raw, m_t = xs
        # disarmed models and unobserved slots carry state unchanged;
        # NaN z-scores (the gated kernels' unobserved marker) are
        # excluded the same way, so a padded or missing slot can never
        # poison an accumulator
        obs = m_t & armed & jnp.isfinite(z_raw)
        z = jnp.where(obs, z_raw, zero)
        anom = obs & (z * z > a_bar)
        # two-sided CUSUM, reset-on-alarm (one alarm per episode)
        cpos_n = jnp.where(obs, jnp.maximum(cpos + z - k, 0.0), cpos)
        cneg_n = jnp.where(obs, jnp.maximum(cneg - z - k, 0.0), cneg)
        cp_hit = obs & ((cpos_n > h) | (cneg_n > h))
        cpos_n = jnp.where(cp_hit, 0.0, cpos_n)
        cneg_n = jnp.where(cp_hit, 0.0, cneg_n)
        # exponentially-windowed lag-1 autocorrelation (LB-style);
        # alarms are RISING EDGES of the over-threshold condition so a
        # persistent excursion books one episode, not one per step
        was = (nef >= warm) & (_lb_q(szz, sz2, nef, tiny) > q_bar)
        szz_n = jnp.where(obs, lam * szz + z * prev, szz)
        sz2_n = jnp.where(obs, lam * sz2 + z * z, sz2)
        nef_n = jnp.where(obs, lam * nef + 1.0, nef)
        prev_n = jnp.where(obs, z, prev)
        now = (nef_n >= warm) & (
            _lb_q(szz_n, sz2_n, nef_n, tiny) > q_bar
        )
        lb_hit = obs & now & ~was
        counts_t = jnp.stack([
            anom.astype(jnp.int32),
            cp_hit.astype(jnp.int32),
            lb_hit.astype(jnp.int32),
        ])
        return (cpos_n, cneg_n, prev_n, szz_n, sz2_n, nef_n), counts_t

    carry0 = tuple(state[i] for i in range(DETECT_STATE_ROWS))
    carry, counts = lax.scan(step, carry0, (zs, mask))
    return jnp.stack(carry), counts.sum(axis=0)


@functools.partial(
    jax.jit,
    static_argnames=(
        "cusum_k", "cusum_h", "lb_window", "lb_thresh", "nsigma",
    ),
)
def detect_append(
    state: jnp.ndarray,
    zs: jnp.ndarray,
    mask: jnp.ndarray,
    armed=True,
    *,
    cusum_k: float = 0.5,
    cusum_h: float = 12.0,
    lb_window: int = 64,
    lb_thresh: float = 25.0,
    nsigma: float = 5.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Advance one model's detector state over ``k`` appended steps.

    Parameters
    ----------
    state : (:data:`DETECT_STATE_ROWS`, N) carried accumulators (see
        module docstring; start from :func:`detect_init`).
    zs : (k, N) signed normalized innovations — the gated serving
        kernels' z-score output (NaN where unobserved).
    mask : (k, N) observed flags (real, non-missing slots).
    armed : scalar bool (traced — per-model under ``vmap``): a cold
        model's innovations are over-dispersed until the filter
        forgets its ``N(0, I)`` init, so the serving layer disarms
        models below ``DetectSpec.min_seen`` exactly like the
        observation gate; disarmed steps carry the state unchanged.
    cusum_k, cusum_h : CUSUM reference value and alarm threshold (in
        innovation sigmas).  ``k`` is the half-shift the test is tuned
        for; ``h`` trades detection delay (~``h / (shift - k)`` steps)
        against the false-alarm rate (Siegmund: ARL grows
        exponentially in ``h``).
    lb_window : effective window of the autocorrelation recursion
        (forgetting factor ``1 - 1/window``); must exceed the lag (1).
    lb_thresh : alarm threshold on ``Q`` (chi-square(1) under
        whiteness; the default 25 is a 5-sigma bar).
    nsigma : per-observation anomaly threshold (``z^2 > nsigma^2``).

    Returns
    -------
    state' : the advanced (6, N) accumulators.
    counts : (3, N) int32 — per-slot ``[anomalies, cusum_alarms,
        lb_alarms]`` booked across the ``k`` steps (alarm = episode:
        CUSUM resets on alarm, LB counts threshold rising edges).

    The thresholds are static (compile-time) like the gate's
    ``policy``/``nsigma`` — they join the serving registry's compile
    keys; ``armed`` and the state are traced.
    """
    return _detect_scan(
        jnp.asarray(state), zs, mask, jnp.asarray(armed, bool),
        cusum_k=float(cusum_k), cusum_h=float(cusum_h),
        lb_window=int(lb_window), lb_thresh=float(lb_thresh),
        nsigma=float(nsigma),
    )
