"""Factor analysis: eigendecomposition, Velicer MAP test, minres, varimax.

Host-side (numpy/scipy) implementation of the classical factor-analysis
pipeline the reference runs once per model fit (``metran/factoranalysis.py``):
correlation -> eigendecomposition -> MAP test (Kaiser fallback) -> minres
loadings -> varimax rotation -> sign convention.  These matrices are tiny
(n_series x n_series); the payoff on TPU comes from batching fits, not from
accelerating a 5x5 eigendecomposition, so this stays numpy with scipy's
L-BFGS-B for minres — mirroring the reference's optimizer so fitted loadings
agree to near machine precision.

Two behavioral quirks of the reference are preserved under
``mode="reference"`` (the default, needed for golden-value parity) and
corrected under ``mode="textbook"``:

1. ``_minresfun`` (``factoranalysis.py:314-347``) builds the candidate
   loading matrix from ``np.linalg.eigh`` output sliced ``[:nf]`` — eigh
   returns eigenvalues in *ascending* order, so the objective uses the
   smallest eigenpairs.  (The analytic jacobian uses ``np.linalg.eig``
   whose LAPACK ordering is effectively descending, which is what steers
   L-BFGS-B to the classical solution anyway.)
2. ``_maptest`` (``factoranalysis.py:219-312``) writes its criterion table
   with ``np.put`` flat indices, so entry ``[m+1, 1]`` actually lands at
   flat positions ``m+1`` and ``1``.  In practice the negative-partial-
   variance early exit (returning 1 factor) fires for strongly correlated
   data, which is why the reference still behaves sensibly.
"""

from __future__ import annotations

from dataclasses import dataclass
from logging import getLogger
from typing import Optional, Tuple

import numpy as np
import scipy.optimize as scopt

logger = getLogger(__name__)


def correlation_matrix(oseries) -> np.ndarray:
    """Pairwise-complete correlation matrix of a DataFrame (or 2-D array)."""
    import pandas as pd

    if not isinstance(oseries, pd.DataFrame):
        oseries = pd.DataFrame(np.asarray(oseries))
    return np.asarray(oseries.corr())


def sorted_scaled_eig(corr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Eigenvalues (descending, negatives clipped to 0) and eigenvectors
    scaled by sqrt(eigenvalue) — i.e. principal-component loadings.

    Raises if the decomposition is complex (reference guard,
    ``factoranalysis.py:446-453``); on a symmetric correlation matrix this
    cannot trigger, but the guard is kept for non-symmetric input.
    """
    eigval, eigvec = np.linalg.eig(corr)
    if np.iscomplexobj(eigval):
        msg = (
            "Serial correlation matrix has complex eigenvalues and "
            "eigenvectors. Factors cannot be estimated for these series."
        )
        logger.error(msg)
        raise Exception(msg)
    order = np.argsort(-eigval)
    eigval = eigval[order]
    eigval[eigval < 0] = 0.0
    eigvec = eigvec[:, order] @ np.sqrt(np.diag(eigval))
    return eigval, np.atleast_2d(eigvec)


def _map_criteria(cov: np.ndarray, eigvec: np.ndarray):
    """Average squared (and 4th-power) partial correlations after removing
    the first m+1 principal components, for m = 0..nvars-2.

    Returns (vals, vals4, early_exit) where early_exit=True means a partial
    covariance had a negative diagonal (reference returns 1 factor then).
    """
    nvars = cov.shape[0]
    denom = nvars * (nvars - 1)
    vals, vals4 = [], []
    for m in range(nvars - 1):
        a = np.atleast_2d(eigvec[:, : m + 1])
        partcov = cov - a @ a.T
        diag = np.diag(partcov)
        if diag.min() < 0:
            return vals, vals4, True
        # a zero partial variance yields inf/nan entries, matching the
        # reference's arithmetic (it early-exits only on NEGATIVE
        # diagonals); silence the numpy warnings, keep the values
        with np.errstate(divide="ignore", invalid="ignore"):
            d = np.diag(1.0 / np.sqrt(diag))
            pr = d @ partcov @ d
        vals.append((np.sum(pr**2) - nvars) / denom)
        vals4.append((np.sum(pr**4) - nvars) / denom)
    return vals, vals4, False


def map_test(
    cov: np.ndarray, eigvec: np.ndarray, mode: str = "reference"
) -> Tuple[int, int]:
    """Velicer's MAP test (original and revised 4th-power variants).

    mode="reference" reproduces the reference's np.put flat-indexing table
    layout; mode="textbook" implements the published test.
    """
    nvars = cov.shape[0]
    denom = nvars * (nvars - 1)
    base = (np.sum(cov**2) - nvars) / denom
    base4 = (np.sum(cov**4) - nvars) / denom
    vals, vals4, early = _map_criteria(cov, eigvec)
    if early:
        return 1, 1

    if mode == "textbook":
        crit = np.array([base] + vals)
        crit4 = np.array([base4] + vals4)
        return int(np.argmin(crit)), int(np.argmin(crit4))

    # --- reference-compatible table construction -------------------------
    def scrambled(b, v):
        # Emulate: fm = [[0..nvars-1], [0..nvars-1]].T; np.put(fm,[0,1],b);
        # then per m: np.put(fm,[m+1,1],v[m]).  Selection scans column 1
        # keeping the first strict minimum.
        fm = np.array(
            [np.arange(nvars, dtype=float), np.arange(nvars, dtype=float)]
        ).T
        np.put(fm, [0, 1], b)
        for m, vm in enumerate(v):
            np.put(fm, [m + 1, 1], vm)
        running = fm[0, 1]
        nfacts = 0
        for s in range(nvars):
            if fm[s, 1] < running:
                running = fm[s, 1]
                nfacts = s
        return nfacts

    return scrambled(base, vals), scrambled(base4, vals4)


def _minres_objective(psi: np.ndarray, s: np.ndarray, nf: int, mode: str):
    """Off-diagonal squared residual of ``s_psi - L L'``.

    Candidate loadings come from the eigendecomposition of the reduced
    correlation matrix (diag replaced by ``1 - psi``); see module docstring
    for the mode="reference" ordering quirk.
    """
    s2 = s.copy()
    np.fill_diagonal(s2, 1.0 - psi)
    eigval, eigvec = np.linalg.eigh(s2)  # ascending
    eps = np.finfo(float).eps
    eigval = np.where(eigval < eps, 100 * eps, eigval)
    if mode == "textbook":
        eigval = eigval[::-1]
        eigvec = eigvec[:, ::-1]
    if nf > 1:
        loadings = eigvec[:, :nf] @ np.diag(np.sqrt(eigval[:nf]))
    else:
        loadings = eigvec[:, :1] * np.sqrt(eigval[0])
    residual = (s2 - loadings @ loadings.T) ** 2
    np.fill_diagonal(residual, 0.0)
    return np.sum(residual)


def psi_to_loadings(
    psi: np.ndarray, s: np.ndarray, nf: int, mode: str = "reference"
) -> np.ndarray:
    """Loadings implied by a uniqueness vector ``psi`` (minres extraction).

    ``sstar = diag(psi)^-1/2 s diag(psi)^-1/2``; the top ``nf`` eigenpairs
    give ``L = diag(sqrt(psi)) V sqrt(max(lambda - 1, 0))``.  In
    mode="reference" the LAPACK ``eig`` ordering is used unsorted, exactly
    as ``_get_loadings`` (``factoranalysis.py:375-401``) does.
    """
    sc = np.diag(1.0 / np.sqrt(psi))
    sstar = sc @ s @ sc
    if mode == "textbook":
        eigval, eigvec = np.linalg.eigh(sstar)
        eigval, eigvec = eigval[::-1], eigvec[:, ::-1]
    else:
        eigval, eigvec = np.linalg.eig(sstar)
    load = eigvec[:, :nf] @ np.diag(np.sqrt(np.maximum(eigval[:nf] - 1.0, 0.0)))
    return np.diag(np.sqrt(psi)) @ load


def _minres_jac(psi, s, nf, mode):
    load = psi_to_loadings(psi, s, nf, mode)
    g = load @ load.T + np.diag(psi) - s
    return np.diag(g) / psi**2


def minres(
    s: np.ndarray, nf: int, mode: str = "reference"
) -> Optional[np.ndarray]:
    """Minimum-residual factor loadings via bounded L-BFGS-B over psi.

    Returns None when the correlation matrix cannot be inverted for the
    SMC-based start (reference bare-except path, ``factoranalysis.py:
    199-200``).
    """
    try:
        ssmc = 1.0 - 1.0 / np.diag(np.linalg.inv(s))
        if np.sum(ssmc) == nf and nf > 1:
            start = 0.5 * np.ones(nf)
        else:
            start = np.diag(s) - ssmc
    except Exception:
        return None

    res = scopt.minimize(
        _minres_objective,
        start,
        method="L-BFGS-B",
        jac=_minres_jac,
        bounds=[(0.005, 1.0)] * len(start),
        args=(s, nf, mode),
    )
    return psi_to_loadings(res.x, s, nf, mode)


def varimax(
    phi: np.ndarray, gamma: float = 1.0, maxiter: int = 20, tol: float = 1e-6
) -> np.ndarray:
    """Orthogonal (varimax for gamma=1) rotation by SVD iteration.

    Kaiser (1958); same iteration and stopping rule as the reference's
    ``_rotate`` (``factoranalysis.py:120-171``).
    """
    p, k = phi.shape
    rot = np.eye(k)
    d = 0.0
    for _ in range(maxiter):
        d_old = d
        lam = phi @ rot
        u, s, vh = np.linalg.svd(
            phi.T @ (lam**3 - (gamma / p) * lam @ np.diag(np.diag(lam.T @ lam)))
        )
        rot = u @ vh
        d = np.sum(s)
        if d_old != 0 and d / d_old < 1 + tol:
            break
    return phi @ rot


def fix_signs(factors: np.ndarray) -> np.ndarray:
    """Flip any factor column whose entry sum is negative (nonzero entries
    only, matching the reference's sign convention loop)."""
    factors = factors.copy()
    for j in range(factors.shape[1]):
        if factors[:, j].sum() < 0:
            nz = np.sign(factors[:, j]) != 0
            factors[nz, j] *= -1.0
    return factors


@dataclass
class FAResult:
    eigval: np.ndarray
    nfactors: int
    factors: Optional[np.ndarray]  # (n_series, nfactors) or None
    fep: Optional[float]  # percentage explained by kept factors


def factor_analysis(
    corr: np.ndarray, maxfactors: Optional[int] = None, mode: str = "reference"
) -> FAResult:
    """Full pipeline: eig -> MAP (Kaiser fallback) -> minres -> varimax.

    Behavior parity with ``FactorAnalysis.solve`` (``factoranalysis.py:
    42-118``) including the nfactors==0 / all-zero-loadings "no proper
    factors" path (factors=None).
    """
    eigval, eigvec = sorted_scaled_eig(corr)
    try:
        nfactors, _ = map_test(corr, eigvec, mode=mode)
        logger.info("Number of factors according to Velicer's MAP test: %d", nfactors)
        if nfactors == 0:
            nfactors = int(np.sum(eigval > 1))
            logger.info("Number of factors according to Kaiser criterion: %d", nfactors)
        if maxfactors is not None:
            nfactors = min(nfactors, maxfactors)
    except Exception:
        nfactors = 0

    factors = minres(corr, nfactors, mode=mode) if nfactors >= 0 else None

    if nfactors > 0 and factors is not None and np.count_nonzero(factors) > 0:
        if nfactors > 1:
            comm = np.sum(factors[:, :nfactors] ** 2, axis=1)
            normalized = factors[:, :nfactors] / np.sqrt(comm)[:, None]
            factors = varimax(normalized) * np.sqrt(comm)[:, None]
        factors = fix_signs(np.atleast_2d(factors[:, :nfactors]))
        fep = 100.0 * np.sum(eigval[:nfactors] / np.sum(eigval))
        return FAResult(eigval=eigval, nfactors=nfactors, factors=factors, fep=fep)

    logger.warning("No proper common factors could be derived from series.")
    return FAResult(eigval=eigval, nfactors=0, factors=None, fep=None)
