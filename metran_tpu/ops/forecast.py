"""Closed-form out-of-sample forecasting for diagonal-transition models.

The reference has no forecasting at all — its grid ends at the last
observation (`metran/metran.py:571`, `kalmanfilter.py`
stores nothing beyond ``T``).  For a diagonal transition matrix the
h-step-ahead predictive moments need no filter iteration, so the whole
forecast horizon is one vectorized expression instead of a scan —
exactly the shape XLA/TPU wants:

With ``x_{T+h} | y_{1:T} ~ N(m_h, P_h)`` and diagonal ``Phi``,

    m_h      = phi^h * m_T                                (elementwise)
    P_h[i,j] = (phi_i phi_j)^h P_T[i,j]
               + q[i,j] (1 - (phi_i phi_j)^h) / (1 - phi_i phi_j)

(the second term is the geometric accumulation of process noise; its
``phi_i phi_j -> 1`` limit is ``h q[i,j]``, guarded explicitly).  The
DFM's AR(1) states always have ``|phi| < 1``, so forecasts decay to the
stationary prior — mean 0, the standardized series' unconditional
level — with variances growing to the stationary variance.

Observation-space forecasts are the usual projection ``Z m_h`` with
variances ``diag(Z P_h Z') + r``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from .statespace import StateSpace


@jax.jit
def forecast_state_moments(
    ss: StateSpace, mean_last: jnp.ndarray, cov_last: jnp.ndarray,
    horizons: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """h-step-ahead state means (H, n) and covariances (H, n, n).

    Parameters
    ----------
    mean_last, cov_last : filtered state moments at the last timestep,
        ``E[x_T | y_{1:T}]`` and its covariance (``FilterResult.mean_f[-1]``,
        ``cov_f[-1]``).
    horizons : (H,) integer steps ahead (typically ``1..H``); vectorized,
        no sequential dependence between horizons.
    """
    h = jnp.asarray(horizons, mean_last.dtype)[:, None]  # (H, 1)
    phi = ss.phi
    mean_h = phi[None, :] ** h * mean_last[None, :]

    pp = phi[:, None] * phi[None, :]  # (n, n) pairwise decay
    hb = h[:, :, None]  # (H, 1, 1)
    # expm1 form of (1 - pp^h) / (1 - pp): the literal difference
    # cancels catastrophically near unit root (pp -> 1, the alpha ~ 3e4
    # regime) in float32 — same guard statespace.py uses for q.  The
    # pp == 1 limit of the ratio is h.
    log_pp = jnp.log(pp)
    pp_h = jnp.exp(hb * log_pp[None])  # (H, n, n)
    denom = jnp.expm1(log_pp)
    at_one = denom == 0
    geom = jnp.where(
        at_one[None],
        hb * jnp.ones_like(pp)[None],
        jnp.expm1(hb * log_pp[None]) / jnp.where(at_one, 1.0, denom)[None],
    )
    cov_h = pp_h * cov_last[None] + geom * ss.q[None]
    return mean_h, cov_h


@jax.jit
def forecast_observation_moments(
    ss: StateSpace, mean_last: jnp.ndarray, cov_last: jnp.ndarray,
    horizons: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """h-step-ahead observation means (H, N) and variances (H, N)."""
    from .kalman import project

    mean_h, cov_h = forecast_state_moments(ss, mean_last, cov_last, horizons)
    means, variances = project(ss.z, mean_h, cov_h)
    return means, variances + ss.r[None]


@functools.partial(jax.jit, static_argnames=("steps",))
def _forecast_from_filtered(ss, mean_f_last, cov_f_last, steps: int):
    horizons = jnp.arange(1, steps + 1)
    return forecast_observation_moments(
        ss, mean_f_last, cov_f_last, horizons
    )


@functools.partial(jax.jit, static_argnames=("sqrt",))
def forecast_horizons(
    ss: StateSpace, mean_last: jnp.ndarray, fac_last: jnp.ndarray,
    horizons: jnp.ndarray, sqrt: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The commit-time horizon pass of the materialized read path.

    Predictive observation means/variances (H, N) at an **arbitrary
    horizon set** from either posterior carry form: ``fac_last`` is the
    filtered covariance (``sqrt=False``) or its Cholesky factor
    (``sqrt=True``, reconstituted here — the one ``chol chol'`` a
    square-root serving path pays per *commit* instead of per read).
    Fused into the serving update kernels (``serve.engine.
    make_update_fn``/``make_arena_update_fn(horizons=...)``) this runs
    in the same dispatch that commits the posterior, so a snapshot read
    path (``serve.readpath``) can answer forecasts without any device
    work; the moments are exactly :func:`forecast_observation_moments`
    of the committed posterior — per-horizon rows are independent, so
    the first ``s`` rows of a ``1..H`` set equal a ``steps=s`` compute
    call's output.
    """
    cov = fac_last @ fac_last.T if sqrt else fac_last
    return forecast_observation_moments(ss, mean_last, cov, horizons)
