"""Implicit-MAP update kernels for non-Gaussian observation models.

Real sensor fleets are not Gaussian: loggers saturate at rails (a
censored reading carries *one-sided* information the reject gate throws
away), ADCs quantize readings onto a grid, and error regimes go
heavier-tailed than the chi-square gating null.  Following the
implicit-MAP filtering construction (arXiv:2311.10580 — the Kalman
update reframed as a per-step MAP optimization), the kernels here
replace the Gaussian conditioning of one appended observation row with
the per-step MAP problem

    argmax_x  log p(y_t | x) + log N(x; m_pred, P_pred)

under per-slot observation likelihoods, solved by a fixed-iteration
jittable Newton inner solve and summarized by a Laplace approximation —
so the result is again ``(mean, factor)`` and every downstream consumer
(forecast moments, gating z-scores, CUSUM detection, the materialized
read path) keeps working unchanged.

**The scalar reduction.**  Every supported likelihood depends on the
state only through the slot's predicted observation ``s = z_i' x``, so
conditioning ``N(m, P)`` on one slot reduces *exactly* to a scalar
problem: with prior ``s ~ N(mu, c)`` (``mu = z_i' m``, ``c = z_i' P
z_i``) and MAP/Laplace summary ``(s_hat, post_var)``,

    m'  =  m + (P z_i) (s_hat - mu) / c
    P'  =  P - (P z_i)(P z_i)' (c - post_var) / c^2

is the exact conditional-Gaussian update given that scalar posterior.
The inner solve is therefore a **scalar** damped Newton iteration per
flagged slot (fixed ``NEWTON_ITERS`` steps, curvature floored at the
prior precision, step clamped to a multiple of the prior sd — jittable,
vmapped across the batch like every other serving kernel), with
derivatives taken by ``jax.grad`` of the likelihood's negative log.

**Likelihoods** (``ROBUST_LIKELIHOODS``; the slot scale ``sigma_i =
max(sqrt(r_i), scale_i)`` smooths the censored/quantized likelihoods —
the DFM's exact ``r = 0`` observation channel would otherwise make
them hard indicators with no usable curvature):

- ``"gaussian"``: the exact closed-form update, verbatim — this kernel
  IS :func:`~metran_tpu.ops.filter_append` then (the pinned fallback);
- ``"censored"`` (Tobit): a reading at/beyond a rail contributes the
  one-sided tail mass ``log Phi((s - hi)/sigma)`` (high rail; mirrored
  for the low rail) — the railed reading's one-sided information is
  *used*, not rejected.  Un-railed readings take the exact Gaussian
  path;
- ``"quantized"``: every reading contributes the interval likelihood
  over its quantization cell ``log [Phi((y + q/2 - s)/sigma) -
  Phi((y - q/2 - s)/sigma)]`` (evaluated in log-space via
  ``log_ndtr`` so deep-tail curvature survives);
- ``"huber_t"``: the heavy-tailed Student-t robust loss
  ``(nu+1)/2 log(1 + (y - s)^2 / (nu sigma^2))`` — full weight for
  small residuals, bounded influence beyond (its curvature clamps at
  zero in the tail, so an extreme outlier barely moves the state and
  barely tightens the variance — the redescending behavior the gate's
  hard reject approximates crudely).

**Bit-exact Gaussian fallback.**  A slot that is not *flagged* (not
armed, masked, likelihood ``"gaussian"``, or — censored — inside the
rails) computes the exact same floating-point operations as the plain
kernels: :func:`implicit_map_filter_append` is bit-identical to
:func:`~metran_tpu.ops.filter_append` (sequential engine) and
:func:`implicit_map_sqrt_filter_append` to
:func:`~metran_tpu.ops.sqrt_filter_append` whenever nothing flags —
the same pinned contract the observation gate carries
(tests/test_implicit_map.py, f32 + f64).

**Square-root form.**  The sqrt kernel converts each flagged slot's
Laplace summary into an equivalent Gaussian *pseudo-observation* —
effective noise ``r_eff = 1 / l''(s_hat)`` and pseudo-innovation
``v_eff = (c + r_eff)(s_hat - mu)/c`` — and feeds the SAME orthogonal
QR array update as the plain/gated kernels, so posteriors stay PSD by
construction (``r_eff >= 0`` always; the curvature is floored at a
dtype-scaled epsilon so the pre-array stays representable).

**Caveat (documented, by design).**  The per-slot sequential reduction
and the Laplace variance are approximations: the exact posterior under
a censored/quantized likelihood is non-Gaussian, and the factor
returned here is its local Gaussian summary at the MAP point.  For the
unimodal, log-concave censored/quantized likelihoods this is the
standard Tobit/Laplace filter; for the non-convex Student-t loss the
curvature floor makes the step a damped majorization.  The serving
layer treats any flagged slot as a time-invariance break (frozen
steady-state gains thaw), exactly like a gate hit.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.scipy.special import log_ndtr

from .kalman import (
    _check_diagonal_q,
    _make_core_step,
    _make_sqrt_core_step,
    _predict,
    _q_sqrt_diag,
    _sqrt_qr_update,
    _tria,
)
from .statespace import StateSpace

#: observation likelihoods accepted by the implicit-MAP kernels
#: (XLA-static — part of the serving compile key).
ROBUST_LIKELIHOODS = ("gaussian", "censored", "quantized", "huber_t")

#: per-slot verdict codes (disjoint from the gate's 0/1/2 so one
#: booking path can tell them apart): a flagged slot that took the MAP
#: path, and one whose inner solve did not meet the residual bar.
ROBUST_MAP = 3
ROBUST_NONCONV = 4

#: fixed inner-solve budget (damped scalar Newton steps per flagged
#: slot).  Quadratic convergence from the prior mean typically lands in
#: 3-6 steps; the budget is XLA-static so the kernel stays jittable.
NEWTON_ITERS = 12


def _solver_tols(dtype):
    """(done_tol, nonconv_tol) on the dimensionless residual
    ``|phi'(s)| * sqrt(c)`` — dtype-scaled so f32 kernels do not spin
    the budget chasing digits the arithmetic cannot hold."""
    eps = float(np.finfo(np.dtype(dtype)).eps)
    tol = 8.0 * eps ** 0.5
    return tol, 125.0 * tol


def _nll_factory(likelihood: str, nu: float):
    """The slot negative log-likelihood ``nll(s, y, sigma, quantum,
    lo, hi)`` for one reading, as a scalar-in-``s`` jax function (its
    first and second derivatives come from ``jax.grad``)."""
    if likelihood == "censored":

        def nll(s, y, sigma, quantum, lo, hi):
            # a flagged reading sits at/beyond exactly one rail; the
            # tail-mass argument for the other side is computed but
            # discarded by the where (its non-finite value never
            # propagates — jnp.where selects, it does not blend)
            hi_side = y >= hi
            arg = jnp.where(hi_side, (s - hi) / sigma, (lo - s) / sigma)
            return -log_ndtr(arg)

        return nll
    if likelihood == "quantized":

        def nll(s, y, sigma, quantum, lo, hi):
            half = 0.5 * quantum
            b = (y + half - s) / sigma
            a = (y - half - s) / sigma
            # stable log of a normal-CDF difference: reflect into the
            # lower tail first (Phi(b) - Phi(a) = Phi(-a) - Phi(-b)),
            # then log Phi(bb) + log1p(-exp(la - lb)) keeps curvature
            # alive deep in the tail where the direct difference
            # underflows; the clip keeps the log1p argument off -1
            # when both tails underflow to equal logs
            flip = (a + b) > 0
            aa = jnp.where(flip, -b, a)
            bb = jnp.where(flip, -a, b)
            la = log_ndtr(aa)
            lb = log_ndtr(bb)
            eps = jnp.asarray(np.finfo(np.dtype(s.dtype)).eps, s.dtype)
            diff = jnp.minimum(la - lb, jnp.log1p(-eps))
            return -(lb + jnp.log1p(-jnp.exp(diff)))

        return nll
    if likelihood == "huber_t":
        nu_c = float(nu)

        def nll(s, y, sigma, quantum, lo, hi):
            resid2 = ((y - s) / sigma) ** 2
            return 0.5 * (nu_c + 1.0) * jnp.log1p(resid2 / nu_c)

        return nll
    raise ValueError(
        f"unknown robust likelihood {likelihood!r}; expected one of "
        f"{ROBUST_LIKELIHOODS}"
    )


def _flag_fn(likelihood: str):
    """Which *observed, armed* slots take the MAP path: censored flags
    railed readings only (everything else is a clean Gaussian reading
    of the same sensor); quantized/huber_t model every reading."""
    if likelihood == "censored":
        return lambda y, lo, hi: (y >= hi) | (y <= lo)
    return lambda y, lo, hi: jnp.ones_like(y, bool)


def _scalar_map_solve(mu, c_safe, nll, dtype, active=None):
    """Damped Newton on ``phi(s) = (s - mu)^2 / (2c) + nll(s)``.

    ``mu``/``c_safe`` and the captured likelihood arguments may be any
    matching-shape arrays (the solve vectorizes elementwise — the
    per-slot problems are independent).  Returns ``(s_hat, w, iters,
    nonconv)`` with ``w = max(nll''(s_hat), 0)`` the floored Laplace
    curvature, ``iters`` the Newton steps actually taken, and
    ``nonconv`` the flagged-residual verdict.

    The loop is a **capped while** (budget :data:`NEWTON_ITERS`):
    lanes outside ``active`` — the caller's flagged mask — start
    converged, and the loop exits the moment every lane is done, so a
    dispatch where nothing flags pays ONE gradient/curvature
    evaluation instead of the full budget (the <10% armed-overhead
    bar).  Value-identical to the fixed-budget loop: a done lane never
    moves, so early exit changes wall time, not results.
    """
    one = jnp.ones((), dtype)
    zero = jnp.zeros((), dtype)
    tol, nonconv_tol = _solver_tols(dtype)
    g1 = jax.grad(lambda s: jnp.sum(nll(s)))

    def g1_and_g2(s):
        # one jvp pass yields the gradient AND its directional
        # (elementwise) derivative — the per-iteration cost is ~1.5
        # gradient evaluations instead of two separate autodiff passes
        return jax.jvp(g1, (s,), (jnp.ones_like(s),))

    inv_c = one / c_safe
    sqrt_c = jnp.sqrt(c_safe)
    max_step = 8.0 * sqrt_c

    def cond(st):
        _s, _iters, done, k = st
        return (k < NEWTON_ITERS) & ~jnp.all(done)

    def body(st):
        s, iters, done, k = st
        d1, d2 = g1_and_g2(s)
        gtot = (s - mu) * inv_c + d1
        h = inv_c + jnp.maximum(d2, zero)
        step = jnp.clip(-gtot / h, -max_step, max_step)
        done = done | (jnp.abs(gtot) * sqrt_c <= tol)
        s = jnp.where(done, s, s + step)
        iters = iters + jnp.where(done, 0, 1).astype(jnp.int32)
        return (s, iters, done, k + 1)

    s0 = mu
    iters0 = jnp.zeros(jnp.shape(mu), jnp.int32)
    done0 = (
        jnp.zeros(jnp.shape(mu), bool) if active is None
        else jnp.broadcast_to(~active, jnp.shape(mu))
    )
    s_hat, iters, _, _ = lax.while_loop(
        cond, body, (s0, iters0, done0, jnp.zeros((), jnp.int32))
    )
    d1_f, d2_f = g1_and_g2(s_hat)
    g_final = (s_hat - mu) * inv_c + d1_f
    nonconv = jnp.abs(g_final) * sqrt_c > nonconv_tol
    w = jnp.maximum(d2_f, zero)
    return s_hat, w, iters, nonconv


def _robust_sequential_update(
    mean, cov, y, mask, z, r, dtype, nll_fn, flag_fn, armed,
    scale, quantum, rail_lo, rail_hi,
):
    """Masked sequential update with per-slot implicit-MAP conditioning.

    The robust twin of ``_sequential_update`` (same slot order, same
    rank-1 recursion): each observed slot is conditioned one at a time,
    and a *flagged* slot replaces the closed-form Gaussian conditioning
    with the scalar MAP/Laplace summary of its non-Gaussian likelihood.
    A slot that does NOT flag computes the exact same floating-point
    operations as the ungated update — the bit-exactness contract.

    Returns ``(mean, cov, sigma, detf, zscore, verdict, iters)``; for
    flagged slots ``sigma``/``detf`` book the Laplace-approximate
    likelihood terms ``(s_hat - mu)^2/c + 2 nll(s_hat)`` and
    ``log(1 + c w)`` — finite by construction, which is what the
    serving integrity gate requires of them.
    """
    zero = jnp.zeros((), dtype)
    one = jnp.ones((), dtype)
    nan = jnp.asarray(jnp.nan, dtype)
    c_floor = jnp.asarray(np.finfo(np.dtype(dtype)).tiny ** 0.5, dtype)

    def step(carry, xs):
        m, p, sigma, detf = carry
        y_i, mask_i, z_i, r_i, sc_i, q_i, lo_i, hi_i = xs
        v = y_i - z_i @ m
        d = p @ z_i
        c = z_i @ d
        f = c + r_i
        f_safe = jnp.where(mask_i, f, one)
        zscore = v / jnp.sqrt(f_safe)
        flagged = armed & mask_i & flag_fn(y_i, lo_i, hi_i)
        # --- exact Gaussian branch: verbatim _sequential_update ops ---
        k = d / f_safe
        m_g = m + k * v
        p_g = p - jnp.outer(k, k) * f_safe
        sig_g = jnp.where(mask_i, v * v / f_safe, zero)
        det_g = jnp.where(mask_i, jnp.log(f_safe), zero)
        # --- implicit-MAP branch (scalar solve on s = z_i' x) ---
        mu = y_i - v  # z_i' m, reusing the already-computed projection
        c_safe = jnp.maximum(c, c_floor)
        sig_i = jnp.maximum(jnp.sqrt(jnp.maximum(r_i, zero)), sc_i)
        nll = lambda s: nll_fn(s, y_i, sig_i, q_i, lo_i, hi_i)  # noqa: E731
        s_hat, w, iters, nonconv = _scalar_map_solve(
            mu, c_safe, nll, dtype, active=flagged
        )
        gain_r = (s_hat - mu) / c_safe
        shrink = w / (one + c_safe * w)  # (c - post_var) / c^2
        m_r = m + d * gain_r
        p_r = p - jnp.outer(d, d) * shrink
        sig_r = (s_hat - mu) ** 2 / c_safe + 2.0 * nll(s_hat)
        det_r = jnp.log1p(c_safe * w)
        # --- select ---
        m = jnp.where(flagged, m_r, jnp.where(mask_i, m_g, m))
        p = jnp.where(flagged, p_r, jnp.where(mask_i, p_g, p))
        sigma = sigma + jnp.where(flagged, sig_r, sig_g)
        detf = detf + jnp.where(flagged, det_r, det_g)
        verdict = jnp.where(
            flagged,
            jnp.where(nonconv, ROBUST_NONCONV, ROBUST_MAP),
            0,
        ).astype(jnp.int8)
        iters = jnp.where(flagged, iters, 0)
        return (m, p, sigma, detf), (
            jnp.where(mask_i, zscore, nan), verdict, iters
        )

    (mean, cov, sigma, detf), (zs, verdicts, iters) = lax.scan(
        step, (mean, cov, zero, zero),
        (y, mask, z, r, scale, quantum, rail_lo, rail_hi),
    )
    return mean, cov, sigma, detf, zs, verdicts, iters


def _make_robust_core_step(ss: StateSpace, dtype, nll_fn, flag_fn,
                           armed, scale, quantum, rail_lo, rail_hi):
    """Predict + robust sequential update body of one filter timestep
    (the implicit-MAP twin of ``_make_core_step``, sequential engine)."""

    def core(mean, cov, y_t, mask_t):
        mean_p, cov_p = _predict(mean, cov, ss.phi, ss.q)
        has_obs = jnp.any(mask_t)
        mean_f, cov_f, sigma, detf, zs, verdicts, iters = (
            _robust_sequential_update(
                mean_p, cov_p, y_t, mask_t, ss.z, ss.r, dtype,
                nll_fn, flag_fn, armed, scale, quantum, rail_lo,
                rail_hi,
            )
        )
        mean_f = jnp.where(has_obs, mean_f, mean_p)
        cov_f = jnp.where(has_obs, cov_f, cov_p)
        return mean_f, cov_f, sigma, detf, zs, verdicts, iters

    return core


def _make_robust_sqrt_core_step(ss: StateSpace, dtype, nll_fn, flag_fn,
                                armed, scale, quantum, rail_lo,
                                rail_hi):
    """Predict + robust QR update body of one square-root timestep.

    Like the gated sqrt core, per-slot decisions come off the
    *predicted* factor (marginal prior variances ``c_i = ||(Z S_p)_i||^2``
    — the same quantities the gate reads), then every flagged slot's
    Laplace summary is converted to a Gaussian pseudo-observation
    (``r_eff = 1/w``, ``v_eff = (c + r_eff)(s_hat - mu)/c``) and ONE
    joint QR of the same pre-array as the plain core conditions on all
    slots at once — PSD by construction for any ``r_eff >= 0``.  A slot
    that does not flag feeds its untouched ``(r, v)`` row, so the QR is
    bit-identical to the plain core's when nothing flags.
    """
    n = ss.phi.shape[-1]
    m_obs = ss.z.shape[-2]
    eye_m = jnp.eye(m_obs, dtype=dtype)
    q_sqrt = _q_sqrt_diag(ss.q).astype(dtype)
    zero = jnp.zeros((), dtype)
    one = jnp.ones((), dtype)
    inf = jnp.asarray(jnp.inf, dtype)
    nan = jnp.asarray(jnp.nan, dtype)
    eps = jnp.asarray(np.finfo(np.dtype(dtype)).eps, dtype)
    c_floor = jnp.asarray(np.finfo(np.dtype(dtype)).tiny ** 0.5, dtype)

    def core(mean, chol, y_t, mask_t):
        mean_p = ss.phi * mean
        chol_p = _tria(jnp.concatenate(
            [ss.phi[:, None] * chol, jnp.diag(q_sqrt)], axis=1
        ))
        maskf = mask_t.astype(dtype)
        z_m = ss.z * maskf[:, None]
        r_t = jnp.where(mask_t, ss.r, 0.0) + (1.0 - maskf)
        v = jnp.where(mask_t, y_t - ss.z @ mean_p, 0.0)
        c_diag = jnp.sum((z_m @ chol_p) ** 2, axis=-1)
        f_diag = c_diag + r_t
        zscore = v / jnp.sqrt(f_diag)
        flagged = armed & mask_t & flag_fn(y_t, rail_lo, rail_hi)
        # scalar MAP per slot, vectorized (slots are independent given
        # the predicted state — the same marginal treatment the gate
        # uses on this engine)
        mu = ss.z @ mean_p
        c_safe = jnp.maximum(c_diag, c_floor)
        sig = jnp.maximum(
            jnp.sqrt(jnp.maximum(ss.r, zero)), scale
        )
        nll = lambda s: nll_fn(  # noqa: E731
            s, y_t, sig, quantum, rail_lo, rail_hi
        )
        s_hat, w, iters, nonconv = _scalar_map_solve(
            mu, c_safe, nll, dtype, active=flagged
        )
        # pseudo-observation: floor the curvature so r_eff stays
        # representable (w -> 0 means "no information": the slot then
        # contributes a near-infinite-noise observation, i.e. nothing)
        w_eff = jnp.maximum(w, eps * 1e-2 / c_safe)
        r_eff = one / w_eff
        v_eff = (c_safe + r_eff) * (s_hat - mu) / c_safe
        r_u = jnp.where(flagged, r_eff, r_t)
        v_u = jnp.where(flagged, v_eff, v)
        mean_f, chol_f, sigma, detf = _sqrt_qr_update(
            z_m, r_u, v_u, mean_p, chol_p, n, m_obs, eye_m, zero, inf,
            dtype,
        )
        verdict = jnp.where(
            flagged,
            jnp.where(nonconv, ROBUST_NONCONV, ROBUST_MAP),
            0,
        ).astype(jnp.int8)
        iters = jnp.where(flagged, iters, 0)
        return (mean_f, chol_f, sigma, detf,
                jnp.where(mask_t, zscore, nan), verdict, iters)

    return core


def implicit_map_filter_append(
    ss: StateSpace,
    mean: jnp.ndarray,
    cov: jnp.ndarray,
    y_new: jnp.ndarray,
    mask_new: jnp.ndarray,
    armed=True,
    rail_lo=None,
    rail_hi=None,
    quantum=None,
    scale=None,
    likelihood: str = "censored",
    nu: float = 4.0,
) -> Tuple[jnp.ndarray, ...]:
    """:func:`~metran_tpu.ops.filter_append` with per-slot implicit-MAP
    conditioning under a non-Gaussian observation likelihood.

    Sequential-processing engine (the MAP reduction is per slot, like
    the gate; a ``joint``-engine serving bucket arming the robust path
    switches to this kernel — posteriors agree to float tolerance).
    ``likelihood``/``nu`` are XLA-static (serving compile-key
    material); ``armed`` is traced (scalar bool, per-model under
    ``vmap``) and ``rail_lo``/``rail_hi``/``quantum``/``scale`` are
    traced per-slot ``(n_obs,)`` arrays in the kernel's (standardized)
    observation units — the serving layer derives them from the
    physical :class:`~metran_tpu.serve.engine.RobustSpec` through each
    model's scaler, so heterogeneous fleets share one executable.

    Returns ``(mean_T, cov_T, sigma, detf, zscore, verdict, iters)``:
    the first four exactly as :func:`~metran_tpu.ops.filter_append`,
    plus the per-step (k, n_obs) signed normalized innovations (NaN
    where unobserved), int8 verdicts (0 pass, :data:`ROBUST_MAP`,
    :data:`ROBUST_NONCONV`) and int32 inner-solver iteration counts
    (0 on unflagged slots).

    Contract: with ``likelihood="gaussian"``, ``armed=False``, or no
    flagged slot (censored likelihood, no railed reading), the
    posterior and likelihood outputs are bit-identical to
    :func:`~metran_tpu.ops.filter_append` with ``engine="sequential"``.
    """
    if likelihood not in ROBUST_LIKELIHOODS:
        raise ValueError(
            f"unknown robust likelihood {likelihood!r}; expected one "
            f"of {ROBUST_LIKELIHOODS}"
        )
    dtype = ss.q.dtype
    n_obs = ss.z.shape[-2]
    rail_lo, rail_hi, quantum, scale = _default_params(
        rail_lo, rail_hi, quantum, scale, n_obs, dtype
    )
    return _implicit_map_filter_append(
        ss, mean, cov, y_new, mask_new, jnp.asarray(armed, bool),
        rail_lo, rail_hi, quantum, scale,
        likelihood=likelihood, nu=float(nu),
    )


def _default_params(rail_lo, rail_hi, quantum, scale, n_obs, dtype):
    """Fill traced per-slot parameter vectors for direct (registry-less)
    kernel use; the serving layer always passes them explicitly."""
    def vec(x, default):
        if x is None:
            x = default
        return jnp.broadcast_to(jnp.asarray(x, dtype), (n_obs,))

    return (
        vec(rail_lo, -jnp.inf),
        vec(rail_hi, jnp.inf),
        vec(quantum, 1.0),
        vec(scale, 0.05),
    )


@functools.partial(jax.jit, static_argnames=("likelihood", "nu"))
def _implicit_map_filter_append(ss, mean, cov, y_new, mask_new, armed,
                                rail_lo, rail_hi, quantum, scale, *,
                                likelihood, nu):
    dtype = ss.q.dtype
    y_new = jnp.atleast_2d(jnp.asarray(y_new, dtype))
    mask_new = jnp.atleast_2d(jnp.asarray(mask_new, bool))
    if likelihood == "gaussian":
        # the plain core, verbatim (bit-exactness by construction);
        # z-scores/verdicts/iters come back NaN/0/0
        core = _make_core_step(ss, "sequential", dtype)

        def step(carry, xs):
            m, p = carry
            y_t, mask_t = xs
            _, _, mean_f, cov_f, sigma, detf = core(m, p, y_t, mask_t)
            return (mean_f, cov_f), (sigma, detf)

        (mean_t, cov_t), (sigma, detf) = lax.scan(
            step, (jnp.asarray(mean, dtype), jnp.asarray(cov, dtype)),
            (y_new, mask_new),
        )
        return (
            mean_t, cov_t, sigma, detf,
            jnp.full(y_new.shape, jnp.nan, dtype),
            jnp.zeros(y_new.shape, jnp.int8),
            jnp.zeros(y_new.shape, jnp.int32),
        )
    nll_fn = _nll_factory(likelihood, nu)
    flag_fn = _flag_fn(likelihood)
    core = _make_robust_core_step(
        ss, dtype, nll_fn, flag_fn, armed,
        jnp.asarray(scale, dtype), jnp.asarray(quantum, dtype),
        jnp.asarray(rail_lo, dtype), jnp.asarray(rail_hi, dtype),
    )

    def step(carry, xs):
        m, p = carry
        y_t, mask_t = xs
        mean_f, cov_f, sigma, detf, zs, verdicts, iters = core(
            m, p, y_t, mask_t
        )
        return (mean_f, cov_f), (sigma, detf, zs, verdicts, iters)

    (mean_t, cov_t), (sigma, detf, zs, verdicts, iters) = lax.scan(
        step, (jnp.asarray(mean, dtype), jnp.asarray(cov, dtype)),
        (y_new, mask_new),
    )
    return mean_t, cov_t, sigma, detf, zs, verdicts, iters


def implicit_map_sqrt_filter_append(
    ss: StateSpace,
    mean: jnp.ndarray,
    chol: jnp.ndarray,
    y_new: jnp.ndarray,
    mask_new: jnp.ndarray,
    armed=True,
    rail_lo=None,
    rail_hi=None,
    quantum=None,
    scale=None,
    likelihood: str = "censored",
    nu: float = 4.0,
) -> Tuple[jnp.ndarray, ...]:
    """:func:`~metran_tpu.ops.sqrt_filter_append` with per-slot
    implicit-MAP conditioning — the square-root counterpart of
    :func:`implicit_map_filter_append`.

    Carries a Cholesky factor, makes per-slot decisions off the
    predicted factor's marginal variances (like the gated sqrt kernel),
    converts each flagged slot's Laplace summary into a Gaussian
    pseudo-observation and runs the same orthogonal QR update — the
    returned factor is PSD **by construction** for every likelihood.
    Same outputs and the same bit-exact fallback contract as the
    covariance form, against :func:`~metran_tpu.ops.
    sqrt_filter_append`.
    """
    if likelihood not in ROBUST_LIKELIHOODS:
        raise ValueError(
            f"unknown robust likelihood {likelihood!r}; expected one "
            f"of {ROBUST_LIKELIHOODS}"
        )
    _check_diagonal_q(ss.q)
    dtype = ss.q.dtype
    n_obs = ss.z.shape[-2]
    rail_lo, rail_hi, quantum, scale = _default_params(
        rail_lo, rail_hi, quantum, scale, n_obs, dtype
    )
    return _implicit_map_sqrt_filter_append(
        ss, mean, chol, y_new, mask_new, jnp.asarray(armed, bool),
        rail_lo, rail_hi, quantum, scale,
        likelihood=likelihood, nu=float(nu),
    )


@functools.partial(jax.jit, static_argnames=("likelihood", "nu"))
def _implicit_map_sqrt_filter_append(ss, mean, chol, y_new, mask_new,
                                     armed, rail_lo, rail_hi, quantum,
                                     scale, *, likelihood, nu):
    dtype = ss.q.dtype
    y_new = jnp.atleast_2d(jnp.asarray(y_new, dtype))
    mask_new = jnp.atleast_2d(jnp.asarray(mask_new, bool))
    if likelihood == "gaussian":
        core = _make_sqrt_core_step(ss, dtype)

        def step(carry, xs):
            m, s = carry
            y_t, mask_t = xs
            _, _, mean_f, chol_f, sigma, detf = core(m, s, y_t, mask_t)
            return (mean_f, chol_f), (sigma, detf)

        (mean_t, chol_t), (sigma, detf) = lax.scan(
            step, (jnp.asarray(mean, dtype), jnp.asarray(chol, dtype)),
            (y_new, mask_new),
        )
        return (
            mean_t, chol_t, sigma, detf,
            jnp.full(y_new.shape, jnp.nan, dtype),
            jnp.zeros(y_new.shape, jnp.int8),
            jnp.zeros(y_new.shape, jnp.int32),
        )
    nll_fn = _nll_factory(likelihood, nu)
    flag_fn = _flag_fn(likelihood)
    core = _make_robust_sqrt_core_step(
        ss, dtype, nll_fn, flag_fn, armed,
        jnp.asarray(scale, dtype), jnp.asarray(quantum, dtype),
        jnp.asarray(rail_lo, dtype), jnp.asarray(rail_hi, dtype),
    )

    def step(carry, xs):
        m, s = carry
        y_t, mask_t = xs
        mean_f, chol_f, sigma, detf, zs, verdicts, iters = core(
            m, s, y_t, mask_t
        )
        return (mean_f, chol_f), (sigma, detf, zs, verdicts, iters)

    (mean_t, chol_t), (sigma, detf, zs, verdicts, iters) = lax.scan(
        step, (jnp.asarray(mean, dtype), jnp.asarray(chol, dtype)),
        (y_new, mask_new),
    )
    return mean_t, chol_t, sigma, detf, zs, verdicts, iters


__all__ = [
    "NEWTON_ITERS",
    "ROBUST_LIKELIHOODS",
    "ROBUST_MAP",
    "ROBUST_NONCONV",
    "implicit_map_filter_append",
    "implicit_map_sqrt_filter_append",
]
