"""TPU-native Kalman filtering/smoothing for the Metran DFM.

The reference implementation runs a sequential-processing Kalman filter as a
numba-compiled per-timestep Python loop with ragged missing-data index arrays
(``metran/kalmanfilter.py:236-400``) and an RTS smoother with ``pinv``
(``metran/kalmanfilter.py:403-476``).  Here the recursions are expressed as
``lax.scan`` over time with **static shapes**: missing observations are
handled by a boolean mask per timestep and masked no-op updates (XLA-friendly
``where``-selects instead of ragged indices).  Everything is pure, jittable,
differentiable and vmappable over leading batch axes.

Three update engines are provided here (plus the associative-scan
engines in :mod:`metran_tpu.ops.pkalman`):

- ``sequential``: processes observed series one scalar at a time (rank-1
  covariance downdates), numerically step-for-step equivalent to the
  reference's sequential processing (Koopman-style), hence used for parity.
- ``joint``: conditions on all observed series at once via a Cholesky solve
  of the masked innovation covariance; mathematically identical likelihood,
  maps the inner work onto batched matmuls/Cholesky (MXU-friendly).
- ``sqrt``: propagates lower-triangular Cholesky factors instead of
  covariances, with predict/update as QR factorizations of stacked
  factor blocks (orthogonal transformations, arXiv:2502.11686) —
  covariances are PSD by construction and there is no ``cholesky`` of
  a computed matrix anywhere, so no NaN path exists even where float32
  roundoff makes the explicit innovation covariance indefinite.  The
  numerically robust float32 engine.

Every engine's deviance maps a non-finite filter path to ``+inf`` — a
rejectable line-search value — instead of a NaN that would poison the
optimizer state (see :func:`_finite_or_inf`).

Log-likelihood semantics match ``SPKalmanFilter.get_mle``
(``metran/kalmanfilter.py:550-567``): the returned objective is the deviance
``-2 log L = nobs log(2 pi) + sum(log f) + sum(v^2/f)`` where the first
``warmup`` *observed* timesteps are excluded from the ``f``/``v`` sums while
``nobs`` excludes the first ``warmup`` *grid* timesteps.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .statespace import StateSpace

LOG2PI = 1.8378770664093453  # log(2*pi)


class FilterStep(NamedTuple):
    """Per-timestep filter quantities (shapes lead with time when stacked)."""

    mean_p: jnp.ndarray  # predicted state mean  E[x_t | y_{1:t-1}]
    cov_p: jnp.ndarray  # predicted state covariance
    mean_f: jnp.ndarray  # filtered state mean   E[x_t | y_{1:t}]
    cov_f: jnp.ndarray  # filtered state covariance
    sigma: jnp.ndarray  # sum of v^2/f over observed entries at t
    detf: jnp.ndarray  # sum of log f over observed entries at t


class FilterResult(NamedTuple):
    mean_p: jnp.ndarray  # (T, n)
    cov_p: jnp.ndarray  # (T, n, n)
    mean_f: jnp.ndarray  # (T, n)
    cov_f: jnp.ndarray  # (T, n, n)
    sigma: jnp.ndarray  # (T,)
    detf: jnp.ndarray  # (T,)


def _predict(mean, cov, phi, q):
    """Diagonal-transition predict step: exploits Phi = diag(phi)."""
    mean_p = phi * mean
    cov_p = phi[:, None] * cov * phi[None, :] + q
    return mean_p, cov_p


def _sequential_update(mean, cov, y, mask, z, r, dtype):
    """Masked sequential-processing update over all observation slots.

    Iterates the series slots in ascending order (the same order the
    reference visits its compressed observation indices) and applies a
    rank-1 update per observed slot; masked slots leave the state unchanged
    and contribute zero to sigma/detf.
    """
    zero = jnp.zeros((), dtype)

    def step(carry, xs):
        m, p, sigma, detf = carry
        y_i, mask_i, z_i, r_i = xs
        v = y_i - z_i @ m
        d = p @ z_i
        f = z_i @ d + r_i
        f_safe = jnp.where(mask_i, f, jnp.ones((), dtype))
        k = d / f_safe
        m_new = m + k * v
        p_new = p - jnp.outer(k, k) * f_safe
        m = jnp.where(mask_i, m_new, m)
        p = jnp.where(mask_i, p_new, p)
        sigma = sigma + jnp.where(mask_i, v * v / f_safe, zero)
        detf = detf + jnp.where(mask_i, jnp.log(f_safe), zero)
        return (m, p, sigma, detf), None

    (mean, cov, sigma, detf), _ = lax.scan(
        step, (mean, cov, zero, zero), (y, mask, z, r)
    )
    return mean, cov, sigma, detf


def _joint_update(mean, cov, y, mask, z, r, dtype):
    """Masked joint update via Cholesky of the innovation covariance.

    Unobserved slots get a unit innovation variance and zero innovation, so
    they contribute nothing to the gain, ``sigma`` or ``detf`` (log 1 = 0);
    the result equals conditioning on the observed subset only.

    An innovation covariance that is indefinite in the working precision
    (the float32 failure mode near ``phi -> 1``) makes the raw Cholesky
    emit NaN columns; instead of letting them poison the remainder of
    the scan, the step degrades to a no-op with ``detf = +inf`` — the
    deviance becomes ``+inf`` (a rejectable line-search value) while the
    state carry stays finite.
    """
    maskf = mask.astype(dtype)
    z_m = z * maskf[:, None]
    v = jnp.where(mask, y - z @ mean, 0.0)
    pz = cov @ z_m.T  # (n, m)
    f = z_m @ pz + jnp.diag(jnp.where(mask, r, 0.0) + (1.0 - maskf))
    chol = jnp.linalg.cholesky(f)
    ok = jnp.all(jnp.isfinite(chol))
    chol_safe = jnp.where(ok, chol, jnp.eye(f.shape[0], dtype=dtype))
    # K = P Z' F^-1  ->  solve F K' = Z P
    kt = jax.scipy.linalg.cho_solve((chol_safe, True), pz.T)  # (m, n)
    mean_u = mean + kt.T @ v
    cov_u = cov - kt.T @ f @ kt
    w = jax.scipy.linalg.solve_triangular(chol_safe, v, lower=True)
    mean = jnp.where(ok, mean_u, mean)
    cov = jnp.where(ok, cov_u, cov)
    sigma = jnp.where(ok, jnp.sum(w * w), jnp.zeros((), dtype))
    detf = jnp.where(
        ok,
        2.0 * jnp.sum(jnp.log(jnp.diagonal(chol_safe))),
        jnp.asarray(jnp.inf, dtype),
    )
    return mean, cov, sigma, detf


_UPDATES = {"sequential": _sequential_update, "joint": _joint_update}


def _init_state(ss: StateSpace, dtype):
    """Reference initialization: zero mean, identity covariance
    (``metran/kalmanfilter.py:747-750``)."""
    n = ss.phi.shape[-1]
    return jnp.zeros(n, dtype), jnp.eye(n, dtype=dtype)


def _make_core_step(ss: StateSpace, engine: str, dtype):
    """Shared predict+update body of one filter timestep.

    Single source of the masked-update semantics, used by both the plain
    ``kalman_filter`` scan and the segmented remat scan so they cannot
    drift apart.  Returns ``(mean_p, cov_p, mean_f, cov_f, sigma, detf)``.
    """
    update = _UPDATES[engine]

    def core(mean, cov, y_t, mask_t):
        mean_p, cov_p = _predict(mean, cov, ss.phi, ss.q)
        has_obs = jnp.any(mask_t)
        mean_f, cov_f, sigma, detf = update(
            mean_p, cov_p, y_t, mask_t, ss.z, ss.r, dtype
        )
        # timestep with zero observations: state passes through unchanged
        # (the where is redundant given masked updates but keeps the
        # no-observation semantics explicit and gradients clean)
        mean_f = jnp.where(has_obs, mean_f, mean_p)
        cov_f = jnp.where(has_obs, cov_f, cov_p)
        return mean_p, cov_p, mean_f, cov_f, sigma, detf

    return core


@functools.partial(jax.jit, static_argnames=("engine", "store"))
def kalman_filter(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    engine: str = "sequential",
    store: bool = True,
) -> FilterResult:
    """Run the masked sequential-processing Kalman filter as a ``lax.scan``.

    Parameters
    ----------
    ss : StateSpace (diagonal transition).
    y : (T, n_obs) observations; entries at masked positions are ignored.
    mask : (T, n_obs) bool, True where a real observation is present.
    engine : "sequential" (parity), "joint" (Cholesky batch update),
        "sqrt" (QR square-root updates, PSD by construction —
        covariances here are reconstituted ``S S'``; use
        :func:`sqrt_kalman_filter` to keep the factors), "parallel"
        (associative scan) or "sqrt_parallel" (associative scan over
        triangular factors).
    store : if False, per-step means/covariances are not stacked (loglik-only
        path — keeps memory O(n^2) instead of O(T n^2)).  Note this memory
        saving applies to the ``sequential``/``joint``/``sqrt`` scan
        engines only: the ``parallel``/``sqrt_parallel`` associative-scan
        engines materialize all per-step moments regardless of ``store``
        (only the return shapes follow the contract), so their memory is
        always O(T n^2).

    Returns
    -------
    FilterResult; when ``store=False`` the mean/cov arrays hold only the
    final carry values (shape (n,)/(n, n)).
    """
    if engine == "parallel":
        from .pkalman import parallel_filter

        res = parallel_filter(ss, y, mask)
        if not store:  # return shapes follow the store=False contract, but
            # the associative scan has already materialized O(T n^2) moments
            return FilterResult(
                res.mean_f[-1], res.cov_f[-1], res.mean_f[-1],
                res.cov_f[-1], res.sigma, res.detf,
            )
        return res
    if engine == "sqrt_parallel":
        from .pkalman import sqrt_parallel_filter

        res = sqrt_parallel_filter(ss, y, mask)
        if not store:  # store=False contract; O(T n^2) already spent
            cov_t = chol_outer(res.chol_f[-1])
            return FilterResult(
                res.mean_f[-1], cov_t, res.mean_f[-1], cov_t,
                res.sigma, res.detf,
            )
        return FilterResult(
            res.mean_p, chol_outer(res.chol_p), res.mean_f,
            chol_outer(res.chol_f), res.sigma, res.detf,
        )
    if engine == "sqrt":
        res = _sqrt_kalman_filter(ss, y, mask, store)
        if not store:
            cov_t = chol_outer(res.chol_f)
            return FilterResult(
                res.mean_f, cov_t, res.mean_f, cov_t, res.sigma, res.detf
            )
        return FilterResult(
            res.mean_p, chol_outer(res.chol_p), res.mean_f,
            chol_outer(res.chol_f), res.sigma, res.detf,
        )
    dtype = ss.q.dtype
    y = jnp.asarray(y, dtype)
    mask = jnp.asarray(mask, bool)
    core = _make_core_step(ss, engine, dtype)
    mean0, cov0 = _init_state(ss, dtype)

    def step(carry, xs):
        mean, cov = carry
        y_t, mask_t = xs
        mean_p, cov_p, mean_f, cov_f, sigma, detf = core(
            mean, cov, y_t, mask_t
        )
        out = FilterStep(mean_p, cov_p, mean_f, cov_f, sigma, detf)
        if not store:
            out = FilterStep(
                jnp.zeros(0, dtype),
                jnp.zeros(0, dtype),
                jnp.zeros(0, dtype),
                jnp.zeros(0, dtype),
                sigma,
                detf,
            )
        return (mean_f, cov_f), out

    (mean_T, cov_T), steps = lax.scan(step, (mean0, cov0), (y, mask))
    if store:
        return FilterResult(
            steps.mean_p, steps.cov_p, steps.mean_f, steps.cov_f,
            steps.sigma, steps.detf,
        )
    return FilterResult(mean_T, cov_T, mean_T, cov_T, steps.sigma, steps.detf)


@functools.partial(jax.jit, static_argnames=("engine",))
def filter_update(
    ss: StateSpace,
    mean: jnp.ndarray,
    cov: jnp.ndarray,
    y_t: jnp.ndarray,
    mask_t: jnp.ndarray,
    engine: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One online-assimilation step from an arbitrary carried posterior.

    Exactly the predict+update body of one :func:`kalman_filter`
    timestep (the same ``_make_core_step`` the scan uses, so the two
    cannot drift apart), but exposed as a standalone entry point: given
    the filtered posterior ``N(mean, cov)`` at time ``t-1`` and one new
    observation row, return the filtered posterior at ``t`` plus that
    step's likelihood terms.  This is what turns the filter into an
    incremental service — appending an observation costs one step, not
    a full-history refilter (``serve/engine.py`` builds on it).

    Returns ``(mean_f, cov_f, sigma, detf)``; ``sigma``/``detf`` are the
    step's ``v^2/f`` and ``log f`` sums (zero when ``mask_t`` is all
    False, matching the scan's no-op semantics for missing rows).
    """
    if engine in ("sqrt", "sqrt_parallel"):
        raise ValueError(
            "filter_update carries a covariance; the square-root engine "
            "carries a Cholesky factor — use sqrt_filter_update"
        )
    dtype = ss.q.dtype
    core = _make_core_step(ss, engine, dtype)
    _, _, mean_f, cov_f, sigma, detf = core(
        jnp.asarray(mean, dtype), jnp.asarray(cov, dtype),
        jnp.asarray(y_t, dtype), jnp.asarray(mask_t, bool),
    )
    return mean_f, cov_f, sigma, detf


@functools.partial(jax.jit, static_argnames=("engine",))
def filter_append(
    ss: StateSpace,
    mean: jnp.ndarray,
    cov: jnp.ndarray,
    y_new: jnp.ndarray,
    mask_new: jnp.ndarray,
    engine: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Assimilate ``k`` appended observation rows from a carried posterior.

    Runs ONLY the new timesteps through the filter recursion, starting
    from the filtered posterior ``N(mean, cov)`` at the last already-
    assimilated timestep — the incremental-update path of the serving
    layer.  Equivalent (to float tolerance) to refiltering the full
    history and reading the final carry, at O(k) cost instead of O(T).

    Parameters
    ----------
    y_new : (k, n_obs) appended observations (masked entries ignored).
    mask_new : (k, n_obs) bool, True where a real observation is present.

    Returns
    -------
    ``(mean_T, cov_T, sigma, detf)``: the filtered posterior after the
    last appended step and the per-step (k,) likelihood-term arrays.
    """
    if engine in ("sqrt", "sqrt_parallel"):
        raise ValueError(
            "filter_append carries a covariance; the square-root engine "
            "carries a Cholesky factor — use sqrt_filter_append"
        )
    dtype = ss.q.dtype
    y_new = jnp.atleast_2d(jnp.asarray(y_new, dtype))
    mask_new = jnp.atleast_2d(jnp.asarray(mask_new, bool))
    core = _make_core_step(ss, engine, dtype)

    def step(carry, xs):
        m, p = carry
        y_t, mask_t = xs
        _, _, mean_f, cov_f, sigma, detf = core(m, p, y_t, mask_t)
        return (mean_f, cov_f), (sigma, detf)

    (mean_T, cov_T), (sigma, detf) = lax.scan(
        step, (jnp.asarray(mean, dtype), jnp.asarray(cov, dtype)),
        (y_new, mask_new),
    )
    return mean_T, cov_T, sigma, detf


# ----------------------------------------------------------------------
# square-root (Cholesky-factor) engine
# ----------------------------------------------------------------------
#
# The covariance-form engines above propagate P itself and factor the
# innovation covariance with ``jnp.linalg.cholesky`` — the one operation
# that can fail (NaN columns) when float32 roundoff makes its argument
# indefinite, silently poisoning the remainder of the scan.  The
# square-root engine instead propagates the lower-triangular Cholesky
# factor S of every covariance (P = S S') and performs predict/update
# as QR factorizations of stacked factor blocks (orthogonal
# transformations, cf. arXiv:2502.11686): covariances are PSD **by
# construction** and no Cholesky of a computed — possibly indefinite —
# matrix ever happens.  This is the numerically robust float32 path.


class SqrtFilterResult(NamedTuple):
    """Filter moments in square-root (Cholesky-factor) form.

    ``chol_p``/``chol_f`` are lower-triangular factors of the
    predicted/filtered covariances (``P = S S'``); keeping the factored
    form through downstream consumers (smoother, serving updates) is
    what preserves the PSD-by-construction guarantee end to end
    (cf. arXiv:2405.08971).
    """

    mean_p: jnp.ndarray  # (T, n)
    chol_p: jnp.ndarray  # (T, n, n) lower factor of the predicted cov
    mean_f: jnp.ndarray  # (T, n)
    chol_f: jnp.ndarray  # (T, n, n) lower factor of the filtered cov
    sigma: jnp.ndarray  # (T,)
    detf: jnp.ndarray  # (T,)


class SqrtSmootherResult(NamedTuple):
    mean_s: jnp.ndarray  # (T, n)
    chol_s: jnp.ndarray  # (T, n, n) lower factor of the smoothed cov


def _tria(blocks: jnp.ndarray) -> jnp.ndarray:
    """Lower-triangular ``L`` with ``L L' = B B'`` via QR of ``B'``.

    The orthogonal-transformation primitive of the square-root engine:
    ``B B'`` is never formed, so the result is a valid Cholesky factor
    (PSD by construction) even where the explicit product would come
    out indefinite in float32.  The diagonal is sign-normalized to be
    nonnegative (the factor is then the unique Cholesky factor when
    ``B`` has full row rank).  ``B`` is (n, k) with k >= n (QR of a
    wide transpose has no JAX derivative; callers with k < n pad zero
    columns instead — a rank-deficient but exact factor).
    """
    return _sign_normalize_rows(jnp.linalg.qr(blocks.T, mode="r")).T


def _sign_normalize_rows(r: jnp.ndarray) -> jnp.ndarray:
    """Flip rows of an upper-triangular QR factor so its diagonal is
    nonnegative (``R' R`` is invariant; the factor becomes the unique
    Cholesky factor where full-rank).  The single source of the sign
    convention for every square-root triangularization."""
    sign = jnp.sign(jnp.diagonal(r))
    sign = jnp.where(sign == 0, jnp.ones_like(sign), sign)
    return sign[:, None] * r


def _q_sqrt_diag(q: jnp.ndarray) -> jnp.ndarray:
    """(n,) elementwise sqrt of the (diagonal) process covariance.

    The square-root engines read ``Q^{1/2}`` off the diagonal — the
    only form the DFM builder emits.  A non-diagonal ``Q`` reaching a
    traced path (where :func:`_check_diagonal_q` cannot concretize)
    must never be *silently* truncated to its diagonal: the returned
    factor is NaN-poisoned instead, so moments come back NaN and the
    deviance books a loud ``+inf`` (the rejectable-step guard) rather
    than a plausible-but-wrong likelihood.  For the concrete/constant
    diagonal ``Q`` of the DFM, XLA folds the check away.
    """
    diag = jnp.diagonal(q)
    is_diag = jnp.all(q == jnp.diag(diag))
    return jnp.where(
        is_diag,
        jnp.sqrt(jnp.clip(diag, 0.0)),
        jnp.asarray(jnp.nan, q.dtype),
    )


def chol_outer(chol: jnp.ndarray) -> jnp.ndarray:
    """Reconstitute ``S S'`` from stacked factors (leading batch axes).

    The product is exactly symmetric and PSD up to the roundoff of one
    matmul — use only at true consumer boundaries; inside the engine the
    factored form is carried instead.
    """
    return jnp.einsum("...ij,...kj->...ik", chol, chol)


def _check_diagonal_q(q) -> None:
    """Reject concrete non-diagonal transition covariances.

    The square-root engine reads ``Q^{1/2}`` off the diagonal (the DFM
    builder only emits diagonal Q); a non-diagonal Q would silently
    drop process-noise correlations.  Tracers cannot be concretized —
    skipping the check under a trace is fine, same contract as
    :func:`sample_states`.
    """
    try:
        q_np = np.asarray(q)
    except (jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        return
    if np.abs(q_np - np.diag(np.diagonal(q_np))).max() > 0.0:
        raise ValueError(
            "the square-root engine requires a diagonal transition "
            "covariance Q (the DFM builder's form); got off-diagonal "
            "entries"
        )


def _make_sqrt_core_step(ss: StateSpace, dtype):
    """Predict+update body of one square-root filter timestep.

    Carries ``(mean, chol)`` with ``chol`` the lower Cholesky factor of
    the state covariance.  Predict stacks ``[Phi S | Q^{1/2}]`` and
    re-triangularizes; the update is the classical array algorithm: one
    QR of the pre-array

        [[ R^{1/2}     0   ]
         [ (Z S_p)'   S_p' ]]

    whose triangular result holds the innovation factor ``F^{1/2}``,
    the scaled gain ``Kbar = P Z' F^{-T/2}`` and the filtered factor —
    all PSD by construction, no Cholesky of a computed matrix anywhere.
    Masked slots carry unit pseudo-noise and zero Z rows, contributing
    exactly nothing to gain, ``sigma`` or ``detf`` (their innovation-
    factor diagonal is exactly 1).

    A step whose innovation factor degenerates (zero/non-finite
    diagonal — possible only when the model itself is degenerate, e.g.
    exactly-zero process noise on an observed slot) passes the state
    through and books ``detf = +inf``: the deviance becomes a
    rejectable ``+inf`` instead of NaN-poisoning the scan.
    """
    n = ss.phi.shape[-1]
    m = ss.z.shape[-2]
    eye_m = jnp.eye(m, dtype=dtype)
    q_sqrt = _q_sqrt_diag(ss.q).astype(dtype)
    zero = jnp.zeros((), dtype)
    inf = jnp.asarray(jnp.inf, dtype)

    def core(mean, chol, y_t, mask_t):
        mean_p = ss.phi * mean
        chol_p = _tria(jnp.concatenate(
            [ss.phi[:, None] * chol, jnp.diag(q_sqrt)], axis=1
        ))
        maskf = mask_t.astype(dtype)
        z_m = ss.z * maskf[:, None]
        r_t = jnp.where(mask_t, ss.r, 0.0) + (1.0 - maskf)
        v = jnp.where(mask_t, y_t - ss.z @ mean_p, 0.0)
        mean_f, chol_f, sigma, detf = _sqrt_qr_update(
            z_m, r_t, v, mean_p, chol_p, n, m, eye_m, zero, inf, dtype
        )
        return mean_p, chol_p, mean_f, chol_f, sigma, detf

    return core


def _sqrt_qr_update(z_m, r_t, v, mean_p, chol_p, n, m, eye_m, zero, inf,
                    dtype):
    """The QR array-update body of one square-root step.

    Shared verbatim by the plain core (:func:`_make_sqrt_core_step`)
    and the gated core (:func:`_make_gated_sqrt_core_step`), so the
    gate-off/never-hit paths of the gated kernels stay *bit-identical*
    to the plain ones — the gated callers only pre-transform the
    masked observation row (``z_m``/``r_t``/``v``) and those
    transforms are exact identities when no slot trips the gate.
    """
    pre = jnp.concatenate([
        jnp.concatenate(
            [jnp.diag(jnp.sqrt(r_t)), jnp.zeros((m, n), dtype)], axis=1
        ),
        jnp.concatenate([(z_m @ chol_p).T, chol_p.T], axis=1),
    ], axis=0)
    rfull = _sign_normalize_rows(jnp.linalg.qr(pre, mode="r"))
    fu = rfull[:m, :m]  # F^{1/2}' (upper)
    kbar = rfull[:m, m:].T  # P Z' F^{-T/2}
    chol_u = rfull[m:, m:].T  # filtered factor, PSD by construction
    d = jnp.diagonal(fu)
    ok = jnp.all(d > 0) & jnp.all(jnp.isfinite(rfull))
    fu_safe = jnp.where(ok, fu, eye_m)
    w = jax.scipy.linalg.solve_triangular(fu_safe.T, v, lower=True)
    mean_f = jnp.where(ok, mean_p + kbar @ w, mean_p)
    chol_f = jnp.where(ok, chol_u, chol_p)
    sigma = jnp.where(ok, jnp.sum(w * w), zero)
    detf = jnp.where(
        ok, 2.0 * jnp.sum(jnp.log(jnp.where(ok, d, 1.0))), inf
    )
    return mean_f, chol_f, sigma, detf


@functools.partial(jax.jit, static_argnames=("store",))
def _sqrt_kalman_filter(ss, y, mask, store):
    dtype = ss.q.dtype
    y = jnp.asarray(y, dtype)
    mask = jnp.asarray(mask, bool)
    core = _make_sqrt_core_step(ss, dtype)
    mean0, chol0 = _init_state(ss, dtype)  # identity factor == identity cov

    def step(carry, xs):
        mean, chol = carry
        y_t, mask_t = xs
        mean_p, chol_p, mean_f, chol_f, sigma, detf = core(
            mean, chol, y_t, mask_t
        )
        if store:
            out = (mean_p, chol_p, mean_f, chol_f, sigma, detf)
        else:
            out = (sigma, detf)
        return (mean_f, chol_f), out

    (mean_t, chol_t), outs = lax.scan(step, (mean0, chol0), (y, mask))
    if store:
        return SqrtFilterResult(*outs)
    sigma, detf = outs
    return SqrtFilterResult(mean_t, chol_t, mean_t, chol_t, sigma, detf)


def sqrt_kalman_filter(
    ss: StateSpace, y: jnp.ndarray, mask: jnp.ndarray, store: bool = True
) -> SqrtFilterResult:
    """Masked Kalman filter propagating Cholesky factors (QR updates).

    The ``engine="sqrt"`` workhorse: same recursion, masking and
    likelihood semantics as :func:`kalman_filter`, but every covariance
    is carried as its lower-triangular factor and updated by orthogonal
    transformations — PSD by construction, no ``cholesky`` of a
    computed matrix, hence no NaN path even when float32 roundoff would
    make the explicit innovation covariance indefinite (the
    near-unit-root ``phi -> 0.99997`` regime of
    ``tests/test_precision.py``).  Requires the DFM's diagonal ``Q``.

    ``store=False`` keeps only the final carry (loglik-only path,
    memory O(n^2) instead of O(T n^2)), mirroring
    :func:`kalman_filter`.
    """
    _check_diagonal_q(ss.q)
    return _sqrt_kalman_filter(ss, y, mask, bool(store))


def sqrt_filter_update(
    ss: StateSpace,
    mean: jnp.ndarray,
    chol: jnp.ndarray,
    y_t: jnp.ndarray,
    mask_t: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One online-assimilation step carrying a Cholesky factor.

    The square-root counterpart of :func:`filter_update` (the same
    ``_make_sqrt_core_step`` body the scan uses): given the filtered
    posterior ``N(mean, chol chol')`` at ``t-1`` and one observation
    row, returns ``(mean_f, chol_f, sigma, detf)`` with ``chol_f`` PSD
    by construction — the serving path's integrity gate collapses to a
    finiteness check (``serve.engine.posterior_fault``).
    """
    _check_diagonal_q(ss.q)
    return _sqrt_filter_update(ss, mean, chol, y_t, mask_t)


@jax.jit
def _sqrt_filter_update(ss, mean, chol, y_t, mask_t):
    dtype = ss.q.dtype
    core = _make_sqrt_core_step(ss, dtype)
    _, _, mean_f, chol_f, sigma, detf = core(
        jnp.asarray(mean, dtype), jnp.asarray(chol, dtype),
        jnp.asarray(y_t, dtype), jnp.asarray(mask_t, bool),
    )
    return mean_f, chol_f, sigma, detf


def sqrt_filter_append(
    ss: StateSpace,
    mean: jnp.ndarray,
    chol: jnp.ndarray,
    y_new: jnp.ndarray,
    mask_new: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Assimilate ``k`` appended rows carrying a Cholesky factor.

    Square-root counterpart of :func:`filter_append` — the incremental
    serving path in factored form.  Returns ``(mean_T, chol_T, sigma,
    detf)`` with per-step (k,) likelihood terms.
    """
    _check_diagonal_q(ss.q)
    return _sqrt_filter_append(ss, mean, chol, y_new, mask_new)


@jax.jit
def _sqrt_filter_append(ss, mean, chol, y_new, mask_new):
    dtype = ss.q.dtype
    y_new = jnp.atleast_2d(jnp.asarray(y_new, dtype))
    mask_new = jnp.atleast_2d(jnp.asarray(mask_new, bool))
    core = _make_sqrt_core_step(ss, dtype)

    def step(carry, xs):
        m, s = carry
        y_t, mask_t = xs
        _, _, mean_f, chol_f, sigma, detf = core(m, s, y_t, mask_t)
        return (mean_f, chol_f), (sigma, detf)

    (mean_t, chol_t), (sigma, detf) = lax.scan(
        step, (jnp.asarray(mean, dtype), jnp.asarray(chol, dtype)),
        (y_new, mask_new),
    )
    return mean_t, chol_t, sigma, detf


# ----------------------------------------------------------------------
# observation gating (statistical input robustness)
# ----------------------------------------------------------------------
#
# A finite observation is not necessarily a TRUE observation: a sensor
# spike, stuck gauge or unit-conversion error passes every finiteness
# check and then corrupts the posterior permanently (serving never
# refilters history, so there is no later pass to catch it).  Under the
# model, each slot's one-step-ahead normalized innovation z = v/sqrt(f)
# is standard normal, so z^2 has a known chi-square(1) null — exactly
# the statistic the offline Ljung-Box diagnostics standardize
# (metran_tpu/diagnostics.py) — and testing it ONLINE against a
# configurable gate lets the update defend itself (cf. the robust /
# heavy-tailed filtering argument of arXiv:2310.01122: outliers must be
# downweighted inside the update, not discovered post-mortem).  Three
# XLA-static policies for a slot whose z^2 exceeds nsigma^2:
#
# - ``reject``: treat the slot as missing for this step (no state
#   update, no likelihood contribution) — the hard gate;
# - ``huber``: scale the innovation by w = nsigma/|z| before the gain
#   is applied (full weight inside the clip point, decaying influence
#   beyond — the classical Huberized update);
# - ``inflate``: inflate that slot's observation variance so its
#   realized z^2 equals the gate (the update is tempered, never
#   discarded — the right choice when level shifts may be real).
#
# All three are value-identical (bit-exact) to the ungated kernels when
# the gate is off or never trips; ``armed`` (a traced scalar, per-model
# under vmap) lets a serving layer disarm the gate for cold models
# without recompiling.

#: gate policies accepted by the gated kernels (XLA-static).
GATE_POLICIES = ("off", "reject", "huber", "inflate")

#: per-slot verdict codes in the gated kernels' verdict output.
GATE_PASS = 0
GATE_DOWNWEIGHTED = 1
GATE_REJECTED = 2


def _gated_sequential_update(
    mean, cov, y, mask, z, r, dtype, policy, thresh, armed
):
    """Masked sequential update with per-slot innovation gating.

    The gated counterpart of :func:`_sequential_update` (same slot
    order, same rank-1 recursion): each observed slot's normalized
    innovation ``z_i = v/sqrt(f)`` is tested against the chi-square
    gate ``z_i^2 > thresh`` and the armed policy applied.  Every state
    and likelihood expression is written so that a slot that does NOT
    trip the gate computes the exact same floating-point operations as
    the ungated update — the bit-exactness contract
    (tests/test_gating.py).

    Returns ``(mean, cov, sigma, detf, zscore, verdict)`` with
    ``zscore`` (n_obs,) the signed normalized innovations (NaN where
    unobserved) and ``verdict`` (n_obs,) int8 per-slot codes
    (:data:`GATE_PASS`/:data:`GATE_DOWNWEIGHTED`/:data:`GATE_REJECTED`).
    """
    zero = jnp.zeros((), dtype)
    one = jnp.ones((), dtype)
    nan = jnp.asarray(jnp.nan, dtype)
    t = jnp.asarray(thresh, dtype)

    def step(carry, xs):
        m, p, sigma, detf = carry
        y_i, mask_i, z_i, r_i = xs
        v = y_i - z_i @ m
        d = p @ z_i
        f = z_i @ d + r_i
        f_safe = jnp.where(mask_i, f, one)
        zscore = v / jnp.sqrt(f_safe)
        score = zscore * zscore
        hit = armed & mask_i & (score > t)
        if policy == "reject":
            use = mask_i & ~hit
            k = d / f_safe
            m_new = m + k * v
            p_new = p - jnp.outer(k, k) * f_safe
            m = jnp.where(use, m_new, m)
            p = jnp.where(use, p_new, p)
            sigma = sigma + jnp.where(use, v * v / f_safe, zero)
            detf = detf + jnp.where(use, jnp.log(f_safe), zero)
        elif policy == "huber":
            # weight 1 inside the clip point, nsigma/|z| beyond; the
            # covariance update keeps full weight (the information
            # content of the slot is unchanged, only the innovation's
            # influence on the mean is clipped)
            w = jnp.where(hit, jnp.sqrt(t / score), one)
            vv = w * v
            k = d / f_safe
            m_new = m + k * vv
            p_new = p - jnp.outer(k, k) * f_safe
            m = jnp.where(mask_i, m_new, m)
            p = jnp.where(mask_i, p_new, p)
            sigma = sigma + jnp.where(mask_i, vv * vv / f_safe, zero)
            detf = detf + jnp.where(mask_i, jnp.log(f_safe), zero)
        else:  # "inflate"
            # inflate r so the realized v^2/f equals the gate: the
            # update proceeds with a tempered gain instead of being
            # discarded (f_eff = v^2/thresh > f exactly when hit)
            f_eff = jnp.where(hit, v * v / t, f_safe)
            k = d / f_eff
            m_new = m + k * v
            p_new = p - jnp.outer(k, k) * f_eff
            m = jnp.where(mask_i, m_new, m)
            p = jnp.where(mask_i, p_new, p)
            sigma = sigma + jnp.where(mask_i, v * v / f_eff, zero)
            detf = detf + jnp.where(mask_i, jnp.log(f_eff), zero)
        verdict = jnp.where(
            hit,
            GATE_REJECTED if policy == "reject" else GATE_DOWNWEIGHTED,
            GATE_PASS,
        ).astype(jnp.int8)
        return (m, p, sigma, detf), (
            jnp.where(mask_i, zscore, nan), verdict
        )

    (mean, cov, sigma, detf), (zs, verdicts) = lax.scan(
        step, (mean, cov, zero, zero), (y, mask, z, r)
    )
    return mean, cov, sigma, detf, zs, verdicts


def _make_gated_core_step(ss: StateSpace, dtype, policy, thresh, armed):
    """Predict + gated sequential update body of one filter timestep
    (the gated twin of :func:`_make_core_step`, sequential engine)."""

    def core(mean, cov, y_t, mask_t):
        mean_p, cov_p = _predict(mean, cov, ss.phi, ss.q)
        has_obs = jnp.any(mask_t)
        mean_f, cov_f, sigma, detf, zs, verdicts = (
            _gated_sequential_update(
                mean_p, cov_p, y_t, mask_t, ss.z, ss.r, dtype,
                policy, thresh, armed,
            )
        )
        mean_f = jnp.where(has_obs, mean_f, mean_p)
        cov_f = jnp.where(has_obs, cov_f, cov_p)
        return mean_f, cov_f, sigma, detf, zs, verdicts

    return core


def _make_gated_sqrt_core_step(ss: StateSpace, dtype, policy, thresh,
                               armed):
    """Predict + gated QR update body of one square-root timestep.

    Gating on the sqrt path uses each slot's *marginal* innovation
    variance off the predicted factor (``f_i = ||(Z S_p)_i||^2 + r_i``
    — the same vector-innovation definition :func:`innovations` uses),
    then pre-transforms the masked observation row and hands it to the
    SAME QR body the plain core runs (:func:`_sqrt_qr_update`):

    - ``reject`` re-derives the masked quantities under the post-gate
      mask (a rejected slot becomes a unit-pseudo-noise no-op slot);
    - ``huber`` scales the innovation per slot;
    - ``inflate`` adds ``v^2/thresh - f_i`` to the slot's ``r``.

    A slot that does not trip computes bit-identically to the plain
    core (the transforms are exact identities there).
    """
    n = ss.phi.shape[-1]
    m = ss.z.shape[-2]
    eye_m = jnp.eye(m, dtype=dtype)
    q_sqrt = _q_sqrt_diag(ss.q).astype(dtype)
    zero = jnp.zeros((), dtype)
    one = jnp.ones((), dtype)
    inf = jnp.asarray(jnp.inf, dtype)
    nan = jnp.asarray(jnp.nan, dtype)
    t = jnp.asarray(thresh, dtype)

    def core(mean, chol, y_t, mask_t):
        mean_p = ss.phi * mean
        chol_p = _tria(jnp.concatenate(
            [ss.phi[:, None] * chol, jnp.diag(q_sqrt)], axis=1
        ))
        maskf = mask_t.astype(dtype)
        z_m = ss.z * maskf[:, None]
        r_t = jnp.where(mask_t, ss.r, 0.0) + (1.0 - maskf)
        v = jnp.where(mask_t, y_t - ss.z @ mean_p, 0.0)
        f_diag = jnp.sum((z_m @ chol_p) ** 2, axis=-1) + r_t
        zscore = v / jnp.sqrt(f_diag)
        score = zscore * zscore
        hit = armed & mask_t & (score > t)
        if policy == "reject":
            use = mask_t & ~hit
            usef = use.astype(dtype)
            z_u = ss.z * usef[:, None]
            r_u = jnp.where(use, ss.r, 0.0) + (1.0 - usef)
            v_u = jnp.where(use, y_t - ss.z @ mean_p, 0.0)
            upd = _sqrt_qr_update(
                z_u, r_u, v_u, mean_p, chol_p, n, m, eye_m, zero, inf,
                dtype,
            )
        elif policy == "huber":
            w_i = jnp.where(hit, jnp.sqrt(t / score), one)
            upd = _sqrt_qr_update(
                z_m, r_t, w_i * v, mean_p, chol_p, n, m, eye_m, zero,
                inf, dtype,
            )
        else:  # "inflate"
            # v^2/thresh > f_i exactly when hit, so the added term is
            # positive and sqrt(r_eff) stays well-defined
            r_i = jnp.where(hit, r_t + (v * v / t - f_diag), r_t)
            upd = _sqrt_qr_update(
                z_m, r_i, v, mean_p, chol_p, n, m, eye_m, zero, inf,
                dtype,
            )
        mean_f, chol_f, sigma, detf = upd
        verdict = jnp.where(
            hit,
            GATE_REJECTED if policy == "reject" else GATE_DOWNWEIGHTED,
            GATE_PASS,
        ).astype(jnp.int8)
        return (mean_p, chol_p, mean_f, chol_f, sigma, detf,
                jnp.where(mask_t, zscore, nan), verdict)

    return core


def gated_filter_append(
    ss: StateSpace,
    mean: jnp.ndarray,
    cov: jnp.ndarray,
    y_new: jnp.ndarray,
    mask_new: jnp.ndarray,
    armed=True,
    policy: str = "reject",
    nsigma: float = 4.0,
) -> Tuple[jnp.ndarray, ...]:
    """:func:`filter_append` with per-slot online innovation gating.

    Sequential-processing engine only (the gate is a per-slot test, so
    the slots must be conditioned one at a time; a serving bucket on
    the ``joint`` engine that arms the gate switches to this kernel —
    same posterior to float tolerance).  ``policy``/``nsigma`` are
    XLA-static; ``armed`` is traced (a scalar bool, per-model under
    ``vmap``) so a serving layer can disarm cold models per slot
    without recompiling.

    Returns ``(mean_T, cov_T, sigma, detf, zscore, verdict)``:
    the first four exactly as :func:`filter_append`, plus the per-step
    (k, n_obs) signed normalized innovations (NaN where unobserved)
    and int8 verdicts (:data:`GATE_PASS`/:data:`GATE_DOWNWEIGHTED`/
    :data:`GATE_REJECTED`).

    Contract: with ``policy="off"`` — or an armed gate that never
    trips (``nsigma=inf``, or clean data) — the posterior and
    likelihood outputs are bit-identical to :func:`filter_append`
    with ``engine="sequential"``.
    """
    if policy not in GATE_POLICIES:
        raise ValueError(
            f"unknown gate policy {policy!r}; expected one of "
            f"{GATE_POLICIES}"
        )
    return _gated_filter_append(
        ss, mean, cov, y_new, mask_new, jnp.asarray(armed, bool),
        policy=policy, nsigma=float(nsigma),
    )


@functools.partial(jax.jit, static_argnames=("policy", "nsigma"))
def _gated_filter_append(ss, mean, cov, y_new, mask_new, armed, *,
                         policy, nsigma):
    dtype = ss.q.dtype
    y_new = jnp.atleast_2d(jnp.asarray(y_new, dtype))
    mask_new = jnp.atleast_2d(jnp.asarray(mask_new, bool))
    if policy == "off":
        # the plain core, verbatim (bit-exactness by construction);
        # scores/verdicts come back NaN/PASS
        core = _make_core_step(ss, "sequential", dtype)

        def step(carry, xs):
            m, p = carry
            y_t, mask_t = xs
            _, _, mean_f, cov_f, sigma, detf = core(m, p, y_t, mask_t)
            return (mean_f, cov_f), (sigma, detf)

        (mean_t, cov_t), (sigma, detf) = lax.scan(
            step, (jnp.asarray(mean, dtype), jnp.asarray(cov, dtype)),
            (y_new, mask_new),
        )
        return (
            mean_t, cov_t, sigma, detf,
            jnp.full(y_new.shape, jnp.nan, dtype),
            jnp.zeros(y_new.shape, jnp.int8),
        )
    core = _make_gated_core_step(
        ss, dtype, policy, nsigma * nsigma, armed
    )

    def step(carry, xs):
        m, p = carry
        y_t, mask_t = xs
        mean_f, cov_f, sigma, detf, zs, verdicts = core(
            m, p, y_t, mask_t
        )
        return (mean_f, cov_f), (sigma, detf, zs, verdicts)

    (mean_t, cov_t), (sigma, detf, zs, verdicts) = lax.scan(
        step, (jnp.asarray(mean, dtype), jnp.asarray(cov, dtype)),
        (y_new, mask_new),
    )
    return mean_t, cov_t, sigma, detf, zs, verdicts


def gated_sqrt_filter_append(
    ss: StateSpace,
    mean: jnp.ndarray,
    chol: jnp.ndarray,
    y_new: jnp.ndarray,
    mask_new: jnp.ndarray,
    armed=True,
    policy: str = "reject",
    nsigma: float = 4.0,
) -> Tuple[jnp.ndarray, ...]:
    """:func:`sqrt_filter_append` with per-slot online innovation gating.

    Square-root counterpart of :func:`gated_filter_append` — carries a
    Cholesky factor, gates on the marginal normalized innovations off
    the predicted factor, and keeps the PSD-by-construction guarantee
    for every policy (all three only pre-transform the observation row
    fed to the same orthogonal QR update).

    Returns ``(mean_T, chol_T, sigma, detf, zscore, verdict)``; same
    bit-exactness contract as :func:`gated_filter_append`, against
    :func:`sqrt_filter_append`.
    """
    if policy not in GATE_POLICIES:
        raise ValueError(
            f"unknown gate policy {policy!r}; expected one of "
            f"{GATE_POLICIES}"
        )
    _check_diagonal_q(ss.q)
    return _gated_sqrt_filter_append(
        ss, mean, chol, y_new, mask_new, jnp.asarray(armed, bool),
        policy=policy, nsigma=float(nsigma),
    )


@functools.partial(jax.jit, static_argnames=("policy", "nsigma"))
def _gated_sqrt_filter_append(ss, mean, chol, y_new, mask_new, armed, *,
                              policy, nsigma):
    dtype = ss.q.dtype
    y_new = jnp.atleast_2d(jnp.asarray(y_new, dtype))
    mask_new = jnp.atleast_2d(jnp.asarray(mask_new, bool))
    if policy == "off":
        core = _make_sqrt_core_step(ss, dtype)

        def step(carry, xs):
            m, s = carry
            y_t, mask_t = xs
            _, _, mean_f, chol_f, sigma, detf = core(m, s, y_t, mask_t)
            return (mean_f, chol_f), (sigma, detf)

        (mean_t, chol_t), (sigma, detf) = lax.scan(
            step, (jnp.asarray(mean, dtype), jnp.asarray(chol, dtype)),
            (y_new, mask_new),
        )
        return (
            mean_t, chol_t, sigma, detf,
            jnp.full(y_new.shape, jnp.nan, dtype),
            jnp.zeros(y_new.shape, jnp.int8),
        )
    core = _make_gated_sqrt_core_step(
        ss, dtype, policy, nsigma * nsigma, armed
    )

    def step(carry, xs):
        m, s = carry
        y_t, mask_t = xs
        _, _, mean_f, chol_f, sigma, detf, zs, verdicts = core(
            m, s, y_t, mask_t
        )
        return (mean_f, chol_f), (sigma, detf, zs, verdicts)

    (mean_t, chol_t), (sigma, detf, zs, verdicts) = lax.scan(
        step, (jnp.asarray(mean, dtype), jnp.asarray(chol, dtype)),
        (y_new, mask_new),
    )
    return mean_t, chol_t, sigma, detf, zs, verdicts


# ----------------------------------------------------------------------
# steady-state serving (bounded-cost hot path)
# ----------------------------------------------------------------------
#
# For a time-invariant model with a fixed missing pattern, the Kalman
# covariance recursion converges to the stabilizing solution of the
# discrete algebraic Riccati equation (DARE) and the gain freezes with
# it — after which every update's covariance work (the QR of stacked
# factor blocks, the O(S^3) part of a serving step) recomputes the same
# numbers.  The utilities here let a serving layer collapse the hot
# path to an O(S·N) mean-only recursion once a model has converged
# (the calibrated-approximation framing of arXiv:2405.08971: spend the
# covariance compute ONCE, serve from the frozen summary, and fall
# back to the exact kernel the moment time-invariance breaks):
#
# - :func:`dare_solve`: the steady predicted covariance, by
#   Newton-Kleinman iteration with each Lyapunov solve evaluated by
#   doubling (quadratic convergence; handles the DFM's exact r = 0
#   observation noise, where the classical symplectic/SDA doubling
#   needs R^{-1} and cannot start);
# - :func:`steady_gains`: the frozen per-slot gain, innovation
#   variances and steady filtered covariance derived from it;
# - :func:`steady_filter_append`: the frozen-gain mean recursion over
#   k appended rows, with on-kernel detection of every condition that
#   breaks time-invariance (missing slots, a tripped observation gate)
#   so the caller can thaw back to the exact kernel.


class SteadyGains(NamedTuple):
    """The frozen serving summary of a converged filter.

    ``kgain`` is the steady Kalman gain ``K = P Z' F^{-1}`` (S, N) for
    the fully-observed pattern, ``fdiag`` the (N,) marginal innovation
    variances ``diag(F)`` with padded (zero-``Z``-row) slots carrying
    1.0, ``p_pred``/``p_filt`` the steady predicted and filtered state
    covariances.  ``kgain_seq``/``fdiag_seq`` are the frozen
    SEQUENTIAL-PROCESSING per-slot quantities — the rank-1 gain and
    conditional innovation variance of each slot GIVEN the slots
    before it, read off the same per-slot recursion the sequential
    filter runs, evaluated at the fixed point.  At the steady state
    these are constants too, and they are what a frozen gate on a
    sequential-gated (covariance-engine) serving path must test
    against: the conditional variances are smaller than the marginal
    ones, so gating on marginals would silently pass observations the
    exact kernel rejects (square-root engines gate on marginals by
    design, so they use ``fdiag``).  Everything a steady-path update
    or forecast needs; nothing depends on the data, so it is computed
    once per model at freeze time and reused for every subsequent
    step.
    """

    kgain: jnp.ndarray  # (S, N)
    fdiag: jnp.ndarray  # (N,)
    p_pred: jnp.ndarray  # (S, S)
    p_filt: jnp.ndarray  # (S, S)
    kgain_seq: jnp.ndarray  # (S, N) per-slot sequential gains
    fdiag_seq: jnp.ndarray  # (N,) per-slot conditional variances


def _real_slots(z: jnp.ndarray) -> jnp.ndarray:
    """(N,) True where an observation slot is real (nonzero ``Z`` row).

    Correct for TRUE-dimension state spaces (the DFM observation
    matrix is ``[I | Λ]`` — every real series owns an identity
    column).  NOT correct for bucket-PADDED state spaces: the padded
    layout keeps the identity block over all ``n_pad`` sdf slots, so a
    padded slot's ``Z`` row is nonzero too — padded-bucket callers
    (the serving kernels) must pass their explicit ``real`` mask from
    the host-side series counts instead.
    """
    return jnp.any(z != 0.0, axis=-1)


@functools.partial(
    jax.jit, static_argnames=("newton_iters", "doubling_iters")
)
def dare_solve(
    ss: StateSpace,
    newton_iters: int = 24,
    doubling_iters: int = 32,
) -> jnp.ndarray:
    """Steady-state *predicted* covariance of the masked filter (DARE).

    Solves ``P = Phi (P - P Z'(Z P Z' + R)^{-1} Z P) Phi' + Q`` for the
    fully-observed missing pattern (padded zero-``Z``-row slots carry
    the same unit pseudo-noise the masked update gives them, so they
    contribute exactly nothing) by **Newton-Kleinman iteration with
    doubled Lyapunov solves**:

    - each Newton step fixes the gain ``K_j = P_j Z' F_j^{-1}`` and
      solves the Joseph-form Lyapunov equation ``P = A P A' + B`` with
      ``A = Phi (I - K_j Z)`` and ``B = Phi K_j R K_j' Phi' + Q``;
    - each Lyapunov solve runs the classical doubling recursion
      ``S <- S + M S M'``, ``M <- M M`` (``2^m`` series terms after
      ``m`` steps), so even the near-unit-root regime (``phi ->
      0.99997``, contraction 1 - 3e-5 per step) converges inside the
      fixed iteration budget — ``2^32`` effective steps.

    Newton-Kleinman converges quadratically from any stabilizing gain;
    ``K_0 = 0`` is stabilizing because the DFM transition is strictly
    stable (``|phi| < 1``).  Unlike the symplectic/SDA doubling it
    never forms ``R^{-1}``, which does not exist for the DFM (exact
    observations, ``r = 0``).  Fixed iteration counts keep it jittable
    and vmappable; with f64 inputs the fixed point is tight to ~1e-14
    and the unit test pins 1e-10 against the filter-converged
    covariance across all alpha regimes (tests/test_steady.py).
    """
    dtype = ss.q.dtype
    phi, q, z, r = ss.phi, ss.q, ss.z, ss.r
    s_dim = phi.shape[-1]
    eye = jnp.eye(s_dim, dtype=dtype)
    real = _real_slots(z)
    realf = real.astype(dtype)
    z_m = z * realf[:, None]
    # unit pseudo-noise on padded slots (the masked-update convention):
    # their F rows become e_i, their gain columns exactly zero
    r_eff = jnp.where(real, r, 0.0) + (1.0 - realf)

    def lyap(a, b):
        """Fixed point of ``X = a X a' + b`` by doubling."""

        def body(carry, _):
            m, s = carry
            s = s + m @ s @ m.T
            s = 0.5 * (s + s.T)
            return (m @ m, s), None

        (_, s), _ = lax.scan(
            body, (a, b), None, length=doubling_iters
        )
        return s

    p0 = lyap(jnp.diag(phi), q)  # K = 0: the stationary prior

    def newton(p, _):
        f = z_m @ p @ z_m.T + jnp.diag(r_eff)
        chol = jnp.linalg.cholesky(0.5 * (f + f.T))
        kt = jax.scipy.linalg.cho_solve((chol, True), z_m @ p)  # K'
        a = phi[:, None] * (eye - kt.T @ z_m)
        b = (
            phi[:, None]
            * ((kt.T * r_eff[None, :]) @ kt)
            * phi[None, :]
            + q
        )
        p_new = lyap(a, b)
        return 0.5 * (p_new + p_new.T), None

    p, _ = lax.scan(newton, p0, None, length=newton_iters)
    return p


@jax.jit
def steady_gains(
    ss: StateSpace, p_pred: Optional[jnp.ndarray] = None
) -> SteadyGains:
    """The frozen serving summary from a steady predicted covariance.

    ``p_pred`` defaults to :func:`dare_solve`'s fixed point.  Padded
    (zero-``Z``-row) slots get unit innovation variance and an exactly
    zero gain column, matching the masked update's no-op semantics, so
    the returned arrays are safe to use at any bucket padding.
    """
    if p_pred is None:
        p_pred = dare_solve(ss)
    dtype = ss.q.dtype
    z, r = ss.z, ss.r
    real = _real_slots(z)
    realf = real.astype(dtype)
    z_m = z * realf[:, None]
    r_eff = jnp.where(real, r, 0.0) + (1.0 - realf)
    f = z_m @ p_pred @ z_m.T + jnp.diag(r_eff)
    chol = jnp.linalg.cholesky(0.5 * (f + f.T))
    kt = jax.scipy.linalg.cho_solve((chol, True), z_m @ p_pred)
    kgain = kt.T
    p_filt = p_pred - kgain @ f @ kt
    p_filt = 0.5 * (p_filt + p_filt.T)

    # the frozen sequential-processing per-slot quantities: the same
    # rank-1 recursion _sequential_update runs, evaluated at P∞ (a
    # padded slot's zero Z row gives f = 1, gain exactly 0 — a no-op)
    def seq_step(p, xs):
        z_i, r_i = xs
        d = p @ z_i
        f_i = z_i @ d + r_i
        k_i = d / f_i
        return p - jnp.outer(k_i, k_i) * f_i, (k_i, f_i)

    _, (ks, fs) = lax.scan(seq_step, p_pred, (z_m, r_eff))
    return SteadyGains(
        kgain=kgain,
        fdiag=jnp.diagonal(f),
        p_pred=p_pred,
        p_filt=p_filt,
        kgain_seq=ks.T,
        fdiag_seq=fs,
    )


def steady_filter_append(
    ss: StateSpace,
    mean: jnp.ndarray,
    kgain: jnp.ndarray,
    fdiag: jnp.ndarray,
    y_new: jnp.ndarray,
    mask_new: jnp.ndarray,
    armed=True,
    policy: str = "off",
    nsigma: float = 4.0,
    real=None,
    sequential_gate: bool = False,
) -> Tuple[jnp.ndarray, ...]:
    """Assimilate ``k`` appended rows through the FROZEN steady gain.

    The bounded-cost serving hot path: a mean-only recursion
    ``m <- Phi m + K (y - Z Phi m)`` per step — O(S·N), no QR, no
    covariance propagation at all — valid exactly when the model is at
    its steady state and every step keeps the fully-observed pattern
    the gain was solved for.  Branch-free: every condition that breaks
    that premise is *detected* (the sticky ``broke`` flag) rather than
    branched on, and a broken row's result is simply discarded by the
    caller, which replays the rows through the exact kernel (thaw —
    ``serve/engine.py``).  ``broke`` trips on:

    - any step whose mask differs from the full real-slot pattern
      (missing/NaN-masked observations — the covariance would have
      widened);
    - an armed observation gate firing under ``policy="reject"`` or
      ``"inflate"`` (both modify the covariance recursion; ``huber``
      only reweights the mean innovation, so the frozen gain absorbs
      it exactly and serving stays steady);
    - a non-finite mean result.

    ``sequential_gate`` selects which frozen gate the kernel applies
    (it must MATCH the exact kernel the model would thaw back to):

    - ``False`` (default): vector form — one fused matvec per step
      through ``kgain``/``fdiag`` = the JOINT gain and *marginal*
      innovation variances (:class:`SteadyGains` ``.kgain``/
      ``.fdiag``).  This is the right gate for square-root serving
      paths, whose exact gated kernel tests marginal innovations by
      design, and for any ungated path.
    - ``True``: per-slot form — the same slot-ordered rank-1
      recursion :func:`_gated_sequential_update` runs, through the
      frozen per-slot gains and CONDITIONAL variances
      (``.kgain_seq``/``.fdiag_seq``).  The right gate for
      covariance-engine (sequential-gated) serving paths: conditional
      variances are smaller than marginal ones, so the vector gate
      would silently pass observations the exact kernel rejects.
      Same O(S·N) flops per step, scanned instead of fused.

    ``sigma``/``detf`` and z-scores come from the corresponding
    frozen variances — steady-state diagnostics; the posterior MEAN
    is the quantity with an equivalence contract (frozen ≡ exact
    within the freeze tolerance, tests/test_steady.py; with no gate
    hit the two forms are the same affine map, associativity aside).

    Returns ``(mean_T, sigma, detf, broke, zscore, verdict)`` with
    ``zscore``/``verdict`` shaped (k, N) like the gated kernels'.

    ``real`` is the (N,) true-observation-slot mask the full pattern
    is tested against; defaults to the nonzero-``Z``-row slots —
    correct for true-dimension state spaces, while bucket-PADDED
    callers must pass theirs explicitly (see :func:`_real_slots`).
    """
    if policy not in GATE_POLICIES:
        raise ValueError(
            f"unknown gate policy {policy!r}; expected one of "
            f"{GATE_POLICIES}"
        )
    if real is None:
        real = _real_slots(ss.z)
    return _steady_filter_append(
        ss, mean, kgain, fdiag, y_new, mask_new,
        jnp.asarray(armed, bool), jnp.asarray(real, bool),
        policy=policy, nsigma=float(nsigma),
        sequential_gate=bool(sequential_gate),
    )


@functools.partial(
    jax.jit, static_argnames=("policy", "nsigma", "sequential_gate")
)
def _steady_filter_append(ss, mean, kgain, fdiag, y_new, mask_new,
                          armed, real, *, policy, nsigma,
                          sequential_gate=False):
    dtype = ss.q.dtype
    y_new = jnp.atleast_2d(jnp.asarray(y_new, dtype))
    mask_new = jnp.atleast_2d(jnp.asarray(mask_new, bool))
    kgain = jnp.asarray(kgain, dtype)
    fdiag = jnp.asarray(fdiag, dtype)
    f_safe = jnp.where(fdiag > 0, fdiag, 1.0)
    sqrt_f = jnp.sqrt(f_safe)
    log_f = jnp.where(real, jnp.log(f_safe), 0.0)
    zero = jnp.zeros((), dtype)
    one = jnp.ones((), dtype)
    nan = jnp.asarray(jnp.nan, dtype)
    t = jnp.asarray(nsigma * nsigma, dtype)
    verdict_hit = (
        GATE_REJECTED if policy == "reject" else GATE_DOWNWEIGHTED
    )

    if sequential_gate and policy != "off":
        # per-slot form: the frozen twin of _gated_sequential_update
        # (same slot order, same interim-mean innovations) with the
        # per-slot gains/conditional variances constants
        kgain_cols = kgain.T  # (N, S): slot i's rank-1 gain

        def step(carry, xs):
            m, sigma, detf, broke = carry
            y_t, mask_t = xs
            m_p = ss.phi * m
            full = jnp.all(mask_t == real)

            def slot(c, s_xs):
                m_s, sig, det, gate_break = c
                y_i, mask_i, z_i, k_i, f_i, lf_i = s_xs
                v = y_i - z_i @ m_s
                zsc = v / jnp.sqrt(f_i)
                score = zsc * zsc
                hit = armed & mask_i & (score > t)
                if policy == "huber":
                    w = jnp.where(
                        hit,
                        jnp.sqrt(t / jnp.where(hit, score, one)), one,
                    )
                else:  # reject/inflate break the frozen recursion
                    w = one
                    gate_break = gate_break | hit
                wv = w * v
                m_s = jnp.where(mask_i, m_s + k_i * wv, m_s)
                sig = sig + jnp.where(mask_i, wv * wv / f_i, zero)
                det = det + jnp.where(mask_i, lf_i, zero)
                verdict = jnp.where(
                    hit, verdict_hit, GATE_PASS
                ).astype(jnp.int8)
                return (m_s, sig, det, gate_break), (
                    jnp.where(mask_i, zsc, nan), verdict
                )

            (m_f, sigma, detf, gate_break), (zs_t, verd_t) = lax.scan(
                slot, (m_p, sigma, detf, jnp.zeros((), bool)),
                (y_t, mask_t, ss.z, kgain_cols, f_safe, log_f),
            )
            broke = broke | ~full | gate_break
            return (m_f, sigma, detf, broke), (zs_t, verd_t)

    else:

        def step(carry, xs):
            m, sigma, detf, broke = carry
            y_t, mask_t = xs
            m_p = ss.phi * m
            v = jnp.where(mask_t, y_t - ss.z @ m_p, 0.0)
            zs = v / sqrt_f
            score = zs * zs
            full = jnp.all(mask_t == real)
            if policy == "off":
                hit = jnp.zeros_like(mask_t)
                w = jnp.ones_like(v)
                gate_break = jnp.zeros((), bool)
            else:
                hit = armed & mask_t & (score > t)
                if policy == "huber":
                    w = jnp.where(
                        hit,
                        jnp.sqrt(t / jnp.where(hit, score, one)), one,
                    )
                    gate_break = jnp.zeros((), bool)
                else:  # reject/inflate change the covariance recursion
                    w = jnp.ones_like(v)
                    gate_break = jnp.any(hit)
            wv = w * v
            m_f = m_p + kgain @ wv
            sigma = sigma + jnp.sum(
                jnp.where(mask_t, wv * wv / f_safe, zero)
            )
            detf = detf + jnp.sum(jnp.where(mask_t, log_f, zero))
            broke = broke | ~full | gate_break
            verdict = jnp.where(
                hit, verdict_hit, GATE_PASS
            ).astype(jnp.int8)
            return (m_f, sigma, detf, broke), (
                jnp.where(mask_t, zs, nan), verdict
            )

    (mean_t, sigma, detf, broke), (zs, verdicts) = lax.scan(
        step,
        (jnp.asarray(mean, dtype), zero, zero, jnp.zeros((), bool)),
        (y_new, mask_new),
    )
    broke = broke | ~jnp.all(jnp.isfinite(mean_t))
    return mean_t, sigma, detf, broke, zs, verdicts


def steady_converged(
    fac_before: jnp.ndarray,
    fac_after: jnp.ndarray,
    mask: jnp.ndarray,
    real: jnp.ndarray,
    tol,
) -> jnp.ndarray:
    """Per-row convergence verdict of one batched exact update.

    ``True`` where (a) every appended step carried the FULL real-slot
    observation pattern (time-invariance — a masked step widens the
    covariance again) and (b) the posterior factor/covariance moved by
    at most ``tol`` (max-abs over the (S, S) block) across the whole
    append.  All leading axes batched: ``fac`` is (..., S, S), ``mask``
    (..., k, N), ``real`` the (..., N) true-observation-slot flags
    (from the host-side series counts — a padded bucket's ``Z`` rows
    cannot distinguish padding, see :func:`_real_slots`).  The
    on-device half of steady-state detection — the serving layer ANDs
    in its host-side conditions (``t_seen`` floor, no gate verdicts)
    before freezing.
    """
    full = jnp.all(mask == real[..., None, :], axis=(-2, -1))
    delta = jnp.max(jnp.abs(fac_after - fac_before), axis=(-2, -1))
    return full & (delta <= tol) & jnp.isfinite(delta)


# ----------------------------------------------------------------------
# fixed-lag smoothing (recent-window products at O(L) cost)
# ----------------------------------------------------------------------


def fixed_lag_smooth(
    ss: StateSpace,
    mean: jnp.ndarray,
    chol: jnp.ndarray,
    y_win: jnp.ndarray,
    mask_win: jnp.ndarray,
) -> SqrtSmootherResult:
    """Smoothed state moments for the trailing ``L``-step window.

    Runs the square-root filter over ONLY the ``L`` windowed rows,
    starting from the carried filtered posterior ``N(mean, chol chol')``
    at the step before the window, then the square-root RTS smoother
    backward across the window — O(L) work however long the full
    history is.  Because the filter is Markov, the windowed forward
    pass reproduces the full filter's moments for those steps exactly
    (same ``_make_sqrt_core_step`` body, same carry), and RTS smoothing
    at step ``t`` depends only on filtered/predicted moments from ``t``
    forward — so the result is **bit-identical (f64) to running the
    full filter + smoother over the entire history and slicing its
    last ``L`` steps** (tests/test_steady.py pins this).  The one
    approximation a fixed-lag product carries is the window boundary
    itself: steps older than the window are not revised.

    Returns the smoothed means (L, S) and covariance factors
    (L, S, S), PSD by construction like every square-root path.
    """
    _check_diagonal_q(ss.q)
    return _fixed_lag_smooth(ss, mean, chol, y_win, mask_win)


@jax.jit
def _fixed_lag_smooth(ss, mean, chol, y_win, mask_win):
    dtype = ss.q.dtype
    y_win = jnp.atleast_2d(jnp.asarray(y_win, dtype))
    mask_win = jnp.atleast_2d(jnp.asarray(mask_win, bool))
    core = _make_sqrt_core_step(ss, dtype)

    def step(carry, xs):
        m, s = carry
        y_t, mask_t = xs
        mean_p, chol_p, mean_f, chol_f, sigma, detf = core(
            m, s, y_t, mask_t
        )
        return (mean_f, chol_f), (mean_p, chol_p, mean_f, chol_f,
                                  sigma, detf)

    (_, _), outs = lax.scan(
        step, (jnp.asarray(mean, dtype), jnp.asarray(chol, dtype)),
        (y_win, mask_win),
    )
    filt = SqrtFilterResult(*outs)
    return sqrt_rts_smoother(ss, filt)


def deviance_terms(
    sigma: jnp.ndarray, detf: jnp.ndarray, mask: jnp.ndarray, warmup: int = 1
) -> jnp.ndarray:
    """Combine per-timestep filter terms into the reference's MLE objective.

    Implements ``SPKalmanFilter.get_mle`` (``metran/kalmanfilter.py:550-567``)
    under static shapes: ``sigma``/``detf`` sums skip the first ``warmup``
    *observed* timesteps (the reference slices its compressed per-observed-
    timestep arrays), while ``nobs`` skips the first ``warmup`` *grid*
    timesteps.
    """
    mask = jnp.asarray(mask, bool)
    count = jnp.sum(mask, axis=-1)
    has_obs = count > 0
    # rank of each timestep among observed timesteps (0-based), for skipping
    # the first `warmup` observed ones
    obs_rank = jnp.cumsum(has_obs, axis=-1) - 1
    keep = has_obs & (obs_rank >= warmup)
    nobs = jnp.sum(jnp.where(jnp.arange(count.shape[-1]) >= warmup, count, 0))
    dtype = sigma.dtype
    return (
        nobs.astype(dtype) * jnp.asarray(LOG2PI, dtype)
        + jnp.sum(jnp.where(keep, detf, 0.0))
        + jnp.sum(jnp.where(keep, sigma, 0.0))
    )


def _scan_likelihood_engine(ss, engine, dtype):
    """Engine-agnostic ``(carry0, step)`` pair for likelihood-only scans.

    The covariance engines carry ``(mean, cov)``, the square-root
    engine ``(mean, chol)`` — both initialize at ``(0, I)`` and share
    the step signature, so the segmented remat scan and the plain
    loglik scan stay engine-generic.
    """
    core = (
        _make_sqrt_core_step(ss, dtype)
        if engine == "sqrt"
        else _make_core_step(ss, engine, dtype)
    )
    carry0 = _init_state(ss, dtype)

    def step(carry, xs):
        y_t, mask_t = xs
        _, _, mean_f, cov_f, sigma, detf = core(
            carry[0], carry[1], y_t, mask_t
        )
        return (mean_f, cov_f), (sigma, detf)

    return carry0, step


def _finite_or_inf(total):
    """Map a non-finite deviance to ``+inf``.

    ``+inf`` is a *rejectable* line-search value — Armijo comparisons
    against it fail and the optimizer backs off — whereas a NaN
    objective poisons the L-BFGS memory and every later iteration
    (``run_lbfgs(raise_on_divergence=True)`` only catches that after
    the fact).  Gradients at such points are meaningless (possibly
    NaN); the value alone is what rejects the step.
    """
    return jnp.where(
        jnp.isfinite(total), total, jnp.asarray(jnp.inf, total.dtype)
    )


def _deviance_terms_remat(ss, y, mask, engine, remat_seg):
    """Per-timestep (sigma, detf) via a segmented, checkpointed scan.

    Time is split into segments of ``remat_seg`` steps (padded with
    all-masked no-op steps); each segment body is wrapped in
    ``jax.checkpoint`` so the backward pass stores only O(T/seg) segment
    carries plus one segment of step residuals instead of O(T) — the
    rematerialization recipe that lets fleet batches of hundreds of
    models fit in HBM under autodiff.  Padded trailing steps carry
    ``mask=False`` everywhere, so they contribute exactly zero to both
    sums (same no-op semantics the masked filter gives missing rows).
    """
    dtype = ss.q.dtype
    y = jnp.asarray(y, dtype)
    mask = jnp.asarray(mask, bool)
    t_steps = y.shape[0]
    (mean0, cov0), step = _scan_likelihood_engine(ss, engine, dtype)

    pad = (-t_steps) % remat_seg
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad,) + y.shape[1:], dtype)])
        mask = jnp.concatenate(
            [mask, jnp.zeros((pad,) + mask.shape[1:], bool)]
        )
    y_seg = y.reshape(-1, remat_seg, *y.shape[1:])
    m_seg = mask.reshape(-1, remat_seg, *mask.shape[1:])

    @jax.checkpoint
    def seg_body(carry, xs):
        return lax.scan(step, carry, xs)

    _, (sigma, detf) = lax.scan(seg_body, (mean0, cov0), (y_seg, m_seg))
    return sigma.reshape(-1)[:t_steps], detf.reshape(-1)[:t_steps]


def deviance(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    warmup: int = 1,
    engine: str = "sequential",
    remat_seg: Optional[int] = None,
    grad: Optional[str] = None,
) -> jnp.ndarray:
    """-2 log-likelihood (the quantity the reference minimizes).

    ``remat_seg`` (e.g. 100) evaluates the filter as a segmented
    checkpointed scan, cutting autodiff residual memory from O(T n^2) to
    O(seg n^2) at the cost of one extra forward recompute in the
    backward pass; results are identical to the plain scan.

    ``grad`` selects how this value differentiates (docs/concepts.md
    "Gradient engine"): ``"adjoint"`` attaches the closed-form
    Kalman-score VJP (:mod:`metran_tpu.ops.adjoint` — one cheap
    covariance-form reverse sweep, no autodiff through QR/Cholesky,
    cotangents for the transition parameters only), ``"autodiff"``
    keeps reverse-mode autodiff through the scan (required for
    gradients w.r.t. loadings/observations, and for anything that
    forward-differentiates the result — ``jax.hessian`` included),
    ``"auto"`` picks the adjoint where it is defined.  ``None``
    (default) reads the configured mode
    (:func:`metran_tpu.config.grad_engine`, env
    ``METRAN_TPU_GRAD_ENGINE``) at trace time.  The VALUE is
    bit-identical across modes; only the gradient path changes (in
    adjoint mode ``remat_seg`` maps onto the backward segment length).

    A non-finite result is mapped to ``+inf`` in every engine (see
    :func:`_finite_or_inf`): optimizers see a rejectable step, never a
    NaN-poisoned state.
    """
    from .adjoint import resolve_grad_engine

    mode = resolve_grad_engine(grad, engine, dtype=ss.q.dtype)
    if mode == "adjoint":
        _check_diagonal_q(ss.q)
    return _deviance_impl(
        ss, y, mask, warmup=warmup, engine=engine, remat_seg=remat_seg,
        grad=mode,
    )


@functools.partial(
    jax.jit, static_argnames=("engine", "warmup", "remat_seg", "grad")
)
def _deviance_impl(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    warmup: int = 1,
    engine: str = "sequential",
    remat_seg: Optional[int] = None,
    grad: str = "autodiff",
) -> jnp.ndarray:
    if grad == "adjoint":
        from .adjoint import DEFAULT_SEG, adjoint_deviance_terms

        sigma, detf = adjoint_deviance_terms(
            ss, y, mask, engine=engine, seg=remat_seg or DEFAULT_SEG
        )
        return _finite_or_inf(
            deviance_terms(sigma, detf, mask, warmup=warmup)
        )
    if engine in ("parallel", "sqrt_parallel"):
        if remat_seg:
            raise ValueError(
                f"remat_seg is not supported by the {engine!r} "
                "(associative-scan) engine: it materializes O(T n^2) "
                "moments regardless, so the O(seg) memory promise "
                "cannot hold — use engine='sequential'/'joint'/'sqrt'"
            )
        if engine == "sqrt_parallel":
            from .pkalman import sqrt_parallel_deviance

            return sqrt_parallel_deviance(ss, y, mask, warmup=warmup)
        from .pkalman import parallel_deviance

        return parallel_deviance(ss, y, mask, warmup=warmup)
    if remat_seg:
        sigma, detf = _deviance_terms_remat(ss, y, mask, engine, remat_seg)
        return _finite_or_inf(
            deviance_terms(sigma, detf, mask, warmup=warmup)
        )
    if engine == "sqrt":
        res = _sqrt_kalman_filter(ss, y, mask, False)
    else:
        res = kalman_filter(ss, y, mask, engine=engine, store=False)
    return _finite_or_inf(
        deviance_terms(res.sigma, res.detf, mask, warmup=warmup)
    )


def log_likelihood(ss, y, mask, warmup: int = 1, engine: str = "sequential",
                   grad: Optional[str] = None):
    """Actual log-likelihood ``-deviance / 2`` (``-inf`` when the filter
    path is non-finite — the rejectable-step guard of :func:`deviance`)."""
    return -0.5 * deviance(ss, y, mask, warmup=warmup, engine=engine,
                           grad=grad)


class SmootherResult(NamedTuple):
    mean_s: jnp.ndarray  # (T, n)
    cov_s: jnp.ndarray  # (T, n, n)


@functools.partial(jax.jit, static_argnames=("engine",))
def rts_smoother(
    ss: StateSpace, filtered: FilterResult, engine: str = "sequential"
) -> SmootherResult:
    """RTS smoother as a reverse ``lax.scan``.

    Matches ``kalmansmoother`` (``metran/kalmanfilter.py:403-476``) but uses a
    symmetric Cholesky solve against the predicted covariance instead of
    ``pinv`` (both agree when the predicted covariance is PD, which holds for
    the DFM with identity initial covariance).  ``engine="parallel"``
    dispatches to the O(log T) associative-scan smoother; other engine
    names use the sequential reverse scan.  A :class:`SqrtFilterResult`
    input is smoothed in factored form (:func:`sqrt_rts_smoother` — or
    its associative-scan variant for the parallel engines) and
    reconstituted only at return, so the PSD-by-construction guarantee
    carries through the smoothing boundary.
    """
    if isinstance(filtered, SqrtFilterResult):
        if engine in ("parallel", "sqrt_parallel"):
            from .pkalman import sqrt_parallel_smoother

            sm = sqrt_parallel_smoother(ss, filtered)
        else:
            sm = sqrt_rts_smoother(ss, filtered)
        return SmootherResult(sm.mean_s, chol_outer(sm.chol_s))
    if engine == "parallel":
        from .pkalman import parallel_smoother

        return parallel_smoother(ss, filtered)
    phi = ss.phi
    mean_f, cov_f = filtered.mean_f, filtered.cov_f
    mean_p, cov_p = filtered.mean_p, filtered.cov_p

    def step(carry, xs):
        mean_next, cov_next = carry  # smoothed at t+1
        mf, pf, mp_next, pp_next = xs  # filtered at t, predicted at t+1
        # G = P^f Phi' (P^p_{t+1})^-1 with diagonal Phi
        a = pf * phi[None, :]
        chol = jnp.linalg.cholesky(pp_next)
        # a predicted covariance gone indefinite in f32 would NaN the
        # whole reverse scan; degrade that step to smoothed == filtered
        ok = jnp.all(jnp.isfinite(chol))
        chol_safe = jnp.where(
            ok, chol, jnp.eye(pp_next.shape[-1], dtype=pp_next.dtype)
        )
        g = jax.scipy.linalg.cho_solve((chol_safe, True), a.T).T
        mean_s = jnp.where(ok, mf + g @ (mean_next - mp_next), mf)
        cov_s = jnp.where(ok, pf + g @ (cov_next - pp_next) @ g.T, pf)
        return (mean_s, cov_s), (mean_s, cov_s)

    xs = (mean_f[:-1], cov_f[:-1], mean_p[1:], cov_p[1:])
    init = (mean_f[-1], cov_f[-1])
    _, (means, covs) = lax.scan(step, init, xs, reverse=True)
    mean_s = jnp.concatenate([means, mean_f[-1:]], axis=0)
    cov_s = jnp.concatenate([covs, cov_f[-1:]], axis=0)
    return SmootherResult(mean_s, cov_s)


@jax.jit
def sqrt_rts_smoother(
    ss: StateSpace, filtered: SqrtFilterResult
) -> SqrtSmootherResult:
    """RTS smoother propagating Cholesky factors (QR re-triangularization).

    Uses the Joseph-like PSD decomposition of the smoothed covariance

        C_s = (I - G Phi) P_f (I - G Phi)' + G Q G' + G C_next G'

    — algebraically identical to the classical ``P_f + G (C_next -
    P_pn) G'`` but a sum of three PSD terms, so the smoothed factor is
    one :func:`_tria` of stacked blocks: PSD by construction, mirroring
    the forward square-root filter.  The gain solves against the
    *predicted factor* from the filter pass (triangular solves only —
    no Cholesky of a computed matrix, unlike the covariance smoother's
    ``cholesky(P_pn)``).
    """
    phi = ss.phi
    dtype = filtered.chol_f.dtype
    n = phi.shape[-1]
    eye = jnp.eye(n, dtype=dtype)
    q_sqrt = _q_sqrt_diag(ss.q).astype(dtype)

    def step(carry, xs):
        mean_next, chol_next = carry  # smoothed at t+1
        mf, cf, mp_next, sp_next = xs  # filtered t; predicted t+1 factor
        d = jnp.diagonal(sp_next)
        ok = jnp.all(d > 0) & jnp.all(jnp.isfinite(sp_next))
        sp_safe = jnp.where(ok, sp_next, eye)
        a = phi[:, None] * (cf @ cf.T)  # Phi P_f
        g = jax.scipy.linalg.cho_solve((sp_safe, True), a).T
        mean_s = jnp.where(ok, mf + g @ (mean_next - mp_next), mf)
        chol_s = _tria(jnp.concatenate([
            (eye - g * phi[None, :]) @ cf,
            g * q_sqrt[None, :],
            g @ chol_next,
        ], axis=1))
        chol_s = jnp.where(ok, chol_s, cf)
        return (mean_s, chol_s), (mean_s, chol_s)

    xs = (filtered.mean_f[:-1], filtered.chol_f[:-1],
          filtered.mean_p[1:], filtered.chol_p[1:])
    init = (filtered.mean_f[-1], filtered.chol_f[-1])
    _, (means, chols) = lax.scan(step, init, xs, reverse=True)
    mean_s = jnp.concatenate([means, filtered.mean_f[-1:]], axis=0)
    chol_s = jnp.concatenate([chols, filtered.chol_f[-1:]], axis=0)
    return SqrtSmootherResult(mean_s, chol_s)


def sample_states(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    key,
    n_draws: int = 1,
    engine: str = "joint",
    sm_data: Optional[jnp.ndarray] = None,
    draw_chunk: int = 8,
) -> jnp.ndarray:
    """Joint posterior draws of the state paths (simulation smoother).

    The RTS smoother returns per-timestep marginals; for stochastic
    gap filling or any functional of a whole path, the *joint*
    posterior is what matters.  This is the Durbin-Koopman
    mean-correction simulation smoother: draw an unconditional state
    path ``x*`` from the model's own prior (the filter's pre-sample
    ``N(0, I)``, then ``x_t = phi x_{t-1} + w_t`` with the DFM's
    diagonal ``Q``), build its pseudo-observations ``y* = Z x*`` (plus
    measurement noise when ``r > 0``) ON THE SAME missing pattern,
    smooth both, and return ``m_s(y) + (x* - m_s(y*))`` — exactly
    distributed as ``x | y`` because ``x* - m_s(y*)`` has the posterior
    covariance and zero mean, independent of the data.  One smoothing
    of the data is shared; each draw adds one filter+smoother pass, and
    draws ride ``vmap``.  No reference counterpart (the reference has
    no sampling at all).

    ``sm_data`` optionally supplies the precomputed smoothed state
    means of the data (``rts_smoother(...).mean_s``) so a caller with a
    cached smoother pass does not pay it again.  Draws are evaluated in
    ``draw_chunk``-sized vmapped batches (``lax.map``): peak memory is
    O(draw_chunk · T · n²) filter/smoother moments, not O(n_draws · …).

    Returns (n_draws, T, n_state).  With ``r = 0`` the projection
    ``Z x`` of every draw reproduces the observed entries exactly —
    draws only spread where the data has gaps.

    The process-noise draw is elementwise, exploiting the DFM's
    diagonal ``Q`` (ops/statespace.py); a non-diagonal ``Q`` would make
    the returned "posterior" silently mis-correlated, so concrete
    non-diagonal inputs are rejected loudly.
    """
    q = ss.q
    try:
        # tracers cannot be concretized; skipping the check under a
        # trace is fine (the DFM builder only emits diagonal Q).  The
        # public jax.errors types replace the old jax.core.Tracer
        # isinstance check; any OTHER conversion failure still raises.
        q_np = np.asarray(q)
    except (jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        q_np = None
    if q_np is not None and np.abs(
        q_np - np.diag(np.diagonal(q_np))
    ).max() > 0.0:
        raise ValueError(
            "sample_states draws process noise elementwise and "
            "requires a diagonal transition covariance Q (the DFM "
            "builder's form); got off-diagonal entries"
        )
    return _sample_states(
        ss, y, mask, key, sm_data, n_draws=int(n_draws), engine=engine,
        draw_chunk=max(1, min(int(draw_chunk), int(n_draws))),
    )


@functools.partial(
    jax.jit, static_argnames=("n_draws", "engine", "draw_chunk")
)
def _sample_states(ss, y, mask, key, sm_data, *, n_draws, engine,
                   draw_chunk):
    dtype = ss.q.dtype
    y = jnp.asarray(y, dtype)
    mask = jnp.asarray(mask, bool)
    t_steps, n = y.shape[0], ss.phi.shape[0]
    if sm_data is None:
        sm_data = _smoothed_means(ss, y, mask, engine)
    # clip guards exact-zero variances (communality 1) against -0.0
    q_sd = jnp.sqrt(jnp.clip(jnp.diagonal(ss.q), 0.0))
    r_sd = jnp.sqrt(jnp.clip(ss.r, 0.0))

    def one(k):
        k0, kw, ke = jax.random.split(k, 3)
        x0 = jax.random.normal(k0, (n,), dtype)
        w = jax.random.normal(kw, (t_steps, n), dtype) * q_sd

        def step(x, w_t):
            x = ss.phi * x + w_t
            return x, x

        _, xs = lax.scan(step, x0, w)
        y_star = xs @ ss.z.T + jax.random.normal(ke, y.shape, dtype) * r_sd
        sm_star = _smoothed_means(ss, y_star, mask, engine)
        return sm_data + xs - sm_star

    return lax.map(
        one, jax.random.split(key, n_draws), batch_size=draw_chunk
    )


def _smoothed_means(ss, y, mask, engine):
    """Smoothed state means under ``engine``; the square-root engines
    stay in factored form through the smoother (no reconstituted
    covariance is ever refactored).  ``sqrt_parallel`` runs the
    sequential factored pass here: the draws in :func:`sample_states`
    are already mapped sequentially, and routing it through the
    covariance-form smoother would reintroduce the ``cholesky`` of a
    reconstituted (possibly indefinite-in-f32) matrix."""
    if engine in ("sqrt", "sqrt_parallel"):
        return sqrt_rts_smoother(
            ss, _sqrt_kalman_filter(ss, y, mask, True)
        ).mean_s
    return rts_smoother(
        ss, kalman_filter(ss, y, mask, engine=engine), engine=engine
    ).mean_s


@functools.partial(jax.jit, static_argnames=("standardized", "engine"))
def innovations(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    filt: Optional[FilterResult] = None,
    standardized: bool = True,
    engine: str = "joint",
    warmup: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One-step-ahead prediction residuals and their variances.

    The classic state-space misspecification diagnostic (no reference
    equivalent — ``metran`` exposes no residual accessor at all): for a
    well-specified model at the fitted parameters the standardized
    innovations are white noise (zero mean, unit variance, serially
    uncorrelated), so departures localize WHERE and WHEN the model
    fails.

    Joint (vector) definition: ``v_t = y_t - Z x_{t|t-1}`` with
    variances ``F_t = diag(Z P_{t|t-1} Z') + r`` from the
    time-predicted moments — NOT the sequential-processing per-scalar
    innovations (which condition each series on the ones updated before
    it at the same timestep and therefore depend on series order).

    Parameters
    ----------
    ss, y, mask : model matrices and masked observations, as for
        :func:`kalman_filter`.
    filt : optionally a precomputed ``store=True`` filter result (the
        predicted moments are reused; nothing is re-run).
    standardized : return ``v_t / sqrt(F_t)`` (scale-free) instead of
        raw residuals in observation units.
    engine : filter engine when ``filt`` is not supplied.
    warmup : NaN out the first ``warmup`` timesteps.  The filter
        initializes at mean 0 / covariance I rather than the stationary
        prior, so the earliest standardized residuals are mildly
        miscalibrated (typically over-dispersed) until the filter
        forgets the init — a transient of the order of the longest
        ``alpha`` time scale, NOT the deviance path's ``warmup=1``.
        Default 0: all steps returned; pass e.g. ``warmup=50`` for
        calibration-sensitive uses (the whiteness test in
        ``tests/test_innovations.py`` does exactly this).  Traced, not
        static: sweeping warmup values does not recompile.

    Returns
    -------
    v : (T, n_obs) innovations, NaN where no observation is present.
    f : (T, n_obs) innovation variances, NaN at the same positions.
    """
    if filt is None:
        filt = kalman_filter(ss, y, mask, engine=engine)
    pred_means, pred_vars = project(ss.z, filt.mean_p, filt.cov_p)
    f = pred_vars + ss.r
    v = y - pred_means
    if standardized:
        v = v / jnp.sqrt(jnp.maximum(f, jnp.finfo(f.dtype).tiny))
    keep = mask & (jnp.arange(y.shape[0])[:, None] >= warmup)
    nan = jnp.asarray(jnp.nan, v.dtype)
    return jnp.where(keep, v, nan), jnp.where(keep, f, nan)


@jax.jit
def project(
    z: jnp.ndarray, means: jnp.ndarray, covs: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Project states onto the observation space.

    Equivalent to ``SPKalmanFilter.simulate`` (``metran/kalmanfilter.py:
    569-603``): per-timestep means ``Z x_t`` and variances
    ``diag(Z P_t Z')`` clipped at zero.
    """
    sim_means = means @ z.T
    sim_vars = jnp.einsum("ij,tjk,ik->ti", z, covs, z)
    return sim_means, jnp.maximum(sim_vars, 0.0)


@functools.partial(jax.jit, static_argnames=("n_series",))
def decompose_states(
    z: jnp.ndarray, means: jnp.ndarray, n_series: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Split projected means into sdf and per-factor cdf contributions.

    Equivalent to ``SPKalmanFilter.decompose`` (``metran/kalmanfilter.py:
    605-644``).

    Returns
    -------
    sdf : (T, n_series) specific contribution per series.
    cdf : (n_factors, T, n_series) contribution of each common factor.
    """
    sdf = means[:, :n_series] @ z[:, :n_series].T
    # cdf_k[t, i] = z[i, n_series+k] * means[t, n_series+k]
    cdf = jnp.einsum("ik,tk->kti", z[:, n_series:], means[:, n_series:])
    return sdf, cdf
