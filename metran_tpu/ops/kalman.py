"""TPU-native Kalman filtering/smoothing for the Metran DFM.

The reference implementation runs a sequential-processing Kalman filter as a
numba-compiled per-timestep Python loop with ragged missing-data index arrays
(``metran/kalmanfilter.py:236-400``) and an RTS smoother with ``pinv``
(``metran/kalmanfilter.py:403-476``).  Here the recursions are expressed as
``lax.scan`` over time with **static shapes**: missing observations are
handled by a boolean mask per timestep and masked no-op updates (XLA-friendly
``where``-selects instead of ragged indices).  Everything is pure, jittable,
differentiable and vmappable over leading batch axes.

Two update engines are provided:

- ``sequential``: processes observed series one scalar at a time (rank-1
  covariance downdates), numerically step-for-step equivalent to the
  reference's sequential processing (Koopman-style), hence used for parity.
- ``joint``: conditions on all observed series at once via a Cholesky solve
  of the masked innovation covariance; mathematically identical likelihood,
  maps the inner work onto batched matmuls/Cholesky (MXU-friendly).

Log-likelihood semantics match ``SPKalmanFilter.get_mle``
(``metran/kalmanfilter.py:550-567``): the returned objective is the deviance
``-2 log L = nobs log(2 pi) + sum(log f) + sum(v^2/f)`` where the first
``warmup`` *observed* timesteps are excluded from the ``f``/``v`` sums while
``nobs`` excludes the first ``warmup`` *grid* timesteps.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .statespace import StateSpace

LOG2PI = 1.8378770664093453  # log(2*pi)


class FilterStep(NamedTuple):
    """Per-timestep filter quantities (shapes lead with time when stacked)."""

    mean_p: jnp.ndarray  # predicted state mean  E[x_t | y_{1:t-1}]
    cov_p: jnp.ndarray  # predicted state covariance
    mean_f: jnp.ndarray  # filtered state mean   E[x_t | y_{1:t}]
    cov_f: jnp.ndarray  # filtered state covariance
    sigma: jnp.ndarray  # sum of v^2/f over observed entries at t
    detf: jnp.ndarray  # sum of log f over observed entries at t


class FilterResult(NamedTuple):
    mean_p: jnp.ndarray  # (T, n)
    cov_p: jnp.ndarray  # (T, n, n)
    mean_f: jnp.ndarray  # (T, n)
    cov_f: jnp.ndarray  # (T, n, n)
    sigma: jnp.ndarray  # (T,)
    detf: jnp.ndarray  # (T,)


def _predict(mean, cov, phi, q):
    """Diagonal-transition predict step: exploits Phi = diag(phi)."""
    mean_p = phi * mean
    cov_p = phi[:, None] * cov * phi[None, :] + q
    return mean_p, cov_p


def _sequential_update(mean, cov, y, mask, z, r, dtype):
    """Masked sequential-processing update over all observation slots.

    Iterates the series slots in ascending order (the same order the
    reference visits its compressed observation indices) and applies a
    rank-1 update per observed slot; masked slots leave the state unchanged
    and contribute zero to sigma/detf.
    """
    zero = jnp.zeros((), dtype)

    def step(carry, xs):
        m, p, sigma, detf = carry
        y_i, mask_i, z_i, r_i = xs
        v = y_i - z_i @ m
        d = p @ z_i
        f = z_i @ d + r_i
        f_safe = jnp.where(mask_i, f, jnp.ones((), dtype))
        k = d / f_safe
        m_new = m + k * v
        p_new = p - jnp.outer(k, k) * f_safe
        m = jnp.where(mask_i, m_new, m)
        p = jnp.where(mask_i, p_new, p)
        sigma = sigma + jnp.where(mask_i, v * v / f_safe, zero)
        detf = detf + jnp.where(mask_i, jnp.log(f_safe), zero)
        return (m, p, sigma, detf), None

    (mean, cov, sigma, detf), _ = lax.scan(
        step, (mean, cov, zero, zero), (y, mask, z, r)
    )
    return mean, cov, sigma, detf


def _joint_update(mean, cov, y, mask, z, r, dtype):
    """Masked joint update via Cholesky of the innovation covariance.

    Unobserved slots get a unit innovation variance and zero innovation, so
    they contribute nothing to the gain, ``sigma`` or ``detf`` (log 1 = 0);
    the result equals conditioning on the observed subset only.
    """
    maskf = mask.astype(dtype)
    z_m = z * maskf[:, None]
    v = jnp.where(mask, y - z @ mean, 0.0)
    pz = cov @ z_m.T  # (n, m)
    f = z_m @ pz + jnp.diag(jnp.where(mask, r, 0.0) + (1.0 - maskf))
    chol = jnp.linalg.cholesky(f)
    # K = P Z' F^-1  ->  solve F K' = Z P
    kt = jax.scipy.linalg.cho_solve((chol, True), pz.T)  # (m, n)
    mean = mean + kt.T @ v
    cov = cov - kt.T @ f @ kt
    w = jax.scipy.linalg.solve_triangular(chol, v, lower=True)
    sigma = jnp.sum(w * w)
    detf = 2.0 * jnp.sum(jnp.log(jnp.diagonal(chol)))
    return mean, cov, sigma, detf


_UPDATES = {"sequential": _sequential_update, "joint": _joint_update}


def _init_state(ss: StateSpace, dtype):
    """Reference initialization: zero mean, identity covariance
    (``metran/kalmanfilter.py:747-750``)."""
    n = ss.phi.shape[-1]
    return jnp.zeros(n, dtype), jnp.eye(n, dtype=dtype)


def _make_core_step(ss: StateSpace, engine: str, dtype):
    """Shared predict+update body of one filter timestep.

    Single source of the masked-update semantics, used by both the plain
    ``kalman_filter`` scan and the segmented remat scan so they cannot
    drift apart.  Returns ``(mean_p, cov_p, mean_f, cov_f, sigma, detf)``.
    """
    update = _UPDATES[engine]

    def core(mean, cov, y_t, mask_t):
        mean_p, cov_p = _predict(mean, cov, ss.phi, ss.q)
        has_obs = jnp.any(mask_t)
        mean_f, cov_f, sigma, detf = update(
            mean_p, cov_p, y_t, mask_t, ss.z, ss.r, dtype
        )
        # timestep with zero observations: state passes through unchanged
        # (the where is redundant given masked updates but keeps the
        # no-observation semantics explicit and gradients clean)
        mean_f = jnp.where(has_obs, mean_f, mean_p)
        cov_f = jnp.where(has_obs, cov_f, cov_p)
        return mean_p, cov_p, mean_f, cov_f, sigma, detf

    return core


@functools.partial(jax.jit, static_argnames=("engine", "store"))
def kalman_filter(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    engine: str = "sequential",
    store: bool = True,
) -> FilterResult:
    """Run the masked sequential-processing Kalman filter as a ``lax.scan``.

    Parameters
    ----------
    ss : StateSpace (diagonal transition).
    y : (T, n_obs) observations; entries at masked positions are ignored.
    mask : (T, n_obs) bool, True where a real observation is present.
    engine : "sequential" (parity) or "joint" (Cholesky batch update).
    store : if False, per-step means/covariances are not stacked (loglik-only
        path — keeps memory O(n^2) instead of O(T n^2)).  Note this memory
        saving applies to the ``sequential``/``joint`` scan engines only:
        the ``parallel`` associative-scan engine materializes all per-step
        moments regardless of ``store`` (only the return shapes follow the
        contract), so its memory is always O(T n^2).

    Returns
    -------
    FilterResult; when ``store=False`` the mean/cov arrays hold only the
    final carry values (shape (n,)/(n, n)).
    """
    if engine == "parallel":
        from .pkalman import parallel_filter

        res = parallel_filter(ss, y, mask)
        if not store:  # return shapes follow the store=False contract, but
            # the associative scan has already materialized O(T n^2) moments
            return FilterResult(
                res.mean_f[-1], res.cov_f[-1], res.mean_f[-1],
                res.cov_f[-1], res.sigma, res.detf,
            )
        return res
    dtype = ss.q.dtype
    y = jnp.asarray(y, dtype)
    mask = jnp.asarray(mask, bool)
    core = _make_core_step(ss, engine, dtype)
    mean0, cov0 = _init_state(ss, dtype)

    def step(carry, xs):
        mean, cov = carry
        y_t, mask_t = xs
        mean_p, cov_p, mean_f, cov_f, sigma, detf = core(
            mean, cov, y_t, mask_t
        )
        out = FilterStep(mean_p, cov_p, mean_f, cov_f, sigma, detf)
        if not store:
            out = FilterStep(
                jnp.zeros(0, dtype),
                jnp.zeros(0, dtype),
                jnp.zeros(0, dtype),
                jnp.zeros(0, dtype),
                sigma,
                detf,
            )
        return (mean_f, cov_f), out

    (mean_T, cov_T), steps = lax.scan(step, (mean0, cov0), (y, mask))
    if store:
        return FilterResult(
            steps.mean_p, steps.cov_p, steps.mean_f, steps.cov_f,
            steps.sigma, steps.detf,
        )
    return FilterResult(mean_T, cov_T, mean_T, cov_T, steps.sigma, steps.detf)


@functools.partial(jax.jit, static_argnames=("engine",))
def filter_update(
    ss: StateSpace,
    mean: jnp.ndarray,
    cov: jnp.ndarray,
    y_t: jnp.ndarray,
    mask_t: jnp.ndarray,
    engine: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One online-assimilation step from an arbitrary carried posterior.

    Exactly the predict+update body of one :func:`kalman_filter`
    timestep (the same ``_make_core_step`` the scan uses, so the two
    cannot drift apart), but exposed as a standalone entry point: given
    the filtered posterior ``N(mean, cov)`` at time ``t-1`` and one new
    observation row, return the filtered posterior at ``t`` plus that
    step's likelihood terms.  This is what turns the filter into an
    incremental service — appending an observation costs one step, not
    a full-history refilter (``serve/engine.py`` builds on it).

    Returns ``(mean_f, cov_f, sigma, detf)``; ``sigma``/``detf`` are the
    step's ``v^2/f`` and ``log f`` sums (zero when ``mask_t`` is all
    False, matching the scan's no-op semantics for missing rows).
    """
    dtype = ss.q.dtype
    core = _make_core_step(ss, engine, dtype)
    _, _, mean_f, cov_f, sigma, detf = core(
        jnp.asarray(mean, dtype), jnp.asarray(cov, dtype),
        jnp.asarray(y_t, dtype), jnp.asarray(mask_t, bool),
    )
    return mean_f, cov_f, sigma, detf


@functools.partial(jax.jit, static_argnames=("engine",))
def filter_append(
    ss: StateSpace,
    mean: jnp.ndarray,
    cov: jnp.ndarray,
    y_new: jnp.ndarray,
    mask_new: jnp.ndarray,
    engine: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Assimilate ``k`` appended observation rows from a carried posterior.

    Runs ONLY the new timesteps through the filter recursion, starting
    from the filtered posterior ``N(mean, cov)`` at the last already-
    assimilated timestep — the incremental-update path of the serving
    layer.  Equivalent (to float tolerance) to refiltering the full
    history and reading the final carry, at O(k) cost instead of O(T).

    Parameters
    ----------
    y_new : (k, n_obs) appended observations (masked entries ignored).
    mask_new : (k, n_obs) bool, True where a real observation is present.

    Returns
    -------
    ``(mean_T, cov_T, sigma, detf)``: the filtered posterior after the
    last appended step and the per-step (k,) likelihood-term arrays.
    """
    dtype = ss.q.dtype
    y_new = jnp.atleast_2d(jnp.asarray(y_new, dtype))
    mask_new = jnp.atleast_2d(jnp.asarray(mask_new, bool))
    core = _make_core_step(ss, engine, dtype)

    def step(carry, xs):
        m, p = carry
        y_t, mask_t = xs
        _, _, mean_f, cov_f, sigma, detf = core(m, p, y_t, mask_t)
        return (mean_f, cov_f), (sigma, detf)

    (mean_T, cov_T), (sigma, detf) = lax.scan(
        step, (jnp.asarray(mean, dtype), jnp.asarray(cov, dtype)),
        (y_new, mask_new),
    )
    return mean_T, cov_T, sigma, detf


def deviance_terms(
    sigma: jnp.ndarray, detf: jnp.ndarray, mask: jnp.ndarray, warmup: int = 1
) -> jnp.ndarray:
    """Combine per-timestep filter terms into the reference's MLE objective.

    Implements ``SPKalmanFilter.get_mle`` (``metran/kalmanfilter.py:550-567``)
    under static shapes: ``sigma``/``detf`` sums skip the first ``warmup``
    *observed* timesteps (the reference slices its compressed per-observed-
    timestep arrays), while ``nobs`` skips the first ``warmup`` *grid*
    timesteps.
    """
    mask = jnp.asarray(mask, bool)
    count = jnp.sum(mask, axis=-1)
    has_obs = count > 0
    # rank of each timestep among observed timesteps (0-based), for skipping
    # the first `warmup` observed ones
    obs_rank = jnp.cumsum(has_obs, axis=-1) - 1
    keep = has_obs & (obs_rank >= warmup)
    nobs = jnp.sum(jnp.where(jnp.arange(count.shape[-1]) >= warmup, count, 0))
    dtype = sigma.dtype
    return (
        nobs.astype(dtype) * jnp.asarray(LOG2PI, dtype)
        + jnp.sum(jnp.where(keep, detf, 0.0))
        + jnp.sum(jnp.where(keep, sigma, 0.0))
    )


def _deviance_terms_remat(ss, y, mask, engine, remat_seg):
    """Per-timestep (sigma, detf) via a segmented, checkpointed scan.

    Time is split into segments of ``remat_seg`` steps (padded with
    all-masked no-op steps); each segment body is wrapped in
    ``jax.checkpoint`` so the backward pass stores only O(T/seg) segment
    carries plus one segment of step residuals instead of O(T) — the
    rematerialization recipe that lets fleet batches of hundreds of
    models fit in HBM under autodiff.  Padded trailing steps carry
    ``mask=False`` everywhere, so they contribute exactly zero to both
    sums (same no-op semantics the masked filter gives missing rows).
    """
    dtype = ss.q.dtype
    y = jnp.asarray(y, dtype)
    mask = jnp.asarray(mask, bool)
    t_steps = y.shape[0]
    core = _make_core_step(ss, engine, dtype)
    mean0, cov0 = _init_state(ss, dtype)

    def step(carry, xs):
        mean, cov = carry
        y_t, mask_t = xs
        _, _, mean_f, cov_f, sigma, detf = core(mean, cov, y_t, mask_t)
        return (mean_f, cov_f), (sigma, detf)

    pad = (-t_steps) % remat_seg
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad,) + y.shape[1:], dtype)])
        mask = jnp.concatenate(
            [mask, jnp.zeros((pad,) + mask.shape[1:], bool)]
        )
    y_seg = y.reshape(-1, remat_seg, *y.shape[1:])
    m_seg = mask.reshape(-1, remat_seg, *mask.shape[1:])

    @jax.checkpoint
    def seg_body(carry, xs):
        return lax.scan(step, carry, xs)

    _, (sigma, detf) = lax.scan(seg_body, (mean0, cov0), (y_seg, m_seg))
    return sigma.reshape(-1)[:t_steps], detf.reshape(-1)[:t_steps]


@functools.partial(
    jax.jit, static_argnames=("engine", "warmup", "remat_seg")
)
def deviance(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    warmup: int = 1,
    engine: str = "sequential",
    remat_seg: Optional[int] = None,
) -> jnp.ndarray:
    """-2 log-likelihood (the quantity the reference minimizes).

    ``remat_seg`` (e.g. 100) evaluates the filter as a segmented
    checkpointed scan, cutting autodiff residual memory from O(T n^2) to
    O(seg n^2) at the cost of one extra forward recompute in the
    backward pass; results are identical to the plain scan.
    """
    if engine == "parallel":
        if remat_seg:
            raise ValueError(
                "remat_seg is not supported by the 'parallel' "
                "(associative-scan) engine: it materializes O(T n^2) "
                "moments regardless, so the O(seg) memory promise "
                "cannot hold — use engine='sequential'/'joint'"
            )
        from .pkalman import parallel_deviance

        return parallel_deviance(ss, y, mask, warmup=warmup)
    if remat_seg:
        sigma, detf = _deviance_terms_remat(ss, y, mask, engine, remat_seg)
        return deviance_terms(sigma, detf, mask, warmup=warmup)
    res = kalman_filter(ss, y, mask, engine=engine, store=False)
    return deviance_terms(res.sigma, res.detf, mask, warmup=warmup)


def log_likelihood(ss, y, mask, warmup: int = 1, engine: str = "sequential"):
    """Actual log-likelihood ``-deviance / 2``."""
    return -0.5 * deviance(ss, y, mask, warmup=warmup, engine=engine)


class SmootherResult(NamedTuple):
    mean_s: jnp.ndarray  # (T, n)
    cov_s: jnp.ndarray  # (T, n, n)


@functools.partial(jax.jit, static_argnames=("engine",))
def rts_smoother(
    ss: StateSpace, filtered: FilterResult, engine: str = "sequential"
) -> SmootherResult:
    """RTS smoother as a reverse ``lax.scan``.

    Matches ``kalmansmoother`` (``metran/kalmanfilter.py:403-476``) but uses a
    symmetric Cholesky solve against the predicted covariance instead of
    ``pinv`` (both agree when the predicted covariance is PD, which holds for
    the DFM with identity initial covariance).  ``engine="parallel"``
    dispatches to the O(log T) associative-scan smoother; other engine
    names use the sequential reverse scan.
    """
    if engine == "parallel":
        from .pkalman import parallel_smoother

        return parallel_smoother(ss, filtered)
    phi = ss.phi
    mean_f, cov_f = filtered.mean_f, filtered.cov_f
    mean_p, cov_p = filtered.mean_p, filtered.cov_p

    def step(carry, xs):
        mean_next, cov_next = carry  # smoothed at t+1
        mf, pf, mp_next, pp_next = xs  # filtered at t, predicted at t+1
        # G = P^f Phi' (P^p_{t+1})^-1 with diagonal Phi
        a = pf * phi[None, :]
        chol = jnp.linalg.cholesky(pp_next)
        g = jax.scipy.linalg.cho_solve((chol, True), a.T).T
        mean_s = mf + g @ (mean_next - mp_next)
        cov_s = pf + g @ (cov_next - pp_next) @ g.T
        return (mean_s, cov_s), (mean_s, cov_s)

    xs = (mean_f[:-1], cov_f[:-1], mean_p[1:], cov_p[1:])
    init = (mean_f[-1], cov_f[-1])
    _, (means, covs) = lax.scan(step, init, xs, reverse=True)
    mean_s = jnp.concatenate([means, mean_f[-1:]], axis=0)
    cov_s = jnp.concatenate([covs, cov_f[-1:]], axis=0)
    return SmootherResult(mean_s, cov_s)


def sample_states(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    key,
    n_draws: int = 1,
    engine: str = "joint",
    sm_data: Optional[jnp.ndarray] = None,
    draw_chunk: int = 8,
) -> jnp.ndarray:
    """Joint posterior draws of the state paths (simulation smoother).

    The RTS smoother returns per-timestep marginals; for stochastic
    gap filling or any functional of a whole path, the *joint*
    posterior is what matters.  This is the Durbin-Koopman
    mean-correction simulation smoother: draw an unconditional state
    path ``x*`` from the model's own prior (the filter's pre-sample
    ``N(0, I)``, then ``x_t = phi x_{t-1} + w_t`` with the DFM's
    diagonal ``Q``), build its pseudo-observations ``y* = Z x*`` (plus
    measurement noise when ``r > 0``) ON THE SAME missing pattern,
    smooth both, and return ``m_s(y) + (x* - m_s(y*))`` — exactly
    distributed as ``x | y`` because ``x* - m_s(y*)`` has the posterior
    covariance and zero mean, independent of the data.  One smoothing
    of the data is shared; each draw adds one filter+smoother pass, and
    draws ride ``vmap``.  No reference counterpart (the reference has
    no sampling at all).

    ``sm_data`` optionally supplies the precomputed smoothed state
    means of the data (``rts_smoother(...).mean_s``) so a caller with a
    cached smoother pass does not pay it again.  Draws are evaluated in
    ``draw_chunk``-sized vmapped batches (``lax.map``): peak memory is
    O(draw_chunk · T · n²) filter/smoother moments, not O(n_draws · …).

    Returns (n_draws, T, n_state).  With ``r = 0`` the projection
    ``Z x`` of every draw reproduces the observed entries exactly —
    draws only spread where the data has gaps.

    The process-noise draw is elementwise, exploiting the DFM's
    diagonal ``Q`` (ops/statespace.py); a non-diagonal ``Q`` would make
    the returned "posterior" silently mis-correlated, so concrete
    non-diagonal inputs are rejected loudly.
    """
    q = ss.q
    try:
        # tracers cannot be concretized; skipping the check under a
        # trace is fine (the DFM builder only emits diagonal Q).  The
        # public jax.errors types replace the old jax.core.Tracer
        # isinstance check; any OTHER conversion failure still raises.
        q_np = np.asarray(q)
    except (jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        q_np = None
    if q_np is not None and np.abs(
        q_np - np.diag(np.diagonal(q_np))
    ).max() > 0.0:
        raise ValueError(
            "sample_states draws process noise elementwise and "
            "requires a diagonal transition covariance Q (the DFM "
            "builder's form); got off-diagonal entries"
        )
    return _sample_states(
        ss, y, mask, key, sm_data, n_draws=int(n_draws), engine=engine,
        draw_chunk=max(1, min(int(draw_chunk), int(n_draws))),
    )


@functools.partial(
    jax.jit, static_argnames=("n_draws", "engine", "draw_chunk")
)
def _sample_states(ss, y, mask, key, sm_data, *, n_draws, engine,
                   draw_chunk):
    dtype = ss.q.dtype
    y = jnp.asarray(y, dtype)
    mask = jnp.asarray(mask, bool)
    t_steps, n = y.shape[0], ss.phi.shape[0]
    if sm_data is None:
        sm_data = rts_smoother(
            ss, kalman_filter(ss, y, mask, engine=engine), engine=engine
        ).mean_s
    # clip guards exact-zero variances (communality 1) against -0.0
    q_sd = jnp.sqrt(jnp.clip(jnp.diagonal(ss.q), 0.0))
    r_sd = jnp.sqrt(jnp.clip(ss.r, 0.0))

    def one(k):
        k0, kw, ke = jax.random.split(k, 3)
        x0 = jax.random.normal(k0, (n,), dtype)
        w = jax.random.normal(kw, (t_steps, n), dtype) * q_sd

        def step(x, w_t):
            x = ss.phi * x + w_t
            return x, x

        _, xs = lax.scan(step, x0, w)
        y_star = xs @ ss.z.T + jax.random.normal(ke, y.shape, dtype) * r_sd
        sm_star = rts_smoother(
            ss, kalman_filter(ss, y_star, mask, engine=engine),
            engine=engine,
        ).mean_s
        return sm_data + xs - sm_star

    return lax.map(
        one, jax.random.split(key, n_draws), batch_size=draw_chunk
    )


@functools.partial(jax.jit, static_argnames=("standardized", "engine"))
def innovations(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    filt: Optional[FilterResult] = None,
    standardized: bool = True,
    engine: str = "joint",
    warmup: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One-step-ahead prediction residuals and their variances.

    The classic state-space misspecification diagnostic (no reference
    equivalent — ``metran`` exposes no residual accessor at all): for a
    well-specified model at the fitted parameters the standardized
    innovations are white noise (zero mean, unit variance, serially
    uncorrelated), so departures localize WHERE and WHEN the model
    fails.

    Joint (vector) definition: ``v_t = y_t - Z x_{t|t-1}`` with
    variances ``F_t = diag(Z P_{t|t-1} Z') + r`` from the
    time-predicted moments — NOT the sequential-processing per-scalar
    innovations (which condition each series on the ones updated before
    it at the same timestep and therefore depend on series order).

    Parameters
    ----------
    ss, y, mask : model matrices and masked observations, as for
        :func:`kalman_filter`.
    filt : optionally a precomputed ``store=True`` filter result (the
        predicted moments are reused; nothing is re-run).
    standardized : return ``v_t / sqrt(F_t)`` (scale-free) instead of
        raw residuals in observation units.
    engine : filter engine when ``filt`` is not supplied.
    warmup : NaN out the first ``warmup`` timesteps.  The filter
        initializes at mean 0 / covariance I rather than the stationary
        prior, so the earliest standardized residuals are mildly
        miscalibrated (typically over-dispersed) until the filter
        forgets the init — a transient of the order of the longest
        ``alpha`` time scale, NOT the deviance path's ``warmup=1``.
        Default 0: all steps returned; pass e.g. ``warmup=50`` for
        calibration-sensitive uses (the whiteness test in
        ``tests/test_innovations.py`` does exactly this).  Traced, not
        static: sweeping warmup values does not recompile.

    Returns
    -------
    v : (T, n_obs) innovations, NaN where no observation is present.
    f : (T, n_obs) innovation variances, NaN at the same positions.
    """
    if filt is None:
        filt = kalman_filter(ss, y, mask, engine=engine)
    pred_means, pred_vars = project(ss.z, filt.mean_p, filt.cov_p)
    f = pred_vars + ss.r
    v = y - pred_means
    if standardized:
        v = v / jnp.sqrt(jnp.maximum(f, jnp.finfo(f.dtype).tiny))
    keep = mask & (jnp.arange(y.shape[0])[:, None] >= warmup)
    nan = jnp.asarray(jnp.nan, v.dtype)
    return jnp.where(keep, v, nan), jnp.where(keep, f, nan)


@jax.jit
def project(
    z: jnp.ndarray, means: jnp.ndarray, covs: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Project states onto the observation space.

    Equivalent to ``SPKalmanFilter.simulate`` (``metran/kalmanfilter.py:
    569-603``): per-timestep means ``Z x_t`` and variances
    ``diag(Z P_t Z')`` clipped at zero.
    """
    sim_means = means @ z.T
    sim_vars = jnp.einsum("ij,tjk,ik->ti", z, covs, z)
    return sim_means, jnp.maximum(sim_vars, 0.0)


@functools.partial(jax.jit, static_argnames=("n_series",))
def decompose_states(
    z: jnp.ndarray, means: jnp.ndarray, n_series: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Split projected means into sdf and per-factor cdf contributions.

    Equivalent to ``SPKalmanFilter.decompose`` (``metran/kalmanfilter.py:
    605-644``).

    Returns
    -------
    sdf : (T, n_series) specific contribution per series.
    cdf : (n_factors, T, n_series) contribution of each common factor.
    """
    sdf = means[:, :n_series] @ z[:, :n_series].T
    # cdf_k[t, i] = z[i, n_series+k] * means[t, n_series+k]
    cdf = jnp.einsum("ik,tk->kti", z[:, n_series:], means[:, n_series:])
    return sdf, cdf
