"""Lane-layout Kalman deviance: the TPU fleet hot path.

The batch-leading filter (``ops.kalman``) is the right shape for one
model; for a *fleet* of hundreds of reference-sized models it wastes the
machine.  XLA tiles the two minor dimensions of every array into (8, 128)
vector registers, so a 21x21 covariance occupies 3 tiles of which >90%
is padding, and the per-step Cholesky/triangular solves are
latency-bound.  This module keeps the **fleet axis in the 128-wide lane
dimension** instead: covariances are ``(n, n, B)``, every filter op is an
elementwise/broadcast op across models at full lane utilization, and the
update is the reference's sequential processing (rank-1, no Cholesky —
``/root/reference/metran/kalmanfilter.py:315-378`` is the behavioral
spec).  Measured on TPU v5e for the 20-series/5k-step fleet workload:
~15-45x faster per pass than the batch-leading layout.

Autodiff memory is handled by a segmented, checkpointed scan: time is
split into ``remat_seg``-step segments (padded with all-masked no-op
steps), each segment body wrapped in ``jax.checkpoint``, so the backward
pass stores O(T/seg) segment carries plus one segment of residuals
instead of O(T) — that is what lets lane batches of 512+ models fit in
HBM under ``value_and_grad``.  The same composition expressed as
``jax.checkpoint`` + ``vmap(in_axes=-1)`` over the single-model filter
compiles ~15x slower on TPU, which is why this kernel is written
directly in lane layout.

Shapes (B = fleet size, always LAST):
    alpha    (N+K, B)   AR decay parameters [sdf..., cdf...]
    loadings (N, K, B)  factor loadings
    dt       (B,)       grid step in days
    y, mask  (T, N, B)  observations / observed-flags
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .kalman import LOG2PI


def lanes_statespace(
    alpha: jnp.ndarray, loadings: jnp.ndarray, dt: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """DFM state-space matrices in lane layout.

    Same math as :func:`metran_tpu.ops.dfm_statespace` (diagonal
    transition ``phi = exp(-dt/alpha)``, diagonal process noise with the
    ``expm1`` form and the communality scaling on the specific states,
    ``Z = [I | loadings]``, ``r = 0``), with every output carrying the
    fleet axis last.  Q is returned as its diagonal ``(n, B)``.
    """
    n, k, b = loadings.shape
    dtype = loadings.dtype
    phi = jnp.exp(-dt[None, :] / alpha)  # (n+k, B)
    comm = jnp.sum(loadings**2, axis=1)  # (N, B)
    decay2 = -jnp.expm1(-2.0 * dt[None, :] / alpha)  # 1 - phi^2, stable
    q = jnp.concatenate([decay2[:n] * (1.0 - comm), decay2[n:]], axis=0)
    eye = jnp.broadcast_to(jnp.eye(n, dtype=dtype)[:, :, None], (n, n, b))
    z = jnp.concatenate([eye, loadings], axis=1)  # (N, n+k, B)
    r = jnp.zeros((n, b), dtype)
    return phi, q, z, r


def _lanes_filter_terms(phi, q, z, r, y, mask, remat_seg):
    """Per-timestep (sigma, detf), both (T, B), via the masked
    sequential-processing filter in lane layout."""
    n, b = phi.shape
    t_steps = y.shape[0]
    dtype = phi.dtype
    eye = jnp.eye(n, dtype=dtype)[:, :, None]

    def update_series(carry, xs):
        m, p, sigma, detf = carry
        y_i, mask_i, z_i, r_i = xs  # (B,), (B,), (n, B), (B,)
        v = y_i - jnp.sum(z_i * m, axis=0)
        d = jnp.sum(p * z_i[None, :, :], axis=1)  # (n, B)
        f = jnp.sum(z_i * d, axis=0) + r_i
        f_safe = jnp.where(mask_i, f, jnp.ones((), dtype))
        k = d / f_safe
        m_new = m + k * v
        p_new = p - k[:, None, :] * k[None, :, :] * f_safe
        m = jnp.where(mask_i, m_new, m)
        p = jnp.where(mask_i, p_new, p)
        sigma = sigma + jnp.where(mask_i, v * v / f_safe, 0.0)
        detf = detf + jnp.where(mask_i, jnp.log(f_safe), 0.0)
        return (m, p, sigma, detf), None

    def step(carry, xs):
        mean, cov = carry
        y_t, mask_t = xs  # (N, B)
        mean_p = phi * mean
        cov_p = phi[:, None, :] * cov * phi[None, :, :] + eye * q[None]
        (mean_f, cov_f, sigma, detf), _ = lax.scan(
            update_series,
            (mean_p, cov_p, jnp.zeros(b, dtype), jnp.zeros(b, dtype)),
            (y_t, mask_t, z, r),
        )
        return (mean_f, cov_f), (sigma, detf)

    pad = (-t_steps) % remat_seg
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad,) + y.shape[1:], dtype)])
        mask = jnp.concatenate(
            [mask, jnp.zeros((pad,) + mask.shape[1:], bool)]
        )
    y_seg = y.reshape(-1, remat_seg, *y.shape[1:])
    m_seg = mask.reshape(-1, remat_seg, *mask.shape[1:])

    @jax.checkpoint
    def seg_body(carry, xs):
        return lax.scan(step, carry, xs)

    init = (jnp.zeros((n, b), dtype), jnp.broadcast_to(eye, (n, n, b)))
    _, (sigma, detf) = lax.scan(seg_body, init, (y_seg, m_seg))
    t_pad = t_steps + pad
    return (
        sigma.reshape(t_pad, b)[:t_steps],
        detf.reshape(t_pad, b)[:t_steps],
    )


def lanes_deviance_terms(sigma, detf, mask, warmup: int = 1):
    """Combine (T, B) filter terms into per-lane deviances.

    Same semantics as :func:`metran_tpu.ops.kalman.deviance_terms`
    (reference ``SPKalmanFilter.get_mle``): sigma/detf sums skip the
    first ``warmup`` *observed* timesteps; nobs skips the first
    ``warmup`` *grid* timesteps.
    """
    dtype = sigma.dtype
    count = jnp.sum(mask, axis=1)  # (T, B)
    has_obs = count > 0
    obs_rank = jnp.cumsum(has_obs, axis=0) - 1
    keep = has_obs & (obs_rank >= warmup)
    t_steps = count.shape[0]
    nobs = jnp.sum(
        jnp.where(jnp.arange(t_steps)[:, None] >= warmup, count, 0), axis=0
    )
    return (
        nobs.astype(dtype) * jnp.asarray(LOG2PI, dtype)
        + jnp.sum(jnp.where(keep, detf, 0.0), axis=0)
        + jnp.sum(jnp.where(keep, sigma, 0.0), axis=0)
    )


@functools.partial(jax.jit, static_argnames=("warmup", "remat_seg"))
def lanes_dfm_deviance(
    alpha: jnp.ndarray,
    loadings: jnp.ndarray,
    dt: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    warmup: int = 1,
    remat_seg: Optional[int] = 100,
) -> jnp.ndarray:
    """(B,) deviance of a fleet at ``alpha`` — the lanes hot path.

    Numerically step-for-step the sequential-processing engine
    (``engine="sequential"`` of :func:`metran_tpu.ops.deviance`), so its
    values match the reference parity bar; only the array layout (and
    hence rounding-neutral op order within each reduction) differs.
    """
    phi, q, z, r = lanes_statespace(alpha, loadings, dt)
    sigma, detf = _lanes_filter_terms(
        phi, q, z, r, y, mask, remat_seg or y.shape[0]
    )
    return lanes_deviance_terms(sigma, detf, mask, warmup=warmup)
