"""Lane-layout Kalman deviance: the TPU fleet hot path.

The batch-leading filter (``ops.kalman``) is the right shape for one
model; for a *fleet* of hundreds of reference-sized models it wastes the
machine.  XLA tiles the two minor dimensions of every array into (8, 128)
vector registers, so a 21x21 covariance occupies 3 tiles of which >90%
is padding, and the per-step Cholesky/triangular solves are
latency-bound.  This module keeps the **fleet axis in the 128-wide lane
dimension** instead: covariances are ``(n, n, B)``, every filter op is an
elementwise/broadcast op across models at full lane utilization, and the
update is the reference's sequential processing (rank-1, no Cholesky —
``metran/kalmanfilter.py:315-378`` is the behavioral
spec).  Measured on TPU v5e for the 20-series/5k-step fleet workload:
~15-45x faster per pass than the batch-leading layout.

Autodiff memory is handled by a segmented, checkpointed scan: time is
split into ``remat_seg``-step segments (padded with all-masked no-op
steps), each segment body wrapped in ``jax.checkpoint``, so the backward
pass stores O(T/seg) segment carries plus one segment of residuals
instead of O(T) — that is what lets lane batches of 512+ models fit in
HBM under ``value_and_grad``.  The same composition expressed as
``jax.checkpoint`` + ``vmap(in_axes=-1)`` over the single-model filter
compiles ~15x slower on TPU, which is why this kernel is written
directly in lane layout.

Shapes (B = fleet size, always LAST):
    alpha    (N+K, B)   AR decay parameters [sdf..., cdf...]
    loadings (N, K, B)  factor loadings
    dt       (B,)       grid step in days
    y, mask  (T, N, B)  observations / observed-flags
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .kalman import LOG2PI


def lanes_statespace(
    alpha: jnp.ndarray, loadings: jnp.ndarray, dt: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """DFM state-space matrices in lane layout.

    Same math as :func:`metran_tpu.ops.dfm_statespace` (diagonal
    transition ``phi = exp(-dt/alpha)``, diagonal process noise with the
    ``expm1`` form and the communality scaling on the specific states,
    ``Z = [I | loadings]``, ``r = 0``), with every output carrying the
    fleet axis last.  Q is returned as its diagonal ``(n, B)``.
    """
    n, k, b = loadings.shape
    dtype = loadings.dtype
    phi = jnp.exp(-dt[None, :] / alpha)  # (n+k, B)
    comm = jnp.sum(loadings**2, axis=1)  # (N, B)
    decay2 = -jnp.expm1(-2.0 * dt[None, :] / alpha)  # 1 - phi^2, stable
    q = jnp.concatenate([decay2[:n] * (1.0 - comm), decay2[n:]], axis=0)
    eye = jnp.broadcast_to(jnp.eye(n, dtype=dtype)[:, :, None], (n, n, b))
    z = jnp.concatenate([eye, loadings], axis=1)  # (N, n+k, B)
    r = jnp.zeros((n, b), dtype)
    return phi, q, z, r


def _adj_series_update(carry, xs, dtype):
    m, p, sigma, detf = carry
    y_i, mask_i, z_i, r_i = xs
    obs = mask_i > 0
    v = y_i - jnp.sum(z_i * m, axis=0)
    d = jnp.sum(p * z_i[None, :, :], axis=1)
    f = jnp.sum(z_i * d, axis=0) + r_i
    f_safe = jnp.where(obs, f, jnp.ones((), dtype))
    k = d / f_safe
    m = jnp.where(obs, m + k * v, m)
    p = jnp.where(obs, p - k[:, None, :] * k[None, :, :] * f_safe, p)
    sigma = sigma + jnp.where(obs, v * v / f_safe, 0.0)
    detf = detf + jnp.where(obs, jnp.log(f_safe), 0.0)
    return (m, p, sigma, detf), (d, f_safe, v)


def _predict_step(phi, q, carry, eye):
    """Time-propagate the lane carry: diagonal transition + diagonal Q."""
    mean, cov = carry
    mean_p = phi * mean
    cov_p = phi[:, None, :] * cov * phi[None, :, :] + eye * q[None]
    return mean_p, cov_p


def _update_scan(z, r, mean_p, cov_p, y_t, m_t, dtype):
    """Sequential (per-series) measurement update of the predicted lane
    moments; returns the updated carry with accumulated (sigma, detf) and
    the per-series (d, f_safe, v) residuals."""
    b = mean_p.shape[-1]
    return lax.scan(
        lambda c, xs: _adj_series_update(c, xs, dtype),
        (mean_p, cov_p, jnp.zeros(b, dtype), jnp.zeros(b, dtype)),
        (y_t, m_t, z, r),
    )


def _adj_step(phi, q, z, r, carry, y_t, m_t, eye):
    mean_p, cov_p = _predict_step(phi, q, carry, eye)
    (m_f, p_f, sig, det), res = _update_scan(
        z, r, mean_p, cov_p, y_t, m_t, phi.dtype
    )
    return (m_f, p_f), (sig, det), res


def _adj_init_carry(phi, eye):
    n, b = phi.shape
    return (
        jnp.zeros((n, b), phi.dtype),
        jnp.broadcast_to(eye, (n, n, b)),
    )


def _segment(y, mask, seg, dtype):
    """Zero-pad (y, mask-as-float) to a multiple of ``seg`` timesteps and
    reshape to (n_seg, seg, ...) — padded steps are all-masked no-ops.
    One definition shared by both score paths so the padding semantics
    cannot drift between them."""
    t_steps = y.shape[0]
    maskf = jnp.asarray(mask, dtype)
    pad = (-t_steps) % seg
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad,) + y.shape[1:], dtype)])
        maskf = jnp.concatenate(
            [maskf, jnp.zeros((pad,) + maskf.shape[1:], dtype)]
        )
    return (
        y.reshape(-1, seg, *y.shape[1:]),
        maskf.reshape(-1, seg, *maskf.shape[1:]),
    )


def _lanes_filter_terms(phi, q, z, r, y, mask, remat_seg):
    """Per-timestep (sigma, detf), both (T, B), via the masked
    sequential-processing filter in lane layout (checkpointed segments;
    shares the single filter-step definition ``_adj_step`` with the
    analytical-adjoint path so the two score paths cannot drift)."""
    n, b = phi.shape
    t_steps = y.shape[0]
    dtype = phi.dtype
    eye = jnp.eye(n, dtype=dtype)[:, :, None]
    y_seg, m_seg = _segment(y, mask, remat_seg, dtype)

    @jax.checkpoint
    def seg_body(carry, xs):
        def step(c, t_xs):
            c2, out, _ = _adj_step(phi, q, z, r, c, *t_xs, eye)
            return c2, out

        return lax.scan(step, carry, xs)

    _, (sigma, detf) = lax.scan(
        seg_body, _adj_init_carry(phi, eye), (y_seg, m_seg)
    )
    t_pad = sigma.shape[0] * sigma.shape[1]
    return (
        sigma.reshape(t_pad, b)[:t_steps],
        detf.reshape(t_pad, b)[:t_steps],
    )


def lanes_deviance_terms(sigma, detf, mask, warmup: int = 1):
    """Combine (T, B) filter terms into per-lane deviances.

    Same semantics as :func:`metran_tpu.ops.kalman.deviance_terms`
    (reference ``SPKalmanFilter.get_mle``): sigma/detf sums skip the
    first ``warmup`` *observed* timesteps; nobs skips the first
    ``warmup`` *grid* timesteps.
    """
    dtype = sigma.dtype
    count = jnp.sum(mask, axis=1)  # (T, B)
    has_obs = count > 0
    obs_rank = jnp.cumsum(has_obs, axis=0) - 1
    keep = has_obs & (obs_rank >= warmup)
    t_steps = count.shape[0]
    nobs = jnp.sum(
        jnp.where(jnp.arange(t_steps)[:, None] >= warmup, count, 0), axis=0
    )
    return (
        nobs.astype(dtype) * jnp.asarray(LOG2PI, dtype)
        + jnp.sum(jnp.where(keep, detf, 0.0), axis=0)
        + jnp.sum(jnp.where(keep, sigma, 0.0), axis=0)
    )


def _run_segments(phi, q, z, r, y_seg, m_seg, keep_bounds):
    """Forward filter over pre-segmented inputs; one definition for the
    custom-vjp primal and fwd rules.  Returns flattened (sigma, detf)
    plus the stacked segment-boundary carries when ``keep_bounds``."""
    n = phi.shape[0]
    eye = jnp.eye(n, dtype=phi.dtype)[:, :, None]

    def body(c, xs):
        def inner(cc, t_xs):
            cc2, out, _ = _adj_step(phi, q, z, r, cc, *t_xs, eye)
            return cc2, out

        c2, out = lax.scan(inner, c, xs)
        return (c2, out + (c,)) if keep_bounds else (c2, out)

    _, outs = lax.scan(body, _adj_init_carry(phi, eye), (y_seg, m_seg))
    sig, det = outs[0], outs[1]
    t_pad, b = sig.shape[0] * sig.shape[1], sig.shape[2]
    flat = (sig.reshape(t_pad, b), det.reshape(t_pad, b))
    return flat + (outs[2],) if keep_bounds else flat + (None,)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def _terms_adjoint_core(phi, q, z, r, y_seg, m_seg, seg):
    """Segmented filter terms with an analytical (phi, q) adjoint.

    See :func:`_lanes_terms_adjoint` for the derivation and layout; this
    core takes pre-segmented ``y_seg``/``m_seg`` of shape
    (n_seg, seg, N, B) (mask as float) and returns (sigma, detf) of
    shape (n_seg*seg, B).
    """
    sig, det, _ = _run_segments(phi, q, z, r, y_seg, m_seg, False)
    return sig, det


def _terms_adjoint_fwd(phi, q, z, r, y_seg, m_seg, seg):
    sig, det, bounds = _run_segments(phi, q, z, r, y_seg, m_seg, True)
    return (sig, det), (phi, q, z, r, y_seg, m_seg, bounds)


def _terms_adjoint_bwd(seg, residuals, cotangents):
    phi, q, z, r, y_seg, m_seg, bounds = residuals
    n, b = phi.shape
    dtype = phi.dtype
    eye = jnp.eye(n, dtype=dtype)[:, :, None]
    n_seg = y_seg.shape[0]
    sb_all, db_all = cotangents
    sb_seg = sb_all.reshape(n_seg, seg, b)
    db_seg = db_all.reshape(n_seg, seg, b)

    def step_bwd(ubar, stored, sb_t, db_t, m_t):
        mean0, cov0, d_all, f_all, v_all = stored
        u, s = ubar  # adjoint of the step-END (post-update) state

        def series_bwd(carry, xs):
            u, s = carry
            d, f, v, z_i, mask_i = xs
            obs = mask_i > 0
            ud = jnp.sum(u * d, axis=0)  # (B,)
            sd = jnp.sum(s * d[None, :, :], axis=1)  # S d
            std = jnp.sum(s * d[:, None, :], axis=0)  # S' d
            dsd = jnp.sum(d * sd, axis=0)
            vbar = 2.0 * sb_t * v / f + ud / f
            fbar = (-sb_t * v * v / (f * f) + db_t / f
                    + dsd / (f * f) - ud * v / (f * f))
            dvec = -(sd + std) / f + u * (v / f) + fbar * z_i
            s_new = s + dvec[:, None, :] * z_i[None, :, :]
            u_new = u - vbar * z_i
            u = jnp.where(obs, u_new, u)
            s = jnp.where(obs, s_new, s)
            return (u, s), None

        (u, s), _ = lax.scan(
            series_bwd, (u, s), (d_all, f_all, v_all, z, m_t),
            reverse=True,
        )
        # predict backward: (u, s) is now the adjoint of
        # (mean_p, cov_p); mean0/cov0 are the pre-predict carry
        sc = s * cov0
        phibar_t = (
            u * mean0
            + jnp.sum(sc * phi[None, :, :], axis=1)
            + jnp.sum(sc * phi[:, None, :], axis=0)
        )
        qbar_t = jnp.sum(s * eye, axis=1)  # diag(S)
        u_prev = u * phi
        s_prev = s * phi[:, None, :] * phi[None, :, :]
        return (u_prev, s_prev), phibar_t, qbar_t

    def seg_replay(carry, ys, ms):
        """Replay one segment, stacking per-step residuals."""
        def body(c, xs):
            c2, _, res = _adj_step(phi, q, z, r, c, *xs, eye)
            return c2, (c[0], c[1]) + res

        return lax.scan(body, carry, (ys, ms))[1]

    def seg_bwd(carry, seg_idx):
        ubar, pb, qb = carry
        stored = seg_replay(
            jax.tree.map(lambda a: a[seg_idx], bounds),
            y_seg[seg_idx], m_seg[seg_idx],
        )
        sb_s, db_s, m_s = (
            sb_seg[seg_idx], db_seg[seg_idx], m_seg[seg_idx]
        )

        def body(c, t):
            ub, pbi, qbi = c
            ub, pbar_t, qbar_t = step_bwd(
                ub, jax.tree.map(lambda a: a[t], stored),
                sb_s[t], db_s[t], m_s[t],
            )
            return (ub, pbi + pbar_t, qbi + qbar_t), None

        (ubar, pb, qb), _ = lax.scan(
            body, (ubar, pb, qb), jnp.arange(seg), reverse=True
        )
        return (ubar, pb, qb), None

    ubar0 = (jnp.zeros((n, b), dtype), jnp.zeros((n, n, b), dtype))
    (_, phibar, qbar), _ = lax.scan(
        seg_bwd, (ubar0, jnp.zeros_like(phi), jnp.zeros_like(q)),
        jnp.arange(n_seg), reverse=True,
    )
    return (phibar, qbar, jnp.zeros_like(z), jnp.zeros_like(r),
            jnp.zeros_like(y_seg), jnp.zeros_like(m_seg))


_terms_adjoint_core.defvjp(_terms_adjoint_fwd, _terms_adjoint_bwd)


def _lanes_terms_adjoint(phi, q, z, r, y, mask, seg):
    """Filter terms with a hand-derived analytical (phi, q) adjoint.

    JAX autodiff through the sequential-update scan generates a backward
    pass ~5x the forward cost (generic transposition materializes an
    adjoint temporary per rank-1 update).  The score of a linear-
    Gaussian state-space model has a compact closed-form adjoint,
    derived per series update (validated against autodiff to machine
    precision in tests/test_lanes_adjoint.py):

        v = y_i - z_i.m ; d = P z_i ; f = z_i.d ; k = d/f
        m' = m + k v ;  P' = P - d d'/f
        sigma_t += v^2/f ; detf_t += log f

    with incoming adjoints ``u = mbar'``, ``S = Pbar'``, ``sb``, ``db``:

        vbar = 2 sb v/f + (u.d)/f
        fbar = -sb v^2/f^2 + db/f + (d'Sd)/f^2 - (u.d) v/f^2
        dbar = -(S + S')d/f + u v/f + fbar z_i
        Pbar = S + outer(dbar, z_i) ;  mbar = u - vbar z_i

    and for the predict step ``m_p = phi m``, ``P_p = (phi phi')P +
    diag(q)``:

        phibar += u m + sum_j S_kj phi_j P_kj + sum_i S_ik phi_i P_ik
        qbar   += diag(S)
        mbar = u phi ;  Pbar_ij = S_ij phi_i phi_j

    Memory: the forward stores only segment-boundary carries
    (O(T/seg n^2 B)); the backward replays each segment once, storing
    that segment's per-step (carry, d, f, v) residuals, then runs the
    reverse sweep — the same two-level rematerialization the autodiff
    path uses, with a leaner hand-written inner adjoint.  Cotangents
    are produced for (phi, q) only; z/r/y/mask are fixed data in the
    MLE (the optimizer differentiates the AR decay parameters alpha).
    """
    t_steps = y.shape[0]
    y_seg, m_seg = _segment(y, mask, seg, z.dtype)
    sig, det = _terms_adjoint_core(phi, q, z, r, y_seg, m_seg, seg)
    return sig[:t_steps], det[:t_steps]


@functools.partial(
    jax.jit, static_argnames=("warmup", "remat_seg", "score")
)
def lanes_dfm_deviance(
    alpha: jnp.ndarray,
    loadings: jnp.ndarray,
    dt: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    warmup: int = 1,
    remat_seg: Optional[int] = 100,
    score: str = "adjoint",
) -> jnp.ndarray:
    """(B,) deviance of a fleet at ``alpha`` — the lanes hot path.

    Numerically step-for-step the sequential-processing engine
    (``engine="sequential"`` of :func:`metran_tpu.ops.deviance`), so its
    values match the reference parity bar; only the array layout (and
    hence rounding-neutral op order within each reduction) differs.

    ``score="adjoint"`` (default) uses the hand-derived analytical
    (phi, q) adjoint under differentiation (~2x faster than autodiff
    through the scan on TPU v5e, same values to f32 rounding).  The
    adjoint differentiates the MLE parameters only: gradients w.r.t.
    ``alpha`` and ``dt`` are exact, while ``loadings``, ``y`` and
    ``mask`` are treated as fixed data (``stop_gradient`` — their
    cotangents are exactly zero, never silently partial).  Pass
    ``score="autodiff"`` to differentiate the plain checkpointed scan
    instead when gradients w.r.t. loadings or observations are needed.
    Both scores execute the same single forward-step definition
    (``_adj_step``), so their values are identical.
    """
    if score == "adjoint":
        # the analytical adjoint covers (phi, q) only: freeze the data
        # inputs so their gradients are an explicit zero rather than a
        # silently partial value (loadings otherwise still reaches q
        # through the communality term)
        phi, q, z, r = lanes_statespace(
            alpha, lax.stop_gradient(loadings), dt
        )
        y = lax.stop_gradient(y)
        sigma, detf = _lanes_terms_adjoint(
            phi, q, z, r, y, mask, remat_seg or y.shape[0]
        )
    elif score == "autodiff":
        phi, q, z, r = lanes_statespace(alpha, loadings, dt)
        sigma, detf = _lanes_filter_terms(
            phi, q, z, r, y, mask, remat_seg or y.shape[0]
        )
    else:
        raise ValueError(
            f"unknown score {score!r}; expected 'adjoint' or 'autodiff'"
        )
    return lanes_deviance_terms(sigma, detf, mask, warmup=warmup)
