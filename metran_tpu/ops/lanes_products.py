"""Lane-layout post-fit products: smoother, projections, innovations.

The fit hot path runs in lane layout (:mod:`metran_tpu.ops.lanes`) at
~50 fits/s/chip; through round 4 the post-fit products (smoother /
simulate / decompose / innovations) still ran batch-leading and measured
5-6 models/s on-chip — for a fit+products workflow the products were the
wall.  This module gives them the same lane treatment as the fit:

- **Smoother**: the Durbin-Koopman *univariate-treatment* backward
  recursion on the adjoints ``(r_t, N_t)`` (Durbin & Koopman 2012,
  section 6.4; the sequential-processing dual of the forward filter in
  ``ops/lanes.py``), NOT the RTS gain form.  The RTS gain needs a
  per-step (n, n) Cholesky solve, which XLA serializes per model; the
  D-K recursion is rank-1 elementwise/broadcast updates across the lane
  axis throughout — nothing the TPU can't tile.  On the same filter it
  produces the same smoothed moments as the reference's ``kalmansmoother``
  (``metran/kalmanfilter.py:403-476``); parity vs :func:`ops.rts_smoother`
  is pinned by tests/test_lanes_products.py.
- **Memory** follows the adjoint-score discipline of ``ops/lanes.py``:
  the forward pass stores segment-boundary carries only; the backward
  replays one segment at a time, so peak residual memory is
  O(seg * N * n * B) instead of O(T * n^2 * B).
- **Innovations** use the joint (vector) definition from the
  time-predicted moments — identical semantics to :func:`ops.innovations`
  (series-order independent), emitted by a forward-only lane scan.

Shapes follow ops/lanes.py: the fleet axis B is LAST everywhere.
    phi, q   (n, B)     diagonal transition / process noise
    z        (N, n, B)  observation rows
    r        (N, B)     measurement noise
    y, mask  (T, N, B)  observations / observed flags
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .lanes import (
    _adj_init_carry,
    _adj_step,
    _predict_step,
    _segment,
    _update_scan,
)


def _zdot(z, m):
    """Observation-space means ``Z m`` in lane layout: (N, B)."""
    return jnp.einsum("iaB,aB->iB", z, m)


def _zcovz(z, cov):
    """Projected variances ``diag(Z C Z')`` in lane layout: (N, B)."""
    return jnp.einsum("iaB,abB,ibB->iB", z, cov, z)


def _series_bwd(carry, xs, want_cov: bool):
    """One reverse series update of the D-K adjoints.

    With ``k_i = d_i / f_i`` and ``L_i = I - k_i z_i'``:

        r  <-  z_i v_i / f_i + L_i' r
        N  <-  z_i z_i' / f_i + L_i' N L_i

    expanded to rank-1 broadcast form (no matmuls):

        L_i' r      = r - z_i (k_i . r)
        L_i' N L_i  = N - z_i (k'N) - (N k) z_i' + z_i z_i' (k'N k)
    """
    r_adj, n_adj = carry
    d, f, v, z_i, mask_i = xs
    obs = mask_i > 0
    k = d / f
    kr = jnp.sum(k * r_adj, axis=0)  # (B,)
    r_new = r_adj + z_i * (v / f - kr)
    r_adj = jnp.where(obs, r_new, r_adj)
    if want_cov:
        # N is symmetric throughout the recursion (starts at 0; the
        # rank-1 update and the diagonal transition both preserve
        # symmetry), so N'k == Nk — one reduction instead of two
        nk = jnp.sum(n_adj * k[None, :, :], axis=1)  # N k   (n, B)
        knk = jnp.sum(k * nk, axis=0)  # (B,)
        n_new = (
            n_adj
            - z_i[:, None, :] * nk[None, :, :]
            - nk[:, None, :] * z_i[None, :, :]
            + z_i[:, None, :] * z_i[None, :, :] * (knk + 1.0 / f)
        )
        n_adj = jnp.where(obs, n_new, n_adj)
    return (r_adj, n_adj), None


def _smooth_emit(phi, z, rn, mean_p, cov_p, want_cov: bool):
    """Smoothed moments at one timestep from the predicted moments and
    the post-series adjoints ``r_{t,0} / N_{t,0}``:

        m_s = m_p + P_p r ;  P_s = P_p - P_p N P_p

    emitting the observation-space projections directly (``Z m_s``,
    ``diag(Z P_s Z')``) so the (n, n, B) smoothed covariance is never
    materialized across time.  Returns the transitioned adjoints for
    t-1 plus the per-step outputs."""
    r_adj, n_adj = rn
    mean_s = mean_p + jnp.sum(cov_p * r_adj[None, :, :], axis=1)
    pm = _zdot(z, mean_s)
    if want_cov:
        dp = jnp.einsum("iaB,ajB->ijB", z, cov_p)  # rows Z P_p  (N, n, B)
        pv = jnp.maximum(
            jnp.einsum("ijB,ijB->iB", z, dp) - _zcovz(dp, n_adj), 0.0
        )
    else:
        pv = jnp.zeros_like(pm)
    # transition the adjoints across the (diagonal) state recursion
    r_adj = phi * r_adj
    if want_cov:
        n_adj = phi[:, None, :] * n_adj * phi[None, :, :]
    return (r_adj, n_adj), (mean_s, pm, pv)


@functools.partial(jax.jit, static_argnames=("seg", "want_cov"))
def lanes_smooth(
    phi: jnp.ndarray,
    q: jnp.ndarray,
    z: jnp.ndarray,
    r: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    seg: int = 100,
    want_cov: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Smoothed states and observation-space projections, lane layout.

    Returns ``(mean_s, proj_mean, proj_var)`` of shapes
    (T, n, B), (T, N, B), (T, N, B) — the lane analog of
    ``rts_smoother`` + ``project`` (reference ``kalmansmoother`` +
    ``simulate``, ``metran/kalmanfilter.py:403-476,569-603``).  With
    ``want_cov=False`` the N recursion is skipped entirely (about 3x
    cheaper) and ``proj_var`` is zeros — for consumers that need
    smoothed means only (decompose, the simulation smoother).
    """
    t_steps = y.shape[0]
    dtype = phi.dtype
    n, b = phi.shape
    eye = jnp.eye(n, dtype=dtype)[:, :, None]
    y_seg, m_seg = _segment(y, mask, seg, dtype)
    n_seg = y_seg.shape[0]

    # forward: keep segment-boundary carries only
    def fwd_body(c, xs):
        def inner(cc, t_xs):
            cc2, _, _ = _adj_step(phi, q, z, r, cc, *t_xs, eye)
            return cc2, None

        c2, _ = lax.scan(inner, c, xs)
        return c2, c

    _, bounds = lax.scan(
        fwd_body, _adj_init_carry(phi, eye), (y_seg, m_seg)
    )

    def seg_replay(carry, ys, ms):
        """Replay one segment, storing per-step predicted moments and
        series residuals for the backward sweep."""

        def body(c, xs):
            mean_p, cov_p = _predict_step(phi, q, c, eye)
            (m_f, p_f, _, _), res = _update_scan(
                z, r, mean_p, cov_p, *xs, dtype
            )
            return (m_f, p_f), (mean_p, cov_p) + res

        return lax.scan(body, carry, (ys, ms))[1]

    def step_bwd(rn, stored, m_t):
        mean_p, cov_p, d_all, f_all, v_all = stored
        rn, _ = lax.scan(
            functools.partial(_series_bwd, want_cov=want_cov),
            rn,
            (d_all, f_all, v_all, z, m_t),
            reverse=True,
        )
        return _smooth_emit(phi, z, rn, mean_p, cov_p, want_cov)

    def seg_bwd(rn, seg_idx):
        stored = seg_replay(
            jax.tree.map(lambda a: a[seg_idx], bounds),
            y_seg[seg_idx],
            m_seg[seg_idx],
        )
        m_s = m_seg[seg_idx]

        def body(c, t):
            return step_bwd(
                c, jax.tree.map(lambda a: a[t], stored), m_s[t]
            )

        return lax.scan(body, rn, jnp.arange(seg), reverse=True)

    rn0 = (
        jnp.zeros((n, b), dtype),
        # mean-only consumers skip the N recursion: a scalar dummy keeps
        # the (n, n, B) adjoint out of every scan carry
        jnp.zeros((n, n, b), dtype) if want_cov
        else jnp.zeros((), dtype),
    )
    _, (mean_s, pm, pv) = lax.scan(
        seg_bwd, rn0, jnp.arange(n_seg), reverse=True
    )
    t_pad = n_seg * seg
    n_obs = y.shape[1]
    return (
        mean_s.reshape(t_pad, n, b)[:t_steps],
        pm.reshape(t_pad, n_obs, b)[:t_steps],
        pv.reshape(t_pad, n_obs, b)[:t_steps],
    )


@jax.jit
def lanes_filter_project(
    phi: jnp.ndarray,
    q: jnp.ndarray,
    z: jnp.ndarray,
    r: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Filtered states and observation-space projections, lane layout.

    Returns ``(mean_f, proj_mean, proj_var)`` — the ``smooth=False``
    analog of :func:`lanes_smooth` (reference ``simulate`` on the
    filtered moments).  Forward-only scan, no segment storage."""
    dtype = phi.dtype
    n = phi.shape[0]
    eye = jnp.eye(n, dtype=dtype)[:, :, None]
    maskf = jnp.asarray(mask, dtype)

    def step(c, xs):
        c2, _, _ = _adj_step(phi, q, z, r, c, *xs, eye)
        m_f, p_f = c2
        pm = _zdot(z, m_f)
        pv = jnp.maximum(_zcovz(z, p_f), 0.0)
        return c2, (m_f, pm, pv)

    _, outs = lax.scan(step, _adj_init_carry(phi, eye), (y, maskf))
    return outs


@functools.partial(jax.jit, static_argnames=("standardized",))
def lanes_innovations(
    phi: jnp.ndarray,
    q: jnp.ndarray,
    z: jnp.ndarray,
    r: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    standardized: bool = True,
    warmup: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One-step-ahead innovations in lane layout, (T, N, B).

    Joint (vector) definition from the time-predicted moments —
    ``v_t = y_t - Z m_{t|t-1}``, ``F_t = diag(Z P_{t|t-1} Z') + r`` —
    identical semantics to :func:`metran_tpu.ops.innovations`
    (series-order independent, unlike the sequential per-scalar
    updates the filter itself runs).  NaN where unobserved or before
    ``warmup`` (traced, no recompile across warmup values)."""
    dtype = phi.dtype
    n = phi.shape[0]
    eye = jnp.eye(n, dtype=dtype)[:, :, None]
    maskf = jnp.asarray(mask, dtype)

    def step(c, xs):
        y_t, m_t = xs
        mean_p, cov_p = _predict_step(phi, q, c, eye)
        pm = _zdot(z, mean_p)
        # clip like ops.project: with r = 0 a tight posterior can round
        # z'P_p z slightly negative in f32, which would blow up the
        # standardized residual
        pv = jnp.maximum(_zcovz(z, cov_p), 0.0)
        v = y_t - pm
        f = pv + r
        (m_f, p_f, _, _), _ = _update_scan(
            z, r, mean_p, cov_p, y_t, m_t, dtype
        )
        return (m_f, p_f), (v, f)

    _, (v, f) = lax.scan(step, _adj_init_carry(phi, eye), (y, maskf))
    if standardized:
        v = v / jnp.sqrt(jnp.maximum(f, jnp.finfo(dtype).tiny))
    keep = (jnp.asarray(mask, bool)) & (
        jnp.arange(y.shape[0])[:, None, None] >= warmup
    )
    nan = jnp.asarray(jnp.nan, dtype)
    return jnp.where(keep, v, nan), jnp.where(keep, f, nan)


@functools.partial(jax.jit, static_argnames=("steps",))
def lanes_forecast(
    phi: jnp.ndarray,
    q: jnp.ndarray,
    z: jnp.ndarray,
    r: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    t_last: jnp.ndarray,
    steps: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Out-of-sample observation forecasts in lane layout.

    The lane analog of :mod:`metran_tpu.ops.forecast` (no reference
    equivalent): one forward filter pass that LATCHES each lane's
    filtered moments at its own ``t_last`` (members forecast from their
    own data end, not the padded grid end), then the closed-form
    diagonal-transition h-step moments vectorized over horizons — same
    ``expm1``-guarded geometric accumulation as
    ``forecast_state_moments``, with the lanes' diagonal ``q``.
    Returns ``(means, variances)`` of shape (steps, N, B)."""
    dtype = phi.dtype
    n, b = phi.shape
    eye = jnp.eye(n, dtype=dtype)[:, :, None]
    maskf = jnp.asarray(mask, dtype)
    t_steps = y.shape[0]
    t_last = jnp.asarray(t_last, jnp.int32)

    def step(carry, xs):
        state, latch = carry
        t, y_t, m_t = xs
        state2, _, _ = _adj_step(phi, q, z, r, state, y_t, m_t, eye)
        hit = t == (t_last - 1)  # (B,)
        lm = jnp.where(hit[None, :], state2[0], latch[0])
        lp = jnp.where(hit[None, None, :], state2[1], latch[1])
        return (state2, (lm, lp)), None

    init = _adj_init_carry(phi, eye)
    (_, (m0, p0)), _ = lax.scan(
        step, (init, init),
        (jnp.arange(t_steps), y, maskf),
    )

    h1 = jnp.arange(1, steps + 1, dtype=dtype)[:, None, None]  # (H,1,1)
    h2 = h1[..., None]  # (H,1,1,1)
    phih = phi[None] ** h1  # (H, n, B)
    mean_h = phih * m0[None]
    log_pp = jnp.log(phi[:, None, :] * phi[None, :, :])  # (n, n, B)
    pp_h = jnp.exp(h2 * log_pp[None])  # (H, n, n, B)
    # expm1 form of (1 - pp^h)/(1 - pp); the pp == 1 limit is h (same
    # guard as forecast_state_moments)
    denom = jnp.expm1(log_pp)
    at_one = denom == 0
    geom = jnp.where(
        at_one[None],
        h2 * jnp.ones_like(log_pp)[None],
        jnp.expm1(h2 * log_pp[None])
        / jnp.where(at_one, 1.0, denom)[None],
    )
    cov_h = pp_h * p0[None] + geom * (eye * q[None])[None]
    pm = jnp.einsum("iaB,haB->hiB", z, mean_h)
    pv = jnp.maximum(
        jnp.einsum("iaB,habB,ibB->hiB", z, cov_h, z), 0.0
    )
    return pm, pv + r[None]


@functools.partial(
    jax.jit, static_argnames=("n_draws", "seg", "project")
)
def lanes_sample(
    phi: jnp.ndarray,
    q: jnp.ndarray,
    z: jnp.ndarray,
    r: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    keys,
    n_draws: int = 16,
    seg: int = 100,
    project: bool = True,
) -> jnp.ndarray:
    """Durbin-Koopman simulation smoother with draws riding the lanes.

    The lane analog of :func:`metran_tpu.ops.sample_states`: one
    mean-only smoothing of the data (B lanes), then every (model, draw)
    pair becomes its own lane — the unconditional path draw, its
    pseudo-observations on the same missing pattern, and the pseudo
    smoothing all run as ONE (B * n_draws)-lane pass instead of a
    per-model ``lax.map`` over draws.  ``keys`` is one PRNG key per
    model (B,): each model's draws are a function of ITS key only, so
    results are invariant to how a caller chunks the fleet axis.
    Returns (n_draws, T, N, B) observation-space draws when ``project``
    (passing exactly through each model's observed entries when r = 0)
    or (n_draws, T, n, B) state draws otherwise."""
    dtype = phi.dtype
    t_steps, n_obs, b = y.shape
    n = phi.shape[0]

    sm_data, _, _ = lanes_smooth(
        phi, q, z, r, y, mask, seg=seg, want_cov=False
    )  # (T, n, B)

    def rep(a):
        return jnp.tile(a, (1,) * (a.ndim - 1) + (n_draws,))

    phi_l, q_l, z_l, r_l = rep(phi), rep(q), rep(z), rep(r)
    bl = b * n_draws
    # per-model normals (chunk-invariant), rearranged so lane = d*B + m
    # matches the tile() cycling of the model arrays above
    def model_normals(key, shape):
        # (B,) keys -> (*shape, n_draws) per model -> (*shape, D*B)
        draws = jax.vmap(
            lambda k: jax.random.normal(k, shape + (n_draws,), dtype)
        )(key)  # (B, *shape, D)
        moved = jnp.moveaxis(draws, 0, -1)  # (*shape, D, B)
        return moved.reshape(shape + (bl,))

    ks = jax.vmap(lambda k: jax.random.split(k, 3))(keys)  # (B, 3, 2)
    # unconditional state path from the filter's own prior: x0 ~ N(0, I),
    # then the diagonal AR recursion with diagonal Q (elementwise lanes)
    q_sd = jnp.sqrt(jnp.clip(q_l, 0.0))
    x0 = model_normals(ks[:, 0], (n,))
    w = model_normals(ks[:, 1], (t_steps, n)) * q_sd[None]

    def ar_step(x, w_t):
        x = phi_l * x + w_t
        return x, x

    _, xs = lax.scan(ar_step, x0, w)  # (T, n, BL)
    y_star = jnp.einsum("iaB,taB->tiB", z_l, xs)
    r_sd = jnp.sqrt(jnp.clip(r_l, 0.0))
    y_star = y_star + model_normals(ks[:, 2], (t_steps, n_obs)) * r_sd
    mask_l = rep(jnp.asarray(mask, dtype))
    sm_star, _, _ = lanes_smooth(
        phi_l, q_l, z_l, r_l, y_star, mask_l, seg=seg, want_cov=False
    )
    draws = rep(sm_data) + xs - sm_star  # (T, n, BL)
    if project:
        draws = jnp.einsum("iaB,taB->tiB", z_l, draws)
    # (T, *, B*D) -> (D, T, *, B): tile() cycles the fleet fastest, so
    # lane index = d * B + model
    d = draws.reshape(t_steps, -1, n_draws, b)
    return jnp.transpose(d, (2, 0, 1, 3))
