"""Parallel-in-time Kalman filtering/smoothing via associative scans.

The sequential filter (``metran_tpu.ops.kalman``) has O(T) depth — each
timestep waits for the previous one.  This module reformulates the same
Bayesian recursions as **associative operators** combined with
``jax.lax.associative_scan`` (temporal parallelization of Bayesian
filters/smoothers, cf. PAPERS.md "Parallel-in-Time Kalman Smoothing"),
giving O(log T) depth on parallel hardware and making the *time axis* a
shardable dimension: under ``jit`` with the elements sharded over a mesh
axis, XLA turns the combine tree into collectives over ICI — the
framework's sequence-parallelism backend for long series.

The reference implementation has no equivalent (its recursion is a numba
loop, ``metran/kalmanfilter.py:236-400``); results are numerically
equivalent to the sequential engines and tested against them to float64
precision.

Missing data is handled with the same static-shape trick as the joint
update: masked observation rows are zeroed in Z and given unit pseudo-
noise, which provably leaves gains, likelihood terms, and posteriors
identical to conditioning on the observed subset only.

Filtering elements (per timestep): ``(A, b, C, J, eta)`` such that the
pair ``(b, C)`` of the combined prefix equals the filtered mean/cov.
Smoothing elements: ``(E, g, L)`` combined in reverse.

A square-root variant (``sqrt_parallel_filter``/``sqrt_parallel_
smoother``) carries the covariance parts of the elements as
lower-triangular Cholesky factors combined via orthogonal
transformations — per-step moments PSD by construction in float32,
the robustness layer of arXiv:2502.11686 on the same combine
machinery (including :func:`blocked_associative_scan`).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .kalman import FilterResult, SmootherResult
from .statespace import StateSpace


AUTO_BLOCK = 512  # block size picked when block="auto" resolves at long T
AUTO_BLOCK_MIN_T = 2048  # full-length scan below this (small programs)


def _resolve_block(block, t: int):
    """``"auto"`` -> ``AUTO_BLOCK`` for long series, else full-length.

    The full-length combine tree's HLO grows with ``log2(T)`` levels of
    progressively-sliced ops; beyond a few thousand steps it is slow to
    compile everywhere and has crashed XLA:CPU outright (segfault in
    ``backend_compile_and_load`` at T=6,255 on a 1-core host, round 4).
    ``"auto"`` keeps exact full-length semantics for short series and
    switches to the blocked decomposition when compile size starts to
    matter.
    """
    if block == "auto":
        return AUTO_BLOCK if t > AUTO_BLOCK_MIN_T else None
    return block


def _masked_obs(ss: StateSpace, mask_t, dtype):
    """Static-shape masked observation model for one timestep.

    Masked slots get a zero Z-row and unit observation noise; with y=0
    there they contribute nothing to gains or likelihood (log 1 = 0).
    """
    maskf = mask_t.astype(dtype)
    z_t = ss.z * maskf[:, None]
    r_t = jnp.where(mask_t, ss.r, 0.0) + (1.0 - maskf)
    return z_t, r_t


def blocked_associative_scan(combine, elements, block: int,
                             reverse: bool = False):
    """``lax.associative_scan`` with compile cost O(log block), not O(log T).

    The full-length associative scan unrolls ~log2(T) combine levels over
    progressively-sliced arrays; at T = 32k that is a huge HLO program
    (measured 188.8 s XLA compile on TPU for the filter, BASELINE.md
    round 3).  By associativity the same prefix (suffix, when
    ``reverse``) combines decompose into

    1. within-block scans over ``block`` elements — ONE compiled
       program, ``vmap``-ed over the T/block blocks;
    2. a sequential ``lax.scan`` over the block totals (T/block steps of
       a single combine — trivial to compile, negligible to run);
    3. one broadcast combine applying each block's incoming exclusive
       prefix/suffix to its within-block results.

    Results are numerically equivalent (same operator, same element
    order; only the combine tree's shape changes, so values agree to
    floating-point reassociation rounding — parity-tested at 1e-10).
    ``combine`` must be elementwise
    along the leading axis of its inputs — the ``associative_scan``
    contract.  A non-divisible tail is padded with replicated edge
    elements on the side that cannot influence the kept results (after
    the true end for forward scans, before the true start for reverse)
    and trimmed.
    """
    leaves = jax.tree.leaves(elements)
    t = leaves[0].shape[0]
    if block >= t:
        return lax.associative_scan(combine, elements, reverse=reverse)
    nb = -(-t // block)
    pad = nb * block - t

    def prep(x):
        if pad:
            edge = x[:1] if reverse else x[-1:]
            reps = jnp.broadcast_to(edge, (pad,) + x.shape[1:])
            x = jnp.concatenate([reps, x] if reverse else [x, reps], axis=0)
        return x.reshape((nb, block) + x.shape[1:])

    el = jax.tree.map(prep, elements)
    within = jax.vmap(
        lambda e: lax.associative_scan(combine, e, reverse=reverse)
    )(el)
    # block totals, then their exclusive running combine across blocks.
    # In both directions ``combine``'s first argument is the
    # already-combined far side (earlier prefix forward, later suffix in
    # reverse), so the cross-block steps share one expression.
    # block totals keep a singleton leading axis: ``combine`` is
    # elementwise over the leading axis by contract, so single elements
    # are passed as length-1 batches
    totals = jax.tree.map(
        lambda x: x[:, :1] if reverse else x[:, -1:], within
    )
    edge_tot = jax.tree.map(
        lambda x: x[-1] if reverse else x[0], totals
    )
    inner_tot = jax.tree.map(
        lambda x: x[:-1] if reverse else x[1:], totals
    )
    _, excl = lax.scan(
        lambda carry, tot: (combine(carry, tot), carry),
        edge_tot, inner_tot, reverse=reverse,
    )
    # apply the incoming combine to every block that has one; the edge
    # block (first forward, last in reverse) passes through unchanged
    affected = jax.tree.map(
        lambda x: x[:-1] if reverse else x[1:], within
    )

    def apply(pref, win):
        s = jax.tree.leaves(win)[0].shape[0]
        pref_b = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (s,) + x.shape[1:]), pref
        )
        return combine(pref_b, win)

    applied = jax.vmap(apply)(excl, affected)
    edge_win = jax.tree.map(
        lambda x: x[-1:] if reverse else x[:1], within
    )
    parts = [applied, edge_win] if reverse else [edge_win, applied]
    out = jax.tree.map(
        lambda a, b: jnp.concatenate([a, b], axis=0), parts[0], parts[1]
    )
    out = jax.tree.map(
        lambda x: x.reshape((nb * block,) + x.shape[2:]), out
    )
    if pad:
        out = jax.tree.map(
            lambda x: x[pad:] if reverse else x[:t], out
        )
    return out


def _filter_element(ss: StateSpace, y_t, mask_t, p_prior, first, dtype):
    """Build one associative filtering element.

    ``p_prior`` is the predicted covariance entering this step when it is
    the first one (Phi I Phi' + Q, reference init semantics); interior
    steps use Q (the paper's construction with A = Phi absorbed).
    """
    n = ss.phi.shape[-1]
    eye = jnp.eye(n, dtype=dtype)
    z_t, r_t = _masked_obs(ss, mask_t, dtype)

    cov_pred = jnp.where(first, p_prior, ss.q)
    phi_eff = jnp.where(first, jnp.zeros_like(ss.phi), ss.phi)

    s = z_t @ cov_pred @ z_t.T + jnp.diag(r_t)
    chol = jnp.linalg.cholesky(s)
    # an innovation covariance indefinite in f32 would make the raw
    # Cholesky emit NaN columns that the combine then spreads over the
    # whole scan; degrade this step to the no-observation element (the
    # post-scan loglik terms book its +inf) instead
    ok = jnp.all(jnp.isfinite(chol))
    chol_safe = jnp.where(ok, chol, jnp.eye(s.shape[0], dtype=dtype))
    # K = cov_pred Z' S^-1  (via Cholesky solves)
    k = jax.scipy.linalg.cho_solve((chol_safe, True), z_t @ cov_pred).T
    ikh = eye - k @ z_t

    a = ikh * phi_eff[None, :]  # (I - K Z) Phi, diagonal Phi
    b = k @ y_t
    c = ikh @ cov_pred
    # eta = Phi' Z' S^-1 y ; J = Phi' Z' S^-1 Z Phi
    sinv_y = jax.scipy.linalg.cho_solve((chol_safe, True), y_t)
    sinv_z = jax.scipy.linalg.cho_solve((chol_safe, True), z_t)
    eta = phi_eff * (z_t.T @ sinv_y)
    j = (z_t.T @ sinv_z) * jnp.outer(phi_eff, phi_eff)
    a = jnp.where(ok, a, jnp.diag(phi_eff))
    b = jnp.where(ok, b, jnp.zeros_like(b))
    c = jnp.where(ok, c, cov_pred)
    j = jnp.where(ok, j, jnp.zeros_like(j))
    eta = jnp.where(ok, eta, jnp.zeros_like(eta))
    return a, b, c, j, eta


def _filter_combine(e1, e2):
    """Associative combine of filtering elements (e1 earlier, e2 later)."""
    a1, b1, c1, j1, eta1 = e1
    a2, b2, c2, j2, eta2 = e2
    n = a1.shape[-1]
    eye = jnp.eye(n, dtype=a1.dtype)

    def comb(a1, b1, c1, j1, eta1, a2, b2, c2, j2, eta2):
        m = jnp.linalg.solve(eye + c1 @ j2, jnp.concatenate(
            [a1, (b1 + c1 @ eta2)[:, None], c1], axis=1))
        m_a1, m_vec, m_c1 = m[:, :n], m[:, n], m[:, n + 1:]
        a = a2 @ m_a1
        b = a2 @ m_vec + b2
        c = a2 @ m_c1 @ a2.T + c2
        w = jnp.linalg.solve(eye + j2 @ c1, jnp.concatenate(
            [(eta2 - j2 @ b1)[:, None], j2], axis=1))
        eta = a1.T @ w[:, 0] + eta1
        j = a1.T @ w[:, 1:] @ a1 + j1
        return a, b, c, j, eta

    return jax.vmap(comb)(a1, b1, c1, j1, eta1, a2, b2, c2, j2, eta2)


def _filter_from_scan(ss: StateSpace, y, mask, scan_fn) -> FilterResult:
    """Shared body of :func:`parallel_filter` and the sequence-sharded
    filter: element build -> ``scan_fn(combine, elements)`` -> moments
    and likelihood terms.  ``scan_fn`` is the only thing that differs
    between the full-length, blocked, and time-sharded variants — one
    definition keeps their masked-likelihood semantics from diverging.
    """
    dtype = ss.q.dtype
    mask = jnp.asarray(mask, bool)
    # zero out masked slots: unlike the sequential engines (whose gains
    # never touch masked entries), 0-gain columns here still multiply y,
    # and 0 * NaN would poison the scan
    y = jnp.where(mask, jnp.asarray(y, dtype), 0.0)
    t_steps = y.shape[0]
    n = ss.phi.shape[-1]

    # reference init: x0 ~ N(0, I) then one predict => P1- = Phi^2 + Q
    p1p = jnp.diag(ss.phi**2).astype(dtype) + ss.q
    first = jnp.arange(t_steps) == 0

    elements = jax.vmap(
        lambda y_t, m_t, f: _filter_element(ss, y_t, m_t, p1p, f, dtype)
    )(y, mask, first)

    a, b, c, j, eta = scan_fn(_filter_combine, elements)
    mean_f, cov_f = b, c

    # predicted moments: from the filtered state one step back
    mean_p = jnp.concatenate(
        [jnp.zeros((1, n), dtype), mean_f[:-1] * ss.phi[None, :]], axis=0
    )
    cov_p = jnp.concatenate(
        [
            p1p[None],
            ss.phi[None, :, None] * cov_f[:-1] * ss.phi[None, None, :]
            + ss.q[None],
        ],
        axis=0,
    )

    # likelihood terms from masked innovations at the predicted state
    def loglik_terms(y_t, mask_t, mp, pp):
        z_t, r_t = _masked_obs(ss, mask_t, dtype)
        v = jnp.where(mask_t, y_t - z_t @ mp, 0.0)
        f = z_t @ pp @ z_t.T + jnp.diag(r_t)
        chol = jnp.linalg.cholesky(f)
        # indefinite-in-f32 step: book +inf (rejectable deviance), no NaN
        ok = jnp.all(jnp.isfinite(chol))
        chol_safe = jnp.where(ok, chol, jnp.eye(f.shape[0], dtype=dtype))
        w = jax.scipy.linalg.solve_triangular(chol_safe, v, lower=True)
        sigma = jnp.where(ok, jnp.sum(w * w), jnp.zeros((), dtype))
        detf = jnp.where(
            ok,
            2.0 * jnp.sum(jnp.log(jnp.diagonal(chol_safe))),
            jnp.asarray(jnp.inf, dtype),
        )
        return sigma, detf

    sigma, detf = jax.vmap(loglik_terms)(y, mask, mean_p, cov_p)
    return FilterResult(mean_p, cov_p, mean_f, cov_f, sigma, detf)


def _block_scan_fn(block):
    """The single-device scan dispatcher shared by filter and smoother."""

    def scan(combine, elements, reverse=False):
        if block is not None:
            return blocked_associative_scan(
                combine, elements, block, reverse=reverse
            )
        return lax.associative_scan(combine, elements, reverse=reverse)

    return scan


@functools.partial(jax.jit, static_argnames=("block",))
def parallel_filter(ss: StateSpace, y: jnp.ndarray, mask: jnp.ndarray,
                    block="auto") -> FilterResult:
    """Kalman filter with O(log T) depth via ``lax.associative_scan``.

    Returns the same :class:`FilterResult` as the sequential
    ``kalman_filter(store=True)``: predicted/filtered moments per step
    and per-step likelihood terms (``sigma``, ``detf``) with identical
    masked-data semantics.

    ``block`` routes the combine through
    :func:`blocked_associative_scan` (numerically equivalent results;
    compile time scales with ``log(block)`` instead of ``log(T)`` —
    essential at T >~ 10k, see docs/performance.md).  Default
    ``"auto"``: full-length below ``AUTO_BLOCK_MIN_T`` steps, blocked
    above; ``None`` forces the full-length scan.  For a time axis
    sharded over a mesh, use :func:`sequence_sharded_filter` (which
    composes blocking with the sharding).
    """
    block = _resolve_block(block, y.shape[0])
    return _filter_from_scan(ss, y, mask, _block_scan_fn(block))


def _smoother_element(phi, mf, pf, mp_next, pp_next, last):
    """Build one associative smoothing element (E, g, L)."""
    n = phi.shape[-1]
    # E = P^f Phi' (P^p_next)^-1 via Cholesky; a factorization gone
    # non-finite (indefinite P^p in f32) degrades this element to the
    # boundary form (smoothed == filtered) instead of NaN-poisoning the
    # reverse combine
    chol = jnp.linalg.cholesky(pp_next)
    ok = jnp.all(jnp.isfinite(chol))
    chol_safe = jnp.where(ok, chol, jnp.eye(n, dtype=pf.dtype))
    e = jax.scipy.linalg.cho_solve((chol_safe, True), phi[:, None] * pf.T).T
    cut = last | ~ok
    e = jnp.where(cut, jnp.zeros((n, n), pf.dtype), e)
    g = jnp.where(cut, mf, mf - e @ mp_next)
    l = jnp.where(cut, pf, pf - e @ pp_next @ e.T)  # noqa: E741
    return e, g, l


def _smoother_combine(later, earlier):
    """Combine for the reverse scan.

    ``associative_scan(reverse=True)`` folds from the right, so the first
    argument is the already-combined *suffix* (later timesteps) and the
    second the new earlier element; the smoothing operator composes as
    earlier ⊗ later: ``(E_e E_l, E_e g_l + g_e, E_e L_l E_e' + L_e)``.
    """

    def comb(e_l, g_l, l_l, e_e, g_e, l_e):
        return (
            e_e @ e_l,
            e_e @ g_l + g_e,
            e_e @ l_l @ e_e.T + l_e,
        )

    return jax.vmap(comb)(*later, *earlier)


def _smoother_from_scan(ss: StateSpace, filtered: FilterResult,
                        scan_fn) -> SmootherResult:
    """Shared body of :func:`parallel_smoother` and the sequence-sharded
    smoother (see :func:`_filter_from_scan`)."""
    t_steps = filtered.mean_f.shape[0]
    last = jnp.arange(t_steps) == t_steps - 1
    # dummy next-step moments for the final element (unused: last flag)
    mp_next = jnp.concatenate(
        [filtered.mean_p[1:], filtered.mean_p[-1:]], axis=0
    )
    pp_next = jnp.concatenate(
        [filtered.cov_p[1:], filtered.cov_p[-1:]], axis=0
    )
    elements = jax.vmap(
        lambda mf, pf, mpn, ppn, lt: _smoother_element(
            ss.phi, mf, pf, mpn, ppn, lt
        )
    )(filtered.mean_f, filtered.cov_f, mp_next, pp_next, last)
    _, g, l = scan_fn(  # noqa: E741
        _smoother_combine, elements, reverse=True
    )
    return SmootherResult(g, l)


@functools.partial(jax.jit, static_argnames=("block",))
def parallel_smoother(ss: StateSpace, filtered: FilterResult,
                      block="auto") -> SmootherResult:
    """RTS smoother with O(log T) depth via reverse associative scan.

    ``block`` as in :func:`parallel_filter` (blocked combine tree,
    numerically equivalent results, O(log block) compile)."""
    block = _resolve_block(block, filtered.mean_f.shape[0])
    return _smoother_from_scan(ss, filtered, _block_scan_fn(block))


@functools.partial(jax.jit, static_argnames=("warmup", "block"))
def parallel_deviance(
    ss: StateSpace, y: jnp.ndarray, mask: jnp.ndarray, warmup: int = 1,
    block="auto",
) -> jnp.ndarray:
    """-2 log L evaluated with the parallel filter (reference semantics).

    ``block`` as in :func:`parallel_filter`.  Non-finite results map to
    ``+inf`` (the rejectable-step guard shared with the sequential
    engines, :func:`metran_tpu.ops.kalman.deviance`)."""
    from .kalman import _finite_or_inf, deviance_terms

    res = parallel_filter(ss, y, mask, block=block)
    return _finite_or_inf(
        deviance_terms(res.sigma, res.detf, mask, warmup=warmup)
    )


# ----------------------------------------------------------------------
# square-root (Cholesky-factor) associative scan
# ----------------------------------------------------------------------
#
# The filtering elements above carry covariance-like matrices (C, J)
# whose construction and combination factor computed matrices with
# ``jnp.linalg.cholesky`` / ``jnp.linalg.solve`` — the f32 NaN path.
# The square-root elements instead carry the *covariance* part in
# lower-triangular factored form (C = U U') and update/combine it via
# orthogonal transformations (QR of stacked factor blocks —
# "Parallel-in-Time Kalman Smoothing Using Orthogonal
# Transformations", arXiv:2502.11686): every per-step covariance
# factor, and everything reconstituted from one, is PSD by
# construction.  The information-like term J stays an explicit PSD
# matrix: its only factorization in the combine is
# ``cholesky(I + U' J U)``, whose argument is bounded below by the
# identity — it cannot go indefinite the way an innovation covariance
# can, so no NaN path is reintroduced.  (A fully factored J would need
# QR of rank-deficient stacks, where JAX's QR derivative is undefined —
# the hybrid keeps the engine differentiable, which the deviance
# gradient path requires.)


def _sqrt_filter_element(ss: StateSpace, y_t, mask_t, first, dtype):
    """Build one square-root associative filtering element.

    Same ``(A, b, C, J, eta)`` semantics as :func:`_filter_element`,
    with ``C = U U'`` and ``J = Zf Zf'`` carried in factored form.  The
    predicted covariance entering the step (``P1-`` when first, ``Q``
    interior) is diagonal for the DFM, so its factor is an exact
    elementwise sqrt; the update runs the same QR array algorithm as
    the sequential square-root engine.
    """
    from .kalman import _q_sqrt_diag, _sign_normalize_rows

    n = ss.phi.shape[-1]
    m = ss.z.shape[-2]
    z_t, r_t = _masked_obs(ss, mask_t, dtype)
    q_sq = _q_sqrt_diag(ss.q).astype(dtype)
    # reference init: x0 ~ N(0, I) then one predict => P1- = Phi^2 + Q
    n_pred = jnp.sqrt(jnp.where(first, ss.phi**2 + q_sq**2, q_sq**2))
    phi_eff = jnp.where(first, jnp.zeros_like(ss.phi), ss.phi)

    # array update: QR of [[sqrt(r), 0], [(Z N)', N']] with N = diag
    pre = jnp.concatenate([
        jnp.concatenate(
            [jnp.diag(jnp.sqrt(r_t)), jnp.zeros((m, n), dtype)], axis=1
        ),
        jnp.concatenate(
            [(z_t * n_pred[None, :]).T, jnp.diag(n_pred)], axis=1
        ),
    ], axis=0)
    rfull = _sign_normalize_rows(jnp.linalg.qr(pre, mode="r"))
    sf = rfull[:m, :m].T  # innovation factor S^{1/2} (lower)
    kbar = rfull[:m, m:].T  # cov_pred Z' S^{-T/2}
    u = rfull[m:, m:].T  # factor of (I - K Z) cov_pred

    d = jnp.diagonal(sf)
    ok = jnp.all(d > 0) & jnp.all(jnp.isfinite(rfull))
    sf_safe = jnp.where(ok, sf, jnp.eye(m, dtype=dtype))
    # K = kbar S^{-1/2}: apply through triangular solves against sf
    z_hat = jax.scipy.linalg.solve_triangular(sf_safe, z_t, lower=True)
    w_y = jax.scipy.linalg.solve_triangular(sf_safe, y_t, lower=True)
    a = (jnp.eye(n, dtype=dtype) - kbar @ z_hat) * phi_eff[None, :]
    b = kbar @ w_y
    # eta = Phi' Z' S^-1 y ; J = Phi' Z' S^-1 Z Phi = B'B (PSD, formed
    # from the triangular-solve products — never from an inverse)
    eta = phi_eff * (z_hat.T @ w_y)
    bmat = z_hat * phi_eff[None, :]  # (m, n)
    j = bmat.T @ bmat
    # degenerate innovation factor: emit the no-observation element
    # (the post-scan loglik terms book the +inf)
    a = jnp.where(ok, a, jnp.diag(phi_eff))
    b = jnp.where(ok, b, jnp.zeros_like(b))
    u = jnp.where(ok, u, jnp.diag(n_pred))
    j = jnp.where(ok, j, jnp.zeros_like(j))
    eta = jnp.where(ok, eta, jnp.zeros_like(eta))
    return a, b, u, j, eta


def _sqrt_filter_combine(e1, e2):
    """Associative combine of square-root filtering elements.

    Implements exactly the covariance combine of
    :func:`_filter_combine` with ``C = U U'`` carried in factored form,
    using the push-through identity ``(I + C1 J2)^{-1} C1 = U1 (I +
    U1' J2 U1)^{-1} U1'``: the only factorization is the Cholesky of
    ``S = I + U1' J2 U1``, which is bounded below by the identity (it
    cannot go indefinite the way an innovation covariance can), and the
    combined covariance factor is one re-triangularization of
    ``[G | U2]`` with ``G = A2 U1 S^{-T/2}`` — so ``C`` stays PSD by
    construction through every level of the combine tree.
    """
    a1, b1, u1, j1, eta1 = e1
    a2, b2, u2, j2, eta2 = e2

    def comb(a1, b1, u1, j1, eta1, a2, b2, u2, j2, eta2):
        from .kalman import _tria

        n = a1.shape[-1]
        eye = jnp.eye(n, dtype=a1.dtype)
        solve = jax.scipy.linalg.solve_triangular
        ju = j2 @ u1
        # S = I + U1' J2 U1 >= I: Cholesky cannot meet an indefinite
        # argument here (contrast the raw innovation covariances the
        # covariance engines factor)
        ls = jnp.linalg.cholesky(eye + u1.T @ ju)
        g = solve(ls, (a2 @ u1).T, lower=True).T  # A2 U1 S^{-T/2}
        sinv = lambda x: jax.scipy.linalg.cho_solve((ls, True), x)  # noqa: E731
        a = a2 @ a1 - (a2 @ u1) @ sinv(ju.T @ a1)
        u_mid = b1 + u1 @ (u1.T @ eta2)  # b1 + C1 eta2
        b = a2 @ u_mid - (a2 @ u1) @ sinv(ju.T @ u_mid) + b2
        u = _tria(jnp.concatenate([g, u2], axis=1))
        v = eta2 - j2 @ b1
        eta = a1.T @ (v - ju @ sinv(u1.T @ v)) + eta1
        # J combine: A1' (J2 - J2 U1 S^-1 U1' J2) A1 + J1 (PSD;
        # symmetrized against accumulation drift)
        j = a1.T @ (j2 - ju @ sinv(ju.T)) @ a1 + j1
        j = 0.5 * (j + j.T)
        return a, b, u, j, eta

    return jax.vmap(comb)(a1, b1, u1, j1, eta1, a2, b2, u2, j2, eta2)


def _sqrt_filter_from_scan(ss: StateSpace, y, mask, scan_fn):
    """Shared body of :func:`sqrt_parallel_filter` (element build ->
    combine -> factored moments and likelihood terms), mirroring
    :func:`_filter_from_scan` in square-root form."""
    from .kalman import SqrtFilterResult, _q_sqrt_diag, _tria

    dtype = ss.q.dtype
    mask = jnp.asarray(mask, bool)
    y = jnp.where(mask, jnp.asarray(y, dtype), 0.0)
    t_steps = y.shape[0]
    n = ss.phi.shape[-1]
    m = ss.z.shape[-2]
    first = jnp.arange(t_steps) == 0
    q_sqrt = _q_sqrt_diag(ss.q).astype(dtype)

    elements = jax.vmap(
        lambda y_t, m_t, f: _sqrt_filter_element(ss, y_t, m_t, f, dtype)
    )(y, mask, first)

    _, b, u, _, _ = scan_fn(_sqrt_filter_combine, elements)
    mean_f, chol_f = b, u

    # predicted moments in factored form: one re-triangularization of
    # [Phi S_f | Q^{1/2}] per step from the filtered factor one back
    mean_p = jnp.concatenate(
        [jnp.zeros((1, n), dtype), mean_f[:-1] * ss.phi[None, :]], axis=0
    )
    chol_p1 = jnp.diag(jnp.sqrt(ss.phi**2 + q_sqrt**2))
    chol_p_rest = jax.vmap(
        lambda cf: _tria(jnp.concatenate(
            [ss.phi[:, None] * cf, jnp.diag(q_sqrt)], axis=1
        ))
    )(chol_f[:-1])
    chol_p = jnp.concatenate([chol_p1[None], chol_p_rest], axis=0)

    # likelihood terms from masked innovations at the predicted state,
    # factored: S^{1/2} = tria([Z S_p | diag(sqrt(r))]) — no Cholesky
    def loglik_terms(y_t, mask_t, mp, sp):
        z_t, r_t = _masked_obs(ss, mask_t, dtype)
        sf = _tria(jnp.concatenate(
            [z_t @ sp, jnp.diag(jnp.sqrt(r_t))], axis=1
        ))
        d = jnp.diagonal(sf)
        ok = jnp.all(d > 0) & jnp.all(jnp.isfinite(sf))
        sf_safe = jnp.where(ok, sf, jnp.eye(m, dtype=dtype))
        v = jnp.where(mask_t, y_t - z_t @ mp, 0.0)
        w = jax.scipy.linalg.solve_triangular(sf_safe, v, lower=True)
        sigma = jnp.where(ok, jnp.sum(w * w), jnp.zeros((), dtype))
        detf = jnp.where(
            ok,
            2.0 * jnp.sum(jnp.log(jnp.where(ok, d, 1.0))),
            jnp.asarray(jnp.inf, dtype),
        )
        return sigma, detf

    sigma, detf = jax.vmap(loglik_terms)(y, mask, mean_p, chol_p)
    return SqrtFilterResult(mean_p, chol_p, mean_f, chol_f, sigma, detf)


@functools.partial(jax.jit, static_argnames=("block",))
def sqrt_parallel_filter(ss: StateSpace, y: jnp.ndarray,
                         mask: jnp.ndarray, block="auto"):
    """Square-root Kalman filter with O(log T) depth.

    The ``engine="sqrt_parallel"`` workhorse: associative elements
    carry triangular factors and combine via orthogonal transformations
    (arXiv:2502.11686), so every per-step covariance factor — and
    anything reconstituted from it — is PSD by construction even in
    float32, with the same masked-data and likelihood semantics as
    :func:`parallel_filter`.  Returns a
    :class:`~metran_tpu.ops.kalman.SqrtFilterResult`; ``block`` routes
    the combine through :func:`blocked_associative_scan` exactly as in
    :func:`parallel_filter`.  Requires the DFM's diagonal ``Q``.

    Autodiff caveat: with the DFM's exact observations (``r = 0``) the
    filtered covariance is structurally rank-deficient in the observed
    directions, and re-triangularizing such factors inside the combine
    tree is not a differentiable operation (the factor's null space
    rotates with the parameters) — gradients through this engine carry
    O(1e-5) relative noise while *values* match the other engines to
    reassociation rounding.  For optimization use ``engine="sqrt"``:
    the sequential square-root scan is gradient-exact (its singular
    factors feed only full-rank predict re-triangularizations).  This
    engine is the robust long-series *filtering/smoothing* path.
    """
    block = _resolve_block(block, y.shape[0])
    return _sqrt_filter_from_scan(ss, y, mask, _block_scan_fn(block))


@functools.partial(jax.jit, static_argnames=("warmup", "block"))
def sqrt_parallel_deviance(
    ss: StateSpace, y: jnp.ndarray, mask: jnp.ndarray, warmup: int = 1,
    block="auto",
) -> jnp.ndarray:
    """-2 log L evaluated with the square-root parallel filter.

    Non-finite results map to ``+inf`` (rejectable step), matching
    every other engine's deviance guard."""
    from .kalman import _finite_or_inf, deviance_terms

    res = sqrt_parallel_filter(ss, y, mask, block=block)
    return _finite_or_inf(
        deviance_terms(res.sigma, res.detf, mask, warmup=warmup)
    )


def _sqrt_smoother_element(phi, q_sqrt, mf, cf, mp_next, sp_next, last):
    """Build one square-root associative smoothing element (E, g, D).

    ``D`` is the factor of the element's additive covariance term:
    the boundary identity ``P_f - E P_pn E' = (I - E Phi) P_f (I - E
    Phi)' + E Q E'`` (a sum of two PSD terms) makes it one
    re-triangularization of stacked blocks.
    """
    from .kalman import _tria

    n = phi.shape[-1]
    eye = jnp.eye(n, dtype=cf.dtype)
    d = jnp.diagonal(sp_next)
    ok = jnp.all(d > 0) & jnp.all(jnp.isfinite(sp_next))
    sp_safe = jnp.where(ok, sp_next, eye)
    a = phi[:, None] * (cf @ cf.T)  # Phi P_f
    e = jax.scipy.linalg.cho_solve((sp_safe, True), a).T
    cut = last | ~ok
    e = jnp.where(cut, jnp.zeros((n, n), cf.dtype), e)
    g = jnp.where(cut, mf, mf - e @ mp_next)
    dfac = _tria(jnp.concatenate(
        [(eye - e * phi[None, :]) @ cf, e * q_sqrt[None, :]], axis=1
    ))
    dfac = jnp.where(cut, cf, dfac)
    return e, g, dfac


def _sqrt_smoother_combine(later, earlier):
    """Square-root combine for the reverse scan: composes as
    :func:`_smoother_combine` with ``L = D D'``; the combined factor is
    one re-triangularization of ``[E_e D_l | D_e]``."""

    def comb(e_l, g_l, d_l, e_e, g_e, d_e):
        from .kalman import _tria

        return (
            e_e @ e_l,
            e_e @ g_l + g_e,
            _tria(jnp.concatenate([e_e @ d_l, d_e], axis=1)),
        )

    return jax.vmap(comb)(*later, *earlier)


@functools.partial(jax.jit, static_argnames=("block",))
def sqrt_parallel_smoother(ss: StateSpace, filtered, block="auto"):
    """RTS smoother with O(log T) depth over triangular factors.

    Takes the :class:`~metran_tpu.ops.kalman.SqrtFilterResult` of
    :func:`sqrt_parallel_filter` (or the sequential
    :func:`~metran_tpu.ops.kalman.sqrt_kalman_filter`) and returns a
    :class:`~metran_tpu.ops.kalman.SqrtSmootherResult` — smoothed
    covariance factors PSD by construction, combine tree identical in
    shape to :func:`parallel_smoother`.
    """
    from .kalman import SqrtSmootherResult, _q_sqrt_diag

    dtype = filtered.chol_f.dtype
    t_steps = filtered.mean_f.shape[0]
    block = _resolve_block(block, t_steps)
    scan_fn = _block_scan_fn(block)
    last = jnp.arange(t_steps) == t_steps - 1
    q_sqrt = _q_sqrt_diag(ss.q).astype(dtype)
    mp_next = jnp.concatenate(
        [filtered.mean_p[1:], filtered.mean_p[-1:]], axis=0
    )
    sp_next = jnp.concatenate(
        [filtered.chol_p[1:], filtered.chol_p[-1:]], axis=0
    )
    elements = jax.vmap(
        lambda mf, cf, mpn, spn, lt: _sqrt_smoother_element(
            ss.phi, q_sqrt, mf, cf, mpn, spn, lt
        )
    )(filtered.mean_f, filtered.chol_f, mp_next, sp_next, last)
    _, g, dfac = scan_fn(_sqrt_smoother_combine, elements, reverse=True)
    return SqrtSmootherResult(g, dfac)


def _sharded_associative_scan(combine, elements, mesh, axis, block,
                              reverse: bool = False):
    """Associative scan with the LEADING (time) axis sharded over
    ``axis`` — the two-level composition that makes the blocked scan and
    the sharded time axis compose (round-4's standing gap: they were
    mutually exclusive, ``block=None`` being required exactly in the
    long-T regime blocking exists for).

    Three levels, mirroring :func:`blocked_associative_scan` with the
    device axis on top:

    1. each device runs the blocked scan over its LOCAL shard — compile
       cost O(log block), independent of both T and the device count;
    2. per-device totals are ``all_gather``-ed (tiny: one element each)
       and every device redundantly computes the cross-device exclusive
       prefix — n_dev elements, a trivial combine tree;
    3. one broadcast combine applies each device's incoming prefix
       (suffix, in reverse) to its local results.

    Values equal the unsharded scan up to floating-point reassociation
    (parity-tested at 1e-10).  Requires the leading dimension divisible
    by the mesh axis size (pad with masked steps first; the filter
    treats them as ordinary all-missing timesteps).
    """
    from jax.sharding import PartitionSpec

    from ..config import shard_map_compat as shard_map

    n_dev = mesh.shape[axis]
    t = jax.tree.leaves(elements)[0].shape[0]
    if t % n_dev:
        raise ValueError(
            f"time axis ({t}) must be divisible by mesh axis "
            f"{axis!r} ({n_dev}); pad with all-masked timesteps"
        )
    t_local = t // n_dev

    def local(el):
        blk = _resolve_block(block, t_local)
        if blk is None or blk >= t_local:
            within = lax.associative_scan(combine, el, reverse=reverse)
        else:
            within = blocked_associative_scan(
                combine, el, blk, reverse=reverse
            )
        # this device's total (first element in reverse), gathered from
        # every device — one element each, so the collective is tiny
        tot = jax.tree.map(
            lambda x: x[0] if reverse else x[-1], within
        )
        totals = jax.tree.map(
            lambda x: lax.all_gather(x, axis, axis=0), tot
        )  # (n_dev, ...)
        incl = lax.associative_scan(combine, totals, reverse=reverse)
        i = lax.axis_index(axis)
        # exclusive prefix: the inclusive combine of the neighbor on the
        # far side; the edge device passes through unchanged
        nb = (i + 1) if reverse else (i - 1)
        pref = jax.tree.map(
            lambda x: jnp.take(x, jnp.clip(nb, 0, n_dev - 1), axis=0),
            incl,
        )
        pref_b = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (t_local,) + x.shape), pref
        )
        # combine's first argument is the already-combined far side in
        # both directions (see blocked_associative_scan)
        applied = combine(pref_b, within)
        edge = (i == n_dev - 1) if reverse else (i == 0)
        return jax.tree.map(
            lambda w, a: jnp.where(edge, w, a), within, applied
        )

    spec = PartitionSpec(axis)
    return shard_map(
        local, mesh=mesh, in_specs=spec, out_specs=spec,
        check_vma=False,
    )(elements)


def _sharded_scan_fn(mesh, axis, block):
    def scan(combine, elements, reverse=False):
        return _sharded_associative_scan(
            combine, elements, mesh, axis, block, reverse=reverse
        )

    return scan


@functools.lru_cache(maxsize=8)
def _make_seq_filter(mesh, axis, block):
    scan = _sharded_scan_fn(mesh, axis, block)
    return jax.jit(lambda ss, y, mask: _filter_from_scan(
        ss, y, mask, scan
    ))


@functools.lru_cache(maxsize=8)
def _make_seq_smoother(mesh, axis, block):
    scan = _sharded_scan_fn(mesh, axis, block)
    return jax.jit(lambda ss, filtered: _smoother_from_scan(
        ss, filtered, scan
    ))


def sequence_sharded_filter(
    ss: StateSpace,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    mesh,
    axis: str = "seq",
    block="auto",
) -> Tuple[FilterResult, SmootherResult]:
    """Filter + smoother with the time axis sharded over a mesh axis.

    The associative-scan reformulation is what makes the time dimension
    shardable at all; :func:`_sharded_associative_scan` composes it with
    the blocked decomposition (``shard_map`` within-device blocked
    scans + one tiny cross-device combine over ICI), so compile cost is
    O(log block) — seconds — even at T = 32k+, where the full-length
    combine tree took 188 s to compile on TPU and segfaulted XLA:CPU
    (round 3/4 findings; this resolves pkalman's former
    block-xor-sharding limitation).  Single-chip semantics are
    unchanged (parity-tested on the virtual CPU mesh at 1e-10).

    Requires T divisible by the mesh axis size — pad with all-masked
    timesteps (the filter treats them as ordinary missing rows).
    ``block`` as in :func:`parallel_filter`; ``"auto"`` resolves
    against the PER-DEVICE shard length.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    def put(x):
        return jax.device_put(
            x,
            NamedSharding(
                mesh, PartitionSpec(axis, *([None] * (x.ndim - 1)))
            ),
        )

    y = put(jnp.asarray(y, ss.q.dtype))
    mask = put(jnp.asarray(mask, bool))
    if isinstance(block, str) or block is None:
        blk = block
    else:
        blk = int(block)
    filtered = _make_seq_filter(mesh, axis, blk)(ss, y, mask)
    smoothed = _make_seq_smoother(mesh, axis, blk)(ss, filtered)
    return filtered, smoothed


__all__ = [
    "parallel_deviance",
    "parallel_filter",
    "parallel_smoother",
    "sequence_sharded_filter",
    "sqrt_parallel_deviance",
    "sqrt_parallel_filter",
    "sqrt_parallel_smoother",
]
