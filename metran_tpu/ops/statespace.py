"""State-space construction for the Metran dynamic factor model (DFM).

The DFM decomposes ``n`` standardized observed series into ``n`` specific
dynamic factors (one AR(1) latent state per series) and ``k`` common dynamic
factors (AR(1) latent states shared through factor loadings).  The state-space
form is

    x_t = Phi x_{t-1} + w_t,   w_t ~ N(0, Q)
    y_t = Z x_t + v_t,         v_t ~ N(0, diag(r))

with diagonal ``Phi`` (``phi_i = exp(-dt / alpha_i)``), diagonal ``Q``
(``q_sdf = (1 - phi^2) (1 - communality)``, ``q_cdf = 1 - phi^2``),
``Z = [I_n | Gamma]`` and ``r = 0``.

Parity: behavior of the matrix builders in the reference implementation
(``metran/metran.py:246-416``: ``_phi``, ``get_transition_matrix``,
``get_transition_covariance``, ``get_observation_matrix``,
``get_observation_variance``, ``get_scaled_observation_matrix``), rebuilt here
as pure jittable functions of the parameter vector so the whole model is
differentiable and vmappable.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class StateSpace(NamedTuple):
    """Matrices of a (diagonal-transition) linear-Gaussian state-space model.

    Attributes
    ----------
    phi : (n_state,) diagonal of the transition matrix.
    q : (n_state, n_state) transition (process noise) covariance.
    z : (n_obs, n_state) observation matrix.
    r : (n_obs,) diagonal observation noise variance.
    """

    phi: jnp.ndarray
    q: jnp.ndarray
    z: jnp.ndarray
    r: jnp.ndarray

    @property
    def n_state(self) -> int:
        return self.phi.shape[-1]

    @property
    def n_obs(self) -> int:
        return self.z.shape[-2]


def ar1_decay(alpha: jnp.ndarray, dt) -> jnp.ndarray:
    """AR(1) decay ``phi = exp(-dt / alpha)`` for time step ``dt`` (days)."""
    return jnp.exp(-dt / alpha)


def dfm_statespace(
    alpha_sdf: jnp.ndarray,
    alpha_cdf: jnp.ndarray,
    loadings: jnp.ndarray,
    dt=1.0,
) -> StateSpace:
    """Build the Metran DFM state-space matrices from parameters.

    Parameters
    ----------
    alpha_sdf : (n_series,) AR decay parameter per specific dynamic factor.
    alpha_cdf : (n_factors,) AR decay parameter per common dynamic factor.
    loadings : (n_series, n_factors) factor loadings from factor analysis.
    dt : time step in days (scalar).

    Returns
    -------
    StateSpace with state ordering ``[sdf_0..sdf_{n-1}, cdf_0..cdf_{k-1}]``.
    """
    alpha_sdf = jnp.asarray(alpha_sdf)
    alpha_cdf = jnp.asarray(alpha_cdf)
    loadings = jnp.atleast_2d(jnp.asarray(loadings))
    # the input dtype decides the engine precision: explicit float32
    # inputs stay float32 even when x64 is enabled (the TPU policy needs
    # f32 programs testable on the x64 CPU backend, tests/test_precision)
    dtype = jnp.result_type(alpha_sdf, alpha_cdf, loadings)
    if not jnp.issubdtype(dtype, jnp.floating):  # e.g. int parameter inits
        from ..config import default_dtype

        dtype = default_dtype()
    n_series = loadings.shape[0]

    alpha_sdf = alpha_sdf.astype(dtype)
    alpha_cdf = alpha_cdf.astype(dtype)
    phi_sdf = ar1_decay(alpha_sdf, dt)
    phi_cdf = ar1_decay(alpha_cdf, dt)
    phi = jnp.concatenate([phi_sdf, phi_cdf])

    communality = jnp.sum(jnp.square(loadings), axis=1)
    # 1 - phi^2 = -expm1(-2 dt / alpha): the expm1 form avoids the
    # catastrophic cancellation of literal ``1 - phi**2`` as phi -> 1
    # (near-unit-root alpha ~ 3e4 loses ~4 digits in float32 otherwise;
    # in float64 both forms agree to machine precision)
    q_sdf = -jnp.expm1(-2.0 * dt / alpha_sdf) * (1.0 - communality)
    q_cdf = -jnp.expm1(-2.0 * dt / alpha_cdf)
    q = jnp.diag(jnp.concatenate([q_sdf, q_cdf]).astype(dtype))

    z = jnp.concatenate(
        [jnp.eye(n_series, dtype=dtype), loadings.astype(dtype)], axis=1
    )
    r = jnp.zeros(n_series, dtype=dtype)
    return StateSpace(phi=phi, q=q, z=z, r=r)


def scale_observation_matrix(z: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Scale the observation matrix by per-series standard deviations.

    Equivalent in behavior to the reference's scaled observation matrix
    (``metran/metran.py:944-961``): the identity block becomes ``diag(scale)``
    and the loading columns are multiplied row-wise by ``scale``, so projected
    states land in the unstandardized data units.
    """
    return z * scale[:, None]
